"""External-builder contract: detect / build / release / run.

Rebuild of `core/container/externalbuilder/externalbuilder.go`: the
supported way to run chaincode this peer did not link in-process and
does not hand-manage as CCaaS. Operators configure builders in
core.yaml —

    chaincode:
      externalBuilders:
        - name: my-builder
          path: /opt/builders/my-builder     # has bin/{detect,build,release,run}
          propagateEnvironment: [GOCACHE, HOME]

and each builder is a directory of four executables invoked exactly
like the reference's:

    bin/detect  SOURCE_DIR METADATA_DIR            rc 0 = claim
    bin/build   SOURCE_DIR METADATA_DIR BUILD_DIR
    bin/release BUILD_DIR  RELEASE_DIR             (optional)
    bin/run     BUILD_DIR  ARTIFACTS_DIR           (long-running)

Chaincode packages are .tar.gz archives holding `metadata.json`
({"type": ..., "label": ...}) and the source tree — the reference's
package shape (`core/chaincode/persistence/chaincode_package.go`)
without the nested code.tar.gz indirection.

Connection model (documented divergence): this framework's chaincode
transport is peer→chaincode in both modes (see external.py). A builder
whose release step writes `chaincode/server/connection.json`
({"address": host:port}) declares a server-mode (CCaaS) chaincode the
peer dials directly; otherwise `bin/run` is spawned with
ARTIFACTS_DIR/chaincode.json telling it which address to LISTEN on,
and the peer dials that. The reference's reverse (chaincode-dials-
peer) registration flow does not exist here.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import socket
import subprocess
import tarfile
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

logger = logging.getLogger("chaincode.externalbuilder")


class BuildError(Exception):
    pass


@dataclass
class BuilderConfig:
    name: str
    path: str
    propagate_environment: tuple = ()

    @classmethod
    def from_config(cls, cfg: dict) -> "BuilderConfig":
        return cls(name=cfg.get("Name") or cfg.get("name", ""),
                   path=cfg.get("Path") or cfg.get("path", ""),
                   propagate_environment=tuple(
                       cfg.get("PropagateEnvironment")
                       or cfg.get("propagateEnvironment") or ()))


@dataclass
class LaunchedChaincode:
    name: str
    package_id: str
    address: str
    client: object
    process: Optional[subprocess.Popen] = None
    build_dir: str = ""

    def stop(self) -> None:
        try:
            self.client.close()
        # ftpu-lint: allow-swallow(teardown close of a possibly-dead
        # chaincode client; the process terminate/kill below is the
        # real stop)
        except Exception:
            pass
        if self.process is not None and self.process.poll() is None:
            self.process.terminate()
            try:
                self.process.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.process.kill()


def package_id_of(package_path: str, label: str = "") -> str:
    """label:sha256 — the reference's package identifier shape."""
    h = hashlib.sha256()
    with open(package_path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return f"{label or 'cc'}:{h.hexdigest()}"


def write_package(path: str, metadata: dict, sources: dict) -> str:
    """Create a chaincode package: metadata.json + src/<files>."""
    import io
    with tarfile.open(path, "w:gz") as tar:
        def add(name, data: bytes):
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mode = 0o644
            tar.addfile(info, io.BytesIO(data))
        add("metadata.json", json.dumps(metadata).encode())
        for rel, data in sources.items():
            add(f"src/{rel}", data)
    return path


class ExternalBuilderRegistry:
    """Orders builders and drives the 4-phase contract per package."""

    def __init__(self, builders: Sequence[BuilderConfig],
                 build_root: str):
        self._builders = list(builders)
        self._root = build_root
        os.makedirs(build_root, exist_ok=True)

    # -- phases --

    def _env(self, b: BuilderConfig) -> dict:
        env = {"PATH": os.environ.get("PATH", "")}
        for k in b.propagate_environment:
            if k in os.environ:
                env[k] = os.environ[k]
        if b.name == "ftpu-python":
            # the built-in platform's run script hosts the chaincode
            # with the framework's own shim/server modules: make THIS
            # process's fabric_tpu importable in the child regardless
            # of how the peer itself was launched
            import fabric_tpu
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.abspath(fabric_tpu.__file__)))
            env["PYTHONPATH"] = os.pathsep.join(
                p for p in (env.get("PYTHONPATH", ""), pkg_root) if p)
        return env

    def _exec(self, b: BuilderConfig, phase: str, args: list,
              check: bool = True) -> int:
        exe = os.path.join(b.path, "bin", phase)
        if not os.path.exists(exe):
            if phase == "release":
                return 0           # optional phase (reference semantics)
            raise BuildError(f"builder {b.name}: missing bin/{phase}")
        proc = subprocess.run([exe, *args], env=self._env(b),
                              capture_output=True, text=True)
        if proc.returncode != 0 and check:
            raise BuildError(
                f"builder {b.name} {phase} failed (rc {proc.returncode}): "
                f"{proc.stderr.strip() or proc.stdout.strip()}")
        return proc.returncode

    def detect(self, source_dir: str, metadata_dir: str
               ) -> Optional[BuilderConfig]:
        """First builder whose bin/detect exits 0 claims the package."""
        for b in self._builders:
            exe = os.path.join(b.path, "bin", "detect")
            if not os.path.exists(exe):
                continue
            rc = subprocess.run([exe, source_dir, metadata_dir],
                                env=self._env(b),
                                capture_output=True).returncode
            if rc == 0:
                return b
        return None

    # -- the full pipeline --

    def launch(self, name: str, package_path: str, support,
               connect_timeout_s: float = 15.0) -> LaunchedChaincode:
        """Unpack → detect → build → release → run/connect → register.

        `support` is the peer's ChaincodeSupport; on success the
        chaincode is registered under `name` and endorsement flows to
        it transparently (reference: externalbuilder.Run + the
        chaincode_support launch path).
        """
        from fabric_tpu.core.chaincode.external import (
            ExternalChaincodeClient,
        )

        pkg_id = package_id_of(package_path)
        work = os.path.join(
            self._root, pkg_id.split(":", 1)[1][:16])
        src = os.path.join(work, "src")
        meta = os.path.join(work, "metadata")
        bld = os.path.join(work, "bld")
        rel = os.path.join(work, "release")
        run_meta = os.path.join(work, "artifacts")
        for d in (src, meta, bld, rel, run_meta):
            shutil.rmtree(d, ignore_errors=True)
            os.makedirs(d)

        with tarfile.open(package_path, "r:gz") as tar:
            for member in tar.getmembers():
                target = os.path.normpath(member.name)
                if target.startswith(("/", "..")):
                    raise BuildError(f"unsafe path in package: "
                                     f"{member.name!r}")
                if target == "metadata.json":
                    tar.extract(member, meta, filter="data")
                elif target.startswith("src/"):
                    member.name = target[4:]
                    tar.extract(member, src, filter="data")

        builder = self.detect(src, meta)
        if builder is None:
            raise BuildError(
                f"no configured external builder claims package "
                f"{pkg_id} (builders: "
                f"{[b.name for b in self._builders]})")
        logger.info("builder %s claimed %s", builder.name, pkg_id)
        self._exec(builder, "build", [src, meta, bld])
        self._exec(builder, "release", [bld, rel])

        conn_path = os.path.join(rel, "chaincode", "server",
                                 "connection.json")
        process = None
        if os.path.exists(conn_path):
            with open(conn_path) as f:
                address = json.load(f)["address"]
            logger.info("%s: server-mode chaincode at %s", name, address)
        else:
            # spawn via bin/run; tell it where to LISTEN
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                address = "127.0.0.1:%d" % s.getsockname()[1]
            with open(os.path.join(run_meta, "chaincode.json"),
                      "w") as f:
                json.dump({"address": address, "chaincode_id": pkg_id,
                           "name": name}, f)
            exe = os.path.join(builder.path, "bin", "run")
            if not os.path.exists(exe):
                raise BuildError(
                    f"builder {builder.name}: no connection.json "
                    "released and no bin/run to start the chaincode")
            process = subprocess.Popen(
                [exe, bld, run_meta], env=self._env(builder),
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

        client = ExternalChaincodeClient(
            name, address,
            metrics_provider=getattr(support, "metrics_provider",
                                     None))
        deadline = time.monotonic() + connect_timeout_s
        last = None
        while True:
            try:
                client.ping()
                break
            except Exception as e:           # noqa: BLE001
                last = e
                if process is not None and process.poll() is not None:
                    raise BuildError(
                        f"chaincode process exited rc "
                        f"{process.returncode} before serving") from e
                if time.monotonic() > deadline:
                    if process is not None:
                        process.terminate()
                    raise BuildError(
                        f"chaincode at {address} not reachable: "
                        f"{last}") from e
                time.sleep(0.1)
        support.register(name, client)
        return LaunchedChaincode(name=name, package_id=pkg_id,
                                 address=address, client=client,
                                 process=process, build_dir=bld)


def builtin_python_builder() -> BuilderConfig:
    """The framework's built-in python platform (the role the docker
    controller + core/chaincode/platforms play in the reference:
    arbitrary source tree → running chaincode process with ZERO
    operator-provided builders — here daemon-free, as a subprocess
    hosting ChaincodeServer). Last in detection order, so operator
    builders always win."""
    here = os.path.dirname(os.path.abspath(__file__))
    return BuilderConfig(
        name="ftpu-python",
        path=os.path.join(here, "builtin_builder"),
        # the run script imports fabric_tpu + jax-free shim modules
        propagate_environment=["PYTHONPATH", "HOME", "LANG",
                               "JAX_PLATFORMS",
                               "PALLAS_AXON_POOL_IPS"])


def registry_from_config(cfg: dict, build_root: str
                         ) -> ExternalBuilderRegistry:
    """core.yaml `chaincode.externalBuilders` → registry, plus the
    built-in python platform (disable with
    `chaincode.disableBuiltinPlatform: true`)."""
    builders = [BuilderConfig.from_config(b)
                for b in (cfg or {}).get("externalBuilders", [])]
    if not (cfg or {}).get("disableBuiltinPlatform"):
        builders.append(builtin_python_builder())
    return ExternalBuilderRegistry(builders, build_root)
