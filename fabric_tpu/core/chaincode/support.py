"""Chaincode execution support: registry + launch + invoke.

Rebuild of `core/chaincode/chaincode_support.go` (`Execute:160`,
`Invoke:197`): the endorser hands a chaincode invocation spec and a tx
simulator to `execute`; the runtime resolves the chaincode, runs it,
and returns the response + events for the ProposalResponsePayload.

The reference launches chaincode as external processes (docker /
external builder / CCaaS) and talks gRPC
(`core/chaincode/handler.go:362` ProcessStream). Here the native mode
is in-process Python (registered `Chaincode` objects — the analog of
the reference's built-in system chaincodes, `core/scc/scc.go`
in-proc stream); an external CCaaS-style gRPC mode plugs in through the
same `Runtime` seam.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

from fabric_tpu.protos import proposal as pb
from fabric_tpu.core.chaincode import shim

logger = logging.getLogger("chaincode")


class ExecuteError(Exception):
    pass


@dataclass
class ChaincodeDefinition:
    """What `_lifecycle` tracks per committed chaincode (reference:
    `core/chaincode/lifecycle/lifecycle.go` ChaincodeDefinition):
    name, sequence, version, endorsement-policy bytes, private-data
    collection configs."""
    name: str
    version: str = "1.0"
    sequence: int = 1
    endorsement_policy: bytes = b""   # marshaled ApplicationPolicy; empty = channel default
    init_required: bool = False
    collections: tuple = ()           # CollectionConfig, ordered
    endorsement_plugin: str = "escc"  # core/handlers registry name
    validation_plugin: str = "vscc"

    def collection(self, name: str):
        for c in self.collections:
            if c.name == name:
                return c
        return None


class ChaincodeSupport:
    """Registry + executor for one peer (all channels).

    The registry maps name → `Chaincode` instance (in-process) — the
    launch step of the reference (`Launch`, docker build etc.) has no
    TPU-side analog worth reproducing for in-process code; external
    processes register themselves at connect time (CCaaS).
    """

    def __init__(self, execute_timeout_s: float = 30.0):
        self._chaincodes: dict[str, shim.Chaincode] = {}
        self._timeout = execute_timeout_s

    def register(self, name: str, chaincode) -> None:
        """`chaincode`: anything with init(stub)/invoke(stub) — an
        in-process shim.Chaincode or an ExternalChaincodeClient."""
        if not (callable(getattr(chaincode, "invoke", None)) and
                callable(getattr(chaincode, "init", None))):
            raise TypeError("chaincode must implement init/invoke")
        self._chaincodes[name] = chaincode
        logger.info("chaincode %s registered", name)

    def is_registered(self, name: str) -> bool:
        return name in self._chaincodes

    def registered(self) -> list[str]:
        return sorted(self._chaincodes)

    def execute(self, channel_id: str, tx_id: str,
                spec: pb.ChaincodeInvocationSpec, simulator,
                creator: bytes = b"",
                transient: Optional[dict] = None,
                timestamp: int = 0) -> tuple[pb.Response,
                                             Optional[pb.ChaincodeEvent],
                                             pb.ChaincodeID]:
        """Reference: `ChaincodeSupport.Execute` → `Invoke` → handler
        round-trips; returns (response, event, resolved chaincode id).
        Raises ExecuteError only for infrastructure faults; contract
        errors come back as Response.status >= 400 like the reference
        (endorser propagates them, `core/endorser/endorser.go:178`).
        """
        cc_id = spec.chaincode_spec.chaincode_id
        cc = self._chaincodes.get(cc_id.name)
        if cc is None:
            raise ExecuteError(f"chaincode {cc_id.name} not found")
        stub = shim.ChaincodeStub(
            channel_id=channel_id, tx_id=tx_id, namespace=cc_id.name,
            simulator=simulator,
            args=list(spec.chaincode_spec.input.args),
            creator=creator, transient=transient, support=self,
            timestamp=timestamp)
        try:
            if spec.chaincode_spec.input.is_init:
                resp = cc.init(stub)
            else:
                resp = cc.invoke(stub)
        except Exception as e:
            logger.exception("chaincode %s panicked", cc_id.name)
            # reference: a chaincode panic fails the proposal, not the peer
            resp = shim.error(f"chaincode {cc_id.name} crashed: {e}")
        if not isinstance(resp, pb.Response):
            resp = shim.error(
                f"chaincode {cc_id.name} returned invalid response type")
        return resp, stub.chaincode_event, cc_id

    def invoke_chaincode(self, caller_stub: shim.ChaincodeStub,
                         name: str, args: list, channel: str) -> pb.Response:
        """cc2cc: same-channel shares the caller's simulator (writes
        merge into one rwset, reference `handler.go:1081`)."""
        cc = self._chaincodes.get(name)
        if cc is None:
            return shim.error(f"chaincode {name} not found")
        if channel != caller_stub.get_channel_id():
            return shim.error(
                "cross-channel chaincode invocation is read-only and "
                "not yet supported")
        stub = shim.ChaincodeStub(
            channel_id=channel, tx_id=caller_stub.get_tx_id(),
            namespace=name, simulator=caller_stub._sim,
            args=args, creator=caller_stub.get_creator(),
            transient=caller_stub.get_transient(), support=self,
            timestamp=caller_stub.get_tx_timestamp())
        try:
            return cc.invoke(stub)
        except Exception as e:
            logger.exception("chaincode %s panicked in cc2cc", name)
            return shim.error(f"chaincode {name} crashed: {e}")
