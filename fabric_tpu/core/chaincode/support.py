"""Chaincode execution support: registry + launch + invoke.

Rebuild of `core/chaincode/chaincode_support.go` (`Execute:160`,
`Invoke:197`): the endorser hands a chaincode invocation spec and a tx
simulator to `execute`; the runtime resolves the chaincode, runs it,
and returns the response + events for the ProposalResponsePayload.

The reference launches chaincode as external processes (docker /
external builder / CCaaS) and talks gRPC
(`core/chaincode/handler.go:362` ProcessStream). Here the native mode
is in-process Python (registered `Chaincode` objects — the analog of
the reference's built-in system chaincodes, `core/scc/scc.go`
in-proc stream); an external CCaaS-style gRPC mode plugs in through the
same `Runtime` seam.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Optional

from fabric_tpu.protos import proposal as pb
from fabric_tpu.core.chaincode import shim

logger = logging.getLogger("chaincode")

from fabric_tpu.common import metrics as _m  # noqa: E402

EXECUTE_TIMEOUTS = _m.CounterOpts(
    namespace="chaincode", name="execute_timeouts",
    help="The number of chaincode invocations that exceeded the "
         "execute timeout and were abandoned.",
    label_names=("chaincode",))
EXECUTE_DURATION = _m.HistogramOpts(
    namespace="chaincode", name="shim_request_duration",
    help="The time a chaincode invocation took end to end (init or "
         "invoke), including cc2cc sub-calls.",
    label_names=("chaincode", "success"))
SHIM_REQUESTS_RECEIVED = _m.CounterOpts(
    namespace="chaincode", name="shim_requests_received",
    help="The number of chaincode shim requests received (state "
         "access, range/query iteration, events, cc2cc), by request "
         "type.", label_names=("type", "channel", "chaincode"))
SHIM_REQUESTS_COMPLETED = _m.CounterOpts(
    namespace="chaincode", name="shim_requests_completed",
    help="The number of chaincode shim requests completed, by "
         "request type and success.",
    label_names=("type", "channel", "chaincode", "success"))


class ExecuteError(Exception):
    pass


class ChaincodeNotFoundError(ExecuteError):
    """The named chaincode is not registered on this peer (the
    endorser maps this to chaincode_instantiation_failures)."""


@dataclass
class ChaincodeDefinition:
    """What `_lifecycle` tracks per committed chaincode (reference:
    `core/chaincode/lifecycle/lifecycle.go` ChaincodeDefinition):
    name, sequence, version, endorsement-policy bytes, private-data
    collection configs."""
    name: str
    version: str = "1.0"
    sequence: int = 1
    endorsement_policy: bytes = b""   # marshaled ApplicationPolicy; empty = channel default
    init_required: bool = False
    collections: tuple = ()           # CollectionConfig, ordered
    endorsement_plugin: str = "escc"  # core/handlers registry name
    validation_plugin: str = "vscc"
    # rich-query indexes shipped with the chaincode, (name, index_json)
    # pairs (reference: META-INF/statedb/couchdb/indexes JSON files) —
    # installed into the channel's state DB when the definition commits
    indexes: tuple = ()

    def collection(self, name: str):
        for c in self.collections:
            if c.name == name:
                return c
        return None


class ChaincodeSupport:
    """Registry + executor for one peer (all channels).

    The registry maps name → `Chaincode` instance (in-process) — the
    launch step of the reference (`Launch`, docker build etc.) has no
    TPU-side analog worth reproducing for in-process code; external
    processes register themselves at connect time (CCaaS).
    """

    def __init__(self, execute_timeout_s: float = 30.0,
                 channel_source=None, metrics_provider=None):
        """`channel_source(channel_id)` → peer Channel (or None) — the
        seam cross-channel chaincode-to-chaincode queries resolve
        through (reference: handler.go InvokeChaincode → peer.Channel
        lookup)."""
        self._chaincodes: dict[str, shim.Chaincode] = {}
        self._timeout = execute_timeout_s
        self._channel_source = channel_source
        provider = metrics_provider or _m.DisabledProvider()
        self.metrics_provider = metrics_provider
        self._m_timeouts = provider.new_counter(EXECUTE_TIMEOUTS)
        self._m_duration = provider.new_histogram(EXECUTE_DURATION)
        self._m_shim_rx = provider.new_counter(SHIM_REQUESTS_RECEIVED)
        self._m_shim_done = provider.new_counter(
            SHIM_REQUESTS_COMPLETED)

    def count_shim_received(self, rtype: str, channel: str,
                            chaincode: str) -> None:
        """One shim request ENTERING (called by ChaincodeStub at
        method entry — in-flight/hung requests show as a
        received-minus-completed gap)."""
        self._m_shim_rx.with_labels(
            "type", rtype, "channel", channel,
            "chaincode", chaincode).add(1)

    def count_shim(self, rtype: str, channel: str, chaincode: str,
                   ok: bool) -> None:
        """One COMPLETED shim request (both in-process chaincode and
        the external-builder/CCaaS dialog funnel through the same
        stub)."""
        self._m_shim_done.with_labels(
            "type", rtype, "channel", channel, "chaincode", chaincode,
            "success", "true" if ok else "false").add(1)

    def register(self, name: str, chaincode) -> None:
        """`chaincode`: anything with init(stub)/invoke(stub) — an
        in-process shim.Chaincode or an ExternalChaincodeClient."""
        if not (callable(getattr(chaincode, "invoke", None)) and
                callable(getattr(chaincode, "init", None))):
            raise TypeError("chaincode must implement init/invoke")
        self._chaincodes[name] = chaincode
        logger.info("chaincode %s registered", name)

    def is_registered(self, name: str) -> bool:
        return name in self._chaincodes

    def registered(self) -> list[str]:
        return sorted(self._chaincodes)

    def execute(self, channel_id: str, tx_id: str,
                spec: pb.ChaincodeInvocationSpec, simulator,
                creator: bytes = b"",
                transient: Optional[dict] = None,
                timestamp: int = 0,
                ledger=None) -> tuple[pb.Response,
                                      Optional[pb.ChaincodeEvent],
                                      pb.ChaincodeID]:
        """Reference: `ChaincodeSupport.Execute` → `Invoke` → handler
        round-trips; returns (response, event, resolved chaincode id).
        Raises ExecuteError only for infrastructure faults; contract
        errors come back as Response.status >= 400 like the reference
        (endorser propagates them, `core/endorser/endorser.go:178`).
        A call exceeding the execute timeout fails the proposal
        (reference: chaincode_support.go:160 ExecuteTimeout) — the
        runaway worker thread is abandoned with a warning (in-process
        Python has no kill; the reference kills the container).
        """
        cc_id = spec.chaincode_spec.chaincode_id
        cc = self._chaincodes.get(cc_id.name)
        if cc is None:
            raise ChaincodeNotFoundError(
                f"chaincode {cc_id.name} not found")
        stub = shim.ChaincodeStub(
            channel_id=channel_id, tx_id=tx_id, namespace=cc_id.name,
            simulator=simulator,
            args=list(spec.chaincode_spec.input.args),
            creator=creator, transient=transient, support=self,
            timestamp=timestamp, ledger=ledger)

        # a dedicated daemon thread per invocation: a hung chaincode
        # abandons ITS thread only — no shared pool whose workers a
        # chain of timeouts could permanently exhaust
        import threading
        outcome: dict = {}
        done = threading.Event()

        def run():
            try:
                if spec.chaincode_spec.input.is_init:
                    outcome["resp"] = cc.init(stub)
                else:
                    outcome["resp"] = cc.invoke(stub)
            except Exception as e:          # noqa: BLE001
                outcome["exc"] = e
            finally:
                done.set()

        t0 = time.perf_counter()
        threading.Thread(target=run, daemon=True,
                         name=f"cc-exec-{cc_id.name}").start()
        if not done.wait(self._timeout):
            self._m_timeouts.with_labels(
                "chaincode", cc_id.name).add(1)
            self._m_duration.with_labels(
                "chaincode", cc_id.name, "success", "false").observe(
                time.perf_counter() - t0)
            logger.warning("chaincode %s exceeded the %.0fs execute "
                           "timeout in tx %s; abandoning the worker",
                           cc_id.name, self._timeout, tx_id)
            # fence the stub: the abandoned thread keeps a reference to
            # the SHARED simulator (endorser-owned; caller-owned for
            # same-channel cc2cc) — a late finisher must not mutate
            # simulation state after the proposal already failed
            stub.cancel(f"execute timeout after {self._timeout:.0f}s "
                        f"in tx {tx_id}")
            # events of a failed, abandoned invocation must not escape
            # (the reference only emits events for successful runs)
            return (shim.error(
                f"chaincode {cc_id.name} timed out after "
                f"{self._timeout:.0f}s"), None, cc_id)
        elif "exc" in outcome:
            logger.error("chaincode %s panicked: %s", cc_id.name,
                         outcome["exc"])
            # reference: a chaincode panic fails the proposal, not the peer
            resp = shim.error(
                f"chaincode {cc_id.name} crashed: {outcome['exc']}")
        else:
            resp = outcome["resp"]
        if not isinstance(resp, pb.Response):
            resp = shim.error(
                f"chaincode {cc_id.name} returned invalid response type")
        self._m_duration.with_labels(
            "chaincode", cc_id.name, "success",
            "true" if resp.status < shim.ERRORTHRESHOLD else "false",
        ).observe(time.perf_counter() - t0)
        return resp, stub.chaincode_event, cc_id

    def invoke_chaincode(self, caller_stub: shim.ChaincodeStub,
                         name: str, args: list, channel: str) -> pb.Response:
        """cc2cc (reference `handler.go:1081` HandleInvokeChaincode):
        same-channel calls share the caller's simulator so their writes
        merge into one rwset; cross-channel calls run READ-ONLY on the
        target channel's committed state — their rwset is discarded and
        never ordered (reference semantics: queries only)."""
        cc = self._chaincodes.get(name)
        if cc is None:
            return shim.error(f"chaincode {name} not found")
        same_channel = channel == caller_stub.get_channel_id()
        ledger = caller_stub._ledger
        if same_channel:
            sim = caller_stub._sim
        else:
            if self._channel_source is None:
                return shim.error(
                    "cross-channel invocation unavailable: no channel "
                    "source wired")
            target = self._channel_source(channel)
            if target is None:
                return shim.error(f"channel {channel} not found")
            ledger = target.ledger
            sim = target.ledger.new_tx_simulator(
                caller_stub.get_tx_id())
        stub = shim.ChaincodeStub(
            channel_id=channel, tx_id=caller_stub.get_tx_id(),
            namespace=name, simulator=sim,
            args=args, creator=caller_stub.get_creator(),
            transient=caller_stub.get_transient(), support=self,
            timestamp=caller_stub.get_tx_timestamp(), ledger=ledger,
            fence=caller_stub._fence)   # share the cancellation fence:
        #   a timeout on the parent must stop the whole invocation tree
        try:
            resp = cc.invoke(stub)
        except Exception as e:
            logger.exception("chaincode %s panicked in cc2cc", name)
            return shim.error(f"chaincode {name} crashed: {e}")
        if not same_channel:
            results = sim.get_tx_simulation_results()
            if any(_has_writes(nsrw) for nsrw in results.ns_rwset):
                logger.warning(
                    "cross-channel cc2cc %s->%s attempted writes on "
                    "channel %s; discarded (queries only)",
                    caller_stub._ns, name, channel)
        return resp


def _has_writes(nsrw) -> bool:
    from fabric_tpu.protos import rwset as rwpb
    kv = rwpb.KVRWSet()
    kv.ParseFromString(nsrw.rwset)
    return bool(kv.writes or kv.metadata_writes or
                nsrw.collection_hashed_rwset)
