"""Application-policy evaluator (VSCC-facing).

Rebuild of `core/policy/application.go:70-160`: an ApplicationPolicy is
either an inline SignaturePolicyEnvelope or a by-name reference into the
channel policy manager. Resolution returns a policies.Policy supporting
both one-shot and two-phase (`prepare`) evaluation.
"""

from __future__ import annotations

from fabric_tpu.protos import policies as polpb
from fabric_tpu.common.policies import cauthdsl
from fabric_tpu.common.policies import policy as papi


class OneShotPrepared:
    """Adapter giving any Policy the two-phase shape: contributes no
    items to the block batch and evaluates eagerly at finish()."""

    items: list = []

    def __init__(self, policy, signed_data):
        self._policy = policy
        self._sd = signed_data

    def finish(self, ok) -> None:
        self._policy.evaluate_signed_data(self._sd)


def prepare_policy(policy, signed_data):
    """policy.prepare(sd) when supported, one-shot fallback otherwise."""
    prep = getattr(policy, "prepare", None)
    if prep is not None:
        try:
            return prep(signed_data)
        except papi.PolicyError:
            pass
    return OneShotPrepared(policy, signed_data)


class CombinedPrepared:
    """ALL of several prepared policies must pass (collection-level
    endorsement rules composed with the chaincode policy — reference:
    the v20 plugin validating each collection's writes)."""

    def __init__(self, parts):
        self._parts = list(parts)
        self.items = [it for p in self._parts for it in p.items]

    def finish(self, flags) -> None:
        pos = 0
        for p in self._parts:
            n = len(p.items)
            p.finish(flags[pos:pos + n])
            pos += n


def org_member_policy_bytes(org: str) -> bytes:
    """ApplicationPolicy requiring one member signature of `org` (the
    implicit-collection write rule)."""
    env = polpb.SignaturePolicyEnvelope(version=0)
    p = env.identities.add(classification=polpb.MSPPrincipal.ROLE)
    role = polpb.MSPRole(msp_identifier=org, role=polpb.MSPRole.MEMBER)
    p.principal = role.SerializeToString()
    env.rule.signed_by = 0
    return polpb.ApplicationPolicy(
        signature_policy=env).SerializeToString()


class ApplicationPolicyEvaluator:
    """Reference: `core/policy/application.go` — Evaluate(policyBytes,
    signedData); here split into resolve + evaluate so the txvalidator
    can batch."""

    def __init__(self, policy_manager, deserializer, csp):
        self._mgr = policy_manager
        self._deserializer = deserializer
        self._csp = csp

    def resolve(self, policy_bytes: bytes):
        """ApplicationPolicy bytes → Policy. Raises on malformed or
        unresolvable policies (the VSCC maps that to
        INVALID_CHAINCODE/ENDORSEMENT_POLICY_FAILURE)."""
        app = polpb.ApplicationPolicy()
        app.ParseFromString(policy_bytes)
        which = app.WhichOneof("type")
        if which == "signature_policy":
            return cauthdsl.SignaturePolicy(
                app.signature_policy, self._deserializer, self._csp)
        if which == "channel_config_policy_reference":
            return self._mgr.get_policy(
                app.channel_config_policy_reference)
        raise ValueError("empty application policy")
