"""Block validation — the north-star path, batch-first.

Rebuild of `core/committer/txvalidator/v20/validator.go:180-265`
(Validate), the plugin dispatcher
(`v20/plugindispatcher/dispatcher.go:102`) and the default VSCC
(`core/handlers/validation/builtin/v20/validation_logic.go:109,185`) —
re-architected for TPU:

The reference validates txs in parallel goroutines, each VSCC verifying
its endorsement signatures *sequentially* on CPU
(`common/policies/policy.go:363` under ★ of SURVEY §3.4). Here
validation is three phases:

  1. CPU: per-tx structural checks + identity deserialization; every
     signature in the block (creator sigs + endorsement sigs) becomes a
     pending VerifyItem.
  2. ONE `csp.verify_batch` over all of them — on the TPU provider,
     one fixed-shape XLA dispatch for the entire block.
  3. CPU: per-tx policy evaluation over precomputed results (pure
     principal matching — no crypto), then MVCC at commit time.

Accept/reject per tx is identical to the reference's sequential
semantics: batch membership never changes a verdict, only *when* the
ECDSA math happens.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from fabric_tpu.protos import common, proposal as pb, transaction as txpb
from fabric_tpu.protoutil import protoutil as pu
from fabric_tpu.common.policies import policy as papi
from fabric_tpu.core import msgvalidation, statebased
from fabric_tpu.core.policycheck import (
    ApplicationPolicyEvaluator, org_member_policy_bytes,
)

logger = logging.getLogger("txvalidator")

TVC = txpb.TxValidationCode

from fabric_tpu.common import metrics as _m  # noqa: E402

VALIDATION_DURATION = _m.HistogramOpts(
    namespace="txvalidator", name="validation_duration",
    help="The time to validate one block end to end: structural "
         "checks, the batched signature verify, and policy matching.",
    label_names=("channel",))
SIGNATURES_BATCHED = _m.CounterOpts(
    namespace="txvalidator", name="signatures_batched",
    help="The number of signatures dispatched through the batched "
         "verify path (creator + endorsement + config signatures).",
    label_names=("channel",))
TXS_VALIDATED = _m.CounterOpts(
    namespace="txvalidator", name="transactions_validated",
    help="The number of transactions validated, by final validation "
         "code.", label_names=("channel", "code"))


class TxValidatorMetrics:
    """The rebuild's analog of the reference's per-block validation
    timing log (`validator.go:262`) as first-class metrics, plus the
    TPU-batch observability SURVEY §5 asks for."""

    def __init__(self, provider=None, channel: str = ""):
        provider = provider or _m.DisabledProvider()
        self.validation_duration = provider.new_histogram(
            VALIDATION_DURATION).with_labels("channel", channel)
        self.signatures_batched = provider.new_counter(
            SIGNATURES_BATCHED).with_labels("channel", channel)
        self._txs = provider.new_counter(TXS_VALIDATED)
        self._channel = channel

    def count_tx(self, code: int, n: int = 1) -> None:
        try:
            name = txpb.TxValidationCode.Name(code)
        except ValueError:
            name = str(code)
        self._txs.with_labels("channel", self._channel,
                              "code", name).add(n)


@dataclass
class ValidationResult:
    """What `validate_ahead` computed for one block, with every side
    effect (TRANSACTIONS_FILTER stamp, metrics) still pending — the
    commit pipeline publishes them via `publish_validation` only once
    the predecessor block is durably committed, so speculative
    validation leaves no early trace."""
    codes: list
    n_items: int = 0
    duration_s: float = 0.0
    # True when a VALID tx of this block changed key-level
    # validation parameters (the BlockOverlay is dirty): later blocks
    # must not be validated until this one's state commit lands
    vp_dirty: bool = False


@dataclass
class _TxCheck:
    """One tx that survived structural checks: its pending crypto."""
    index: int
    creator_item: object                 # VerifyItem for the envelope sig
    prepared_policy: object = None       # two-phase endorsement eval
    tx_id: str = ""
    config_envelope: object = None       # ConfigEnvelope for CONFIG txs


class TxValidator:
    """Per-channel block validator (reference: v20 TxValidator)."""

    def __init__(self, channel_id: str, ledger,
                 bundle_source: Callable[[], object],
                 csp,
                 cc_definition: Callable[[str], object] = lambda name: None,
                 configtx_validator_source: Optional[Callable] = None,
                 metrics=None):
        """`bundle_source` returns the channel's current config Bundle;
        `cc_definition(name)` returns the committed ChaincodeDefinition
        (endorsement-policy bytes) or None; `configtx_validator_source`
        returns the channel's current configtx.Validator so CONFIG txs
        can be replayed against the running config before adoption."""
        self._channel_id = channel_id
        self._ledger = ledger
        self._bundle_source = bundle_source
        self._csp = csp
        self._cc_definition = cc_definition
        self._configtx_validator_source = configtx_validator_source
        self._overlay = statebased.BlockOverlay()
        # tx-ids of validated-but-uncommitted predecessor blocks (set
        # by the commit pipeline): they are not in the ledger's txid
        # index yet but must still trip the duplicate-txid check
        self._known_txids: frozenset = frozenset()
        self.metrics = metrics or TxValidatorMetrics(
            channel=channel_id)

    # -- phase 1 helpers --

    def _extract_endorsement_set(self, checked) -> tuple[str, list]:
        """VSCC artifact extraction (reference:
        `validation_logic.go:109` extractValidationArtifacts): returns
        (chaincode name, endorsement SignedData list)."""
        action = checked.transaction.actions[0]
        cap = txpb.ChaincodeActionPayload()
        cap.ParseFromString(action.payload)
        if not cap.action.proposal_response_payload:
            raise ValueError("no proposal response payload")
        prp_bytes = cap.action.proposal_response_payload
        prp = pb.ProposalResponsePayload()
        prp.ParseFromString(prp_bytes)
        cc_action = pb.ChaincodeAction()
        cc_action.ParseFromString(prp.extension)
        if not cc_action.chaincode_id.name:
            raise ValueError("no chaincode id in chaincode action")
        sd = [
            pu.SignedData(data=prp_bytes + e.endorser,
                          identity=e.endorser, signature=e.signature)
            for e in cap.action.endorsements
        ]
        # written keys/collections drive collection-level and key-level
        # (state-based) validation rules (reference: v20 plugin +
        # statebased validator_keylevel)
        from fabric_tpu.protos import rwset as rwpb

        def kv_parser(raw):
            kv = rwpb.KVRWSet()
            kv.ParseFromString(raw)
            return kv

        def hashed_parser(raw):
            h = rwpb.HashedRWSet()
            h.ParseFromString(raw)
            return h

        try:
            txrw = rwpb.TxReadWriteSet()
            txrw.ParseFromString(cc_action.results)
            write_info = statebased.extract_write_info(
                cc_action.chaincode_id.name, txrw, kv_parser,
                hashed_parser)
        except Exception as e:
            # an unparsable rwset must fail validation loudly: silently
            # defaulting to "no collection writes" would validate the
            # tx under a weaker policy composition than what the
            # commit path later applies (caller maps this to
            # INVALID_ENDORSER_TRANSACTION)
            raise ValueError(f"malformed results/rwset in chaincode "
                             f"action: {e}") from e
        return cc_action.chaincode_id.name, sd, write_info

    def _endorsement_policy(self, bundle, cc_name: str):
        """Resolve the chaincode's endorsement policy (reference:
        plugindispatcher → lifecycle; default when the definition
        leaves it unset is /Channel/Application/Endorsement —
        `core/chaincode/lifecycle/lifecycle.go` defaultEndorsementPolicy)."""
        evaluator = ApplicationPolicyEvaluator(
            bundle.policy_manager, bundle.msp_manager, self._csp)
        definition = self._cc_definition(cc_name)
        if definition is not None and definition.endorsement_policy:
            return evaluator.resolve(definition.endorsement_policy)
        return bundle.policy_manager.get_policy(
            "/Channel/Application/Endorsement")

    def _prepare_validation(self, bundle, cc_name: str,
                            endorsement_sd, write_info):
        """Dispatch to the chaincode's validation plugin (reference:
        plugindispatcher.Dispatch); the built-in "vscc" is the default."""
        from fabric_tpu.core import handlers
        definition = self._cc_definition(cc_name)
        name = (definition.validation_plugin
                if definition is not None and
                getattr(definition, "validation_plugin", None)
                else handlers.DEFAULT_VALIDATION)
        plugin = handlers.validation_plugins.get(name)
        return plugin(self, bundle, cc_name, endorsement_sd,
                      write_info)

    def builtin_vscc_prepare(self, bundle, cc_name: str,
                             endorsement_sd, write_info):
        """Compose the tx's validation policy from the chaincode policy,
        implicit-collection write rules, and key-level (state-based)
        endorsement parameters: a tx writing ONLY its own org's implicit
        collection (a _lifecycle approval) validates against that org
        alone; keys carrying a VALIDATION_PARAMETER validate against
        that policy (resolved at finish time so same-block parameter
        updates by earlier valid txs apply); the chaincode-level policy
        is required whenever any written key has no key-level policy."""
        evaluator = ApplicationPolicyEvaluator(
            bundle.policy_manager, bundle.msp_manager, self._csp)
        org_policies = [
            evaluator.resolve(org_member_policy_bytes(org))
            for org in write_info.implicit_orgs
        ]
        state_db = getattr(self._ledger, "state_db", None)

        def metadata_getter(coll, key):
            if state_db is None:
                return None
            if coll is None:
                return state_db.get_state_metadata(cc_name, key)
            from fabric_tpu.ledger import pvtdata as pvt
            return state_db.get_state_metadata(
                pvt.hash_ns(cc_name, coll), key)

        return statebased.KeyLevelPrepared(
            cc_policy=self._endorsement_policy(bundle, cc_name),
            org_policies=org_policies,
            info=write_info,
            overlay=self._overlay,
            cc_name=cc_name,
            metadata_getter=metadata_getter,
            evaluator=evaluator,
            deserializer=bundle.msp_manager,
            csp=self._csp,
            endorsement_sd=endorsement_sd)

    def _validate_config_tx(self, index: int, config_bytes: bytes) -> int:
        """Replay the config update embedded in a CONFIG tx against the
        channel's running config (reference: the orderer did this in
        msgprocessor; the peer re-derives it so a rogue orderer cannot
        push an arbitrary config — the analog of configtx re-validation
        in the reference's config customtx processor). Returns the
        validation code."""
        from fabric_tpu.protos import configtx as ctxpb
        try:
            cfg_env = ctxpb.ConfigEnvelope()
            cfg_env.ParseFromString(config_bytes)
        except Exception:
            return TVC.INVALID_CONFIG_TRANSACTION
        if self._configtx_validator_source is None:
            return TVC.VALID
        validator = self._configtx_validator_source()
        if cfg_env.config.sequence == validator.sequence():
            # re-delivery of the current config (e.g. catch-up replay)
            # — only if it IS the current config, byte for byte; an
            # equal-sequence config with different contents is exactly
            # the rogue-orderer push this replay defends against
            if pu.marshal(cfg_env.config) == pu.marshal(validator.config):
                return TVC.VALID
            logger.warning("tx[%d] config tx repeats sequence %d with "
                           "different contents", index,
                           validator.sequence())
            return TVC.INVALID_CONFIG_TRANSACTION
        if not cfg_env.last_update:
            logger.warning("tx[%d] config tx lacks its originating "
                           "update", index)
            return TVC.INVALID_CONFIG_TRANSACTION
        try:
            update_env = pu.unmarshal_envelope(cfg_env.last_update)
            payload = pu.get_payload(update_env)
            cue = ctxpb.ConfigUpdateEnvelope()
            cue.ParseFromString(payload.data)
            derived = validator.propose_config_update(cue)
        except Exception as e:
            logger.warning("tx[%d] config update replay failed: %s",
                           index, e)
            return TVC.INVALID_CONFIG_TRANSACTION
        if pu.marshal(derived) != pu.marshal(cfg_env.config):
            logger.warning("tx[%d] delivered config does not match "
                           "replayed update", index)
            return TVC.INVALID_CONFIG_TRANSACTION
        return TVC.VALID

    # -- the entry point --

    def _phase1_tx(self, i: int, env_bytes: bytes, bundle,
                   txids_in_block: set) -> tuple[int, Optional[_TxCheck]]:
        """Phase-1 work for ONE tx: structural checks, creator identity,
        duplicate-txid, VSCC artifact extraction, validation prepare.
        Returns (code, check); code == NOT_VALIDATED means the check is
        pending crypto (its items join the block batch)."""
        try:
            env = pu.unmarshal_envelope(env_bytes)
        except Exception:
            return TVC.MARSHAL_TX_ERROR, None
        code, checked = msgvalidation.check_envelope(
            env, self._channel_id)
        if code != TVC.NOT_VALIDATED:
            return code, None

        # creator identity: deserialize + validity now, sig later
        sd = checked.creator_signed_data
        try:
            ident = bundle.msp_manager.deserialize_identity(
                sd.identity)
            ident.validate()
        except Exception as e:
            logger.debug("tx[%d] creator invalid: %s", i, e)
            return TVC.BAD_CREATOR_SIGNATURE, None
        creator_item = ident.verify_item(sd.data, sd.signature)

        if checked.config_envelope is not None:
            # config txs: creator (orderer) signature joins the
            # batch; the config itself is replayed against the
            # running configtx.Validator in phase 3 before the
            # peer adopts it
            return TVC.NOT_VALIDATED, _TxCheck(
                index=i, creator_item=creator_item,
                config_envelope=checked.config_envelope)

        tx_id = checked.channel_header.tx_id
        if tx_id in txids_in_block or \
                self._ledger.get_transaction_by_id(tx_id) is not None:
            return TVC.DUPLICATE_TXID, None
        txids_in_block.add(tx_id)

        try:
            cc_name, endorsement_sd, write_info = \
                self._extract_endorsement_set(checked)
        except Exception as e:
            logger.debug("tx[%d] bad endorsed action: %s", i, e)
            return TVC.INVALID_ENDORSER_TRANSACTION, None
        try:
            prepared = self._prepare_validation(
                bundle, cc_name, endorsement_sd, write_info)
        except Exception as e:
            logger.debug("tx[%d] chaincode %s unresolvable: %s",
                         i, cc_name, e)
            return TVC.INVALID_CHAINCODE, None
        return TVC.NOT_VALIDATED, _TxCheck(
            index=i, creator_item=creator_item,
            prepared_policy=prepared, tx_id=tx_id)

    def finish_check(self, c: _TxCheck, creator_ok: bool,
                     flags) -> int:
        """Phase-3 verdict for one pending check given its batch
        results (shared by the reference path and the fast path)."""
        if not creator_ok:
            return TVC.BAD_CREATOR_SIGNATURE
        if c.config_envelope is not None:
            return self._validate_config_tx(c.index, c.config_envelope)
        try:
            c.prepared_policy.finish(flags)
        except papi.PolicyError as e:
            logger.debug("tx[%d] endorsement policy failed: %s",
                         c.index, e)
            return TVC.ENDORSEMENT_POLICY_FAILURE
        except Exception as e:
            logger.warning("tx[%d] validation plugin error: %s",
                           c.index, e)
            return TVC.INVALID_OTHER_REASON
        # a VALID tx's validation-parameter updates become visible
        # to later txs in this block (reference: vpmanagerimpl
        # SetTxValidationResult → dependency release)
        record = getattr(c.prepared_policy, "record_valid", None)
        if record is not None:
            record()
        return TVC.VALID

    def validate(self, block: common.Block) -> list[int]:
        """Validate every tx; returns and stamps per-tx validation codes
        (TRANSACTIONS_FILTER — reference validator.go:259). MVCC runs
        later, at commit (`kvledger.commit_block`)."""
        result = self.validate_ahead(block)
        self.publish_validation(block, result)
        return result.codes

    def validate_ahead(self, block: common.Block,
                       known_txids=None) -> ValidationResult:
        """The pure computation of `validate`: every verdict, ZERO
        published side effects — no TRANSACTIONS_FILTER stamp, no
        metrics. The commit pipeline runs this for block N+1 while
        block N commits and publishes via `publish_validation` once N
        is durable; `known_txids` carries the tx-ids of those
        validated-but-uncommitted predecessors so the duplicate-txid
        verdicts stay bit-identical to the sequential order."""
        t0 = time.perf_counter()
        bundle = self._bundle_source()
        # fresh per-block overlay for same-block validation-parameter
        # updates (statebased.BlockOverlay)
        self._overlay = statebased.BlockOverlay()
        self._known_txids = frozenset(known_txids or ())
        n = len(block.data.data)

        try:
            result = None
            from fabric_tpu.core import fastvalidate
            if fastvalidate.available(self._csp):
                try:
                    result = fastvalidate.validate_fast(self, block,
                                                        bundle)
                except Exception:
                    logger.exception(
                        "fast validation path failed; falling back to "
                        "the reference path for block [%d]",
                        block.header.number)
                    self._overlay = statebased.BlockOverlay()
                    result = None
            if result is None:
                result = self._validate_reference_path(block, bundle)
        finally:
            self._known_txids = frozenset()
        codes, n_items = result

        dur = time.perf_counter() - t0
        logger.info("[%s] validated block [%d] in %.0fms (%d txs, "
                    "%d signatures batched)",
                    self._channel_id, block.header.number,
                    dur * 1e3, n, n_items)
        return ValidationResult(codes=codes, n_items=n_items,
                                duration_s=dur,
                                vp_dirty=self._overlay.dirty)

    def publish_validation(self, block: common.Block,
                           result: ValidationResult) -> None:
        """The side effects of `validate`, deferred: stamp the
        TRANSACTIONS_FILTER and publish the validation metrics."""
        codes = result.codes
        # init-extend metadata first (reference protoutil.CopyBlockMetadata
        # semantics): a block from a rogue orderer may arrive with no
        # metadata slots at all, and that must invalidate txs, not crash
        # the deliverer
        while len(block.metadata.metadata) <= \
                common.BlockMetadataIndex.TRANSACTIONS_FILTER:
            block.metadata.metadata.append(b"")
        block.metadata.metadata[
            common.BlockMetadataIndex.TRANSACTIONS_FILTER] = bytes(codes)
        self.metrics.validation_duration.observe(result.duration_s)
        self.metrics.signatures_batched.add(result.n_items)
        # aggregate per distinct code: validation codes repeat heavily
        # within a block, so one labeled add per code, not per tx
        from collections import Counter
        for code, cnt in Counter(codes).items():
            self.metrics.count_tx(code, cnt)

    def _validate_reference_path(self, block, bundle
                                 ) -> tuple[list[int], int]:
        """The per-tx unmarshal pipeline (semantics oracle). The fast
        path (core/fastvalidate.py) must agree with this byte for
        byte; it is also the fallback whenever the native library is
        unavailable."""
        n = len(block.data.data)
        codes: list[int] = [TVC.NOT_VALIDATED] * n
        checks: list[_TxCheck] = []
        txids_in_block: set[str] = set(self._known_txids)

        # ---- phase 1: CPU structural + collect ----
        for i, env_bytes in enumerate(block.data.data):
            code, check = self._phase1_tx(i, env_bytes, bundle,
                                          txids_in_block)
            if code != TVC.NOT_VALIDATED:
                codes[i] = code
            else:
                checks.append(check)

        # ---- phase 2: ONE batched verify for the whole block ----
        items = []
        for c in checks:
            items.append(c.creator_item)
            if c.prepared_policy is not None:
                items.extend(c.prepared_policy.items)
        ok = self._csp.verify_batch(items) if items else []

        # ---- phase 3: apply results, pure principal matching ----
        pos = 0
        for c in checks:
            creator_ok = ok[pos]
            pos += 1
            n_items = len(c.prepared_policy.items) \
                if c.prepared_policy is not None else 0
            flags = ok[pos:pos + n_items]
            pos += n_items
            codes[c.index] = self.finish_check(c, creator_ok, flags)
        return codes, len(items)
