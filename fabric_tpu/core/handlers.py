"""Pluggable endorsement + validation handlers.

Rebuild of `core/handlers/{endorsement,validation}` + the plugin
dispatcher (`core/committer/txvalidator/v20/plugindispatcher`): a
chaincode definition names its endorsement plugin (default "escc") and
validation plugin (default "vscc"); registries resolve them. The
defaults reproduce the built-in behaviors (sign prpBytes‖identity /
batched endorsement-policy evaluation); operators register custom
plugins under new names — nothing above the registry knows which ran.
"""

from __future__ import annotations

import threading
from typing import Callable

DEFAULT_ENDORSEMENT = "escc"
DEFAULT_VALIDATION = "vscc"


class PluginError(Exception):
    pass


class _Registry:
    def __init__(self, kind: str):
        self._kind = kind
        self._lock = threading.Lock()
        self._plugins: dict[str, Callable] = {}

    def register(self, name: str, plugin: Callable) -> None:
        with self._lock:
            self._plugins[name] = plugin

    def get(self, name: str) -> Callable:
        with self._lock:
            plugin = self._plugins.get(name)
        if plugin is None:
            raise PluginError(
                f"no {self._kind} plugin named {name!r}")
        return plugin

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._plugins)


# endorsement plugin: fn(proposal_bytes, results, events, response,
#   cc_id, signer) -> ProposalResponse
endorsement_plugins = _Registry("endorsement")

# validation plugin: fn(validator, bundle, cc_name, endorsement_sd,
#   write_info) -> prepared (two-phase: .items + .finish(flags))
validation_plugins = _Registry("validation")


def _default_endorsement(proposal_bytes, results, events, response,
                         cc_id, signer):
    """Reference: default_endorsement.go:35-53 — sign prpBytes‖identity
    with the peer's signing identity."""
    from fabric_tpu.protoutil import txutils
    return txutils.create_proposal_response(
        proposal_bytes, results, events, response, cc_id, signer)


def _default_validation(validator, bundle, cc_name, endorsement_sd,
                        write_info):
    """Reference: builtin/v20 VSCC — endorsement-policy evaluation
    (batched here) with collection-level rules."""
    return validator.builtin_vscc_prepare(bundle, cc_name,
                                          endorsement_sd, write_info)


endorsement_plugins.register(DEFAULT_ENDORSEMENT, _default_endorsement)
validation_plugins.register(DEFAULT_VALIDATION, _default_validation)
