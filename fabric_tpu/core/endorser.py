"""The endorser: simulate a proposal, sign the result.

Rebuild of `core/endorser/endorser.go` ProcessProposal (:304) /
preProcess (:255) / simulateProposal (:178), with the default
endorsement plugin inlined
(`core/handlers/endorsement/builtin/default_endorsement.go:35-53` —
sign prpBytes‖identity with the peer's signing identity).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Callable, Optional

from fabric_tpu.protos import proposal as pb
from fabric_tpu.protoutil import protoutil as pu, txutils
from fabric_tpu.core import aclmgmt
from fabric_tpu.core.chaincode import ChaincodeSupport, shim
from fabric_tpu.core.msgvalidation import (
    ProposalValidationError, UnpackedProposal,
)

logger = logging.getLogger("endorser")


@dataclass
class ChannelSupport:
    """What the endorser needs from one channel (reference:
    `core/endorser/support.go` Support, narrowed)."""
    ledger: object          # KVLedger: new_tx_simulator, get_transaction_by_id
    policy_manager: object  # policies.Manager
    deserializer: object    # msp manager for the channel
    transient_store: object = None  # TransientStore (pvt distribution)
    pvt_distributor: object = None  # gossip push to collection members
    acls: dict = None               # channel-config ACL overrides
    cc_definition: object = None    # fn(name) -> ChaincodeDefinition


def _error_response(status: int, message: str) -> pb.ProposalResponse:
    resp = pb.ProposalResponse(version=1)
    resp.response.status = status
    resp.response.message = message
    return resp


class Endorser:
    def __init__(self, signer,
                 cc_support: ChaincodeSupport,
                 channel_support: Callable[[str], Optional[ChannelSupport]],
                 acl_provider: Optional[aclmgmt.ACLProvider] = None,
                 metrics=None):
        self._signer = signer
        self._cc = cc_support
        self._channel = channel_support
        self._acl = acl_provider or aclmgmt.ACLProvider()

    def process_proposal(self, sp: pb.SignedProposal) -> pb.ProposalResponse:
        """gRPC-facing entry (reference: endorser.go:304). All failures
        come back as a ProposalResponse with status>=500, mirroring the
        reference's error envelope behavior."""
        try:
            up = UnpackedProposal.unpack(sp)
        except ProposalValidationError as e:
            return _error_response(500, str(e))

        support = self._channel(up.channel_id)
        if support is None:
            return _error_response(
                500, f"access denied: channel [{up.channel_id}] not found")

        # -- preProcess: creator sig, ACL, duplicate txid --
        try:
            up.validate(support.deserializer)
        except ProposalValidationError as e:
            return _error_response(
                500, f"error validating proposal: {e}")

        sd = [pu.SignedData(data=sp.proposal_bytes,
                            identity=up.signature_header.creator,
                            signature=sp.signature)]
        try:
            self._acl.check_acl(aclmgmt.PROPOSE,
                                support.policy_manager, sd,
                                channel_acls=support.acls)
        except aclmgmt.ACLError as e:
            return _error_response(500, str(e))

        if support.ledger.get_transaction_by_id(up.tx_id) is not None:
            return _error_response(
                500, f"duplicate transaction found [{up.tx_id}]")

        # -- simulate --
        sim = support.ledger.new_tx_simulator(up.tx_id)
        try:
            resp, event, cc_id = self._cc.execute(
                up.channel_id, up.tx_id, up.input, sim,
                creator=up.signature_header.creator,
                transient=up.transient,
                timestamp=up.channel_header.timestamp,
                ledger=support.ledger)
        except Exception as e:
            logger.warning("chaincode execution failed for [%s]: %s",
                           up.tx_id, e)
            return _error_response(500, f"chaincode execute failed: {e}")

        if resp.status >= shim.ERRORTHRESHOLD:
            # contract refused: propagate without endorsement
            # (reference endorser.go:343-349)
            out = pb.ProposalResponse(version=1)
            out.response.CopyFrom(resp)
            return out

        results = pu.marshal(sim.get_tx_simulation_results())
        events = pu.marshal(event) if event is not None else b""

        # resolve the endorsement plugin from the chaincode definition
        # (reference: plugin_endorser.go; "escc" is the default)
        from fabric_tpu.core import handlers
        plugin_name = handlers.DEFAULT_ENDORSEMENT
        if support.cc_definition is not None:
            definition = support.cc_definition(cc_id.name)
            if definition is not None and \
                    getattr(definition, "endorsement_plugin", None):
                plugin_name = definition.endorsement_plugin
        try:
            plugin = handlers.endorsement_plugins.get(plugin_name)
        except handlers.PluginError as e:
            return _error_response(500, str(e))

        # private writes: the cleartext NEVER enters the proposal
        # response — it is parked in the transient store (and, with
        # gossip, pushed to authorized peers) until commit
        # (reference endorser.go:234 DistributePrivateData)
        pvt_results = sim.get_private_simulation_results()
        if pvt_results is not None:
            if support.transient_store is None:
                return _error_response(
                    500, "private data written but this peer has no "
                         "transient store")
            support.transient_store.persist(
                up.tx_id, support.ledger.height, pvt_results)
            if support.pvt_distributor is not None:
                try:
                    support.pvt_distributor(up.tx_id,
                                            support.ledger.height,
                                            pvt_results)
                except Exception:
                    logger.exception("private data distribution failed "
                                     "for [%s]", up.tx_id)

        # -- endorse via the resolved plugin --
        return plugin(sp.proposal_bytes, results, events, resp, cc_id,
                      self._signer)
