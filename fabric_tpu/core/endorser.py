"""The endorser: simulate a proposal, sign the result.

Rebuild of `core/endorser/endorser.go` ProcessProposal (:304) /
preProcess (:255) / simulateProposal (:178), with the default
endorsement plugin inlined
(`core/handlers/endorsement/builtin/default_endorsement.go:35-53` —
sign prpBytes‖identity with the peer's signing identity).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Optional

from fabric_tpu.protos import proposal as pb
from fabric_tpu.protoutil import protoutil as pu, txutils
from fabric_tpu.core import aclmgmt
from fabric_tpu.core.chaincode import ChaincodeSupport, shim
from fabric_tpu.core.msgvalidation import (
    ProposalValidationError, UnpackedProposal,
)

logger = logging.getLogger("endorser")


@dataclass
class ChannelSupport:
    """What the endorser needs from one channel (reference:
    `core/endorser/support.go` Support, narrowed)."""
    ledger: object          # KVLedger: new_tx_simulator, get_transaction_by_id
    policy_manager: object  # policies.Manager
    deserializer: object    # msp manager for the channel
    transient_store: object = None  # TransientStore (pvt distribution)
    pvt_distributor: object = None  # gossip push to collection members
    acls: dict = None               # channel-config ACL overrides
    cc_definition: object = None    # fn(name) -> ChaincodeDefinition


from fabric_tpu.common import metrics as _m

PROPOSALS_RECEIVED = _m.CounterOpts(
    namespace="endorser", name="proposals_received",
    help="The number of proposals received.")
SUCCESSFUL_PROPOSALS = _m.CounterOpts(
    namespace="endorser", name="successful_proposals",
    help="The number of successful proposals.")
PROPOSAL_VALIDATION_FAILURES = _m.CounterOpts(
    namespace="endorser", name="proposal_validation_failures",
    help="The number of proposals that have failed initial "
         "validation (malformed envelope or bad creator signature).")
PROPOSAL_ACL_CHECK_FAILURES = _m.CounterOpts(
    namespace="endorser", name="proposal_acl_check_failures",
    help="The number of proposals that failed the channel ACL check.",
    label_names=("channel",))
PROPOSAL_SIMULATION_FAILURES = _m.CounterOpts(
    namespace="endorser", name="proposal_simulation_failures",
    help="The number of proposals that failed chaincode simulation.",
    label_names=("channel", "chaincode"))
ENDORSEMENT_FAILURES = _m.CounterOpts(
    namespace="endorser", name="endorsement_failures",
    help="The number of proposals the endorsement plugin refused "
         "(including chaincode-level errors).",
    label_names=("channel", "chaincode"))
DUPLICATE_TXS_FAILURES = _m.CounterOpts(
    namespace="endorser", name="duplicate_transaction_failures",
    help="The number of proposals rejected as duplicate "
         "transaction IDs.", label_names=("channel",))
CHAINCODE_INSTANTIATION_FAILURES = _m.CounterOpts(
    namespace="endorser", name="chaincode_instantiation_failures",
    help="The number of proposals naming a chaincode that is not "
         "registered/committed on the channel.",
    label_names=("channel", "chaincode"))
PROPOSAL_DURATION = _m.HistogramOpts(
    namespace="endorser", name="proposal_duration",
    help="The time to complete a proposal end to end.",
    label_names=("channel", "chaincode", "success"))


class EndorserMetrics:
    """Reference: `core/endorser/metrics.go`."""

    def __init__(self, provider=None):
        provider = provider or _m.DisabledProvider()
        self.proposals_received = provider.new_counter(
            PROPOSALS_RECEIVED)
        self.successful_proposals = provider.new_counter(
            SUCCESSFUL_PROPOSALS)
        self.validation_failures = provider.new_counter(
            PROPOSAL_VALIDATION_FAILURES)
        self.acl_failures = provider.new_counter(
            PROPOSAL_ACL_CHECK_FAILURES)
        self.simulation_failures = provider.new_counter(
            PROPOSAL_SIMULATION_FAILURES)
        self.endorsement_failures = provider.new_counter(
            ENDORSEMENT_FAILURES)
        self.duplicate_failures = provider.new_counter(
            DUPLICATE_TXS_FAILURES)
        self.instantiation_failures = provider.new_counter(
            CHAINCODE_INSTANTIATION_FAILURES)
        self.proposal_duration = provider.new_histogram(
            PROPOSAL_DURATION)


def _error_response(status: int, message: str) -> pb.ProposalResponse:
    resp = pb.ProposalResponse(version=1)
    resp.response.status = status
    resp.response.message = message
    return resp


class Endorser:
    def __init__(self, signer,
                 cc_support: ChaincodeSupport,
                 channel_support: Callable[[str], Optional[ChannelSupport]],
                 acl_provider: Optional[aclmgmt.ACLProvider] = None,
                 metrics=None):
        self._signer = signer
        self._cc = cc_support
        self._channel = channel_support
        self._acl = acl_provider or aclmgmt.ACLProvider()
        self.metrics = metrics or EndorserMetrics()

    def process_proposal(self, sp: pb.SignedProposal) -> pb.ProposalResponse:
        """gRPC-facing entry (reference: endorser.go:304). All failures
        come back as a ProposalResponse with status>=500, mirroring the
        reference's error envelope behavior."""
        self.metrics.proposals_received.add(1)
        labels = {"channel": "", "chaincode": ""}
        t0 = time.perf_counter()
        resp = self._process(sp, labels)
        ok = resp.response.status < shim.ERRORTHRESHOLD
        if ok:
            self.metrics.successful_proposals.add(1)
        self.metrics.proposal_duration.with_labels(
            "channel", labels["channel"],
            "chaincode", labels["chaincode"],
            "success", "true" if ok else "false",
        ).observe(time.perf_counter() - t0)
        return resp

    def _process(self, sp: pb.SignedProposal,
                 labels: dict) -> pb.ProposalResponse:
        try:
            up = UnpackedProposal.unpack(sp)
        except ProposalValidationError as e:
            self.metrics.validation_failures.add(1)
            return _error_response(500, str(e))
        labels["channel"] = up.channel_id
        labels["chaincode"] = up.chaincode_name

        support = self._channel(up.channel_id)
        if support is None:
            return _error_response(
                500, f"access denied: channel [{up.channel_id}] not found")

        # -- preProcess: creator sig, ACL, duplicate txid --
        try:
            up.validate(support.deserializer)
        except ProposalValidationError as e:
            self.metrics.validation_failures.add(1)
            return _error_response(
                500, f"error validating proposal: {e}")

        sd = [pu.SignedData(data=sp.proposal_bytes,
                            identity=up.signature_header.creator,
                            signature=sp.signature)]
        try:
            self._acl.check_acl(aclmgmt.PROPOSE,
                                support.policy_manager, sd,
                                channel_acls=support.acls)
        except aclmgmt.ACLError as e:
            self.metrics.acl_failures.with_labels(
                "channel", up.channel_id).add(1)
            return _error_response(500, str(e))

        if support.ledger.get_transaction_by_id(up.tx_id) is not None:
            self.metrics.duplicate_failures.with_labels(
                "channel", up.channel_id).add(1)
            return _error_response(
                500, f"duplicate transaction found [{up.tx_id}]")

        # -- simulate --
        sim = support.ledger.new_tx_simulator(up.tx_id)
        try:
            resp, event, cc_id = self._cc.execute(
                up.channel_id, up.tx_id, up.input, sim,
                creator=up.signature_header.creator,
                transient=up.transient,
                timestamp=up.channel_header.timestamp,
                ledger=support.ledger)
        except Exception as e:
            logger.warning("chaincode execution failed for [%s]: %s",
                           up.tx_id, e)
            from fabric_tpu.core.chaincode.support import (
                ChaincodeNotFoundError,
            )
            if isinstance(e, ChaincodeNotFoundError):
                # the named chaincode is not registered on this peer
                # (reference: chaincode_instantiation_failures)
                self.metrics.instantiation_failures.with_labels(
                    "channel", up.channel_id,
                    "chaincode", up.chaincode_name).add(1)
            self.metrics.simulation_failures.with_labels(
                "channel", up.channel_id,
                "chaincode", up.chaincode_name).add(1)
            return _error_response(500, f"chaincode execute failed: {e}")

        if resp.status >= shim.ERRORTHRESHOLD:
            # contract refused: propagate without endorsement
            # (reference endorser.go:343-349)
            self.metrics.endorsement_failures.with_labels(
                "channel", up.channel_id,
                "chaincode", up.chaincode_name).add(1)
            out = pb.ProposalResponse(version=1)
            out.response.CopyFrom(resp)
            return out

        results = pu.marshal(sim.get_tx_simulation_results())
        events = pu.marshal(event) if event is not None else b""

        # resolve the endorsement plugin from the chaincode definition
        # (reference: plugin_endorser.go; "escc" is the default)
        from fabric_tpu.core import handlers
        plugin_name = handlers.DEFAULT_ENDORSEMENT
        if support.cc_definition is not None:
            definition = support.cc_definition(cc_id.name)
            if definition is not None and \
                    getattr(definition, "endorsement_plugin", None):
                plugin_name = definition.endorsement_plugin
        try:
            plugin = handlers.endorsement_plugins.get(plugin_name)
        except handlers.PluginError as e:
            return _error_response(500, str(e))

        # private writes: the cleartext NEVER enters the proposal
        # response — it is parked in the transient store (and, with
        # gossip, pushed to authorized peers) until commit
        # (reference endorser.go:234 DistributePrivateData)
        pvt_results = sim.get_private_simulation_results()
        if pvt_results is not None:
            if support.transient_store is None:
                return _error_response(
                    500, "private data written but this peer has no "
                         "transient store")
            support.transient_store.persist(
                up.tx_id, support.ledger.height, pvt_results)
            if support.pvt_distributor is not None:
                try:
                    support.pvt_distributor(up.tx_id,
                                            support.ledger.height,
                                            pvt_results)
                except Exception:
                    logger.exception("private data distribution failed "
                                     "for [%s]", up.tx_id)

        # -- endorse via the resolved plugin --
        return plugin(sp.proposal_bytes, results, events, resp, cc_id,
                      self._signer)
