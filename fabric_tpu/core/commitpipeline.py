"""Pipelined block intake: validate block N+1 while block N commits.

The peer's intake path was strictly sequential per block: pop one
block, verify + validate (device-bound), gather private data, commit
(host/IO-bound), then touch the next block — so the TPU idles during
every state-DB/block-store commit and the host idles during every
batched verify. `CommitPipeline` decouples the two:

  stage A (device)  mcs.verify_block + TxValidator.validate_ahead for
                    block N+1 — including protobuf parse, the tx-id
                    scan and ONE up-front extract_tx_rwset pass — on
                    the validate worker thread;
  stage B (host)    pvt-data gather + kvledger.commit_block for block
                    N on the commit worker thread.

This is the cross-block analog of the within-batch host<->device
overlap from round 6 (`BCCSP.TPU.PipelineChunk`), the same structure
hardware verification engines use to keep the cryptographic unit
saturated (arXiv:2112.02229) under the batching-vs-latency trade of
arXiv:2302.00418.

Correctness barriers (the interesting part) are explicit:

  * config blocks — validating past block N requires N's bundle
    (including the BlockValidation policy `verify_block` evaluates),
    so stage A drains — waits for the commit of N — before touching
    N+1 whenever an uncommitted predecessor is a config block;
  * validation-parameter updates — a predecessor whose VALID txs
    changed key-level endorsement parameters (statebased.BlockOverlay
    via record_valid), or that touched the `_lifecycle` namespace,
    must reach the state DB before later blocks resolve policies
    against it;
  * stage-A failure — any unexpected validate-ahead error (including
    an armed `commit.validate_ahead` / `commit.barrier` fault) demotes
    that block to the sequential path on the commit worker and
    barriers everything behind it. Only a genuine
    `BlockVerificationError` (forged/mismatched block) rejects.

Speculative validation publishes NO side effects early: the
TRANSACTIONS_FILTER stamp and the validation metrics for N+1 are
deferred (`TxValidator.publish_validation`) until N is durably
committed, and nothing of N+1 touches disk — a crash mid-pipeline
replays identically to the sequential path. Duplicate-txid detection
stays bit-identical: the tx-ids of validated-but-uncommitted
predecessors are threaded into `validate_ahead(known_txids=...)` so a
txid repeated across adjacent in-flight blocks is still caught.

Any error is sticky: the next `submit()`/`drain()` raises a
`CommitPipelineError`, the feeder calls `reset()` (which drops all
in-flight work and re-syncs to the committed ledger height) and
re-fetches from there — exactly the sequential retry semantics, with
at most `depth` extra blocks of re-fetch.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from fabric_tpu.common import clustertrace, faults
from fabric_tpu.common import metrics as metrics_mod
from fabric_tpu.common import overload
from fabric_tpu.common import tracing
from fabric_tpu.common.hotpath import hot_path

logger = logging.getLogger("commitpipeline")


class CommitPipelineError(Exception):
    """A pipelined block failed. `seq` is the failing block, `stage`
    is "verify" | "validate" | "commit". The feeder's recovery is the
    sequential path's: reset + re-fetch from the committed height."""

    def __init__(self, seq: int, stage: str, cause: BaseException):
        super().__init__(f"block [{seq}] failed in pipeline stage "
                         f"{stage}: {cause}")
        self.seq = seq
        self.stage = stage
        self.cause = cause


class _Rejected(Exception):
    """Internal: a genuine block rejection (not a pipeline fault)."""

    def __init__(self, stage: str, cause: BaseException):
        super().__init__(str(cause))
        self.stage = stage
        self.cause = cause


class _Stale(Exception):
    """Internal: the pipeline was reset while this item was in
    flight; drop it without side effects."""


@dataclass
class _Item:
    seq: int
    epoch: int
    raw: Optional[bytes] = None
    block: object = None
    # stage-A products (None until validated)
    result: object = None        # txvalidator.ValidationResult
    rwsets: Optional[list] = None
    tx_ids: Optional[list] = None
    # sequential-fallback demotion (stage-A failure)
    fallback: bool = False
    verified: bool = False       # mcs.verify_block already passed
    # trace context captured at submit (the feeder's ambient one):
    # the validate/commit spans keep the block's trace_id across both
    # worker threads
    tctx: object = None


class CommitPipeline:
    """Two-stage overlapped intake for one channel.

    `channel` duck-type: `.channel_id`, `.ledger` (block_store +
    height), `.validator` (validate_ahead/publish_validation),
    `.commit_validated(block, codes, rwsets=, tx_ids=)` and
    `.process_block(block)` (the sequential fallback) —
    fabric_tpu.peer.Channel satisfies it. `mcs` is optional (None
    skips block verification — the caller already verified)."""

    def __init__(self, channel, mcs=None, depth: int = 1,
                 metrics_provider=None,
                 on_committed: Optional[Callable] = None,
                 node_id: Optional[str] = None):
        if depth < 1:
            raise ValueError("CommitPipeline needs depth >= 1 "
                             "(0 = sequential: do not build one)")
        self.channel = channel
        self.depth = depth
        # cross-node trace attribution (round 18): the COMMITTING
        # node's identity — labels e2e_commit_seconds and the
        # validate/commit spans' track in the merged cluster trace
        self.node_id = node_id
        self._e2e_node = node_id or tracing.current_node() or "local"
        self._mcs = mcs
        self.on_committed = on_committed
        self._cond = threading.Condition()
        self._intake: list[_Item] = []     # submitted, not validated
        self._validated: list[_Item] = []  # validated, not committed
        self._committing: Optional[_Item] = None
        self._inflight = 0                 # submitted - committed
        self._epoch = 0                    # bumped by reset()
        self._next_seq = channel.ledger.height
        self._committed_through = channel.ledger.height - 1
        self._validated_through = channel.ledger.height - 1
        # validation of blocks AFTER _barrier_seq must wait until
        # _barrier_seq is committed; reason feeds the metric label
        self._barrier_seq: Optional[int] = None
        self._barrier_reason = ""
        self._error: Optional[CommitPipelineError] = None
        self._stop = threading.Event()
        # tx-ids of in-flight validated/committing blocks, for the
        # duplicate-txid check of later blocks; entries are dropped
        # only AFTER their block is durably committed (and therefore
        # visible through the ledger's own txid index)
        self._inflight_txids: dict[int, list[str]] = {}
        # overlap accounting: the commit-busy windows stage A
        # intersects — the currently-active commit plus the most
        # recently completed one
        self._commit_window: tuple[float, float] = (0.0, 0.0)
        self._commit_active_since: Optional[float] = None

        self.stats = {
            "submitted": 0, "validated_ahead": 0, "committed": 0,
            "fallbacks": 0, "barriers": 0, "sheds": 0,
            "validate_s": 0.0, "commit_s": 0.0, "overlap_s": 0.0,
        }
        self._last_shed_t: Optional[float] = None
        overload.register_stage(
            f"commit.pipeline.{channel.channel_id}", self)

        provider = metrics_provider or metrics_mod.DisabledProvider()
        cid = channel.channel_id
        self._m_depth = provider.new_gauge(
            metrics_mod.COMMIT_PIPELINE_DEPTH_OPTS).with_labels(
            "channel", cid)
        self._m_validate = provider.new_gauge(
            metrics_mod.COMMIT_PIPELINE_VALIDATE_SECONDS_OPTS
        ).with_labels("channel", cid)
        self._m_commit = provider.new_gauge(
            metrics_mod.COMMIT_PIPELINE_COMMIT_SECONDS_OPTS
        ).with_labels("channel", cid)
        self._m_overlap = provider.new_gauge(
            metrics_mod.COMMIT_PIPELINE_OVERLAP_RATIO_OPTS
        ).with_labels("channel", cid)
        self._m_barriers = provider.new_counter(
            metrics_mod.COMMIT_PIPELINE_BARRIER_TOTAL_OPTS)
        self._barrier_labels = ("channel", cid)
        self._m_depth.set(depth)

        self._validate_thread = threading.Thread(
            target=self._validate_loop,
            name=f"commit-pipeline-validate-{cid}", daemon=True)
        self._commit_thread = threading.Thread(
            target=self._commit_loop,
            name=f"commit-pipeline-commit-{cid}", daemon=True)
        self._validate_thread.start()
        self._commit_thread.start()

    # -- feeder API (the ingest thread) --

    @property
    def next_seq(self) -> int:
        with self._cond:
            return self._next_seq

    def overload_stats(self) -> dict:
        """Overload-registry protocol (common/overload.py): in-flight
        blocks are the stage's depth, deadline-expired backpressure
        waits its sheds."""
        with self._cond:
            return {
                "depth": self._inflight,
                "capacity": self.depth + 1,
                "sheds": self.stats["sheds"],
                "puts": self.stats["submitted"],
                "last_shed_t": self._last_shed_t,
            }

    def submit(self, seq: int, raw: Optional[bytes] = None,
               block=None, abort=None) -> None:
        """Enqueue the next in-sequence block (bytes or parsed).
        Blocks while more than `depth` blocks are in flight
        (backpressure); raises the pipeline's sticky error if a
        previous block failed. `abort` (an optional threading.Event,
        e.g. the feeder's own stop flag) breaks the backpressure wait
        so a stopping feeder is not held hostage by a slow commit.

        The backpressure wait is bounded (round 12) by the caller's
        ambient deadline budget, else `default_enqueue_budget_s()`:
        on expiry it raises `OverloadError` — NON-sticky and clean
        (nothing was enqueued, `next_seq` unchanged); the feeder
        simply retries the same block, keeping backpressure on the
        network without an unbounded wait."""
        if raw is None and block is None:
            raise ValueError("submit needs raw bytes or a parsed block")
        budget = overload.Deadline.remaining_or(
            overload.default_enqueue_budget_s())
        deadline = time.monotonic() + max(0.0, budget)
        with self._cond:
            self._raise_if_error()
            if seq != self._next_seq:
                raise CommitPipelineError(
                    seq, "verify",
                    ValueError(f"out-of-order submit: expected "
                               f"[{self._next_seq}]"))
            while self._inflight > self.depth and \
                    self._error is None and not self._stop.is_set() \
                    and not (abort is not None and abort.is_set()):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.stats["sheds"] += 1
                    self._last_shed_t = time.monotonic()
                    tracing.note_shed(
                        f"commit.pipeline.{self.channel.channel_id}")
                    raise overload.OverloadError(
                        f"commit.pipeline.{self.channel.channel_id}",
                        f"backpressure wait for block [{seq}] "
                        f"exceeded the deadline budget")
                self._cond.wait(timeout=min(0.2, remaining))
            self._raise_if_error()
            if self._stop.is_set() or \
                    (abort is not None and abort.is_set()):
                raise CommitPipelineError(
                    seq, "verify", RuntimeError("pipeline stopped"))
            self._intake.append(_Item(seq=seq, epoch=self._epoch,
                                      raw=raw, block=block,
                                      tctx=tracing.capture()))
            self._inflight += 1
            self._next_seq = seq + 1
            self.stats["submitted"] += 1
            self._cond.notify_all()

    def drain(self, timeout: Optional[float] = None,
              abort=None) -> None:
        """Wait until every submitted block is committed; raises the
        sticky error if any block failed. `abort` (an optional
        threading.Event) ends the wait early without error — for a
        feeder that is shutting down."""
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._cond:
            while self._inflight > 0 and self._error is None and \
                    not self._stop.is_set() and \
                    not (abort is not None and abort.is_set()):
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"commit pipeline drain timed out with "
                        f"{self._inflight} block(s) in flight")
                self._cond.wait(timeout=0.2 if remaining is None
                                else min(0.2, remaining))
            self._raise_if_error()

    def reset(self) -> None:
        """Drop all in-flight work, clear the sticky error, and
        re-sync to the committed ledger height. Waits for an
        in-progress commit to finish first (a commit is durable work;
        it cannot be abandoned mid-write). Workers recognize items
        from the old epoch and discard them without side effects."""
        with self._cond:
            self._epoch += 1
            self._intake.clear()
            self._validated.clear()
            self._cond.notify_all()
            while self._committing is not None and \
                    not self._stop.is_set():
                self._cond.wait(timeout=0.2)
            self._inflight_txids.clear()
            self._error = None
            self._barrier_seq = None
            self._inflight = 0
            self._next_seq = self.channel.ledger.height
            self._committed_through = self._next_seq - 1
            self._validated_through = self._next_seq - 1
            self._cond.notify_all()

    def stop(self) -> None:
        """Abandon in-flight work and join the workers. Uncommitted
        blocks are simply not committed — crash-equivalent, which the
        sequential replay heals."""
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        for t in (self._validate_thread, self._commit_thread):
            t.join(timeout=5)

    def wait_validated(self, seq: int,
                       timeout: Optional[float] = None,
                       abort=None) -> None:
        """Block until stage A has handled block `seq` (validated, or
        demoted to the sequential fallback), raising the sticky error
        if it was rejected instead. A deliver-stream feeder calls this
        after each submit so a forged block from the orderer surfaces
        IMMEDIATELY — triggering reconnect + endpoint failover — as it
        did on the sequential path, instead of idling at the tip; the
        overlap is untouched (block N's commit still runs during this
        wait for validate(N+1))."""
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._cond:
            while self._validated_through < seq and \
                    self._error is None and not self._stop.is_set() \
                    and not (abort is not None and abort.is_set()):
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"block [{seq}] not validated in time")
                self._cond.wait(timeout=0.2 if remaining is None
                                else min(0.2, remaining))
            self._raise_if_error()

    def check_error(self) -> None:
        """Non-blocking probe: raise the sticky error if a pipelined
        block failed, return immediately otherwise. Feeders call this
        on idle ticks so failures surface without draining (and
        therefore serializing) the pipeline."""
        with self._cond:
            self._raise_if_error()

    def _raise_if_error(self) -> None:
        if self._error is not None:
            raise self._error

    # -- stage A: validate ahead --

    def _validate_loop(self) -> None:
        tracing.set_node(self.node_id)
        while not self._stop.is_set():
            with self._cond:
                # a pending sticky error also parks the worker (the
                # feeder must reset() first) — without the second
                # clause this would busy-spin until then
                while (not self._intake or self._error is not None) \
                        and not self._stop.is_set():
                    self._cond.wait(timeout=0.2)
                if self._stop.is_set():
                    return
                item = self._intake.pop(0)
            reject: Optional[_Rejected] = None
            demoted: Optional[BaseException] = None
            try:
                self._validate_one(item)
            except _Stale:
                continue
            except _Rejected as e:
                reject = e
            except Exception as e:   # noqa: BLE001 — demote, never drop
                demoted = e
            with self._cond:
                if item.epoch != self._epoch or self._stop.is_set():
                    continue          # reset raced us: drop silently
                if reject is not None:
                    if self._error is None:
                        self._error = CommitPipelineError(
                            item.seq, reject.stage, reject.cause)
                    self._cond.notify_all()
                    continue
                if demoted is not None:
                    self._demote_locked(item, demoted)
                if self._error is None:
                    self._validated.append(item)
                    self._validated_through = item.seq
                    if item.tx_ids is not None:
                        self._inflight_txids[item.seq] = [
                            t for t in item.tx_ids if t]
                    self._cond.notify_all()

    def _demote_locked(self, item: _Item, cause: BaseException) -> None:
        """Stage-A failure → sequential fallback: the commit worker
        runs the plain verify+validate+commit path for this block, and
        everything behind it barriers until it lands."""
        logger.warning("[%s] validate-ahead of block [%d] failed (%s);"
                       " falling back to sequential",
                       self.channel.channel_id, item.seq, cause)
        item.fallback = True
        item.result = None
        item.rwsets = None
        item.tx_ids = None
        self.stats["fallbacks"] += 1
        self._barrier_seq = item.seq
        self._barrier_reason = "fallback"

    def _wait_barrier(self, item: _Item) -> None:
        """Drain the pipeline up to the pending barrier before
        validating `item`."""
        with self._cond:
            if item.epoch != self._epoch:
                raise _Stale()    # reset raced us: skip the (device-
                #                   bound) validation work entirely
            barrier = self._barrier_seq
            reason = self._barrier_reason
            if barrier is None or self._committed_through >= barrier:
                return
        # the armed chaos point: an error here demotes the block to
        # the sequential path (safe — stage B is ordered), a delay
        # models a slow predecessor commit
        faults.check("commit.barrier")
        self.stats["barriers"] += 1
        self._m_barriers.with_labels(*self._barrier_labels,
                                     "reason", reason).add(1)
        logger.debug("[%s] barrier before block [%d]: waiting for "
                     "commit of [%d] (%s)", self.channel.channel_id,
                     item.seq, barrier, reason)
        with self._cond:
            while self._committed_through < barrier and \
                    self._error is None and not self._stop.is_set() \
                    and item.epoch == self._epoch:
                self._cond.wait(timeout=0.2)
            if item.epoch != self._epoch:
                raise _Stale()
            if self._error is not None or self._stop.is_set():
                raise _Stale()

    @staticmethod
    def _parse_item(item: _Item) -> None:
        """Parse raw bytes into item.block (idempotent); a parse
        failure is a genuine rejection."""
        from fabric_tpu.protos import common
        if item.block is None:
            try:
                block = common.Block()
                block.ParseFromString(item.raw)
                item.block = block
            except Exception as e:
                raise _Rejected("verify", e) from e

    def _ensure_parsed_and_verified(self, item: _Item) -> None:
        """Parse (if needed) and run mcs.verify_block once, wrapping
        genuine rejections in _Rejected. Shared by stage A and the
        sequential-fallback path so rejection classification can
        never drift between them."""
        self._parse_item(item)
        if self._mcs is not None and not item.verified:
            from fabric_tpu.peer.mcs import BlockVerificationError
            try:
                self._mcs.verify_block(self.channel.channel_id,
                                       item.seq, item.block)
            except BlockVerificationError as e:
                raise _Rejected("verify", e) from e
        item.verified = True

    @hot_path
    def _validate_one(self, item: _Item) -> None:
        with tracing.span("commit.validate", parent=item.tctx,
                          seq=item.seq):
            self._validate_one_traced(item)

    @hot_path
    def _validate_one_traced(self, item: _Item) -> None:
        from fabric_tpu import protoutil as pu
        from fabric_tpu.ledger.kvledger import extract_tx_rwset

        faults.check("commit.validate_ahead")
        # parse WITHOUT verifying yet: verification must wait for the
        # barrier below (a config predecessor can change the
        # BlockValidation policy), but a parse failure rejects now
        self._parse_item(item)
        block = item.block

        # barrier BEFORE verify_block too: a config predecessor can
        # change the BlockValidation policy the verify evaluates
        self._wait_barrier(item)

        self._ensure_parsed_and_verified(item)

        t0 = time.perf_counter()
        with self._cond:
            known = [t for txids in self._inflight_txids.values()
                     for t in txids]

        tx_ids = self.channel.ledger.block_store.block_tx_ids(block)
        result = self.channel.validator.validate_ahead(
            block, known_txids=known)
        is_config = pu.is_config_block(block)
        rwsets = None
        barrier_reason = ""
        if is_config or block.header.number == 0:
            barrier_reason = "config"
        else:
            rwsets = [extract_tx_rwset(e) for e in block.data.data]
            if result.vp_dirty:
                barrier_reason = "vp_update"
            elif self._touches_lifecycle(rwsets, result.codes):
                barrier_reason = "lifecycle"
        t1 = time.perf_counter()

        item.result = result
        item.rwsets = rwsets
        item.tx_ids = tx_ids
        self.stats["validated_ahead"] += 1
        self._account_validate(t0, t1)
        if barrier_reason:
            with self._cond:
                if item.epoch == self._epoch:
                    self._barrier_seq = item.seq
                    self._barrier_reason = barrier_reason

    @staticmethod
    def _touches_lifecycle(rwsets, codes) -> bool:
        """Conservative: a VALID tx whose rwset mentions the
        `_lifecycle` namespace may change a chaincode definition later
        blocks validate under."""
        from fabric_tpu.core.scc import lifecycle as lc
        from fabric_tpu.protos import transaction as txpb
        for i, txrw in enumerate(rwsets):
            if txrw is None or \
                    codes[i] != txpb.TxValidationCode.VALID:
                continue
            for nsrw in txrw.ns_rwset:
                if nsrw.namespace == lc.NAMESPACE:
                    return True
        return False

    # -- stage B: ordered commit --

    def _commit_loop(self) -> None:
        tracing.set_node(self.node_id)
        while not self._stop.is_set():
            with self._cond:
                # park (don't spin) while a sticky error awaits reset
                while (not self._validated or
                       self._error is not None) and \
                        not self._stop.is_set():
                    self._cond.wait(timeout=0.2)
                if self._stop.is_set():
                    return
                item = self._validated.pop(0)
                self._committing = item
                self._commit_active_since = time.perf_counter()
            codes = None
            t0 = time.perf_counter()
            try:
                with tracing.span("commit.commit", parent=item.tctx,
                                  seq=item.seq,
                                  fallback=item.fallback):
                    if item.fallback:
                        codes = self._commit_fallback(item)
                    else:
                        # deferred validation side effects: the
                        # predecessor is durably committed NOW, so the
                        # TRANSACTIONS_FILTER stamp and validation
                        # metrics for this block are published
                        # sequentially-equivalently
                        self.channel.validator.publish_validation(
                            item.block, item.result)
                        codes = self.channel.commit_validated(
                            item.block, list(item.result.codes),
                            rwsets=item.rwsets, tx_ids=item.tx_ids)
            except _Rejected as e:
                self._fail_locked(item, e.stage, e.cause)
            except Exception as e:   # noqa: BLE001 — sticky, feeder retries
                logger.exception("[%s] pipelined commit of block [%d] "
                                 "failed", self.channel.channel_id,
                                 item.seq)
                self._fail_locked(item, "commit", e)
            t1 = time.perf_counter()
            with self._cond:
                self._committing = None
                self._commit_active_since = None
                self._commit_window = (t0, t1)
                if item.epoch == self._epoch:
                    self._inflight_txids.pop(item.seq, None)
                    if self._error is None and codes is not None:
                        self._committed_through = item.seq
                        self._inflight -= 1
                self._cond.notify_all()
            if codes is not None:
                self.stats["committed"] += 1
                self.stats["commit_s"] += t1 - t0
                self.stats["last_commit_s"] = t1 - t0
                # user-visible finality (round 18): first-ingress
                # birth -> durable commit on THIS node, feeding
                # e2e_commit_seconds{node=} and the SLO error budget.
                # No carrier/birth for this block's trace = no
                # observation (and tracing-off is a no-op).
                clustertrace.note_commit(item.tctx,
                                         node=self._e2e_node)
                # validate+commit wall for THIS block (fallbacks run
                # validation inside the commit window already): keeps
                # gossip's commit_duration histogram meaning the same
                # thing whether or not the pipeline is on
                self.stats["last_block_s"] = (t1 - t0) + (
                    item.result.duration_s
                    if not item.fallback and item.result is not None
                    else 0.0)
                self._m_commit.set(t1 - t0)
                if self.on_committed is not None:
                    try:
                        self.on_committed(item.seq, item.block, codes)
                    except Exception:   # noqa: BLE001
                        logger.exception("on_committed callback failed")

    def _fail_locked(self, item: _Item, stage: str,
                     cause: BaseException) -> None:
        with self._cond:
            if item.epoch == self._epoch and self._error is None:
                self._error = CommitPipelineError(item.seq, stage,
                                                  cause)
            self._cond.notify_all()

    def _commit_fallback(self, item: _Item) -> list[int]:
        """The sequential path for a demoted block: verify (if stage A
        never got there) + validate + commit, all on this worker, in
        order."""
        self._ensure_parsed_and_verified(item)
        return self.channel.process_block(item.block)

    # -- overlap accounting --

    def _account_validate(self, t0: float, t1: float) -> None:
        """How much of stage A's [t0,t1] ran while stage B was
        committing — the time the pipeline actually hid."""
        with self._cond:
            active = self._commit_active_since
            window = self._commit_window
        overlap = 0.0
        if active is not None:
            overlap += max(0.0, t1 - max(t0, active))
        cs, ce = window
        if ce > cs:
            overlap += max(0.0, min(t1, ce) - max(t0, cs))
        overlap = min(overlap, t1 - t0)
        self.stats["validate_s"] += t1 - t0
        self.stats["overlap_s"] += overlap
        self._m_validate.set(t1 - t0)
        if self.stats["validate_s"] > 0:
            self._m_overlap.set(
                self.stats["overlap_s"] / self.stats["validate_s"])

    @property
    def overlap_ratio(self) -> float:
        return (self.stats["overlap_s"] / self.stats["validate_s"]
                if self.stats["validate_s"] else 0.0)
