"""Transient store: endorsement-time private data, purged by height.

Rebuild of `core/transientstore/store.go`: when a peer endorses a tx
with private writes, the cleartext TxPvtReadWriteSet is parked here
(keyed by tx id + the block height at endorsement time) until the tx
commits — at which point the committer reads it back — or until it goes
stale and is purged by height. Backed by the same embedded ordered KV
store as the ledger (the reference uses a dedicated leveldb).
"""

from __future__ import annotations

import struct
import threading
from typing import Optional

from fabric_tpu.ledger.kvdb import DBHandle, KVStore
from fabric_tpu.protos import rwset as rwpb

_BY_TXID = b"t"    # t + txid + 0x00 + pack(height) -> TxPvtReadWriteSet
_BY_HEIGHT = b"h"  # h + pack(height) + txid -> b""


class TransientStore:
    def __init__(self, path: str):
        self._kv = KVStore(path)
        self._db = DBHandle(self._kv, "transient")
        self._lock = threading.Lock()

    def persist(self, tx_id: str, endorsement_height: int,
                pvt: rwpb.TxPvtReadWriteSet) -> None:
        """Reference: Store.Persist — idempotent per (txid, height)."""
        hb = struct.pack(">Q", endorsement_height)
        batch = self._db.new_batch()
        batch.put(_BY_TXID + tx_id.encode() + b"\x00" + hb,
                  pvt.SerializeToString(deterministic=True))
        batch.put(_BY_HEIGHT + hb + tx_id.encode(), b"")
        self._db.write_batch(batch)

    def get(self, tx_id: str) -> Optional[rwpb.TxPvtReadWriteSet]:
        """Latest entry for the tx id (reference returns an iterator of
        all endorsements; one entry per height suffices here — peers
        re-endorse at a later height under a fresh key)."""
        prefix = _BY_TXID + tx_id.encode() + b"\x00"
        latest = None
        for _k, v in self._db.iterate(start=prefix,
                                      end=prefix + b"\xff"):
            latest = v
        if latest is None:
            return None
        pvt = rwpb.TxPvtReadWriteSet()
        pvt.ParseFromString(latest)
        return pvt

    def purge_by_txids(self, tx_ids: list[str]) -> None:
        """Reference: PurgeByTxids — called after the txs commit."""
        batch = self._db.new_batch()
        for tx_id in tx_ids:
            prefix = _BY_TXID + tx_id.encode() + b"\x00"
            for k, _v in self._db.iterate(start=prefix,
                                          end=prefix + b"\xff"):
                batch.delete(k)
                hb = k[len(prefix):]
                batch.delete(_BY_HEIGHT + hb + tx_id.encode())
        if batch.ops:
            self._db.write_batch(batch)

    def purge_below_height(self, height: int) -> None:
        """Reference: PurgeBelowHeight — drop entries endorsed before
        `height` (their txs either committed long ago or never will)."""
        end = _BY_HEIGHT + struct.pack(">Q", height)
        batch = self._db.new_batch()
        for k, _v in self._db.iterate(start=_BY_HEIGHT, end=end):
            hb = k[1:9]
            tx_id = k[9:]
            batch.delete(k)
            batch.delete(_BY_TXID + tx_id + b"\x00" + hb)
        if batch.ops:
            self._db.write_batch(batch)

    def min_height(self) -> Optional[int]:
        for k, _v in self._db.iterate(start=_BY_HEIGHT,
                                      end=_BY_HEIGHT + b"\xff"):
            return struct.unpack(">Q", k[1:9])[0]
        return None

    def close(self) -> None:
        self._kv.close()
