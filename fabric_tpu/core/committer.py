"""Committer: validated block → ledger, config-block hook.

Rebuild of `core/committer/committer_impl.go:55-70` LedgerCommitter —
a thin wrapper over the ledger commit that first gives the channel a
chance to process config blocks (bundle update), mirroring the
reference's `preCommit` eventer.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Sequence

from fabric_tpu.protos import common
from fabric_tpu.protoutil import protoutil as pu

logger = logging.getLogger("committer")


class LedgerCommitter:
    def __init__(self, ledger,
                 on_config_block: Optional[Callable] = None):
        self._ledger = ledger
        self._on_config_block = on_config_block

    def commit(self, block: common.Block,
               flags: Optional[Sequence[int]] = None,
               pvt_data: Optional[dict] = None,
               rwsets=None, tx_ids=None) -> list[int]:
        if self._on_config_block is not None and \
                pu.is_config_block(block):
            # adopt the config only if the validator accepted it
            # (an INVALID_CONFIG_TRANSACTION block still commits to the
            # chain — with its invalid marker — but changes nothing)
            from fabric_tpu.protos import transaction as txpb
            if not flags or flags[0] == txpb.TxValidationCode.VALID:
                self._on_config_block(block)
            else:
                logger.warning("config block [%d] rejected by "
                               "validation (code %s); not adopting",
                               block.header.number, flags[0])
        return self._ledger.commit_block(block, flags,
                                         pvt_data=pvt_data,
                                         rwsets=rwsets, tx_ids=tx_ids)

    def height(self) -> int:
        return self._ledger.height
