"""Key-level (state-based) endorsement validation.

Rebuild of `core/common/validation/statebased/validator_keylevel.go:1`
and `vpmanagerimpl.go`, wired into the default VSCC the way
`core/handlers/validation/builtin/v20/validation_logic.go:185` does.

Semantics (matching the reference):
  * A key may carry a VALIDATION_PARAMETER metadata entry — an
    endorsement policy that OVERRIDES the chaincode-level policy for
    writes (and metadata updates) to that key.
  * A tx must satisfy the key-level policy of EVERY key it writes that
    has one; the chaincode-level policy is evaluated only if the tx
    writes at least one key with no key-level policy (or writes no keys
    at all).
  * Same-block ordering: if an earlier tx in the block updates a key's
    validation parameter and is VALID, later txs in the block see the
    NEW parameter; if it is invalid, the committed parameter applies.
    The reference resolves this with a dependency/wait graph across its
    parallel validator pool (vpmanagerimpl.go); this validator's policy
    phase is sequential in block order, so the graph degenerates to the
    `BlockOverlay` dict updated as verdicts land.

Batch-first shape: the endorsement signature set is registered ONCE per
tx in the block-wide verify batch (phase 2); every policy — chaincode
level, implicit-collection org rules, and key-level parameters resolved
at finish time — is then pure principal matching over the recovered
valid identities (phase 3, no crypto).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Callable, Optional

from fabric_tpu.common.policies import cauthdsl
from fabric_tpu.common.policies import policy as papi
from fabric_tpu.core.chaincode.shim import VALIDATION_PARAMETER
from fabric_tpu.ledger import pvtdata as pvt
from fabric_tpu.ledger.txmgr import deserialize_metadata
from fabric_tpu.protos import policies as polpb

logger = logging.getLogger("statebased")


@dataclass
class WriteSetInfo:
    """What the VSCC learned from a tx's rwset (extraction phase)."""
    namespace: str = ""              # the chaincode whose rwset this is
    implicit_orgs: tuple = ()        # orgs whose _implicit_org_ colls are written
    public_writes: bool = False
    other_coll_writes: bool = False
    # every written/metadata-updated key: public ones as (None, key),
    # named-collection ones as (coll, hashed_key_str)
    written_keys: list = field(default_factory=list)
    # this tx's VALIDATION_PARAMETER updates, applied to the overlay if
    # the tx commits VALID: (coll_or_None, key) -> policy bytes
    # (b"" = parameter removed — key deleted or VP entry dropped)
    vp_updates: dict = field(default_factory=dict)


class BlockOverlay:
    """VALIDATION_PARAMETER updates by earlier VALID txs of this block,
    keyed by (chaincode namespace, collection, key) — two chaincodes
    writing the same key name must never see each other's parameters."""

    def __init__(self):
        self._vp: dict[tuple[str, Optional[str], str], bytes] = {}

    def get(self, ns: str, coll: Optional[str],
            key: str) -> Optional[bytes]:
        """None = no in-block update; b'' = parameter removed."""
        return self._vp.get((ns, coll, key))

    @property
    def dirty(self) -> bool:
        """True when a VALID tx of this block changed (or removed) a
        validation parameter — the commit-pipeline barrier signal:
        later blocks must see this block's state commit before their
        key-level policies resolve."""
        return bool(self._vp)

    def apply(self, info: WriteSetInfo) -> None:
        for (coll, key), vp in info.vp_updates.items():
            self._vp[(info.namespace, coll, key)] = vp


def extract_write_info(cc_name: str, txrw, kv_parser, hashed_parser
                       ) -> WriteSetInfo:
    """Walk a parsed TxReadWriteSet for the VSCC (helper for
    txvalidator._extract_endorsement_set)."""
    info = WriteSetInfo(namespace=cc_name)
    implicit: list[str] = []
    for nsrw in txrw.ns_rwset:
        if nsrw.namespace != cc_name:
            continue
        kv = kv_parser(nsrw.rwset)
        for w in kv.writes:
            info.written_keys.append((None, w.key))
            if w.is_delete:
                info.vp_updates[(None, w.key)] = b""
        for mw in kv.metadata_writes:
            info.written_keys.append((None, mw.key))
            vp = b""
            for e in mw.entries:
                if e.name == VALIDATION_PARAMETER:
                    vp = e.value
            info.vp_updates[(None, mw.key)] = vp
        if kv.writes:
            info.public_writes = True
        for chrw in nsrw.collection_hashed_rwset:
            hset = hashed_parser(chrw.rwset)
            name = chrw.collection_name
            is_implicit = name.startswith("_implicit_org_")
            if is_implicit:
                if hset.hashed_writes or hset.metadata_writes:
                    implicit.append(name[len("_implicit_org_"):])
                continue
            for hw in hset.hashed_writes:
                hkey = pvt.hashed_key_str(hw.key_hash)
                info.other_coll_writes = True
                info.written_keys.append((name, hkey))
                if hw.is_delete:
                    info.vp_updates[(name, hkey)] = b""
            for mw in hset.metadata_writes:
                hkey = pvt.hashed_key_str(mw.key_hash)
                info.other_coll_writes = True
                info.written_keys.append((name, hkey))
                vp = b""
                for e in mw.entries:
                    if e.name == VALIDATION_PARAMETER:
                        vp = e.value
                info.vp_updates[(name, hkey)] = vp
    info.implicit_orgs = tuple(implicit)
    return info


def memoized_evaluate(cache, pol, identities) -> None:
    """pol.evaluate_identities with optional block-scope memoing keyed
    by (policy, identity sequence) object ids — a pure function over
    block-lifetime objects. Exception-transparent: a cached
    PolicyError re-raises. cache=None evaluates directly."""
    if cache is None:
        pol.evaluate_identities(identities)
        return
    key = (id(pol), tuple(map(id, identities)))
    hit = cache.get(key)
    if hit is None:
        try:
            pol.evaluate_identities(identities)
            cache[key] = True
        except papi.PolicyError as e:
            cache[key] = e
            raise
    elif hit is not True:
        raise hit


def resolve_vp_policy(vp_bytes: bytes, evaluator, deserializer, csp):
    """A validation parameter is ApplicationPolicy bytes (the lifecycle
    format) or a bare SignaturePolicyEnvelope (what the reference's
    statebased shim helpers emit). Accept both."""
    try:
        app = polpb.ApplicationPolicy()
        app.ParseFromString(vp_bytes)
        if app.WhichOneof("type") is not None:
            return evaluator.resolve(vp_bytes)
    # ftpu-lint: allow-swallow(format detection, not failure handling:
    # bytes that do not parse as ApplicationPolicy fall through to the
    # bare SignaturePolicyEnvelope interpretation below)
    except Exception:
        pass
    return cauthdsl.SignaturePolicy.from_bytes(vp_bytes, deserializer, csp)


class KeyLevelPrepared:
    """Two-phase VSCC evaluation with key-level policy resolution
    deferred to finish() — when the block overlay is authoritative for
    every earlier tx.

    items: the endorsement SignedData set, registered ONCE; all policy
    math happens over the valid identities it yields.
    """

    def __init__(self, *, cc_policy, org_policies, info: WriteSetInfo,
                 overlay: BlockOverlay, cc_name: str,
                 metadata_getter: Callable[[Optional[str], str],
                                           Optional[bytes]],
                 evaluator, deserializer, csp, endorsement_sd=None,
                 prepared=None, eval_cache=None, vp_cache=None):
        """`endorsement_sd` (SignedData list) is the item-path input;
        the block fast path passes a ready `prepared`
        (PreparedSignatureSet with already-deduped identities) instead.
        `eval_cache`/`vp_cache` are optional block-scope memo dicts:
        policy evaluation is a pure function of (policy, identities)
        and vp resolution of the parameter bytes, so a block that
        repeats them (the common case) pays once."""
        self._cc_policy = cc_policy
        self._org_policies = list(org_policies)
        self._info = info
        self._overlay = overlay
        self._cc_name = cc_name
        self._get_md = metadata_getter
        self._evaluator = evaluator
        self._deserializer = deserializer
        self._csp = csp
        self._prepared = prepared if prepared is not None else \
            papi.prepare_signature_set(endorsement_sd, deserializer)
        self._eval_cache = eval_cache
        self._vp_cache = vp_cache

    @property
    def items(self):
        return self._prepared.items

    def _eval(self, pol, identities) -> None:
        memoized_evaluate(self._eval_cache, pol, identities)

    def _validation_parameter(self, coll: Optional[str],
                              key: str) -> bytes:
        vp = self._overlay.get(self._cc_name, coll, key)
        if vp is not None:
            return vp
        raw = self._get_md(coll, key)
        return deserialize_metadata(raw).get(VALIDATION_PARAMETER, b"")

    def finish(self, flags) -> None:
        identities = self._prepared.finish(flags)
        # implicit-collection org rules always apply to their writes
        for pol in self._org_policies:
            self._eval(pol, identities)

        info = self._info
        uncovered = not info.written_keys    # no writes → cc policy
        evaluated: set[bytes] = set()
        for coll, key in info.written_keys:
            vp = self._validation_parameter(coll, key)
            if not vp:
                uncovered = True
                continue
            if vp in evaluated:
                continue
            evaluated.add(vp)
            pol = None if self._vp_cache is None \
                else self._vp_cache.get(vp)
            if pol is None:
                try:
                    pol = resolve_vp_policy(vp, self._evaluator,
                                            self._deserializer,
                                            self._csp)
                except Exception as e:
                    raise papi.PolicyError(
                        f"unresolvable validation parameter on key "
                        f"[{self._cc_name}/{coll or ''}/{key}]: {e}"
                    ) from e
                if self._vp_cache is not None:
                    self._vp_cache[vp] = pol
            self._eval(pol, identities)

        if info.implicit_orgs and not info.written_keys:
            # a pure _lifecycle approval (implicit-collection writes
            # only) validates against the org rules alone
            return
        if uncovered and self._cc_policy is not None:
            self._eval(self._cc_policy, identities)

    def record_valid(self) -> None:
        """Called by the validator when this tx's verdict is VALID —
        its VP updates become visible to later txs in the block."""
        self._overlay.apply(self._info)
