"""fabric-tpu: a TPU-native permissioned-blockchain framework.

Clean-room rebuild of the capability surface of Hyperledger Fabric
(reference layer map: SURVEY.md §1) with batched TPU signature
verification as the core compute path (see ARCHITECTURE.md).
"""

__version__ = "0.1.0"
