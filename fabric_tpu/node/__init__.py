from fabric_tpu.node.operations import OperationsServer  # noqa: F401
from fabric_tpu.node.peer_node import PeerNode  # noqa: F401
from fabric_tpu.node.orderer_node import OrdererNode  # noqa: F401
