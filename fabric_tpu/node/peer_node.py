"""Peer node assembly: core.yaml → a serving peer process.

Rebuild of `internal/peer/node/start.go:189-911` serve(): wire BCCSP →
local MSP → Peer (ledgers, endorser, chaincode support) → gossip
service (gRPC transport) → gRPC server (Endorser, Deliver, Gateway,
Gossip) → operations endpoint (metrics/healthz/logspec/version).
Config keys mirror core.yaml (`sampleconfig/core.yaml`), env overrides
CORE_* (e.g. CORE_PEER_ADDRESS) via viperutil.
"""

from __future__ import annotations

import importlib
import logging
import os
from typing import Optional

from fabric_tpu.bccsp import factory as bccsp_factory
from fabric_tpu.comm import clients as comm_clients
from fabric_tpu.comm import services as comm_services
from fabric_tpu.comm.gossip_grpc import GRPCGossipTransport
from fabric_tpu.comm.server import GRPCServer, ServerConfig
from fabric_tpu.common import metrics as metrics_mod
from fabric_tpu.common.viperutil import Config
from fabric_tpu.gossip import GossipService
from fabric_tpu.gossip.discovery import DiscoveryConfig
from fabric_tpu.msp import msp_config_from_dir
from fabric_tpu.msp.mspimpl import X509MSP
from fabric_tpu.node.operations import OperationsServer
from fabric_tpu.peer import Peer
from fabric_tpu.peer.deliverclient import Deliverer
from fabric_tpu.peer.gateway import Gateway
from fabric_tpu.protos import common
from fabric_tpu.protoutil import protoutil as pu

logger = logging.getLogger("peer.node")


class _FailoverBroadcast:
    """Broadcast across orderer endpoints with rotation on failure
    (reference: the SDK/gateway orderer failover behavior; a raft
    follower also rejects while leaderless, which counts as failure
    here)."""

    def __init__(self, endpoints):
        self._endpoints = list(endpoints)
        self._clients = {}

    def process_message(self, env):
        last = None
        for _ in range(len(self._endpoints)):
            ep = self._endpoints[0]
            client = self._clients.get(ep)
            if client is None:
                client = comm_clients.BroadcastClient(
                    comm_clients.channel_to(ep), timeout_s=10.0)
                self._clients[ep] = client
            try:
                resp = client.process_message(env)
                if resp.status == common.Status.SUCCESS:
                    return resp
                last = resp
            except Exception as e:
                logger.warning("broadcast to %s failed: %s", ep, e)
                last = None
            self._endpoints.append(self._endpoints.pop(0))
        if last is not None:
            return last
        from fabric_tpu.protos import orderer as opb
        return opb.BroadcastResponse(
            status=common.Status.SERVICE_UNAVAILABLE,
            info="no orderer reachable")


class PeerNode:
    def __init__(self, config: Config):
        self.cfg = config
        self.peer: Optional[Peer] = None
        self.server: Optional[GRPCServer] = None
        self.ops: Optional[OperationsServer] = None
        self.gossip: Optional[GossipService] = None
        self._orderer_channels = []

    # -- assembly (start.go serve()) --

    def start(self) -> None:
        cfg = self.cfg
        # persistent XLA cache: a restarting peer must not recompile the
        # verify kernel before its first big block (BENCH_r01: ~2 min)
        from fabric_tpu.common import jaxenv
        jaxenv.enable_compilation_cache(
            cfg.get("peer.xlaCompilationCacheDir"))
        provider = metrics_mod.provider_from_config(
            cfg.get("metrics.provider", "prometheus"),
            statsd_address=cfg.get("metrics.statsd.address",
                                   "127.0.0.1:8125"),
            statsd_prefix=cfg.get("metrics.statsd.prefix", ""),
            statsd_interval_s=cfg.get_duration(
                "metrics.statsd.writeInterval", 10.0))
        self.metrics = provider
        from fabric_tpu.common import flogging as _flog
        _flog.wire_logging_metrics(provider)
        # round-14 lifecycle tracing: operations.tracing.* knobs (the
        # viperutil lookup is case-insensitive) + span durations into
        # the trace_stage_seconds histogram; /debug/trace reads the
        # always-on flight recorder
        from fabric_tpu.common import tracing as _tracing
        _tracing.configure_from_config(cfg, metrics_provider=provider)
        # round-18 cross-node layer: the commit-latency SLO target
        # (operations.slo.commitP99S -> /healthz components.slo)
        from fabric_tpu.common import clustertrace as _ctrace
        _ctrace.configure_from_config(cfg)
        # round-19 serving knobs: Operations.Overload.* config keys
        # (env remains the override) + the adaptive controller toggle
        from fabric_tpu.common import adaptive as _adaptive
        from fabric_tpu.common import overload as _overload
        _overload.configure_from_config(cfg)
        _adaptive.configure_from_config(cfg)

        fs_path = cfg.get_path("peer.fileSystemPath")
        os.makedirs(fs_path, exist_ok=True)

        bccsp_cfg = dict(cfg.get("peer.BCCSP") or {})
        # default the warm-key persistence under the peer's data dir so
        # a restarted peer's prewarm rebuilds its Q tables before the
        # first block needs them (BCCSP.TPU.WarmKeysDir overrides)
        tpu_cfg = dict(bccsp_cfg.get("TPU") or {})
        tpu_cfg.setdefault("WarmKeysDir",
                           os.path.join(fs_path, "bccsp-warm"))
        bccsp_cfg["TPU"] = tpu_cfg
        csp = bccsp_factory.new_bccsp(
            bccsp_factory.FactoryOpts.from_config(bccsp_cfg))
        # the TPU provider's perf-cliff counters become scrapeable
        # gauges (bccsp_*) on /metrics
        from fabric_tpu.common import profiling
        profiling.publish_provider_stats(provider, csp)
        # round-16 device-cost gauges: per-chip memory occupancy +
        # busy ratios beside the compile/cache counters above
        profiling.publish_devicecost_stats(provider, csp)
        # round-12 overload stages (commit pipeline, gossip inboxes)
        # as overload_* gauges
        profiling.publish_overload_stats(provider)
        # pre-compile the standard validation shapes in the background
        # so the first blocks after (re)start don't stall on device
        # compilation (BCCSP.TPU.Prewarm: false to disable)
        if hasattr(csp, "prewarm") and \
                (bccsp_cfg.get("TPU") or {}).get("Prewarm", True):
            import threading as _threading
            _threading.Thread(target=csp.prewarm, name="bccsp-prewarm",
                              daemon=True).start()

        msp_dir = cfg.get_path("peer.mspConfigPath")
        msp_id = cfg.get("peer.localMspId", "SampleOrg")
        local_msp = X509MSP(csp)
        local_msp.setup(msp_config_from_dir(msp_dir, msp_id, csp=csp))

        # pluggable state database (reference core.yaml
        # ledger.state.stateDatabase goleveldb|CouchDB): "http" points
        # the VersionedDB seam at an external state-server process
        # (fabric_tpu/ledger/stateserver.py, statecouchdb's role)
        state_db_factory = None
        state_kind = cfg.get("ledger.state.stateDatabase", "embedded")
        if str(state_kind).lower() in ("http", "couchdb"):
            state_addr = cfg.get("ledger.state.stateDatabaseAddress",
                                 "127.0.0.1:5984")
            state_token = cfg.get("ledger.state.stateDatabaseAuthToken",
                                  os.environ.get("FTPU_STATE_TOKEN")
                                  or None)
            from fabric_tpu.ledger.stateserver import HTTPVersionedDB

            def state_db_factory(ledger_id, _handle,
                                 _addr=state_addr,
                                 _tok=state_token):
                return HTTPVersionedDB(_addr, ledger_id,
                                       auth_token=_tok)

            logger.info("state database: external http engine at %s",
                        state_addr)

        # pipelined block intake (core.yaml `peer.CommitPipeline`):
        # Depth N > 0 lets each channel validate up to N blocks ahead
        # of the block being committed; 0 (the default) keeps the
        # sequential verify→validate→commit loop
        cp_cfg = dict(cfg.get("peer.CommitPipeline") or {})
        commit_pipeline_depth = int(cp_cfg.get("Depth", 0) or 0)

        self.peer = Peer(fs_path, local_msp, csp,
                         metrics_provider=provider,
                         state_db_factory=state_db_factory,
                         commit_pipeline_depth=commit_pipeline_depth)
        self.msp_id = msp_id

        # gossip over gRPC; external endpoint = peer.address
        address = cfg.get("peer.address", "127.0.0.1:7051")
        self.gossip = GossipService(
            self.peer, GRPCGossipTransport(address), self.peer.mcs,
            org_id=msp_id,
            config=DiscoveryConfig(
                alive_interval_s=cfg.get_duration(
                    "peer.gossip.aliveTimeInterval", 0.3),
                alive_expiration_s=cfg.get_duration(
                    "peer.gossip.aliveExpirationTimeout", 1.5)))
        self.peer.gossip_service = self.gossip

        # cert-expiration tracking + thread-dump diagnostics
        # (reference start.go:319 TrackExpiration, :913 handleSignals)
        from fabric_tpu.common import cryptoutil, diag
        signcert_dir = os.path.join(msp_dir, "signcerts")
        if os.path.isdir(signcert_dir):
            for name in os.listdir(signcert_dir):
                with open(os.path.join(signcert_dir, name), "rb") as f:
                    cryptoutil.track_expiration("peer enrollment",
                                                f.read())
        diag.capture_thread_dumps_on_signal()

        # gRPC server (+ per-service concurrency caps —
        # reference internal/peer/node/grpc_limiters.go, keys
        # peer.limits.concurrency.* in core.yaml:473-485)
        limits = {}
        for key, svc in (
                ("endorserService", comm_services.ENDORSER_SERVICE),
                ("deliverService", comm_services.DELIVER_SERVICE),
                ("gatewayService", comm_services.GATEWAY_SERVICE)):
            n = int(cfg.get(f"peer.limits.concurrency.{key}", 0) or 0)
            if n > 0:
                limits[svc] = n
        sc = ServerConfig(address=address, metrics_provider=provider,
                          concurrency_limits=limits or None)
        tls_cert = cfg.get_path("peer.tls.cert.file")
        if cfg.get_bool("peer.tls.enabled") and tls_cert:
            sc.tls_cert = open(tls_cert, "rb").read()
            sc.tls_key = open(
                cfg.get_path("peer.tls.key.file"), "rb").read()
            root = cfg.get_path("peer.tls.rootcert.file")
            if cfg.get_bool("peer.tls.clientAuthRequired") and root:
                sc.client_root_cas = open(root, "rb").read()
        self.server = GRPCServer(sc)
        self.address = self.server.address

        gateway = Gateway(self.peer, self._broadcast_client())
        gateway.endorsers[msp_id] = self.peer.endorser
        gateway.endorser_source = self._gossip_endorsers
        self._endorser_clients: dict[str, object] = {}
        from fabric_tpu.discovery import DiscoveryService
        self.discovery = DiscoveryService(self.peer, self.gossip)
        gateway.layout_source = (
            lambda cid, cc: self.discovery.chaincode_layouts(
                self.peer.channel(cid), cc)
            if self.peer.channel(cid) else [])
        comm_services.register_endorser(self.server,
                                        self.peer.endorser)
        comm_services.register_gateway(self.server, gateway)
        comm_services.register_discovery(self.server, self.discovery)
        from fabric_tpu.peer.deliverevents import EventsDeliverHandler
        comm_services.register_peer_deliver(
            self.server, EventsDeliverHandler(
                lambda cid: self.peer.channel(cid),
                metrics_provider=provider))
        comm_services.register_gossip(
            self.server, self.gossip.node._on_message)
        self.server.start()

        bootstrap = cfg.get("peer.gossip.bootstrap") or []
        if isinstance(bootstrap, str):
            bootstrap = bootstrap.split()
        self.gossip.start(bootstrap=bootstrap)

        # operations endpoint (+ the local admin surface the peer CLI
        # uses — the reference routes `peer channel join` through the
        # in-process cscc; here it is an operator-local HTTP call)
        ops_addr = cfg.get("operations.listenAddress", "127.0.0.1:0")
        self.ops = OperationsServer(
            ops_addr, metrics_provider=provider,
            profile_enabled=bool(cfg.get("operations.profile.enabled",
                                         False)))
        self.ops.register_checker("peer", lambda: None)
        # the TPU provider's breaker state on /healthz: degraded means
        # verdicts are served (bit-identically) by the sw path while
        # the device cools down — report, don't fail the node. The
        # elastic-mesh sub-state rides the same string
        # (`device;degraded_mesh:<k>/<n>`): serving on k of n chips
        # after a quarantine — or 1/<requested> when startup device
        # enumeration failed — is degraded-but-serving, never a
        # failed check.
        health = getattr(csp, "health", None)
        if callable(health):
            self.ops.register_checker("bccsp", health)
        # overload state (ok | shedding:<stages>): shedding is
        # degraded-but-serving — load past capacity refused cleanly,
        # never a failed health check
        self.ops.register_checker("overload", _overload.health)
        # commit-latency SLO burn state (ok | burning:<rate>) — this
        # IS the node that commits, so the e2e histogram/error budget
        # fills here; a sustained burn auto-dumps the flight recorder
        self.ops.register_checker("slo", _ctrace.slo_health)
        # round-19 adaptive admission controller: closes the loop
        # from the slo/overload/devicecost signals above onto the
        # registered serving knobs (disabled -> no thread, no moves)
        self.adaptive = _adaptive.start_controller(
            csp=csp, metrics_provider=provider)
        self.ops.register_checker("adaptive", _adaptive.health)
        self.ops.set_trace_peers(
            cfg.get("operations.tracing.clusterPeers")
            or os.environ.get("FTPU_TRACE_PEERS", ""))
        self.ops.register_handler("/admin", self._admin_http)
        self.ops.start()

        # register python chaincodes listed in config (in-process
        # runtime; external CCaaS chaincodes register over gRPC)
        for spec in cfg.get("chaincode.registered") or []:
            name, _, target = spec.partition("=")
            mod_name, _, cls_name = target.partition(":")
            cls = getattr(importlib.import_module(mod_name), cls_name)
            self.peer.chaincode_support.register(name, cls())
            logger.info("registered in-process chaincode %s (%s)",
                        name, target)
        # chaincode-as-a-service processes (reference ccaas_builder):
        # "name=host:port" — the peer dials the chaincode server
        from fabric_tpu.core.chaincode.external import (
            ExternalChaincodeClient,
        )
        for spec in cfg.get("chaincode.external") or []:
            name, _, address = spec.partition("=")
            self.peer.chaincode_support.register(
                name, ExternalChaincodeClient(
                    name, address, metrics_provider=provider))
            logger.info("registered external chaincode %s at %s",
                        name, address)

        # join channels whose genesis blocks are on disk
        for path in cfg.get("peer.channels") or []:
            with open(path, "rb") as f:
                block = common.Block()
                block.ParseFromString(f.read())
            self.join_channel(block)
        logger.info("peer node up: grpc=%s ops=%s", self.address,
                    self.ops.address)

    def _broadcast_client(self):
        endpoints = self.cfg.get("peer.ordererEndpoints") or []
        if not endpoints:
            return None
        return _FailoverBroadcast(endpoints)

    def _deliver_client_factory(self):
        endpoints = list(self.cfg.get("peer.ordererEndpoints") or [])

        def source():
            if not endpoints:
                return None
            # failover rotation (reference blocksprovider endpoint
            # shuffling)
            endpoints.append(endpoints.pop(0))
            return comm_clients.DeliverClient(
                comm_clients.channel_to(endpoints[-1]))
        return source

    def join_channel(self, genesis_block) -> None:
        from fabric_tpu.core.chaincode import ChaincodeDefinition
        channel = self.peer.join_channel(genesis_block)
        # lifecycle-lite: registered USER chaincodes are defined with
        # the channel-default endorsement policy (the state-backed
        # _lifecycle flow supersedes this per-definition)
        from fabric_tpu.core.scc import SYSTEM_CHAINCODES
        for name in self.peer.chaincode_support.registered():
            if name not in SYSTEM_CHAINCODES:
                channel.define_chaincode(ChaincodeDefinition(name=name))
        source = self._deliver_client_factory()
        self.gossip.initialize_channel(
            channel,
            lambda adapter: Deliverer(
                adapter, self.peer.signer, source, self.peer.mcs,
                metrics_provider=getattr(self, "metrics", None)))
        logger.info("joined channel %s", channel.channel_id)

    def _gossip_endorsers(self, channel_id: str) -> dict:
        """One endorser per org, resolved from gossip channel
        membership (the discovery-service feed of the reference's
        gateway registry)."""
        out = {}
        gchannel = self.gossip.node.channel(channel_id)
        if gchannel is None:
            return out
        for m in gchannel.members():
            if not m.identity:
                continue
            org = self.gossip._org_of_identity(m.identity)
            if org is None or org in out or org == self.msp_id:
                continue
            client = self._endorser_clients.get(m.member.endpoint)
            if client is None:
                client = comm_clients.EndorserClient(
                    comm_clients.channel_to(m.member.endpoint))
                self._endorser_clients[m.member.endpoint] = client
            out[org] = client
        return out

    def _admin_http(self, method: str, path: str,
                    body: bytes) -> tuple[int, bytes]:
        import json
        parts = [p for p in path.split("/") if p]
        try:
            if method == "POST" and parts[:2] == ["admin", "channels"]:
                block = common.Block()
                block.ParseFromString(body)
                self.join_channel(block)
                return 201, json.dumps({"status": "joined"}).encode()
            if method == "GET" and parts[:2] == ["admin", "channels"]:
                return 200, json.dumps(
                    {"channels": sorted(self.peer.channels)}).encode()
            if method == "GET" and parts[:2] == ["admin", "chaincodes"]:
                return 200, json.dumps(
                    {"chaincodes":
                     self.peer.chaincode_support.registered()}).encode()
            # snapshots (reference: `peer snapshot` CLI → snapshotgrpc)
            if parts[:2] == ["admin", "snapshots"]:
                return self._snapshot_http(method, parts, body)
        except Exception as e:
            return 400, json.dumps({"error": str(e)}).encode()
        return 404, json.dumps({"error": "not found"}).encode()

    def _snapshot_http(self, method: str, parts: list[str],
                       body: bytes) -> tuple[int, bytes]:
        import json
        # /admin/snapshots/<channel>  POST body={"height": N} submit
        #                             GET → pending + completed
        # /admin/snapshots/<channel>/join  POST body={"dir": path}
        channel = parts[2] if len(parts) > 2 else ""
        if len(parts) == 4 and parts[3] == "join" and method == "POST":
            req = json.loads(body or b"{}")
            ch = self.peer.join_channel_by_snapshot(req["dir"], channel)
            from fabric_tpu.core.chaincode import ChaincodeDefinition
            for name in self.peer.chaincode_support.registered():
                ch.define_chaincode(ChaincodeDefinition(name=name))
            source = self._deliver_client_factory()
            self.gossip.initialize_channel(
                ch, lambda adapter: Deliverer(
                    adapter, self.peer.signer, source, self.peer.mcs,
                    metrics_provider=getattr(self, "metrics", None)))
            return 201, json.dumps(
                {"status": "joined", "height": ch.ledger.height}
            ).encode()
        ch = self.peer.channel(channel)
        if ch is None:
            return 404, json.dumps({"error": "unknown channel"}).encode()
        if method == "POST":
            req = json.loads(body or b"{}")
            height = int(req.get("height") or ch.ledger.height)
            ch.ledger.snapshot_requests.submit(height)
            return 201, json.dumps({"status": "submitted",
                                    "height": height}).encode()
        completed_dir = ch.ledger.snapshots_dir()
        completed = sorted(os.listdir(completed_dir)) \
            if os.path.isdir(completed_dir) else []
        return 200, json.dumps(
            {"pending": ch.ledger.snapshot_requests.pending(),
             "completed": completed,
             "dir": completed_dir}).encode()

    def stop(self) -> None:
        from fabric_tpu.common import adaptive as _adaptive
        _adaptive.stop_controller()
        if self.gossip:
            self.gossip.stop()
        if self.server:
            self.server.stop()
        if self.ops:
            self.ops.stop()
        if self.peer:
            self.peer.close()
        # final metrics flush + flusher-thread shutdown (statsd)
        stop_metrics = getattr(getattr(self, "metrics", None), "stop",
                               None)
        if stop_metrics is not None:
            stop_metrics()
