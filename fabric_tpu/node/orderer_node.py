"""Orderer node assembly: orderer.yaml → a serving orderer process.

Rebuild of `orderer/common/server/main.go:73-300` Main(): local config
→ BCCSP → local MSP → multichannel registrar (solo + raft consenters,
gRPC cluster transport) → gRPC server (AtomicBroadcast, Deliver,
Cluster) → operations endpoint with the channel-participation admin
API mounted (reference: admin server + osnadmin). Env overrides
ORDERER_* (e.g. ORDERER_GENERAL_LISTENADDRESS).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

from fabric_tpu.bccsp import factory as bccsp_factory
from fabric_tpu.comm import services as comm_services
from fabric_tpu.comm.cluster_grpc import GRPCClusterTransport
from fabric_tpu.comm.server import GRPCServer, ServerConfig
from fabric_tpu.common import metrics as metrics_mod
from fabric_tpu.common.deliver import DeliverHandler
from fabric_tpu.common.viperutil import Config
from fabric_tpu.msp import msp_config_from_dir
from fabric_tpu.msp.mspimpl import X509MSP
from fabric_tpu.node.operations import OperationsServer
from fabric_tpu.orderer import raft as raft_mod, solo
from fabric_tpu.orderer.broadcast import BroadcastHandler
from fabric_tpu.orderer.channelparticipation import (
    ChannelParticipation, ParticipationError,
)
from fabric_tpu.orderer.multichannel import Registrar
from fabric_tpu.protos import common

logger = logging.getLogger("orderer.node")


class OrdererNode:
    def __init__(self, config: Config):
        self.cfg = config
        self.server: Optional[GRPCServer] = None
        self.ops: Optional[OperationsServer] = None
        self.registrar: Optional[Registrar] = None
        self.cluster: Optional[GRPCClusterTransport] = None

    def start(self) -> None:
        cfg = self.cfg
        from fabric_tpu.common import jaxenv
        jaxenv.enable_compilation_cache(
            cfg.get("General.XLACompilationCacheDir"))
        provider = metrics_mod.provider_from_config(
            cfg.get("Metrics.Provider", "prometheus"),
            statsd_address=cfg.get("Metrics.Statsd.Address",
                                   "127.0.0.1:8125"),
            statsd_prefix=cfg.get("Metrics.Statsd.Prefix", ""),
            statsd_interval_s=cfg.get_duration(
                "Metrics.Statsd.WriteInterval", 10.0))
        self.metrics = provider
        from fabric_tpu.common import flogging as _flog
        _flog.wire_logging_metrics(provider)
        # round-14 lifecycle tracing: Operations.Tracing.* knobs +
        # span durations into the trace_stage_seconds histogram (the
        # recorder itself is always on; /debug/trace reads it)
        from fabric_tpu.common import tracing as _tracing
        _tracing.configure_from_config(cfg, metrics_provider=provider)
        # round-18 cross-node layer: the commit-latency SLO target
        # (Operations.SLO.CommitP99S -> /healthz components.slo)
        from fabric_tpu.common import clustertrace as _ctrace
        _ctrace.configure_from_config(cfg)
        # round-19 serving knobs: Operations.Overload.* config keys
        # (env remains the override) + the adaptive controller toggle
        from fabric_tpu.common import adaptive as _adaptive
        from fabric_tpu.common import overload as _overload
        _overload.configure_from_config(cfg)
        _adaptive.configure_from_config(cfg)

        bccsp_cfg = cfg.get("General.BCCSP") or {}
        csp = bccsp_factory.new_bccsp(
            bccsp_factory.FactoryOpts.from_config(bccsp_cfg))
        # breaker/degradation counters (bccsp_*) scrapeable on the
        # orderer's /metrics too, not just the peer's
        from fabric_tpu.common import profiling
        profiling.publish_provider_stats(provider, csp)
        # round-16 device-cost gauges: per-chip memory occupancy +
        # busy ratios beside the compile/cache counters above
        profiling.publish_devicecost_stats(provider, csp)
        # round-12 overload stages (broadcast ingress, raft event
        # queues, write stages, admission window) as overload_* gauges
        profiling.publish_overload_stats(provider)
        msp_dir = cfg.get_path("General.LocalMSPDir")
        msp_id = cfg.get("General.LocalMSPID", "OrdererMSP")
        local_msp = X509MSP(csp)
        local_msp.setup(msp_config_from_dir(msp_dir, msp_id, csp=csp))
        signer = local_msp.get_default_signing_identity()

        address = cfg.get("General.ListenAddress", "127.0.0.1") + ":" \
            + str(cfg.get("General.ListenPort", 7050))

        # Cluster transport. With TLS material configured (reference
        # `General.Cluster` in orderer.yaml), cluster RPCs get a
        # DEDICATED mutual-TLS listener and callers are authenticated
        # against each channel's consenter set; without it, the Cluster
        # service shares the general listener unauthenticated (dev
        # only — a warning is logged on first use).
        cluster_server_cert = cfg.get_path("Cluster.ServerCertificate")
        cluster_tls = bool(cluster_server_cert)
        cluster_listen = (cfg.get("Cluster.ListenAddress", "127.0.0.1")
                          + ":" + str(cfg.get("Cluster.ListenPort", 0)))
        root_ca_paths = cfg.get("Cluster.RootCAs") or []
        if isinstance(root_ca_paths, str):
            root_ca_paths = [root_ca_paths]
        root_cas = b"".join(
            open(cfg.resolve_path(p), "rb").read()
            for p in root_ca_paths) or None

        def _read(key):
            p = cfg.get_path(key)
            return open(p, "rb").read() if p else None

        client_cert = _read("Cluster.ClientCertificate") or \
            (_read("Cluster.ServerCertificate") if cluster_tls else None)
        client_key = _read("Cluster.ClientPrivateKey") or \
            (_read("Cluster.ServerPrivateKey") if cluster_tls else None)

        # the advertised consenter endpoint
        cluster_ep = cfg.get("Cluster.Endpoint",
                             cluster_listen if cluster_tls else address)
        self.cluster = GRPCClusterTransport(
            cluster_ep,
            tls_root_ca=root_cas if cluster_tls else None,
            client_cert=client_cert, client_key=client_key,
            require_client_auth=cluster_tls,
            metrics_provider=provider)

        ledger_dir = cfg.get_path("FileLedger.Location")
        os.makedirs(ledger_dir, exist_ok=True)
        tick = cfg.get_duration("Consensus.TickInterval", 0.1)
        def _kafka_deprecated(support):
            raise ValueError(
                f"[{support.channel_id}] the kafka consenter is "
                "deprecated (as in the reference's 2.x line) and not "
                "provided; migrate the channel to etcdraft")

        self.registrar = Registrar(
            ledger_dir, signer, csp,
            {"solo": solo.consenter,
             "raft": raft_mod.consenter(self.cluster,
                                        tick_interval_s=tick,
                                        metrics_provider=provider),
             "etcdraft": raft_mod.consenter(self.cluster,
                                            tick_interval_s=tick,
                                            metrics_provider=provider),
             "kafka": _kafka_deprecated},
            metrics_provider=provider,
            cluster_transport=self.cluster)
        # batched-ordering pipeline gauges (orderer_batch_*) beside
        # the provider's bccsp_* ones
        profiling.publish_order_stats(provider, self.registrar)
        from fabric_tpu.orderer.broadcast import BroadcastMetrics
        broadcast = BroadcastHandler(
            self.registrar, metrics=BroadcastMetrics(provider))
        from fabric_tpu.common.deliver import DeliverMetrics
        deliver = DeliverHandler(self.registrar.get_chain,
                                 metrics=DeliverMetrics(provider))
        participation = ChannelParticipation(self.registrar)

        from fabric_tpu.common import cryptoutil, diag
        signcert_dir = os.path.join(msp_dir, "signcerts")
        if os.path.isdir(signcert_dir):
            for name in os.listdir(signcert_dir):
                with open(os.path.join(signcert_dir, name), "rb") as f:
                    cryptoutil.track_expiration("orderer enrollment",
                                                f.read())
        diag.capture_thread_dumps_on_signal()

        sc = ServerConfig(address=address, metrics_provider=provider)
        tls_cert = cfg.get_path("General.TLS.Certificate")
        if cfg.get_bool("General.TLS.Enabled") and tls_cert:
            sc.tls_cert = open(tls_cert, "rb").read()
            sc.tls_key = open(
                cfg.get_path("General.TLS.PrivateKey"), "rb").read()
        self.server = GRPCServer(sc)
        self.address = self.server.address
        comm_services.register_broadcast(self.server, broadcast)
        comm_services.register_deliver(self.server, deliver)
        if cluster_tls:
            cluster_sc = ServerConfig(
                address=cluster_listen,
                tls_cert=open(cluster_server_cert, "rb").read(),
                tls_key=open(
                    cfg.get_path("Cluster.ServerPrivateKey"),
                    "rb").read(),
                client_root_cas=root_cas,  # mTLS required
                metrics_provider=provider)
            self.cluster_server = GRPCServer(cluster_sc)
            comm_services.register_cluster(self.cluster_server,
                                           self.cluster)
            self.cluster_server.start()
            logger.info("cluster mTLS listener on %s",
                        self.cluster_server.address)
        else:
            self.cluster_server = None
            comm_services.register_cluster(self.server, self.cluster)
        self.server.start()

        ops_addr = cfg.get("Admin.ListenAddress",
                           cfg.get("Operations.ListenAddress",
                                   "127.0.0.1:0"))
        self.ops = OperationsServer(
            ops_addr, metrics_provider=provider,
            profile_enabled=bool(cfg.get("Operations.Profile.Enabled",
                                         False)))
        self.ops.register_checker("orderer", lambda: None)
        # breaker state of the sig-filter's TPU provider on /healthz
        # (device | degraded | probing); degraded still serves. The
        # elastic-mesh sub-state (`;degraded_mesh:<k>/<n>` — serving
        # on k of n chips after a quarantine, or 1/<requested> when
        # startup enumeration failed) rides the same string.
        health = getattr(csp, "health", None)
        if callable(health):
            self.ops.register_checker("bccsp", health)
        # onboarding/replication state (discover|pull|verify|commit|
        # failed per channel) — degraded-but-serving, like the bccsp
        # breaker: catch-up in progress never fails the health check
        self.ops.register_checker("onboarding",
                                  self.registrar.onboarding_health)
        # overload state (ok | shedding:<stages>): shedding is
        # degraded-but-serving — the orderer refusing load past
        # capacity with SERVICE_UNAVAILABLE is working as designed
        self.ops.register_checker("overload", _overload.health)
        # commit-latency SLO burn state (ok | burning:<rate>):
        # degraded-but-serving, the breaker-trip trigger discipline —
        # a sustained burn also auto-dumps the flight recorder
        self.ops.register_checker("slo", _ctrace.slo_health)
        # round-19 adaptive admission controller: closes the loop
        # from the slo/overload/devicecost signals above onto the
        # registered serving knobs (disabled -> no thread, no moves)
        self.adaptive = _adaptive.start_controller(
            csp=csp, metrics_provider=provider)
        self.ops.register_checker("adaptive", _adaptive.health)
        self.ops.set_trace_peers(
            cfg.get("Operations.Tracing.ClusterPeers")
            or os.environ.get("FTPU_TRACE_PEERS", ""))
        self.ops.register_handler("/participation",
                                  self._participation_http(
                                      participation))
        self.ops.start()

        # bootstrap: join channels from configured genesis blocks
        for path in cfg.get("General.BootstrapFiles") or []:
            with open(path, "rb") as f:
                block = common.Block()
                block.ParseFromString(f.read())
            try:
                self.registrar.join(block)
            except ValueError as e:
                if "already exists" not in str(e):
                    raise
        logger.info("orderer node up: grpc=%s admin=%s", self.address,
                    self.ops.address)

    @staticmethod
    def _participation_http(participation: ChannelParticipation):
        """REST-ish mapping (reference
        `orderer/common/channelparticipation/rest.go`):
        GET  /participation/v1/channels
        GET  /participation/v1/channels/<name>
        POST /participation/v1/channels        (body: config block)
        DELETE /participation/v1/channels/<name>"""
        from google.protobuf.json_format import MessageToDict

        def handler(method: str, path: str,
                    body: bytes) -> tuple[int, bytes]:
            parts = [p for p in path.split("/") if p]
            # ["participation", "v1", "channels", <name>?]
            try:
                if method == "GET" and len(parts) == 3:
                    out = MessageToDict(participation.list())
                    return 200, json.dumps(out).encode()
                if method == "GET" and len(parts) == 4:
                    out = MessageToDict(participation.info(parts[3]))
                    return 200, json.dumps(out).encode()
                if method == "POST" and len(parts) == 3:
                    info = participation.join(body)
                    return 201, json.dumps(
                        MessageToDict(info)).encode()
                if method == "DELETE" and len(parts) == 4:
                    participation.remove(parts[3])
                    return 204, b""
            except ParticipationError as e:
                return e.status, json.dumps(
                    {"error": str(e)}).encode()
            return 405, json.dumps({"error": "bad request"}).encode()
        return handler

    def stop(self) -> None:
        from fabric_tpu.common import adaptive as _adaptive
        _adaptive.stop_controller()
        if self.registrar:
            self.registrar.halt()
        if self.cluster:
            self.cluster.close()
        if getattr(self, "cluster_server", None):
            self.cluster_server.stop()
        if self.server:
            self.server.stop()
        if self.ops:
            self.ops.stop()
        stop_metrics = getattr(getattr(self, "metrics", None), "stop",
                               None)
        if stop_metrics is not None:
            stop_metrics()
