"""Operations endpoint: /metrics, /healthz, /logspec, /version.

Rebuild of `core/operations/system.go:67-195` + `common/fabhttp`: one
HTTP listener per node serving Prometheus metrics, health checks
(pluggable checkers, reference healthz lib), runtime log-level
changes (flogging admin) and the version. Extra handlers (orderer
channel participation) mount under their own prefixes.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from fabric_tpu.common import flogging

logger = logging.getLogger("operations")

VERSION = "0.2.0"


class OperationsServer:
    def __init__(self, address: str = "127.0.0.1:0",
                 metrics_provider=None, version: str = VERSION,
                 profile_enabled: bool = False):
        host, port = address.rsplit(":", 1)
        self._metrics = metrics_provider
        self._version = version
        if metrics_provider is not None:
            from fabric_tpu.common import metrics as _m
            try:
                metrics_provider.new_gauge(_m.GaugeOpts(
                    namespace="fabric", name="version",
                    help="The active version of the node software "
                         "(constant 1, labeled by version).",
                    label_names=("version",))).with_labels(
                    "version", version).set(1)
            except Exception:
                logger.debug("fabric_version gauge unavailable")
        self._profile_enabled = profile_enabled
        self._checkers: dict[str, Callable[[], None]] = {}
        self._extra: dict[str, Callable] = {}
        # round 18: peer ops endpoints the cluster-trace merge pulls
        # /debug/trace from (host:port strings)
        self._trace_peers: list[str] = []
        ops = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                logger.debug("ops: " + fmt, *args)

            def _reply(self, code: int, body: bytes,
                       ctype: str = "application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                ops._route(self, "GET")

            def do_POST(self):
                ops._route(self, "POST")

            def do_PUT(self):
                ops._route(self, "PUT")

            def do_DELETE(self):
                ops._route(self, "DELETE")

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self.port = self._httpd.server_address[1]
        self.address = f"{host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    # -- plumbing --

    def register_checker(self, component: str,
                         check: Callable[[], None]) -> None:
        """`check()` raises when unhealthy (reference: healthz
        HealthChecker). A checker may also RETURN a status string
        (e.g. the bccsp breaker's device|degraded|probing) — surfaced
        in the healthz body's `components` map without failing the
        check, for states that are degraded-but-serving."""
        self._checkers[component] = check

    def set_trace_peers(self, peers) -> None:
        """Configure the ops addresses `/debug/trace/cluster` merges
        (`Operations.Tracing.ClusterPeers` — list or comma string)."""
        if isinstance(peers, str):
            peers = [p.strip() for p in peers.split(",") if p.strip()]
        self._trace_peers = list(peers or [])

    def register_handler(self, prefix: str,
                         fn: Callable[[str, str, bytes],
                                      tuple[int, bytes]]) -> None:
        """Mount `fn(method, path, body) -> (status, json_bytes)`
        under a path prefix (participation API etc.)."""
        self._extra[prefix] = fn

    def _route(self, h, method: str) -> None:
        path = h.path.split("?", 1)[0]
        try:
            if path == "/healthz" and method == "GET":
                self._healthz(h)
            elif path == "/metrics" and method == "GET":
                body = (self._metrics.render()
                        if self._metrics is not None and
                        hasattr(self._metrics, "render") else "")
                h._reply(200, body.encode(),
                         "text/plain; version=0.0.4")
            elif path == "/version" and method == "GET":
                h._reply(200, json.dumps(
                    {"Version": self._version}).encode())
            elif path == "/logspec":
                self._logspec(h, method)
            elif path == "/debug/trace" and method == "GET":
                # the flight recorder (common/tracing.py) is always on
                # by design — reading it is the POSTMORTEM surface, so
                # unlike the profiling endpoints below it is not gated
                # by operations.profile.enabled. ?trace_id= filters to
                # one transaction's spans (round 18: pulling one probe
                # must not ship the whole ring).
                from fabric_tpu.common import tracing
                h._reply(200, json.dumps(tracing.chrome_trace(
                    trace_id=self._query_param(h, "trace_id")
                )).encode())
            elif path == "/debug/trace/cluster" and method == "GET":
                # cluster view (round 18): this recorder merged with
                # every configured peer's /debug/trace onto one wall-
                # aligned timeline (tids = node/stage; residual clock
                # skew reported in the ftpu.cluster header, peer fetch
                # failures reported, never fatal)
                from fabric_tpu.common import clustertrace
                h._reply(200, json.dumps(clustertrace.cluster_trace(
                    self._trace_peers,
                    trace_id=self._query_param(h, "trace_id")
                )).encode())
            elif path.startswith("/debug/") and method == "GET":
                self._debug(h, path)
            else:
                for prefix, fn in self._extra.items():
                    if path.startswith(prefix):
                        length = int(h.headers.get("Content-Length",
                                                   "0") or 0)
                        body = h.rfile.read(length) if length else b""
                        status, out = fn(method, path, body)
                        h._reply(status, out)
                        return
                h._reply(404, b'{"Error":"not found"}')
        except Exception as e:
            logger.exception("ops handler error")
            try:
                h._reply(500, json.dumps({"Error": str(e)}).encode())
            except Exception as reply_exc:
                logger.warning("ops: could not deliver 500 reply for "
                               "%s %s: %s", method, path, reply_exc)

    @staticmethod
    def _query_param(h, name: str) -> Optional[str]:
        from urllib.parse import parse_qs, urlparse
        try:
            return parse_qs(urlparse(h.path).query)[name][0] or None
        except (KeyError, IndexError):
            return None

    def _healthz(self, h) -> None:
        failed = []
        components = {}
        for name, check in self._checkers.items():
            try:
                status = check()
            except Exception as e:
                failed.append({"component": name, "reason": str(e)})
                components[name] = "failed"
                continue
            if isinstance(status, str) and status:
                components[name] = status
        body: dict = {"status": "OK"}
        if components:
            body["components"] = components
        if failed:
            body["status"] = "Service Unavailable"
            body["failed_checks"] = failed
            h._reply(503, json.dumps(body).encode())
        else:
            h._reply(200, json.dumps(body).encode())

    def _debug(self, h, path: str) -> None:
        """pprof-analog surfaces (reference: net/http/pprof on the ops
        listener when peer.profile.enabled — `cmd/peer/main.go:10`,
        `internal/peer/node/start.go:842-850`):
          /debug/threads            thread stacks (goroutine dump twin)
          /debug/profile?seconds=N  sampling CPU profile
          /debug/jax/trace?seconds=N         xplane capture of device
                                             activity (SURVEY §5)
        Gated by `operations.profile.enabled` exactly like the
        reference's pprof listener; trace output always lands in a
        server-chosen temp directory (clients must not pick filesystem
        paths).
        """
        from urllib.parse import parse_qs, urlparse

        from fabric_tpu.common import diag, profiling
        if not self._profile_enabled:
            h._reply(403, b'{"Error":"profiling disabled: set '
                          b'operations.profile.enabled"}')
            return
        q = parse_qs(urlparse(h.path).query)

        def qf(name, default):
            try:
                return float(q[name][0])
            except (KeyError, ValueError, IndexError):
                return default

        if path == "/debug/threads":
            h._reply(200, diag.dump_threads(log=lambda *a: None)
                     .encode(), "text/plain")
        elif path == "/debug/profile":
            secs = min(60.0, qf("seconds", 5.0))
            h._reply(200, profiling.sample_profile(secs).encode(),
                     "text/plain")
        elif path == "/debug/jax/trace":
            secs = min(60.0, qf("seconds", 3.0))
            try:
                # bounded output (keep-last-N capture dirs under one
                # managed parent) and an immediate 409 when a capture
                # is already live — the second request used to park
                # on the profiler lock for the whole window
                traced = profiling.capture_jax_trace_bounded(secs)
            except profiling.ProfilerBusyError as e:
                h._reply(409, json.dumps({"Error": str(e)}).encode())
                return
            h._reply(200, json.dumps({"trace_dir": traced}).encode())
        else:
            h._reply(404, b'{"Error":"unknown debug surface"}')

    def _logspec(self, h, method: str) -> None:
        if method == "GET":
            h._reply(200, json.dumps(
                {"spec": flogging.spec()}).encode())
            return
        if method == "PUT":
            length = int(h.headers.get("Content-Length", "0") or 0)
            body = json.loads(h.rfile.read(length) or b"{}")
            flogging.activate_spec(body.get("spec", "info"))
            h._reply(204, b"")
            return
        h._reply(405, b'{"Error":"method not allowed"}')

    # -- lifecycle --

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="operations", daemon=True)
        self._thread.start()
        logger.info("operations endpoint on %s", self.address)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=2)
