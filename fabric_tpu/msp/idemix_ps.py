"""Pointcheval-Sanders anonymous credentials over BN254: the
zero-knowledge layer of the idemix MSP.

Round-3 verdict #6: the pseudonym scheme let the ISSUER link a
member's transactions. This module removes that caveat with the same
cryptographic architecture the reference uses (`msp/idemix.go`
wrapping IBM/idemix BBS+ over BN254 — SURVEY §2.2): a randomizable
pairing-based credential plus a Schnorr signature of knowledge.
Pointcheval-Sanders (CT-RSA'16) is the modern, simpler construction
with the same properties BBS+ provides here: blind issuance (the
issuer never learns the member secret), perfect re-randomization (two
presentations of one credential share no common values), and selective
disclosure (OU/role shown, member secret hidden).

Protocol (additive notation; G1/G2 are BN254 groups of prime order R,
G~ the G2 generator on the twist):

  Issuer keys   sk = (x, y_sk, y_ou, y_role);
                pk = (X~ = x*G~, Y~_i = y_i*G~) in G2
                   + Y_sk = y_sk*G in G1 (the blind-issuance base).

  Blind issue   member: secret m_sk, blinder s;
                  C = m_sk*Y_sk + s*G  (Pedersen, perfectly hiding)
                  + Schnorr PoK of (m_sk, s) on C.
                issuer: random u; sigma1 = u*G,
                  sigma2 = u*(x*G + C + (m_ou*y_ou + m_role*y_role)*G).
                member unblinds: sigma2 -= s*sigma1 — a PS signature on
                (m_sk, m_ou, m_role). The issuer saw only C.

  Present       random t, r: sigma1' = t*sigma1,
                sigma2' = t*(sigma2 + r*sigma1);
                T~ = m_sk*Y~_sk + r*G~   (perfectly hiding in r)
                SoK over the presented message (Fiat-Shamir):
                  K~ = k1*Y~_sk + k2*G~
                  c  = H(pk | sigma' | T~ | K~ | disclosed | msg)
                  s1 = k1 + c*m_sk,  s2 = k2 + c*r   (mod R)

  Verify        K~' = s1*Y~_sk + s2*G~ - c*T~ ; recompute c; and the
                pairing equation
                  e(sigma1', D~ + T~) == e(sigma2', G~)
                with D~ = X~ + m_ou*Y~_ou + m_role*Y~_role computed by
                the VERIFIER from the disclosed attributes. The
                pairing rides `csp.pairing_check_batch` — one 2-term
                product lane per credential, device-batched on the TPU
                provider (BASELINE config 4's surface).

Host math is integer scalar work (this module + ops/bn254_ref); the
pairing products are the only heavy step and stay on device.
Differential tests: tests/test_idemix_ps.py (hand-computed vectors,
tamper corpus, unlinkability property).
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass

from fabric_tpu.ops import bn254_ref as b

G1 = b.G1
G2T = (b.G2_X, b.G2_Y)
R = b.R

_CTX = b"ftpu-idemix-ps-v1|"


def _h_scalar(*parts: bytes) -> int:
    h = hashlib.sha256()
    for p in parts:
        h.update(len(p).to_bytes(4, "big"))
        h.update(p)
    return int.from_bytes(h.digest(), "big") % R


def _rand_scalar() -> int:
    return (int.from_bytes(os.urandom(48), "big") % (R - 1)) + 1


def attr_scalar(value: str | int) -> int:
    """Disclosed attributes enter the credential as scalars."""
    if isinstance(value, int):
        return value % R
    return _h_scalar(b"attr", value.encode())


def _g1b(p) -> bytes:
    return b.g1_to_bytes(p) if p is not None else b"\x00" * 64


def _g2b(q) -> bytes:
    return b.g2_to_bytes(q) if q is not None else b"\x00" * 128


@dataclass
class PSPublicKey:
    X_t: tuple          # X~  (G2 twist)
    Y_sk_t: tuple       # Y~_sk
    Y_ou_t: tuple       # Y~_ou
    Y_role_t: tuple     # Y~_role
    Y_sk_1: tuple       # Y_sk (G1 blind-issuance base)

    def to_bytes(self) -> bytes:
        return (_g2b(self.X_t) + _g2b(self.Y_sk_t) + _g2b(self.Y_ou_t)
                + _g2b(self.Y_role_t) + _g1b(self.Y_sk_1))

    @classmethod
    def from_bytes(cls, raw: bytes) -> "PSPublicKey":
        if len(raw) != 4 * 128 + 64:
            raise ValueError("PS public key must be 576 bytes")
        qs = [b.g2_from_bytes(raw[i * 128:(i + 1) * 128])
              for i in range(4)]
        return cls(*qs, b.g1_from_bytes(raw[512:]))


@dataclass
class PSSecretKey:
    x: int
    y_sk: int
    y_ou: int
    y_role: int


def keygen(seed: bytes | None = None) -> tuple[PSSecretKey, PSPublicKey]:
    if seed is not None:
        def rnd(tag):
            return _h_scalar(b"ps-keygen", seed, tag) or 1
        x, y_sk, y_ou, y_role = (rnd(b"x"), rnd(b"ysk"), rnd(b"you"),
                                 rnd(b"yrole"))
    else:
        x, y_sk, y_ou, y_role = (_rand_scalar() for _ in range(4))
    sk = PSSecretKey(x, y_sk, y_ou, y_role)
    pk = PSPublicKey(
        X_t=b.g2_mul_fast(x, G2T), Y_sk_t=b.g2_mul_fast(y_sk, G2T),
        Y_ou_t=b.g2_mul_fast(y_ou, G2T), Y_role_t=b.g2_mul_fast(y_role, G2T),
        Y_sk_1=b.g1_mul_fast(y_sk, G1))
    return sk, pk


# ---- blind issuance ----

@dataclass
class CredentialRequest:
    commitment: tuple       # C in G1
    c: int                  # PoK challenge
    s_sk: int               # PoK responses
    s_blind: int


def request_credential(pk: PSPublicKey, m_sk: int
                       ) -> tuple[CredentialRequest, int]:
    """Member side: Pedersen commitment to the member secret + PoK.
    Returns (request, blinder) — keep the blinder for unblinding."""
    s = _rand_scalar()
    C = b.g1_add_fast(b.g1_mul_fast(m_sk, pk.Y_sk_1), b.g1_mul_fast(s, G1))
    k1, k2 = _rand_scalar(), _rand_scalar()
    K = b.g1_add_fast(b.g1_mul_fast(k1, pk.Y_sk_1), b.g1_mul_fast(k2, G1))
    c = _h_scalar(_CTX + b"req", pk.to_bytes(), _g1b(C), _g1b(K))
    return CredentialRequest(
        commitment=C, c=c, s_sk=(k1 + c * m_sk) % R,
        s_blind=(k2 + c * s) % R), s


def verify_request(pk: PSPublicKey, req: CredentialRequest) -> bool:
    """Issuer side: the requester must KNOW the committed secret (a
    commitment lifted from another member would not verify)."""
    lhs = b.g1_add_fast(b.g1_mul_fast(req.s_sk, pk.Y_sk_1),
                   b.g1_mul_fast(req.s_blind, G1))
    K = b.g1_add_fast(lhs, b.g1_neg(b.g1_mul_fast(req.c, req.commitment)))
    c = _h_scalar(_CTX + b"req", pk.to_bytes(), _g1b(req.commitment),
                  _g1b(K))
    return c == req.c


def blind_sign(sk: PSSecretKey, pk: PSPublicKey,
               req: CredentialRequest, ou: str, role: int
               ) -> tuple[tuple, tuple]:
    """Issuer side: sign the hidden commitment + disclosed attributes.
    Returns (sigma1, blinded sigma2)."""
    if not verify_request(pk, req):
        raise ValueError("credential request proof of knowledge failed")
    u = _rand_scalar()
    sigma1 = b.g1_mul_fast(u, G1)
    m_ou, m_role = attr_scalar(ou), attr_scalar(role)
    acc = b.g1_mul_fast((sk.x + m_ou * sk.y_ou + m_role * sk.y_role) % R,
                   G1)
    acc = b.g1_add_fast(acc, req.commitment)
    return sigma1, b.g1_mul_fast(u, acc)


def unblind(sigma1: tuple, sigma2_blinded: tuple,
            blinder: int) -> tuple[tuple, tuple]:
    """Member side: sigma2 = sigma2' - s*sigma1."""
    return sigma1, b.g1_add_fast(sigma2_blinded,
                            b.g1_neg(b.g1_mul_fast(blinder, sigma1)))


def credential_valid(pk: PSPublicKey, sigma: tuple[tuple, tuple],
                     m_sk: int, ou: str, role: int) -> bool:
    """Member-side intake check (host pairing): e(sigma1, X~ +
    m_sk*Y~_sk + m_ou*Y~_ou + m_role*Y~_role) == e(sigma2, G~)."""
    sigma1, sigma2 = sigma
    if sigma1 is None or sigma2 is None:
        return False
    q = pk.X_t
    q = b.g2_add_fast(q, b.g2_mul_fast(m_sk, pk.Y_sk_t))
    q = b.g2_add_fast(q, b.g2_mul_fast(attr_scalar(ou), pk.Y_ou_t))
    q = b.g2_add_fast(q, b.g2_mul_fast(attr_scalar(role), pk.Y_role_t))
    f1 = b.miller_loop(q, sigma1)
    f2 = b.miller_loop(b.g2_neg_tw(G2T), sigma2)
    return b.final_exponentiation(b.f12_mul(f1, f2)) == b.F12_ONE


# ---- presentation (signature of knowledge) ----

@dataclass
class Presentation:
    sigma1: tuple
    sigma2: tuple
    T_t: tuple
    c: int
    s_sk: int
    s_r: int

    def to_proto(self):
        from fabric_tpu.protos import msp as msppb
        return msppb.IdemixPresentation(
            sigma1=_g1b(self.sigma1), sigma2=_g1b(self.sigma2),
            t_commit=_g2b(self.T_t),
            c=self.c.to_bytes(32, "big"),
            s_sk=self.s_sk.to_bytes(32, "big"),
            s_r=self.s_r.to_bytes(32, "big"))

    @classmethod
    def from_proto(cls, p, defer_subgroup: bool = False
                   ) -> "Presentation":
        """defer_subgroup=True skips only T~'s prime-order membership
        test (on-curve still enforced) — the MSP batch verifier runs
        it on device alongside the Schnorr recombination
        (subgroup_msm_lane); NEVER defer without that companion
        check."""
        return cls(
            sigma1=b.g1_from_bytes(bytes(p.sigma1)),
            sigma2=b.g1_from_bytes(bytes(p.sigma2)),
            T_t=b.g2_from_bytes(bytes(p.t_commit),
                                subgroup_check=not defer_subgroup),
            c=int.from_bytes(bytes(p.c), "big"),
            s_sk=int.from_bytes(bytes(p.s_sk), "big"),
            s_r=int.from_bytes(bytes(p.s_r), "big"))


def _challenge(pk: PSPublicKey, sigma1, sigma2, T_t, K_t, ou: str,
               role: int, msg: bytes) -> int:
    return _h_scalar(
        _CTX + b"present", pk.to_bytes(), _g1b(sigma1), _g1b(sigma2),
        _g2b(T_t), _g2b(K_t), ou.encode(),
        role.to_bytes(4, "big", signed=True), msg)


def present(pk: PSPublicKey, sigma: tuple[tuple, tuple], m_sk: int,
            ou: str, role: int, msg: bytes) -> Presentation:
    """Prove possession of a credential over the hidden member secret,
    binding `msg` (the authorized pseudonym key, a tx digest, ...)."""
    sigma1, sigma2 = sigma
    t, r = _rand_scalar(), _rand_scalar()
    s1p = b.g1_mul_fast(t, sigma1)
    s2p = b.g1_mul_fast(t, b.g1_add_fast(sigma2, b.g1_mul_fast(r, sigma1)))
    T_t = b.g2_add_fast(b.g2_mul_fast(m_sk, pk.Y_sk_t), b.g2_mul_fast(r, G2T))
    k1, k2 = _rand_scalar(), _rand_scalar()
    K_t = b.g2_add_fast(b.g2_mul_fast(k1, pk.Y_sk_t), b.g2_mul_fast(k2, G2T))
    c = _challenge(pk, s1p, s2p, T_t, K_t, ou, role, msg)
    return Presentation(sigma1=s1p, sigma2=s2p, T_t=T_t, c=c,
                        s_sk=(k1 + c * m_sk) % R,
                        s_r=(k2 + c * r) % R)


def schnorr_checks(pres: Presentation) -> bool:
    """Structural gates before any expensive math."""
    if pres.sigma1 is None or pres.sigma1 == (0, 0):
        return False
    if not (b.on_curve_g1(pres.sigma1) and b.on_curve_g1(pres.sigma2)
            and b.on_curve_g2(pres.T_t)):
        return False
    return (0 < pres.c < R and 0 <= pres.s_sk < R
            and 0 <= pres.s_r < R)


def schnorr_msm_lane(pk: PSPublicKey, pres: Presentation) -> list:
    """The 3-term G2 MSM whose result is the recombined commitment
    K~ = s_sk*Y~ + s_r*G~ - c*T~ — batchable across presentations on
    device (TPUProvider.g2_msm_batch)."""
    return [(pres.s_sk, pk.Y_sk_t), (pres.s_r, G2T),
            ((R - pres.c) % R, pres.T_t)]


def subgroup_msm_lane(pres: Presentation) -> list:
    """[6x^2]T~ as a 3-term lane (zero-padded): with the host-cheap
    psi(T~) compare this is the prime-order membership test
    (bn254_ref.g2_in_subgroup), batched on device for deferred
    deserializations."""
    return [(6 * b.T_BN * b.T_BN, pres.T_t), (0, None), (0, None)]


def verify_schnorr_prepared(pk: PSPublicKey, pres: Presentation,
                            ou: str, role: int, msg: bytes,
                            K_t) -> bool:
    """Finish half: the challenge-hash compare, given the recombined
    K~ (from the batched device MSM or the host Strauss MSM)."""
    c = _challenge(pk, pres.sigma1, pres.sigma2, pres.T_t, K_t, ou,
                   role, msg)
    return c == pres.c


def verify_schnorr(pk: PSPublicKey, pres: Presentation, ou: str,
                   role: int, msg: bytes) -> bool:
    """The host half of verification: the Schnorr signature of
    knowledge. The pairing half is `pairing_product` below. (Single
    presentation; the MSP batches the MSM across presentations via
    schnorr_msm_lane + verify_schnorr_prepared.)"""
    if not schnorr_checks(pres):
        return False
    # one interleaved 3-term MSM (shared doublings) instead of three
    # independent ladders — the host half's measured hot spot
    K_t = b.g2_msm(schnorr_msm_lane(pk, pres))
    return verify_schnorr_prepared(pk, pres, ou, role, msg, K_t)


def pairing_product(pk: PSPublicKey, pres: Presentation, ou: str,
                    role: int) -> list[tuple]:
    """The device half: one 2-term pairing-product lane —
    e(sigma1', D~ + T~) * e(-sigma2', G~) == 1 — in the
    `csp.pairing_check_batch` input format."""
    D_t = b.g2_add_fast(pk.X_t,
                   b.g2_mul_fast(attr_scalar(ou), pk.Y_ou_t))
    D_t = b.g2_add_fast(D_t, b.g2_mul_fast(attr_scalar(role), pk.Y_role_t))
    q = b.g2_add_fast(D_t, pres.T_t)
    return [(pres.sigma1, q),
            (b.g1_neg(pres.sigma2), G2T)]


def verify_presentation_host(pk: PSPublicKey, pres: Presentation,
                             ou: str, role: int, msg: bytes) -> bool:
    """Full host verification (the exact oracle for tests; production
    batches the pairing half on device)."""
    if not verify_schnorr(pk, pres, ou, role, msg):
        return False
    terms = pairing_product(pk, pres, ou, role)
    f = b.f12_scalar(1)
    for p1, q2 in terms:
        if p1 is None:
            return False
        f = b.f12_mul(f, b.miller_loop(q2, p1))
    return b.final_exponentiation(f) == b.F12_ONE
