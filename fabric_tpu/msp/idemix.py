"""Idemix MSP: anonymous, verifier-unlinkable transaction identities.

Rebuild of the reference's idemix MSP surface (`msp/idemix.go` wrapping
`github.com/IBM/idemix` — SURVEY §2.2): an org's members transact under
pseudonyms; a verifier learns ONLY the org (MSP id) and the disclosed
attributes (OU, role) — two transactions by the same member cannot be
linked by any channel participant.

Construction (scheme="ps", the default): a zero-knowledge credential
layer over BN254 pairings (Pointcheval-Sanders randomizable
signatures, fabric_tpu/msp/idemix_ps.py) with the SAME architecture as
the reference's BBS+ — blind issuance (the issuer never learns the
member secret), per-use re-randomized presentation (a signature of
knowledge over the pseudonym being authorized), selective disclosure
of OU/role. Unlinkability holds against EVERY party including the
issuer. The transaction signature itself stays ECDSA-P256 under a
fresh pseudonym key certified by the presentation, so tx verification
rides the TPU batch verify path unchanged, and the credential
presentations verify with host Schnorr math plus ONE device-batched
pairing product per credential (`csp.pairing_check_batch`).

Legacy schemes kept for benchmarks/back-compat: "ecdsa" (issuer binds
pseudonym batches by P-256 — issuer CAN link) and "bls" (pairing-bound
pseudonym batches — the round-3 construction).
"""

from __future__ import annotations

import hashlib
import threading
from typing import Optional, Sequence

from fabric_tpu.bccsp._crypto_compat import ec, serialization

from fabric_tpu.bccsp import bccsp as bapi
from fabric_tpu.bccsp import utils as butils
from fabric_tpu.msp import msp as api
from fabric_tpu.msp.mspimpl import MSPError
from fabric_tpu.protos import msp as msppb, policies as polpb

_CRED_CONTEXT = b"ftpu-idemix-credential-v1|"


def _presentation_msg(nym_pub: bytes, ou: str, role: int) -> bytes:
    """What a PS presentation signs: the pseudonym being authorized
    plus the disclosed attributes (binding them to the proof). Signed
    encoding: the proto role is int32, and a hostile negative value
    must fail verification, not raise."""
    return (b"ftpu-idemix-nym-v1|" + nym_pub + b"|" + ou.encode() +
            b"|" + role.to_bytes(4, "big", signed=True))


def _credential_digest(nym_pub: bytes, ou: str, role: int) -> bytes:
    return hashlib.sha256(
        _CRED_CONTEXT + nym_pub + b"|" + ou.encode() + b"|" +
        role.to_bytes(4, "big")).digest()


class IdemixIssuer:
    """Org-side credential issuer (the reference's idemixgen +
    issuer role).

    Two signature schemes over the SAME credential digest:
      * "ecdsa" (default): issuer binding by P-256 — rides the batched
        TPU ECDSA verify path with zero extra kernels;
      * "bls": issuer binding by a BLS signature over BN254 — verified
        with PAIRINGS, device-batched (`bls_verify_batch`), the
        measurable analog of the reference's pairing-based credential
        check (`msp/idemix.go` → vendored IBM/idemix; BASELINE
        config 4).
    """

    def __init__(self, csp, signing_key=None, scheme: str = "ps"):
        self._csp = csp
        self.scheme = scheme
        self._key = signing_key or ec.generate_private_key(
            ec.SECP256R1())
        if scheme == "bls":
            from fabric_tpu.ops import bn254_ref as bref
            import os as _os
            self._bls_sk, self._bls_pk = bref.bls_keygen(_os.urandom(32))
        elif scheme == "ps":
            from fabric_tpu.msp import idemix_ps as ps
            self._ps_sk, self._ps_pk = ps.keygen()

    def public_key_pem(self) -> bytes:
        return self._key.public_key().public_bytes(
            serialization.Encoding.PEM,
            serialization.PublicFormat.SubjectPublicKeyInfo)

    def bls_public_key_bytes(self) -> bytes:
        from fabric_tpu.ops import bn254_ref as bref
        return bref.g2_to_bytes(self._bls_pk)

    def ps_public_key_bytes(self) -> bytes:
        return self._ps_pk.to_bytes()

    def issue_blind(self, request, ou: str,
                    role: int = api.MSPRole.MEMBER):
        """The blind half of PS issuance (member built `request` with
        `idemix_ps.request_credential` — the issuer sees only a
        perfectly-hiding commitment to the member secret). Returns
        (sigma1, blinded sigma2) for the member to unblind."""
        from fabric_tpu.msp import idemix_ps as ps
        return ps.blind_sign(self._ps_sk, self._ps_pk, request, ou,
                             role)

    def issue(self, ou: str, role: int = api.MSPRole.MEMBER,
              count: int = 1) -> list[tuple[object,
                                            msppb.IdemixCredential]]:
        """A batch of one-time pseudonym credentials: [(private key,
        credential)]. Under "ps" (the default) each credential carries
        a zero-knowledge presentation authorizing the pseudonym — the
        convenience form of the blind protocol (issue_blind /
        idemix_ps.request_credential are the real separated halves;
        this helper runs both sides in-process for tooling/tests)."""
        out = []
        if self.scheme == "ps":
            from fabric_tpu.msp import idemix_ps as ps
            for _ in range(count):
                m_sk = ps._rand_scalar()
                req, blinder = ps.request_credential(self._ps_pk, m_sk)
                s1, s2b = ps.blind_sign(self._ps_sk, self._ps_pk, req,
                                        ou, role)
                sigma = ps.unblind(s1, s2b, blinder)
                nym_priv = ec.generate_private_key(ec.SECP256R1())
                nym_pub = nym_priv.public_key().public_bytes(
                    serialization.Encoding.DER,
                    serialization.PublicFormat.SubjectPublicKeyInfo)
                pres = ps.present(
                    self._ps_pk, sigma, m_sk, ou, role,
                    _presentation_msg(nym_pub, ou, role))
                cred = msppb.IdemixCredential(nym_pub=nym_pub, ou=ou,
                                              role=role)
                out.append((nym_priv, (cred, pres.to_proto())))
            return out
        for _ in range(count):
            nym_priv = ec.generate_private_key(ec.SECP256R1())
            nym_pub = nym_priv.public_key().public_bytes(
                serialization.Encoding.DER,
                serialization.PublicFormat.SubjectPublicKeyInfo)
            digest = _credential_digest(nym_pub, ou, role)
            if self.scheme == "bls":
                from fabric_tpu.ops import bn254_ref as bref
                sig_pt = bref.bls_sign(self._bls_sk, digest)
                out.append((nym_priv, msppb.IdemixCredential(
                    nym_pub=nym_pub, ou=ou, role=role,
                    bls_sig=bref.g1_to_bytes(sig_pt))))
                continue
            from fabric_tpu.bccsp._crypto_compat import Prehashed, hashes
            sig = self._key.sign(digest,
                                 ec.ECDSA(Prehashed(hashes.SHA256())))
            r, s = butils.unmarshal_signature(sig)
            sig = butils.marshal_signature(r, butils.to_low_s(s))
            out.append((nym_priv, msppb.IdemixCredential(
                nym_pub=nym_pub, ou=ou, role=role, issuer_sig=sig)))
        return out


class IdemixIdentity(api.Identity):
    def __init__(self, msp: "IdemixMSP",
                 credential: msppb.IdemixCredential, nym_key,
                 presentation=None):
        self._msp = msp
        self.credential = credential
        self._nym_key = nym_key   # bccsp key (public)
        # PS scheme: the zero-knowledge presentation authorizing this
        # pseudonym (msppb.IdemixPresentation)
        self.presentation = presentation

    def id_bytes(self) -> bytes:
        return bytes(self.credential.nym_pub)

    def mspid(self) -> str:
        return self._msp.identifier()

    def serialize(self) -> bytes:
        sid = msppb.SerializedIdentity()
        sid.mspid = self.mspid()
        wrapped = msppb.SerializedIdemixIdentity()
        wrapped.credential.CopyFrom(self.credential)
        if self.presentation is not None:
            wrapped.presentation.CopyFrom(self.presentation)
        sid.id_bytes = wrapped.SerializeToString()
        return sid.SerializeToString()

    def validate(self) -> None:
        self._msp.validate(self)

    def verify(self, msg: bytes, sig: bytes) -> bool:
        digest = self._msp.csp.hash(msg)
        return self._msp.csp.verify(self._nym_key, sig, digest)

    def verify_item(self, msg: bytes, sig: bytes) -> bapi.VerifyItem:
        """Pseudonym signatures are plain P-256 — they join the SAME
        batched verify as X.509 identities."""
        return bapi.VerifyItem(key=self._nym_key, signature=sig,
                               message=msg)

    def organizational_units(self) -> Sequence[str]:
        return (self.credential.ou,) if self.credential.ou else ()

    def expires_at(self) -> Optional[float]:
        return None

    def satisfies_principal(self, principal) -> None:
        self._msp.satisfies_principal(self, principal)


class IdemixSigningIdentity(IdemixIdentity, api.SigningIdentity):
    def __init__(self, msp: "IdemixMSP",
                 credential: msppb.IdemixCredential, nym_key,
                 nym_priv, presentation=None):
        super().__init__(msp, credential, nym_key,
                         presentation=presentation)
        self._priv = nym_priv

    def sign(self, msg: bytes) -> bytes:
        from fabric_tpu.bccsp._crypto_compat import hashes
        sig = self._priv.sign(msg, ec.ECDSA(hashes.SHA256()))
        r, s = butils.unmarshal_signature(sig)
        return butils.marshal_signature(r, butils.to_low_s(s))


class IdemixMSP(api.MSP):
    """Reference surface: `msp/idemix.go` idemixmsp."""

    def __init__(self, csp):
        self.csp = csp
        self._id = ""
        self._issuer_pub = None          # bccsp key
        self._issuer_pub_raw = b""
        self._lock = threading.Lock()
        self._signers: list[IdemixSigningIdentity] = []

    def identifier(self) -> str:
        return self._id

    def setup(self, config: msppb.MSPConfig) -> None:
        if config.type != 1:
            raise MSPError("not an idemix MSP config")
        idc = msppb.IdemixMSPConfig()
        idc.ParseFromString(config.config)
        self._id = idc.name
        self._issuer_pub_raw = bytes(idc.issuer_public_key)
        issuer_key = serialization.load_pem_public_key(
            self._issuer_pub_raw)
        self._issuer_pub = self.csp.key_import(
            issuer_key, bapi.ECDSAPublicKeyImportOpts())
        self._issuer_bls_pk = None
        if idc.issuer_bls_public_key:
            from fabric_tpu.ops import bn254_ref as bref
            self._issuer_bls_pk = bref.g2_from_bytes(
                bytes(idc.issuer_bls_public_key))
        self._issuer_ps_pk = None
        if idc.issuer_ps_public_key:
            from fabric_tpu.msp import idemix_ps as ps
            self._issuer_ps_pk = ps.PSPublicKey.from_bytes(
                bytes(idc.issuer_ps_public_key))

    # -- credential intake (member side) --

    def add_credentials(self, creds) -> None:
        """Load issued (nym_priv, credential) pairs for signing. PS
        credentials arrive as (nym_priv, (credential, presentation))."""
        with self._lock:
            for nym_priv, cred in creds:
                pres = None
                if isinstance(cred, tuple):
                    cred, pres = cred
                nym_key = self._import_nym(bytes(cred.nym_pub))
                self._signers.append(IdemixSigningIdentity(
                    self, cred, nym_key, nym_priv,
                    presentation=pres))

    def get_default_signing_identity(self) -> IdemixSigningIdentity:
        """Pops a FRESH pseudonym per call — consecutive transactions
        are unlinkable (the reference re-randomizes its credential per
        signature; same observable effect)."""
        with self._lock:
            if not self._signers:
                raise MSPError(
                    f"idemix MSP {self._id}: no unused pseudonym "
                    "credentials; request a new batch from the issuer")
            return self._signers.pop()

    # -- deserialization / validation (verifier side) --

    def _import_nym(self, nym_pub_der: bytes):
        return self.csp.key_import(nym_pub_der,
                                   bapi.ECDSAPublicKeyImportOpts())

    def deserialize_identity(self, serialized: bytes) -> IdemixIdentity:
        sid = msppb.SerializedIdentity()
        sid.ParseFromString(serialized)
        if sid.mspid != self._id:
            raise MSPError(
                f"expected MSP ID {self._id!r}, got {sid.mspid!r}")
        wrapped = msppb.SerializedIdemixIdentity()
        wrapped.ParseFromString(sid.id_bytes)
        cred = wrapped.credential
        has_pres = wrapped.HasField("presentation")
        if not cred.nym_pub or not (cred.issuer_sig or cred.bls_sig
                                    or has_pres):
            raise MSPError("idemix identity lacks a credential")
        nym_key = self._import_nym(bytes(cred.nym_pub))
        return IdemixIdentity(
            self, cred, nym_key,
            presentation=wrapped.presentation if has_pres else None)

    def is_well_formed(self, serialized: bytes) -> None:
        self.deserialize_identity(serialized)

    def validate(self, identity: IdemixIdentity) -> None:
        """Issuer binding: the credential must carry a valid issuer
        signature over (nym, disclosed attributes) — P-256, or BLS
        verified by PAIRING when the org configured a BLS issuer key."""
        if not self.validate_credentials_batch([identity])[0]:
            raise MSPError(
                f"idemix credential not signed by the {self._id} "
                "issuer")

    def validate_credentials_batch(self, identities) -> list[bool]:
        """Batched issuer-binding checks; BLS credentials go through
        ONE pairing-product dispatch (`csp.bls_verify_batch` — the
        device path on the TPU provider), ECDSA credentials through
        the ordinary batched verify. This is the measurable surface
        for BASELINE config 4."""
        import time as _time
        _t = {"parse_s": 0.0, "msm_s": 0.0, "schnorr_s": 0.0,
              "pairing_s": 0.0}
        _t0 = _time.perf_counter()
        out = [False] * len(identities)
        bls_idx, bls_digests, bls_sigs = [], [], []
        ec_idx, ec_items = [], []
        ps_idx, ps_products = [], []
        ps_pending = []            # (i, pres, ou, role, msg)
        for i, ident in enumerate(identities):
            cred = ident.credential
            if getattr(ident, "presentation", None) is not None:
                if self._issuer_ps_pk is None:
                    continue                  # no PS trust anchor
                from fabric_tpu.msp import idemix_ps as ps
                try:
                    # subgroup test deferred: it batches on device
                    # below with the Schnorr recombination
                    pres = ps.Presentation.from_proto(
                        ident.presentation, defer_subgroup=True)
                    msg = _presentation_msg(bytes(cred.nym_pub),
                                            cred.ou, cred.role)
                    if not ps.schnorr_checks(pres):
                        continue
                except Exception:
                    # a hostile presentation must fail ITS lane, never
                    # poison the whole batch
                    continue
                ps_pending.append((i, pres, cred.ou, cred.role, msg))
                continue
            digest = _credential_digest(bytes(cred.nym_pub), cred.ou,
                                        cred.role)
            if cred.bls_sig:
                if self._issuer_bls_pk is None:
                    continue                      # no BLS trust anchor
                from fabric_tpu.ops import bn254_ref as bref
                try:
                    pt = bref.g1_from_bytes(bytes(cred.bls_sig))
                except ValueError:
                    pt = None
                bls_idx.append(i)
                bls_digests.append(digest)
                bls_sigs.append(pt)
            else:
                ec_idx.append(i)
                ec_items.append(bapi.VerifyItem(
                    key=self._issuer_pub,
                    signature=bytes(cred.issuer_sig), digest=digest))
        _t["parse_s"] = _time.perf_counter() - _t0
        if ps_pending:
            # ONE device dispatch recombines every presentation's
            # Schnorr K~ AND runs every T~'s prime-order membership
            # test ([6x^2]T~ vs host-cheap psi(T~)); the reference
            # verifies each credential proof serially on CPU
            from fabric_tpu.msp import idemix_ps as ps
            from fabric_tpu.ops import bn254_ref as bref
            lanes = []
            for _i, pres, _ou, _role, _msg in ps_pending:
                lanes.append(ps.schnorr_msm_lane(
                    self._issuer_ps_pk, pres))
                lanes.append(ps.subgroup_msm_lane(pres))
            csp = self.csp
            _t1 = _time.perf_counter()
            if hasattr(csp, "g2_msm_batch"):
                msm = csp.g2_msm_batch(lanes)
            else:
                msm = [bref.g2_msm(lane) for lane in lanes]
            _t["msm_s"] = _time.perf_counter() - _t1
            _t1 = _time.perf_counter()
            for j, (i, pres, ou, role, msg) in enumerate(ps_pending):
                K_t, sub = msm[2 * j], msm[2 * j + 1]
                if sub != bref.g2_frobenius_fast(pres.T_t):
                    continue          # T~ outside the r-subgroup
                if not ps.verify_schnorr_prepared(
                        self._issuer_ps_pk, pres, ou, role, msg, K_t):
                    continue
                ps_idx.append(i)
                ps_products.append(ps.pairing_product(
                    self._issuer_ps_pk, pres, ou, role))
            _t["schnorr_s"] = _time.perf_counter() - _t1
        if ec_items:
            for i, ok in zip(ec_idx, self.csp.verify_batch(ec_items)):
                out[i] = ok
        if bls_idx:
            csp = self.csp
            if not hasattr(csp, "bls_verify_batch"):
                from fabric_tpu.bccsp.sw import SWProvider
                csp = SWProvider()       # exact host pairing fallback
            res = csp.bls_verify_batch(
                self._issuer_bls_pk, bls_digests, bls_sigs)
            for i, ok in zip(bls_idx, res):
                out[i] = ok
        if ps_idx:
            csp = self.csp
            if not hasattr(csp, "pairing_check_batch"):
                from fabric_tpu.bccsp.sw import SWProvider
                csp = SWProvider()       # exact host pairing fallback
            _t1 = _time.perf_counter()
            res = csp.pairing_check_batch(ps_products)
            _t["pairing_s"] = _time.perf_counter() - _t1
            for i, ok in zip(ps_idx, res):
                out[i] = ok
        # coarse phase timings for the perf harness (bench_idemix):
        # where a PS batch's wall clock went on the last call
        self.last_batch_timings = {k: round(v, 4)
                                   for k, v in _t.items()}
        return out

    def satisfies_principal(self, identity: IdemixIdentity,
                            principal: polpb.MSPPrincipal) -> None:
        self.validate(identity)
        cred = identity.credential
        if principal.classification == polpb.MSPPrincipal.ROLE:
            role = polpb.MSPRole()
            role.ParseFromString(principal.principal)
            if role.msp_identifier != self._id:
                raise MSPError(
                    f"role principal is for MSP "
                    f"{role.msp_identifier!r}")
            if role.role == polpb.MSPRole.MEMBER:
                return
            if role.role == polpb.MSPRole.ADMIN and \
                    cred.role == api.MSPRole.ADMIN:
                return
            if role.role == polpb.MSPRole.CLIENT and \
                    cred.role in (api.MSPRole.CLIENT,
                                  api.MSPRole.MEMBER):
                return
            raise MSPError(
                f"idemix identity does not hold role {role.role}")
        if principal.classification == \
                polpb.MSPPrincipal.ORGANIZATION_UNIT:
            ou = polpb.OrganizationUnit()
            ou.ParseFromString(principal.principal)
            if ou.msp_identifier != self._id:
                raise MSPError("OU principal is for another MSP")
            if cred.ou != ou.organizational_unit_identifier:
                raise MSPError(
                    f"disclosed OU {cred.ou!r} does not match")
            return
        raise MSPError(
            "idemix supports ROLE and ORGANIZATION_UNIT principals")


def idemix_msp_config(name: str,
                      issuer: IdemixIssuer) -> msppb.MSPConfig:
    """Channel-config material for an idemix org (reference:
    idemixgen output consumed by configtxgen)."""
    idc = msppb.IdemixMSPConfig(
        name=name, issuer_public_key=issuer.public_key_pem())
    if issuer.scheme == "bls":
        idc.issuer_bls_public_key = issuer.bls_public_key_bytes()
    elif issuer.scheme == "ps":
        idc.issuer_ps_public_key = issuer.ps_public_key_bytes()
    return msppb.MSPConfig(type=1,
                           config=idc.SerializeToString())
