"""Idemix MSP: anonymous, verifier-unlinkable transaction identities.

Rebuild of the reference's idemix MSP surface (`msp/idemix.go` wrapping
`github.com/IBM/idemix` — SURVEY §2.2): an org's members transact under
pseudonyms; a verifier learns ONLY the org (MSP id) and the disclosed
attributes (OU, role) — two transactions by the same member cannot be
linked by any channel participant.

Construction (documented divergence): the reference uses BBS+
credentials over BN254 pairings, where the member re-randomizes one
long-lived credential per transaction and proves possession in zero
knowledge. Pairing verification is CPU-heavy and incompatible with this
framework's batched P-256 verify path. Here the SAME privacy contract
is met with *pseudonym credentials*: the org's idemix issuer signs
batches of fresh one-time pseudonym keys (plus the disclosed OU/role —
never the holder's enrollment identity), and the member signs each
transaction with a different pseudonym. Verifier-side unlinkability is
information-theoretic (independent keys); org membership is bound by
the issuer signature. Trade-offs vs BBS+: the ISSUER can link (the
reference grants its auditor the same power via the encrypted
enrollment id), and members must refresh credential batches. In
exchange every idemix verification is ordinary ECDSA-P256 — it rides
the TPU batch verify path with zero extra kernels.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Optional, Sequence

from cryptography.hazmat.primitives import serialization
from cryptography.hazmat.primitives.asymmetric import ec

from fabric_tpu.bccsp import bccsp as bapi
from fabric_tpu.bccsp import utils as butils
from fabric_tpu.msp import msp as api
from fabric_tpu.msp.mspimpl import MSPError
from fabric_tpu.protos import msp as msppb, policies as polpb

_CRED_CONTEXT = b"ftpu-idemix-credential-v1|"


def _credential_digest(nym_pub: bytes, ou: str, role: int) -> bytes:
    return hashlib.sha256(
        _CRED_CONTEXT + nym_pub + b"|" + ou.encode() + b"|" +
        role.to_bytes(4, "big")).digest()


class IdemixIssuer:
    """Org-side credential issuer (the reference's idemixgen +
    issuer role).

    Two signature schemes over the SAME credential digest:
      * "ecdsa" (default): issuer binding by P-256 — rides the batched
        TPU ECDSA verify path with zero extra kernels;
      * "bls": issuer binding by a BLS signature over BN254 — verified
        with PAIRINGS, device-batched (`bls_verify_batch`), the
        measurable analog of the reference's pairing-based credential
        check (`msp/idemix.go` → vendored IBM/idemix; BASELINE
        config 4).
    """

    def __init__(self, csp, signing_key=None, scheme: str = "ecdsa"):
        self._csp = csp
        self.scheme = scheme
        self._key = signing_key or ec.generate_private_key(
            ec.SECP256R1())
        if scheme == "bls":
            from fabric_tpu.ops import bn254_ref as bref
            import os as _os
            self._bls_sk, self._bls_pk = bref.bls_keygen(_os.urandom(32))

    def public_key_pem(self) -> bytes:
        return self._key.public_key().public_bytes(
            serialization.Encoding.PEM,
            serialization.PublicFormat.SubjectPublicKeyInfo)

    def bls_public_key_bytes(self) -> bytes:
        from fabric_tpu.ops import bn254_ref as bref
        return bref.g2_to_bytes(self._bls_pk)

    def issue(self, ou: str, role: int = api.MSPRole.MEMBER,
              count: int = 1) -> list[tuple[object,
                                            msppb.IdemixCredential]]:
        """A batch of one-time pseudonym credentials: [(private key,
        credential)]. The issuer NEVER sees how/when each is used on
        channel — only that it issued `count` of them."""
        out = []
        for _ in range(count):
            nym_priv = ec.generate_private_key(ec.SECP256R1())
            nym_pub = nym_priv.public_key().public_bytes(
                serialization.Encoding.DER,
                serialization.PublicFormat.SubjectPublicKeyInfo)
            digest = _credential_digest(nym_pub, ou, role)
            if self.scheme == "bls":
                from fabric_tpu.ops import bn254_ref as bref
                sig_pt = bref.bls_sign(self._bls_sk, digest)
                out.append((nym_priv, msppb.IdemixCredential(
                    nym_pub=nym_pub, ou=ou, role=role,
                    bls_sig=bref.g1_to_bytes(sig_pt))))
                continue
            from cryptography.hazmat.primitives.asymmetric.utils import (
                Prehashed,
            )
            from cryptography.hazmat.primitives import hashes
            sig = self._key.sign(digest,
                                 ec.ECDSA(Prehashed(hashes.SHA256())))
            r, s = butils.unmarshal_signature(sig)
            sig = butils.marshal_signature(r, butils.to_low_s(s))
            out.append((nym_priv, msppb.IdemixCredential(
                nym_pub=nym_pub, ou=ou, role=role, issuer_sig=sig)))
        return out


class IdemixIdentity(api.Identity):
    def __init__(self, msp: "IdemixMSP",
                 credential: msppb.IdemixCredential, nym_key):
        self._msp = msp
        self.credential = credential
        self._nym_key = nym_key   # bccsp key (public)

    def id_bytes(self) -> bytes:
        return bytes(self.credential.nym_pub)

    def mspid(self) -> str:
        return self._msp.identifier()

    def serialize(self) -> bytes:
        sid = msppb.SerializedIdentity()
        sid.mspid = self.mspid()
        wrapped = msppb.SerializedIdemixIdentity()
        wrapped.credential.CopyFrom(self.credential)
        sid.id_bytes = wrapped.SerializeToString()
        return sid.SerializeToString()

    def validate(self) -> None:
        self._msp.validate(self)

    def verify(self, msg: bytes, sig: bytes) -> bool:
        digest = self._msp.csp.hash(msg)
        return self._msp.csp.verify(self._nym_key, sig, digest)

    def verify_item(self, msg: bytes, sig: bytes) -> bapi.VerifyItem:
        """Pseudonym signatures are plain P-256 — they join the SAME
        batched verify as X.509 identities."""
        return bapi.VerifyItem(key=self._nym_key, signature=sig,
                               message=msg)

    def organizational_units(self) -> Sequence[str]:
        return (self.credential.ou,) if self.credential.ou else ()

    def expires_at(self) -> Optional[float]:
        return None

    def satisfies_principal(self, principal) -> None:
        self._msp.satisfies_principal(self, principal)


class IdemixSigningIdentity(IdemixIdentity, api.SigningIdentity):
    def __init__(self, msp: "IdemixMSP",
                 credential: msppb.IdemixCredential, nym_key,
                 nym_priv):
        super().__init__(msp, credential, nym_key)
        self._priv = nym_priv

    def sign(self, msg: bytes) -> bytes:
        from cryptography.hazmat.primitives import hashes
        sig = self._priv.sign(msg, ec.ECDSA(hashes.SHA256()))
        r, s = butils.unmarshal_signature(sig)
        return butils.marshal_signature(r, butils.to_low_s(s))


class IdemixMSP(api.MSP):
    """Reference surface: `msp/idemix.go` idemixmsp."""

    def __init__(self, csp):
        self.csp = csp
        self._id = ""
        self._issuer_pub = None          # bccsp key
        self._issuer_pub_raw = b""
        self._lock = threading.Lock()
        self._signers: list[IdemixSigningIdentity] = []

    def identifier(self) -> str:
        return self._id

    def setup(self, config: msppb.MSPConfig) -> None:
        if config.type != 1:
            raise MSPError("not an idemix MSP config")
        idc = msppb.IdemixMSPConfig()
        idc.ParseFromString(config.config)
        self._id = idc.name
        self._issuer_pub_raw = bytes(idc.issuer_public_key)
        issuer_key = serialization.load_pem_public_key(
            self._issuer_pub_raw)
        self._issuer_pub = self.csp.key_import(
            issuer_key, bapi.ECDSAPublicKeyImportOpts())
        self._issuer_bls_pk = None
        if idc.issuer_bls_public_key:
            from fabric_tpu.ops import bn254_ref as bref
            self._issuer_bls_pk = bref.g2_from_bytes(
                bytes(idc.issuer_bls_public_key))

    # -- credential intake (member side) --

    def add_credentials(self, creds) -> None:
        """Load issued (nym_priv, credential) pairs for signing."""
        with self._lock:
            for nym_priv, cred in creds:
                nym_key = self._import_nym(bytes(cred.nym_pub))
                self._signers.append(IdemixSigningIdentity(
                    self, cred, nym_key, nym_priv))

    def get_default_signing_identity(self) -> IdemixSigningIdentity:
        """Pops a FRESH pseudonym per call — consecutive transactions
        are unlinkable (the reference re-randomizes its credential per
        signature; same observable effect)."""
        with self._lock:
            if not self._signers:
                raise MSPError(
                    f"idemix MSP {self._id}: no unused pseudonym "
                    "credentials; request a new batch from the issuer")
            return self._signers.pop()

    # -- deserialization / validation (verifier side) --

    def _import_nym(self, nym_pub_der: bytes):
        return self.csp.key_import(nym_pub_der,
                                   bapi.ECDSAPublicKeyImportOpts())

    def deserialize_identity(self, serialized: bytes) -> IdemixIdentity:
        sid = msppb.SerializedIdentity()
        sid.ParseFromString(serialized)
        if sid.mspid != self._id:
            raise MSPError(
                f"expected MSP ID {self._id!r}, got {sid.mspid!r}")
        wrapped = msppb.SerializedIdemixIdentity()
        wrapped.ParseFromString(sid.id_bytes)
        cred = wrapped.credential
        if not cred.nym_pub or not (cred.issuer_sig or cred.bls_sig):
            raise MSPError("idemix identity lacks a credential")
        nym_key = self._import_nym(bytes(cred.nym_pub))
        return IdemixIdentity(self, cred, nym_key)

    def is_well_formed(self, serialized: bytes) -> None:
        self.deserialize_identity(serialized)

    def validate(self, identity: IdemixIdentity) -> None:
        """Issuer binding: the credential must carry a valid issuer
        signature over (nym, disclosed attributes) — P-256, or BLS
        verified by PAIRING when the org configured a BLS issuer key."""
        if not self.validate_credentials_batch([identity])[0]:
            raise MSPError(
                f"idemix credential not signed by the {self._id} "
                "issuer")

    def validate_credentials_batch(self, identities) -> list[bool]:
        """Batched issuer-binding checks; BLS credentials go through
        ONE pairing-product dispatch (`csp.bls_verify_batch` — the
        device path on the TPU provider), ECDSA credentials through
        the ordinary batched verify. This is the measurable surface
        for BASELINE config 4."""
        out = [False] * len(identities)
        bls_idx, bls_digests, bls_sigs = [], [], []
        ec_idx, ec_items = [], []
        for i, ident in enumerate(identities):
            cred = ident.credential
            digest = _credential_digest(bytes(cred.nym_pub), cred.ou,
                                        cred.role)
            if cred.bls_sig:
                if self._issuer_bls_pk is None:
                    continue                      # no BLS trust anchor
                from fabric_tpu.ops import bn254_ref as bref
                try:
                    pt = bref.g1_from_bytes(bytes(cred.bls_sig))
                except ValueError:
                    pt = None
                bls_idx.append(i)
                bls_digests.append(digest)
                bls_sigs.append(pt)
            else:
                ec_idx.append(i)
                ec_items.append(bapi.VerifyItem(
                    key=self._issuer_pub,
                    signature=bytes(cred.issuer_sig), digest=digest))
        if ec_items:
            for i, ok in zip(ec_idx, self.csp.verify_batch(ec_items)):
                out[i] = ok
        if bls_idx:
            csp = self.csp
            if not hasattr(csp, "bls_verify_batch"):
                from fabric_tpu.bccsp.sw import SWProvider
                csp = SWProvider()       # exact host pairing fallback
            res = csp.bls_verify_batch(
                self._issuer_bls_pk, bls_digests, bls_sigs)
            for i, ok in zip(bls_idx, res):
                out[i] = ok
        return out

    def satisfies_principal(self, identity: IdemixIdentity,
                            principal: polpb.MSPPrincipal) -> None:
        self.validate(identity)
        cred = identity.credential
        if principal.classification == polpb.MSPPrincipal.ROLE:
            role = polpb.MSPRole()
            role.ParseFromString(principal.principal)
            if role.msp_identifier != self._id:
                raise MSPError(
                    f"role principal is for MSP "
                    f"{role.msp_identifier!r}")
            if role.role == polpb.MSPRole.MEMBER:
                return
            if role.role == polpb.MSPRole.ADMIN and \
                    cred.role == api.MSPRole.ADMIN:
                return
            if role.role == polpb.MSPRole.CLIENT and \
                    cred.role in (api.MSPRole.CLIENT,
                                  api.MSPRole.MEMBER):
                return
            raise MSPError(
                f"idemix identity does not hold role {role.role}")
        if principal.classification == \
                polpb.MSPPrincipal.ORGANIZATION_UNIT:
            ou = polpb.OrganizationUnit()
            ou.ParseFromString(principal.principal)
            if ou.msp_identifier != self._id:
                raise MSPError("OU principal is for another MSP")
            if cred.ou != ou.organizational_unit_identifier:
                raise MSPError(
                    f"disclosed OU {cred.ou!r} does not match")
            return
        raise MSPError(
            "idemix supports ROLE and ORGANIZATION_UNIT principals")


def idemix_msp_config(name: str,
                      issuer: IdemixIssuer) -> msppb.MSPConfig:
    """Channel-config material for an idemix org (reference:
    idemixgen output consumed by configtxgen)."""
    idc = msppb.IdemixMSPConfig(
        name=name, issuer_public_key=issuer.public_key_pem())
    if issuer.scheme == "bls":
        idc.issuer_bls_public_key = issuer.bls_public_key_bytes()
    return msppb.MSPConfig(type=1,
                           config=idc.SerializeToString())
