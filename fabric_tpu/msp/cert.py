"""X.509 certificate sanitization: low-S ECDSA signature normalization.

Rebuild of reference `msp/cert.go:25-88` (certToPEM / sanitizeCert /
isECDSASignedCert): ECDSA signatures are malleable — (r, s) and
(r, n-s) both verify — so two byte-level encodings of the SAME
certificate circulate unless normalized. The reference re-serializes
every certificate it ingests with the signature forced to the low-S
form, so subject key identifiers and identity-byte comparisons (the
IDENTITY principal, admin matching, consenter identity checks during
onboarding) agree regardless of which variant the issuing CA emitted.

This port does the normalization with plain DER surgery — no OpenSSL
needed, so it works on hosts running the pure-python crypto fallback.
Only ECDSA-signed certificates are touched (P-256, the curve this
stack implements); anything unparsable or non-ECDSA passes through
unchanged — sanitization is normalization, not validation.
"""

from __future__ import annotations

import base64
import re

# the (r,s) codec and low-S policy are the bccsp ones — ONE
# implementation of the signature-space boundary for the whole stack.
# An s outside [1, n) means the signature is for some other curve than
# P-256 and the certificate is left alone.
from fabric_tpu.bccsp.utils import (
    P256_N,
    SignatureFormatError,
    is_low_s,
    marshal_signature,
    to_low_s,
    unmarshal_signature,
)

# AlgorithmIdentifier OIDs (DER content bytes) for ecdsa-with-SHA{1,
# 224,256,384,512} — 1.2.840.10045.4.1 / 4.3.{1,2,3,4}
_ECDSA_OID_PREFIX = bytes((0x2A, 0x86, 0x48, 0xCE, 0x3D, 0x04))

_PEM_RE = re.compile(
    rb"-----BEGIN CERTIFICATE-----\s*(.*?)\s*-----END CERTIFICATE-----",
    re.DOTALL)


# -- minimal DER codec (TLV) --

def _read_tlv(buf: bytes, off: int) -> tuple[int, bytes, int]:
    """Returns (tag, content, end_offset). Raises on malformed input."""
    if off + 2 > len(buf):
        raise ValueError("DER: truncated TLV header")
    tag = buf[off]
    length = buf[off + 1]
    off += 2
    if length & 0x80:
        n = length & 0x7F
        if n == 0 or n > 4 or off + n > len(buf):
            raise ValueError("DER: bad long-form length")
        length = int.from_bytes(buf[off:off + n], "big")
        off += n
    if off + length > len(buf):
        raise ValueError("DER: content overruns buffer")
    return tag, buf[off:off + length], off + length


def _enc_len(n: int) -> bytes:
    if n < 0x80:
        return bytes((n,))
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes((0x80 | len(body),)) + body


def _tlv(tag: int, content: bytes) -> bytes:
    return bytes((tag,)) + _enc_len(len(content)) + content


def _is_ecdsa_alg(alg_der_content: bytes) -> bool:
    """True when the AlgorithmIdentifier SEQUENCE content starts with
    an ecdsa-with-SHA* OID."""
    try:
        tag, oid, _ = _read_tlv(alg_der_content, 0)
    except ValueError:
        return False
    return tag == 0x06 and oid.startswith(_ECDSA_OID_PREFIX)


def sanitize_der(der: bytes) -> bytes:
    """Return `der` with a high-S ECDSA certificate signature replaced
    by its low-S twin (s' = n - s); byte-identical input when the
    signature is already low-S, not ECDSA, or the DER is not a
    certificate shape we understand."""
    try:
        outer_tag, outer, end = _read_tlv(der, 0)
        if outer_tag != 0x30 or end != len(der):
            return der
        # Certificate ::= SEQUENCE { tbs, sigAlg, sigValue }
        t_tag, _t, o1 = _read_tlv(outer, 0)
        a_tag, alg, o2 = _read_tlv(outer, o1)
        b_tag, bits, o3 = _read_tlv(outer, o2)
        if (t_tag, a_tag, b_tag) != (0x30, 0x30, 0x03) or \
                o3 != len(outer):
            return der
        if not _is_ecdsa_alg(alg) or not bits or bits[0] != 0:
            return der
        # ECDSA-Sig-Value ::= SEQUENCE { r INTEGER, s INTEGER } —
        # parsed/re-encoded by the bccsp signature codec
        r, s = unmarshal_signature(bits[1:])
        if s >= P256_N or is_low_s(s):
            return der
        new_bits = _tlv(0x03, b"\x00" + marshal_signature(
            r, to_low_s(s)))
        return _tlv(0x30, outer[:o2] + new_bits)
    except (ValueError, SignatureFormatError):
        return der


def is_low_s_der(der: bytes) -> bool:
    """True when the certificate's ECDSA signature is already in
    canonical low-S form (or is not an ECDSA signature at all)."""
    return sanitize_der(der) == der


def sanitize_pem(pem: bytes) -> bytes:
    """Normalize every CERTIFICATE block in `pem` (surrounding text —
    key blocks, comments — is preserved verbatim)."""
    def _one(m: re.Match) -> bytes:
        try:
            der = base64.b64decode(m.group(1))
        except Exception:
            return m.group(0)
        fixed = sanitize_der(der)
        if fixed == der:
            return m.group(0)
        b64 = base64.b64encode(fixed)
        lines = [b64[i:i + 64] for i in range(0, len(b64), 64)]
        return (b"-----BEGIN CERTIFICATE-----\n" + b"\n".join(lines)
                + b"\n-----END CERTIFICATE-----")
    return _PEM_RE.sub(_one, pem)
