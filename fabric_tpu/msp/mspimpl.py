"""X.509 MSP implementation.

Rebuild of the reference's `bccspmsp` (`msp/mspimpl.go` Setup:250,
Validate:312, DeserializeIdentity:379, SatisfiesPrincipal:424; chain and
CRL logic from `msp/mspimplsetup.go` / `msp/mspimplvalidate.go`;
identity verify hot path `msp/identities.go:170-199`).

Differences from the reference, by design:
- signature verification produces `VerifyItem`s on demand so callers
  (the policy engine) can batch whole signature sets to the TPU
  provider; `verify()` stays for single-shot callers.
- certifiers-identifier matching for OUs compares the certifying CA
  cert directly instead of the reference's chain-hash scheme (our
  configs are built by our own cryptogen-equivalent).
"""

from __future__ import annotations

import datetime
from typing import Optional, Sequence

# gated import: hosts without the `cryptography` wheel can still
# import the node assemblies; actual x509 use raises
# MissingCryptographyError (see bccsp/_crypto_compat.py)
from fabric_tpu.bccsp._crypto_compat import (
    InvalidSignature,
    ec,
    padding,
    serialization,
    x509,
)

_DER = serialization.Encoding.DER

from fabric_tpu.bccsp import bccsp as bccsp_api
from fabric_tpu.bccsp.bccsp import VerifyItem
from fabric_tpu.msp.cert import sanitize_pem
from fabric_tpu.protos import msp as msppb, policies as polpb
from fabric_tpu.msp import msp as api


class MSPError(Exception):
    pass


class PrincipalNotSatisfied(MSPError):
    pass


def _verify_issued(cert: x509.Certificate, issuer: x509.Certificate) -> bool:
    """Check `cert` carries a valid signature by `issuer`'s key."""
    pub = issuer.public_key()
    try:
        if isinstance(pub, ec.EllipticCurvePublicKey):
            pub.verify(cert.signature, cert.tbs_certificate_bytes,
                       ec.ECDSA(cert.signature_hash_algorithm))
        else:
            pub.verify(cert.signature, cert.tbs_certificate_bytes,
                       padding.PKCS1v15(), cert.signature_hash_algorithm)
        return True
    except InvalidSignature:
        return False


def _subject_ous(cert: x509.Certificate) -> list[str]:
    return [a.value for a in cert.subject.get_attributes_for_oid(
        x509.oid.NameOID.ORGANIZATIONAL_UNIT_NAME)]


class X509Identity(api.Identity):
    """Reference: `msp/identities.go` identity."""

    def __init__(self, msp: "X509MSP", cert: x509.Certificate,
                 pem: bytes, key: bccsp_api.Key):
        self._msp = msp
        self.cert = cert
        self._pem = pem
        self.key = key

    def id_bytes(self) -> bytes:
        return self._pem

    def mspid(self) -> str:
        return self._msp.identifier()

    def serialize(self) -> bytes:
        sid = msppb.SerializedIdentity()
        sid.mspid = self.mspid()
        sid.id_bytes = self._pem
        return sid.SerializeToString(deterministic=True)

    def validate(self) -> None:
        self._msp.validate(self)

    def verify(self, msg: bytes, sig: bytes) -> bool:
        csp = self._msp.csp
        if getattr(self.key, "sign_message", False):
            # message-based schemes (Ed25519 modern-MSP identities):
            # the scheme hashes internally — pre-hashing would verify
            # the WRONG bytes (reference: FAB-18401 ed25519 bccsp
            # passes the full message through)
            return csp.verify(self.key, sig, msg)
        return csp.verify(self.key, sig, csp.hash(msg))

    def verify_item(self, msg: bytes, sig: bytes) -> VerifyItem:
        return VerifyItem(key=self.key, signature=sig, message=msg)

    def satisfies_principal(self, principal) -> None:
        self._msp.satisfies_principal(self, principal)

    def organizational_units(self) -> Sequence[str]:
        return _subject_ous(self.cert)

    def expires_at(self) -> Optional[float]:
        return self.cert.not_valid_after_utc.timestamp()


class X509SigningIdentity(X509Identity, api.SigningIdentity):
    def __init__(self, msp, cert, pem, key, private_key: bccsp_api.Key):
        super().__init__(msp, cert, pem, key)
        self._priv = private_key

    def sign(self, msg: bytes) -> bytes:
        csp = self._msp.csp
        if getattr(self.key, "sign_message", False):
            return csp.sign(self._priv, msg)
        return csp.sign(self._priv, csp.hash(msg))


class X509MSP(api.MSP):
    """One org's X.509 membership rules."""

    MAX_CHAIN = 8  # sanity bound on path length

    def __init__(self, csp: bccsp_api.BCCSP, now=None):
        self.csp = csp
        self._now = now  # injectable clock for tests; None = wall clock
        self._id = ""
        self._roots: list[x509.Certificate] = []
        self._intermediates: list[x509.Certificate] = []
        self._admins: list[bytes] = []       # DER images of admin certs
        self._revoked: set[tuple[bytes, int]] = set()  # (issuer DER, serial)
        self._node_ous: Optional[msppb.NodeOUs] = None
        self._ou_ids: list[msppb.OUIdentifier] = []
        self._signer: Optional[X509SigningIdentity] = None
        self._epoch = 0   # bumped by setup(); invalidates identity memos

    # -- setup (reference: mspimpl.go:250 Setup + mspimplsetup.go) --

    def identifier(self) -> str:
        return self._id

    def setup(self, config: msppb.MSPConfig) -> None:
        if config.type != 0:
            raise MSPError(f"X509MSP cannot setup config type {config.type}")
        conf = msppb.X509MSPConfig()
        conf.ParseFromString(config.config)
        if not conf.name:
            raise MSPError("MSP name is required")
        if not conf.root_certs:
            raise MSPError("at least one root CA is required")
        self._id = conf.name
        self._epoch += 1        # stale identity memos die here
        self._revoked = set()   # re-setup must drop stale CRLs
        # every ingested certificate is sanitized to the canonical
        # low-S signature encoding first (reference msp/cert.go:25-88)
        # so SKI and identity-byte comparisons are representation-free
        self._roots = [x509.load_pem_x509_certificate(sanitize_pem(p))
                       for p in conf.root_certs]
        self._intermediates = [
            x509.load_pem_x509_certificate(sanitize_pem(p))
            for p in conf.intermediate_certs]
        self._admins = [
            x509.load_pem_x509_certificate(
                sanitize_pem(p)).public_bytes(_DER)
            for p in conf.admins
        ]
        for crl_pem in conf.revocation_list:
            crl = x509.load_pem_x509_crl(crl_pem)
            issuer_der = crl.issuer.public_bytes()
            for rc in crl:
                self._revoked.add((issuer_der, rc.serial_number))
        self._node_ous = conf.fabric_node_ous \
            if conf.HasField("fabric_node_ous") else None
        self._ou_ids = list(conf.organizational_unit_identifiers)

        if conf.HasField("signing_identity") and \
                conf.signing_identity.public_signer:
            pem = sanitize_pem(bytes(conf.signing_identity.public_signer))
            cert = x509.load_pem_x509_certificate(pem)
            pub = self.csp.key_import(
                cert, bccsp_api.X509PublicKeyImportOpts(ephemeral=True))
            priv = self.csp.get_key(bytes.fromhex(
                conf.signing_identity.private_signer.decode()))
            self._signer = X509SigningIdentity(self, cert, pem, pub, priv)
            self._signer.validate()

    # -- deserialization (reference: mspimpl.go:379) --

    def is_well_formed(self, serialized: bytes) -> None:
        sid = msppb.SerializedIdentity()
        try:
            sid.ParseFromString(serialized)
        except Exception as e:
            raise MSPError(f"not a SerializedIdentity: {e}") from e
        if not sid.id_bytes:
            raise MSPError("empty id_bytes")
        try:
            x509.load_pem_x509_certificate(sid.id_bytes)
        except Exception as e:
            raise MSPError(f"id_bytes is not a PEM certificate: {e}") from e

    def deserialize_identity(self, serialized: bytes) -> X509Identity:
        sid = msppb.SerializedIdentity()
        sid.ParseFromString(serialized)
        if sid.mspid != self._id:
            raise MSPError(
                f"expected MSP ID {self._id!r}, got {sid.mspid!r}")
        return self._identity_from_pem(bytes(sid.id_bytes))

    def _identity_from_pem(self, pem: bytes) -> X509Identity:
        # normalize BEFORE parsing: the sanitized PEM becomes the
        # identity's id_bytes, so serialize()d identities compare
        # equal whichever (r,s)/(r,n-s) variant arrived on the wire
        pem = sanitize_pem(pem)
        cert = x509.load_pem_x509_certificate(pem)
        # ephemeral: deserialization is the per-signature hot path and
        # must never touch the keystore (reference imports identity
        # certs with Temporary: true)
        key = self.csp.key_import(
            cert, bccsp_api.X509PublicKeyImportOpts(ephemeral=True))
        return X509Identity(self, cert, pem, key)

    def get_default_signing_identity(self) -> X509SigningIdentity:
        if self._signer is None:
            raise MSPError(f"MSP {self._id} holds no signing identity")
        return self._signer

    # -- validation (reference: mspimpl.go:312 + mspimplvalidate.go) --

    def validate(self, identity: api.Identity) -> None:
        if not isinstance(identity, X509Identity):
            raise MSPError("not an X.509 identity")
        # memoized per identity object: policy evaluation calls validate
        # once per SignedBy leaf, and chain crypto is the expensive part
        # memo is epoch-stamped: setup() bumps the epoch, so identities
        # retained across a reconfig re-validate against the new config
        memo = identity.__dict__.get("_validation_result")
        if memo is not None and memo[0] == self._epoch:
            if memo[1] is True:
                return
            raise memo[1]
        try:
            chain = self._validation_chain(identity.cert)
            self._check_revocation(chain)
        except MSPError as e:
            identity.__dict__["_validation_result"] = (self._epoch, e)
            raise
        identity.__dict__["_validation_result"] = (self._epoch, True)

    def _validation_chain(self, cert: x509.Certificate
                          ) -> list[x509.Certificate]:
        """Build leaf→root path through our CA material, checking
        signatures, CA flags, and validity windows."""
        now = self._now or datetime.datetime.now(datetime.timezone.utc)
        root_ders = {c.public_bytes(_DER) for c in self._roots}

        def in_window(c):
            return c.not_valid_before_utc <= now <= c.not_valid_after_utc

        if not in_window(cert):
            raise MSPError("certificate is outside its validity period")

        chain = [cert]
        current = cert
        for _ in range(self.MAX_CHAIN):
            candidates = [c for c in self._roots + self._intermediates
                          if c.subject == current.issuer]
            issuer = next((c for c in candidates
                           if _verify_issued(current, c)), None)
            if issuer is None:
                raise MSPError(
                    f"no trusted issuer for {current.subject.rfc4514_string()}")
            if not in_window(issuer):
                raise MSPError("CA certificate is outside its validity period")
            try:
                bc = issuer.extensions.get_extension_for_class(
                    x509.BasicConstraints).value
                if not bc.ca:
                    raise MSPError("issuer is not a CA")
            except x509.ExtensionNotFound:
                raise MSPError("issuer lacks BasicConstraints") from None
            chain.append(issuer)
            if issuer.public_bytes(_DER) in root_ders:
                return chain
            current = issuer
        raise MSPError("validation chain too long")

    def _check_revocation(self, chain) -> None:
        """Every cert in the chain is checked, so a revoked intermediate
        poisons everything below it (reference:
        `msp/mspimplvalidate.go` validateCertAgainstChain per link)."""
        for cert in chain:
            issuer_der = cert.issuer.public_bytes()
            if (issuer_der, cert.serial_number) in self._revoked:
                raise MSPError("certificate is revoked")

    # -- principal matching (reference: mspimpl.go:424,606) --

    def satisfies_principal(self, identity: api.Identity,
                            principal: polpb.MSPPrincipal) -> None:
        cls = principal.classification
        if cls == polpb.MSPPrincipal.ROLE:
            role = polpb.MSPRole()
            role.ParseFromString(principal.principal)
            self._satisfies_role(identity, role)
        elif cls == polpb.MSPPrincipal.IDENTITY:
            if identity.serialize() != principal.principal:
                raise PrincipalNotSatisfied("identity bytes mismatch")
        elif cls == polpb.MSPPrincipal.ORGANIZATION_UNIT:
            ou = polpb.OrganizationUnit()
            ou.ParseFromString(principal.principal)
            if ou.msp_identifier != self._id:
                raise PrincipalNotSatisfied(
                    f"OU principal is for MSP {ou.msp_identifier!r}")
            self.validate(identity)
            if ou.organizational_unit_identifier not in \
                    identity.organizational_units():
                raise PrincipalNotSatisfied(
                    f"identity lacks OU "
                    f"{ou.organizational_unit_identifier!r}")
        elif cls == polpb.MSPPrincipal.COMBINED:
            combined = polpb.CombinedPrincipal()
            combined.ParseFromString(principal.principal)
            if not combined.principals:
                raise PrincipalNotSatisfied("empty combined principal")
            for sub in combined.principals:
                self.satisfies_principal(identity, sub)
        elif cls == polpb.MSPPrincipal.ANONYMITY:
            anon = polpb.MSPIdentityAnonymity()
            anon.ParseFromString(principal.principal)
            if anon.anonymity_type == polpb.MSPIdentityAnonymity.ANONYMOUS:
                raise PrincipalNotSatisfied(
                    "X.509 identities cannot be anonymous")
        else:
            raise PrincipalNotSatisfied(f"unknown classification {cls}")

    def _satisfies_role(self, identity: X509Identity,
                        role: polpb.MSPRole) -> None:
        if role.msp_identifier != self._id:
            raise PrincipalNotSatisfied(
                f"role principal is for MSP {role.msp_identifier!r}, "
                f"identity is {self._id!r}")
        # every role requires a valid identity first
        self.validate(identity)
        r = role.role
        if r == polpb.MSPRole.MEMBER:
            return
        if r == polpb.MSPRole.ADMIN:
            if identity.cert.public_bytes(_DER) in self._admins:
                return
            if self._node_ous and self._node_ous.enable and \
                    self._match_node_ou(identity,
                                        self._node_ous.admin_ou_identifier):
                return
            raise PrincipalNotSatisfied("identity is not an admin")
        if r in (polpb.MSPRole.CLIENT, polpb.MSPRole.PEER,
                 polpb.MSPRole.ORDERER):
            if not (self._node_ous and self._node_ous.enable):
                raise PrincipalNotSatisfied(
                    "NodeOUs disabled: cannot classify client/peer/orderer")
            ou_id = {
                polpb.MSPRole.CLIENT: self._node_ous.client_ou_identifier,
                polpb.MSPRole.PEER: self._node_ous.peer_ou_identifier,
                polpb.MSPRole.ORDERER: self._node_ous.orderer_ou_identifier,
            }[r]
            if not self._match_node_ou(identity, ou_id):
                raise PrincipalNotSatisfied(
                    f"identity lacks the {polpb.MSPRole.RoleType.Name(r)} OU")
            return
        raise PrincipalNotSatisfied(f"unknown role {r}")

    def _match_node_ou(self, identity: X509Identity,
                       ou_id: msppb.OUIdentifier) -> bool:
        if not ou_id.organizational_unit_identifier:
            return False
        return ou_id.organizational_unit_identifier in \
            identity.organizational_units()
