"""MSP manager (per-channel multiplexer) and memoizing cache.

Rebuild of `msp/mspmgrimpl.go` and `msp/cache/cache.go`: the manager
routes deserialization to the owning MSP by the embedded mspid; the
cache wraps an MSP and memoizes the three hot, pure-given-config
operations (deserialize, validate, satisfies-principal) keyed on
identity bytes — the reference sizes these LRUs at
`msp/cache/cache.go` (deserialize/validate/satisfiesPrincipal caches).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Sequence

from fabric_tpu.protos import msp as msppb
from fabric_tpu.msp import msp as api
from fabric_tpu.msp.mspimpl import MSPError


class Manager(api.MSPManager):
    def __init__(self):
        self._msps: dict[str, api.MSP] = {}

    def setup(self, msps: Sequence[api.MSP]) -> None:
        self._msps = {m.identifier(): m for m in msps}

    def get_msps(self) -> dict[str, api.MSP]:
        return dict(self._msps)

    def deserialize_identity(self, serialized: bytes) -> api.Identity:
        sid = msppb.SerializedIdentity()
        sid.ParseFromString(serialized)
        msp = self._msps.get(sid.mspid)
        if msp is None:
            raise MSPError(f"MSP {sid.mspid!r} is unknown on this channel")
        return msp.deserialize_identity(serialized)

    def is_well_formed(self, serialized: bytes) -> None:
        sid = msppb.SerializedIdentity()
        try:
            sid.ParseFromString(serialized)
        except Exception as e:
            raise MSPError(f"not a SerializedIdentity: {e}") from e
        for msp in self._msps.values():
            try:
                msp.is_well_formed(serialized)
                return
            except MSPError:
                continue
        raise MSPError("no MSP recognizes this identity")


class _LRU:
    def __init__(self, cap: int):
        self._cap = cap
        self._d: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def get(self, k):
        with self._lock:
            if k in self._d:
                self._d.move_to_end(k)
                return self._d[k]
            return None

    def put(self, k, v):
        with self._lock:
            self._d[k] = v
            self._d.move_to_end(k)
            if len(self._d) > self._cap:
                self._d.popitem(last=False)


class CachedMSP(api.MSP):
    """Decorator MSP memoizing the hot calls (reference:
    `msp/cache/cache.go`, default cache sizes 100/100/100)."""

    def __init__(self, inner: api.MSP, size: int = 100):
        self._inner = inner
        self._deser = _LRU(size)
        self._valid = _LRU(size)
        self._sat = _LRU(size)

    def identifier(self) -> str:
        return self._inner.identifier()

    def setup(self, config) -> None:
        self._inner.setup(config)
        # a reconfig changes the accept set (roots, CRLs, OUs): every
        # memoized result is stale
        size = self._deser._cap
        self._deser = _LRU(size)
        self._valid = _LRU(size)
        self._sat = _LRU(size)

    def deserialize_identity(self, serialized: bytes) -> api.Identity:
        hit = self._deser.get(serialized)
        if hit is not None:
            return hit
        ident = self._inner.deserialize_identity(serialized)
        self._deser.put(serialized, ident)
        return ident

    def is_well_formed(self, serialized: bytes) -> None:
        self._inner.is_well_formed(serialized)

    def validate(self, identity: api.Identity) -> None:
        key = identity.serialize()
        hit = self._valid.get(key)
        if hit is True:
            return
        if isinstance(hit, Exception):
            raise hit
        try:
            self._inner.validate(identity)
        except Exception as e:
            self._valid.put(key, e)
            raise
        self._valid.put(key, True)

    def satisfies_principal(self, identity: api.Identity, principal) -> None:
        key = (identity.serialize(), principal.SerializeToString())
        hit = self._sat.get(key)
        if hit is True:
            return
        if isinstance(hit, Exception):
            raise hit
        try:
            self._inner.satisfies_principal(identity, principal)
        except Exception as e:
            self._sat.put(key, e)
            raise
        self._sat.put(key, True)

    def get_default_signing_identity(self):
        return self._inner.get_default_signing_identity()
