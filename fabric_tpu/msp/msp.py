"""MSP interfaces — identity layer contracts.

Rebuild of the reference's `msp/msp.go` (Identity at :115, MSP,
MSPManager, IdentityDeserializer). Identities verify signatures through
BCCSP, so the TPU batch path serves every consumer above (policies,
gossip, block verification) without any of them knowing.
"""

from __future__ import annotations

import abc
import enum
from typing import Optional, Sequence

from fabric_tpu.bccsp.bccsp import VerifyItem


class MSPRole(enum.IntEnum):
    """Mirrors ftpu.policies.MSPRole.RoleType (and the reference's
    msp_principal.proto)."""
    MEMBER = 0
    ADMIN = 1
    CLIENT = 2
    PEER = 3
    ORDERER = 4


class Identity(abc.ABC):
    """A validated(able) member of some MSP (reference: `msp/msp.go:115`)."""

    @abc.abstractmethod
    def id_bytes(self) -> bytes:
        """The raw serialized form (PEM cert)."""

    @abc.abstractmethod
    def mspid(self) -> str: ...

    @abc.abstractmethod
    def serialize(self) -> bytes:
        """Marshaled ftpu.msp.SerializedIdentity."""

    @abc.abstractmethod
    def validate(self) -> None:
        """Raise if this identity is not (or no longer) valid under its
        MSP: untrusted chain, expired, revoked."""

    @abc.abstractmethod
    def verify(self, msg: bytes, sig: bytes) -> bool:
        """hash(msg) then BCCSP verify — reference
        `msp/identities.go:170-199`."""

    @abc.abstractmethod
    def verify_item(self, msg: bytes, sig: bytes) -> VerifyItem:
        """The batch-path equivalent of `verify`: an item to feed
        `bccsp.verify_batch`. New in this framework — lets the policy
        engine collect a whole signature set and verify it in one
        device dispatch."""

    @abc.abstractmethod
    def satisfies_principal(self, principal) -> None:
        """Raise if this identity does not match the given
        ftpu.policies.MSPPrincipal."""

    @abc.abstractmethod
    def organizational_units(self) -> Sequence[str]: ...

    def expires_at(self) -> Optional[float]:
        """Unix seconds of cert expiry, None if unknowable."""
        return None


class SigningIdentity(Identity):
    """An identity we hold the private key for (reference:
    `msp/msp.go` SigningIdentity)."""

    @abc.abstractmethod
    def sign(self, msg: bytes) -> bytes: ...


class IdentityDeserializer(abc.ABC):
    """Reference: `msp/msp.go` IdentityDeserializer — implemented by both
    MSP (one org) and MSPManager (a channel's orgs)."""

    @abc.abstractmethod
    def deserialize_identity(self, serialized: bytes) -> Identity: ...

    @abc.abstractmethod
    def is_well_formed(self, serialized: bytes) -> None:
        """Raise if the bytes cannot possibly be one of our identities
        (cheap syntactic check before any crypto)."""


class MSP(IdentityDeserializer):
    """One organization's membership rules (reference: `msp/msp.go` MSP)."""

    @abc.abstractmethod
    def identifier(self) -> str: ...

    @abc.abstractmethod
    def setup(self, config) -> None:
        """Configure from a ftpu.msp.MSPConfig."""

    @abc.abstractmethod
    def validate(self, identity: Identity) -> None: ...

    @abc.abstractmethod
    def satisfies_principal(self, identity: Identity, principal) -> None: ...

    def get_default_signing_identity(self) -> SigningIdentity:
        raise NotImplementedError("MSP holds no signing identity")


class MSPManager(IdentityDeserializer):
    """Multiplexes MSPs by identifier (reference: `msp/mspmgrimpl.go`)."""

    @abc.abstractmethod
    def setup(self, msps: Sequence[MSP]) -> None: ...

    @abc.abstractmethod
    def get_msps(self) -> dict[str, MSP]: ...
