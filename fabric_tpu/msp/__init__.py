from fabric_tpu.msp.msp import (
    Identity,
    IdentityDeserializer,
    MSP,
    MSPManager,
    MSPRole,
    SigningIdentity,
)
from fabric_tpu.msp.mspimpl import X509MSP
from fabric_tpu.msp.mgr import CachedMSP, Manager
from fabric_tpu.msp.configbuilder import (
    build_msp_config,
    msp_config_from_dir,
)

__all__ = [
    "Identity", "IdentityDeserializer", "MSP", "MSPManager", "MSPRole",
    "SigningIdentity", "X509MSP", "CachedMSP", "Manager",
    "build_msp_config", "msp_config_from_dir",
]
