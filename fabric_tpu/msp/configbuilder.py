"""Build MSPConfig protos from the on-disk MSP directory layout.

Rebuild of `msp/configbuilder.go`: the same directory convention the
reference's cryptogen emits —

    <msp-dir>/
      cacerts/*.pem            root CAs (required)
      intermediatecerts/*.pem  intermediate CAs
      admincerts/*.pem         explicit admin certs
      signcerts/*.pem          this node's certificate
      keystore/*_sk            this node's private key (PEM PKCS#8)
      crls/*.pem               revocation lists
      tlscacerts/*.pem         TLS root CAs
      config.yaml              NodeOUs declaration

`msp_config_from_dir` also imports the keystore key into the BCCSP
keystore so `signing_identity.private_signer` (an SKI) resolves.
"""

from __future__ import annotations

import os
from typing import Optional

import yaml

from fabric_tpu.protos import msp as msppb


def _read_pems(d: str) -> list[bytes]:
    if not os.path.isdir(d):
        return []
    out = []
    for name in sorted(os.listdir(d)):
        with open(os.path.join(d, name), "rb") as f:
            out.append(f.read())
    return out


def _node_ous_from_config(msp_dir: str) -> Optional[msppb.NodeOUs]:
    path = os.path.join(msp_dir, "config.yaml")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        cfg = yaml.safe_load(f)
    nodeous = (cfg or {}).get("NodeOUs")
    if not nodeous or not nodeous.get("Enable"):
        return None
    out = msppb.NodeOUs(enable=True)
    for yaml_key, field in (
        ("ClientOUIdentifier", out.client_ou_identifier),
        ("PeerOUIdentifier", out.peer_ou_identifier),
        ("AdminOUIdentifier", out.admin_ou_identifier),
        ("OrdererOUIdentifier", out.orderer_ou_identifier),
    ):
        spec = nodeous.get(yaml_key) or {}
        field.organizational_unit_identifier = \
            spec.get("OrganizationalUnitIdentifier", "")
        cert_rel = spec.get("Certificate")
        if cert_rel:
            with open(os.path.join(msp_dir, cert_rel), "rb") as f:
                field.certificate = f.read()
    return out


def build_msp_config(name: str, root_certs: list[bytes],
                     intermediate_certs: list[bytes] = (),
                     admins: list[bytes] = (),
                     revocation_list: list[bytes] = (),
                     tls_root_certs: list[bytes] = (),
                     node_ous: Optional[msppb.NodeOUs] = None,
                     signing_cert: Optional[bytes] = None,
                     signing_key_ski: Optional[bytes] = None
                     ) -> msppb.MSPConfig:
    """Assemble an X.509 MSPConfig proto from in-memory material."""
    conf = msppb.X509MSPConfig()
    conf.name = name
    conf.root_certs.extend(root_certs)
    conf.intermediate_certs.extend(intermediate_certs)
    conf.admins.extend(admins)
    conf.revocation_list.extend(revocation_list)
    conf.tls_root_certs.extend(tls_root_certs)
    if node_ous is not None:
        conf.fabric_node_ous.CopyFrom(node_ous)
    if signing_cert is not None:
        conf.signing_identity.public_signer = signing_cert
        if signing_key_ski is not None:
            conf.signing_identity.private_signer = \
                signing_key_ski.hex().encode()
    wrapper = msppb.MSPConfig(type=0)
    wrapper.config = conf.SerializeToString(deterministic=True)
    return wrapper


def msp_config_from_dir(msp_dir: str, name: str,
                        csp=None) -> msppb.MSPConfig:
    """Read the directory layout; if `csp` is given and a keystore/ key
    exists, import it so the signing identity is usable."""
    roots = _read_pems(os.path.join(msp_dir, "cacerts"))
    if not roots:
        raise ValueError(f"{msp_dir}/cacerts is empty — not an MSP dir")
    signing_cert = None
    signing_ski = None
    signcerts = _read_pems(os.path.join(msp_dir, "signcerts"))
    if signcerts and csp is not None:
        from cryptography.hazmat.primitives.serialization import (
            load_pem_private_key,
        )
        from fabric_tpu.bccsp.bccsp import ECDSAPrivateKeyImportOpts
        keys = _read_pems(os.path.join(msp_dir, "keystore"))
        if keys:
            priv = csp.key_import(load_pem_private_key(keys[0], None),
                                  ECDSAPrivateKeyImportOpts())
            signing_cert = signcerts[0]
            signing_ski = priv.ski()
    return build_msp_config(
        name=name,
        root_certs=roots,
        intermediate_certs=_read_pems(
            os.path.join(msp_dir, "intermediatecerts")),
        admins=_read_pems(os.path.join(msp_dir, "admincerts")),
        revocation_list=_read_pems(os.path.join(msp_dir, "crls")),
        tls_root_certs=_read_pems(os.path.join(msp_dir, "tlscacerts")),
        node_ous=_node_ous_from_config(msp_dir),
        signing_cert=signing_cert,
        signing_key_ski=signing_ski,
    )
