"""Proposal / transaction assembly.

Rebuild of the reference's `protoutil/{proputils,txutils}.go`: build a
SignedProposal from an invocation spec, a ProposalResponse from a
simulation result, and the final ENDORSER_TRANSACTION envelope from a
proposal + endorsements (`protoutil/txutils.go` CreateSignedTx — the
inverse of what the txvalidator unpacks, SURVEY.md §3.4)."""

from __future__ import annotations

import hashlib
import time
from typing import Sequence

from fabric_tpu.protos import common, proposal as pb, transaction as txpb
from fabric_tpu.protoutil import protoutil as pu


def create_proposal(channel_id: str, cc_name: str, args: Sequence[bytes],
                    creator: bytes, transient_map=None,
                    is_init: bool = False):
    """Build (Proposal, tx_id). Reference:
    `protoutil/proputils.go` CreateChaincodeProposal."""
    nonce = pu.random_nonce()
    tx_id = pu.compute_tx_id(nonce, creator)

    spec = pb.ChaincodeInvocationSpec()
    spec.chaincode_spec.type = pb.ChaincodeSpec.PYTHON
    spec.chaincode_spec.chaincode_id.name = cc_name
    spec.chaincode_spec.input.args.extend(args)
    spec.chaincode_spec.input.is_init = is_init

    ext = pb.ChaincodeHeaderExtension()
    ext.chaincode_id.name = cc_name

    ch = pu.make_channel_header(
        common.HeaderType.ENDORSER_TRANSACTION, channel_id, tx_id=tx_id,
        extension=pu.marshal(ext))
    sh = pu.create_signature_header(creator, nonce)

    ccpp = pb.ChaincodeProposalPayload()
    ccpp.input = pu.marshal(spec)
    if transient_map:
        for k, v in transient_map.items():
            ccpp.transient_map[k] = v

    prop = pb.Proposal()
    hdr = common.Header()
    hdr.channel_header = pu.marshal(ch)
    hdr.signature_header = pu.marshal(sh)
    prop.header = pu.marshal(hdr)
    prop.payload = pu.marshal(ccpp)
    return prop, tx_id


def sign_proposal(prop: pb.Proposal, signer) -> pb.SignedProposal:
    sp = pb.SignedProposal()
    sp.proposal_bytes = pu.marshal(prop)
    sp.signature = signer.sign(sp.proposal_bytes)
    return sp


def proposal_hash(proposal_bytes: bytes) -> bytes:
    """The image endorsements bind to (reference:
    `protoutil/proputils.go` GetProposalHash2)."""
    return hashlib.sha256(proposal_bytes).digest()


def create_proposal_response(proposal_bytes: bytes, results: bytes,
                             events: bytes, response: pb.Response,
                             chaincode_id: pb.ChaincodeID,
                             signer) -> pb.ProposalResponse:
    """Simulate→endorse: sign (payload || endorser identity). Reference:
    `protoutil/proputils.go` CreateProposalResponse +
    `core/handlers/endorsement/builtin/default_endorsement.go:35-53`."""
    action = pb.ChaincodeAction()
    action.results = results
    action.events = events
    action.response.CopyFrom(response)
    action.chaincode_id.CopyFrom(chaincode_id)

    prp = pb.ProposalResponsePayload()
    prp.proposal_hash = proposal_hash(proposal_bytes)
    prp.extension = pu.marshal(action)
    prp_bytes = pu.marshal(prp)

    resp = pb.ProposalResponse()
    resp.version = 1
    resp.timestamp = time.time_ns()
    resp.response.CopyFrom(response)
    resp.payload = prp_bytes
    resp.endorsement.endorser = signer.serialize()
    resp.endorsement.signature = signer.sign(prp_bytes +
                                             resp.endorsement.endorser)
    return resp


def create_signed_tx(prop: pb.Proposal,
                     responses: Sequence[pb.ProposalResponse],
                     signer=None) -> common.Envelope:
    """Assemble the final transaction envelope from a proposal and its
    endorsements. Reference: `protoutil/txutils.go` CreateSignedTx —
    all responses must carry identical payloads. With `signer=None` the
    envelope comes back UNSIGNED (the remote-gateway flow: the server
    prepares the transaction, the client adds its signature —
    `internal/pkg/gateway/api.go` Endorse)."""
    if not responses:
        raise ValueError("at least one proposal response is required")
    payloads = {r.payload for r in responses}
    if len(payloads) != 1:
        raise ValueError("proposal responses do not match")
    first = responses[0]
    if first.response.status < 200 or first.response.status >= 400:
        raise ValueError(f"proposal response was not successful: "
                         f"{first.response.status}")

    hdr = common.Header()
    hdr.ParseFromString(prop.header)

    # strip transient data from the committed payload (reference:
    # txutils.go — GetBytesChaincodeProposalPayload w/o transient field)
    ccpp = pb.ChaincodeProposalPayload()
    ccpp.ParseFromString(prop.payload)
    ccpp.ClearField("transient_map")

    cap = txpb.ChaincodeActionPayload()
    cap.chaincode_proposal_payload = pu.marshal(ccpp)
    cap.action.proposal_response_payload = first.payload
    for r in responses:
        cap.action.endorsements.add().CopyFrom(r.endorsement)

    ta = txpb.TransactionAction()
    ta.header = hdr.signature_header
    ta.payload = pu.marshal(cap)

    tx = txpb.Transaction()
    tx.actions.add().CopyFrom(ta)

    payload = common.Payload()
    payload.header.CopyFrom(hdr)
    payload.data = pu.marshal(tx)
    if signer is None:
        return common.Envelope(payload=pu.marshal(payload))
    return pu.sign_or_panic(signer, payload)


def get_action_from_envelope(env_bytes: bytes) -> pb.ChaincodeAction:
    """Dig the ChaincodeAction out of a tx envelope (reference:
    `protoutil/txutils.go` GetActionFromEnvelope)."""
    env = pu.unmarshal_envelope(env_bytes)
    payload = pu.get_payload(env)
    tx = txpb.Transaction()
    tx.ParseFromString(payload.data)
    if not tx.actions:
        raise ValueError("transaction has no actions")
    cap = txpb.ChaincodeActionPayload()
    cap.ParseFromString(tx.actions[0].payload)
    prp = pb.ProposalResponsePayload()
    prp.ParseFromString(cap.action.proposal_response_payload)
    action = pb.ChaincodeAction()
    action.ParseFromString(prp.extension)
    return action
