"""Block/envelope assembly and hashing helpers.

Rebuild of the reference's `protoutil/` package (`blockutils.go`,
`commonutils.go`, `signeddata.go` — SURVEY.md §2.12): the glue every
layer uses to build, hash, and pick apart wire messages. Hash
definitions are this framework's own (the wire format is new), but the
*roles* mirror the reference: `block_data_hash` chains block contents,
`block_header_hash` chains blocks, `compute_tx_id` makes tx ids unique
per (nonce, creator).
"""

from __future__ import annotations

import hashlib
import os
import time
from dataclasses import dataclass
from typing import Optional, Sequence

from fabric_tpu.protos import common


def marshal(msg) -> bytes:
    """Deterministic protobuf serialization — anything that gets hashed
    or signed goes through here so byte images are reproducible."""
    return msg.SerializeToString(deterministic=True)


def random_nonce(n: int = 24) -> bytes:
    """Reference: `protoutil/commonutils.go` CreateNonce (24 bytes)."""
    return os.urandom(n)


def compute_tx_id(nonce: bytes, creator: bytes) -> str:
    """TxID = hex(sha256(nonce || creator)) — reference:
    `protoutil/txutils.go` ComputeTxID."""
    return hashlib.sha256(nonce + creator).hexdigest()


def block_data_hash(data: common.BlockData) -> bytes:
    """SHA-256 over the concatenated envelope bytes — reference:
    `protoutil/blockutils.go` ComputeBlockDataHash. Verified on every
    received block (`internal/peer/gossip/mcs.go:155`)."""
    h = hashlib.sha256()
    for d in data.data:
        h.update(d)
    return h.digest()


def block_header_bytes(header: common.BlockHeader) -> bytes:
    """Deterministic image of a header for hashing/signing. The
    reference uses ASN.1 DER (`protoutil/blockutils.go
    BlockHeaderBytes`); we use a fixed-width encoding with the same
    injectivity property."""
    return (
        header.number.to_bytes(8, "big")
        + len(header.previous_hash).to_bytes(4, "big")
        + header.previous_hash
        + len(header.data_hash).to_bytes(4, "big")
        + header.data_hash
    )


def block_header_hash(header: common.BlockHeader) -> bytes:
    return hashlib.sha256(block_header_bytes(header)).digest()


def new_block(seq: int, previous_hash: bytes) -> common.Block:
    block = common.Block()
    block.header.number = seq
    block.header.previous_hash = previous_hash
    # one metadata slot per BlockMetadataIndex value
    for _ in range(5):
        block.metadata.metadata.append(b"")
    return block


def create_signature_header(creator: bytes,
                            nonce: Optional[bytes] = None
                            ) -> common.SignatureHeader:
    sh = common.SignatureHeader()
    sh.creator = creator
    sh.nonce = nonce if nonce is not None else random_nonce()
    return sh


def make_channel_header(header_type: int, channel_id: str, tx_id: str = "",
                        epoch: int = 0, extension: bytes = b"",
                        version: int = 0) -> common.ChannelHeader:
    ch = common.ChannelHeader()
    ch.type = header_type
    ch.version = version
    ch.timestamp = time.time_ns()
    ch.channel_id = channel_id
    ch.tx_id = tx_id
    ch.epoch = epoch
    ch.extension = extension
    return ch


def make_payload(channel_header: common.ChannelHeader,
                 signature_header: common.SignatureHeader,
                 data: bytes) -> common.Payload:
    payload = common.Payload()
    payload.header.channel_header = marshal(channel_header)
    payload.header.signature_header = marshal(signature_header)
    payload.data = data
    return payload


def sign_or_panic(signer, payload: common.Payload) -> common.Envelope:
    """Wrap a payload in a signed envelope. `signer` is anything with
    `sign(bytes) -> bytes` and `serialize() -> bytes` (msp
    SigningIdentity or a test signer)."""
    env = common.Envelope()
    env.payload = marshal(payload)
    env.signature = signer.sign(env.payload)
    return env


# ---- unpacking ----

def unmarshal_envelope(raw: bytes) -> common.Envelope:
    env = common.Envelope()
    env.ParseFromString(raw)
    return env


def unmarshal_block(raw: bytes) -> common.Block:
    block = common.Block()
    block.ParseFromString(raw)
    return block


def extract_envelope(block: common.Block, index: int) -> common.Envelope:
    """Reference: `protoutil/blockutils.go` ExtractEnvelope."""
    if index < 0 or index >= len(block.data.data):
        raise IndexError(f"envelope index {index} out of bounds "
                         f"({len(block.data.data)} entries)")
    return unmarshal_envelope(block.data.data[index])


def get_payload(env: common.Envelope) -> common.Payload:
    payload = common.Payload()
    payload.ParseFromString(env.payload)
    return payload


def get_channel_header(payload: common.Payload) -> common.ChannelHeader:
    ch = common.ChannelHeader()
    ch.ParseFromString(payload.header.channel_header)
    return ch


def get_signature_header(raw: bytes) -> common.SignatureHeader:
    sh = common.SignatureHeader()
    sh.ParseFromString(raw)
    return sh


def is_config_block(block: common.Block) -> bool:
    """True iff the block's first envelope is a CONFIG transaction
    (reference: `protoutil/blockutils.go` IsConfigBlock). The single
    shared predicate — committer, ledger, peer and orderer all route
    here."""
    if not block.data.data:
        return False
    try:
        env = extract_envelope(block, 0)
        ch = get_channel_header(get_payload(env))
        return ch.type == common.HeaderType.CONFIG
    except Exception:
        return False


def encode_last_config(last_config_index: int) -> bytes:
    """Metadata.value payload of the SIGNATURES slot: a serialized
    OrdererBlockMetadata pointing at the governing config block
    (reference: `protoutil/blockutils.go` — LastConfig folded into the
    SIGNATURES metadata in Fabric 2.x)."""
    return common.OrdererBlockMetadata(
        last_config_index=last_config_index
    ).SerializeToString(deterministic=True)


def get_last_config_index(block: common.Block) -> int:
    """Read the last-config pointer back out of a committed block.
    Raises on blocks without the pointer (pre-genesis artifacts)."""
    md = common.Metadata()
    md.ParseFromString(
        block.metadata.metadata[common.BlockMetadataIndex.SIGNATURES])
    obm = common.OrdererBlockMetadata()
    obm.ParseFromString(md.value)
    return obm.last_config_index


# ---- signed-data extraction (reference: protoutil/signeddata.go) ----

@dataclass(frozen=True)
class SignedData:
    """One (message, identity, signature) triple for policy evaluation.
    Reference: `protoutil/signeddata.go` SignedData — the unit the
    policy engine (and the batched TPU verify) consumes."""

    data: bytes       # what was signed
    identity: bytes   # serialized identity of the signer
    signature: bytes


def envelope_as_signed_data(env: common.Envelope) -> list[SignedData]:
    """Reference: `protoutil/signeddata.go` EnvelopeAsSignedData —
    the envelope signature covers the raw payload bytes."""
    payload = get_payload(env)
    sh = get_signature_header(payload.header.signature_header)
    return [SignedData(data=env.payload, identity=sh.creator,
                       signature=env.signature)]


def block_signature_set(block: common.Block) -> list[SignedData]:
    """SignedData for each block-metadata signature — what block
    verification feeds the BlockValidation policy (reference:
    `protoutil/signeddata.go` BlockSignatureVerifier /
    `internal/peer/gossip/mcs.go:174-191`). Each signature covers
    (metadata.value || signature_header || header bytes)."""
    md = common.Metadata()
    md.ParseFromString(
        block.metadata.metadata[common.BlockMetadataIndex.SIGNATURES])
    out = []
    hdr = block_header_bytes(block.header)
    for sig in md.signatures:
        sh = get_signature_header(sig.signature_header)
        out.append(SignedData(
            data=md.value + sig.signature_header + hdr,
            identity=sh.creator,
            signature=sig.signature,
        ))
    return out
