"""Gateway: the server-side client SDK.

Rebuild of `internal/pkg/gateway/api.go`: `Evaluate:38` (one peer,
no ordering), `Endorse:127` (collect endorsements satisfying the
policy), `Submit:402` (broadcast to an orderer), `CommitStatus:472`
(wait for finality). In-process peers/orderers plug in directly; gRPC
remotes adapt to the same duck-types.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from fabric_tpu.protos import common, proposal as pb
from fabric_tpu.protoutil import protoutil as pu, txutils

logger = logging.getLogger("gateway")


class GatewayError(Exception):
    pass


def _block_events(block, cc_name: str):
    """Extract VALID txs' chaincode events for one chaincode
    (reference: gateway/commit event extraction)."""
    from fabric_tpu.protos import gateway as gwpb
    from fabric_tpu.protos import transaction as txpb
    filt = b""
    if len(block.metadata.metadata) > \
            common.BlockMetadataIndex.TRANSACTIONS_FILTER:
        filt = bytes(block.metadata.metadata[
            common.BlockMetadataIndex.TRANSACTIONS_FILTER])
    out = []
    for i, env_bytes in enumerate(block.data.data):
        if i < len(filt) and filt[i] != txpb.TxValidationCode.VALID:
            continue
        try:
            action = txutils.get_action_from_envelope(env_bytes)
            if not action.events:
                continue
            event = pb.ChaincodeEvent()
            event.ParseFromString(action.events)
        except Exception:
            continue
        if cc_name and event.chaincode_id != cc_name:
            continue
        env = pu.unmarshal_envelope(env_bytes)
        ch = pu.get_channel_header(pu.get_payload(env))
        out.append(gwpb.ChaincodeEventRecord(
            chaincode_id=event.chaincode_id, tx_id=ch.tx_id,
            event_name=event.event_name, payload=event.payload))
    return out


def _chaincode_of(sp) -> str:
    """Chaincode name targeted by a signed proposal."""
    prop = pb.Proposal()
    prop.ParseFromString(sp.proposal_bytes)
    cpp = pb.ChaincodeProposalPayload()
    cpp.ParseFromString(prop.payload)
    spec = pb.ChaincodeInvocationSpec()
    spec.ParseFromString(cpp.input)
    return spec.chaincode_spec.chaincode_id.name


@dataclass
class SubmitResult:
    tx_id: str
    status: int


class Gateway:
    def __init__(self, peer, broadcast, signer=None):
        """`peer`: the local Peer (endorser + channels); `broadcast`:
        BroadcastHandler (or gRPC adapter) to the ordering service;
        `signer`: a client signing identity for the in-process
        convenience API (the gRPC surface has no server-side signer —
        clients sign their own proposals/envelopes)."""
        self._peer = peer
        self._broadcast = broadcast
        self._signer = signer
        # org MSP id -> endorser-like (process_proposal); discovery
        # populates this with remote peers, the local peer always works
        self.endorsers: dict[str, object] = {}
        # optional dynamic source: fn(channel_id) -> {org: endorser};
        # the node assembly wires this to gossip-membership discovery
        # (reference: gateway registry fed by the discovery service)
        self.endorser_source = None
        # optional layout planner: fn(channel_id, cc_name) ->
        # list[{org: qty}] from endorsement-policy analysis (discovery
        # service); used to endorse with the MINIMAL satisfying org set
        self.layout_source = None

    # -- Evaluate (api.go:38): simulate on one peer, return result --

    def evaluate(self, channel_id: str, cc_name: str,
                 args: Sequence[bytes],
                 transient: Optional[dict] = None) -> pb.Response:
        prop, _tx_id = txutils.create_proposal(
            channel_id, cc_name, list(args),
            self._signer.serialize(), transient_map=transient)
        sp = txutils.sign_proposal(prop, self._signer)
        resp = self._peer.endorser.process_proposal(sp)
        return resp.response

    # -- Endorse (api.go:127): collect endorsements --

    def endorse(self, channel_id: str, cc_name: str,
                args: Sequence[bytes],
                endorsing_peers: Optional[Sequence] = None,
                transient: Optional[dict] = None,
                is_init: bool = False
                ) -> tuple[common.Envelope, str]:
        """Returns (signed tx envelope, tx_id). `endorsing_peers`
        defaults to just the local peer; the discovery-driven layout
        planner replaces this as discovery lands."""
        peers = list(endorsing_peers or [self._peer])
        prop, tx_id = txutils.create_proposal(
            channel_id, cc_name, list(args),
            self._signer.serialize(), transient_map=transient,
            is_init=is_init)
        sp = txutils.sign_proposal(prop, self._signer)
        responses = []
        for peer in peers:
            resp = peer.endorser.process_proposal(sp)
            if resp.response.status >= 400:
                raise GatewayError(
                    f"endorsement refused by peer: "
                    f"{resp.response.status} {resp.response.message}")
            responses.append(resp)
        env = txutils.create_signed_tx(prop, responses, self._signer)
        return env, tx_id

    # -- signed-proposal surface (what the gRPC service exposes; the
    #    client built + signed the proposal itself) --

    def evaluate_signed(self, channel_id: str, sp) -> pb.Response:
        resp = self._peer.endorser.process_proposal(sp)
        return resp.response

    def endorse_signed(self, channel_id: str, sp,
                       endorsing_organizations: Sequence[str] = (),
                       ) -> common.Envelope:
        """Collect endorsements for a client-signed proposal; returns
        the UNSIGNED prepared transaction (the client signs it before
        Submit — reference api.go:127 Endorse)."""
        pool = dict(self.endorsers)
        if self.endorser_source is not None:
            try:
                for org, target in (self.endorser_source(channel_id)
                                    or {}).items():
                    pool.setdefault(org, target)
            except Exception:
                logger.exception("endorser discovery failed")
        targets = []
        if endorsing_organizations:
            for org in endorsing_organizations:
                target = pool.get(org)
                if target is None:
                    raise GatewayError(
                        f"no endorsing peer known for org {org}")
                targets.append(target)
        else:
            targets = self._plan_targets(channel_id, sp, pool)
        responses = []
        for target in targets:
            resp = target.process_proposal(sp)
            if resp.response.status >= 400:
                raise GatewayError(
                    f"endorsement refused: {resp.response.status} "
                    f"{resp.response.message}")
            responses.append(resp)
        prop = pb.Proposal()
        prop.ParseFromString(sp.proposal_bytes)
        return txutils.create_signed_tx(prop, responses, signer=None)

    def _plan_targets(self, channel_id: str, sp, pool: dict) -> list:
        """Pick endorsers: the smallest discovery layout whose orgs are
        all reachable (reference api.go:127 planFromLayouts); fall back
        to one endorser per known org."""
        if self.layout_source is not None:
            try:
                cc_name = _chaincode_of(sp)
                for layout in self.layout_source(channel_id, cc_name):
                    if all(org in pool for org in layout):
                        return [pool[org] for org in sorted(layout)]
            except Exception:
                logger.exception("endorsement planning failed; "
                                 "falling back to all-orgs")
        return list(pool.values()) or [self._peer.endorser]

    # -- Submit (api.go:402) --

    def submit(self, env: common.Envelope) -> None:
        resp = self._broadcast.process_message(env)
        if resp.status != common.Status.SUCCESS:
            raise GatewayError(
                f"broadcast failed: {resp.status} {resp.info}")

    # -- CommitStatus (api.go:472) --

    def commit_status(self, channel_id: str, tx_id: str,
                      timeout_s: float = 10.0) -> int:
        """Wait until the tx lands in a committed block on the local
        peer; returns its TxValidationCode."""
        channel = self._peer.channel(channel_id)
        if channel is None:
            raise GatewayError(f"unknown channel {channel_id}")
        import time
        deadline = time.monotonic() + timeout_s
        while True:
            code = channel.tx_validation_code(tx_id)
            if code is not None:
                return code
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise GatewayError(
                    f"timed out waiting for commit of {tx_id}")
            channel.wait_for_height(channel.ledger.height + 1,
                                    min(remaining, 0.5))

    # -- ChaincodeEvents (api.go:508): stream committed events --

    def chaincode_events(self, channel_id: str, cc_name: str,
                         start_block: Optional[int] = None,
                         stop=None):
        """Yield (block_number, [ChaincodeEventRecord]) per committed
        block from `start_block` (None = next block), following the
        chain live. Only VALID txs' events are delivered (reference
        behavior). `stop`: optional threading.Event ending the stream."""
        from fabric_tpu.protos import gateway as gwpb
        channel = self._peer.channel(channel_id)
        if channel is None:
            raise GatewayError(f"unknown channel {channel_id}")
        num = channel.ledger.height if start_block is None \
            else start_block
        while stop is None or not stop.is_set():
            if not channel.wait_for_height(num + 1, timeout=0.5):
                if stop is not None:
                    continue
                if channel.ledger.height <= num:
                    continue
            block = channel.get_block(num)
            if block is None:
                num += 1
                continue
            events = _block_events(block, cc_name)
            yield num, events
            num += 1

    # -- convenience: the full endorse→submit→wait round trip --

    def submit_transaction(self, channel_id: str, cc_name: str,
                           args: Sequence[bytes],
                           endorsing_peers: Optional[Sequence] = None,
                           transient: Optional[dict] = None,
                           timeout_s: float = 10.0) -> SubmitResult:
        env, tx_id = self.endorse(channel_id, cc_name, args,
                                  endorsing_peers, transient)
        self.submit(env)
        code = self.commit_status(channel_id, tx_id, timeout_s)
        return SubmitResult(tx_id=tx_id, status=code)
