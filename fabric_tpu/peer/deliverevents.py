"""Peer deliver event streams: filtered blocks + blocks with private data.

Rebuild of `core/peer/deliverevents.go:1` (DeliverFiltered,
DeliverWithPrivateData) over the shared deliver engine
(`common/deliver/deliver.go:173` — here fabric_tpu/common/deliver.py):
the engine handles SeekInfo parsing, Readers-policy session AC and
block streaming; this module transforms each block into the stream's
payload shape:

  * Filtered: per-tx verdicts + chaincode events with the PAYLOAD
    STRIPPED — what event-consumer SDKs subscribe to.
  * BlockAndPrivateData: the raw block plus every cleartext private
    rwset this peer holds for it, with collections the REQUESTER is not
    a member of removed (the reference's CollectionPolicyChecker; here
    membership = the requester MSP appearing in the collection's
    member_orgs, fail-closed when the config is unresolvable).
"""

from __future__ import annotations

import logging
from typing import Iterator

from fabric_tpu.common.deliver import DeliverHandler
from fabric_tpu.protos import common, events as evpb, orderer as ordpb
from fabric_tpu.protos import proposal as ppb, transaction as txpb
from fabric_tpu.protoutil import protoutil as pu

logger = logging.getLogger("deliverevents")


def filter_block(channel_id: str, block: common.Block
                 ) -> evpb.FilteredBlock:
    """Reference: blockEvent.toFilteredBlock (deliverevents.go)."""
    fb = evpb.FilteredBlock(channel_id=channel_id,
                            number=block.header.number)
    flags = b""
    meta = block.metadata.metadata
    if len(meta) > common.BlockMetadataIndex.TRANSACTIONS_FILTER:
        flags = meta[common.BlockMetadataIndex.TRANSACTIONS_FILTER]
    for i, env_bytes in enumerate(block.data.data):
        ft = fb.filtered_transactions.add()
        if i < len(flags):
            ft.tx_validation_code = flags[i]
        else:
            ft.tx_validation_code = txpb.TxValidationCode.NOT_VALIDATED
        try:
            env = pu.unmarshal_envelope(env_bytes)
            payload = pu.get_payload(env)
            ch = pu.get_channel_header(payload)
        except Exception:
            continue
        ft.txid = ch.tx_id
        ft.type = ch.type
        if ch.type != common.HeaderType.ENDORSER_TRANSACTION:
            continue
        try:
            tx = txpb.Transaction()
            tx.ParseFromString(payload.data)
            actions = ft.transaction_actions
            for action in tx.actions:
                cap = txpb.ChaincodeActionPayload()
                cap.ParseFromString(action.payload)
                prp = ppb.ProposalResponsePayload()
                prp.ParseFromString(cap.action.proposal_response_payload)
                cc_action = ppb.ChaincodeAction()
                cc_action.ParseFromString(prp.extension)
                fca = actions.chaincode_actions.add()
                if cc_action.events:
                    ev = ppb.ChaincodeEvent()
                    ev.ParseFromString(cc_action.events)
                    ev.payload = b""        # stripped by contract
                    fca.chaincode_event.CopyFrom(ev)
        except Exception:
            logger.debug("block %d tx %d: unparsable endorser tx in "
                         "filtered stream", block.header.number, i)
    return fb


class EventsDeliverHandler:
    """The peer's three deliver stream variants over one engine.

    `channel_getter(channel_id)` returns the peer Channel (exposing
    `.ledger` with `get_pvt_data_by_num`, `.bundle()` and
    `.chaincode_definition(name)`); the base engine resolves chains
    through the same getter.
    """

    def __init__(self, channel_getter,
                 timeout_s=None, metrics_provider=None):
        from fabric_tpu.common.deliver import DeliverMetrics
        self._channels = channel_getter
        self._base = DeliverHandler(
            channel_getter, timeout_s=timeout_s,
            metrics=DeliverMetrics(metrics_provider))

    # -- plain blocks (parity with the orderer-style stream) --

    def handle(self, env) -> Iterator[ordpb.DeliverResponse]:
        yield from self._base.handle(env)

    # -- filtered blocks --

    def handle_filtered(self, env) -> Iterator[evpb.DeliverResponse]:
        channel_id = _channel_of(env)
        for resp in self._base.handle(env):
            if resp.WhichOneof("type") == "block":
                yield evpb.DeliverResponse(
                    filtered_block=filter_block(channel_id, resp.block))
            else:
                yield evpb.DeliverResponse(status=resp.status)

    # -- blocks + private data --

    def handle_with_pvtdata(self, env) -> Iterator[evpb.DeliverResponse]:
        channel_id = _channel_of(env)
        requester_msp = self._requester_msp(channel_id, env)
        chan = self._channels(channel_id)
        for resp in self._base.handle(env):
            if resp.WhichOneof("type") != "block":
                yield evpb.DeliverResponse(status=resp.status)
                continue
            bpd = evpb.BlockAndPrivateData()
            bpd.block.CopyFrom(resp.block)
            ledger = getattr(chan, "ledger", None)
            if ledger is not None:
                num = resp.block.header.number
                for i in range(len(resp.block.data.data)):
                    txpvt = ledger.get_pvt_data_by_num(num, i)
                    if txpvt is None:
                        continue
                    visible = self._filter_collections(
                        chan, txpvt, requester_msp)
                    if visible is not None:
                        bpd.private_data_map[i].CopyFrom(visible)
            yield evpb.DeliverResponse(block_and_private_data=bpd)

    def _requester_msp(self, channel_id: str, env) -> str:
        """MSP ID of the stream's signer — collection visibility pivot."""
        try:
            chan = self._channels(channel_id)
            sd = pu.envelope_as_signed_data(env)[0]
            ident = chan.bundle().msp_manager.deserialize_identity(
                sd.identity)
            return ident.mspid()
        except Exception:
            return ""

    def _filter_collections(self, chan, txpvt, requester_msp: str):
        """Drop collections the requester is not a member of
        (reference: CollectionPolicyChecker in deliverevents.go);
        unresolvable configs fail closed."""
        out = type(txpvt)()
        out.data_model = txpvt.data_model
        kept = False
        for nspvt in txpvt.ns_pvt_rwset:
            try:
                definition = chan.chaincode_definition(nspvt.namespace)
            except Exception:
                definition = None
            ns_out = None
            for coll in nspvt.collection_pvt_rwset:
                cfg = definition.collection(coll.collection_name) \
                    if definition is not None else None
                if cfg is None or requester_msp not in cfg.member_orgs:
                    continue
                if ns_out is None:
                    ns_out = out.ns_pvt_rwset.add(
                        namespace=nspvt.namespace)
                ns_out.collection_pvt_rwset.add().CopyFrom(coll)
                kept = True
        return out if kept else None


def _channel_of(env) -> str:
    try:
        return pu.get_channel_header(pu.get_payload(env)).channel_id
    except Exception:
        return ""
