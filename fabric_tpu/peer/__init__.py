"""Peer node assembly (reference: `core/peer` + `internal/peer/node`)."""

from fabric_tpu.peer.peer import Peer, Channel  # noqa: F401
