"""The peer singleton and its per-channel resources.

Rebuild of `core/peer/peer.go` (per-channel bundle of ledger, policy
manager, MSP manager, tx validator — :335-344) and the channel wiring
part of `internal/peer/node/start.go:189-911`. A `Peer` owns the
ledger manager, the chaincode runtime, the endorser, and N `Channel`s;
each `Channel` owns the batched TxValidator + committer and updates its
config bundle when config blocks commit.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Optional

from fabric_tpu.protos import common, configtx as ctxpb, transaction as txpb
from fabric_tpu.protoutil import protoutil as pu
from fabric_tpu.common.channelconfig import Bundle
from fabric_tpu.common.configtx import Validator as ConfigTxValidator
from fabric_tpu.internal.configtxgen import genesis as genesis_mod
from fabric_tpu.core import endorser as endorser_mod
from fabric_tpu.core.chaincode import ChaincodeDefinition, ChaincodeSupport
from fabric_tpu.core.committer import LedgerCommitter
from fabric_tpu.core.transientstore import TransientStore
from fabric_tpu.core.txvalidator import TxValidator
from fabric_tpu.ledger.ledgermgmt import LedgerManager
from fabric_tpu.peer.mcs import MSPMessageCryptoService

logger = logging.getLogger("peer")


from fabric_tpu.common import metrics as _pm  # noqa: E402

PVT_COMMIT_BLOCK_DURATION = _pm.HistogramOpts(
    namespace="gossip", subsystem="privdata",
    name="commit_block_duration",
    help="The time the coordinator took to store a block together "
         "with its private data in seconds.", label_names=("channel",))
PVT_PULL_DURATION = _pm.HistogramOpts(
    namespace="gossip", subsystem="privdata", name="pull_duration",
    help="The time to gather a block's private data from the "
         "transient store at commit in seconds.",
    label_names=("channel",))
PVT_PURGE_DURATION = _pm.HistogramOpts(
    namespace="gossip", subsystem="privdata", name="purge_duration",
    help="The time to purge committed transactions' private data "
         "from the transient store in seconds.",
    label_names=("channel",))


class Channel:
    """Per-channel resources (reference: `core/peer/peer.go` Channel)."""

    def __init__(self, peer: "Peer", channel_id: str, ledger):
        self.channel_id = channel_id
        self.ledger = ledger
        self._peer = peer
        self._lock = threading.Lock()
        self._bundle: Optional[Bundle] = None
        self._definitions: dict[str, ChaincodeDefinition] = {}
        self._commit_listeners: list[Callable] = []
        self._commit_cond = threading.Condition()

        cfg_block = self._find_last_config_block()
        self._apply_config(cfg_block)
        # the ledger resolves collection configs (BTL etc.) through the
        # channel's chaincode definitions
        ledger.set_collection_info_source(self._collection_info)

        from fabric_tpu.core.txvalidator import TxValidatorMetrics
        self.validator = TxValidator(
            channel_id, ledger, self.bundle, peer.csp,
            self.chaincode_definition,
            configtx_validator_source=self.configtx_validator,
            metrics=TxValidatorMetrics(peer.metrics_provider,
                                       channel=channel_id))
        self.committer = LedgerCommitter(
            ledger, on_config_block=self._on_config_block)
        # overlapped intake (Peer.CommitPipeline.Depth > 0): validate
        # block N+1 on the device while block N's host commit runs
        self.commit_pipeline = None
        depth = getattr(peer, "commit_pipeline_depth", 0) or 0
        if depth > 0:
            from fabric_tpu.core.commitpipeline import CommitPipeline
            self.commit_pipeline = CommitPipeline(
                self, mcs=peer.mcs, depth=depth,
                metrics_provider=peer.metrics_provider,
                # e2e_commit_seconds/trace-track attribution: the
                # peer's gossip endpoint names the committing node
                node_id=getattr(peer, "endpoint", None))
        _prov = peer.metrics_provider or _pm.DisabledProvider()
        self._m_pvt_commit = _prov.new_histogram(
            PVT_COMMIT_BLOCK_DURATION).with_labels(
            "channel", channel_id)
        self._m_pvt_pull = _prov.new_histogram(
            PVT_PULL_DURATION).with_labels("channel", channel_id)
        self._m_pvt_purge = _prov.new_histogram(
            PVT_PURGE_DURATION).with_labels("channel", channel_id)

    # -- config --

    def _find_last_config_block(self) -> common.Block:
        """O(1) via the LAST_CONFIG pointer the orderer stamps into
        every block's SIGNATURES metadata (protoutil
        get_last_config_index); linear scan only as a salvage path for
        chains written before the pointer existed."""
        height = self.ledger.height
        tip = self.ledger.block_store.get_block_by_number(height - 1)
        if tip is not None:
            if pu.is_config_block(tip):
                return tip
            try:
                cfg = self.ledger.block_store.get_block_by_number(
                    pu.get_last_config_index(tip))
                if cfg is not None and pu.is_config_block(cfg):
                    return cfg
            except Exception:
                logger.warning("[%s] last-config pointer unreadable; "
                               "falling back to scan", self.channel_id)
        for num in range(height - 1, -1, -1):
            block = self.ledger.block_store.get_block_by_number(num)
            if block is not None and pu.is_config_block(block):
                return block
        # join-by-snapshot: no blocks on disk, the snapshot carried the
        # governing config block
        if hasattr(self.ledger, "bootstrap_config_block"):
            block = self.ledger.bootstrap_config_block()
            if block is not None:
                return block
        raise ValueError(f"no config block found on {self.channel_id}")

    def _apply_config(self, block: common.Block) -> None:
        env = pu.extract_envelope(block, 0)
        payload = pu.get_payload(env)
        cfg_env = ctxpb.ConfigEnvelope()
        cfg_env.ParseFromString(payload.data)
        bundle = Bundle(self.channel_id, cfg_env.config, self._peer.csp)
        with self._lock:
            self._bundle = bundle
            self._configtx_validator = ConfigTxValidator(
                self.channel_id, cfg_env.config, bundle.policy_manager)
        logger.info("[%s] channel config applied from block %d",
                    self.channel_id, block.header.number)

    def _on_config_block(self, block: common.Block) -> None:
        try:
            self._apply_config(block)
        except Exception:
            logger.exception("[%s] failed to apply config block %d",
                             self.channel_id, block.header.number)
            raise

    def bundle(self) -> Bundle:
        with self._lock:
            return self._bundle

    def configtx_validator(self) -> ConfigTxValidator:
        with self._lock:
            return self._configtx_validator

    # -- chaincode definitions (lifecycle-lite; the state-backed
    #    _lifecycle SCC replaces this as the source of truth later) --

    def define_chaincode(self, definition: ChaincodeDefinition) -> None:
        with self._lock:
            self._definitions[definition.name] = definition
        # install the chaincode's rich-query indexes (reference:
        # CouchDB index build on chaincode installation)
        for name, index_json in getattr(definition, "indexes", ()):
            try:
                self.ledger.define_index(definition.name, name,
                                         index_json)
            except Exception:
                logger.exception("[%s] index %s for chaincode %s "
                                 "failed to build", self.channel_id,
                                 name, definition.name)

    def chaincode_definition(self, name: str
                             ) -> Optional[ChaincodeDefinition]:
        """Committed `_lifecycle` state is the source of truth
        (reference: the lifecycle cache over the state DB); the
        in-memory table is the dev-mode / pre-lifecycle fallback."""
        from fabric_tpu.core.scc import lifecycle as lc
        raw = self.ledger.get_state(lc.NAMESPACE,
                                    lc._DEF_PREFIX + name)
        if raw is not None:
            try:
                return lc.definition_from_state(raw)
            except Exception:
                logger.exception("[%s] undecodable committed "
                                 "definition for %s", self.channel_id,
                                 name)
        with self._lock:
            return self._definitions.get(name)

    def _collection_info(self, ns: str, coll: str):
        from fabric_tpu.core.scc import lifecycle as lc
        if coll.startswith("_implicit_org_"):
            # org-scoped implicit collections exist on EVERY namespace
            # (reference: implicit collections of _lifecycle + per-cc)
            return lc.implicit_collection_config(
                coll[len("_implicit_org_"):])
        definition = self.chaincode_definition(ns)
        return definition.collection(coll) if definition else None

    # -- block intake (what the deliver client calls) --

    def process_block(self, block: common.Block) -> list[int]:
        """validate (batched) → gather private data → commit; returns
        final tx codes. Reference: gossip/state deliverPayloads →
        coordinator.StoreBlock (`gossip/privdata/coordinator.go:152`,
        SURVEY §3.4)."""
        flags = self.validator.validate(block)
        rwsets = None
        if not pu.is_config_block(block) and block.header.number != 0:
            from fabric_tpu.ledger.kvledger import extract_tx_rwset
            rwsets = [extract_tx_rwset(e) for e in block.data.data]
        tx_ids = self.ledger.block_store.block_tx_ids(block)
        return self.commit_validated(block, flags, rwsets=rwsets,
                                     tx_ids=tx_ids)

    def commit_validated(self, block: common.Block, flags: list[int],
                         rwsets=None, tx_ids=None) -> list[int]:
        """The host half of block intake: gather private data →
        commit → purge → notify, with the validation verdicts (and
        optionally the parsed rwsets + scanned tx-ids — each envelope
        decoded exactly once per block) already in hand. The commit
        pipeline calls this for block N while block N+1 validates."""
        import time as _t
        t0 = _t.perf_counter()
        pvt_data, committed_txids = self._gather_pvt_data(
            block, flags, rwsets=rwsets, tx_ids=tx_ids)
        t1 = _t.perf_counter()
        codes = self.committer.commit(block, flags, pvt_data=pvt_data,
                                      rwsets=rwsets, tx_ids=tx_ids)
        t2 = _t.perf_counter()
        if committed_txids:
            self._peer.transient_store.purge_by_txids(committed_txids)
            self._m_pvt_purge.observe(_t.perf_counter() - t2)
        self._m_pvt_pull.observe(t1 - t0)
        self._m_pvt_commit.observe(t2 - t1)
        self._notify_commit(block, codes, tx_ids=tx_ids)
        return codes

    def _gather_pvt_data(self, block: common.Block, flags: list[int],
                         rwsets=None, tx_ids=None
                         ) -> tuple[dict, list[str]]:
        """Transient-store lookup per valid tx that advertises hashed
        collection writes (the gossip pull for still-missing data is
        the reconciler's job). `rwsets`/`tx_ids` reuse the intake
        path's single parse pass when provided."""
        from fabric_tpu.ledger.kvledger import extract_tx_rwset
        pvt_data: dict[int, object] = {}
        txids: list[str] = []
        store = self._peer.transient_store
        for i, env_bytes in enumerate(block.data.data):
            if flags[i] != txpb.TxValidationCode.VALID:
                continue
            txrw = rwsets[i] if rwsets is not None else \
                extract_tx_rwset(env_bytes)
            if txrw is None or not any(
                    nsrw.collection_hashed_rwset
                    for nsrw in txrw.ns_rwset):
                continue
            if tx_ids is not None:
                tx_id = tx_ids[i]
            else:
                try:
                    env = pu.unmarshal_envelope(env_bytes)
                    tx_id = pu.get_channel_header(
                        pu.get_payload(env)).tx_id
                except Exception:
                    continue
            if not tx_id:
                continue
            txids.append(tx_id)
            stored = store.get(tx_id)
            if stored is not None:
                pvt_data[i] = stored
        return pvt_data, txids

    # -- commit notification (gateway CommitStatus; reference:
    #    internal/pkg/gateway/commit) --

    def _notify_commit(self, block: common.Block,
                       codes: list[int], tx_ids=None) -> None:
        events = []
        if tx_ids is not None:
            events = [(tid, codes[i]) for i, tid in enumerate(tx_ids)
                      if tid]
        else:
            for i, env_bytes in enumerate(block.data.data):
                try:
                    env = pu.unmarshal_envelope(env_bytes)
                    ch = pu.get_channel_header(pu.get_payload(env))
                    if ch.tx_id:
                        events.append((ch.tx_id, codes[i]))
                except Exception:
                    continue
        with self._commit_cond:
            self._last_committed = block.header.number
            self._commit_cond.notify_all()
        for cb in list(self._commit_listeners):
            try:
                cb(self.channel_id, block, dict(events))
            except Exception:
                logger.exception("commit listener failed")

    def add_commit_listener(self, cb: Callable) -> None:
        self._commit_listeners.append(cb)

    def wait_for_height(self, height: int,
                        timeout: Optional[float] = None) -> bool:
        with self._commit_cond:
            return self._commit_cond.wait_for(
                lambda: self.ledger.height >= height, timeout)

    def tx_validation_code(self, tx_id: str) -> Optional[int]:
        ptx = self.ledger.get_transaction_by_id(tx_id)
        if ptx is None:
            return None
        return ptx.validation_code

    # -- duck-type for the shared DeliverHandler (peer-side deliver
    #    events service) --

    @property
    def height(self) -> int:
        return self.ledger.height

    def get_block(self, number: int):
        return self.ledger.block_store.get_block_by_number(number)

    def wait_for_block(self, number: int,
                       timeout: Optional[float] = None) -> bool:
        return self.wait_for_height(number + 1, timeout)


class Peer:
    """Reference: `core/peer/peer.go` Peer + the wiring in
    `internal/peer/node/start.go` serve()."""

    def __init__(self, ledger_root: str, local_msp, csp,
                 metrics_provider=None, state_db_factory=None,
                 commit_pipeline_depth: int = 0):
        self.csp = csp
        self.local_msp = local_msp
        self.metrics_provider = metrics_provider
        # Peer.CommitPipeline.Depth (0 = off): blocks validated ahead
        # of the one being committed, per channel
        self.commit_pipeline_depth = int(commit_pipeline_depth or 0)
        self.signer = local_msp.get_default_signing_identity()
        self.ledger_mgr = LedgerManager(
            ledger_root, metrics_provider=metrics_provider,
            state_db_factory=state_db_factory)
        self.transient_store = TransientStore(
            os.path.join(ledger_root, "transient.db"))
        self.chaincode_support = ChaincodeSupport(
            channel_source=lambda cid: self.channels.get(cid),
            metrics_provider=metrics_provider)
        self.channels: dict[str, Channel] = {}
        self._lock = threading.Lock()
        self.mcs = MSPMessageCryptoService(
            lambda cid: (self.channels[cid].bundle()
                         if cid in self.channels else None),
            local_deserializer=local_msp)
        self.gossip_service = None   # attached by node assembly
        self.endorser = endorser_mod.Endorser(
            self.signer, self.chaincode_support, self._channel_support,
            metrics=endorser_mod.EndorserMetrics(metrics_provider))
        from fabric_tpu.core.scc import register_system_chaincodes
        register_system_chaincodes(self)
        # reopen any previously joined channels (start.go:770
        # peerInstance.Initialize)
        for channel_id in self.ledger_mgr.ledger_ids():
            ledger = self.ledger_mgr.open(channel_id)
            self._register_channel(channel_id, ledger)

    def _register_channel(self, channel_id: str, ledger) -> Channel:
        channel = Channel(self, channel_id, ledger)
        with self._lock:
            self.channels[channel_id] = channel
        return channel

    def _channel_support(self, channel_id: str
                         ) -> Optional[endorser_mod.ChannelSupport]:
        channel = self.channels.get(channel_id)
        if channel is None:
            return None
        bundle = channel.bundle()
        distributor = None
        if self.gossip_service is not None:
            gs = self.gossip_service
            distributor = (lambda tx_id, height, pvt_results:
                           gs.distribute_private_data(
                               channel_id, tx_id, height, pvt_results))
        return endorser_mod.ChannelSupport(
            ledger=channel.ledger,
            policy_manager=bundle.policy_manager,
            deserializer=bundle.msp_manager,
            transient_store=self.transient_store,
            pvt_distributor=distributor,
            acls=(bundle.application.acls
                  if bundle.application else None),
            cc_definition=channel.chaincode_definition)

    # -- channel lifecycle (reference: cscc JoinChain →
    #    peer.CreateChannel, core/peer/channel.go) --

    def join_channel(self, genesis_block: common.Block) -> Channel:
        cfg = genesis_mod.config_from_block(genesis_block)
        env = pu.extract_envelope(genesis_block, 0)
        ch = pu.get_channel_header(pu.get_payload(env))
        channel_id = ch.channel_id
        if channel_id in self.channels:
            raise ValueError(f"already joined {channel_id}")
        # sanity: the config must parse into a bundle before we commit
        Bundle(channel_id, cfg, self.csp)
        ledger = self.ledger_mgr.create(genesis_block, channel_id)
        return self._register_channel(channel_id, ledger)

    def join_channel_by_snapshot(self, snapshot_dir: str,
                                 channel_id: str) -> Channel:
        """Join without replaying history (reference:
        `internal/peer/channel/joinbysnapshot.go`)."""
        ledger = self.ledger_mgr.create_from_snapshot(snapshot_dir,
                                                      channel_id)
        return self._register_channel(channel_id, ledger)

    def channel(self, channel_id: str) -> Optional[Channel]:
        return self.channels.get(channel_id)

    def close(self) -> None:
        for channel in list(self.channels.values()):
            if channel.commit_pipeline is not None:
                channel.commit_pipeline.stop()
        self.transient_store.close()
        self.ledger_mgr.close()
