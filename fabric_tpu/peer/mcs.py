"""Message crypto service: block + gossip message verification.

Rebuild of `internal/peer/gossip/mcs.go` (MSPMessageCryptoService):
`VerifyBlock:123-192` = data-hash integrity + BlockValidation policy
over the metadata signatures; `Verify/VerifyByChannel:203,229` for
gossip message authentication. All signature evaluation routes through
the batched policy path.
"""

from __future__ import annotations

import logging

from fabric_tpu.protos import common
from fabric_tpu.protoutil import protoutil as pu
from fabric_tpu.common.policies import policy as papi

logger = logging.getLogger("peer.mcs")


class BlockVerificationError(Exception):
    pass


class MSPMessageCryptoService:
    def __init__(self, channel_policy_getter, local_deserializer=None):
        """`channel_policy_getter(channel_id)` → the channel's policy
        manager + msp manager source (a bundle); `local_deserializer`
        authenticates channel-less gossip messages."""
        self._bundle_for = channel_policy_getter
        self._local = local_deserializer

    def verify_block(self, channel_id: str, seq_num: int,
                     block: common.Block) -> None:
        """Reference mcs.go:123: structural checks, header-number match,
        data-hash integrity, then the BlockValidation policy over the
        orderer signatures."""
        if not block.HasField("header"):
            raise BlockVerificationError(
                f"invalid block on [{channel_id}]: no header")
        if block.header.number != seq_num:
            raise BlockVerificationError(
                f"expected block [{seq_num}] but got "
                f"[{block.header.number}]")
        data_hash = pu.block_data_hash(block.data)
        if data_hash != block.header.data_hash:
            raise BlockVerificationError(
                f"block [{seq_num}] data hash mismatch")
        sig_idx = common.BlockMetadataIndex.SIGNATURES
        if len(block.metadata.metadata) <= sig_idx or \
                not block.metadata.metadata[sig_idx]:
            raise BlockVerificationError(
                f"block [{seq_num}] carries no signatures")
        try:
            signed = pu.block_signature_set(block)
        except Exception as e:
            raise BlockVerificationError(
                f"block [{seq_num}] signature metadata unreadable: {e}")
        bundle = self._bundle_for(channel_id)
        if bundle is None:
            raise BlockVerificationError(
                f"no channel [{channel_id}]")
        try:
            policy = bundle.policy_manager.get_policy(
                "/Channel/Orderer/BlockValidation")
        except papi.PolicyError as e:
            raise BlockVerificationError(
                f"no BlockValidation policy on [{channel_id}]: {e}")
        try:
            policy.evaluate_signed_data(signed)
        except papi.PolicyError as e:
            raise BlockVerificationError(
                f"block [{seq_num}] signature set rejected: {e}")

    def verify_by_channel(self, channel_id: str, identity_bytes: bytes,
                          signature: bytes, message: bytes) -> bool:
        """Gossip message auth against the channel's MSPs
        (reference mcs.go:229)."""
        bundle = self._bundle_for(channel_id)
        if bundle is None:
            return False
        try:
            ident = bundle.msp_manager.deserialize_identity(
                identity_bytes)
            ident.validate()
            return ident.verify(message, signature)
        except Exception:
            return False

    def verify(self, identity_bytes: bytes, signature: bytes,
               message: bytes) -> bool:
        """Channel-less verification against the local MSP."""
        if self._local is None:
            return False
        try:
            ident = self._local.deserialize_identity(identity_bytes)
            return ident.verify(message, signature)
        except Exception:
            return False
