"""Deliver client: pull blocks from the ordering service into a channel.

Rebuild of `core/deliverservice/deliveryclient.go` +
`internal/pkg/peer/blocksprovider/blocksprovider.go:113` DeliverBlocks:
request a stream from the peer's next block height, verify every block
(`BlockVerifier.VerifyBlock`, :229), hand it to the channel's
validate→commit pipeline; reconnect with backoff on stream failure.

The transport is pluggable: `orderer_source()` yields "deliver
endpoints" — in-process `DeliverHandler`s for single-process networks
and tests, gRPC stubs in multi-process deployments (same failover
logic either way, mirroring `internal/pkg/peer/orderers`).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from fabric_tpu.common import clustertrace, faults
from fabric_tpu.common import metrics as metrics_mod
from fabric_tpu.common import tracing
from fabric_tpu.common.backoff import FullJitterBackoff
from fabric_tpu.common.overload import OverloadError
from fabric_tpu.protos import common, orderer as ordpb
from fabric_tpu.protoutil import protoutil as pu

logger = logging.getLogger("peer.deliverclient")


def seek_envelope(channel_id: str, start, signer, stop=None,
                  newest: bool = False) -> common.Envelope:
    """Signed SeekInfo (reference: blocksprovider.go:286). Default:
    from `start` to MAX, blocking at the tip; `stop` bounds the range;
    `newest=True` fetches just the newest block."""
    seek = ordpb.SeekInfo()
    if newest:
        seek.start.newest.SetInParent()
        seek.stop.newest.SetInParent()
    else:
        seek.start.specified.number = start
        seek.stop.specified.number = (1 << 63) - 1 if stop is None \
            else stop
    seek.behavior = ordpb.SeekInfo.BLOCK_UNTIL_READY
    ch = pu.make_channel_header(common.HeaderType.DELIVER_SEEK_INFO,
                                channel_id)
    sh = pu.create_signature_header(signer.serialize(),
                                    pu.random_nonce())
    payload = pu.make_payload(ch, sh, pu.marshal(seek))
    return pu.sign_or_panic(signer, payload)


class Deliverer:
    """One channel's block puller (reference: blocksprovider
    Deliverer)."""

    def __init__(self, channel, signer, orderer_source: Callable,
                 mcs, retry_base_s: float = 0.1,
                 retry_max_s: float = 10.0, metrics_provider=None):
        """`orderer_source()` → an object whose `handle(env)` yields
        DeliverResponse (in-process DeliverHandler or a gRPC
        adapter)."""
        self._channel = channel
        self._signer = signer
        self._orderer_source = orderer_source
        self._mcs = mcs
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # full-jitter backoff (common/backoff.py), RESET after every
        # successfully processed block, so one long outage doesn't pin
        # the stream at retry_max_s forever afterwards
        self._backoff = FullJitterBackoff(retry_base_s, retry_max_s)
        # pipelined intake: backoff resets on committed progress, not
        # on submit (commits land async on the pipeline's worker)
        self._last_committed_height = channel.ledger.height
        self.reconnects = 0
        self._reconnects_metric = None
        if metrics_provider is not None:
            try:
                self._reconnects_metric = metrics_provider.new_counter(
                    metrics_mod.DELIVER_RECONNECTS_OPTS).with_labels(
                    "channel", channel.channel_id)
            except Exception:
                logger.debug("deliver_reconnects counter unavailable")

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run,
            name=f"deliver-{self._channel.channel_id}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                endpoint = self._orderer_source()
                if endpoint is None:
                    raise ConnectionError("no orderer endpoint")
                self._pull(endpoint)
                self._backoff.reset()
            except Exception as e:
                self.reconnects += 1
                if self._reconnects_metric is not None:
                    self._reconnects_metric.add(1)
                delay = self._backoff.next()
                logger.warning(
                    "[%s] deliver stream failed (%s); retry in %.2fs "
                    "(attempt %d)", self._channel.channel_id, e, delay,
                    self._backoff.failures)
                self._stop.wait(delay)

    def _pull(self, endpoint) -> None:
        channel = self._channel
        # overlapped intake: when the channel carries a CommitPipeline
        # (Peer.CommitPipeline.Depth > 0), this stream becomes its
        # feeder — the pipeline verifies + validates block N+1 on its
        # stage-A worker while block N's host commit runs, and commits
        # land on the pipeline's own worker (no waiting on the next
        # stream message). The leader-adapter path has no pipeline
        # attribute and keeps the sequential flow (its blocks enter
        # the gossip state provider, which pipelines on its own).
        pipeline = getattr(channel, "commit_pipeline", None)
        start = channel.ledger.height if pipeline is None else \
            pipeline.next_seq
        env = seek_envelope(channel.channel_id, start, self._signer)
        # the next block the STREAM must produce: with a pipelined
        # channel (or the leader adapter's bounded runahead) the
        # ledger height lags in-flight commits, so it is no longer a
        # valid expected-sequence source per iteration
        expected = start
        try:
            for resp in endpoint.handle(env):
                if self._stop.is_set():
                    return
                faults.check("deliver.stream")
                which = resp.WhichOneof("type")
                if which == "status":
                    raise ConnectionError(
                        f"deliver ended with status {resp.status}")
                block = resp.block
                # resume the block's wire trace (round 18): the
                # writer registered a carrier per block number —
                # submit under it so the commit-pipeline's validate/
                # commit spans (and the e2e_commit_seconds
                # observation) join the orderer-side trace instead of
                # opening an orphan one. Absent carrier/tracing-off:
                # shared no-op.
                carrier = clustertrace.block_carrier(
                    channel.channel_id, block.header.number)
                if pipeline is not None:
                    # verification happens inside stage A (same
                    # next-expected-block contract as below); wait for
                    # stage A to HANDLE this block before reading the
                    # next response, so a forged block surfaces now —
                    # reconnect + endpoint failover, like the
                    # sequential path — instead of idling unseen at
                    # the tip. Block N's commit still overlaps this
                    # wait for validate(N+1).
                    # abort=self._stop: a stopping deliverer must not
                    # park in backpressure behind a slow commit.
                    # `expected` (== pipeline.next_seq within one
                    # stream: both start there and advance per block)
                    # is the single sequence tracker for both branches
                    # resume ONCE around the whole retry loop: a
                    # backpressure retry is local queueing, not
                    # another network hop — re-entering resumed()
                    # per attempt would flood hop_seconds/the ring
                    # with duplicate hop.recv observations
                    with clustertrace.resumed(
                            carrier,
                            link=f"deliver:{channel.channel_id}"):
                        while True:
                            try:
                                pipeline.submit(expected, block=block,
                                                abort=self._stop)
                                break
                            except OverloadError:
                                # deadline-bounded backpressure:
                                # nothing was enqueued — retry the
                                # SAME block in place (a reset +
                                # re-seek would re-fetch work the
                                # pipeline still holds)
                                if self._stop.is_set():
                                    return
                    pipeline.wait_validated(expected,
                                            abort=self._stop)
                    # backoff resets only on COMMITTED progress — a
                    # validated-but-uncommitted block is not yet proof
                    # the stream is healthy
                    height = channel.ledger.height
                    if height > self._last_committed_height:
                        self._last_committed_height = height
                        self._backoff.reset()
                else:
                    # verify BEFORE touching the pipeline
                    # (blocksprovider.go:229)
                    self._mcs.verify_block(channel.channel_id,
                                           expected, block)
                    with clustertrace.resumed(
                            carrier,
                            link=f"deliver:{channel.channel_id}"):
                        channel.process_block(block)
                        clustertrace.note_commit(tracing.capture())
                    # a processed block proves the stream is healthy
                    # again: reset the backoff so the NEXT outage
                    # starts from the base delay instead of the
                    # previous outage's ceiling
                    self._backoff.reset()
                expected += 1
            if pipeline is not None:
                # orderly stream end: land the in-flight tail before
                # the re-seek (a reset here would drop the last
                # blocks and re-fetch them forever on a stream that
                # closes at the tip)
                pipeline.drain(abort=self._stop)
        except Exception:
            if pipeline is not None:
                # torn stream / rejected block: drop in-flight work
                # and re-seek from the committed height
                pipeline.reset()
            raise
