"""Generated wire-format modules (layer 0 — SURVEY.md §1).

Sources are the sibling .proto files; regenerate with
`python tools/gen_protos.py` after editing them.
"""

from fabric_tpu.protos import chaincode_shim_pb2 as ccshim
from fabric_tpu.protos import common_pb2 as common
from fabric_tpu.protos import configtx_pb2 as configtx
from fabric_tpu.protos import gateway_pb2 as gateway
from fabric_tpu.protos import gossip_pb2 as gossip
from fabric_tpu.protos import msp_pb2 as msp
from fabric_tpu.protos import orderer_pb2 as orderer
from fabric_tpu.protos import policies_pb2 as policies
from fabric_tpu.protos import proposal_pb2 as proposal
from fabric_tpu.protos import rwset_pb2 as rwset
from fabric_tpu.protos import transaction_pb2 as transaction

__all__ = [
    "ccshim", "common", "configtx", "gateway", "gossip", "msp",
    "orderer", "policies", "proposal", "rwset", "transaction",
]
from fabric_tpu.protos import raft_pb2 as raft  # noqa: F401,E402

__all__.append("raft")
from fabric_tpu.protos import discovery_pb2 as discovery  # noqa: F401,E402

__all__.append("discovery")
from fabric_tpu.protos import events_pb2 as events  # noqa: F401,E402

__all__.append("events")
