"""Persisted-table integrity (ISSUE 2 satellite): sha256 sidecars.

Every *.npy the warm machinery writes carries a `<path>.sha256`
sidecar; a load whose bytes no longer match falls back to a REBUILD.
The failure mode this closes: the old loader only checked dtype and
byte COUNT, so same-size corruption (bit rot, a torn write that
survived rename) fed the verify kernel wrong curve points — silent
verdict flips. Builders are stubbed (test_q16_cache idiom); the
G-table path runs its real 2-second host build.
"""

import os

import numpy as np
import pytest

from fabric_tpu.bccsp.tpu import TPUProvider
from fabric_tpu.ops import comb

EST = 1000


def _stub(monkeypatch):
    import jax.numpy as jnp

    def fake_qtab_fn(self, K):
        return lambda qx, qy: jnp.zeros((2,), jnp.int32)

    def fake_q16_fn(self, K):
        return lambda q8, k: jnp.arange(EST // 4, dtype=jnp.int32)

    monkeypatch.setattr(TPUProvider, "_qtab_fn", fake_qtab_fn)
    monkeypatch.setattr(TPUProvider, "_q16_fn", fake_q16_fn)
    monkeypatch.setattr(TPUProvider, "_q16_est_bytes",
                        lambda self, K: EST)


_QX = np.zeros((1, 20), dtype=np.int32)
_KEY = (bytes([7]) * 64,)


def _flip_one_payload_byte(path):
    """Same-size corruption: flip a byte in the npy payload (past the
    header) so the legacy dtype/nbytes checks still pass."""
    with open(path, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        last = f.read(1)[0]
        f.seek(-1, os.SEEK_END)
        f.write(bytes([last ^ 0xFF]))


class TestSidecarHelpers:
    def test_roundtrip_and_mismatch(self, tmp_path):
        p = str(tmp_path / "t.npy")
        np.save(p, np.arange(16, dtype=np.int32))
        assert comb.verify_digest_sidecar(p) is None    # no sidecar yet
        comb.write_digest_sidecar(p)
        assert comb.verify_digest_sidecar(p) is True
        _flip_one_payload_byte(p)
        assert comb.verify_digest_sidecar(p) is False
        comb.drop_digest_sidecar(p)
        assert comb.verify_digest_sidecar(p) is None


class TestQTableIntegrity:
    def test_persist_writes_sidecar(self, monkeypatch, tmp_path):
        _stub(monkeypatch)
        warm = str(tmp_path / "warm")
        p1 = TPUProvider(use_g16=True, table_cache_bytes=3 * EST,
                         warm_keys_dir=warm)
        assert p1._q16_cached(_KEY, 1, _QX, _QX) is not None
        p1.flush_warm_tables()
        path = p1._table_path(_KEY)
        assert os.path.exists(path)
        assert os.path.exists(path + ".sha256")
        assert comb.verify_digest_sidecar(path) is True

    def test_same_size_corruption_rebuilds(self, monkeypatch,
                                           tmp_path):
        _stub(monkeypatch)
        warm = str(tmp_path / "warm")
        p1 = TPUProvider(use_g16=True, table_cache_bytes=3 * EST,
                         warm_keys_dir=warm)
        assert p1._q16_cached(_KEY, 1, _QX, _QX) is not None
        p1.flush_warm_tables()
        path = p1._table_path(_KEY)
        _flip_one_payload_byte(path)     # nbytes/dtype still "valid"

        p2 = TPUProvider(use_g16=True, table_cache_bytes=3 * EST,
                         warm_keys_dir=warm)
        assert p2._prewarm_tables() == 1
        assert p2.stats["q16_disk_loads"] == 0   # corrupt bytes refused
        assert p2.stats["q16_builds"] == 1       # rebuilt instead

    def test_reclaim_removes_sidecar(self, monkeypatch, tmp_path):
        _stub(monkeypatch)
        warm = str(tmp_path / "warm")
        p1 = TPUProvider(use_g16=True, table_cache_bytes=EST,
                         warm_keys_dir=warm)
        assert p1._q16_cached(_KEY, 1, _QX, _QX) is not None
        p1.flush_warm_tables()
        path = p1._table_path(_KEY)
        assert os.path.exists(path + ".sha256")
        p1._drop_warm_keys(_KEY)
        assert not os.path.exists(path)
        assert not os.path.exists(path + ".sha256")


class TestGTableIntegrity:
    def test_corrupt_gtab_cache_rebuilds(self, tmp_path, monkeypatch):
        cache = str(tmp_path / "gtab8.npy")
        monkeypatch.setenv("FABRIC_TPU_GTAB_CACHE", cache)
        comb.g_tables.cache_clear()
        try:
            good = comb.g_tables()
            assert os.path.exists(cache + ".sha256")
            _flip_one_payload_byte(cache)
            comb.g_tables.cache_clear()
            again = comb.g_tables()      # detects mismatch, rebuilds
            assert np.array_equal(good, again)
            # the rebuild re-published consistent bytes + sidecar
            assert comb.verify_digest_sidecar(cache) is True
        finally:
            comb.g_tables.cache_clear()
