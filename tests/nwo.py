"""nwo — "network world order" multi-process test harness.

Rebuild of `integration/nwo/network.go` (SURVEY §4): renders
core.yaml / orderer.yaml / configtx.yaml / crypto-config.yaml,
runs the cryptogen + configtxgen CLIs, launches REAL peer/orderer
processes (`python -m fabric_tpu.cmd.{peer,orderer}`) on random ports,
joins channels through the admin APIs, and tears everything down.
Node processes run CPU-only (JAX_PLATFORMS=cpu) with the sw BCCSP.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def wait_http(url: str, timeout: float = 30.0) -> None:
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as resp:
                if resp.status == 200:
                    return
        except Exception as e:
            last = e
        time.sleep(0.2)
    raise TimeoutError(f"{url} not healthy: {last}")


class Node:
    def __init__(self, name: str, argv: list[str], log_path: str,
                 extra_env: dict | None = None):
        self.name = name
        self.log_path = log_path
        self.log = open(log_path, "ab")
        env = dict(os.environ)
        env.update({
            "PYTHONPATH": REPO,
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "FABRIC_LOGGING_SPEC": env.get("FABRIC_LOGGING_SPEC",
                                           "info"),
        })
        if extra_env:
            env.update(extra_env)
        self.proc = subprocess.Popen(argv, stdout=self.log,
                                     stderr=subprocess.STDOUT, env=env)

    def kill(self, sig=signal.SIGKILL) -> None:
        if self.proc.poll() is None:
            self.proc.send_signal(sig)
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=5)
        self.log.close()

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None


class Network:
    """2-org (1 peer each by default) × N-orderer raft network."""

    def __init__(self, root: str, n_orderers: int = 3,
                 peers_per_org: int = 1, channel: str = "testchannel",
                 state_backend: dict | None = None,
                 spare_orderers: int = 0):
        self.root = root
        self.channel = channel
        self.n_orderers = n_orderers
        # spare orderers get crypto material and ports but are NOT in
        # the genesis consenter set — they join later (onboarding /
        # consenter-addition tests)
        self.spare_orderers = spare_orderers
        self.peers_per_org = peers_per_org
        # org -> "http" runs that org's peers against an external
        # state-server process (the statecouchdb deployment shape)
        self.state_backend = state_backend or {}
        self.state_server_port = free_port() if self.state_backend \
            else None
        self.nodes: dict[str, Node] = {}
        # (general grpc, ops, mTLS cluster listener) per orderer
        self.orderer_ports = [(free_port(), free_port(), free_port())
                              for _ in range(n_orderers +
                                             spare_orderers)]
        self.peer_ports = {}   # (org, i) -> (grpc, ops)
        for org in ("org1", "org2"):
            for i in range(peers_per_org):
                self.peer_ports[(org, i)] = (free_port(), free_port())
        self._generate_material()

    # -- config generation --

    def orderer_tls_cert_path(self, i: int) -> str:
        return os.path.join(
            self.root, "crypto", "ordererOrganizations", "example.com",
            "orderers", f"orderer{i}.example.com", "tls", "server.crt")

    def orderer_admin_msp_dir(self) -> str:
        return os.path.join(
            self.root, "crypto", "ordererOrganizations", "example.com",
            "users", "Admin@example.com", "msp")

    def _generate_material(self) -> None:
        os.makedirs(self.root, exist_ok=True)
        crypto = os.path.join(self.root, "crypto")
        with open(os.path.join(self.root, "crypto-config.yaml"),
                  "w") as f:
            yaml.safe_dump({
                "OrdererOrgs": [{
                    "Name": "Orderer", "Domain": "example.com",
                    "Template": {"Count": self.n_orderers +
                                 self.spare_orderers}}],
                "PeerOrgs": [
                    {"Name": "Org1", "Domain": "org1.example.com",
                     "Template": {"Count": self.peers_per_org},
                     "Users": {"Count": 1}},
                    {"Name": "Org2", "Domain": "org2.example.com",
                     "Template": {"Count": self.peers_per_org},
                     "Users": {"Count": 1}},
                ],
            }, f)
        self._run_cli("fabric_tpu.cmd.cryptogen", "generate",
                      "--config",
                      os.path.join(self.root, "crypto-config.yaml"),
                      "--output", crypto)

        orderer_eps = [f"127.0.0.1:{g}" for g, _o, _c in
                       self.orderer_ports[:self.n_orderers]]

        _otls = self.orderer_tls_cert_path

        profile = {
            "Consortium": "SampleConsortium",
            "Capabilities": {"V2_0": True},
            "Application": {
                "Organizations": [
                    {"Name": "Org1", "ID": "Org1MSP",
                     "MSPDir": os.path.join(
                         crypto, "peerOrganizations",
                         "org1.example.com", "msp")},
                    {"Name": "Org2", "ID": "Org2MSP",
                     "MSPDir": os.path.join(
                         crypto, "peerOrganizations",
                         "org2.example.com", "msp")},
                ],
                "Capabilities": {"V2_0": True},
            },
            "Orderer": {
                "OrdererType": "etcdraft",
                "Addresses": orderer_eps,
                "BatchTimeout": "250ms",
                "BatchSize": {"MaxMessageCount": 10},
                "Raft": {"Consenters": [
                    {"Host": "127.0.0.1", "Port": c,
                     "ClientTLSCert": _otls(i),
                     "ServerTLSCert": _otls(i)}
                    for i, (_g, _o, c) in
                    enumerate(self.orderer_ports[:self.n_orderers])]},
                "Organizations": [{
                    "Name": "OrdererOrg", "ID": "OrdererMSP",
                    "MSPDir": os.path.join(
                        crypto, "ordererOrganizations",
                        "example.com", "msp"),
                    "OrdererEndpoints": orderer_eps}],
                "Capabilities": {"V2_0": True},
            },
        }
        with open(os.path.join(self.root, "configtx.yaml"), "w") as f:
            yaml.safe_dump({"Profiles": {"Genesis": profile}}, f)
        self.genesis_path = os.path.join(self.root, "genesis.block")
        self._run_cli("fabric_tpu.cmd.configtxgen",
                      "-profile", "Genesis",
                      "-channelID", self.channel,
                      "-configPath",
                      os.path.join(self.root, "configtx.yaml"),
                      "-outputBlock", self.genesis_path)

    def _run_cli(self, module: str, *argv) -> str:
        env = dict(os.environ)
        env.update({"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
                    "PALLAS_AXON_POOL_IPS": ""})
        out = subprocess.run(
            [sys.executable, "-m", module, *argv], env=env,
            capture_output=True, text=True, timeout=120)
        if out.returncode != 0:
            raise RuntimeError(
                f"{module} {argv} failed:\n{out.stdout}\n{out.stderr}")
        return out.stdout

    # -- node lifecycle --

    def start_orderer(self, i: int,
                      extra_env: dict | None = None) -> Node:
        grpc_port, ops_port, cluster_port = self.orderer_ports[i]
        crypto = os.path.join(self.root, "crypto")
        tls_dir = os.path.join(
            crypto, "ordererOrganizations", "example.com", "orderers",
            f"orderer{i}.example.com", "tls")
        cfg = {
            "General": {
                "ListenAddress": "127.0.0.1",
                "ListenPort": grpc_port,
                "LocalMSPDir": os.path.join(
                    crypto, "ordererOrganizations", "example.com",
                    "orderers", f"orderer{i}.example.com", "msp"),
                "LocalMSPID": "OrdererMSP",
                "BootstrapFiles": [self.genesis_path],
            },
            "FileLedger": {"Location": os.path.join(
                self.root, f"orderer{i}", "ledger")},
            "Cluster": {
                "Endpoint": f"127.0.0.1:{cluster_port}",
                "ListenAddress": "127.0.0.1",
                "ListenPort": cluster_port,
                "ServerCertificate": os.path.join(tls_dir,
                                                  "server.crt"),
                "ServerPrivateKey": os.path.join(tls_dir, "server.key"),
                "ClientCertificate": os.path.join(tls_dir,
                                                  "server.crt"),
                "ClientPrivateKey": os.path.join(tls_dir, "server.key"),
                "RootCAs": [os.path.join(tls_dir, "ca.crt")],
            },
            "Consensus": {"TickInterval": "100ms"},
            "Admin": {"ListenAddress": f"127.0.0.1:{ops_port}"},
        }
        path = os.path.join(self.root, f"orderer{i}.yaml")
        with open(path, "w") as f:
            yaml.safe_dump(cfg, f)
        node = Node(f"orderer{i}",
                    [sys.executable, "-m", "fabric_tpu.cmd.orderer",
                     "start", "--config", path],
                    os.path.join(self.root, f"orderer{i}.log"),
                    extra_env=extra_env)
        self.nodes[f"orderer{i}"] = node
        return node

    def start_peer(self, org: str, i: int = 0,
                   bootstrap: str = "") -> Node:
        grpc_port, ops_port = self.peer_ports[(org, i)]
        crypto = os.path.join(self.root, "crypto")
        orderer_eps = [f"127.0.0.1:{g}" for g, _o, _c in
                       self.orderer_ports]
        cfg = {
            "peer": {
                "id": f"peer{i}.{org}.example.com",
                "address": f"127.0.0.1:{grpc_port}",
                "localMspId": f"{org.capitalize()}MSP",
                "mspConfigPath": os.path.join(
                    crypto, "peerOrganizations", f"{org}.example.com",
                    "peers", f"peer{i}.{org}.example.com", "msp"),
                "fileSystemPath": os.path.join(
                    self.root, f"peer_{org}_{i}"),
                "ordererEndpoints": orderer_eps,
                "gossip": {"bootstrap": bootstrap or
                           f"127.0.0.1:{self.peer_ports[('org1', 0)][0]}"},
            },
            "chaincode": {"registered": [
                "assetcc=fabric_tpu.examples.assetcc:AssetChaincode"]},
            "operations": {
                "listenAddress": f"127.0.0.1:{ops_port}"},
        }
        if self.state_backend.get(org) == "http":
            cfg["ledger"] = {"state": {
                "stateDatabase": "http",
                "stateDatabaseAddress":
                    f"127.0.0.1:{self.state_server_port}"}}
        path = os.path.join(self.root, f"core_{org}_{i}.yaml")
        with open(path, "w") as f:
            yaml.safe_dump(cfg, f)
        node = Node(f"peer_{org}_{i}",
                    [sys.executable, "-m", "fabric_tpu.cmd.peer",
                     "node", "start", "--config", path],
                    os.path.join(self.root, f"peer_{org}_{i}.log"))
        self.nodes[f"peer_{org}_{i}"] = node
        return node

    def start_state_server(self) -> Node:
        node = Node("stateserver",
                    [sys.executable, "-m",
                     "fabric_tpu.ledger.stateserver",
                     "--data-dir", os.path.join(self.root,
                                                "stateserver"),
                     "--listen",
                     f"127.0.0.1:{self.state_server_port}"],
                    os.path.join(self.root, "stateserver.log"))
        self.nodes["stateserver"] = node
        return node

    def start_all(self) -> None:
        if self.state_server_port is not None:
            self.start_state_server()
            wait_http(f"http://127.0.0.1:{self.state_server_port}"
                      "/healthz")
        for i in range(self.n_orderers):
            self.start_orderer(i)
        for i in range(self.n_orderers):
            wait_http(f"http://127.0.0.1:{self.orderer_ports[i][1]}"
                      "/healthz")
        for org in ("org1", "org2"):
            for i in range(self.peers_per_org):
                self.start_peer(org, i)
        for (org, i), (_g, ops) in self.peer_ports.items():
            wait_http(f"http://127.0.0.1:{ops}/healthz")

    def join_all(self) -> None:
        for (org, i), (_g, ops) in sorted(self.peer_ports.items()):
            self._run_cli("fabric_tpu.cmd.peer", "channel", "join",
                          "--ops", f"127.0.0.1:{ops}",
                          "--block", self.genesis_path)

    # -- client helpers --

    def peer_cli_identity(self, org: str) -> list[str]:
        crypto = os.path.join(self.root, "crypto")
        return ["--msp-dir",
                os.path.join(crypto, "peerOrganizations",
                             f"{org}.example.com", "users",
                             f"User1@{org}.example.com", "msp"),
                "--msp-id", f"{org.capitalize()}MSP"]

    def invoke(self, org: str, peer_i: int, *cc_args,
               transient: str = "") -> str:
        gport = self.peer_ports[(org, peer_i)][0]
        argv = ["chaincode", "invoke", "--gateway",
                f"127.0.0.1:{gport}",
                *self.peer_cli_identity(org),
                "-C", self.channel, "-n", "assetcc", "-a", *cc_args]
        if transient:
            argv += ["--transient", transient]
        return self._run_cli("fabric_tpu.cmd.peer", *argv)

    def query(self, org: str, peer_i: int, *cc_args) -> str:
        gport = self.peer_ports[(org, peer_i)][0]
        return self._run_cli(
            "fabric_tpu.cmd.peer", "chaincode", "query", "--gateway",
            f"127.0.0.1:{gport}", *self.peer_cli_identity(org),
            "-C", self.channel, "-n", "assetcc", "-a", *cc_args)

    def osnadmin(self, orderer_i: int, *argv) -> str:
        ops = self.orderer_ports[orderer_i][1]
        return self._run_cli("fabric_tpu.cmd.osnadmin", "channel",
                             *argv, "--orderer-address",
                             f"127.0.0.1:{ops}")

    def teardown(self) -> None:
        for node in self.nodes.values():
            node.kill()
