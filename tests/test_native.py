"""Native (C++) batch-prep: differential parity with the Python path.

The native library must accept/reject EXACTLY the signatures the
Python gates (utils.unmarshal_signature + is_low_s + range checks)
accept/reject, and produce identical scalars — over random valid
signatures AND an adversarial corpus (bad DER, high-S, huge/negative
integers, trailing data, truncations).
"""

import os

import numpy as np
import pytest

from fabric_tpu import native
from fabric_tpu.bccsp import sw as swmod, utils

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")

N = utils.P256_N
P256_P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF


def python_prep(sig: bytes):
    """The pure-Python reference pipeline (tpu.py fallback path)."""
    try:
        r, s = utils.unmarshal_signature(sig)
    except utils.SignatureFormatError:
        return None
    if not utils.is_low_s(s):
        return None
    if r >= N or s >= N:
        return None
    rpn = r + N if r + N < P256_P else r
    w = pow(s, -1, N)
    return r, rpn, w


def _assert_parity(sigs):
    ok, r_b, rpn_b, w_b = native.batch_prep(sigs)
    for i, sig in enumerate(sigs):
        expected = python_prep(sig)
        assert bool(ok[i]) == (expected is not None), \
            (i, sig.hex(), bool(ok[i]))
        if expected is None:
            continue
        r, rpn, w = expected
        assert int.from_bytes(bytes(r_b[i]), "big") == r, i
        assert int.from_bytes(bytes(rpn_b[i]), "big") == rpn, i
        assert int.from_bytes(bytes(w_b[i]), "big") == w, i


class TestNativeParity:
    def test_random_valid_signatures(self):
        import hashlib

        from fabric_tpu.bccsp import bccsp as api
        key = swmod.SWProvider()
        k = key.key_gen(api.ECDSAKeyGenOpts(ephemeral=True))
        sigs = []
        for i in range(64):
            der = key.sign(k, hashlib.sha256(f"m{i}".encode()).digest())
            r, s = utils.unmarshal_signature(der)
            sigs.append(utils.marshal_signature(r, utils.to_low_s(s)))
            # high-S re-encode: both paths must agree on the reject
            sigs.append(utils.marshal_signature(r, N - s))
        _assert_parity(sigs)

    def test_adversarial_corpus(self):
        half = N >> 1
        corpus = [
            b"",
            b"\x30",
            b"\x30\x00",
            b"\x02\x01\x01",                       # no SEQUENCE
            b"\x30\x06\x02\x01\x01\x02\x01\x01",   # valid tiny r,s
            b"\x30\x06\x02\x01\x01\x02\x01\x01" + b"xx",  # trailing ok
            b"\x30\x07\x02\x01\x01\x02\x01\x01x",  # trailing INSIDE seq
            b"\x30\x06\x02\x01\x00\x02\x01\x01",   # r == 0
            b"\x30\x06\x02\x01\x01\x02\x01\x00",   # s == 0
            b"\x30\x06\x02\x01\x81\x02\x01\x01",   # r negative
            b"\x30\x08\x02\x03\x00\x00\x01\x02\x01\x01",  # non-minimal r
            b"\x30\x07\x02\x02\x00\x80\x02\x01\x01",      # minimal 0x80
            # s exactly half order (accepted) and half+1 (rejected)
            utils.marshal_signature(1, half),
            utils.marshal_signature(1, half + 1),
            utils.marshal_signature(N - 1, 1),     # r = n-1 ok
            utils.marshal_signature(N, 1),         # r = n rejected
            utils.marshal_signature(N + 5, 1),     # r > n rejected
            utils.marshal_signature(1, 1),
            utils.marshal_signature(2**256 + 7, 1),  # r wider than 256b
            utils.marshal_signature(P256_P - N - 1, half),  # rpn = r+n
            utils.marshal_signature(P256_P - N + 1, half),  # rpn = r
            b"\x30\x84\x00\x00\x00\x06\x02\x01\x01\x02\x01\x01",  # long-form len (non-minimal)
            b"\x30\x81\x06\x02\x01\x01\x02\x01\x01",  # 0x81 len < 0x80
        ]
        _assert_parity(corpus)

    def test_fuzz_mutations(self):
        """Bit-flip fuzz over a valid signature: both paths always
        agree (accept or reject, and scalars when accepted)."""
        rng = np.random.default_rng(42)
        base = utils.marshal_signature(1234567890123456789,
                                       utils.to_low_s(987654321))
        sigs = [base]
        for _ in range(300):
            mutated = bytearray(base)
            for _ in range(rng.integers(1, 4)):
                pos = rng.integers(0, len(mutated))
                mutated[pos] ^= 1 << rng.integers(0, 8)
            sigs.append(bytes(mutated))
        for _ in range(100):
            sigs.append(bytes(rng.integers(0, 256,
                                           rng.integers(0, 80),
                                           dtype=np.uint8)))
        _assert_parity(sigs)

    def test_modinv_edge_scalars(self):
        sigs = [utils.marshal_signature(1, s) for s in
                [1, 2, 3, (N >> 1) - 1, N >> 1]]
        _assert_parity(sigs)

    @pytest.mark.slow
    def test_provider_uses_native_and_matches_sw(self):
        """End-to-end: TPU provider (native prep) and sw provider agree
        on a mixed batch. Slow: jits the real verify kernel (~minutes
        on a CPU-only box) — the tier-1 integration coverage of native
        prep through verify_batch lives in test_pipeline_overlap.py's
        recorder-stub suites."""
        from fabric_tpu.bccsp import bccsp as api
        from fabric_tpu.bccsp.sw import SWProvider
        from fabric_tpu.bccsp.tpu import TPUProvider

        sw = SWProvider()
        tpu = TPUProvider(min_batch=1)
        key = sw.key_gen(api.ECDSAKeyGenOpts(ephemeral=True))
        items = []
        for i in range(24):
            msg = f"payload-{i}".encode()
            digest = sw.hash(msg)
            sig = sw.sign(key, digest)
            if i % 3 == 0:
                sig = bytearray(sig)
                sig[-1] ^= 1  # corrupt
                sig = bytes(sig)
            items.append(api.VerifyItem(key=key, signature=sig,
                                        message=msg))
        want = sw.verify_batch(items)
        got = tpu.verify_batch(items)
        assert got == want
        assert sum(want) > 0 and not all(want)


class TestTxidScan:
    """Tolerant native txid walker vs the Python protobuf parser
    (block-store indexing; native/blockprep.cpp ftpu_txid_scan)."""

    @staticmethod
    def _env(tx_id: str = "ab12", channel: str = "ch") -> bytes:
        from fabric_tpu.protos import common as cpb
        ch = cpb.ChannelHeader(type=cpb.HeaderType.ENDORSER_TRANSACTION,
                               channel_id=channel, tx_id=tx_id)
        pay = cpb.Payload(
            header=cpb.Header(channel_header=ch.SerializeToString()),
            data=b"body")
        return cpb.Envelope(payload=pay.SerializeToString(),
                            signature=b"sig").SerializeToString()

    def test_clean_and_edge_envelopes(self):
        from fabric_tpu import native
        envs = [
            self._env("feedbeef"),
            self._env(""),                      # cleanly absent txid
            b"",                                # empty envelope
            b"\xff\xff\xff",                    # garbage
            self._env("cafe") + b"\x38\x01",    # unknown field appended
        ]
        out = native.txid_scan(envs)
        assert out is not None
        assert out[0] == "feedbeef"
        assert out[1] == ""
        # empty envelope: no payload -> Python decides (skips it)
        assert out[2] is None
        assert out[3] is None                   # malformed -> Python
        assert out[4] == "cafe"                 # unknown fields legal

    def test_repeated_message_fields_route_to_python(self):
        """Protobuf merges repeated embedded-message fields by
        concatenation — last-wins would drop the first occurrence's
        tx_id. The native walker must hand such envelopes to Python
        (code-review finding: a crafted duplicate header could
        otherwise hide a tx_id from the block index and defeat
        DUPLICATE_TXID protection)."""
        from fabric_tpu import native
        from fabric_tpu.protos import common as cpb
        from fabric_tpu.protoutil import protoutil as pu

        # Payload with TWO header fields: first carries the txid,
        # second is an empty Header
        ch = cpb.ChannelHeader(channel_id="ch", tx_id="hidden01")
        hdr1 = cpb.Header(
            channel_header=ch.SerializeToString()).SerializeToString()
        hdr2 = cpb.Header().SerializeToString()
        payload = (b"\x0a" + bytes([len(hdr1)]) + hdr1 +
                   b"\x0a" + bytes([len(hdr2)]) + hdr2)
        env = cpb.Envelope(payload=payload).SerializeToString()
        out = native.txid_scan([env])
        assert out == [None], "duplicate header must route to Python"
        # and the Python parser DOES see the txid (merge semantics)
        e = pu.unmarshal_envelope(env)
        merged = pu.get_channel_header(pu.get_payload(e))
        assert merged.tx_id == "hidden01"

    def test_blockstore_indexes_duplicate_header_envelope(self, tmp_path):
        """End to end through _block_tx_ids: the fallback path indexes
        what the native walker refused."""
        from fabric_tpu.ledger.blkstorage import BlockStore
        from fabric_tpu.ledger.kvdb import DBHandle, KVStore
        from fabric_tpu.protos import common as cpb
        from fabric_tpu.protoutil import protoutil as pu

        ch = cpb.ChannelHeader(channel_id="ch", tx_id="duphdr01")
        hdr1 = cpb.Header(
            channel_header=ch.SerializeToString()).SerializeToString()
        hdr2 = cpb.Header().SerializeToString()
        payload = (b"\x0a" + bytes([len(hdr1)]) + hdr1 +
                   b"\x0a" + bytes([len(hdr2)]) + hdr2)
        env = cpb.Envelope(payload=payload).SerializeToString()

        store = BlockStore(str(tmp_path),
                           DBHandle(KVStore(":memory:"), "blk"))
        block = pu.new_block(0, b"")
        block.data.data.append(env)
        block.metadata.metadata.extend(
            [b""] * (cpb.BlockMetadataIndex.TRANSACTIONS_FILTER + 1))
        block.metadata.metadata[
            cpb.BlockMetadataIndex.TRANSACTIONS_FILTER] = bytes([0])
        store.add_block(block)
        assert store.get_tx_loc("duphdr01") == (0, 0, 0)
