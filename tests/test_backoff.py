"""common/backoff.py: the full-jitter policy shared by the peer
deliver client and the onboarding replicator (ISSUE 3 satellite —
extracted from PR 1's deliverclient so both reconnect loops retry
identically)."""

import pytest

from fabric_tpu.common.backoff import FullJitterBackoff


class TestFullJitterBackoff:
    def test_cap_grows_exponentially_then_clamps(self):
        caps = []
        b = FullJitterBackoff(0.1, 1.0, draw=lambda lo, hi: hi)
        for _ in range(6):
            caps.append(b.next())
        assert caps == [pytest.approx(0.2), pytest.approx(0.4),
                        pytest.approx(0.8), 1.0, 1.0, 1.0]

    def test_draw_is_full_jitter_over_zero_to_cap(self):
        seen = []
        b = FullJitterBackoff(0.1, 10.0,
                              draw=lambda lo, hi: seen.append((lo, hi))
                              or 0.0)
        b.next()
        b.next()
        assert seen == [(0.0, pytest.approx(0.2)),
                        (0.0, pytest.approx(0.4))]

    def test_reset_on_progress_restarts_from_base(self):
        b = FullJitterBackoff(0.1, 10.0, draw=lambda lo, hi: hi)
        for _ in range(5):
            b.next()
        assert b.cap() > 1.0
        b.reset()
        assert b.failures == 0
        # the outage after progress starts from the base delay, not
        # pinned at the previous outage's ceiling
        assert b.next() == pytest.approx(0.2)

    def test_default_draw_within_bounds(self):
        b = FullJitterBackoff(0.05, 0.4)
        for _ in range(50):
            d = b.next()
            assert 0.0 <= d <= 0.4

    def test_invalid_args_rejected(self):
        with pytest.raises(ValueError):
            FullJitterBackoff(0.0, 1.0)
        with pytest.raises(ValueError):
            FullJitterBackoff(1.0, 0.5)
