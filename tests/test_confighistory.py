"""Collection-config history store.

Reference: `core/ledger/confighistory/{mgr,db_helper}.go` — a state
listener persisting each committed chaincode definition that carries
collections, keyed (namespace, committing block); queried by the
private-data reconciler via MostRecentCollectionConfigBelow; exported
into and imported from ledger snapshots.
"""

import json

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.core.scc import lifecycle as lc
from fabric_tpu.ledger import KVLedger
from fabric_tpu.ledger.confighistory import ConfigHistoryMgr, _key, _unkey
from fabric_tpu.ledger.kvdb import DBHandle, KVStore
from fabric_tpu.ledger.statedb import Height, VersionedValue

from tests.test_ledger import append_block, make_tx_envelope


def _definition(name, colls, sequence=1):
    return lc.canonical_definition({
        "name": name, "sequence": sequence,
        "collections": colls,
    })


def _vv(value, block=1):
    return VersionedValue(value, Height(block, 0), b"")


@pytest.fixture()
def mgr(tmp_path):
    kv = KVStore(str(tmp_path / "ch.db"))
    yield ConfigHistoryMgr(DBHandle(kv, "confighist"))
    kv.close()


COLL_A = [{"name": "secrets", "member_orgs": ["Org1MSP"],
           "block_to_live": 10}]
COLL_B = [{"name": "secrets", "member_orgs": ["Org1MSP", "Org2MSP"],
           "block_to_live": 0}]


class TestKeyCodec:
    def test_roundtrip_blocks_with_zero_bytes_in_inverted(self):
        # inverted(2^64-1 - b) contains \x00 bytes for many b values;
        # decoding must not split on them
        for blk in (0, 1, 255, 256, 2**32, 2**40 - 1):
            ns, got = _unkey(_key("mycc", blk))
            assert (ns, got) == ("mycc", blk)

    def test_descending_order_per_namespace(self):
        keys = [_key("cc", b) for b in (5, 9, 200)]
        assert sorted(keys) == [_key("cc", 200), _key("cc", 9),
                                _key("cc", 5)]


class TestMgr:
    def test_records_only_definitions_with_collections(self, mgr):
        mgr.handle_state_updates(4, {
            ("_lifecycle", "namespaces/mycc"):
                _vv(_definition("mycc", COLL_A)),
            ("_lifecycle", "namespaces/plain"):
                _vv(_definition("plain", [])),
            ("_lifecycle", "unrelated/key"): _vv(b"{}"),
            ("othercc", "namespaces/x"): _vv(b"{}"),
        })
        assert mgr.entries() == [("mycc", 4)]

    def test_most_recent_below_picks_governing_config(self, mgr):
        mgr.handle_state_updates(4, {
            ("_lifecycle", "namespaces/mycc"):
                _vv(_definition("mycc", COLL_A))})
        mgr.handle_state_updates(9, {
            ("_lifecycle", "namespaces/mycc"):
                _vv(_definition("mycc", COLL_B, sequence=2))})
        # a gap at block 6 is governed by the block-4 config (BTL 10)
        blk, d = mgr.most_recent_below("mycc", 6)
        assert blk == 4
        assert d.collection("secrets").block_to_live == 10
        assert d.collection("secrets").member_orgs == ("Org1MSP",)
        # a gap at block 12 sees the upgraded config
        blk, d = mgr.most_recent_below("mycc", 12)
        assert blk == 9
        assert d.collection("secrets").member_orgs == \
            ("Org1MSP", "Org2MSP")
        # strictly below: the config committed AT block 4 does not
        # govern block 4 itself
        assert mgr.most_recent_below("mycc", 4) is None
        assert mgr.most_recent_below("mycc", 0) is None
        assert mgr.most_recent_below("nope", 100) is None

    def test_namespaces_do_not_bleed(self, mgr):
        mgr.handle_state_updates(3, {
            ("_lifecycle", "namespaces/cc"):
                _vv(_definition("cc", COLL_A))})
        mgr.handle_state_updates(5, {
            ("_lifecycle", "namespaces/cc2"):
                _vv(_definition("cc2", COLL_B))})
        blk, d = mgr.most_recent_below("cc", 100)
        assert (blk, d.name) == (3, "cc")
        blk, d = mgr.most_recent_below("cc2", 100)
        assert (blk, d.name) == (5, "cc2")

    def test_undecodable_definition_skipped(self, mgr):
        mgr.handle_state_updates(2, {
            ("_lifecycle", "namespaces/bad"): _vv(b"\xff not json")})
        assert mgr.entries() == []

    def test_snapshot_roundtrip(self, mgr, tmp_path):
        mgr.handle_state_updates(4, {
            ("_lifecycle", "namespaces/mycc"):
                _vv(_definition("mycc", COLL_A))})
        mgr.handle_state_updates(9, {
            ("_lifecycle", "namespaces/mycc"):
                _vv(_definition("mycc", COLL_B, sequence=2))})
        out = str(tmp_path / "snap")
        import os
        os.makedirs(out)
        assert mgr.export_snapshot(out) is not None

        kv2 = KVStore(str(tmp_path / "fresh.db"))
        mgr2 = ConfigHistoryMgr(DBHandle(kv2, "confighist"))
        assert mgr2.import_from_snapshot(out) == 2
        blk, d = mgr2.most_recent_below("mycc", 6)
        assert blk == 4
        assert d.collection("secrets").block_to_live == 10
        kv2.close()

    def test_empty_history_exports_nothing(self, mgr, tmp_path):
        assert mgr.export_snapshot(str(tmp_path)) is None
        # importing from a dir without the file is a no-op
        assert mgr.import_from_snapshot(str(tmp_path)) == 0


class TestLedgerWiring:
    def test_commit_of_definition_records_history(self, tmp_path):
        led = KVLedger("ch1", str(tmp_path / "ch1"))
        genesis = pu.new_block(0, b"")
        genesis.data.data.append(b"config-placeholder")
        genesis.header.data_hash = pu.block_data_hash(genesis.data)
        led.initialize_from_genesis(genesis)
        try:
            sim = led.new_tx_simulator()
            sim.put_state("_lifecycle", "namespaces/mycc",
                          _definition("mycc", COLL_A))
            env, _ = make_tx_envelope("ch1", sim, cc="_lifecycle")
            led.commit_block(append_block(led, [env]))
            assert led.config_history.entries() == [("mycc", 1)]
            # an invalid (flagged) tx's writes never reach the batch →
            # no history either
            sim2 = led.new_tx_simulator()
            sim2.put_state("_lifecycle", "namespaces/other",
                           _definition("other", COLL_B))
            env2, _ = make_tx_envelope("ch1", sim2, cc="_lifecycle")
            from fabric_tpu.protos import transaction as txpb
            led.commit_block(
                append_block(led, [env2]),
                flags=[txpb.TxValidationCode.ENDORSEMENT_POLICY_FAILURE])
            assert led.config_history.entries() == [("mycc", 1)]
        finally:
            led.close()

    def test_upgrade_dbs_rebuilds_history_for_old_format(self,
                                                         tmp_path):
        """A pre-2.1 ledger holds committed definitions but an empty
        confighist; the format gate forces `peer node upgrade-dbs`,
        which drops the derived DBs so replay rebuilds the history
        (reference: dataformat.CheckVersion + upgrade_dbs.go)."""
        from fabric_tpu.internal import nodeops
        from fabric_tpu.ledger.kvdb import DBHandle as DBH, KVStore
        from fabric_tpu.ledger.kvledger import LedgerError

        led = KVLedger("ch1", str(tmp_path / "ch1"))
        genesis = pu.new_block(0, b"")
        genesis.data.data.append(b"config-placeholder")
        genesis.header.data_hash = pu.block_data_hash(genesis.data)
        led.initialize_from_genesis(genesis)
        sim = led.new_tx_simulator()
        sim.put_state("_lifecycle", "namespaces/mycc",
                      _definition("mycc", COLL_A))
        env, _ = make_tx_envelope("ch1", sim, cc="_lifecycle")
        led.commit_block(append_block(led, [env]))
        led.close()

        # simulate the ledger having been written by a 2.0 binary:
        # restamp the format and wipe the confighist keyspace
        kv = KVStore(str(tmp_path / "ch1" / "index.db"))
        DBH(kv, "ledgermeta").put(b"datafmt", b"2.0")
        nodeops._drop_keyspaces(kv, ("confighist",))
        kv.close()

        with pytest.raises(LedgerError, match="upgrade-dbs"):
            KVLedger("ch1", str(tmp_path / "ch1"))
        assert nodeops.upgrade_dbs(str(tmp_path)) == ["ch1"]
        led2 = KVLedger("ch1", str(tmp_path / "ch1"))
        try:
            assert led2.config_history.entries() == [("mycc", 1)]
        finally:
            led2.close()

    def test_recovery_replay_is_idempotent(self, tmp_path):
        led = KVLedger("ch1", str(tmp_path / "ch1"))
        genesis = pu.new_block(0, b"")
        genesis.data.data.append(b"config-placeholder")
        genesis.header.data_hash = pu.block_data_hash(genesis.data)
        led.initialize_from_genesis(genesis)
        sim = led.new_tx_simulator()
        sim.put_state("_lifecycle", "namespaces/mycc",
                      _definition("mycc", COLL_A))
        env, _ = make_tx_envelope("ch1", sim, cc="_lifecycle")
        led.commit_block(append_block(led, [env]))
        led.close()
        led2 = KVLedger("ch1", str(tmp_path / "ch1"))
        try:
            assert led2.config_history.entries() == [("mycc", 1)]
        finally:
            led2.close()
