"""Whole-program static analyzer tests (ISSUE 20 tentpole).

Three contracts, mirroring test_ftpu_lint.py's shape:

(1) seeded violations of every interprocedural rule are caught —
    an unguarded device dispatch reachable from a public `verify*`
    entry (seam), wall-clock/set-iteration/traced-branch/environ
    reads inside a trace region (retrace), and the round-5 qtab bug
    shape: one attribute written from two thread roots with no
    common lock (lockset);
(2) the waiver grammar and the fingerprint baseline suppress exactly
    what they name, and nothing else;
(3) the tree at HEAD is CLEAN modulo the committed reasoned
    baseline — the property tools/static_check.sh gates on — and
    surgically reverting the qtab-cache lock fix (overrides, no
    checkout) makes the lockset rule fail again.
"""

import importlib.util
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_check():
    spec = importlib.util.spec_from_file_location(
        "_ftpu_check_under_test",
        os.path.join(REPO, "tools", "ftpu_check.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def chk():
    return _load_check()


def _tree(root, files):
    """Materialize a tiny analyzable package: `files` maps paths
    relative to `fabric_tpu/` onto (dedented) source text."""
    pkg = os.path.join(str(root), "fabric_tpu")
    os.makedirs(pkg, exist_ok=True)
    open(os.path.join(pkg, "__init__.py"), "w").close()
    for rel, src in files.items():
        path = os.path.join(pkg, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            f.write(textwrap.dedent(src))
    return str(root)


def _fps(findings):
    return {f.fingerprint for f in findings}


# ---------------------------------------------------------------- seam

_DISPATCH_SRC = """\
    import jax


    class Prov:
        def __init__(self):
            self._fn = jax.jit(lambda x: x + 1)

        def verify_batch(self, items):
            return self._dispatch(items)

        def _dispatch(self, items):
            return self._fn(items)
    """


def test_seam_unguarded_dispatch_found(chk, tmp_path):
    """A jitted callable stored on self, invoked two hops below a
    public verify* entry with no seam anywhere on the path."""
    root = _tree(tmp_path, {"prov.py": _DISPATCH_SRC})
    # register the dispatcher so only the unguarded finding fires
    reg = {"fabric_tpu/prov.py": ("_dispatch",)}
    findings, _ = chk.run_check(root, rules=("seam",), registry=reg)
    assert _fps(findings) == {
        "seam:unguarded:fabric_tpu/prov.py::_dispatch"}
    (f,) = findings
    assert f.rule == "seam" and "self._fn" in f.message


def test_seam_guarded_path_is_clean(chk, tmp_path):
    """The same dispatch behind a fault-point seam at the entry:
    every path is dominated, no finding."""
    guarded = _DISPATCH_SRC.replace(
        "            return self._dispatch(items)",
        "            faults.check(\"pre-dispatch\")\n"
        "            return self._dispatch(items)")
    root = _tree(tmp_path, {"prov.py": "    import faults\n" + guarded})
    reg = {"fabric_tpu/prov.py": ("_dispatch",)}
    findings, _ = chk.run_check(root, rules=("seam",), registry=reg)
    assert findings == []


def test_seam_uncovered_dispatch_vs_registry(chk, tmp_path):
    """An empty registry: the discovered dispatcher is both unguarded
    and uncovered — the 'new path nobody registered' failure mode."""
    root = _tree(tmp_path, {"prov.py": _DISPATCH_SRC})
    findings, _ = chk.run_check(root, rules=("seam",), registry={})
    assert _fps(findings) == {
        "seam:unguarded:fabric_tpu/prov.py::_dispatch",
        "seam:uncovered:fabric_tpu/prov.py::_dispatch"}


def test_seam_stale_registry_entry(chk, tmp_path):
    """A registered function that reaches no dispatch site is drift
    in the other direction."""
    root = _tree(tmp_path, {"prov.py": _DISPATCH_SRC + """\

    def host_only(items):
        return sorted(items)
    """})
    reg = {"fabric_tpu/prov.py": ("_dispatch", "host_only")}
    findings, _ = chk.run_check(root, rules=("seam",), registry=reg)
    assert "seam:stale:fabric_tpu/prov.py::host_only" in _fps(findings)
    assert "seam:stale:fabric_tpu/prov.py::_dispatch" not in \
        _fps(findings)


# ------------------------------------------------------------- retrace

def test_retrace_hazards_in_trace_region(chk, tmp_path):
    """time.time, os.environ.get, set iteration and a Python branch
    on a jnp value — all inside a function handed to jax.jit."""
    root = _tree(tmp_path, {"kern.py": """\
    import os
    import time
    import jax
    import jax.numpy as jnp


    def kernel(x):
        t = time.time()
        mode = os.environ.get("FTPU_MODE")
        for k in set(mode or "ab"):
            t += ord(k)
        if jnp.sum(x):
            return x
        return x + t


    def build():
        return jax.jit(kernel)
    """})
    findings, _ = chk.run_check(root, rules=("retrace",))
    kinds = {fp.split(":")[1] for fp in _fps(findings)}
    assert kinds == {"clock", "environ", "set-iter", "traced-branch"}
    assert all(f.path == "fabric_tpu/kern.py" for f in findings)


def test_retrace_silent_outside_trace_region(chk, tmp_path):
    """The identical hazards in a function nothing jits: no finding
    — the rule is about trace regions, not a style ban."""
    root = _tree(tmp_path, {"host.py": """\
    import os
    import time


    def plumbing(x):
        t = time.time()
        for k in set(os.environ.get("P", "ab")):
            t += ord(k)
        return t
    """})
    findings, _ = chk.run_check(root, rules=("retrace",))
    assert findings == []


def test_retrace_unhashable_static_arg(chk, tmp_path):
    root = _tree(tmp_path, {"st.py": """\
    import jax


    def helper(x, shape):
        return x


    def run(x):
        f = jax.jit(helper, static_argnums=1)
        return f(x, [4, 4])
    """})
    findings, _ = chk.run_check(root, rules=("retrace",))
    fps = _fps(findings)
    assert any(fp.startswith("retrace:unhashable-static:") and
               ":run:f:1" in fp for fp in fps), fps


# ------------------------------------------------------------- lockset

_RACE_SRC = """\
    import threading


    class Cache:
        def __init__(self):
            self._lock = threading.Lock()
            self._entries = {}

        def start(self):
            t = threading.Thread(target=self._loop, daemon=True)
            t.start()

        def _loop(self):
            self._entries["warm"] = 1

        def record(self, k, v):
            self._entries[k] = v
    """


def test_lockset_two_root_race_found(chk, tmp_path):
    """The qtab bug shape: `_entries` written from the restore thread
    AND the public API with no common lock."""
    root = _tree(tmp_path, {"cache.py": _RACE_SRC})
    findings, _ = chk.run_check(root, rules=("lockset",))
    assert _fps(findings) == {
        "lockset:fabric_tpu/cache.py::Cache._entries"}
    (f,) = findings
    assert "no common lock" in f.message


def test_lockset_common_lock_is_clean(chk, tmp_path):
    locked = _RACE_SRC.replace(
        '            self._entries["warm"] = 1',
        '            with self._lock:\n'
        '                self._entries["warm"] = 1').replace(
        "            self._entries[k] = v",
        "            with self._lock:\n"
        "                self._entries[k] = v")
    root = _tree(tmp_path, {"cache.py": locked})
    findings, _ = chk.run_check(root, rules=("lockset",))
    assert findings == []


def test_lockset_interprocedural_must_hold(chk, tmp_path):
    """The lock held at the CALL SITE, not lexically at the write:
    must-hold dataflow carries it down the call path."""
    src = _RACE_SRC.replace(
        '            self._entries["warm"] = 1',
        '            with self._lock:\n'
        '                self._store()\n\n'
        '        def _store(self):\n'
        '            self._entries["warm"] = 1').replace(
        "            self._entries[k] = v",
        "            with self._lock:\n"
        "                self._entries[k] = v")
    root = _tree(tmp_path, {"cache.py": src})
    findings, _ = chk.run_check(root, rules=("lockset",))
    assert findings == []


def test_lockset_class_waiver_covers_all_attrs(chk, tmp_path):
    """An actor-model annotation on the class line silences the rule
    for every attribute of that class."""
    waived = _RACE_SRC.replace(
        "    class Cache:",
        "    # ftpu-check: allow-lockset(fixture actor: single-writer"
        " by construction)\n    class Cache:")
    root = _tree(tmp_path, {"cache.py": waived})
    findings, _ = chk.run_check(root, rules=("lockset",))
    assert findings == []


def test_lockset_item_increment_gauge_policy(chk, tmp_path):
    """`self.stats[k] += n` is exempt by default (the documented
    GIL-gauge policy) and included under strict."""
    src = _RACE_SRC.replace(
        '            self._entries["warm"] = 1',
        '            self._entries["hits"] += 1').replace(
        "            self._entries[k] = v",
        "            self._entries[k] += v")
    root = _tree(tmp_path, {"cache.py": src})
    findings, _ = chk.run_check(root, rules=("lockset",))
    assert findings == []
    strict, _ = chk.run_check(root, rules=("lockset",), strict=True)
    assert _fps(strict) == {
        "lockset:fabric_tpu/cache.py::Cache._entries"}


# ------------------------------------------------------------- waivers

def test_waiver_suppresses_exactly_named_rule(chk, tmp_path):
    root = _tree(tmp_path, {"kern.py": """\
    import time
    import jax


    def kernel(x):
        # ftpu-check: allow-retrace(fixture: trace-time stamp wanted)
        t = time.time()
        return x + t


    def build():
        return jax.jit(kernel)
    """})
    findings, _ = chk.run_check(root, rules=("retrace",))
    assert findings == []


def test_waiver_wrong_rule_does_not_suppress(chk, tmp_path):
    root = _tree(tmp_path, {"kern.py": """\
    import time
    import jax


    def kernel(x):
        # ftpu-check: allow-lockset(wrong rule for this line)
        t = time.time()
        return x + t


    def build():
        return jax.jit(kernel)
    """})
    findings, _ = chk.run_check(root, rules=("retrace",))
    assert any(f.rule == "retrace" for f in findings)


def test_waiver_malformed_is_itself_a_finding(chk, tmp_path):
    root = _tree(tmp_path, {"m.py": """\
    # ftpu-check: allow-bogus(no such rule)
    # ftpu-check: allow-retrace()
    X = 1
    """})
    findings, _ = chk.run_check(root, rules=())
    msgs = [f.message for f in findings if f.rule == "waiver"]
    assert len(msgs) == 2
    assert any("unknown waiver" in m for m in msgs)
    assert any("without a reason" in m for m in msgs)


# ------------------------------------------------------------ baseline

def test_baseline_round_trip_preserves_reasons(chk, tmp_path):
    root = _tree(tmp_path, {"cache.py": _RACE_SRC})
    findings, _ = chk.run_check(root, rules=("lockset",))
    assert findings
    fp = findings[0].fingerprint
    bl = os.path.join(str(tmp_path), "baseline.json")

    chk.write_baseline(bl, findings, {})
    entries, err = chk.load_baseline(bl)
    assert err is None and set(entries) == {fp}
    assert entries[fp].startswith("TODO")

    # regeneration keeps the reviewed reason
    chk.write_baseline(bl, findings, {fp: "reviewed: fixture race"})
    entries, err = chk.load_baseline(bl)
    assert err is None
    assert entries[fp] == "reviewed: fixture race"

    # a reason-less entry is a setup error, not silently accepted
    with open(bl, "w", encoding="utf-8") as f:
        json.dump({"entries": [{"id": fp, "reason": ""}]}, f)
    entries, err = chk.load_baseline(bl)
    assert entries is None and "reason" in err


def test_missing_baseline_is_empty_not_error(chk, tmp_path):
    entries, err = chk.load_baseline(
        os.path.join(str(tmp_path), "nope.json"))
    assert entries == {} and err is None


# ----------------------------------------------- the real tree at HEAD

def test_hot_path_registry_loads_from_ftpu_lint(chk):
    reg, err = chk.load_hot_path_registry(REPO)
    assert err is None
    assert isinstance(reg, dict) and reg
    assert "fabric_tpu/bccsp/tpu.py" in reg


def test_clean_tree_gate(chk):
    """The committed tree passes the exact invocation
    tools/static_check.sh runs: zero new findings, zero stale
    baseline entries, whole tree analyzed."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ftpu_check.py"),
         "--root", REPO, "--json"],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["findings"] == []
    assert out["stale_baseline"] == []
    assert out["functions_analyzed"] > 2000
    assert len(out["baselined"]) >= 1


def test_reverting_qtab_lock_fix_fails_gate(chk):
    """Surgically strip the q16 cache locking from the live tree
    (overrides — no checkout) and the lockset rule must light up
    again on the qtab-cache attributes, over and above the
    committed baseline."""
    rel = "fabric_tpu/bccsp/tpu.py"
    with open(os.path.join(REPO, rel), encoding="utf-8") as f:
        src = f.read()
    assert "with self._q16_lock:" in src
    reverted = src.replace("with self._q16_lock:",
                           "if True:  # unlocked")
    findings, _ = chk.run_check(REPO, rules=("lockset",),
                                overrides={rel: reverted})
    baseline, err = chk.load_baseline(
        os.path.join(REPO, "tools", "ftpu_check_baseline.json"))
    assert err is None
    new = {f.fingerprint for f in findings} - set(baseline)
    assert ("lockset:fabric_tpu/bccsp/tpu.py::"
            "TPUProvider._qflat_cache") in new, sorted(new)
    assert any("::TPUProvider._q16_" in fp for fp in new)
