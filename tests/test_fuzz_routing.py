"""Seeded fuzz of the fast/reference VALIDATION ROUTING boundary.

Round-4 verdict #6: the mutation sweep covered tampering, but nothing
fuzzed blocks where some txs route native and some route the Python
reference path IN THE SAME BLOCK with key-level policies and custom
plugins active. This corpus generates exactly those blocks: every tx
gets a random recipe (clean, adversarial encodings, >MAX_E
endorsements, duplicates, garbage), some trials pin key-level
VALIDATION_PARAMETER metadata, some switch the chaincode to a custom
validation plugin — and the fast path's verdict bitmap must be
byte-identical to `FTPU_FAST_VALIDATE=0` and to the sw validator.

Seeded (override with FTPU_FUZZ_SEED); failures print the trial's
seed + per-tx recipe list. Previously-interesting recipes replay from
tests/fuzz_routing_corpus.json on every run.
Reference semantics: `core/committer/txvalidator/v20/validator.go:297`.
"""

import copy
import json
import os
import random

import pytest

from fabric_tpu.core.chaincode import ChaincodeDefinition, shim
from fabric_tpu.protos import common as cpb, transaction as txpb2
from fabric_tpu.protoutil import protoutil as pu

# reuse the fastvalidate net fixture (two orgs, one ledger, gateway)
from tests.test_fastvalidate import (  # noqa: F401
    CHANNEL, KV, _diff, _validators, net,
)

SEED = int(os.environ.get("FTPU_FUZZ_SEED", "20260801"))
CORPUS = os.path.join(os.path.dirname(__file__),
                      "fuzz_routing_corpus.json")

RECIPES = ("clean", "unknown_field", "flip", "truncate", "insert",
           "dup_prev", "many_endorsements", "empty", "garbage",
           "nonminimal_len")


def _many_endorsements(raw: bytes, signer, n_extra: int = 8) -> bytes:
    """Exceed fastvalidate.MAX_E by duplicating an existing
    endorsement, then RE-SIGNING as creator (the creator signature
    covers the payload, endorsements included — a real >MAX_E tx is
    creator-signed over all of them). Still a well-formed tx the
    reference path validates; the flat native tables cannot hold it
    (routes BP_NEEDS_PYTHON)."""
    env = pu.unmarshal_envelope(raw)
    pay = pu.get_payload(env)
    tx = txpb2.Transaction()
    tx.ParseFromString(pay.data)
    cap = txpb2.ChaincodeActionPayload()
    cap.ParseFromString(tx.actions[0].payload)
    if not cap.action.endorsements:
        return raw
    base = cap.action.endorsements[0]
    for _ in range(n_extra):
        cap.action.endorsements.append(base)
    tx.actions[0].payload = cap.SerializeToString()
    pay.data = tx.SerializeToString()
    payload_bytes = pu.marshal(pay)
    return pu.marshal(cpb.Envelope(
        payload=payload_bytes, signature=signer.sign(payload_bytes)))


def _nonminimal_len(raw: bytes) -> bytes:
    """Re-encode the outer Envelope.payload length as a 2-byte varint
    even when 1 byte suffices — legal protobuf the strict native
    parser refuses (clean-scan contract) and Python accepts."""
    env = pu.unmarshal_envelope(raw)
    payload = env.payload
    if len(payload) >= 128 or not payload:
        return raw
    out = (b"\x0a" + bytes([0x80 | (len(payload) & 0x7F), 0x01])
           if False else
           b"\x0a" + bytes([(len(payload) & 0x7F) | 0x80, 0x00]))
    # non-minimal: continuation bit set, high byte zero
    out += payload
    if env.signature:
        sig = env.signature
        out += b"\x12" + bytes([len(sig)]) + sig
    return out


def _apply(rng: random.Random, envs: list, i: int, recipe: str,
           signer) -> bytes:
    raw = envs[i]
    if recipe == "clean":
        return raw
    if recipe == "unknown_field":
        return raw + b"\x38\x01"
    if recipe == "flip":
        b = bytearray(raw)
        if b:
            b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
        return bytes(b)
    if recipe == "truncate":
        return raw[: rng.randrange(len(raw))] if raw else raw
    if recipe == "insert":
        b = bytearray(raw)
        b.insert(rng.randrange(len(b) + 1), rng.randrange(256))
        return bytes(b)
    if recipe == "dup_prev":
        return envs[rng.randrange(i)] if i else raw
    if recipe == "many_endorsements":
        return _many_endorsements(raw, signer)
    if recipe == "empty":
        return b""
    if recipe == "garbage":
        return rng.randbytes(rng.randrange(4, 120))
    if recipe == "nonminimal_len":
        return _nonminimal_len(raw)
    raise AssertionError(recipe)


def _pin_key_policy(peers, key: str, expr: str) -> None:
    from fabric_tpu.common.policies import policydsl
    from fabric_tpu.ledger import statedb as sdb
    from fabric_tpu.ledger.txmgr import serialize_metadata
    vp = policydsl.from_string(expr)
    md = serialize_metadata(
        {shim.VALIDATION_PARAMETER: vp.SerializeToString()})
    batch = sdb.UpdateBatch()
    batch.put("fastcc", key, b"seed", sdb.Height(0, 0), md)
    peers["org1"].channel(CHANNEL).ledger.state_db.apply_writes_only(
        batch)


def _run_trial(net_fix, base_block, trial_seed: int,
               recipes=None, keypolicy=False, plugin=False) -> list:
    peers, gw, _ = net_fix
    ref_v, fast_v = _validators(net_fix)
    rng = random.Random(trial_seed)
    block = copy.deepcopy(base_block)
    block.header.number = 1000 + trial_seed % 100000
    envs = list(block.data.data)
    if recipes is None:
        recipes = [rng.choice(RECIPES) for _ in envs]
    assert len(recipes) == len(envs)
    for i, r in enumerate(recipes):
        envs[i] = _apply(rng, envs, i, r, gw._signer)
    del block.data.data[:]
    block.data.data.extend(envs)

    ch = peers["org1"].channel(CHANNEL)
    if keypolicy:
        # pin a key this base block writes: valid for both-org
        # endorsements, but escalates those txs off the plain shortcut
        _pin_key_policy(peers, "kfz_1", "AND('Org2MSP.member')")
    if plugin:
        from fabric_tpu.core import handlers

        def delegate(validator, bundle, cc_name, endorsement_sd,
                     write_info):
            return validator.builtin_vscc_prepare(
                bundle, cc_name, endorsement_sd, write_info)

        handlers.validation_plugins.register("fuzzplugin", delegate)
        ch.define_chaincode(ChaincodeDefinition(
            name="fastcc", validation_plugin="fuzzplugin"))
    try:
        try:
            return _diff(ref_v, fast_v, block)
        except AssertionError as e:
            raise AssertionError(
                f"routing divergence: seed={trial_seed} "
                f"recipes={recipes} keypolicy={keypolicy} "
                f"plugin={plugin}: {e}") from e
    finally:
        if plugin:
            ch.define_chaincode(ChaincodeDefinition(name="fastcc"))
        if keypolicy:
            _pin_key_policy(peers, "kfz_1", "OR('Org1MSP.member',"
                                            "'Org2MSP.member')")


@pytest.fixture(scope="module")
def base_block(net):                             # noqa: F811
    _, gw, _ = net
    peers_fix = net[0]
    envs = [gw.endorse(CHANNEL, "fastcc",
                       [b"put", f"kfz_{i}".encode(), f"v{i}".encode()],
                       endorsing_peers=list(peers_fix.values()))[0]
            for i in range(12)]
    block = pu.new_block(999, b"\x00" * 32)
    for env in envs:
        block.data.data.append(pu.marshal(env))
    block.header.data_hash = pu.block_data_hash(block.data)
    while len(block.metadata.metadata) <= \
            cpb.BlockMetadataIndex.TRANSACTIONS_FILTER:
        block.metadata.metadata.append(b"")
    return block


def test_mixed_recipe_blocks_match(net, base_block):  # noqa: F811
    rng = random.Random(SEED)
    for trial in range(10):
        seed = rng.randrange(1 << 30)
        _run_trial(net, base_block, seed,
                   keypolicy=(trial % 3 == 1),
                   plugin=(trial % 3 == 2))


def test_boundary_spanning_block(net, base_block):   # noqa: F811
    """One block deliberately holding every routing class at once,
    with key-level policy active: clean native txs, Python-routed
    encodings, >MAX_E, duplicates, and garbage."""
    recipes = ["clean", "many_endorsements", "unknown_field",
               "dup_prev", "garbage", "clean", "nonminimal_len",
               "truncate", "clean", "empty", "flip", "clean"]
    codes = _run_trial(net, base_block, 7, recipes=recipes,
                       keypolicy=True)
    from fabric_tpu.protos import transaction as txpb
    TVC = txpb.TxValidationCode
    assert codes[0] == TVC.VALID
    assert codes[1] == TVC.VALID          # >MAX_E still validates
    assert codes[2] == TVC.VALID          # unknown field is legal
    assert codes[3] == TVC.DUPLICATE_TXID


def test_corpus_replays(net, base_block):            # noqa: F811
    with open(CORPUS) as f:
        corpus = json.load(f)
    for entry in corpus:
        _run_trial(net, base_block, entry["seed"],
                   recipes=entry.get("recipes"),
                   keypolicy=entry.get("keypolicy", False),
                   plugin=entry.get("plugin", False))
