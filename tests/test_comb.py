"""Fixed-base comb kernel tests (fabric_tpu/ops/comb.py).

Ground truth: the Python-int projective reference in ops/p256.py, itself
pinned against OpenSSL in test_p256.py.
"""

import hashlib
import os
import random

import numpy as np

import pytest

import jax
import jax.numpy as jnp

from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.utils import (
    decode_dss_signature,
)

from fabric_tpu.ops import comb, limb, p256

rng = random.Random(4242)


def _point(k: int):
    priv = ec.derive_private_key(k, ec.SECP256R1())
    nums = priv.public_key().public_numbers()
    return (nums.x, nums.y)


class TestGTables:
    def test_entries_match_int_reference(self):
        t = comb.g_tables()
        assert t.shape == (comb.NWIN * comb.NENT, 3, limb.L)
        for i, j in [(0, 0), (0, 1), (0, 255), (7, 3), (31, 17)]:
            got = tuple(limb.limbs_to_int(t[i * comb.NENT + j, c])
                        for c in range(3))
            k = (j << (comb.WBITS * i)) % p256.N
            want = p256.scalar_mul_int(k, (p256.GX, p256.GY, 1))
            assert (p256.to_affine_int(got) == p256.to_affine_int(want)), \
                (i, j)


class TestQTables:
    def test_entries_match_int_reference(self):
        ks = [5, 424242]
        pts = [_point(k) for k in ks]
        qx = jnp.asarray(limb.ints_to_limbs([p[0] for p in pts]))
        qy = jnp.asarray(limb.ints_to_limbs([p[1] for p in pts]))
        flat = np.asarray(jax.jit(comb.build_q_tables)(qx, qy))
        K = len(ks)
        assert flat.shape == (comb.NWIN * K * comb.NENT, 3, limb.L)
        for i, k_idx, j in [(0, 0, 0), (0, 1, 1), (3, 0, 2),
                            (31, 1, 255), (16, 0, 128)]:
            row = (i * K + k_idx) * comb.NENT + j
            got = tuple(
                limb.limbs_to_int(
                    np.asarray(p256.FP.canonical(jnp.asarray(flat[row, c]))))
                for c in range(3))
            scalar = j << (comb.WBITS * i)
            want = p256.scalar_mul_int(
                scalar, (pts[k_idx][0], pts[k_idx][1], 1))
            assert (p256.to_affine_int(got) == p256.to_affine_int(want)), \
                (i, k_idx, j)


class TestCombDoubleScalarMul:
    def test_matches_generic_ladder(self):
        B, K = 6, 2
        key_pts = [_point(rng.randrange(1, p256.N)) for _ in range(K)]
        u1s = [rng.randrange(0, p256.N) for _ in range(B)]
        u2s = [rng.randrange(0, p256.N) for _ in range(B)]
        u1s[3] = 0                      # zero scalar: all-infinity windows
        u2s[4] = 0
        key_idx = [i % K for i in range(B)]

        u1 = jnp.asarray(limb.ints_to_limbs(u1s))
        u2 = jnp.asarray(limb.ints_to_limbs(u2s))
        qx = jnp.asarray(limb.ints_to_limbs([p[0] for p in key_pts]))
        qy = jnp.asarray(limb.ints_to_limbs([p[1] for p in key_pts]))

        def run(u1, u2, idx, qx, qy):
            g = jnp.asarray(comb.g_tables())
            q = comb.build_q_tables(qx, qy)
            return comb.comb_double_scalar_mul(u1, u2, idx, g, q, K)

        X, Y, Z = jax.jit(run)(
            u1, u2, jnp.asarray(key_idx, dtype=jnp.int32), qx, qy)
        for i in range(B):
            want = p256.cadd_int(
                p256.scalar_mul_int(u1s[i], (p256.GX, p256.GY, 1)),
                p256.scalar_mul_int(
                    u2s[i],
                    (key_pts[key_idx[i]][0], key_pts[key_idx[i]][1], 1)),
            )
            got = tuple(
                limb.limbs_to_int(np.asarray(p256.FP.canonical(v[i])))
                for v in (X, Y, Z))
            assert (p256.to_affine_int(got) ==
                    p256.to_affine_int(want)), f"lane {i}"


@pytest.mark.skipif(
    os.environ.get("FTPU_SLOW") != "1",
    reason="heavy differential; set FTPU_SLOW=1 (10+ min compile)")
class TestG16Windows:
    def test_g16_matches_generic_ladder(self):
        """16-bit G-side windows (48-point tree) agree with the int
        reference on R = u1*G + u2*Q."""
        B, K = 4, 2
        key_pts = [_point(rng.randrange(1, p256.N)) for _ in range(K)]
        u1s = [rng.randrange(0, p256.N) for _ in range(B)]
        u2s = [rng.randrange(0, p256.N) for _ in range(B)]
        u1s[1] = 0
        key_idx = [i % K for i in range(B)]
        u1 = jnp.asarray(limb.ints_to_limbs(u1s))
        u2 = jnp.asarray(limb.ints_to_limbs(u2s))
        qx = jnp.asarray(limb.ints_to_limbs([p[0] for p in key_pts]))
        qy = jnp.asarray(limb.ints_to_limbs([p[1] for p in key_pts]))
        g16 = comb.g16_tables()

        def run(u1, u2, idx, qx, qy, g16):
            q = comb.build_q_tables(qx, qy)
            return comb.comb_double_scalar_mul(
                u1, u2, idx, None, q, K, g16=g16)

        X, Y, Z = jax.jit(run)(
            u1, u2, jnp.asarray(key_idx, dtype=jnp.int32), qx, qy, g16)
        for i in range(B):
            want = p256.cadd_int(
                p256.scalar_mul_int(u1s[i], (p256.GX, p256.GY, 1)),
                p256.scalar_mul_int(
                    u2s[i],
                    (key_pts[key_idx[i]][0], key_pts[key_idx[i]][1], 1)))
            got = tuple(
                limb.limbs_to_int(np.asarray(p256.FP.canonical(v[i])))
                for v in (X, Y, Z))
            assert (p256.to_affine_int(got) ==
                    p256.to_affine_int(want)), f"lane {i}"


@pytest.mark.skipif(
    os.environ.get("FTPU_SLOW") != "1",
    reason="heavy differential; set FTPU_SLOW=1 (20+ min compile)")
class TestQ16Windows:
    def test_q16_matches_int_reference(self):
        """16-bit windows on BOTH sides (32-point tree)."""
        B, K = 3, 2
        key_pts = [_point(rng.randrange(1, p256.N)) for _ in range(K)]
        u1s = [rng.randrange(0, p256.N) for _ in range(B)]
        u2s = [rng.randrange(0, p256.N) for _ in range(B)]
        key_idx = [i % K for i in range(B)]
        u1 = jnp.asarray(limb.ints_to_limbs(u1s))
        u2 = jnp.asarray(limb.ints_to_limbs(u2s))
        qx = jnp.asarray(limb.ints_to_limbs([p[0] for p in key_pts]))
        qy = jnp.asarray(limb.ints_to_limbs([p[1] for p in key_pts]))
        g16 = comb.g16_tables()
        q8 = jax.jit(comb.build_q_tables)(qx, qy)
        q16 = jax.jit(comb.build_q16_tables,
                      static_argnums=1)(q8, K)

        def run(u1, u2, idx, q16, g16):
            return comb.comb_double_scalar_mul(
                u1, u2, idx, None, q16, K, g16=g16, q16=True)

        X, Y, Z = jax.jit(run)(
            u1, u2, jnp.asarray(key_idx, dtype=jnp.int32), q16, g16)
        for i in range(B):
            want = p256.cadd_int(
                p256.scalar_mul_int(u1s[i], (p256.GX, p256.GY, 1)),
                p256.scalar_mul_int(
                    u2s[i],
                    (key_pts[key_idx[i]][0], key_pts[key_idx[i]][1], 1)))
            got = tuple(
                limb.limbs_to_int(np.asarray(p256.FP.canonical(v[i])))
                for v in (X, Y, Z))
            assert (p256.to_affine_int(got) ==
                    p256.to_affine_int(want)), f"lane {i}"


class TestCombVerifyCore:
    def test_valid_and_tampered(self):
        B, K = 8, 3
        privs = [ec.generate_private_key(ec.SECP256R1()) for _ in range(K)]
        key_pts = [p.public_key().public_numbers() for p in privs]
        msgs, sigs, key_idx = [], [], []
        for i in range(B):
            k = i % K
            msg = f"comb tx {i}".encode() * (i + 1)
            der = privs[k].sign(msg, ec.ECDSA(hashes.SHA256()))
            msgs.append(msg)
            sigs.append(decode_dss_signature(der))
            key_idx.append(k)
        # tamper: lane 5 message, lane 6 sig, lane 7 wrong key
        msgs[5] = msgs[5] + b"!"
        sigs[6] = (sigs[6][0], (sigs[6][1] * 3) % p256.N or 1)
        key_idx[7] = (key_idx[7] + 1) % K
        premask = np.ones((B,), dtype=bool)
        premask[4] = False              # host-side gate rejection

        words = np.zeros((B, 8), dtype=np.uint32)
        for i, m in enumerate(msgs):
            words[i] = np.frombuffer(hashlib.sha256(m).digest(), dtype=">u4")
        rs = [s[0] for s in sigs]
        ws = [pow(s[1], -1, p256.N) for s in sigs]
        rpn = [r + p256.N if r + p256.N < p256.P else r for r in rs]
        out = jax.jit(comb.comb_verify_core)(
            jnp.asarray(words),
            jnp.asarray(key_idx, dtype=jnp.int32),
            jnp.asarray(limb.ints_to_limbs([p.x for p in key_pts])),
            jnp.asarray(limb.ints_to_limbs([p.y for p in key_pts])),
            jnp.asarray(limb.ints_to_limbs(rs)),
            jnp.asarray(limb.ints_to_limbs(rpn)),
            jnp.asarray(limb.ints_to_limbs(ws)),
            jnp.asarray(premask),
        )
        assert np.asarray(out).tolist() == [
            True, True, True, True, False, False, False, False]


@pytest.mark.skipif(
    os.environ.get("FTPU_SLOW") != "1",
    reason="heavy differential; set FTPU_SLOW=1 (20+ min compile)")
class TestProvider16BitPath:
    def test_provider_g16_q16_matches_sw_and_caches(self):
        """TPUProvider(use_g16=True): the 32-point-tree product path
        agrees with the sw oracle and reuses the cached per-key-set
        Q tables on a second batch."""
        from fabric_tpu.bccsp import bccsp as api
        from fabric_tpu.bccsp.sw import SWProvider
        from fabric_tpu.bccsp.tpu import TPUProvider

        sw = SWProvider()
        tpu = TPUProvider(min_batch=1, use_g16=True)
        privs = [ec.generate_private_key(ec.SECP256R1())
                 for _ in range(2)]
        keys = [tpu.key_import(p.public_key(),
                               api.ECDSAPublicKeyImportOpts())
                for p in privs]

        def batch(tag):
            items = []
            for i in range(12):
                msg = f"{tag} {i}".encode() * 2
                sig = privs[i % 2].sign(msg, ec.ECDSA(hashes.SHA256()))
                if i % 4 == 3:
                    msg += b"!"
                items.append(api.VerifyItem(key=keys[i % 2],
                                            signature=sig, message=msg))
            return items

        b1 = batch("one")
        assert tpu.verify_batch(b1) == sw.verify_batch(b1)
        assert len(tpu._qflat_cache) == 1
        b2 = batch("two")        # same keys: cached tables reused
        assert tpu.verify_batch(b2) == sw.verify_batch(b2)
        assert len(tpu._qflat_cache) == 1
        assert tpu.stats["comb_batches"] == 2
