"""Round-10 batched ordering pipeline (ISSUE 7).

The claims under test, over `bench_pipeline.make_order_service`'s
wheel-free stub seam (REAL RaftChain/RaftNode/WAL, BlockWriteStage,
BlockWriter, blockcutter, StandardChannel batched sig-filter and
AdmissionWindow; stubbed x509/MSP/channel-config):

  * the pipelined cut→consensus→deliver path produces a block stream
    BIT-IDENTICAL to the sequential path — numbers, prev-hash linkage,
    data hashes and envelope bytes — on a mixed stream with a config
    block and a reconfiguration;
  * a crash between propose(N+1) and write(N) replays identically
    from the raft WAL at the next start;
  * armed `order.propose` / `raft.step` fault points (and a failing
    write stage) demote to the sequential path without losing a
    single envelope.

Chains are driven synchronously (start=False: tick/elect, feed
`_process_order_window`, `_drain_ready`) so window composition — and
therefore the block stream — is deterministic across twins; the
cluster test runs the real threaded loops.
"""

from __future__ import annotations

import threading
import time

import pytest

import bench_pipeline as bp
from fabric_tpu.common import faults
from fabric_tpu.orderer.raft.core import LEADER
from fabric_tpu.protos import common as cpb
from fabric_tpu.protoutil import protoutil as pu


def _elect(chain, max_ticks: int = 400):
    for _ in range(max_ticks):
        chain.node.tick()
        chain._drain_ready()
        if chain.node.state == LEADER:
            return
    raise AssertionError("single-node chain never elected itself")


def _feed(svc, window) -> None:
    """One admission window, synchronously: process + apply."""
    svc.chain._process_order_window(list(window))
    svc.chain._drain_ready()


def _settle(svc, timeout: float = 30.0) -> None:
    """Barrier: every committed block durably written."""
    svc.chain._drain_ready()
    stage = svc.chain._write_stage
    if stage is not None:
        assert stage.drain(timeout=timeout)


def _stream(svc) -> list:
    lg = svc.support.ledger
    return [lg.get_block(n) for n in range(lg.height)]


def _assert_same_stream(a, b) -> None:
    """Bit-identity on everything consensus replicates: header number,
    prev-hash linkage, data hash, envelope bytes. (Metadata holds each
    orderer's OWN signature — distinct keys by construction — and is
    deliberately outside the comparison, as in the reference's
    VerifyBlocks.)"""
    assert len(a) == len(b), (len(a), len(b))
    for x, y in zip(a, b):
        assert x.header.number == y.header.number
        assert x.header.previous_hash == y.header.previous_hash
        assert x.header.data_hash == y.header.data_hash
        assert list(x.data.data) == list(y.data.data), \
            f"block {x.header.number} data diverged"


def _assert_linked(stream) -> None:
    for i, blk in enumerate(stream):
        assert blk is not None, f"missing block {i}"
        assert blk.header.number == i
        assert blk.header.data_hash == pu.block_data_hash(blk.data)
        if i:
            assert blk.header.previous_hash == \
                pu.block_header_hash(stream[i - 1].header)


def _env_bytes(stream, skip_config: bool = True) -> list:
    out = []
    for blk in stream[1:]:
        if skip_config and pu.is_config_block(blk):
            continue
        out.extend(bytes(d) for d in blk.data.data)
    return out


def _config_env(channel: str, tag: bytes = b"") -> cpb.Envelope:
    """A deterministic CONFIG-class envelope (no nonce, no signature,
    zeroed timestamp): the stub support applies config blocks by
    bumping its sequence + firing on_config, so the payload only needs
    the right channel header. Reused across twins so the resulting
    config blocks are bit-identical."""
    ch = pu.make_channel_header(cpb.HeaderType.CONFIG, channel)
    ch.timestamp = 0
    sh = pu.create_signature_header(b"order-bench-orderer", b"")
    return cpb.Envelope(payload=pu.marshal(
        pu.make_payload(ch, sh, b"cfg" + tag)))


def _twin_services(tmp_path, client, **kw):
    seq = bp.make_order_service(str(tmp_path / "seq"), client=client,
                                write_pipeline=False, start=False, **kw)
    piped = bp.make_order_service(str(tmp_path / "piped"),
                                  client=client, write_pipeline=True,
                                  start=False, **kw)
    _elect(seq.chain)
    _elect(piped.chain)
    return seq, piped


class TestBitIdenticalStreams:
    def test_mixed_stream_with_config_and_reconfiguration(self,
                                                          tmp_path):
        """Normal runs, a config block mid-window, a reconfiguration
        (consenter cert rotation via on_config), stale-sequence
        envelopes revalidating through the batched msgprocessor pass,
        and a timer-style tail cut — sequential and pipelined streams
        must match bit for bit."""
        client = bp.make_order_client()
        rotations = []

        def on_config(support, block):
            # the reconfiguration seam: rotate every consenter's
            # client TLS cert in place (endpoint set unchanged) — the
            # chain's _reconfigure must refresh channel auth without a
            # membership change
            support.orderer_config.consensus_metadata = \
                support.orderer_config.consensus_metadata_fn(
                    b"-rot%d" % support.sequence())
            rotations.append(block.header.number)

        seq, piped = _twin_services(tmp_path, client, block_txs=4,
                                    on_config=on_config)
        try:
            envs = [client.envelope(i) for i in range(26)]
            cfg1 = _config_env(client.channel, b"1")
            cfg2 = _config_env(client.channel, b"2")
            windows = [
                # plain batched run: 6 envelopes -> 1 cut + 2 pending
                [(envs[i], 0, False) for i in range(6)],
                # config mid-window: flushes pending, own block,
                # normal traffic resumes after it
                ([(envs[i], 0, False) for i in range(6, 10)]
                 + [(cfg1, 0, True)]
                 + [(envs[i], 0, False) for i in range(10, 14)]),
                # STALE sequence (config above bumped it to 1): the
                # whole run revalidates in one batched pass
                [(envs[i], 0, False) for i in range(14, 18)],
                # the reconfiguration config block, fresh sequence
                [(cfg2, 1, True)],
                [(envs[i], 1, False) for i in range(18, 26)],
            ]
            for svc in (seq, piped):
                for w in windows:
                    _feed(svc, w)
                # timer-path tail flush (batch_timeout fire analog)
                svc.chain._cut_and_propose(svc.support.cutter.cut())
                _settle(svc)

            s_seq, s_piped = _stream(seq), _stream(piped)
            _assert_linked(s_seq)
            _assert_same_stream(s_seq, s_piped)
            # every envelope ordered exactly once, order preserved
            assert _env_bytes(s_seq) == [pu.marshal(e) for e in envs]
            # both twins saw the config blocks...
            n_cfg = sum(1 for b in s_seq[1:] if pu.is_config_block(b))
            assert n_cfg == 2
            assert len(rotations) == 4  # 2 config blocks x 2 twins
            # ...and the pipelined twin actually pipelined
            assert piped.chain._write_stage is not None
            assert piped.chain._write_stage.stats["written"] > 0
            assert seq.chain._write_stage is None
            if not faults.fires("order.propose"):
                # under ambient chaos (tools/chaos_check.sh order) a
                # counted fault spends its firings on whichever twin
                # runs first — the streams above still match; only
                # this bookkeeping symmetry needs the quiet path
                assert piped.chain.order_stats["demotions"] == \
                    seq.chain.order_stats["demotions"]
        finally:
            seq.close()
            piped.close()

    def test_stale_rejects_match_per_envelope_path(self, tmp_path):
        """A corrupted-signature envelope in a stale run is dropped by
        the batched revalidation exactly like the per-envelope path:
        the rest of the window still orders."""
        client = bp.make_order_client()
        seq, piped = _twin_services(tmp_path, client, block_txs=4)
        try:
            good = [client.envelope(i) for i in range(4)]
            bad = client.envelope(99)
            bad.signature = bytes(len(bad.signature))
            # bump the sequence so the run is stale -> revalidates
            for svc in (seq, piped):
                svc.support._sequence = 1
                _feed(svc, [(e, 0, False)
                            for e in (good[:2] + [bad] + good[2:])])
                svc.chain._cut_and_propose(svc.support.cutter.cut())
                _settle(svc)
            s_seq, s_piped = _stream(seq), _stream(piped)
            _assert_same_stream(s_seq, s_piped)
            assert _env_bytes(s_seq) == [pu.marshal(e) for e in good]
        finally:
            seq.close()
            piped.close()


class TestCrashReplay:
    def test_crash_between_propose_and_write_replays_identically(
            self, tmp_path):
        """Blocks N,N+1 commit in raft while the write stage is wedged
        mid-span (crash-frozen writer): the ledger never sees them.
        A fresh chain over the same root replays them from the WAL —
        the healed stream is bit-identical to the sequential twin's."""
        client = bp.make_order_client()
        seq, piped = _twin_services(tmp_path, client, block_txs=2)
        crashed = False
        try:
            envs = [client.envelope(i) for i in range(8)]
            w_a = [(e, 0, False) for e in envs[:4]]
            w_b = [(e, 0, False) for e in envs[4:]]
            for svc in (seq, piped):
                _feed(svc, w_a)
                _settle(svc)
            assert piped.support.ledger.height == 3  # genesis + 2

            # wedge the writer: spans block forever before touching
            # the store (the gate is never released — crash-frozen)
            gate = threading.Event()

            def frozen(*a, **kw):
                gate.wait()

            piped.support.write_blocks = frozen
            piped.support.write_block = frozen
            _feed(piped, w_b)      # blocks 3,4 commit, never written
            _feed(seq, w_b)
            _settle(seq)
            time.sleep(0.1)        # let the worker wedge
            assert piped.support.ledger.height == 3
            assert seq.support.ledger.height == 5

            piped.close(flush=False)           # the crash
            crashed = True
            healed = bp.make_order_service(str(tmp_path / "piped"),
                                           client=client,
                                           write_pipeline=True,
                                           start=False, block_txs=2)
            try:
                # __init__'s _replay_committed healed the gap before
                # the write stage even existed
                assert healed.support.ledger.height == 5
                _assert_same_stream(_stream(seq), _stream(healed))
            finally:
                healed.close()
        finally:
            seq.close()
            if not crashed:
                piped.close(flush=False)


class TestFaultDemotion:
    def test_order_propose_fault_demotes_without_loss(self, tmp_path):
        """An armed `order.propose` fault fails the batched propose
        span BEFORE any state mutates: the window demotes to
        sequential per-block proposes and every envelope still
        orders — the stream matches the unfaulted sequential twin."""
        client = bp.make_order_client()
        seq, piped = _twin_services(tmp_path, client, block_txs=2)
        try:
            envs = [client.envelope(i) for i in range(6)]
            w = [(e, 0, False) for e in envs]
            _feed(seq, w)
            _settle(seq)

            faults.arm("order.propose", mode="error", count=1)
            _feed(piped, w)
            _settle(piped)
            assert faults.fires("order.propose") >= 1
            assert piped.chain.order_stats["demotions"] >= 1
            _assert_same_stream(_stream(seq), _stream(piped))
        finally:
            faults.reset()
            seq.close()
            piped.close()

    def test_write_stage_failure_demotes_and_heals(self, tmp_path):
        """A failing span write makes the stage's error sticky; the
        next submit demotes the chain to sequential writes and heals
        the gap from the raft log — nothing lost, linkage intact."""
        client = bp.make_order_client()
        svc = bp.make_order_service(str(tmp_path / "o"), client=client,
                                    write_pipeline=True, start=False,
                                    block_txs=2)
        try:
            _elect(svc.chain)
            envs = [client.envelope(i) for i in range(12)]
            _feed(svc, [(e, 0, False) for e in envs[:4]])
            _settle(svc)

            real_write = svc.support.write_block
            real_writes = svc.support.write_blocks
            boom = RuntimeError("injected span-write failure")

            def failing(*a, **kw):
                raise boom

            svc.support.write_block = failing
            svc.support.write_blocks = failing
            stage = svc.chain._write_stage
            _feed(svc, [(e, 0, False) for e in envs[4:8]])
            deadline = time.monotonic() + 10
            while stage._error is None:
                assert time.monotonic() < deadline, \
                    "write stage never recorded the failure"
                time.sleep(0.01)
            # restore the writer BEFORE the demotion replays
            svc.support.write_block = real_write
            svc.support.write_blocks = real_writes

            _feed(svc, [(e, 0, False) for e in envs[8:]])
            _settle(svc)
            assert svc.chain._write_stage is None      # demoted
            assert svc.chain.order_stats["demotions"] >= 1
            stream = _stream(svc)
            _assert_linked(stream)
            assert sorted(_env_bytes(stream)) == \
                sorted(pu.marshal(e) for e in envs)
        finally:
            svc.close()


    def test_config_barrier_demotion_writes_config_once(self,
                                                        tmp_path):
        """A config block committing while the write stage holds a
        sticky error demotes AT the config barrier: the demotion
        replay writes the backlog and the config block itself (its
        entry is committed), so the outer frame must not append it a
        second time — and blocks cut after the config message in the
        same window must still apply (a double-write would abort the
        event drain and drop them)."""
        client = bp.make_order_client()
        svc = bp.make_order_service(str(tmp_path / "o"), client=client,
                                    write_pipeline=True, start=False,
                                    block_txs=2)
        try:
            _elect(svc.chain)
            envs = [client.envelope(i) for i in range(12)]
            _feed(svc, [(e, 0, False) for e in envs[:4]])
            _settle(svc)

            real_write = svc.support.write_block
            real_writes = svc.support.write_blocks

            def failing(*a, **kw):
                raise RuntimeError("injected span-write failure")

            svc.support.write_block = failing
            svc.support.write_blocks = failing
            stage = svc.chain._write_stage
            _feed(svc, [(e, 0, False) for e in envs[4:8]])
            deadline = time.monotonic() + 10
            while stage._error is None:
                assert time.monotonic() < deadline, \
                    "write stage never recorded the failure"
                time.sleep(0.01)
            svc.support.write_block = real_write
            svc.support.write_blocks = real_writes

            # config + trailing normal traffic in ONE window: the
            # barrier demotes, the replay writes the config block,
            # and the trailing blocks still order afterwards
            window = [(_config_env(client.channel), 0, True)] + \
                [(e, 0, False) for e in envs[8:]]
            _feed(svc, window)
            _settle(svc)
            assert svc.chain._write_stage is None      # demoted
            assert svc.chain.order_stats["demotions"] >= 1
            stream = _stream(svc)
            _assert_linked(stream)
            assert sum(1 for b in stream[1:]
                       if pu.is_config_block(b)) == 1
            assert sorted(_env_bytes(stream)) == \
                sorted(pu.marshal(e) for e in envs)
        finally:
            svc.close()


class TestClusterChaos:
    def _wait(self, cond, timeout: float = 30.0, step: float = 0.02,
              kick=None):
        """Poll for `cond`, driving the protocol clock through
        `kick` between polls (RaftChain.force_tick — the raft core's
        tick seam) instead of trusting the 20ms wall-clock tick
        threads to keep pace: on a loaded box those threads starve
        and wall-sleep margins flake (the PR-12 note this deflakes).
        The timeout stays as a genuine-stall backstop only."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return True
            if kick is not None:
                kick()
            time.sleep(step)
        return cond()

    def test_raft_step_fault_tolerated_across_cluster(self, tmp_path):
        """A 2-consenter service with `raft.step` armed: dropped step
        messages are retransmitted by raft itself — broadcast ingest
        completes, both nodes converge on bit-identical streams.
        Election and retransmission progress is DRIVEN via the tick
        seam (force_tick), so convergence speed tracks this loop's
        cadence, not the box's scheduler."""
        from fabric_tpu.orderer.cluster import LocalClusterNetwork

        client = bp.make_order_client()
        net = LocalClusterNetwork()
        eps = ("orderer0.example.com:7050",
               "orderer1.example.com:7050")
        svcs = [bp.make_order_service(
            str(tmp_path / f"o{i}"), client=client, endpoint=eps[i],
            endpoints=eps, net=net, write_pipeline=True, start=True,
            block_txs=4, tick_interval_s=0.02) for i in range(2)]

        def kick():
            for s in svcs:
                s.chain.force_tick()

        try:
            assert self._wait(lambda: any(
                s.chain.node.state == LEADER for s in svcs),
                kick=kick), "no leader elected"
            leader = next(s for s in svcs
                          if s.chain.node.state == LEADER)
            faults.arm("raft.step", mode="error", count=3)

            envs = [client.envelope(i) for i in range(16)]
            pos = 0
            deadline = time.monotonic() + 30
            while pos < len(envs):
                resps = leader.broadcast.process_messages(envs[pos:])
                pos += sum(1 for r in resps
                           if r.status == cpb.Status.SUCCESS)
                assert time.monotonic() < deadline, "broadcast stalled"
                if pos < len(envs):
                    # heal the armed drops NOW: ticks drive raft's
                    # retransmission on this loop's cadence
                    kick()
                    time.sleep(0.02)

            want = [pu.marshal(e) for e in envs]
            assert self._wait(lambda: all(
                sorted(_env_bytes(_stream(s))) == sorted(want)
                for s in svcs), kick=kick), \
                [s.support.ledger.height for s in svcs]
            streams = [_stream(s) for s in svcs]
            _assert_linked(streams[0])
            _assert_same_stream(streams[0], streams[1])
        finally:
            faults.reset()
            for s in svcs:
                s.close()


class TestAdmissionWindow:
    def _items(self, n: int):
        import hashlib

        from fabric_tpu.bccsp import ECDSAKeyGenOpts, VerifyItem
        from fabric_tpu.bccsp.sw import SWProvider

        sw = SWProvider()
        key = sw.key_gen(ECDSAKeyGenOpts(ephemeral=True))
        pub = key.public_key()
        out = []
        for i in range(n):
            msg = b"win%d" % i
            sig = sw.sign(key, hashlib.sha256(msg).digest())
            out.append(VerifyItem(key=pub, signature=sig, message=msg))
        return sw, out

    def test_concurrent_callers_coalesce_one_dispatch(self):
        """Callers arriving while a dispatch is in flight ride the
        next one together: correct per-caller verdicts, fewer provider
        dispatches than callers."""
        from fabric_tpu.bccsp.admission import AdmissionWindow

        sw, items = self._items(8)

        class _Slow:
            def verify_batch(self, batch):
                time.sleep(0.05)
                return sw.verify_batch(batch)

        win = AdmissionWindow(_Slow())
        results: dict[int, list] = {}

        def caller(i):
            results[i] = win.verify_batch([items[i]])

        threads = [threading.Thread(target=caller, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert all(results[i] == [True] for i in range(8))
        assert win.stats["window_callers"] == 8
        assert win.stats["window_items"] == 8
        assert win.stats["window_dispatches"] < 8, win.stats

    def test_provider_error_reaches_every_waiter(self):
        from fabric_tpu.bccsp.admission import AdmissionWindow

        class _Broken:
            def verify_batch(self, batch):
                raise RuntimeError("device gone")

        win = AdmissionWindow(_Broken())
        with pytest.raises(RuntimeError, match="device gone"):
            win.verify_batch([object()])
        assert win.verify_batch([]) == []

    def test_shared_window_is_per_provider(self):
        from fabric_tpu.bccsp.admission import AdmissionWindow
        from fabric_tpu.bccsp.sw import SWProvider

        sw = SWProvider()
        w1 = AdmissionWindow.shared(sw)
        assert AdmissionWindow.shared(sw) is w1
        assert AdmissionWindow.shared(w1) is w1   # idempotent wrap
        assert AdmissionWindow.shared(SWProvider()) is not w1
