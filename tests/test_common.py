"""Tests for fabric_tpu.common: flogging, metrics, viperutil."""

import logging
import os

import pytest

from fabric_tpu.common import flogging, metrics, viperutil


class TestFlogging:
    def test_get_logger_and_default_level(self):
        lg = flogging.must_get_logger("unittest.sub")
        assert lg.level == logging.INFO

    def test_activate_spec_prefix_matching(self):
        a = flogging.must_get_logger("specmod")
        b = flogging.must_get_logger("specmod.child")
        c = flogging.must_get_logger("other")
        flogging.activate_spec("warn:specmod=debug")
        try:
            assert a.level == logging.DEBUG
            assert b.level == logging.DEBUG  # child inherits by prefix
            assert c.level == logging.WARNING  # default applies
        finally:
            flogging.activate_spec("info")

    def test_longest_prefix_wins(self):
        a = flogging.must_get_logger("pfx.x")
        b = flogging.must_get_logger("pfx.x.y")
        flogging.activate_spec("info:pfx=error:pfx.x.y=debug")
        try:
            assert a.level == logging.ERROR
            assert b.level == logging.DEBUG
        finally:
            flogging.activate_spec("info")

    def test_invalid_spec_raises(self):
        with pytest.raises(ValueError):
            flogging.activate_spec("bogus-level")

    def test_spec_roundtrip(self):
        flogging.activate_spec("info:aaa=debug")
        try:
            assert "aaa=debug" in flogging.spec()
        finally:
            flogging.activate_spec("info")


class TestMetrics:
    def test_counter_with_labels(self):
        p = metrics.PrometheusProvider()
        c = p.new_counter(metrics.CounterOpts(
            namespace="ledger", name="tx_count", label_names=("channel", "status")))
        c.with_labels("channel", "ch1", "status", "valid").add(3)
        c.with_labels("channel", "ch1", "status", "invalid").add()
        text = p.render()
        assert 'ledger_tx_count{channel="ch1",status="valid"} 3' in text
        assert 'ledger_tx_count{channel="ch1",status="invalid"} 1' in text

    def test_histogram_buckets(self):
        p = metrics.PrometheusProvider()
        h = p.new_histogram(metrics.HistogramOpts(
            name="commit_time", buckets=(0.1, 1.0)))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        text = p.render()
        assert 'commit_time_bucket{le="0.1"} 1' in text
        assert 'commit_time_bucket{le="1"} 2' in text
        assert 'commit_time_bucket{le="+Inf"} 3' in text
        assert "commit_time_count 3" in text

    def test_reregistration_returns_same_instrument(self):
        p = metrics.PrometheusProvider()
        a = p.new_gauge(metrics.GaugeOpts(name="g"))
        b = p.new_gauge(metrics.GaugeOpts(name="g"))
        assert a is b

    def test_disabled_provider_noops(self):
        p = metrics.DisabledProvider()
        c = p.new_counter(metrics.CounterOpts(name="x"))
        c.add(5)  # must not raise


class TestViperutil:
    def test_yaml_load_and_dotted_get(self, tmp_path):
        cfg_file = tmp_path / "core.yaml"
        cfg_file.write_text(
            "peer:\n  id: peer0\n  gossip:\n    bootstrap: 127.0.0.1:7051\n"
            "  validatorPoolSize: 4\n")
        cfg = viperutil.Config.load(str(cfg_file), env_prefix="CORE")
        assert cfg.get("peer.id") == "peer0"
        assert cfg.get("PEER.Gossip.Bootstrap") == "127.0.0.1:7051"
        assert cfg.get_int("peer.validatorPoolSize") == 4
        assert cfg.get("peer.missing", "dflt") == "dflt"

    def test_env_override(self, tmp_path, monkeypatch):
        cfg_file = tmp_path / "core.yaml"
        cfg_file.write_text("peer:\n  id: peer0\n")
        monkeypatch.setenv("CORE_PEER_ID", "peer9")
        cfg = viperutil.Config.load(str(cfg_file), env_prefix="CORE")
        assert cfg.get("peer.id") == "peer9"

    def test_durations(self):
        assert viperutil.parse_duration("5s") == 5.0
        assert viperutil.parse_duration("250ms") == 0.25
        assert viperutil.parse_duration("1m30s") == 90.0
        with pytest.raises(ValueError):
            viperutil.parse_duration("xyz")

    def test_path_resolution(self, tmp_path):
        cfg_file = tmp_path / "core.yaml"
        cfg_file.write_text("msp: msp/dir\nabs: /tmp/x\n")
        cfg = viperutil.Config.load(str(cfg_file))
        assert cfg.get_path("msp") == str(tmp_path / "msp" / "dir")
        assert cfg.get_path("abs") == "/tmp/x"

    def test_sub_config(self, tmp_path):
        cfg_file = tmp_path / "core.yaml"
        cfg_file.write_text("bccsp:\n  default: SW\n  sw:\n    hash: SHA2\n")
        cfg = viperutil.Config.load(str(cfg_file))
        sub = cfg.sub("bccsp")
        assert sub.get("default") == "SW"
        assert sub.get("sw.hash") == "SHA2"


class TestDeliverHaltedChain:
    def test_tip_stream_ends_when_chain_halts(self):
        """A deliver stream parked at the chain tip must terminate
        with SERVICE_UNAVAILABLE when the chain halts instead of
        blocking its thread forever."""
        import threading
        import time as _time
        from fabric_tpu.common.deliver import DeliverHandler
        from fabric_tpu.protos import common as cpb, orderer as opb

        class _Ledger:
            height = 1

            def get_block(self, n):
                blk = cpb.Block()
                blk.header.number = n
                return blk

            def wait_for_block(self, n, timeout=None):
                _time.sleep(min(timeout or 0.1, 0.1))
                return False

        class _Chain:
            def __init__(self):
                self.halted = False

            def errored(self):
                return self.halted

        class _Support:
            def __init__(self):
                self.ledger = _Ledger()
                self.chain = _Chain()

            def bundle(self):
                class _B:
                    class policy_manager:
                        @staticmethod
                        def get_policy(path):
                            class _P:
                                @staticmethod
                                def evaluate_signed_data(sd):
                                    return None
                            return _P()
                return _B()

        support = _Support()
        handler = DeliverHandler(lambda cid: support)
        from fabric_tpu.protoutil import protoutil as pu
        seek = opb.SeekInfo()
        seek.start.specified.number = 0
        seek.stop.specified.number = 100
        seek.behavior = opb.SeekInfo.BLOCK_UNTIL_READY
        ch = pu.make_channel_header(
            cpb.HeaderType.DELIVER_SEEK_INFO, "ch")
        payload = pu.make_payload(ch, cpb.SignatureHeader(),
                                  seek.SerializeToString())
        env = cpb.Envelope(payload=payload.SerializeToString())

        results = []

        def run():
            results.extend(handler.handle(env))

        t = threading.Thread(target=run, daemon=True)
        t.start()
        _time.sleep(1.0)       # stream reaches the tip and parks
        support.chain.halted = True
        t.join(timeout=5)
        assert not t.is_alive(), "deliver stream leaked its thread"
        assert results[0].WhichOneof("type") == "block"
        assert results[-1].status == cpb.Status.SERVICE_UNAVAILABLE
