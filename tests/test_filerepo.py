"""Crash-tolerant join-block file repo.

Round-4 verdict #9: participation join-blocks lacked the reference's
write-tmp-fsync-rename crash discipline
(`orderer/common/filerepo/filerepo.go`). These tests pin the repo
semantics (atomic save, tmp sweep, idempotent remove) and the
registrar's crash-resume contract: a join that died after the artifact
save but before the ledger append is completed at the next startup.
The process-kill variant lives in test_integration_nwo.py
(FTPU_CRASH_AFTER_JOIN_SAVE injection).
"""

import os

import pytest

from fabric_tpu.bccsp.sw import SWProvider
from fabric_tpu.internal import cryptogen
from fabric_tpu.internal.configtxgen import genesis_block, new_channel_group
from fabric_tpu.msp import msp_config_from_dir
from fabric_tpu.msp.mspimpl import X509MSP
from fabric_tpu.orderer import solo
from fabric_tpu.orderer.filerepo import FileRepo, FileRepoError
from fabric_tpu.orderer.multichannel import Registrar
from fabric_tpu.protoutil import protoutil as pu

CHANNEL = "joinkill"


class TestFileRepo:
    def test_save_read_list_remove(self, tmp_path):
        repo = FileRepo(str(tmp_path), "join")
        repo.save("ch1", b"alpha")
        repo.save("ch2", b"beta")
        assert repo.read("ch1") == b"alpha"
        assert repo.list() == ["ch1", "ch2"]
        repo.save("ch1", b"alpha2")          # atomic replace
        assert repo.read("ch1") == b"alpha2"
        repo.remove("ch1")
        repo.remove("ch1")                   # idempotent
        assert repo.read("ch1") is None
        assert repo.list() == ["ch2"]

    def test_tmp_leftovers_swept_at_startup(self, tmp_path):
        repo = FileRepo(str(tmp_path), "join")
        repo.save("ok", b"good")
        # simulate a crash mid-save: a torn tmp file on disk
        torn = os.path.join(str(tmp_path), "join", "dead.join~tmp")
        with open(torn, "wb") as f:
            f.write(b"half-writ")
        repo2 = FileRepo(str(tmp_path), "join")
        assert not os.path.exists(torn)
        assert repo2.list() == ["ok"]
        assert repo2.read("ok") == b"good"

    def test_bad_names_rejected(self, tmp_path):
        repo = FileRepo(str(tmp_path), "join")
        for bad in ("", "../x", "a/b", "a~tmp\x00"):
            with pytest.raises(FileRepoError):
                repo.save(bad, b"x")
        with pytest.raises(FileRepoError):
            FileRepo(str(tmp_path), "a.b")


@pytest.fixture(scope="module")
def genesis_env(tmp_path_factory):
    root = tmp_path_factory.mktemp("joinrepo")
    cdir = str(root / "crypto")
    ordo = cryptogen.generate_org(cdir, "example.com", orderer_org=True)
    csp = SWProvider()
    profile = {
        "Consortium": "SampleConsortium",
        "Capabilities": {"V2_0": True},
        "Orderer": {
            "OrdererType": "solo",
            "Addresses": ["orderer0:7050"],
            "BatchTimeout": "200ms",
            "BatchSize": {"MaxMessageCount": 16},
            "Organizations": [
                {"Name": "OrdererOrg", "ID": "OrdererMSP",
                 "MSPDir": os.path.join(ordo, "msp"),
                 "OrdererEndpoints": ["orderer0:7050"]}],
            "Capabilities": {"V2_0": True},
        },
    }
    genesis = genesis_block(CHANNEL, new_channel_group(profile))
    msp = X509MSP(csp)
    msp.setup(msp_config_from_dir(
        os.path.join(ordo, "orderers", "orderer0.example.com", "msp"),
        "OrdererMSP", csp=csp))
    return root, msp.get_default_signing_identity(), csp, genesis


def _registrar(root, signer, csp, sub):
    return Registrar(os.path.join(str(root), sub), signer, csp,
                     {"solo": solo.consenter})


class TestJoinCrashResume:
    def test_join_resumed_from_pending_artifact(self, genesis_env):
        """Crash between the artifact save and the ledger append: the
        next startup completes the join."""
        root, signer, csp, genesis = genesis_env
        reg = _registrar(root, signer, csp, "o1")
        reg.halt()
        # simulate the crash window: artifact durable, no channel dir
        repo = FileRepo(os.path.join(str(root), "o1", "pendingops"),
                        "join")
        repo.save(CHANNEL, pu.marshal(genesis))
        reg2 = _registrar(root, signer, csp, "o1")
        try:
            support = reg2.get_chain(CHANNEL)
            assert support is not None, "interrupted join not resumed"
            assert support.ledger.height == 1
            # the artifact is consumed once the ledger holds the block
            assert repo.list() == []
        finally:
            reg2.halt()
        # a THIRD start restores the channel from its ledger dir and
        # does not double-join
        reg3 = _registrar(root, signer, csp, "o1")
        try:
            assert reg3.get_chain(CHANNEL).ledger.height == 1
        finally:
            reg3.halt()

    def test_completed_join_leaves_no_artifact(self, genesis_env):
        root, signer, csp, genesis = genesis_env
        reg = _registrar(root, signer, csp, "o2")
        try:
            reg.join(genesis)
            repo = FileRepo(
                os.path.join(str(root), "o2", "pendingops"), "join")
            assert repo.list() == []
        finally:
            reg.halt()

    def test_crash_injection_hook_fires_after_save(self, genesis_env,
                                                   monkeypatch):
        """The nwo kill-during-join test's injection point must die
        AFTER the artifact save (that ordering is the contract the
        resume path depends on)."""
        root, signer, csp, genesis = genesis_env
        reg = _registrar(root, signer, csp, "o3")
        monkeypatch.setenv("FTPU_CRASH_AFTER_JOIN_SAVE", "1")
        died = []
        monkeypatch.setattr(os, "_exit",
                            lambda code: died.append(code) or
                            (_ for _ in ()).throw(SystemExit(code)))
        with pytest.raises(SystemExit):
            reg.join(genesis)
        reg.halt()
        assert died == [41]
        repo = FileRepo(os.path.join(str(root), "o3", "pendingops"),
                        "join")
        assert repo.list() == [CHANNEL]
        assert not os.path.isdir(os.path.join(str(root), "o3",
                                              CHANNEL))
        # restart (without the injection) completes the join
        monkeypatch.delenv("FTPU_CRASH_AFTER_JOIN_SAVE")
        reg2 = _registrar(root, signer, csp, "o3")
        try:
            assert reg2.get_chain(CHANNEL) is not None
            assert reg2.get_chain(CHANNEL).ledger.height == 1
        finally:
            reg2.halt()
