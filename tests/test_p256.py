"""P-256 verification core tests.

Ground truth comes from two independent oracles: the `cryptography`
package (OpenSSL) for scalar-mul/sign/verify, and a textbook affine
implementation for edge cases the library won't produce.
"""

import hashlib
import random

import numpy as np

import jax
import jax.numpy as jnp

from cryptography.hazmat.primitives.asymmetric import ec

from fabric_tpu.ops import limb, p256

rng = random.Random(99)


def openssl_point(k: int):
    """k*G via OpenSSL — independent oracle."""
    priv = ec.derive_private_key(k, ec.SECP256R1())
    nums = priv.public_key().public_numbers()
    return (nums.x, nums.y)


def rand_proj(pt, z=None):
    """Rescale an affine int point to random-Z projective coordinates."""
    x, y = pt
    z = z or rng.randrange(1, p256.P)
    return (x * z % p256.P, y * z % p256.P, z)


class TestIntReference:
    def test_matches_openssl_scalar_mul(self):
        for _ in range(4):
            k = rng.randrange(1, p256.N)
            got = p256.to_affine_int(p256.scalar_mul_int(k, (p256.GX, p256.GY, 1)))
            assert got == openssl_point(k)

    def test_complete_edge_cases(self):
        G = (p256.GX, p256.GY, 1)
        inf = (0, 1, 0)
        # P + inf = P
        assert p256.to_affine_int(p256.cadd_int(G, inf)) == (p256.GX, p256.GY)
        # inf + inf = inf
        assert p256.to_affine_int(p256.cadd_int(inf, inf)) is None
        # P + (-P) = inf
        negG = (p256.GX, p256.P - p256.GY, 1)
        assert p256.to_affine_int(p256.cadd_int(G, negG)) is None
        # doubling through the same formula: G + G == 2G
        two_g = p256.to_affine_int(p256.cadd_int(G, G))
        assert two_g == openssl_point(2)

    def test_order_times_g_is_infinity(self):
        assert p256.to_affine_int(p256.scalar_mul_int(p256.N, (p256.GX, p256.GY, 1))) is None


class TestLimbCadd:
    def test_matches_int_reference(self):
        pts = []
        for _ in range(4):
            k1, k2 = rng.randrange(1, p256.N), rng.randrange(1, p256.N)
            p1 = rand_proj(openssl_point(k1))
            p2 = rand_proj(openssl_point(k2))
            pts.append((p1, p2))
        # include doubling and inf cases in the same batch
        G = (p256.GX, p256.GY, 1)
        pts.append((rand_proj(openssl_point(5)), rand_proj(openssl_point(5))))
        pts.append(((0, 1, 0), G))

        def stack(coord_idx, side):
            return jnp.asarray(
                limb.ints_to_limbs([pair[side][coord_idx] for pair in pts])
            )

        p1 = tuple(stack(c, 0) for c in range(3))
        p2 = tuple(stack(c, 1) for c in range(3))
        X, Y, Z = jax.jit(p256.cadd)(p1, p2)
        for i, (a, b) in enumerate(pts):
            want = p256.cadd_int(a, b)
            got = tuple(
                limb.limbs_to_int(np.asarray(p256.FP.canonical(v[i])))
                for v in (X, Y, Z)
            )
            assert p256.to_affine_int(got) == p256.to_affine_int(want), f"pair {i}"


class TestDoubleScalarMul:
    def test_matches_int_reference(self):
        B = 4
        u1s = [rng.randrange(0, p256.N) for _ in range(B)]
        u2s = [rng.randrange(1, p256.N) for _ in range(B)]
        qs = [openssl_point(rng.randrange(1, p256.N)) for _ in range(B)]
        u1 = jnp.asarray(limb.ints_to_limbs(u1s))
        u2 = jnp.asarray(limb.ints_to_limbs(u2s))
        qx = jnp.asarray(limb.ints_to_limbs([q[0] for q in qs]))
        qy = jnp.asarray(limb.ints_to_limbs([q[1] for q in qs]))
        X, Y, Z = jax.jit(p256.double_scalar_mul)(u1, u2, qx, qy)
        for i in range(B):
            want = p256.cadd_int(
                p256.scalar_mul_int(u1s[i], (p256.GX, p256.GY, 1)),
                p256.scalar_mul_int(u2s[i], (qs[i][0], qs[i][1], 1)),
            )
            got = tuple(
                limb.limbs_to_int(np.asarray(p256.FP.canonical(v[i])))
                for v in (X, Y, Z)
            )
            assert p256.to_affine_int(got) == p256.to_affine_int(want), f"lane {i}"


class TestVerifyCore:
    def _run(self, msgs, keys, sigs, tamper=None):
        """Build kernel inputs from (msg, key, (r, s)) triples."""
        B = len(msgs)
        digests = [hashlib.sha256(m).digest() for m in msgs]
        words = np.zeros((B, 8), dtype=np.uint32)
        for i, d in enumerate(digests):
            words[i] = np.frombuffer(d, dtype=">u4")
        qx = limb.ints_to_limbs([k[0] for k in keys])
        qy = limb.ints_to_limbs([k[1] for k in keys])
        rs = [s[0] for s in sigs]
        ws = [pow(s[1], -1, p256.N) for s in sigs]
        rpn = [r + p256.N if r + p256.N < p256.P else r for r in rs]
        out = jax.jit(p256.verify_core)(
            jnp.asarray(words),
            jnp.asarray(qx),
            jnp.asarray(qy),
            jnp.asarray(limb.ints_to_limbs(rs)),
            jnp.asarray(limb.ints_to_limbs(rpn)),
            jnp.asarray(limb.ints_to_limbs(ws)),
            jnp.ones((B,), dtype=bool),
        )
        return np.asarray(out)

    def test_valid_and_tampered_signatures(self):
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric.utils import (
            decode_dss_signature,
        )

        B = 6
        msgs, keys, sigs = [], [], []
        for i in range(B):
            priv = ec.generate_private_key(ec.SECP256R1())
            msg = f"fabric tx payload {i}".encode() * (i + 1)
            der = priv.sign(msg, ec.ECDSA(hashes.SHA256()))
            r, s = decode_dss_signature(der)
            nums = priv.public_key().public_numbers()
            msgs.append(msg)
            keys.append((nums.x, nums.y))
            sigs.append((r, s))
        # lanes 0..2 valid; tamper lane 3 msg, lane 4 sig, lane 5 wrong key
        msgs[3] = msgs[3] + b"!"
        sigs[4] = (sigs[4][0], (sigs[4][1] * 7) % p256.N or 1)
        keys[5] = openssl_point(424242)
        got = self._run(msgs, keys, sigs)
        assert got.tolist() == [True, True, True, False, False, False]
