"""Chaincode runtime depth: history queries from the shim,
chaincode-to-chaincode invocation (same- and cross-channel), execute
timeouts.

Reference behaviors pinned: `core/chaincode/handler.go:1081`
(HandleInvokeChaincode: same-channel shares the tx rwset, cross-channel
is queries-only), HandleGetHistoryForKey (history DB reachable from the
shim), `core/chaincode/chaincode_support.go:160` (ExecuteTimeout fails
the proposal).
"""

import os
import time

import pytest

from fabric_tpu.bccsp.sw import SWProvider
from fabric_tpu.common.deliver import DeliverHandler
from fabric_tpu.core.chaincode import Chaincode, ChaincodeDefinition, shim
from fabric_tpu.core.chaincode.support import ChaincodeSupport
from fabric_tpu.core.policycheck import org_member_policy_bytes
from fabric_tpu.internal import cryptogen
from fabric_tpu.internal.configtxgen import genesis_block, new_channel_group
from fabric_tpu.msp import msp_config_from_dir
from fabric_tpu.msp.mspimpl import X509MSP
from fabric_tpu.orderer import solo
from fabric_tpu.orderer.broadcast import BroadcastHandler
from fabric_tpu.orderer.multichannel import Registrar
from fabric_tpu.peer import Peer
from fabric_tpu.peer.deliverclient import Deliverer
from fabric_tpu.peer.gateway import Gateway
from fabric_tpu.protos import proposal as ppb, transaction as txpb

CH1, CH2 = "depthone", "depthtwo"


class AssetCC(Chaincode):
    def init(self, stub):
        return shim.success()

    def invoke(self, stub):
        fn, params = stub.get_function_and_parameters()
        if fn == "put":
            stub.put_state(params[0], params[1].encode())
            return shim.success()
        if fn == "del":
            stub.del_state(params[0])
            return shim.success()
        if fn == "history":
            out = []
            for e in stub.get_history_for_key(params[0]):
                val = "DEL" if e["is_delete"] else e["value"].decode()
                out.append(val)
            return shim.success(",".join(out).encode())
        if fn == "audit":        # cc2cc same channel: read via audit cc
            return stub.invoke_chaincode(
                "audit", [b"check", params[0].encode()
                          if isinstance(params[0], str) else params[0]])
        if fn == "xread":        # cc2cc cross channel (queries only)
            return stub.invoke_chaincode(
                "asset", [b"get", params[1].encode()], channel=params[0])
        if fn == "get":
            v = stub.get_state(params[0])
            return shim.success(v or b"")
        return shim.error("unknown")


class AuditCC(Chaincode):
    """Reads the caller's namespace via cc2cc and writes its own mark."""

    def init(self, stub):
        return shim.success()

    def invoke(self, stub):
        fn, params = stub.get_function_and_parameters()
        if fn == "check":
            r = stub.invoke_chaincode("asset", [b"get",
                                               params[0].encode()])
            stub.put_state("last-audit", params[0].encode())
            return shim.success(b"audited:" + r.payload)
        return shim.error("unknown")


def _mknet(root, channel, orgdirs=None):
    cdir = str(root / "crypto")
    if orgdirs is None:
        org1 = cryptogen.generate_org(cdir, "org1.example.com",
                                      n_peers=1, n_users=1)
        ordo = cryptogen.generate_org(cdir, "example.com",
                                      orderer_org=True)
    else:
        org1, ordo = orgdirs
    profile = {
        "Consortium": "SampleConsortium",
        "Capabilities": {"V2_0": True},
        "Application": {
            "Organizations": [{"Name": "Org1", "ID": "Org1MSP",
                               "MSPDir": os.path.join(org1, "msp")}],
            "Capabilities": {"V2_0": True},
        },
        "Orderer": {
            "OrdererType": "solo",
            "Addresses": ["orderer0.example.com:7050"],
            "BatchTimeout": "100ms",
            "BatchSize": {"MaxMessageCount": 10},
            "Organizations": [
                {"Name": "OrdererOrg", "ID": "OrdererMSP",
                 "MSPDir": os.path.join(ordo, "msp"),
                 "OrdererEndpoints": ["orderer0.example.com:7050"]}],
            "Capabilities": {"V2_0": True},
        },
    }
    return genesis_block(channel, new_channel_group(profile)), org1, ordo


@pytest.fixture(scope="module")
def net(tmp_path_factory):
    root = tmp_path_factory.mktemp("depth")
    genesis1, org1, ordo = _mknet(root, CH1)
    genesis2, _, _ = _mknet(root, CH2, (org1, ordo))
    csp = SWProvider()

    def local_msp(d, mspid):
        m = X509MSP(csp)
        m.setup(msp_config_from_dir(d, mspid, csp=csp))
        return m

    omsp = local_msp(os.path.join(ordo, "orderers",
                                  "orderer0.example.com", "msp"),
                     "OrdererMSP")
    reg = Registrar(str(root / "ord"),
                    omsp.get_default_signing_identity(), csp,
                    {"solo": solo.consenter})
    reg.join(genesis1)
    reg.join(genesis2)
    broadcast = BroadcastHandler(reg)
    deliver = DeliverHandler(reg.get_chain)

    pmsp = local_msp(os.path.join(org1, "peers",
                                  "peer0.org1.example.com", "msp"),
                     "Org1MSP")
    peer = Peer(str(root / "peer"), pmsp, csp)
    definition = ChaincodeDefinition(
        name="asset",
        endorsement_policy=org_member_policy_bytes("Org1MSP"))
    audit_def = ChaincodeDefinition(
        name="audit",
        endorsement_policy=org_member_policy_bytes("Org1MSP"))
    deliverers = []
    for genesis in (genesis1, genesis2):
        ch = peer.join_channel(genesis)
        ch.define_chaincode(definition)
        ch.define_chaincode(audit_def)
        d = Deliverer(ch, peer.signer, lambda: deliver, peer.mcs)
        d.start()
        deliverers.append(d)
    peer.chaincode_support.register("asset", AssetCC())
    peer.chaincode_support.register("audit", AuditCC())

    user = local_msp(os.path.join(org1, "users",
                                  "User1@org1.example.com", "msp"),
                     "Org1MSP")
    gw = Gateway(peer, broadcast, user.get_default_signing_identity())
    yield {"peer": peer, "gw": gw}
    for d in deliverers:
        d.stop()
    reg.halt()
    peer.close()


class TestHistory:
    def test_shim_history_newest_first(self, net):
        gw = net["gw"]
        for v in (b"1", b"2"):
            r = gw.submit_transaction(CH1, "asset", [b"put", b"h", v])
            assert r.status == txpb.TxValidationCode.VALID
        r = gw.submit_transaction(CH1, "asset", [b"del", b"h"])
        assert r.status == txpb.TxValidationCode.VALID
        resp = gw.evaluate(CH1, "asset", [b"history", b"h"])
        assert resp.status == 200
        assert resp.payload == b"DEL,2,1"


class TestCC2CC:
    def test_same_channel_shares_rwset(self, net):
        gw = net["gw"]
        r = gw.submit_transaction(CH1, "asset", [b"put", b"x", b"42"])
        assert r.status == txpb.TxValidationCode.VALID
        r = gw.submit_transaction(CH1, "asset", [b"audit", b"x"])
        assert r.status == txpb.TxValidationCode.VALID
        # the callee's write landed in the same tx's rwset
        resp = gw.evaluate(CH1, "audit", [b"check", b"x"])
        assert resp.payload.startswith(b"audited:")
        ch = net["peer"].channel(CH1)
        assert ch.ledger.get_state("audit", "last-audit") == b"x"

    def test_cross_channel_read_only(self, net):
        gw = net["gw"]
        r = gw.submit_transaction(CH2, "asset", [b"put", b"ck", b"99"])
        assert r.status == txpb.TxValidationCode.VALID
        resp = gw.evaluate(CH1, "asset",
                           [b"xread", CH2.encode(), b"ck"])
        assert resp.status == 200
        assert resp.payload == b"99"


class TestExecuteTimeout:
    def test_slow_chaincode_fails_the_proposal(self):
        class Sleeper(Chaincode):
            def init(self, stub):
                return shim.success()

            def invoke(self, stub):
                time.sleep(2.0)
                return shim.success()

        support = ChaincodeSupport(execute_timeout_s=0.2)
        support.register("slow", Sleeper())
        spec = ppb.ChaincodeInvocationSpec()
        spec.chaincode_spec.chaincode_id.name = "slow"
        t0 = time.perf_counter()
        resp, _ev, _id = support.execute("ch", "tx1", spec, None)
        assert resp.status == shim.ERROR
        assert b"timed out" in resp.message.encode()
        assert time.perf_counter() - t0 < 1.5

    def test_abandoned_worker_cannot_mutate_simulator(self):
        """After a timeout the stub is fenced: the late-finishing
        worker's writes to the SHARED simulator must not land
        (round-2 advisor: the endorser owns that simulator)."""
        import threading

        class Writes:
            def __init__(self):
                self.puts = []

            def put_state(self, ns, key, value):
                self.puts.append((ns, key, value))

        wrote_late = threading.Event()

        class LateWriter(Chaincode):
            def init(self, stub):
                return shim.success()

            def invoke(self, stub):
                time.sleep(0.5)
                try:
                    stub.put_state("k", b"poison")
                finally:
                    wrote_late.set()
                return shim.success()

        sim = Writes()
        support = ChaincodeSupport(execute_timeout_s=0.1)
        support.register("late", LateWriter())
        spec = ppb.ChaincodeInvocationSpec()
        spec.chaincode_spec.chaincode_id.name = "late"
        resp, _ev, _id = support.execute("ch", "tx2", spec, sim)
        assert resp.status == shim.ERROR
        assert wrote_late.wait(3.0)
        assert sim.puts == []           # fence held: no late write

    def test_timeout_fences_cc2cc_child_and_suppresses_event(self):
        """The fence is shared down the cc2cc tree: a worker abandoned
        INSIDE a same-channel child invocation must not write through
        the child stub, and the abandoned run's event must not escape
        with the error response."""
        import threading

        class Writes:
            def __init__(self):
                self.puts = []

            def put_state(self, ns, k, v):
                self.puts.append((ns, k, v))

        child_done = threading.Event()

        class Child(Chaincode):
            def init(self, stub):
                return shim.success()

            def invoke(self, stub):
                time.sleep(0.5)         # outlive the parent's timeout
                try:
                    stub.put_state("k", b"poison-via-child")
                finally:
                    child_done.set()
                return shim.success()

        class Parent(Chaincode):
            def init(self, stub):
                return shim.success()

            def invoke(self, stub):
                stub.set_event("ev", b"partial")
                return stub.invoke_chaincode("child", [b"go"])

        sim = Writes()
        support = ChaincodeSupport(execute_timeout_s=0.1)
        support.register("parent", Parent())
        support.register("child", Child())
        spec = ppb.ChaincodeInvocationSpec()
        spec.chaincode_spec.chaincode_id.name = "parent"
        resp, ev, _id = support.execute("ch", "tx3", spec, sim)
        assert resp.status == shim.ERROR
        assert ev is None               # failed run's event suppressed
        assert child_done.wait(3.0)
        assert sim.puts == []           # child stub fenced too