"""Round-18 cross-node distributed tracing
(fabric_tpu/common/clustertrace.py + the transport carrier seams).

Covers: wire-carrier inject/extract round-trips (absent/corrupt
carrier -> fresh trace, never a crash), carrier-resumed remote spans
(hop.recv linkage, node attribution, exactly-one parent under
duplication), the NetChaos wrappers forwarding carriers on
dup/reorder/partition, multi-node Chrome-trace merging with
deliberately skewed clocks (skew reported, ordering preserved), the
e2e_commit_seconds birth->commit math, SLO burn-rate accounting +
/healthz sub-state + rate-limited auto-dump, the `?trace_id=` filter
on /debug/trace and its forwarding through /debug/trace/cluster, and
the full in-process 3-consenter + 2-peer acceptance rig.

The chaos gate (`tools/chaos_check.sh e2e-trace`) re-runs this file
with net.drop / net.reorder / net.dup / order.propose armed via env —
carriers and error spans must both survive. Tests that pin exact
delivery counts clear the ambient arming themselves (faults.clear).
"""

import json
import threading
import time
import urllib.request

import pytest

from fabric_tpu.common import clustertrace as ct
from fabric_tpu.common import faults, netchaos, tracing
from fabric_tpu.common import metrics as metrics_mod


@pytest.fixture()
def ctrace_env(tmp_path):
    """Isolated recorder + registries; restores process defaults."""
    tracing.configure(enabled=True, ring_size=1024, sample_every=1,
                      dump_dir=str(tmp_path),
                      dump_min_interval_s=0.0, shed_burst=32)
    tracing.set_default_node(None)
    tracing.set_node(None)
    tracing.reset()
    ct.reset()
    ct.configure_slo(None)
    yield tmp_path
    tracing.wait_dumps()
    tracing.configure(enabled=True, ring_size=4096, sample_every=1,
                      dump_dir="", dump_min_interval_s=10.0,
                      shed_burst=32)
    tracing.set_default_node(None)
    tracing.set_node(None)
    tracing.reset()
    ct.reset()
    ct.configure_slo(None)


def _events(name=None):
    return [e for e in tracing.snapshot()
            if name is None or e[1] == name]


# ---------------------------------------------------------------------------
# the wire carrier
# ---------------------------------------------------------------------------

class TestCarrier:
    def test_inject_extract_roundtrip(self, ctrace_env):
        with tracing.span("ingress.batch") as ctx:
            ct.note_birth(ctx.trace_id)
            framed = ct.inject(b"raft-payload")
        assert framed.startswith(ct.MAGIC)
        payload, carrier = ct.extract(framed)
        assert payload == b"raft-payload"
        assert carrier.trace_id == ctx.trace_id
        assert carrier.span_id == ctx.span_id
        assert carrier.birth is not None
        assert carrier.sent is not None

    def test_absent_carrier_is_fresh_trace(self, ctrace_env):
        payload, carrier = ct.extract(b"plain bytes")
        assert payload == b"plain bytes"
        assert carrier is None

    def test_inject_is_idempotent_no_reparenting(self, ctrace_env):
        with tracing.span("a"):
            once = ct.inject(b"x")
        # a foreign ambient context must NOT re-frame (the NetChaos
        # scheduler-thread case): the original parent is preserved
        with tracing.span("b") as other:
            twice = ct.inject(once)
        assert twice == once
        _, carrier = ct.extract(twice)
        assert carrier.trace_id != other.trace_id

    def test_no_ambient_returns_payload_unchanged(self, ctrace_env):
        raw = b"payload"
        assert ct.inject(raw) is raw

    def test_disabled_mode_is_noop_but_still_strips(self, ctrace_env):
        with tracing.span("a"):
            framed = ct.inject(b"x")
        tracing.set_enabled(False)
        try:
            raw = b"y"
            assert ct.inject(raw) is raw           # zero-alloc path
            # a tracing-off RECEIVER must still parse the payload
            payload, carrier = ct.extract(framed)
            assert payload == b"x"
            assert carrier is None                 # resume gated off
        finally:
            tracing.set_enabled(True)

    def test_corrupt_json_never_crashes(self, ctrace_env):
        bad = ct.MAGIC + ct._LEN.pack(7) + b"not-js}" + b"payload"
        payload, carrier = ct.extract(bad)
        assert payload == b"payload"
        assert carrier is None

    def test_implausible_length_treated_as_payload(self, ctrace_env):
        bad = ct.MAGIC + ct._LEN.pack(1 << 30) + b"short"
        payload, carrier = ct.extract(bad)
        assert payload == bad       # not a frame: bytes untouched
        assert carrier is None

    def test_truncated_frame(self, ctrace_env):
        bad = ct.MAGIC + b"\x00"
        payload, carrier = ct.extract(bad)
        assert payload == bad
        assert carrier is None

    def test_header_roundtrip_and_corrupt(self, ctrace_env):
        c = ct.Carrier("t1", "s1", birth=1.5, sent=2.5)
        assert ct.Carrier.from_header(c.to_header()) == c
        assert ct.Carrier.from_header("%%%not-b64") is None
        assert ct.Carrier.from_header(None) is None
        assert ct.Carrier.from_header("") is None


class TestResume:
    def test_resumed_links_hop_and_node(self, ctrace_env):
        c = ct.Carrier("trace-x", "span-x", birth=time.time() - 1.0,
                       sent=time.time() - 0.2)
        with ct.resumed(c, link="a>b", node="nodeB"):
            with tracing.span("order.window"):
                pass
        hops = _events("hop.recv")
        assert len(hops) == 1
        ph, name, tr, sp, par, t0, dur, tname, attrs, err, node = \
            hops[0]
        assert tr == "trace-x" and par == "span-x"
        assert node == "nodeB"
        assert attrs["link"] == "a>b"
        assert 0.1 < dur < 5.0          # the send->receive latency
        # the worker's own span joined the remote trace
        win = _events("order.window")[0]
        assert win[2] == "trace-x"
        assert win[10] == "nodeB"
        # birth carried across the hop
        assert ct.birth_of("trace-x") == c.birth
        # hop stage reservoir fed
        assert tracing.stage_quantile("hop.a>b", "count") == 1

    def test_negative_hop_clamped_but_reported_raw(self, ctrace_env):
        c = ct.Carrier("t", "s", sent=time.time() + 5.0)  # skewed
        with ct.resumed(c, link="skew>me"):
            pass
        hop = _events("hop.recv")[0]
        assert hop[6] == 0.0                       # clamped duration
        assert hop[8]["raw_hop_s"] < 0             # skew evidence

    def test_resumed_none_is_noop(self, ctrace_env):
        with tracing.span("outer") as outer:
            with ct.resumed(None, link="x") as got:
                assert got is None
                assert tracing.capture() is outer
        assert _events("hop.recv") == []

    def test_exactly_one_parent_under_duplication(self, ctrace_env):
        with tracing.span("a"):
            framed = ct.inject(b"msg")
        for _ in range(2):                 # a duplicating link
            payload, carrier = ct.extract(framed)
            with ct.resumed(carrier, link="dup>link"):
                pass
        hops = _events("hop.recv")
        assert len(hops) == 2
        assert len({h[4] for h in hops}) == 1   # ONE distinct parent

    def test_thread_node_binding_restored(self, ctrace_env):
        tracing.set_node("original")
        try:
            c = ct.Carrier("t", "s", sent=time.time())
            with ct.resumed(c, link="l", node="remote"):
                assert tracing.current_node() == "remote"
            assert tracing.current_node() == "original"
        finally:
            tracing.set_node(None)

    def test_birth_first_stamp_wins(self, ctrace_env):
        first = ct.note_birth("tid", 100.0)
        second = ct.note_birth("tid", 200.0)
        assert first == second == 100.0
        assert ct.birth_of("tid") == 100.0


class TestBlockRegistry:
    def test_register_and_lookup(self, ctrace_env):
        with tracing.span("order.write") as ctx:
            ct.note_birth(ctx.trace_id)
            ct.register_block("ch", 7)
        c = ct.block_carrier("ch", 7)
        assert c.trace_id == ctx.trace_id
        assert c.birth is not None
        assert ct.block_carrier("ch", 8) is None

    def test_first_registration_wins(self, ctrace_env):
        with tracing.span("a") as first:
            ct.register_block("ch", 1)
        with tracing.span("b"):
            ct.register_block("ch", 1)      # re-relay: no re-parent
        assert ct.block_carrier("ch", 1).trace_id == first.trace_id

    def test_disabled_mode(self, ctrace_env):
        tracing.set_enabled(False)
        try:
            ct.register_block("ch", 1)
            assert ct.block_carrier("ch", 1) is None
        finally:
            tracing.set_enabled(True)


# ---------------------------------------------------------------------------
# transport seams
# ---------------------------------------------------------------------------

class _ConsensusSink:
    def __init__(self):
        self.got = []       # (sender, payload, ambient trace_id, node)
        self.event = threading.Event()

    def on_consensus(self, sender, payload):
        ctx = tracing.capture()
        self.got.append((sender, payload,
                         ctx.trace_id if ctx else None,
                         tracing.current_node()))
        self.event.set()

    def on_submit(self, env_bytes, config_seq=0):
        from fabric_tpu.protos import common, orderer as opb
        ctx = tracing.capture()
        self.got.append(("submit", env_bytes,
                         ctx.trace_id if ctx else None,
                         tracing.current_node()))
        return opb.SubmitResponse(channel="ch",
                                  status=common.Status.SUCCESS)

    def serve_blocks(self, start, end):
        ctx = tracing.capture()
        self.got.append(("pull", b"", ctx.trace_id if ctx else None,
                         tracing.current_node()))
        return []


def _wait(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            raise AssertionError("condition never met")
        time.sleep(0.01)


class TestClusterTransportSeams:
    def test_consensus_carrier_crosses_nodes(self, ctrace_env):
        from fabric_tpu.orderer.cluster import LocalClusterNetwork
        net = LocalClusterNetwork()
        a = net.register("nodeA:1")
        b = net.register("nodeB:2")
        sink = _ConsensusSink()
        b.set_handler("ch", sink)
        try:
            with tracing.span("order.propose") as ctx:
                a.send_consensus("nodeB:2", "ch", b"raft-append")
            _wait(sink.event.is_set)
            sender, payload, trace_id, node = sink.got[0]
            assert sender == "nodeA:1"
            assert payload == b"raft-append"   # frame stripped
            assert trace_id == ctx.trace_id    # resumed, not orphan
            assert node == "nodeB:2"           # remote's own node id
            hop = _events("hop.recv")[0]
            assert hop[8]["link"] == "nodeA:1>nodeB:2"
        finally:
            a.close()
            b.close()

    def test_submit_and_pull_carriers(self, ctrace_env):
        from fabric_tpu.orderer.cluster import LocalClusterNetwork
        from fabric_tpu.protos import common
        net = LocalClusterNetwork()
        a = net.register("nodeA:1")
        b = net.register("nodeB:2")
        sink = _ConsensusSink()
        b.set_handler("ch", sink)
        try:
            with tracing.span("ingress.batch") as ctx:
                resp = a.submit("nodeB:2", "ch", b"env-bytes")
                a.pull_blocks("nodeB:2", "ch", 0, 1)
            assert resp.status == common.Status.SUCCESS
            kinds = {g[0]: g for g in sink.got}
            assert kinds["submit"][1] == b"env-bytes"
            assert kinds["submit"][2] == ctx.trace_id
            assert kinds["pull"][2] == ctx.trace_id
        finally:
            a.close()
            b.close()

    def test_corrupt_wire_carrier_never_crashes(self, ctrace_env):
        from fabric_tpu.orderer.cluster import LocalClusterNetwork
        net = LocalClusterNetwork()
        b = net.register("nodeB:2")
        sink = _ConsensusSink()
        b.set_handler("ch", sink)
        try:
            bad = ct.MAGIC + ct._LEN.pack(5) + b"{bad}" + b"payload"
            b.enqueue_consensus("evil", "ch", bad)
            _wait(sink.event.is_set)
            _s, payload, trace_id, _n = sink.got[0]
            assert payload == b"payload"
            assert trace_id is None            # fresh trace, no crash
        finally:
            b.close()


class TestGossipTransportSeams:
    def test_gossip_carrier_side_band(self, ctrace_env):
        from fabric_tpu.gossip.transport import LocalNetwork
        net = LocalNetwork()
        a = net.register("peerA:1")
        b = net.register("peerB:2")
        got = []
        done = threading.Event()

        def handler(sender, msg):
            ctx = tracing.capture()
            got.append((sender, msg, ctx.trace_id if ctx else None,
                        tracing.current_node()))
            done.set()

        b.set_handler(handler)
        try:
            with tracing.span("gossip.push") as ctx:
                a.send("peerB:2", b"block-bytes")
            _wait(done.is_set)
            sender, msg, trace_id, node = got[0]
            assert (sender, msg) == ("peerA:1", b"block-bytes")
            assert trace_id == ctx.trace_id
            assert node == "peerB:2"
        finally:
            a.close()
            b.close()

    def test_gossip_without_ambient_is_carrierless(self, ctrace_env):
        from fabric_tpu.gossip.transport import LocalNetwork
        net = LocalNetwork()
        a = net.register("peerA:1")
        b = net.register("peerB:2")
        got = []
        done = threading.Event()

        def handler(sender, msg):
            ctx = tracing.capture()
            got.append(ctx)
            done.set()

        b.set_handler(handler)
        try:
            a.send("peerB:2", b"x")
            _wait(done.is_set)
            assert got[0] is None
        finally:
            a.close()
            b.close()


class TestNetChaosCarriers:
    """The chaos wrappers must FORWARD carriers on dup/reorder
    without re-parenting: the frame is built eagerly at send time, so
    the scheduler thread's foreign ambient never rewrites it."""

    def test_dup_forwards_one_parent(self, ctrace_env):
        faults.clear()       # pinned delivery counts: no env chaos
        from fabric_tpu.orderer.cluster import LocalClusterNetwork
        chaos = netchaos.NetChaos(seed=3)
        chaos.set_policy(netchaos.LinkPolicy(dup_rate=1.0))
        net = LocalClusterNetwork()
        a = chaos.wrap_cluster(net.register("nodeA:1"))
        b = net.register("nodeB:2")
        sink = _ConsensusSink()
        b.set_handler("ch", sink)
        try:
            with tracing.span("order.propose"):
                a.send_consensus("nodeB:2", "ch", b"append")
            chaos.quiesce()
            _wait(lambda: len(sink.got) == 2)
            payloads = {g[1] for g in sink.got}
            traces = {g[2] for g in sink.got}
            assert payloads == {b"append"}
            assert len(traces) == 1 and None not in traces
            hops = _events("hop.recv")
            assert len({h[4] for h in hops}) == 1   # ONE parent
        finally:
            a.close()
            b.close()
            chaos.close()

    def test_reorder_keeps_carriers_intact(self, ctrace_env):
        faults.clear()
        from fabric_tpu.orderer.cluster import LocalClusterNetwork
        chaos = netchaos.NetChaos(seed=5)
        chaos.set_policy(netchaos.LinkPolicy(reorder_rate=1.0,
                                             reorder_window=2,
                                             reorder_hold_s=0.05))
        net = LocalClusterNetwork()
        a = chaos.wrap_cluster(net.register("nodeA:1"))
        b = net.register("nodeB:2")
        sink = _ConsensusSink()
        b.set_handler("ch", sink)
        try:
            ids = []
            for i in range(4):
                with tracing.span("order.propose") as c:
                    ids.append(c.trace_id)
                    a.send_consensus("nodeB:2", "ch",
                                     f"m{i}".encode())
            chaos.quiesce()
            _wait(lambda: len(sink.got) == 4)
            # every delivered message still pairs its OWN trace
            by_payload = {g[1]: g[2] for g in sink.got}
            for i in range(4):
                assert by_payload[f"m{i}".encode()] == ids[i]
        finally:
            a.close()
            b.close()
            chaos.close()

    def test_partition_cuts_without_crash(self, ctrace_env):
        faults.clear()
        from fabric_tpu.orderer.cluster import LocalClusterNetwork
        chaos = netchaos.NetChaos(seed=1)
        net = LocalClusterNetwork()
        a = chaos.wrap_cluster(net.register("nodeA:1"))
        b = net.register("nodeB:2")
        sink = _ConsensusSink()
        b.set_handler("ch", sink)
        try:
            chaos.partition(["nodeB:2"])
            with tracing.span("order.propose"):
                a.send_consensus("nodeB:2", "ch", b"cut")
            chaos.quiesce()
            time.sleep(0.05)
            assert sink.got == []
            assert chaos.stats["partitioned"] == 1
        finally:
            a.close()
            b.close()
            chaos.close()


# ---------------------------------------------------------------------------
# cluster merge
# ---------------------------------------------------------------------------

def _mk_doc(node, epoch, events):
    """A minimal per-node Chrome-trace doc: events = [(name, trace,
    span, ts_us, extra_args)]."""
    tev = []
    for name, tr, sp, ts, extra in events:
        args = {"trace_id": tr, "span_id": sp}
        args.update(extra or {})
        tev.append({"ph": "X", "name": name,
                    "cat": name.split(".", 1)[0], "pid": 7, "tid": 1,
                    "ts": ts, "dur": 1.0, "args": args})
    return {"displayTimeUnit": "ms", "traceEvents": tev,
            "ftpu": {"node_id": node,
                     "clock": {"epoch_wall_s": epoch}}}


class TestMerge:
    def test_skewed_clocks_aligned_and_reported(self, ctrace_env):
        # node B's wall clock is 2s ahead; its event at local ts 0
        # really happened 2s after A's ts 0 — alignment must order
        # A's event first and REPORT the shift
        a = _mk_doc("A", 1000.0, [("order.propose", "t", "s1",
                                   500.0, None)])
        b = _mk_doc("B", 1002.0, [("commit.commit", "t", "s2",
                                   0.0, {"raw_hop_s": -0.25})])
        merged = ct.merge_docs([a, b])
        ev = [e for e in merged["traceEvents"] if e["ph"] != "M"]
        assert [e["name"] for e in ev] == ["order.propose",
                                          "commit.commit"]
        assert ev[1]["ts"] - ev[0]["ts"] == pytest.approx(
            2_000_000 - 500, abs=1.0)
        cluster = merged["ftpu"]["cluster"]
        assert cluster["nodes"]["B"]["shift_us"] == pytest.approx(
            2e6)
        assert cluster["residual_skew_s_observed"] == \
            pytest.approx(0.25)

    def test_dedup_by_span_id(self, ctrace_env):
        a = _mk_doc("A", 0.0, [("x", "t", "same-span", 1.0, None)])
        b = _mk_doc("A", 0.0, [("x", "t", "same-span", 1.0, None)])
        merged = ct.merge_docs([a, b])
        assert len([e for e in merged["traceEvents"]
                    if e["ph"] != "M"]) == 1

    def test_trace_id_filter(self, ctrace_env):
        a = _mk_doc("A", 0.0, [("x", "keep", "s1", 1.0, None),
                               ("y", "drop", "s2", 2.0, None)])
        merged = ct.merge_docs([a], trace_id="keep")
        ev = [e for e in merged["traceEvents"] if e["ph"] != "M"]
        assert [e["args"]["trace_id"] for e in ev] == ["keep"]

    def test_node_stage_tids(self, ctrace_env):
        a = _mk_doc("A", 0.0, [("order.propose", "t", "s1", 1.0,
                                None)])
        b = _mk_doc("B", 0.0, [("commit.commit", "t", "s2", 2.0,
                                None)])
        merged = ct.merge_docs([a, b])
        labels = {e["args"]["name"] for e in merged["traceEvents"]
                  if e["ph"] == "M" and e["name"] == "thread_name"}
        assert labels == {"A/order", "B/commit"}

    def test_unanchored_doc_flagged_not_dropped(self, ctrace_env):
        a = _mk_doc("A", 5.0, [("x", "t", "s1", 1.0, None)])
        b = _mk_doc("B", 0.0, [("y", "t", "s2", 2.0, None)])
        del b["ftpu"]["clock"]
        merged = ct.merge_docs([a, b])
        ev = [e for e in merged["traceEvents"] if e["ph"] != "M"]
        assert len(ev) == 2
        errs = merged["ftpu"]["cluster"]["errors"]
        assert any("clock anchor" in e for e in errs)

    def test_merge_files_reports_unreadable(self, ctrace_env,
                                            tmp_path):
        good = tmp_path / "good.json"
        good.write_text(json.dumps(
            _mk_doc("A", 0.0, [("x", "t", "s1", 1.0, None)])))
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        merged = ct.merge_files([str(good), str(bad)])
        assert len([e for e in merged["traceEvents"]
                    if e["ph"] != "M"]) == 1
        assert any("bad.json" in e
                   for e in merged["ftpu"]["cluster"]["errors"])

    def test_live_ring_merge_dedups_two_exports(self, ctrace_env):
        tracing.set_node("nodeA")
        try:
            with tracing.span("order.window"):
                pass
        finally:
            tracing.set_node(None)
        doc1 = tracing.chrome_trace()
        doc2 = tracing.chrome_trace()     # same ring, second export
        merged = ct.merge_docs([doc1, doc2])
        ev = [e for e in merged["traceEvents"] if e["ph"] != "M"]
        assert len(ev) == 1
        assert ev[0]["args"]["node"] == "nodeA"


# ---------------------------------------------------------------------------
# e2e finality + the SLO error budget
# ---------------------------------------------------------------------------

class TestE2ECommit:
    def test_birth_to_commit_math(self, ctrace_env):
        tid = "trace-e2e"
        ct.note_birth(tid, time.time() - 0.5)
        ctx = tracing.TraceContext(tid, "s")
        e2e = ct.note_commit(ctx, node="peer0")
        assert 0.4 < e2e < 2.0
        assert tracing.stage_quantile("e2e.commit", "count") == 1

    def test_no_birth_no_observation(self, ctrace_env):
        assert ct.note_commit(
            tracing.TraceContext("unknown", "s")) is None
        assert ct.note_commit(None) is None

    def test_multi_peer_commit_histogram_renders(self, ctrace_env):
        provider = metrics_mod.PrometheusProvider()
        tracing.bind_metrics(provider)
        tid = "trace-m"
        ct.note_birth(tid, time.time() - 0.1)
        ctx = tracing.TraceContext(tid, "s")
        ct.note_commit(ctx, node="peer0")
        ct.note_commit(ctx, node="peer1")
        text = provider.render()
        assert 'e2e_commit_seconds_count{node="peer0"} 1' in text
        assert 'e2e_commit_seconds_count{node="peer1"} 1' in text

    def test_hop_histogram_renders(self, ctrace_env):
        provider = metrics_mod.PrometheusProvider()
        tracing.bind_metrics(provider)
        c = ct.Carrier("t", "s", sent=time.time())
        with ct.resumed(c, link="a>b"):
            pass
        assert 'hop_seconds_count{link="a>b"} 1' in provider.render()


class TestSLO:
    def test_burn_rate_math(self, ctrace_env):
        slo = ct.SLOTracker(0.1)
        for _ in range(50):
            slo.observe(0.01)               # all under target
        assert slo.burn_rate() == 0.0
        assert slo.health() == "ok"
        for _ in range(ct.SLO_MIN_OBS):
            slo.observe(1.0)                # all over target
        # 20/70 over budget of 1% -> burning hard
        assert slo.burn_rate() == pytest.approx(
            (ct.SLO_MIN_OBS / 70) / ct.SLO_ERROR_BUDGET)
        assert slo.health().startswith("burning:")

    def test_fractional_budget(self, ctrace_env):
        slo = ct.SLOTracker(0.1)
        for i in range(100):
            slo.observe(1.0 if i < 2 else 0.01)   # 2% violations
        assert slo.burn_rate() == pytest.approx(2.0)

    def test_no_target_is_ok(self, ctrace_env):
        slo = ct.SLOTracker(None)
        slo.observe(100.0)
        assert slo.health() == "ok"
        assert slo.stats["observed"] == 0

    def test_thin_evidence_never_burns(self, ctrace_env):
        slo = ct.SLOTracker(0.1)
        for _ in range(ct.SLO_MIN_OBS - 1):
            slo.observe(1.0)
        assert slo.health() == "ok"     # under SLO_MIN_OBS

    def test_sustained_burn_dumps_once_per_episode(self, ctrace_env):
        slo = ct.SLOTracker(0.1)
        for _ in range(ct.SLO_MIN_OBS + 5):
            slo.observe(1.0)
        assert slo.stats["dumps"] == 1          # latched
        tracing.wait_dumps()
        assert any(e[1] == "slo.burn" for e in tracing.snapshot())
        dumps = [p for p in ctrace_env.iterdir()
                 if "slo_burn" in p.name]
        assert len(dumps) == 1
        doc = json.loads(dumps[0].read_text())
        assert doc["ftpu"]["reason"] == "slo_burn"
        # recover, then burn again -> ONE more dump
        for _ in range(ct.SLO_WINDOW):
            slo.observe(0.01)
        assert slo.health() == "ok"
        for _ in range(ct.SLO_WINDOW):
            slo.observe(1.0)
        assert slo.stats["dumps"] == 2

    def test_healthz_substate(self, ctrace_env):
        from fabric_tpu.node.operations import OperationsServer
        ct.configure_slo(0.1)
        ops = OperationsServer()
        ops.register_checker("slo", ct.slo_health)
        ops.start()
        try:
            def healthz():
                with urllib.request.urlopen(
                        f"http://{ops.address}/healthz",
                        timeout=5) as r:
                    return json.load(r)

            assert healthz()["components"]["slo"] == "ok"
            tid = "slo-trace"
            ct.note_birth(tid, time.time() - 10.0)
            for _ in range(ct.SLO_MIN_OBS + 1):
                ct.note_commit(tracing.TraceContext(tid, "s"),
                               node="p")
            body = healthz()
            assert body["status"] == "OK"       # degraded-but-serving
            assert body["components"]["slo"].startswith("burning:")
        finally:
            ops.stop()
            tracing.wait_dumps()

    def test_config_entry(self, ctrace_env):
        class _Cfg:
            def get(self, key, default=None):
                return {"Operations.SLO.CommitP99S": "0.25"}.get(
                    key, default)

        ct.configure_from_config(_Cfg())
        assert ct.slo().target_p99_s == 0.25


# ---------------------------------------------------------------------------
# the debug surfaces
# ---------------------------------------------------------------------------

class TestTraceEndpoints:
    @pytest.fixture()
    def two_ops(self, ctrace_env):
        from fabric_tpu.node.operations import OperationsServer
        ops_a = OperationsServer()
        ops_b = OperationsServer()
        ops_a.set_trace_peers([ops_b.address])
        ops_a.start()
        ops_b.start()
        yield ops_a, ops_b
        ops_a.stop()
        ops_b.stop()

    @staticmethod
    def _get(addr, path):
        with urllib.request.urlopen(f"http://{addr}{path}",
                                    timeout=5) as r:
            return json.load(r)

    def test_trace_id_filter_on_debug_trace(self, two_ops):
        ops_a, _ = two_ops
        with tracing.span("keep.me") as keep:
            pass
        with tracing.span("drop.me"):
            pass
        doc = self._get(ops_a.address,
                        f"/debug/trace?trace_id={keep.trace_id}")
        names = {e["name"] for e in doc["traceEvents"]
                 if e["ph"] != "M"}
        assert names == {"keep.me"}
        # unfiltered still ships everything
        full = self._get(ops_a.address, "/debug/trace")
        names = {e["name"] for e in full["traceEvents"]
                 if e["ph"] != "M"}
        assert {"keep.me", "drop.me"} <= names
        assert full["ftpu"]["clock"]["epoch_wall_s"] > 0

    def test_cluster_endpoint_merges_and_forwards(self, two_ops):
        ops_a, _ = two_ops
        with tracing.span("order.window") as keep:
            pass
        with tracing.span("other.trace"):
            pass
        doc = self._get(
            ops_a.address,
            f"/debug/trace/cluster?trace_id={keep.trace_id}")
        ev = [e for e in doc["traceEvents"] if e["ph"] != "M"]
        # both endpoints exported the same shared ring: the filter
        # was FORWARDED and the merge deduplicated by span id
        assert len(ev) == 1
        assert ev[0]["args"]["trace_id"] == keep.trace_id
        assert doc["ftpu"]["cluster"]["docs"] == 2
        assert doc["ftpu"]["cluster"]["errors"] == []

    def test_cluster_endpoint_tolerates_dead_peer(self, ctrace_env):
        from fabric_tpu.node.operations import OperationsServer
        ops = OperationsServer()
        ops.set_trace_peers(["127.0.0.1:1"])     # nothing listens
        ops.start()
        try:
            with tracing.span("alive"):
                pass
            doc = self._get(ops.address, "/debug/trace/cluster")
            assert any(e["name"] == "alive"
                       for e in doc["traceEvents"])
            assert doc["ftpu"]["cluster"]["errors"]
        finally:
            ops.stop()

    def test_trace_peers_comma_string(self, ctrace_env):
        from fabric_tpu.node.operations import OperationsServer
        ops = OperationsServer()
        ops.start()
        try:
            ops.set_trace_peers("a:1, b:2 ,")
            assert ops._trace_peers == ["a:1", "b:2"]
            ops.set_trace_peers(None)
            assert ops._trace_peers == []
        finally:
            ops.stop()


# ---------------------------------------------------------------------------
# the acceptance rig: 3 consenters + 2 peers, one merged trace
# ---------------------------------------------------------------------------

class TestClusterRun:
    def test_three_consenter_two_peer_merged_trace(self, ctrace_env):
        import bench_pipeline as bp
        out = bp.cluster_trace_run(ntxs=8, block_txs=4, window=6)
        assert out["probe_trace_id"]
        # commit.validate/commit.commit landed on BOTH peers
        assert set(out["commit_nodes"].split(",")) == {
            "peer0.example.com:7051", "peer1.example.com:7052"}
        # the probe crossed at least one consenter hop + both peers
        nodes = set(out["trace_nodes"].split(","))
        assert len(nodes) >= 4
        for want in ("ingress.batch", "hop.recv", "order.write",
                     "commit.validate", "commit.commit"):
            assert want in out["linked_stages"].split(","), out
        assert out["e2e_commit_p50_s"] > 0
        assert out["e2e_commit_p99_s"] > 0
        assert out["slo_health"] == "ok" or \
            out["slo_health"].startswith("burning:")

    def test_disabled_tracing_skips(self, ctrace_env):
        import bench_pipeline as bp
        tracing.set_enabled(False)
        try:
            assert bp.cluster_trace_run()["skipped"]
        finally:
            tracing.set_enabled(True)
