"""Round-12 overload protection (ISSUE 9).

The claims under test, over `common/overload.py` and the five seams
it wires (broadcast ingress, AdmissionWindow, raft event queue,
BlockWriteStage, CommitPipeline):

  * a full queue sheds at the deadline horizon with a RETRYABLE
    error (`SERVICE_UNAVAILABLE` at the broadcast/stream edges),
    never an indefinite stall;
  * deadline expiry mid-pipeline never half-applies: a shed envelope
    commits nowhere, an accepted envelope commits exactly once;
  * the admission window is notification-driven and sheds only
    callers still QUEUED (in-flight dispatches complete);
  * demotion paths (write stage, commit pipeline fallback) still
    drain under saturation;
  * every stage's depth/shed/wait readings surface through the
    overload registry, the overload_* gauges and /healthz.

Chaos-armed runs (tools/chaos_check.sh overload) re-run this file
with order.propose/tpu.dispatch/raft.step faults live: sheds must
stay clean refusals whichever path serves. The lockcheck-armed run
(tools/static_check.sh) covers the no-deadlock claim.
"""

from __future__ import annotations

import queue
import threading
import time

import pytest

import bench_pipeline as bp
from fabric_tpu.common import faults, overload
from fabric_tpu.common.overload import (
    Deadline, OverloadError, SheddingQueue,
)


class TestDeadline:
    def test_after_remaining_expired(self):
        d = Deadline.after(0.5)
        assert 0.0 < d.remaining() <= 0.5
        assert not d.expired()
        assert Deadline.after(-1).expired()

    def test_ambient_applied_and_restored(self):
        assert Deadline.current() is None
        with Deadline.after(5).applied() as d:
            assert Deadline.current() is d
        assert Deadline.current() is None

    def test_nesting_takes_the_minimum(self):
        with Deadline.after(10).applied() as outer:
            with Deadline.after(100).applied() as inner:
                # the looser inner deadline cannot EXTEND the budget
                assert inner is outer or \
                    inner.expires_at == outer.expires_at
                assert Deadline.current().remaining() <= 10
            with Deadline.after(0.1).applied() as tight:
                assert Deadline.current().remaining() <= 0.1
                assert tight.expires_at < outer.expires_at
            assert Deadline.current() is outer

    def test_thread_isolation(self):
        seen = []

        def probe():
            seen.append(Deadline.current())

        with Deadline.after(5).applied():
            t = threading.Thread(target=probe)
            t.start()
            t.join()
        assert seen == [None]

    def test_remaining_or(self):
        assert Deadline.remaining_or(7.5) == 7.5
        with Deadline.after(2).applied():
            assert Deadline.remaining_or(7.5) <= 2

    def test_env_budgets(self, monkeypatch):
        monkeypatch.setenv("FTPU_INGRESS_BUDGET_S", "12.5")
        monkeypatch.setenv("FTPU_ENQUEUE_BUDGET_S", "3.5")
        assert overload.ingress_budget_s() == 12.5
        assert overload.default_enqueue_budget_s() == 3.5
        monkeypatch.setenv("FTPU_INGRESS_BUDGET_S", "bogus")
        assert overload.ingress_budget_s() == 30.0


class TestSheddingQueueShed:
    def test_put_get_roundtrip(self):
        q = SheddingQueue("t.rt", maxsize=4, register=False)
        q.put("a")
        q.put("b")
        assert q.get_nowait() == "a"
        assert q.get(timeout=0.1) == "b"
        with pytest.raises(queue.Empty):
            q.get_nowait()

    def test_full_queue_sheds_at_budget(self):
        q = SheddingQueue("t.full", maxsize=1, default_budget_s=0.05,
                          register=False)
        q.put("x")
        t0 = time.monotonic()
        with pytest.raises(OverloadError):
            q.put("y")
        dt = time.monotonic() - t0
        assert 0.04 <= dt < 2.0, "shed must land at the budget horizon"
        assert q.overload_stats()["sheds"] == 1
        assert q.overload_stats()["last_shed_t"] is not None
        # the shed left nothing behind
        assert q.get_nowait() == "x"
        assert q.empty()

    def test_ambient_deadline_bounds_the_put(self):
        q = SheddingQueue("t.amb", maxsize=1, default_budget_s=30.0,
                          register=False)
        q.put("x")
        with Deadline.after(0.05).applied():
            t0 = time.monotonic()
            with pytest.raises(OverloadError):
                q.put("y")
            assert time.monotonic() - t0 < 2.0

    def test_unblocks_when_space_frees(self):
        q = SheddingQueue("t.free", maxsize=1, default_budget_s=5.0,
                          register=False)
        q.put("x")
        got = []

        def consumer():
            time.sleep(0.05)
            got.append(q.get(timeout=1))

        t = threading.Thread(target=consumer)
        t.start()
        q.put("y")          # must ride the freed slot, not shed
        t.join()
        assert got == ["x"]
        assert q.get_nowait() == "y"
        assert q.overload_stats()["sheds"] == 0

    def test_put_forced_bypasses_bound(self):
        q = SheddingQueue("t.forced", maxsize=1, register=False)
        q.put("x")
        q.put_forced(None)
        assert q.qsize() == 2
        assert q.overload_stats()["forced"] == 1

    def test_put_nowait_raises_queue_full(self):
        q = SheddingQueue("t.nowait", maxsize=1, register=False)
        q.put_nowait("x")
        with pytest.raises(queue.Full):
            q.put_nowait("y")
        assert q.overload_stats()["sheds"] == 1

    def test_drop_oldest(self):
        q = SheddingQueue("t.drop", maxsize=2, register=False)
        assert q.put_drop_oldest(1) == 0
        assert q.put_drop_oldest(2) == 0
        assert q.put_drop_oldest(3) == 1
        assert [q.get_nowait(), q.get_nowait()] == [2, 3]
        assert q.overload_stats()["sheds"] == 1

    def test_rejects_nonpositive_bound(self):
        with pytest.raises(ValueError):
            SheddingQueue("t.bad", maxsize=0, register=False)

    def test_registry_and_health(self):
        q = SheddingQueue("t.reg.health", maxsize=1,
                          default_budget_s=0.01)
        try:
            q.put("x")
            assert "t.reg.health" in overload.stage_stats()
            with pytest.raises(OverloadError):
                q.put("y")
            assert "t.reg.health" in overload.health()
            assert overload.health().startswith("shedding:")
        finally:
            overload.unregister_stage("t.reg.health", q)
        assert "t.reg.health" not in overload.stage_stats()

    def test_max_depth_high_water(self):
        q = SheddingQueue("t.hw", maxsize=8, register=False)
        for i in range(5):
            q.put(i)
        for _ in range(5):
            q.get_nowait()
        s = q.overload_stats()
        assert s["max_depth"] == 5 and s["depth"] == 0


class _BlockingCSP:
    """Stub provider: verify_batch parks on an event, recording what
    it was asked to verify."""

    def __init__(self):
        self.release = threading.Event()
        self.calls: list = []

    def verify_batch(self, items):
        self.calls.append(list(items))
        assert self.release.wait(timeout=10), "test never released csp"
        return [True] * len(items)


class TestAdmissionWindowShed:
    def _window(self):
        from fabric_tpu.bccsp.admission import AdmissionWindow
        csp = _BlockingCSP()
        return AdmissionWindow(csp), csp

    def test_queued_caller_sheds_at_deadline(self):
        win, csp = self._window()
        leader_done = []

        def leader():
            leader_done.append(win.verify_batch(["L1", "L2"]))

        t = threading.Thread(target=leader)
        t.start()
        for _ in range(200):            # leader in flight
            if csp.calls:
                break
            time.sleep(0.005)
        assert csp.calls, "leader never dispatched"

        with Deadline.after(0.05).applied():
            with pytest.raises(OverloadError):
                win.verify_batch(["W1"])
        assert win.stats["window_sheds"] == 1
        csp.release.set()
        t.join(timeout=5)
        assert leader_done == [[True, True]]
        # the shed caller's lanes never reached the provider
        assert all("W1" not in call for call in csp.calls)

    def test_inflight_caller_waits_out_the_dispatch(self):
        """A caller whose batch was already taken by a leader is NOT
        shed at its deadline: dispatched verdicts cannot be recalled,
        and the provider's breaker bounds the wait."""
        win, csp = self._window()
        results = {}

        def call(tag, items, budget=None):
            try:
                if budget is None:
                    results[tag] = win.verify_batch(items)
                else:
                    with Deadline.after(budget).applied():
                        results[tag] = win.verify_batch(items)
            except BaseException as e:   # noqa: BLE001
                results[tag] = e

        t1 = threading.Thread(target=call, args=("leader", ["A"]))
        t1.start()
        for _ in range(200):
            if csp.calls:
                break
            time.sleep(0.005)
        # the second caller queues, then a THIRD leader takes it after
        # the first dispatch returns — here we release quickly so the
        # deadline (0.15s) expires only while caller 2 is mid-flight
        t2 = threading.Thread(target=call,
                              args=("mid", ["B"], 0.15))
        t2.start()
        time.sleep(0.05)
        csp.release.set()
        t1.join(timeout=5)
        t2.join(timeout=5)
        assert results["leader"] == [True]
        assert results["mid"] == [True], (
            "an in-flight (or promptly-led) caller must receive "
            "verdicts, not a shed")

    def test_notification_not_polling(self):
        """Waiters wake promptly when the leader's verdicts scatter —
        the round-10 implementation polled at 100ms, so a convoy of N
        waiters paid up to N*100ms of pure scheduling latency."""
        win, csp = self._window()
        done_at = {}

        def call(tag, items):
            win.verify_batch(items)
            done_at[tag] = time.perf_counter()

        t1 = threading.Thread(target=call, args=("leader", ["A"]))
        t1.start()
        for _ in range(200):
            if csp.calls:
                break
            time.sleep(0.005)
        t2 = threading.Thread(target=call, args=("w", ["B"]))
        t2.start()
        time.sleep(0.05)    # w is queued behind the in-flight leader
        t0 = time.perf_counter()
        csp.release.set()
        t1.join(timeout=5)
        t2.join(timeout=5)
        # both the leader's return AND the follower's own dispatch
        # completed; the follower led its own (instant) dispatch after
        # one notification — far under a single 100ms poll tick
        assert done_at["w"] - t0 < 0.09, (
            f"waiter took {done_at['w'] - t0:.3f}s after release — "
            "polling, not notification")
        assert win.stats["window_wait_s"] > 0
        assert win.stats["window_last_wait_s"] >= 0

    def test_no_deadline_caller_never_sheds(self):
        win, csp = self._window()
        out = []
        t1 = threading.Thread(
            target=lambda: out.append(win.verify_batch(["A"])))
        t1.start()
        for _ in range(200):
            if csp.calls:
                break
            time.sleep(0.005)
        t2 = threading.Thread(
            target=lambda: out.append(win.verify_batch(["B"])))
        t2.start()
        time.sleep(0.05)
        csp.release.set()
        t1.join(timeout=5)
        t2.join(timeout=5)
        assert out and all(r == [True] for r in out) and len(out) == 2
        assert win.stats["window_sheds"] == 0

    def test_registry_stage(self):
        win, _csp = self._window()
        assert "bccsp.admission" in overload.stage_stats()
        s = win.overload_stats()
        assert s["sheds"] == 0 and s["depth"] == 0


def _elect(chain, max_ticks: int = 400):
    from fabric_tpu.orderer.raft.core import LEADER
    for _ in range(max_ticks):
        chain.node.tick()
        chain._drain_ready()
        if chain.node.state == LEADER:
            return
    raise AssertionError("single-node chain never elected itself")


class TestChainShed:
    """The raft event queue's overload contract, against the REAL
    chain (bench_pipeline stub seam, loop driven synchronously so the
    queue genuinely fills)."""

    @pytest.fixture()
    def svc(self, tmp_path, monkeypatch):
        monkeypatch.setenv("FTPU_RAFT_EVENTS_CAP", "4")
        svc = bp.make_order_service(str(tmp_path / "svc"),
                                    start=False, block_txs=4)
        _elect(svc.chain)
        yield svc
        svc.close(flush=True)

    def _fill_events(self, svc) -> int:
        n = 0
        env = svc.client.envelope(990000 + n)
        while True:
            try:
                with Deadline.after(0.01).applied():
                    svc.chain.order_batch([(env, 0)])
                n += 1
                env = svc.client.envelope(990000 + n)
            except OverloadError:
                return n

    def test_full_event_queue_sheds_retryably(self, svc):
        filled = self._fill_events(svc)
        assert filled == 4      # the FTPU_RAFT_EVENTS_CAP bound
        stats = svc.chain._events.overload_stats()
        assert stats["sheds"] >= 1
        # retryable: drive the loop synchronously — one drain handles
        # the backlog, the queue frees, and a retry of the SAME
        # operation lands
        evs = []
        while True:
            try:
                evs.append(svc.chain._events.get_nowait())
            except queue.Empty:
                break
        window = [(e[1][0][0], e[1][0][1], False) for e in evs
                  if e[0] == "order_batch"]
        svc.chain._process_order_window(window)
        svc.chain._drain_ready()
        with Deadline.after(1.0).applied():
            assert svc.chain.order_batch(
                [(svc.client.envelope(999999), 0)]) == 1

    def test_shed_envelope_never_commits(self, svc):
        accepted = []
        shed = []
        for i in range(10):
            env = svc.client.envelope(880000 + i)
            try:
                with Deadline.after(0.01).applied():
                    svc.chain.order_batch([(env, 0)])
                accepted.append(env)
            except OverloadError:
                shed.append(env)
        assert shed, "queue never filled — rig broken"
        # drain + process everything accepted
        evs = []
        while True:
            try:
                evs.append(svc.chain._events.get_nowait())
            except queue.Empty:
                break
        window = [(e[1][0][0], e[1][0][1], False) for e in evs
                  if e[0] == "order_batch"]
        svc.chain._process_order_window(window)
        svc.chain._drain_ready()
        stage = svc.chain._write_stage
        if stage is not None:
            assert stage.drain(timeout=30)
        lg = svc.support.ledger
        committed = {bytes(d)
                     for n in range(1, lg.height)
                     for d in lg.get_block(n).data.data}
        from fabric_tpu.protoutil import protoutil as pu
        for env in accepted:
            assert pu.marshal(env) in committed, \
                "accepted envelope lost"
        for env in shed:
            assert pu.marshal(env) not in committed, \
                "SHED envelope committed — half-applied state"

    def test_broadcast_maps_shed_to_service_unavailable(self, svc):
        from fabric_tpu.protos import common as cpb
        self._fill_events(svc)
        with Deadline.after(0.01).applied():
            resps = svc.broadcast.process_messages(
                [svc.client.envelope(770000)])
        assert len(resps) == 1
        assert resps[0].status == cpb.Status.SERVICE_UNAVAILABLE

    def test_on_submit_returns_service_unavailable(self, svc):
        from fabric_tpu.protos import common as cpb
        from fabric_tpu.protoutil import protoutil as pu
        self._fill_events(svc)
        with Deadline.after(0.01).applied():
            resp = svc.chain.on_submit(
                pu.marshal(svc.client.envelope(660000)))
        assert resp.status == cpb.Status.SERVICE_UNAVAILABLE

    def test_halt_with_full_queue(self, svc):
        self._fill_events(svc)
        # halt's sentinel is bound-exempt: this returns promptly even
        # though the queue is at capacity (the loop was never started,
        # so join is immediate)
        t0 = time.monotonic()
        svc.chain.halt()
        assert time.monotonic() - t0 < 5
        assert svc.chain.errored()


class _Env:
    """Minimal envelope stand-in for the stream-shed test."""

    def __init__(self, i):
        self.i = i


class TestBroadcastStreamShed:
    def _drive(self, n_envs, handler, **kw):
        from fabric_tpu.comm.services import broadcast_stream
        envs = [_Env(i) for i in range(n_envs)]
        return envs, list(broadcast_stream(iter(envs), handler, **kw))

    def test_responses_stay_one_to_one_under_shed(self):
        from fabric_tpu.protos import common as cpb
        from fabric_tpu.protos import orderer as opb
        release = threading.Event()

        class SlowHandler:
            def __init__(self):
                self.seen = []

            def process_messages(self, batch):
                # the FIRST window parks until released — the reader
                # must shed everything its budget can't hold
                if not self.seen:
                    assert release.wait(timeout=10)
                self.seen.append(list(batch))
                return [opb.BroadcastResponse(
                    status=cpb.Status.SUCCESS)] * len(batch)

        handler = SlowHandler()
        t = threading.Timer(0.3, release.set)
        t.start()
        try:
            envs, resps = self._drive(40, handler, inbox=4,
                                      budget_s=0.05)
        finally:
            t.cancel()
            release.set()
        assert len(resps) == 40, "responses must stay 1:1 in order"
        sheds = [r for r in resps
                 if r.status == cpb.Status.SERVICE_UNAVAILABLE]
        oks = [r for r in resps if r.status == cpb.Status.SUCCESS]
        assert sheds, "no shed despite a parked consumer"
        assert len(sheds) + len(oks) == 40
        # every non-shed envelope reached the handler exactly once
        n_handled = sum(len(b) for b in handler.seen)
        assert n_handled == len(oks)

    def test_quiet_stream_sheds_nothing(self):
        from fabric_tpu.protos import common as cpb
        from fabric_tpu.protos import orderer as opb

        class Echo:
            def process_messages(self, batch):
                return [opb.BroadcastResponse(
                    status=cpb.Status.SUCCESS)] * len(batch)

        _envs, resps = self._drive(25, Echo())
        assert len(resps) == 25
        assert all(r.status == cpb.Status.SUCCESS for r in resps)

    def test_ambient_deadline_reaches_the_handler(self):
        from fabric_tpu.protos import common as cpb
        from fabric_tpu.protos import orderer as opb
        seen = []

        class Probe:
            def process_messages(self, batch):
                seen.append(Deadline.current())
                return [opb.BroadcastResponse(
                    status=cpb.Status.SUCCESS)] * len(batch)

        self._drive(3, Probe(), budget_s=5.0)
        assert seen and all(d is not None for d in seen), \
            "handler must run under the ingress deadline"


class _WedgeSupport:
    channel_id = "wedge"

    def __init__(self):
        self.entered = threading.Event()
        self.release = threading.Event()
        self.written = []

    def write_block(self, block):
        self.entered.set()
        assert self.release.wait(timeout=30)
        self.written.append(block)


class _FakeBlock:
    def __init__(self, n):
        import types
        self.header = types.SimpleNamespace(number=n)


class TestWriteStageBound:
    def test_submit_bounds_then_demotes(self):
        from fabric_tpu.orderer.raft.pipeline import (
            BlockWriteStage, OrderWriteError,
        )
        sup = _WedgeSupport()
        stage = BlockWriteStage(sup, max_pending=2)
        try:
            # block 0: wait until the worker has TAKEN it into a span
            # (wedged inside write_block), so the pending fill below
            # is deterministic
            with Deadline.after(2.0).applied():
                stage.submit(_FakeBlock(0))
            assert sup.entered.wait(timeout=10)
            for n in (1, 2):        # fill the pending bound exactly
                with Deadline.after(2.0).applied():
                    stage.submit(_FakeBlock(n))
            t0 = time.monotonic()
            with Deadline.after(0.05).applied():
                with pytest.raises(OrderWriteError) as ei:
                    stage.submit(_FakeBlock(3))
            assert time.monotonic() - t0 < 2.0
            assert isinstance(ei.value.cause, OverloadError)
            assert stage.overload_stats()["sheds"] == 1
        finally:
            sup.release.set()
            stage.stop(flush=True, timeout=10)
        # everything SUBMITTED was written — a committed block is
        # never dropped by the bound (3 was refused, not lost: the
        # chain demotes and replays it from the WAL)
        assert [b.header.number for b in sup.written] == [0, 1, 2]

    def test_drains_under_saturation(self):
        """The demotion-free path: a slow-but-moving writer with the
        queue pinned at its bound still drains everything."""
        from fabric_tpu.orderer.raft.pipeline import BlockWriteStage

        class Slow:
            channel_id = "slow"

            def __init__(self):
                self.written = []

            def write_block(self, block):
                time.sleep(0.005)
                self.written.append(block.header.number)

            def write_blocks(self, blocks):
                for b in blocks:
                    self.write_block(b)

        sup = Slow()
        stage = BlockWriteStage(sup, max_pending=2)
        try:
            for n in range(20):
                stage.submit(_FakeBlock(n))
            assert stage.drain(timeout=30)
        finally:
            stage.stop(flush=True, timeout=10)
        assert sup.written == list(range(20))


class TestCommitPipelineShed:
    def _pipeline(self, tmp_path, commit_sleep=0.0):
        from fabric_tpu.core.commitpipeline import CommitPipeline

        class Result:
            codes = []
            vp_dirty = False
            duration_s = 0.0

        class Validator:
            def validate_ahead(self, block, known_txids=None):
                return Result()

            def publish_validation(self, block, result):
                pass

        class Store:
            def block_tx_ids(self, block):
                return []

        class Ledger:
            height = 1
            block_store = Store()

        class Chan:
            channel_id = "shedchan"
            ledger = Ledger()
            validator = Validator()
            release = threading.Event()

            def commit_validated(self, block, codes, rwsets=None,
                                 tx_ids=None):
                if commit_sleep:
                    time.sleep(commit_sleep)
                else:
                    assert Chan.release.wait(timeout=30)
                Ledger.height = block.header.number + 1
                return codes

            def process_block(self, block):
                return self.commit_validated(block, [])

        chan = Chan()
        return CommitPipeline(chan, mcs=None, depth=1), chan

    @staticmethod
    def _block(n):
        from fabric_tpu.protos import common as cpb
        b = cpb.Block()
        b.header.number = n
        return b

    def test_backpressure_wait_sheds_clean(self, tmp_path):
        pipeline, chan = self._pipeline(tmp_path)
        try:
            # depth 1 → at most 2 blocks in flight without a commit:
            # 1 and 2 admit immediately, 3 hits the backpressure wait
            for n in (1, 2):
                with Deadline.after(5).applied():
                    pipeline.submit(n, block=self._block(n))
            next_before = pipeline.next_seq
            with Deadline.after(0.05).applied():
                with pytest.raises(OverloadError):
                    pipeline.submit(3, block=self._block(3))
            # NON-sticky and nothing enqueued: next_seq unchanged,
            # check_error clean, the SAME submit succeeds once the
            # wedge clears
            assert pipeline.next_seq == next_before
            pipeline.check_error()
            assert pipeline.stats["sheds"] == 1
            chan.release.set()
            with Deadline.after(30).applied():
                pipeline.submit(3, block=self._block(3))
            pipeline.drain(timeout=30)
            assert pipeline.stats["committed"] == 3
        finally:
            chan.release.set()
            pipeline.stop()

    def test_demotion_path_drains_under_saturation(self, tmp_path):
        """Stage-A faults demote blocks to the sequential fallback
        while the feeder saturates the depth — everything still
        commits, sheds stay clean refusals."""
        pipeline, chan = self._pipeline(tmp_path, commit_sleep=0.003)
        faults.arm("commit.validate_ahead", mode="error", count=3)
        try:
            n = 1
            while n <= 12:
                try:
                    with Deadline.after(0.05).applied():
                        pipeline.submit(n, block=self._block(n))
                    n += 1
                except OverloadError:
                    continue        # retry the same block
            pipeline.drain(timeout=60)
            assert pipeline.stats["committed"] == 12
            assert pipeline.stats["fallbacks"] >= 1, \
                "armed faults should have demoted blocks"
        finally:
            faults.disarm("commit.validate_ahead")
            pipeline.stop()

    def test_registry_stage(self, tmp_path):
        pipeline, chan = self._pipeline(tmp_path, commit_sleep=0.0)
        try:
            assert "commit.pipeline.shedchan" in overload.stage_stats()
        finally:
            chan.release.set()
            pipeline.stop()


class TestExternalQueueBounds:
    def test_session_request_full_out_queue(self):
        from fabric_tpu.core.chaincode import external

        out: queue.Queue = queue.Queue(maxsize=1)
        out.put_nowait("occupied")
        session = external._Session("cc", None, out)
        # monkeys: shrink the 30s wait by prefilling and patching put
        t0 = time.monotonic()
        orig_put = out.put

        def fast_put(item, timeout=None):
            return orig_put(item, timeout=0.05)

        out.put = fast_put
        with pytest.raises(RuntimeError, match="send queue full"):
            session.request(object())
        assert time.monotonic() - t0 < 5

    def test_session_reply_overflow_drops_loudly(self, caplog):
        from fabric_tpu.core.chaincode import external
        M = external.M
        session = external._Session("cc", None, queue.Queue(maxsize=4))
        for _ in range(external.REPLY_QUEUE_BOUND):
            session.handle(M(type=M.RESPONSE))
        with caplog.at_level("WARNING"):
            session.handle(M(type=M.RESPONSE))   # 65th: dropped
        assert any("reply queue full" in r.message
                   for r in caplog.records)

    def test_client_send_full_queue_is_stream_error(self):
        from fabric_tpu.core.chaincode import external
        cli = external.ExternalChaincodeClient("cc", "127.0.0.1:1",
                                               timeout_s=0.05)
        cli._to_cc = queue.Queue(maxsize=1)
        cli._to_cc.put_nowait("occupied")
        with pytest.raises(external.ExternalChaincodeError,
                           match="outbound queue full"):
            cli._send(object())

    def test_queues_are_bounded(self):
        from fabric_tpu.core.chaincode import external
        cli = external.ExternalChaincodeClient("cc", "127.0.0.1:1")
        # _connect would dial; assert the declared bounds instead
        assert external.STREAM_QUEUE_BOUND > 0
        assert external.REPLY_QUEUE_BOUND > 0


class TestGossipInboxDrops:
    def test_dropped_messages_are_counted(self):
        from fabric_tpu.common import metrics as metrics_mod
        from fabric_tpu.gossip import transport as gt

        provider = metrics_mod.PrometheusProvider()
        net = gt.LocalNetwork()
        t = gt.LocalTransport(net, "drops@test", inbox_size=2,
                              metrics_provider=provider)
        try:
            # park the drain thread so the inbox genuinely fills
            t._closed.set()
            t._thread.join(timeout=5)
            for i in range(5):
                t.enqueue("sender", f"m{i}")
            stats = t._inbox.overload_stats()
            assert stats["sheds"] == 3          # 5 in, bound 2
            rendered = provider.render()
            assert "gossip_comm_overflow_count 3" in rendered
            # drop-OLDEST: the freshest survive
            assert t._inbox.get_nowait()[1] == "m3"
            assert t._inbox.get_nowait()[1] == "m4"
        finally:
            net.unregister("drops@test")

    def test_inbox_registered_as_overload_stage(self):
        from fabric_tpu.gossip import transport as gt
        net = gt.LocalNetwork()
        t = net.register("stage@test")
        try:
            assert "gossip.inbox.stage@test" in overload.stage_stats()
        finally:
            t.close()


class TestOverloadGauges:
    def test_publish_overload_stats_renders(self):
        from fabric_tpu.common import metrics as metrics_mod
        from fabric_tpu.common import profiling

        provider = metrics_mod.PrometheusProvider()
        q = SheddingQueue("t.gauges", maxsize=2,
                          default_budget_s=0.01)
        try:
            q.put("a")
            with pytest.raises(OverloadError):
                q.put("b")
                q.put("c")
            t = profiling.publish_overload_stats(provider,
                                                 poll_s=0.05)
            assert t is not None
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                r = provider.render()
                if 'overload_queue_depth{stage="t.gauges"}' in r and \
                        'overload_sheds_total{stage="t.gauges"}' in r:
                    break
                time.sleep(0.05)
            else:
                pytest.fail(f"overload gauges never rendered:\n{r}")
            assert 'overload_queue_capacity{stage="t.gauges"} 2' in r
        finally:
            overload.unregister_stage("t.gauges", q)

    def test_admission_wait_gauge_renders(self):
        from fabric_tpu.bccsp.admission import AdmissionWindow
        from fabric_tpu.common import metrics as metrics_mod
        from fabric_tpu.common import profiling

        class SW:
            stats = {"x": 1}

            def verify_batch(self, items):
                return [True] * len(items)

        provider = metrics_mod.PrometheusProvider()
        csp = SW()
        win = AdmissionWindow.shared(csp)
        win.verify_batch(["a"])
        profiling.publish_provider_stats(provider, csp, poll_s=0.05)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            r = provider.render()
            if "bccsp_admission_wait_s" in r:
                return
            time.sleep(0.05)
        pytest.fail(f"bccsp_admission_wait_s never rendered:\n{r}")

    def test_health_ok_when_quiet(self):
        # (other tests may have shed recently on shared stages; use a
        # fresh queue and assert its absence from the report)
        q = SheddingQueue("t.quiet", maxsize=2)
        try:
            q.put("a")
            assert "t.quiet" not in overload.health()
        finally:
            overload.unregister_stage("t.quiet", q)
