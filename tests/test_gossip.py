"""Gossip: discovery, election, state transfer, privdata dissemination.

Unit layers use an in-process LocalNetwork with fake crypto (the
reference tests gossip with many in-proc instances —
`gossip/gossip/gossip_test.go`); the end-to-end class runs a 2-org ×
2-peer network with real MSPs where only elected leaders talk to the
orderer and everyone else converges via gossip.
"""

import hashlib
import os
import time

import pytest

from fabric_tpu.gossip import GossipNode, GossipService, LocalNetwork
from fabric_tpu.gossip.discovery import DiscoveryConfig
from fabric_tpu.gossip.election import LeaderElectionService
from fabric_tpu.gossip.state import GossipStateProvider, PayloadBuffer
from fabric_tpu.protos import common

FAST = DiscoveryConfig(alive_interval_s=0.1, alive_expiration_s=0.6,
                       fanout=4)


class FakeSigner:
    def __init__(self, ident: bytes):
        self._ident = ident

    def sign(self, msg: bytes) -> bytes:
        return hashlib.sha256(b"sig|" + self._ident + b"|" + msg).digest()

    def serialize(self) -> bytes:
        return self._ident


class FakeMCS:
    def verify(self, identity, signature, payload) -> bool:
        return signature == hashlib.sha256(
            b"sig|" + bytes(identity) + b"|" + payload).digest()

    def verify_by_channel(self, cid, identity, signature, payload):
        return self.verify(identity, signature, payload)

    def verify_block(self, cid, seq, block) -> None:
        pass


def _mk_node(net, name, cfg=FAST):
    ident = f"identity-{name}".encode()
    return GossipNode(name, ident, FakeSigner(ident),
                      net.register(name), FakeMCS(), config=cfg)


def _wait(cond, timeout=8.0, step=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(step)
    return cond()


class TestDiscovery:
    def test_full_membership_convergence_and_death(self):
        net = LocalNetwork()
        nodes = [_mk_node(net, f"n{i}") for i in range(4)]
        try:
            for n in nodes:
                n.start(bootstrap=["n0"])
            assert _wait(lambda: all(
                len(n.discovery.alive_members()) == 3 for n in nodes)), \
                [len(n.discovery.alive_members()) for n in nodes]
            # kill n3 → the rest notice
            nodes[3].stop()
            assert _wait(lambda: all(
                len(n.discovery.alive_members()) == 2
                for n in nodes[:3]))
            dead = {m.member.endpoint
                    for m in nodes[0].discovery.dead_members()}
            assert "n3" in dead
        finally:
            for n in nodes[:3]:
                n.stop()

    def test_forged_alive_rejected(self):
        """An alive message signed with the wrong key must not enter
        membership."""
        net = LocalNetwork()
        honest = _mk_node(net, "honest")
        evil_ident = b"identity-honest2"   # claims an identity...

        class BadSigner(FakeSigner):
            def sign(self, msg):
                return b"\x00" * 32        # ...but can't sign for it

        evil = GossipNode("evil", evil_ident, BadSigner(evil_ident),
                          net.register("evil"), FakeMCS(), config=FAST)
        try:
            honest.start()
            evil.start(bootstrap=["honest"])
            time.sleep(1.0)
            eps = {m.member.endpoint
                   for m in honest.discovery.alive_members()}
            assert "evil" not in eps
        finally:
            honest.stop()
            evil.stop()

    def test_partition_heal(self):
        net = LocalNetwork()
        a, b = _mk_node(net, "a"), _mk_node(net, "b")
        try:
            a.start()
            b.start(bootstrap=["a"])
            assert _wait(lambda: len(a.discovery.alive_members()) == 1)
            net.partition("a", "b")
            assert _wait(lambda: len(a.discovery.alive_members()) == 0)
            net.heal()
            assert _wait(lambda: len(a.discovery.alive_members()) == 1
                         and len(b.discovery.alive_members()) == 1)
        finally:
            a.stop()
            b.stop()


class TestElection:
    def test_single_leader_and_failover(self):
        net = LocalNetwork()
        nodes = [_mk_node(net, f"e{i}") for i in range(3)]
        leaders: dict[str, bool] = {}
        services = []
        try:
            for n in nodes:
                n.start(bootstrap=["e0"])

            def mk(n):
                def gain():
                    leaders[n.endpoint] = True

                def lose():
                    leaders[n.endpoint] = False
                svc = LeaderElectionService(
                    n, "ch", gain, lose, propose_interval_s=0.1,
                    leader_alive_s=0.6)
                services.append(svc)
                return svc
            for n in nodes:
                mk(n).start()
            # peers learn channel membership via state-info
            for n in nodes:
                n.join_channel("ch").publish_state_info(1)
            assert _wait(lambda: sum(
                1 for v in leaders.values() if v) == 1, timeout=10)
            leader_ep = next(ep for ep, v in leaders.items() if v)
            # the smallest pki-id wins determinism isn't guaranteed in
            # the first round; what matters: exactly one leader
            idx = int(leader_ep[1])
            services[idx].stop()
            nodes[idx].stop()
            assert _wait(lambda: sum(
                1 for ep, v in leaders.items()
                if v and ep != leader_ep) == 1, timeout=10)
        finally:
            for i, n in enumerate(nodes):
                try:
                    services[i].stop()
                    n.stop()
                except Exception:
                    pass


class _FakeChannel:
    """Duck-type of peer.Channel for state-transfer tests."""

    def __init__(self, channel_id="ch"):
        self.channel_id = channel_id
        self.blocks: list[common.Block] = []

    @property
    def ledger(self):
        return self

    @property
    def height(self):
        return len(self.blocks)

    def get_block(self, num):
        return self.blocks[num] if num < len(self.blocks) else None

    def process_block(self, block):
        assert block.header.number == len(self.blocks)
        self.blocks.append(block)

    def wait_for_height(self, h, timeout=None):
        return _wait(lambda: self.height >= h, timeout or 5)


def _block(num: int) -> common.Block:
    b = common.Block()
    b.header.number = num
    b.data.data.append(f"payload-{num}".encode())
    return b


class TestStateTransfer:
    def test_payload_buffer_orders(self):
        buf = PayloadBuffer()
        buf.set_next(5)
        buf.push(7, b"seven")
        buf.push(5, b"five")
        buf.push(3, b"stale")     # below next: dropped
        assert buf.pop() == (5, b"five")
        assert buf.pop() is None  # 6 missing
        buf.push(6, b"six")
        assert buf.pop() == (6, b"six")
        assert buf.pop() == (7, b"seven")

    def test_anti_entropy_catchup_and_push(self):
        net = LocalNetwork()
        na, nb = _mk_node(net, "sa"), _mk_node(net, "sb")
        ca, cb = _FakeChannel(), _FakeChannel()
        for i in range(6):
            ca.blocks.append(_block(i))
        sa = GossipStateProvider(na, "ch", ca, FakeMCS(),
                                 anti_entropy_interval_s=0.1)
        sb = GossipStateProvider(nb, "ch", cb, FakeMCS(),
                                 anti_entropy_interval_s=0.1)
        try:
            na.start()
            nb.start(bootstrap=["sa"])
            sa.start()
            sb.start()
            # anti-entropy alone must pull all 6 blocks to b
            assert _wait(lambda: cb.height == 6, timeout=10), cb.height
            # now a NEW block pushed on a reaches b via data gossip
            blk = _block(6)
            ca.process_block(blk)
            sa.add_local_block(blk)
            assert _wait(lambda: cb.height == 7, timeout=10), cb.height
        finally:
            sa.stop()
            sb.stop()
            na.stop()
            nb.stop()


# ---------------------------------------------------------------------------
# End-to-end: 2 orgs × 2 peers, leaders pull from orderer, gossip
# spreads blocks + private data.
# ---------------------------------------------------------------------------

from fabric_tpu.bccsp.sw import SWProvider          # noqa: E402
from fabric_tpu.common.deliver import DeliverHandler  # noqa: E402
from fabric_tpu.common.policies.policydsl import from_string  # noqa: E402
from fabric_tpu.core.chaincode import (             # noqa: E402
    Chaincode, ChaincodeDefinition, shim,
)
from fabric_tpu.internal import cryptogen           # noqa: E402
from fabric_tpu.internal.configtxgen import (       # noqa: E402
    genesis_block, new_channel_group,
)
from fabric_tpu.ledger import CollectionConfig      # noqa: E402
from fabric_tpu.msp import msp_config_from_dir      # noqa: E402
from fabric_tpu.msp.mspimpl import X509MSP          # noqa: E402
from fabric_tpu.orderer import solo                 # noqa: E402
from fabric_tpu.orderer.broadcast import BroadcastHandler  # noqa: E402
from fabric_tpu.orderer.multichannel import Registrar      # noqa: E402
from fabric_tpu.peer import Peer                    # noqa: E402
from fabric_tpu.peer.deliverclient import Deliverer  # noqa: E402
from fabric_tpu.peer.gateway import Gateway         # noqa: E402
from fabric_tpu.protos import policies as polpb     # noqa: E402
from fabric_tpu.protos import transaction as txpb   # noqa: E402

CHANNEL = "gossipchannel"


class SecretCC(Chaincode):
    def init(self, stub):
        return shim.success()

    def invoke(self, stub):
        fn, params = stub.get_function_and_parameters()
        if fn == "put":
            stub.put_state(params[0], b"public")
            stub.put_private_data("secrets", params[0],
                                  stub.get_transient()["v"])
            return shim.success()
        return shim.error("unknown")


@pytest.fixture(scope="class")
def gossip_net(tmp_path_factory):
    root = tmp_path_factory.mktemp("gnet")
    cdir = str(root / "crypto")
    org1 = cryptogen.generate_org(cdir, "org1.example.com", n_peers=2,
                                  n_users=1)
    org2 = cryptogen.generate_org(cdir, "org2.example.com", n_peers=2)
    ordo = cryptogen.generate_org(cdir, "example.com", orderer_org=True)
    profile = {
        "Consortium": "SampleConsortium",
        "Capabilities": {"V2_0": True},
        "Application": {
            "Organizations": [
                {"Name": "Org1", "ID": "Org1MSP",
                 "MSPDir": os.path.join(org1, "msp")},
                {"Name": "Org2", "ID": "Org2MSP",
                 "MSPDir": os.path.join(org2, "msp")},
            ],
            "Capabilities": {"V2_0": True},
        },
        "Orderer": {
            "OrdererType": "solo",
            "Addresses": ["orderer0.example.com:7050"],
            "BatchTimeout": "150ms",
            "BatchSize": {"MaxMessageCount": 10},
            "Organizations": [
                {"Name": "OrdererOrg", "ID": "OrdererMSP",
                 "MSPDir": os.path.join(ordo, "msp"),
                 "OrdererEndpoints": ["orderer0.example.com:7050"]},
            ],
            "Capabilities": {"V2_0": True},
        },
    }
    genesis = genesis_block(CHANNEL, new_channel_group(profile))
    csp = SWProvider()

    def local_msp(msp_dir, mspid):
        m = X509MSP(csp)
        m.setup(msp_config_from_dir(msp_dir, mspid, csp=csp))
        return m

    orderer_msp = local_msp(
        os.path.join(ordo, "orderers", "orderer0.example.com", "msp"),
        "OrdererMSP")
    registrar = Registrar(str(root / "orderer"),
                          orderer_msp.get_default_signing_identity(),
                          csp, {"solo": solo.consenter})
    registrar.join(genesis)
    broadcast = BroadcastHandler(registrar)
    deliver = DeliverHandler(registrar.get_chain)

    definition = ChaincodeDefinition(
        name="secretcc",
        endorsement_policy=polpb.ApplicationPolicy(
            signature_policy=from_string(
                "OR('Org1MSP.member', 'Org2MSP.member')")
        ).SerializeToString(),
        collections=(
            CollectionConfig(name="secrets",
                             member_orgs=("Org1MSP",)),
        ))

    net = LocalNetwork()
    peers, services = {}, []
    for org_name, org_dir, mspid in (("org1", org1, "Org1MSP"),
                                     ("org2", org2, "Org2MSP")):
        for pi in range(2):
            ep = f"peer{pi}.{org_name}.example.com:7051"
            msp = local_msp(
                os.path.join(org_dir, "peers",
                             f"peer{pi}.{org_name}.example.com", "msp"),
                mspid)
            peer = Peer(str(root / f"peer_{org_name}_{pi}"), msp, csp)
            channel = peer.join_channel(genesis)
            peer.chaincode_support.register("secretcc", SecretCC())
            channel.define_chaincode(definition)
            # generous expiration: the full suite runs on few cores
            # and a starved scheduler must not flap membership
            # mid-test (death detection has its own dedicated tests)
            gs = GossipService(peer, net.register(ep), peer.mcs,
                               org_id=mspid,
                               config=DiscoveryConfig(
                                   alive_interval_s=0.2,
                                   alive_expiration_s=6.0, fanout=4))
            peer.gossip_service = gs
            gs.start(bootstrap=["peer0.org1.example.com:7051"])
            gs.initialize_channel(
                channel,
                lambda adapter: Deliverer(adapter, peer.signer,
                                          lambda: deliver, peer.mcs))
            peers[f"{org_name}_{pi}"] = peer
            services.append(gs)

    user_msp = local_msp(
        os.path.join(org1, "users", "User1@org1.example.com", "msp"),
        "Org1MSP")
    gateway = Gateway(peers["org1_0"], broadcast,
                      user_msp.get_default_signing_identity())
    yield {"peers": peers, "gateway": gateway, "services": services,
           "net": net}
    for gs in services:
        gs.stop()
    registrar.halt()
    for p in peers.values():
        p.close()


@pytest.mark.usefixtures("gossip_net")
class TestGossipEndToEnd:
    def test_block_and_pvtdata_dissemination(self, gossip_net):
        gw = gossip_net["gateway"]
        # wait for election so at least one deliverer is live
        assert _wait(lambda: any(
            r.deliverer is not None
            for gs in gossip_net["services"]
            for r in gs._channels.values()), timeout=15)
        res = gw.submit_transaction(
            CHANNEL, "secretcc", [b"put", b"k1"],
            transient={"v": b"org1-only-secret"},
            endorsing_peers=[gossip_net["peers"]["org1_0"]])
        assert res.status == txpb.TxValidationCode.VALID
        # ALL FOUR peers converge on the block via gossip
        assert _wait(lambda: all(
            p.channel(CHANNEL).ledger.get_state("secretcc", "k1")
            == b"public"
            for p in gossip_net["peers"].values()), timeout=20), \
            {k: p.channel(CHANNEL).ledger.height
             for k, p in gossip_net["peers"].items()}

        # cleartext: org1 peers only (push at endorsement to the
        # non-endorsing org1 peer; reconciler covers stragglers)
        def cleartext(p):
            return p.channel(CHANNEL).ledger.get_private_data(
                "secretcc", "secrets", "k1")
        assert _wait(lambda: cleartext(
            gossip_net["peers"]["org1_1"]) == b"org1-only-secret",
            timeout=20)
        assert cleartext(gossip_net["peers"]["org1_0"]) == \
            b"org1-only-secret"
        for k in ("org2_0", "org2_1"):
            assert cleartext(gossip_net["peers"][k]) is None
            # but the hash is everywhere
            assert gossip_net["peers"][k].channel(
                CHANNEL).ledger.get_private_data_hash(
                "secretcc", "secrets", "k1") is not None

    def test_exactly_one_deliverer_per_network(self, gossip_net):
        # elections are per-channel across the whole network here (one
        # LocalNetwork = one org boundary-less fabric); the invariant:
        # a single leader pulls from the orderer at any moment
        def count():
            return sum(1 for gs in gossip_net["services"]
                       for r in gs._channels.values()
                       if r.deliverer is not None)
        assert _wait(lambda: count() == 1, timeout=15), {
            "deliverers": [
                gs.node.endpoint for gs in gossip_net["services"]
                for r in gs._channels.values()
                if r.deliverer is not None],
            "views": {
                gs.node.endpoint: {
                    "is_leader": r.election.is_leader,
                    "leader": (r.election.leader or b"").hex()[:8],
                    "alive": sorted(
                        m.member.endpoint for m in
                        gs.node.discovery.alive_members()),
                }
                for gs in gossip_net["services"]
                for r in gs._channels.values()},
        }

    def test_reconciler_backfills_late_peer(self, gossip_net):
        """A peer partitioned during endorsement misses the pvt push;
        after healing, the reconciler fetches the cleartext."""
        net = gossip_net["net"]
        gw = gossip_net["gateway"]
        late = "peer1.org1.example.com:7051"
        for other in list(net.endpoints()):
            if other != late:
                net.partition(late, other)
        try:
            res = gw.submit_transaction(
                CHANNEL, "secretcc", [b"put", b"k2"],
                transient={"v": b"late-secret"},
                endorsing_peers=[gossip_net["peers"]["org1_0"]])
            assert res.status == txpb.TxValidationCode.VALID
        finally:
            net.heal()
        late_peer = gossip_net["peers"]["org1_1"]
        late_gs = next(
            gs for gs in gossip_net["services"]
            if gs.node.endpoint == late)
        provider = late_gs._channels[CHANNEL].privdata

        # block arrives post-heal; cleartext was missed → ledger
        # records the gap → the reconciler pulls it from org1_0
        # (driven explicitly here so the test isn't hostage to wall-
        # clock timer alignment under CI load)
        def reconciled():
            val = late_peer.channel(CHANNEL).ledger.get_private_data(
                "secretcc", "secrets", "k2")
            if val == b"late-secret":
                return True
            provider.reconcile_once()
            return False
        led = late_peer.channel(CHANNEL).ledger
        assert _wait(reconciled, timeout=90, step=0.5), {
            "height": led.height,
            "missing": [(m.block_num, m.tx_num, m.namespace,
                         m.collection)
                        for m in led.missing_pvt_data(16)],
            "members": [m.member.endpoint
                        for m in late_gs.node.channel(CHANNEL)
                        .members()],
            "alive": [m.member.endpoint for m in
                      late_gs.node.discovery.alive_members()],
            "late_stats": dict(provider.stats),
            "peer_stats": {
                gs.node.endpoint: dict(
                    gs._channels[CHANNEL].privdata.stats)
                for gs in gossip_net["services"]
                if gs.node.endpoint != late},
        }
