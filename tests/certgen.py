"""Test helper: minimal X.509 material (self-signed CAs, leaf certs).

Stands in for the reference's `cryptogen`-generated fixtures until the
fabric_tpu.tools.cryptogen equivalent exists; kept separate so MSP and
BCCSP tests share one generator.
"""

from __future__ import annotations

import datetime

from cryptography import x509
from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

_NOT_BEFORE = datetime.datetime(2020, 1, 1)
_NOT_AFTER = datetime.datetime(2099, 1, 1)


def _name(cn: str, org: str | None = None) -> x509.Name:
    attrs = [x509.NameAttribute(NameOID.COMMON_NAME, cn)]
    if org:
        attrs.append(x509.NameAttribute(NameOID.ORGANIZATION_NAME, org))
    return x509.Name(attrs)


def make_self_signed(cn: str):
    """Self-signed cert + private key (CA:TRUE)."""
    priv = ec.generate_private_key(ec.SECP256R1())
    cert = (
        x509.CertificateBuilder()
        .subject_name(_name(cn))
        .issuer_name(_name(cn))
        .public_key(priv.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(_NOT_BEFORE)
        .not_valid_after(_NOT_AFTER)
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .add_extension(
            x509.KeyUsage(digital_signature=True, content_commitment=False,
                          key_encipherment=False, data_encipherment=False,
                          key_agreement=False, key_cert_sign=True,
                          crl_sign=True, encipher_only=False,
                          decipher_only=False),
            critical=True)
        .sign(priv, hashes.SHA256())
    )
    return cert, priv


def make_leaf(cn: str, ca_cert, ca_priv, org: str | None = None,
              ou: str | None = None, not_after=None,
              sans: list[str] | None = None):
    """Leaf cert signed by the given CA (CA:FALSE), optional OU.
    `sans` adds DNS SubjectAlternativeNames (TLS hostname checks)."""
    priv = ec.generate_private_key(ec.SECP256R1())
    attrs = [x509.NameAttribute(NameOID.COMMON_NAME, cn)]
    if org:
        attrs.append(x509.NameAttribute(NameOID.ORGANIZATION_NAME, org))
    if ou:
        attrs.append(x509.NameAttribute(NameOID.ORGANIZATIONAL_UNIT_NAME, ou))
    builder = (
        x509.CertificateBuilder()
        .subject_name(x509.Name(attrs))
        .issuer_name(ca_cert.subject)
        .public_key(priv.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(_NOT_BEFORE)
        .not_valid_after(not_after or _NOT_AFTER)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                       critical=True)
    )
    if sans:
        builder = builder.add_extension(
            x509.SubjectAlternativeName(
                [x509.DNSName(s) for s in sans]),
            critical=False)
    cert = builder.sign(ca_priv, hashes.SHA256())
    return cert, priv


def make_intermediate(cn: str, ca_cert, ca_priv):
    """Intermediate CA signed by a root (CA:TRUE)."""
    priv = ec.generate_private_key(ec.SECP256R1())
    cert = (
        x509.CertificateBuilder()
        .subject_name(_name(cn))
        .issuer_name(ca_cert.subject)
        .public_key(priv.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(_NOT_BEFORE)
        .not_valid_after(_NOT_AFTER)
        .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                       critical=True)
        .sign(ca_priv, hashes.SHA256())
    )
    return cert, priv


def make_crl(ca_cert, ca_priv, revoked_serials):
    """CRL issued by the given CA revoking the given serial numbers."""
    builder = (
        x509.CertificateRevocationListBuilder()
        .issuer_name(ca_cert.subject)
        .last_update(_NOT_BEFORE)
        .next_update(_NOT_AFTER)
    )
    for serial in revoked_serials:
        builder = builder.add_revoked_certificate(
            x509.RevokedCertificateBuilder()
            .serial_number(serial)
            .revocation_date(_NOT_BEFORE)
            .build()
        )
    return builder.sign(ca_priv, hashes.SHA256())


def pem(obj) -> bytes:
    """PEM-encode a cert or CRL."""
    from cryptography.hazmat.primitives.serialization import Encoding
    return obj.public_bytes(Encoding.PEM)
