"""BN254 pairing reference tests — algebraic-law pinning.

The int reference (fabric_tpu/ops/bn254_ref.py) is the oracle for the
TPU pairing kernels, so its own correctness is established here by the
defining laws of a pairing: bilinearity in both arguments,
non-degeneracy on the generators, and unit output at infinity. A buggy
Miller loop or tower cannot satisfy bilinearity for random scalars.
"""

import random

import pytest

from fabric_tpu.ops import bn254_ref as bn

rng = random.Random(271828)


class TestTower:
    def test_f2_f6_f12_inverses(self):
        for _ in range(3):
            a2 = (rng.randrange(bn.P), rng.randrange(1, bn.P))
            assert bn.f2_mul(a2, bn.f2_inv(a2)) == bn.F2_ONE
            a6 = tuple((rng.randrange(bn.P), rng.randrange(bn.P))
                       for _ in range(3))
            assert bn.f6_mul(a6, bn.f6_inv(a6)) == bn.F6_ONE
            a12 = (a6, tuple((rng.randrange(bn.P), rng.randrange(bn.P))
                             for _ in range(3)))
            assert bn.f12_mul(a12, bn.f12_inv(a12)) == bn.F12_ONE

    def test_w_squared_is_v(self):
        # w^2 = v: (0,1,0) in the Fp6 c-basis of the first component
        assert bn.F12_W2 == ((bn.F2_ZERO, bn.F2_ONE, bn.F2_ZERO),
                             bn.F6_ZERO)


class TestCurve:
    def test_generators_on_curve(self):
        assert bn.on_curve_g1(bn.G1)
        assert bn.on_curve_g2((bn.G2_X, bn.G2_Y))

    def test_generators_have_order_r(self):
        assert bn.ec_mul(bn.R, bn.g1_embed(bn.G1)) is None
        assert bn.ec_mul(bn.R, bn.untwist((bn.G2_X, bn.G2_Y))) is None


class TestFinalExpChain:
    def test_chain_equals_single_pow(self):
        """The structured easy+hard chain (what the device transcribes)
        must equal f^((p^12-1)/r) exactly."""
        rng = random.Random(17)
        for _ in range(2):
            f = tuple(tuple((rng.randrange(bn.P), rng.randrange(bn.P))
                            for _ in range(3)) for _ in range(2))
            assert bn.final_exponentiation_chain(f) == \
                bn.final_exponentiation(f)

    def test_chain_lands_in_cyclotomic_subgroup(self):
        rng = random.Random(18)
        f = tuple(tuple((rng.randrange(bn.P), rng.randrange(bn.P))
                        for _ in range(3)) for _ in range(2))
        out = bn.final_exponentiation_chain(f)
        # order divides r: out^r == 1
        assert bn.f12_pow(out, bn.R) == bn.F12_ONE


@pytest.mark.slow
class TestPairing:
    def test_bilinearity_and_nondegeneracy(self):
        q = (bn.G2_X, bn.G2_Y)
        e = bn.pairing(q, bn.G1)
        assert e != bn.F12_ONE, "pairing is degenerate"
        # e(aP, bQ) == e(P, Q)^(ab)
        a = rng.randrange(2, 1 << 40)
        b = rng.randrange(2, 1 << 40)
        ap = bn.ec_mul(a, bn.g1_embed(bn.G1))
        ap = (ap[0][0][0][0], ap[1][0][0][0])     # back to Fp coords
        bq = bn.g2_mul(b, q)
        lhs = bn.pairing(bq, ap)
        rhs = bn.f12_pow(e, a * b % bn.R)
        assert lhs == rhs, "bilinearity violated"

    def test_infinity_maps_to_one(self):
        q = (bn.G2_X, bn.G2_Y)
        assert bn.miller_loop(None, bn.G1) == bn.F12_ONE
        assert bn.miller_loop(q, None) == bn.F12_ONE


class TestG2SubgroupCheck:
    """Verifier-facing G2 deserialization must reject on-twist points
    outside the prime-order subgroup (round-4 advisor, medium: the
    invalid-point/small-subgroup footgun on idemix presentation
    inputs; the reference's amcl/gurvy stacks reject these at
    deserialization)."""

    # on E'(Fp2) (checked below) but NOT in the order-R subgroup:
    # x = 2 + u, y = sqrt(x^3 + 3/(9+u)), found by try-and-increment
    NON_SUBGROUP = (
        (2, 1),
        (7292567877523311580221095596750716176434782432868683424513645834767876293070,
         19659275751359636165940301690575149581329631496732780143538578556285923319774),
    )

    def test_point_is_on_twist_but_rejected(self):
        q = self.NON_SUBGROUP
        assert bn.on_curve_g2(q)
        assert not bn.g2_in_subgroup(q)
        with pytest.raises(ValueError, match="subgroup"):
            bn.g2_from_bytes(bn.g2_to_bytes(q))

    def test_subgroup_points_accepted(self):
        g2 = (bn.G2_X, bn.G2_Y)
        assert bn.g2_in_subgroup(g2)
        assert bn.g2_in_subgroup(None)
        q = bn.g2_mul_fast(987654321123456789, g2)
        assert bn.g2_in_subgroup(q)
        assert bn.g2_from_bytes(bn.g2_to_bytes(q)) == q

    def test_frobenius_test_matches_full_order_test(self):
        """psi(Q) == [6x^2]Q must agree with the unreduced [R]Q == inf
        oracle (g2_mul_fast reduces k mod R, so the oracle is built
        from adds)."""
        def mul_nored(k, q):
            acc = None
            for bit in bin(k)[2:]:
                acc = bn.g2_add_fast(acc, acc) if acc else None
                if bit == "1":
                    acc = bn.g2_add_fast(acc, q)
            return acc

        g2 = (bn.G2_X, bn.G2_Y)
        for q in (g2, bn.g2_mul_fast(31337, g2), self.NON_SUBGROUP):
            assert bn.g2_in_subgroup(q) == (mul_nored(bn.R, q) is None)
