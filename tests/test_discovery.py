"""Discovery: policy inquiry, layouts, the discovery service, and
gateway endorsement planning.

Reference: `common/policies/inquire`, `discovery/{service.go,
endorsement/endorsement.go}`, `internal/pkg/gateway` planFromLayouts.
"""

import os
import time

import pytest

from fabric_tpu.common.policies.inquire import (
    InquireError, layouts_from_envelope, principal_sets,
)
from fabric_tpu.common.policies.policydsl import from_string
from fabric_tpu.protos import discovery as dpb, policies as polpb


class TestInquire:
    def test_or_yields_singleton_sets(self):
        env = from_string("OR('A.member', 'B.member')")
        sets = principal_sets(env)
        assert len(sets) == 2
        assert all(len(s) == 1 for s in sets)

    def test_and_yields_one_combined_set(self):
        env = from_string("AND('A.member', 'B.member')")
        sets = principal_sets(env)
        assert len(sets) == 1 and len(sets[0]) == 2

    def test_outof_combinations(self):
        env = from_string(
            "OutOf(2, 'A.member', 'B.member', 'C.member')")
        sets = principal_sets(env)
        assert len(sets) == 3
        assert all(len(s) == 2 for s in sets)

    def test_nested_policy(self):
        env = from_string(
            "AND('A.member', OR('B.member', 'C.member'))")
        layouts = layouts_from_envelope(env)
        assert {tuple(sorted(d)) for d in layouts} == \
            {("A", "B"), ("A", "C")}

    def test_layouts_minimal_first_and_deduped(self):
        env = from_string("OR('A.member', AND('A.member', 'B.member'))")
        layouts = layouts_from_envelope(env)
        assert layouts[0] == {"A": 1}

    def test_duplicate_org_needs_two_signatures(self):
        env = from_string("AND('A.member', 'A.admin')")
        layouts = layouts_from_envelope(env)
        assert layouts == [{"A": 2}]

    def test_blowup_capped(self):
        names = ", ".join(f"'O{i}.member'" for i in range(30))
        env = from_string(f"OutOf(15, {names})")
        with pytest.raises(InquireError):
            principal_sets(env)


# ---------------------------------------------------------------------------
# Service + planner over an in-proc gossip network
# ---------------------------------------------------------------------------

from fabric_tpu.bccsp.sw import SWProvider          # noqa: E402
from fabric_tpu.common.deliver import DeliverHandler  # noqa: E402
from fabric_tpu.core.chaincode import (             # noqa: E402
    Chaincode, ChaincodeDefinition, shim,
)
from fabric_tpu.discovery import DiscoveryService   # noqa: E402
from fabric_tpu.gossip import GossipService, LocalNetwork  # noqa: E402
from fabric_tpu.gossip.discovery import DiscoveryConfig  # noqa: E402
from fabric_tpu.internal import cryptogen           # noqa: E402
from fabric_tpu.internal.configtxgen import (       # noqa: E402
    genesis_block, new_channel_group,
)
from fabric_tpu.msp import msp_config_from_dir      # noqa: E402
from fabric_tpu.msp.mspimpl import X509MSP          # noqa: E402
from fabric_tpu.orderer import solo                 # noqa: E402
from fabric_tpu.orderer.broadcast import BroadcastHandler  # noqa: E402
from fabric_tpu.orderer.multichannel import Registrar      # noqa: E402
from fabric_tpu.peer import Peer                    # noqa: E402
from fabric_tpu.peer.deliverclient import Deliverer  # noqa: E402
from fabric_tpu.peer.gateway import Gateway         # noqa: E402
from fabric_tpu.protos import transaction as txpb   # noqa: E402
from fabric_tpu.protoutil import protoutil as pu    # noqa: E402

CHANNEL = "discochannel"


class CC(Chaincode):
    def init(self, stub):
        return shim.success()

    def invoke(self, stub):
        fn, params = stub.get_function_and_parameters()
        if fn == "put":
            stub.put_state(params[0], params[1].encode())
            return shim.success()
        return shim.error("unknown")


def _wait(cond, timeout=10.0, step=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(step)
    return cond()


@pytest.fixture(scope="class")
def disco_net(tmp_path_factory):
    root = tmp_path_factory.mktemp("disco")
    cdir = str(root / "crypto")
    org1 = cryptogen.generate_org(cdir, "org1.example.com", n_peers=1,
                                  n_users=1)
    org2 = cryptogen.generate_org(cdir, "org2.example.com", n_peers=1)
    ordo = cryptogen.generate_org(cdir, "example.com", orderer_org=True)
    profile = {
        "Consortium": "SampleConsortium",
        "Capabilities": {"V2_0": True},
        "Application": {
            "Organizations": [
                {"Name": "Org1", "ID": "Org1MSP",
                 "MSPDir": os.path.join(org1, "msp")},
                {"Name": "Org2", "ID": "Org2MSP",
                 "MSPDir": os.path.join(org2, "msp")},
            ],
            "Capabilities": {"V2_0": True},
        },
        "Orderer": {
            "OrdererType": "solo",
            "Addresses": ["orderer0.example.com:7050"],
            "BatchTimeout": "100ms",
            "BatchSize": {"MaxMessageCount": 10},
            "Organizations": [
                {"Name": "OrdererOrg", "ID": "OrdererMSP",
                 "MSPDir": os.path.join(ordo, "msp"),
                 "OrdererEndpoints": ["orderer0.example.com:7050"]}],
            "Capabilities": {"V2_0": True},
        },
    }
    genesis = genesis_block(CHANNEL, new_channel_group(profile))
    csp = SWProvider()

    def local_msp(d, mspid):
        m = X509MSP(csp)
        m.setup(msp_config_from_dir(d, mspid, csp=csp))
        return m

    omsp = local_msp(os.path.join(ordo, "orderers",
                                  "orderer0.example.com", "msp"),
                     "OrdererMSP")
    reg = Registrar(str(root / "ord"),
                    omsp.get_default_signing_identity(), csp,
                    {"solo": solo.consenter})
    reg.join(genesis)
    bc = BroadcastHandler(reg)
    dh = DeliverHandler(reg.get_chain)

    net = LocalNetwork()
    peers, services, deliverers = {}, {}, []
    for org_name, org_dir, mspid in (("org1", org1, "Org1MSP"),
                                     ("org2", org2, "Org2MSP")):
        ep = f"peer0.{org_name}.example.com:7051"
        msp = local_msp(
            os.path.join(org_dir, "peers",
                         f"peer0.{org_name}.example.com", "msp"),
            mspid)
        peer = Peer(str(root / f"p_{org_name}"), msp, csp)
        ch = peer.join_channel(genesis)
        peer.chaincode_support.register("cc", CC())
        ch.define_chaincode(ChaincodeDefinition(name="cc"))
        gs = GossipService(peer, net.register(ep), peer.mcs,
                           org_id=mspid,
                           config=DiscoveryConfig(
                               alive_interval_s=0.1,
                               alive_expiration_s=0.8, fanout=4))
        peer.gossip_service = gs
        gs.start(bootstrap=["peer0.org1.example.com:7051"])
        gs.initialize_channel(
            ch, lambda adapter, p=peer: Deliverer(
                adapter, p.signer, lambda: dh, p.mcs))
        peers[org_name] = peer
        services[org_name] = gs

    user = local_msp(os.path.join(org1, "users",
                                  "User1@org1.example.com", "msp"),
                     "Org1MSP").get_default_signing_identity()
    disco = DiscoveryService(peers["org1"], services["org1"])
    # wait for cross-org membership
    assert _wait(lambda: len(
        services["org1"].node.channel(CHANNEL).members()) >= 1,
        timeout=15)
    yield {"disco": disco, "peers": peers, "user": user,
           "services": services, "bc": bc, "root": root,
           "org2_dir": org2}
    for gs in services.values():
        gs.stop()
    reg.halt()
    for p in peers.values():
        p.close()


def _signed_request(user, query) -> dpb.SignedRequest:
    req = dpb.Request(authentication=user.serialize())
    req.queries.add().CopyFrom(query)
    payload = req.SerializeToString()
    return dpb.SignedRequest(payload=payload,
                             signature=user.sign(payload))


@pytest.mark.usefixtures("disco_net")
class TestDiscoveryService:
    def test_peer_membership_query(self, disco_net):
        q = dpb.Query(channel=CHANNEL)
        q.peer_query.SetInParent()
        resp = disco_net["disco"].process(
            _signed_request(disco_net["user"], q))
        peers = resp.results[0].members.peers
        orgs = {p.msp_id for p in peers}
        assert orgs == {"Org1MSP", "Org2MSP"}

    def test_config_query(self, disco_net):
        q = dpb.Query(channel=CHANNEL)
        q.config_query.SetInParent()
        resp = disco_net["disco"].process(
            _signed_request(disco_net["user"], q))
        cfg = resp.results[0].config_result
        assert set(cfg.msps) == {"Org1", "Org2", "OrdererOrg"}
        assert "orderer0.example.com:7050" in cfg.orderer_endpoints

    def test_endorsers_query_default_majority(self, disco_net):
        q = dpb.Query(channel=CHANNEL)
        q.cc_query.interests.add().chaincodes.add(name="cc")
        resp = disco_net["disco"].process(
            _signed_request(disco_net["user"], q))
        desc = resp.results[0].cc_query_res.descriptors[0]
        assert desc.chaincode == "cc"
        # MAJORITY of 2 orgs = both
        assert len(desc.layouts) >= 1
        lay = dict(desc.layouts[0].quantities_by_org)
        assert lay == {"Org1MSP": 1, "Org2MSP": 1}
        assert set(desc.endorsers_by_org) == {"Org1MSP", "Org2MSP"}

    def test_unknown_channel_and_denied_access(self, disco_net,
                                               tmp_path):
        q = dpb.Query(channel="nope")
        q.peer_query.SetInParent()
        resp = disco_net["disco"].process(
            _signed_request(disco_net["user"], q))
        assert "not found" in resp.results[0].error.content

        outsider_dir = cryptogen.generate_org(
            str(tmp_path), "evil.example.com", n_peers=1, n_users=1)
        csp = SWProvider()
        msp = X509MSP(csp)
        msp.setup(msp_config_from_dir(
            os.path.join(outsider_dir, "users",
                         "User1@evil.example.com", "msp"),
            "EvilMSP", csp=csp))
        evil = msp.get_default_signing_identity()
        q = dpb.Query(channel=CHANNEL)
        q.peer_query.SetInParent()
        resp = disco_net["disco"].process(_signed_request(evil, q))
        assert resp.results[0].error.content == "access denied"

    def test_gateway_plans_minimal_layout(self, disco_net):
        """An OR policy chaincode needs ONE org: the planner must not
        fan out to both."""
        from fabric_tpu.protoutil import txutils
        peers = disco_net["peers"]
        app = polpb.ApplicationPolicy(
            signature_policy=from_string(
                "OR('Org1MSP.member', 'Org2MSP.member')"))
        definition = ChaincodeDefinition(
            name="cc", endorsement_policy=app.SerializeToString())
        for p in peers.values():
            p.channel(CHANNEL).define_chaincode(definition)

        disco = disco_net["disco"]
        gw = Gateway(peers["org1"], disco_net["bc"])
        gw.endorsers["Org1MSP"] = peers["org1"].endorser
        gw.endorsers["Org2MSP"] = peers["org2"].endorser
        gw.layout_source = (
            lambda cid, cc: disco.chaincode_layouts(
                peers["org1"].channel(cid), cc))

        user = disco_net["user"]
        prop, tx_id = txutils.create_proposal(
            CHANNEL, "cc", [b"put", b"x", b"1"], user.serialize())
        sp = txutils.sign_proposal(prop, user)
        env = gw.endorse_signed(CHANNEL, sp)
        action = pu.get_payload(env)
        tx = txpb.Transaction()
        tx.ParseFromString(action.data)
        cap = txpb.ChaincodeActionPayload()
        cap.ParseFromString(tx.actions[0].payload)
        assert len(cap.action.endorsements) == 1
