"""MSP tests — mirrors the reference's msp package tests
(`msp/msp_test.go`, `msp/cache/cache_test.go` shape): setup, chain
validation, revocation, principal matching, manager routing, cache."""

import datetime

import pytest

from fabric_tpu.bccsp.sw import SWProvider
from fabric_tpu.msp import CachedMSP, Manager, X509MSP, build_msp_config
from fabric_tpu.msp.mspimpl import MSPError, PrincipalNotSatisfied
from fabric_tpu.protos import msp as msppb, policies as polpb
from tests import certgen


@pytest.fixture(scope="module")
def org1():
    """Org1: root CA, intermediate CA, member leaf, admin leaf,
    OU-classified peer leaf, revoked leaf."""
    root, root_key = certgen.make_self_signed("org1-root-ca")
    inter, inter_key = certgen.make_intermediate("org1-inter-ca",
                                                 root, root_key)
    member, member_key = certgen.make_leaf("user1", inter, inter_key)
    admin, admin_key = certgen.make_leaf("admin1", inter, inter_key)
    peer, peer_key = certgen.make_leaf("peer0", inter, inter_key, ou="peer")
    client, client_key = certgen.make_leaf("client3", inter, inter_key,
                                           ou="client")
    revoked, revoked_key = certgen.make_leaf("bad-user", inter, inter_key)
    crl = certgen.make_crl(inter, inter_key, [revoked.serial_number])
    return {
        "root": (root, root_key), "inter": (inter, inter_key),
        "member": (member, member_key), "admin": (admin, admin_key),
        "peer": (peer, peer_key), "client": (client, client_key),
        "revoked": (revoked, revoked_key), "crl": crl,
    }


def _msp_for(org1, node_ous=False, with_crl=True) -> X509MSP:
    csp = SWProvider()
    nodeous = None
    if node_ous:
        nodeous = msppb.NodeOUs(enable=True)
        nodeous.peer_ou_identifier.organizational_unit_identifier = "peer"
        nodeous.client_ou_identifier.organizational_unit_identifier = "client"
        nodeous.admin_ou_identifier.organizational_unit_identifier = "admin"
    config = build_msp_config(
        name="Org1MSP",
        root_certs=[certgen.pem(org1["root"][0])],
        intermediate_certs=[certgen.pem(org1["inter"][0])],
        admins=[certgen.pem(org1["admin"][0])],
        revocation_list=[certgen.pem(org1["crl"])] if with_crl else [],
        node_ous=nodeous,
    )
    msp = X509MSP(csp)
    msp.setup(config)
    return msp


def _sid(cert) -> bytes:
    sid = msppb.SerializedIdentity()
    sid.mspid = "Org1MSP"
    sid.id_bytes = certgen.pem(cert)
    return sid.SerializeToString(deterministic=True)


def _role_principal(mspid, role) -> polpb.MSPPrincipal:
    p = polpb.MSPPrincipal()
    p.classification = polpb.MSPPrincipal.ROLE
    p.principal = polpb.MSPRole(
        msp_identifier=mspid, role=role).SerializeToString()
    return p


class TestValidation:
    def test_member_chain_validates(self, org1):
        msp = _msp_for(org1)
        ident = msp.deserialize_identity(_sid(org1["member"][0]))
        ident.validate()   # no raise

    def test_unknown_ca_rejected(self, org1):
        msp = _msp_for(org1)
        other_root, other_key = certgen.make_self_signed("evil-ca")
        stranger, _ = certgen.make_leaf("mallory", other_root, other_key)
        ident = msp.deserialize_identity(_sid(stranger))
        with pytest.raises(MSPError, match="no trusted issuer"):
            ident.validate()

    def test_revoked_rejected(self, org1):
        msp = _msp_for(org1)
        ident = msp.deserialize_identity(_sid(org1["revoked"][0]))
        with pytest.raises(MSPError, match="revoked"):
            ident.validate()
        # without the CRL the same cert is fine
        ident2 = _msp_for(org1, with_crl=False).deserialize_identity(
            _sid(org1["revoked"][0]))
        ident2.validate()

    def test_expired_rejected(self, org1):
        inter, inter_key = org1["inter"]
        old, _ = certgen.make_leaf(
            "old-user", inter, inter_key,
            not_after=datetime.datetime(2021, 1, 1))
        msp = _msp_for(org1)
        ident = msp.deserialize_identity(_sid(old))
        with pytest.raises(MSPError, match="validity period"):
            ident.validate()

    def test_wrong_mspid_rejected(self, org1):
        msp = _msp_for(org1)
        sid = msppb.SerializedIdentity()
        sid.mspid = "OtherMSP"
        sid.id_bytes = certgen.pem(org1["member"][0])
        with pytest.raises(MSPError, match="expected MSP ID"):
            msp.deserialize_identity(sid.SerializeToString())

    def test_resetup_drops_stale_crl(self, org1):
        """Channel reconfig removing a CRL must un-revoke (setup resets
        revocation state, it doesn't accumulate)."""
        msp = _msp_for(org1)
        ident = msp.deserialize_identity(_sid(org1["revoked"][0]))
        with pytest.raises(MSPError, match="revoked"):
            ident.validate()
        config_no_crl = build_msp_config(
            name="Org1MSP",
            root_certs=[certgen.pem(org1["root"][0])],
            intermediate_certs=[certgen.pem(org1["inter"][0])],
        )
        msp.setup(config_no_crl)
        msp.deserialize_identity(_sid(org1["revoked"][0])).validate()

    def test_deserialize_does_not_touch_keystore(self, org1, tmp_path):
        """Identity deserialization is the hot path: it must not write
        key files (imports are ephemeral)."""
        from fabric_tpu.bccsp.keystore import FileKeyStore
        from fabric_tpu.bccsp.sw import SWProvider as SW
        csp = SW(FileKeyStore(str(tmp_path)))
        msp = X509MSP(csp)
        msp.setup(build_msp_config(
            name="Org1MSP",
            root_certs=[certgen.pem(org1["root"][0])],
            intermediate_certs=[certgen.pem(org1["inter"][0])]))
        msp.deserialize_identity(_sid(org1["member"][0]))
        assert list(tmp_path.iterdir()) == []

    def test_is_well_formed(self, org1):
        msp = _msp_for(org1)
        msp.is_well_formed(_sid(org1["member"][0]))
        with pytest.raises(MSPError):
            msp.is_well_formed(b"\x00garbage")


class TestSignVerify:
    def test_identity_verify_roundtrip(self, org1):
        """identity.verify = hash + bccsp verify
        (reference msp/identities.go:170-199)."""
        from fabric_tpu.bccsp.bccsp import ECDSAPrivateKeyImportOpts
        msp = _msp_for(org1)
        cert, priv = org1["member"]
        csp = msp.csp
        priv_key = csp.key_import(priv, ECDSAPrivateKeyImportOpts())
        ident = msp.deserialize_identity(_sid(cert))
        msg = b"endorsement payload"
        sig = csp.sign(priv_key, csp.hash(msg))
        assert ident.verify(msg, sig)
        assert not ident.verify(msg + b"!", sig)
        # the batch item carries the same key + message
        item = ident.verify_item(msg, sig)
        assert item.key is ident.key and item.message == msg

    def test_verify_items_batch_with_provider(self, org1):
        """Whole-set verification through verify_batch — the path the
        policy engine uses (batched TPU dispatch upstream)."""
        from fabric_tpu.bccsp.bccsp import ECDSAPrivateKeyImportOpts
        msp = _msp_for(org1)
        csp = msp.csp
        items, expect = [], []
        for who in ("member", "admin", "peer"):
            cert, priv = org1[who]
            pk = csp.key_import(priv, ECDSAPrivateKeyImportOpts())
            ident = msp.deserialize_identity(_sid(cert))
            msg = f"payload from {who}".encode()
            sig = csp.sign(pk, csp.hash(msg))
            items.append(ident.verify_item(msg, sig))
            expect.append(True)
            items.append(ident.verify_item(msg + b"x", sig))
            expect.append(False)
        assert csp.verify_batch(items) == expect


class TestPrincipals:
    def test_member_role(self, org1):
        msp = _msp_for(org1)
        ident = msp.deserialize_identity(_sid(org1["member"][0]))
        ident.satisfies_principal(
            _role_principal("Org1MSP", polpb.MSPRole.MEMBER))
        with pytest.raises(PrincipalNotSatisfied, match="for MSP"):
            ident.satisfies_principal(
                _role_principal("Org2MSP", polpb.MSPRole.MEMBER))

    def test_admin_by_list(self, org1):
        msp = _msp_for(org1)
        admin = msp.deserialize_identity(_sid(org1["admin"][0]))
        admin.satisfies_principal(
            _role_principal("Org1MSP", polpb.MSPRole.ADMIN))
        member = msp.deserialize_identity(_sid(org1["member"][0]))
        with pytest.raises(PrincipalNotSatisfied, match="not an admin"):
            member.satisfies_principal(
                _role_principal("Org1MSP", polpb.MSPRole.ADMIN))

    def test_node_ou_roles(self, org1):
        msp = _msp_for(org1, node_ous=True)
        peer = msp.deserialize_identity(_sid(org1["peer"][0]))
        peer.satisfies_principal(
            _role_principal("Org1MSP", polpb.MSPRole.PEER))
        with pytest.raises(PrincipalNotSatisfied):
            peer.satisfies_principal(
                _role_principal("Org1MSP", polpb.MSPRole.CLIENT))
        client = msp.deserialize_identity(_sid(org1["client"][0]))
        client.satisfies_principal(
            _role_principal("Org1MSP", polpb.MSPRole.CLIENT))
        # NodeOUs disabled -> peer/client roles unclassifiable
        msp2 = _msp_for(org1, node_ous=False)
        peer2 = msp2.deserialize_identity(_sid(org1["peer"][0]))
        with pytest.raises(PrincipalNotSatisfied, match="NodeOUs disabled"):
            peer2.satisfies_principal(
                _role_principal("Org1MSP", polpb.MSPRole.PEER))

    def test_identity_principal(self, org1):
        msp = _msp_for(org1)
        ident = msp.deserialize_identity(_sid(org1["member"][0]))
        p = polpb.MSPPrincipal()
        p.classification = polpb.MSPPrincipal.IDENTITY
        p.principal = ident.serialize()
        ident.satisfies_principal(p)
        p.principal = b"someone else"
        with pytest.raises(PrincipalNotSatisfied):
            ident.satisfies_principal(p)

    def test_ou_principal(self, org1):
        msp = _msp_for(org1)
        peer = msp.deserialize_identity(_sid(org1["peer"][0]))
        p = polpb.MSPPrincipal()
        p.classification = polpb.MSPPrincipal.ORGANIZATION_UNIT
        p.principal = polpb.OrganizationUnit(
            msp_identifier="Org1MSP",
            organizational_unit_identifier="peer").SerializeToString()
        peer.satisfies_principal(p)
        member = msp.deserialize_identity(_sid(org1["member"][0]))
        with pytest.raises(PrincipalNotSatisfied):
            member.satisfies_principal(p)

    def test_combined_principal(self, org1):
        msp = _msp_for(org1, node_ous=True)
        peer = msp.deserialize_identity(_sid(org1["peer"][0]))
        combined = polpb.CombinedPrincipal()
        combined.principals.add().CopyFrom(
            _role_principal("Org1MSP", polpb.MSPRole.MEMBER))
        combined.principals.add().CopyFrom(
            _role_principal("Org1MSP", polpb.MSPRole.PEER))
        p = polpb.MSPPrincipal()
        p.classification = polpb.MSPPrincipal.COMBINED
        p.principal = combined.SerializeToString()
        peer.satisfies_principal(p)

    def test_anonymity_principal(self, org1):
        msp = _msp_for(org1)
        ident = msp.deserialize_identity(_sid(org1["member"][0]))
        p = polpb.MSPPrincipal()
        p.classification = polpb.MSPPrincipal.ANONYMITY
        p.principal = polpb.MSPIdentityAnonymity(
            anonymity_type=polpb.MSPIdentityAnonymity.NOMINAL
        ).SerializeToString()
        ident.satisfies_principal(p)
        p.principal = polpb.MSPIdentityAnonymity(
            anonymity_type=polpb.MSPIdentityAnonymity.ANONYMOUS
        ).SerializeToString()
        with pytest.raises(PrincipalNotSatisfied, match="anonymous"):
            ident.satisfies_principal(p)


class TestManagerAndCache:
    def test_manager_routes_by_mspid(self, org1):
        msp = _msp_for(org1)
        mgr = Manager()
        mgr.setup([msp])
        ident = mgr.deserialize_identity(_sid(org1["member"][0]))
        assert ident.mspid() == "Org1MSP"
        sid = msppb.SerializedIdentity(mspid="NopeMSP", id_bytes=b"x")
        with pytest.raises(MSPError, match="unknown"):
            mgr.deserialize_identity(sid.SerializeToString())

    def test_cache_memoizes_deserialize(self, org1):
        inner = _msp_for(org1)
        calls = {"n": 0}
        orig = inner.deserialize_identity

        def counting(serialized):
            calls["n"] += 1
            return orig(serialized)
        inner.deserialize_identity = counting
        cached = CachedMSP(inner)
        a = cached.deserialize_identity(_sid(org1["member"][0]))
        b = cached.deserialize_identity(_sid(org1["member"][0]))
        assert a is b
        assert calls["n"] == 1

    def test_cache_memoizes_failures(self, org1):
        cached = CachedMSP(_msp_for(org1))
        ident = cached.deserialize_identity(_sid(org1["revoked"][0]))
        for _ in range(2):
            with pytest.raises(MSPError, match="revoked"):
                cached.validate(ident)

    def test_cache_purged_on_resetup(self, org1):
        """Reconfig through the cached wrapper must drop memoized
        validation results (a new CRL revokes a previously-valid
        cert)."""
        inner = _msp_for(org1, with_crl=False)
        cached = CachedMSP(inner)
        ident = cached.deserialize_identity(_sid(org1["revoked"][0]))
        cached.validate(ident)   # valid pre-reconfig
        cached.setup(build_msp_config(
            name="Org1MSP",
            root_certs=[certgen.pem(org1["root"][0])],
            intermediate_certs=[certgen.pem(org1["inter"][0])],
            revocation_list=[certgen.pem(org1["crl"])],
        ))
        ident2 = cached.deserialize_identity(_sid(org1["revoked"][0]))
        with pytest.raises(MSPError, match="revoked"):
            cached.validate(ident2)

    def test_revoked_intermediate_poisons_leaves(self, org1):
        """A CRL revoking the intermediate CA rejects every identity
        chained through it."""
        root, root_key = org1["root"]
        inter = org1["inter"][0]
        crl = certgen.make_crl(root, root_key, [inter.serial_number])
        csp = SWProvider()
        msp = X509MSP(csp)
        msp.setup(build_msp_config(
            name="Org1MSP",
            root_certs=[certgen.pem(root)],
            intermediate_certs=[certgen.pem(inter)],
            revocation_list=[certgen.pem(crl)],
        ))
        ident = msp.deserialize_identity(_sid(org1["member"][0]))
        with pytest.raises(MSPError, match="revoked"):
            ident.validate()
