"""Round-19 adaptive admission control (ISSUE 16).

The claims under test, over `common/adaptive.py`, the knob seams it
tunes (`common/overload.py` budgets, SheddingQueue capacities, the
raft proposal gate) and the observability contract:

  * sustained SLO burn TIGHTENS every registered knob in bounded
    multiplicative steps; recovery RELAXES only after the (longer)
    calm hysteresis — backing off is prompt, recovering is cautious;
  * chaos-noise signals flipping hot/calm tick-to-tick produce HOLDS,
    not flapping (direction reversals wait out the cooldown);
  * every knob converges at its floor/ceiling (clamp, not oscillate)
    and a controller move never leaves the declared bounds;
  * `FTPU_ADAPTIVE=0` is a true no-op: no controller, no thread, no
    knob ever moved, `health()` reads `disabled`;
  * each applied move emits an `adaptive.adjust` tracing instant and
    the `adaptive_*` gauges/counters;
  * the serving knobs resolve dynamic (controller) > env >
    `Operations.Overload.*` config > default, and the rolling
    shed-rate window reads sheds-per-second over an injected clock;
  * the raft proposal gate (`chain._ProposalGate`) admits under its
    cap, sheds PAST the deadline budget with a retryable
    OverloadError, and surfaces depth/capacity through the overload
    registry like any stage.

The controller's clock and signal source are injected — no threads,
no sleeps; each `tick()` is one deterministic control decision.
Wired into tools/static_check.sh's lockcheck subset: the decision
path must stay lock-ordering clean alongside the queues it tunes.
"""

from __future__ import annotations

import os

import pytest

from fabric_tpu.common import adaptive, metrics, overload, tracing
from fabric_tpu.common.adaptive import (
    RELAX, TIGHTEN, AdaptiveController, Knob,
)


@pytest.fixture()
def adaptive_env(monkeypatch):
    """Isolated plane: enabled via env, empty registry, clean budget
    overrides, fresh recorder; restores everything afterwards."""
    monkeypatch.setenv("FTPU_ADAPTIVE", "1")
    adaptive.reset()
    overload.clear_dynamic_budgets()
    tracing.configure(enabled=True, ring_size=1024, sample_every=1,
                      dump_dir="", dump_min_interval_s=10.0)
    tracing.reset()
    yield
    adaptive.reset()
    overload.clear_dynamic_budgets()
    tracing.reset()


class _Sig:
    """Scriptable signal source: a list of signal dicts, replayed one
    per tick (the last one repeats)."""

    def __init__(self, *frames):
        self.frames = list(frames)
        self.i = 0

    def __call__(self):
        f = self.frames[min(self.i, len(self.frames) - 1)]
        self.i += 1
        return dict(f)


QUIET = {"slo_burn": 0.0, "shed_rate": 0.0, "queue_pressure": 0.0,
         "device_busy": 0.0, "hbm_headroom": 1.0}
BURNING = dict(QUIET, slo_burn=4.0)


class _Holder:
    """Knob owner for register_attr_knob (keeps the weak registry
    entry alive for the test's duration)."""

    def __init__(self, cap=64):
        self.cap = cap


def _ctl(signals, **policy):
    policy.setdefault("tighten_after", 2)
    policy.setdefault("relax_after", 4)
    policy.setdefault("reversal_cooldown", 4)
    return AdaptiveController(interval_s=3600.0, clock=lambda: 0.0,
                              signal_fn=signals, **policy)


class TestHysteresis:
    def test_burn_tightens_after_streak(self, adaptive_env):
        h = _Holder(cap=64)
        adaptive.register_attr_knob(h, "cap", "t.cap",
                                    floor=8, ceiling=64)
        ctl = _ctl(_Sig(BURNING))
        assert ctl.tick()["moved"] == []      # streak 1 < tighten_after
        assert h.cap == 64
        moved = ctl.tick()["moved"]           # streak 2: tighten
        assert moved == [("t.cap", 64, 32)]
        assert h.cap == 32
        assert ctl.tick()["moved"] == [("t.cap", 32, 16)]
        assert ctl.stats["tightens"] == 2

    def test_recovery_relaxes_only_after_calm_hysteresis(
            self, adaptive_env):
        h = _Holder(cap=64)
        adaptive.register_attr_knob(h, "cap", "t.cap",
                                    floor=8, ceiling=64)
        ctl = _ctl(_Sig(BURNING, BURNING, QUIET),
                   reversal_cooldown=0)
        ctl.tick(), ctl.tick()                # tighten once -> 32
        assert h.cap == 32
        for _ in range(3):                    # calm 1..3 < relax_after
            assert ctl.tick()["moved"] == []
        assert h.cap == 32
        assert ctl.tick()["moved"] == [("t.cap", 32, 64)]
        assert ctl.stats["relaxes"] == 1

    def test_one_hot_tick_among_calm_resets_the_calm_streak(
            self, adaptive_env):
        h = _Holder(cap=32)
        adaptive.register_attr_knob(h, "cap", "t.cap",
                                    floor=8, ceiling=64)
        ctl = _ctl(_Sig(QUIET, QUIET, QUIET, BURNING, QUIET, QUIET,
                        QUIET, QUIET),
                   reversal_cooldown=0)
        for _ in range(7):
            assert ctl.tick()["moved"] == []  # streak broken at tick 4
        assert ctl.tick()["moved"] == [("t.cap", 32, 64)]


class TestAntiFlap:
    def test_chaos_noise_holds_instead_of_flapping(self,
                                                   adaptive_env):
        h = _Holder(cap=64)
        adaptive.register_attr_knob(h, "cap", "t.cap",
                                    floor=4, ceiling=64)
        # hot long enough to tighten, then calm long enough to WANT a
        # relax while the reversal cooldown still runs, then hot again
        frames = [BURNING] * 3 + [QUIET] * 4 + [BURNING] * 3
        ctl = _ctl(_Sig(*frames), reversal_cooldown=6)
        for _ in frames:
            ctl.tick()
        assert ctl.stats["reversals"] == 0
        assert ctl.stats["cooldown_holds"] >= 1
        assert ctl.stats["tightens"] >= 2

    def test_reversal_after_cooldown_is_counted(self, adaptive_env):
        h = _Holder(cap=64)
        adaptive.register_attr_knob(h, "cap", "t.cap",
                                    floor=4, ceiling=64)
        ctl = _ctl(_Sig(BURNING, BURNING, QUIET),
                   reversal_cooldown=2)
        ctl.tick(), ctl.tick()                # tighten; cooldown = 2
        for _ in range(5):                    # calm: cooldown drains,
            ctl.tick()                        # then relax_after trips
        assert ctl.stats["relaxes"] == 1
        assert ctl.stats["reversals"] == 1
        assert h.cap == 64


class TestBounds:
    def test_tighten_converges_at_floor_as_clamps(self, adaptive_env):
        h = _Holder(cap=16)
        adaptive.register_attr_knob(h, "cap", "t.cap",
                                    floor=8, ceiling=64)
        ctl = _ctl(_Sig(BURNING))
        for _ in range(8):
            ctl.tick()
        assert h.cap == 8                     # pinned, never below
        assert ctl.stats["clamps"] >= 1
        assert ctl.stats["moves"] == 1        # 16 -> 8, then clamps

    def test_relax_never_exceeds_ceiling(self, adaptive_env):
        h = _Holder(cap=48)
        adaptive.register_attr_knob(h, "cap", "t.cap",
                                    floor=8, ceiling=64)
        ctl = _ctl(_Sig(QUIET), reversal_cooldown=0)
        for _ in range(10):
            ctl.tick()
        assert h.cap == 64

    def test_knob_declares_sane_bounds(self):
        with pytest.raises(ValueError):
            Knob("bad", get=lambda: 1, set=lambda v: None,
                 floor=10, ceiling=5)
        with pytest.raises(ValueError):
            Knob("bad", get=lambda: 1, set=lambda v: None,
                 floor=1, ceiling=5, step=1.0)

    def test_queue_capacity_knob_anchors_at_configured_cap(
            self, adaptive_env):
        q = overload.SheddingQueue("t.q", maxsize=64)
        k = adaptive.register_queue_capacity(q)
        assert (k.floor, k.ceiling) == (8, 64)
        assert k.move(TIGHTEN) == (64, 32, False)
        assert q.maxsize == 32
        assert k.move(RELAX) == (32, 64, False)
        assert k.move(RELAX) == (64, 64, True)   # clamped at base


class TestDisabled:
    def test_disabled_plane_is_a_no_op(self, adaptive_env,
                                       monkeypatch):
        monkeypatch.setenv("FTPU_ADAPTIVE", "0")
        assert not adaptive.enabled()
        assert adaptive.start_controller() is None
        assert adaptive.controller() is None
        assert adaptive.health() == "disabled"
        # no budget override was installed behind the operator's back
        assert overload.ingress_budget_s() == \
            overload.static_ingress_budget_s()

    def test_env_toggle_spellings(self, adaptive_env, monkeypatch):
        for off in ("0", "false", "No", "OFF"):
            monkeypatch.setenv("FTPU_ADAPTIVE", off)
            assert not adaptive.enabled()
        monkeypatch.setenv("FTPU_ADAPTIVE", "1")
        assert adaptive.enabled()


class TestObservability:
    def test_moves_emit_instants_and_gauges(self, adaptive_env):
        h = _Holder(cap=64)
        adaptive.register_attr_knob(h, "cap", "t.cap",
                                    floor=8, ceiling=64)
        provider = metrics.PrometheusProvider()
        ctl = _ctl(_Sig(BURNING))
        ctl.bind_metrics(provider)
        ctl.tick(), ctl.tick()
        inst = [e for e in tracing.snapshot()
                if e[1] == "adaptive.adjust"]
        assert len(inst) == 1
        attrs = inst[0][8]
        assert attrs["knob"] == "t.cap"
        assert (attrs["frm"], attrs["to"]) == (64, 32)
        assert attrs["direction"] == "tighten"
        assert attrs["reason"] == "slo_burn"
        text = provider.render()
        assert 'adaptive_knob_value{knob="t.cap"} 32' in text
        assert ('adaptive_adjustments_total'
                '{knob="t.cap",direction="tighten"} 1') in text
        assert 'adaptive_signal{signal="slo_burn"} 4' in text

    def test_health_surfaces_controller_counts(self, adaptive_env):
        ctl = adaptive.start_controller(interval_s=3600.0)
        try:
            assert adaptive.health().startswith("ok:moves=")
        finally:
            adaptive.stop_controller()
        assert adaptive.health() == "disabled"


class TestBudgetResolution:
    def test_dynamic_beats_env_beats_config_beats_default(
            self, adaptive_env, monkeypatch):
        class _Cfg:
            def get_duration(self, key, default=0.0):
                return {"Operations.Overload.IngressBudgetS": 20.0,
                        "Operations.Overload.EnqueueBudgetS": 8.0,
                        }.get(key, default)

            def get_int(self, key, default=0):
                return {"Operations.Overload.RaftEventsCap": 512,
                        }.get(key, default)

        monkeypatch.delenv("FTPU_INGRESS_BUDGET_S", raising=False)
        monkeypatch.delenv("FTPU_RAFT_EVENTS_CAP", raising=False)
        overload.configure_from_config(_Cfg())
        try:
            assert overload.ingress_budget_s() == 20.0
            assert overload.raft_events_cap() == 512
            monkeypatch.setenv("FTPU_INGRESS_BUDGET_S", "15")
            monkeypatch.setenv("FTPU_RAFT_EVENTS_CAP", "256")
            assert overload.ingress_budget_s() == 15.0
            assert overload.raft_events_cap() == 256
            overload.set_dynamic_budget("ingress", 5.0)
            assert overload.ingress_budget_s() == 5.0
            # the STATIC base (the controller's anchor) ignores the
            # controller's own override
            assert overload.static_ingress_budget_s() == 15.0
            overload.set_dynamic_budget("ingress", None)
            assert overload.ingress_budget_s() == 15.0
        finally:
            class _Empty:
                def get_duration(self, key, default=0.0):
                    return default

                def get_int(self, key, default=0):
                    return default

            overload.configure_from_config(_Empty())

    def test_budget_knobs_anchor_and_restore(self, adaptive_env,
                                             monkeypatch):
        monkeypatch.setenv("FTPU_INGRESS_BUDGET_S", "16")
        ing, _enq = adaptive.register_budget_knobs()
        assert (ing.floor, ing.ceiling) == (2.0, 16.0)
        ing.move(TIGHTEN)
        assert overload.ingress_budget_s() == 8.0
        ing.move(RELAX)
        assert overload.ingress_budget_s() == 16.0
        adaptive.reset()   # stop_controller clears dynamic overrides
        assert overload.ingress_budget_s() == 16.0

    def test_unknown_dynamic_budget_rejected(self):
        with pytest.raises(KeyError):
            overload.set_dynamic_budget("nonsense", 1.0)


class TestShedRateWindow:
    def test_rolling_rate_over_injected_clock(self):
        now = [0.0]
        w = overload.ShedRateWindow(window_s=10.0,
                                    clock=lambda: now[0])
        assert w.rate() == 0.0
        for _ in range(5):
            w.note()
        assert w.rate() == 0.5                # 5 sheds / 10 s
        now[0] = 9.0
        w.note()
        assert w.rate() == 0.6
        now[0] = 11.0                         # first burst aged out
        assert w.rate() == 0.1


class TestProposalGate:
    """The round-19 consensus pacing seam (orderer/raft/chain.py)."""

    def _gate(self, depth=0, cap=4):
        import types

        from fabric_tpu.orderer.raft.chain import _ProposalGate

        state = {"depth": depth}
        chain = types.SimpleNamespace(
            _support=types.SimpleNamespace(channel_id="tch"),
            node_id=7,
            node=types.SimpleNamespace(
                last_index=lambda: state["depth"],
                applied_index=0),
            _halted=types.SimpleNamespace(is_set=lambda: False))
        return _ProposalGate(chain, cap=cap), state

    def test_admits_below_cap_and_reads_as_a_stage(self,
                                                   adaptive_env):
        gate, _state = self._gate(depth=3, cap=4)
        gate.admit()
        s = overload.stage_stats()["raft.inflight.tch.7"]
        assert (s["depth"], s["capacity"]) == (3, 4)
        assert (s["puts"], s["sheds"]) == (1, 0)

    def test_sheds_past_the_deadline_budget(self, adaptive_env):
        gate, _state = self._gate(depth=4, cap=4)
        with overload.Deadline.after(0.02).applied():
            with pytest.raises(overload.OverloadError):
                gate.admit()
        assert gate.stats["sheds"] == 1
        assert overload.stage_stats()[
            "raft.inflight.tch.7"]["shed_rate"] > 0
        inst = [e for e in tracing.snapshot()
                if e[1] == "overload.shed"]
        assert inst, "shed instant must be recorded"

    def test_admits_when_backlog_drains_within_budget(
            self, adaptive_env):
        gate, state = self._gate(depth=4, cap=4)

        # the pipeline applies an entry while the submitter waits
        import threading
        t = threading.Timer(0.05,
                            lambda: state.update(depth=1))
        t.start()
        try:
            with overload.Deadline.after(2.0).applied():
                gate.admit()                  # blocks, then passes
        finally:
            t.join()
        assert gate.stats["sheds"] == 0
        assert gate.stats["puts"] == 1

    def test_cap_is_an_adaptive_knob(self, adaptive_env):
        gate, _state = self._gate(cap=64)
        k = adaptive.register_attr_knob(
            gate, "cap", "raft.inflight.tch.7.cap",
            floor=8, ceiling=64)
        assert k.move(TIGHTEN) == (64, 32, False)
        assert gate.cap == 32


class TestNoteDrop:
    def test_internal_drop_counts_as_drop_not_shed(self):
        q = overload.SheddingQueue("t.drop", maxsize=2)
        q.note_drop()
        assert q.stats["drops"] == 1
        assert q.stats["sheds"] == 0
