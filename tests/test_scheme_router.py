"""Multi-scheme device verify (ISSUE 8 tentpole): the scheme-dispatch
router, the Ed25519 batch path, BLS aggregate verify, faults and
observability.

The contract under test: `TPUProvider.verify_batch` partitions lanes
by scheme — P-256 to the existing comb/tree pipeline, Ed25519 to the
new batch kernel, BLS to the pairing path, everything else to sw —
and the combined bitmap is BIT-IDENTICAL to all-sw on mixed batches,
invalid signatures, padded non-dividing tails and RFC 8032 edge
vectors. Armed `tpu.ed25519` / `tpu.bls_aggregate` faults serve the
host path with identical verdicts, then the breaker re-enters.

Wheel-free via the recorder-stub idiom (tests/test_shard_verify.py):
the P-256 pipelines are premask recorders; the Ed25519 pipeline stub
REPLAYS the staged device operand rows through `ed25519_host` integer
math — so the staging (gates, challenge, row packing, padding,
scatter) is pinned end to end bit-exactly without the multi-minute
kernel compile, which the slow-marked test at the bottom covers for
real.
"""

import hashlib

import numpy as np
import pytest

from fabric_tpu.bccsp import ECDSAKeyGenOpts, VerifyItem, utils
from fabric_tpu.bccsp import ed25519_host as edh
from fabric_tpu.bccsp.bccsp import BLSKeyGenOpts, Ed25519KeyGenOpts
from fabric_tpu.bccsp.sw import (
    ECDSAPublicKey,
    SWProvider,
    bls_aggregate_signatures,
)
from fabric_tpu.bccsp.tpu import TPUProvider
from fabric_tpu.common import faults

_SW = SWProvider()
_P256 = [_SW.key_gen(ECDSAKeyGenOpts(ephemeral=True)) for _ in range(2)]
_ED = [_SW.key_gen(Ed25519KeyGenOpts(ephemeral=True)) for _ in range(2)]
_BLS = _SW.key_gen(BLSKeyGenOpts(ephemeral=True))


class _NotP256(ECDSAPublicKey):
    """A P-256 key masquerading as an unknown curve: the device must
    route it to the per-lane sw path (where the math still verifies),
    exercising the ecdsa-other scheme lane on a wheel-free host."""

    def __init__(self, inner: ECDSAPublicKey):
        self._pub = inner._pub
        self.x, self.y = inner.x, inner.y
        self._xy_cache = None

    def is_p256(self) -> bool:
        return False


def _stubbed_provider(mesh=None, **kw):
    kw.setdefault("min_batch", 1)
    kw.setdefault("use_g16", False)
    kw.setdefault("pipeline_chunk", 0)
    tpu = TPUProvider(mesh=mesh, **kw)
    calls = {"p256_premask": [], "ed_premask": [], "ed_chunks": 0}

    def fake_qtab_fn(K):
        return lambda qx, qy: np.zeros((K,), dtype=np.int32)

    def fake_pipeline_digest(K, q16=False, donate=False):
        def run(key_idx, q_flat, g16, r8, rpn8, w8, premask, digests):
            calls["p256_premask"].append(np.asarray(premask).copy())
            return np.asarray(premask)
        return run

    def fake_ladder():
        def run(blocks, nblocks, qx, qy, r, rpn, w, premask, digests,
                has_digest):
            return np.asarray(premask)
        return run

    def fake_ed_pipeline():
        def run(tab, s8, k8, anx8, ay8, rx8, ry8, premask):
            # replay the STAGED rows through the host integer math:
            # verdicts depend on exactly what the provider packed, so
            # a staging bug (wrong row, wrong padding, wrong scatter)
            # flips a bit the parity assertions catch
            pm = np.asarray(premask).copy()
            calls["ed_premask"].append(pm)
            calls["ed_chunks"] += 1
            out = np.zeros(len(pm), dtype=bool)
            for i in range(len(pm)):
                if not pm[i]:
                    continue
                s, k, anx, ay, rx, ry = (
                    int.from_bytes(bytes(np.asarray(a)[i]), "big")
                    for a in (s8, k8, anx8, ay8, rx8, ry8))
                acc = edh.pt_add(
                    edh.scalar_mult(s, edh.from_affine(edh.BX,
                                                       edh.BY)),
                    edh.scalar_mult(k, edh.from_affine(anx, ay)))
                out[i] = edh.pt_equal(acc, edh.from_affine(rx, ry))
            return out
        return run

    tpu._qtab_fn = fake_qtab_fn
    tpu._comb_pipeline_digest = fake_pipeline_digest
    tpu._pipeline = fake_ladder
    tpu._ed25519_pipeline = fake_ed_pipeline
    tpu._ed_table = lambda: np.zeros((1,), dtype=np.int32)
    return tpu, calls


def _mixed_corpus(n):
    """n lanes cycling P-256 / Ed25519 / BLS / ecdsa-other / invalid
    variants. Returns (items, expected) with expected == the sw-oracle
    bitmap."""
    items, expected = [], []
    for i in range(n):
        m = f"scheme lane {i}".encode()
        kind = i % 6
        if kind == 0:               # valid P-256
            k = _P256[i % 2]
            sig = _SW.sign(k, hashlib.sha256(m).digest())
            items.append(VerifyItem(key=k.public_key(), signature=sig,
                                    message=m))
            expected.append(True)
        elif kind == 1:             # Ed25519: valid / wrong-message
            k = _ED[i % 2]
            if i % 12 == 7:
                sig = _SW.sign(k, b"some other message")
                expected.append(False)
            else:
                sig = _SW.sign(k, m)
                expected.append(True)
            items.append(VerifyItem(key=k.public_key(), signature=sig,
                                    message=m))
        elif kind == 2:             # BLS per-lane (sw pairing path)
            sig = _SW.sign(_BLS, m)
            if i % 12 == 8:
                sig = _SW.sign(_BLS, m + b"!")
                expected.append(False)
            else:
                expected.append(True)
            items.append(VerifyItem(key=_BLS.public_key(),
                                    signature=sig, message=m))
        elif kind == 3:             # "unknown curve" -> sw lane
            k = _P256[0]
            sig = _SW.sign(k, hashlib.sha256(m).digest())
            items.append(VerifyItem(key=_NotP256(k.public_key()),
                                    signature=sig, message=m))
            expected.append(True)
        elif kind == 4:             # invalid P-256 (high-S, host gate)
            k = _P256[1]
            sig = _SW.sign(k, hashlib.sha256(m).digest())
            r, s = utils.unmarshal_signature(sig)
            items.append(VerifyItem(
                key=k.public_key(),
                signature=utils.marshal_signature(r, utils.P256_N - s),
                message=m))
            expected.append(False)
        else:                       # Ed25519 host-gate invalids
            k = _ED[0]
            sig = _SW.sign(k, m)
            s_int = int.from_bytes(sig[32:], "little")
            if i % 12 == 5 and s_int + edh.L < (1 << 256):
                sig = sig[:32] + (s_int + edh.L).to_bytes(32, "little")
            else:                   # non-canonical R encoding
                sig = edh.P.to_bytes(32, "little") + sig[32:]
            items.append(VerifyItem(key=k.public_key(), signature=sig,
                                    message=m))
            expected.append(False)
    return items, expected


class TestMixedSchemeRouting:
    def test_mixed_batch_bitmap_parity(self):
        """One verify_batch over all schemes at once: bitmap identical
        to all-sw, every lane routed, per-scheme accounting split."""
        faults.clear()
        tpu, calls = _stubbed_provider()
        items, expected = _mixed_corpus(96)
        out = tpu.verify_batch(items)
        assert out == _SW.verify_batch(items) == expected
        assert any(expected) and not all(expected)
        st = tpu.scheme_stats
        assert st["dispatches"].get("p256") == 1
        assert st["dispatches"].get("ed25519") == 1
        assert st["lanes"].get("bls") == 16
        assert st["sw_lanes"].get("bls") == 16
        # the fake-curve lanes took the consolidated sw-scatter helper
        assert st["sw_lanes"].get("ecdsa-other") == 16
        assert tpu.stats["nonp256_sw_lanes"] == 16
        assert tpu.stats["ed25519_batches"] == 1
        # total routed lanes == batch (no scheme silently dropped)
        assert sum(st["lanes"].values()) == 96

    def test_pure_p256_batch_keeps_legacy_path(self):
        """An all-P-256 batch must take the pre-router pipeline (the
        common case pays the router one list scan, nothing else)."""
        faults.clear()
        tpu, calls = _stubbed_provider()
        k = _P256[0]
        items = []
        for i in range(32):
            m = f"pure {i}".encode()
            items.append(VerifyItem(
                key=k.public_key(),
                signature=_SW.sign(k, hashlib.sha256(m).digest()),
                message=m))
        assert tpu.verify_batch(items) == [True] * 32
        assert tpu.stats["comb_batches"] == 1
        assert tpu.stats["ed25519_batches"] == 0
        assert tpu.scheme_stats["lanes"] == {"p256": 32}

    def test_ed25519_nondividing_tail_padded_dead(self):
        """70 Ed25519 lanes bucket to 128: the staged rows carry 58
        padded lanes whose premask is dead, and padding never leaks a
        verdict."""
        faults.clear()
        tpu, calls = _stubbed_provider(min_batch=16)
        k = _ED[0]
        items, expected = [], []
        for i in range(70):
            m = f"tail {i}".encode()
            sig = _SW.sign(k, m if i % 5 else b"wrong")
            items.append(VerifyItem(key=k.public_key(), signature=sig,
                                    message=m))
            expected.append(bool(i % 5))
        out = tpu.verify_batch(items)
        assert out == _SW.verify_batch(items) == expected
        pm = calls["ed_premask"][-1]
        assert len(pm) == 128
        assert not pm[70:].any()

    def test_small_ed25519_subbatch_rides_sw(self):
        """A mixed batch whose Ed25519 remainder is below MinBatch
        must not pay kernel-dispatch latency for 3 lanes."""
        faults.clear()
        tpu, calls = _stubbed_provider(min_batch=8)
        items, expected = [], []
        k = _P256[0]
        for i in range(16):
            m = f"p {i}".encode()
            items.append(VerifyItem(
                key=k.public_key(),
                signature=_SW.sign(k, hashlib.sha256(m).digest()),
                message=m))
            expected.append(True)
        for i in range(3):
            m = f"e {i}".encode()
            items.append(VerifyItem(key=_ED[0].public_key(),
                                    signature=_SW.sign(_ED[0], m),
                                    message=m))
            expected.append(True)
        assert tpu.verify_batch(items) == expected
        assert tpu.stats["ed25519_batches"] == 0
        assert tpu.scheme_stats["sw_lanes"].get("ed25519") == 3

    def test_ed25519_disabled_serves_host_path(self):
        """BCCSP.TPU.Ed25519: false pins Ed25519 lanes to the host
        reference — verdicts identical, no device dispatch."""
        faults.clear()
        tpu, calls = _stubbed_provider(ed25519=False)
        items, expected = [], []
        for i in range(24):
            m = f"off {i}".encode()
            items.append(VerifyItem(key=_ED[0].public_key(),
                                    signature=_SW.sign(_ED[0], m),
                                    message=m))
            expected.append(True)
        assert tpu.verify_batch(items) == expected
        assert tpu.stats["ed25519_batches"] == 0
        assert calls["ed_chunks"] == 0


class TestShardedSchemeRouting:
    @pytest.fixture(scope="class")
    def mesh8(self):
        import jax

        from fabric_tpu.parallel import batch_mesh
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device virtual CPU mesh")
        return batch_mesh(8)

    def test_mixed_batch_sharded_parity(self, mesh8):
        """The router under a device mesh: the Ed25519 sub-batch's
        operand rows ride the round-robin span feeder (`_shard_put`)
        exactly like the P-256 operands, buckets stay mesh-aligned,
        and the combined bitmap matches the mesh-less provider and
        the sw oracle lane for lane."""
        faults.clear()
        sharded, calls8 = _stubbed_provider(mesh=mesh8)
        single, _ = _stubbed_provider()
        items, expected = _mixed_corpus(90)
        out8 = sharded.verify_batch(items)
        assert out8 == single.verify_batch(items) == expected
        # every staged ed25519 span divides the mesh
        assert all(len(p) % 8 == 0 for p in calls8["ed_premask"])
        assert sharded.stats["ed25519_batches"] == 1


class TestEd25519EdgeVectors:
    """RFC 8032 edge handling: the policy gates live in ONE place
    (`ed25519_host.prep_verify`), so host verify, the sw provider and
    the router path must agree lane for lane."""

    def _router_verdict(self, pub_raw, sig, msg):
        faults.clear()
        tpu, _ = _stubbed_provider(min_batch=1)
        from fabric_tpu.bccsp.sw import Ed25519PublicKey
        items = [VerifyItem(key=Ed25519PublicKey(pub_raw),
                            signature=sig, message=msg)] * 16
        out = tpu.verify_batch(items)
        assert len(set(out)) == 1
        return out[0]

    def test_rfc8032_vector_accepts(self):
        seed = bytes.fromhex(
            "9d61b19deffd5a60ba844af492ec2cc4"
            "4449c5697b326919703bac031cae7f60")
        pk = edh.public_from_seed(seed)
        assert pk.hex() == ("d75a980182b10ab7d54bfed3c964073a"
                            "0ee172f3daa62325af021a68f707511a")
        sig = edh.sign(seed, b"")
        assert edh.verify(pk, sig, b"")
        assert self._router_verdict(pk, sig, b"") is True

    def test_noncanonical_s_rejected_identically(self):
        seed = edh.generate_seed()
        pk = edh.public_from_seed(seed)
        sig = edh.sign(seed, b"msg")
        s = int.from_bytes(sig[32:], "little") + edh.L
        assert s < (1 << 256)
        bad = sig[:32] + s.to_bytes(32, "little")
        assert edh.verify(pk, bad, b"msg") is False
        assert self._router_verdict(pk, bad, b"msg") is False

    def test_noncanonical_point_encoding_rejected(self):
        seed = edh.generate_seed()
        pk = edh.public_from_seed(seed)
        sig = edh.sign(seed, b"msg")
        # R replaced by a y >= p encoding: host gate, dead lane
        bad = (edh.P + 1).to_bytes(32, "little") + sig[32:]
        assert edh.verify(pk, bad, b"msg") is False
        assert self._router_verdict(pk, bad, b"msg") is False

    def test_small_order_points_rejected_identically(self):
        seed = edh.generate_seed()
        sig = edh.sign(seed, b"msg")
        # the order-8 torsion component: A replaced by the order-2
        # point (0, -1), canonical encoding — decodes fine, rejected
        # by the small-order gate on host AND router paths
        small = edh.encode_point(0, edh.P - 1)
        assert edh.decode_point(small) is not None
        assert edh.verify(small, sig, b"msg") is False
        assert self._router_verdict(small, sig, b"msg") is False
        # and a small-order R with a valid A
        pk = edh.public_from_seed(seed)
        bad = small + sig[32:]
        assert edh.verify(pk, bad, b"msg") is False
        assert self._router_verdict(pk, bad, b"msg") is False


class TestSchemeFaults:
    def test_armed_ed25519_fault_falls_back_bit_identical(self):
        faults.clear()
        faults.arm("tpu.ed25519", mode="error", count=1)
        try:
            tpu, _ = _stubbed_provider(min_batch=1)
            items, expected = _mixed_corpus(48)
            assert tpu.verify_batch(items) == expected
            assert tpu.stats["sw_fallbacks"] == 1
            assert tpu.stats["ed25519_batches"] == 0
            # breaker re-entry: the next batch rides the kernel again
            assert tpu.verify_batch(items) == expected
            assert tpu.stats["ed25519_batches"] == 1
        finally:
            faults.clear()

    def test_armed_bls_aggregate_fault_falls_back_bit_identical(self):
        faults.clear()
        try:
            tpu, _ = _stubbed_provider()
            msgs = [f"blk {i}".encode() for i in range(4)]
            sigs = [_SW.sign(_BLS, m) for m in msgs]
            agg = bls_aggregate_signatures(sigs)
            keys = [_BLS.public_key()] * 4
            assert tpu.verify_aggregate(keys, msgs, agg) is True
            faults.arm("tpu.bls_aggregate", mode="error", count=2)
            assert tpu.verify_aggregate(keys, msgs, agg) is True
            bad = msgs[:3] + [b"forged"]
            assert tpu.verify_aggregate(keys, bad, agg) is False
        finally:
            faults.clear()


class TestAggregateVerify:
    def test_aggregate_accept_reject(self):
        faults.clear()
        tpu, _ = _stubbed_provider()
        other = _SW.key_gen(BLSKeyGenOpts(ephemeral=True))
        msgs = [b"m1", b"m2", b"m3"]
        sigs = [_SW.sign(_BLS, msgs[0]), _SW.sign(_BLS, msgs[1]),
                _SW.sign(other, msgs[2])]
        keys = [_BLS.public_key(), _BLS.public_key(),
                other.public_key()]
        agg = bls_aggregate_signatures(sigs)
        assert tpu.verify_aggregate(keys, msgs, agg) is True
        assert _SW.verify_aggregate(keys, msgs, agg) is True
        # tampered message / reordered keys / truncated set
        assert tpu.verify_aggregate(keys, [b"m1", b"mX", b"m3"],
                                    agg) is False
        assert tpu.verify_aggregate(list(reversed(keys)), msgs,
                                    agg) is False
        assert tpu.verify_aggregate(keys[:2], msgs[:2], agg) is False
        assert tpu.stats["bls_aggregate_checks"] >= 4

    def test_malformed_aggregate_signature_is_false(self):
        faults.clear()
        tpu, _ = _stubbed_provider()
        keys = [_BLS.public_key()]
        assert tpu.verify_aggregate(keys, [b"m"], b"\x01" * 96) is False
        assert tpu.verify_aggregate(keys, [b"m"], b"short") is False

    def test_non_bls_keys_raise(self):
        faults.clear()
        tpu, _ = _stubbed_provider()
        with pytest.raises(TypeError):
            tpu.verify_aggregate([_P256[0].public_key()], [b"m"],
                                 b"\x00" * 96)
        with pytest.raises(TypeError):
            _SW.verify_aggregate([_ED[0].public_key()], [b"m"],
                                 b"\x00" * 96)

    def test_admission_window_passes_aggregate_through(self):
        from fabric_tpu.bccsp.admission import AdmissionWindow
        faults.clear()
        tpu, _ = _stubbed_provider()
        win = AdmissionWindow.shared(tpu)
        msgs = [b"w1", b"w2"]
        agg = bls_aggregate_signatures(
            [_SW.sign(_BLS, m) for m in msgs])
        assert win.verify_aggregate([_BLS.public_key()] * 2, msgs,
                                    agg) is True


class TestBlockWriterAggregate:
    """The orderer consenter-identity wiring: a BLS cluster identity's
    span signatures verify as ONE aggregate pairing check before
    anything touches the store."""

    class _Store:
        def __init__(self):
            self.blocks = []

        def add_block(self, b):
            self.blocks.append(b)

        def get_block_by_number(self, n):
            return self.blocks[n]

    class _Signer:
        def __init__(self, key, tamper=False):
            self._key = key
            self._tamper = tamper

        def serialize(self):
            return b"bls-orderer"

        def sign(self, msg):
            return _SW.sign(self._key,
                            msg + (b"CORRUPT" if self._tamper else b""))

        def verify_item(self, msg, sig):
            return VerifyItem(key=self._key.public_key(),
                              signature=sig, message=msg)

    @staticmethod
    def _blocks(n):
        from fabric_tpu.protoutil import protoutil as pu
        out = []
        for i in range(n):
            b = pu.new_block(i, b"")
            b.data.data.append(f"tx {i}".encode())
            b.header.data_hash = pu.block_data_hash(b.data)
            out.append(b)
        return out

    def test_bls_span_aggregate_self_verify(self):
        from fabric_tpu.orderer.blockwriter import BlockWriter
        faults.clear()
        tpu, _ = _stubbed_provider()
        store = self._Store()
        bw = BlockWriter(store, self._Signer(_BLS), csp=tpu)
        bw.write_blocks(self._blocks(3))
        assert len(store.blocks) == 3
        # the span verified as ONE aggregate pairing check, not 3 lanes
        assert tpu.stats["bls_aggregate_checks"] == 1

    def test_corrupted_bls_signer_appends_nothing(self):
        from fabric_tpu.orderer.blockwriter import BlockWriter
        faults.clear()
        tpu, _ = _stubbed_provider()
        store = self._Store()
        bw = BlockWriter(store, self._Signer(_BLS, tamper=True),
                         csp=tpu)
        with pytest.raises(ValueError, match="refusing to append"):
            bw.write_blocks(self._blocks(2))
        assert not store.blocks


class TestSchemeObservability:
    def test_scheme_gauges_published(self):
        """bccsp_scheme_{lanes,sw_lanes,dispatches} render on /metrics
        with their canonical help text and a scheme label."""
        import time

        from fabric_tpu.common import metrics as m
        from fabric_tpu.common import profiling

        faults.clear()
        tpu, _ = _stubbed_provider()
        items, _ = _mixed_corpus(48)
        tpu.verify_batch(items)
        provider = m.PrometheusProvider()
        t = profiling.publish_provider_stats(provider, tpu,
                                             poll_s=0.01)
        assert t is not None
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            text = provider.render()
            if 'bccsp_scheme_lanes{scheme="ed25519"}' in text:
                break
            time.sleep(0.02)
        text = provider.render()
        assert 'bccsp_scheme_lanes{scheme="p256"}' in text
        assert 'bccsp_scheme_lanes{scheme="ed25519"}' in text
        assert 'bccsp_scheme_sw_lanes{scheme="bls"}' in text
        assert 'bccsp_scheme_dispatches{scheme="ed25519"} 1' in text
        assert "scheme-dispatch router" in text


@pytest.mark.slow
class TestRealEd25519Kernel:
    def test_real_kernel_parity_vs_host_oracle(self):
        """Full provider, REAL MontMod comb+ladder kernel: verdicts
        bit-identical to the host oracle on a mixed valid/invalid
        batch. Minutes of XLA compile — slow suite only; tier-1
        covers the same staging with the host-math recorder."""
        faults.clear()
        tpu = TPUProvider(min_batch=4, use_g16=False,
                          pipeline_chunk=0)
        items, expected = [], []
        for i in range(8):
            m = f"real {i}".encode()
            sig = _SW.sign(_ED[0], m if i % 3 else b"wrong")
            items.append(VerifyItem(key=_ED[0].public_key(),
                                    signature=sig, message=m))
            expected.append(bool(i % 3))
        assert tpu.verify_batch(items) == expected == \
            _SW.verify_batch(items)
        assert tpu.stats["ed25519_batches"] == 1
