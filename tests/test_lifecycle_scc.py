"""_lifecycle governance flow, system chaincodes, external chaincode.

Reference: `core/chaincode/lifecycle/` (approve per org in implicit
collections → majority commit → committed definitions drive
validation), `core/scc/{cscc,qscc}`, and the CCaaS external-chaincode
protocol (`core/container/ccaas_builder` + handler FSM).
"""

import json
import os

import pytest

from fabric_tpu.bccsp.sw import SWProvider
from fabric_tpu.common.deliver import DeliverHandler
from fabric_tpu.common.policies.policydsl import from_string
from fabric_tpu.core.chaincode import Chaincode, shim
from fabric_tpu.core.chaincode.external import (
    ChaincodeServer, ExternalChaincodeClient,
)
from fabric_tpu.internal import cryptogen
from fabric_tpu.internal.configtxgen import genesis_block, new_channel_group
from fabric_tpu.msp import msp_config_from_dir
from fabric_tpu.msp.mspimpl import X509MSP
from fabric_tpu.orderer import solo
from fabric_tpu.orderer.broadcast import BroadcastHandler
from fabric_tpu.orderer.multichannel import Registrar
from fabric_tpu.peer import Peer
from fabric_tpu.peer.deliverclient import Deliverer
from fabric_tpu.peer.gateway import Gateway, GatewayError
from fabric_tpu.protos import common, policies as polpb
from fabric_tpu.protos import transaction as txpb

CHANNEL = "lcchannel"


class EchoCC(Chaincode):
    def init(self, stub):
        return shim.success()

    def invoke(self, stub):
        fn, params = stub.get_function_and_parameters()
        if fn == "put":
            stub.put_state(params[0], params[1].encode())
            return shim.success()
        if fn == "get":
            return shim.success(stub.get_state(params[0]) or b"")
        return shim.error("unknown")


@pytest.fixture(scope="module")
def net(tmp_path_factory):
    root = tmp_path_factory.mktemp("lcnet")
    cdir = str(root / "crypto")
    org1 = cryptogen.generate_org(cdir, "org1.example.com", n_peers=1,
                                  n_users=1)
    org2 = cryptogen.generate_org(cdir, "org2.example.com", n_peers=1,
                                  n_users=1)
    ordo = cryptogen.generate_org(cdir, "example.com", orderer_org=True)
    profile = {
        "Consortium": "SampleConsortium",
        "Capabilities": {"V2_0": True},
        "Application": {
            "Organizations": [
                {"Name": "Org1", "ID": "Org1MSP",
                 "MSPDir": os.path.join(org1, "msp")},
                {"Name": "Org2", "ID": "Org2MSP",
                 "MSPDir": os.path.join(org2, "msp")},
            ],
            "Capabilities": {"V2_0": True},
        },
        "Orderer": {
            "OrdererType": "solo",
            "Addresses": ["orderer0.example.com:7050"],
            "BatchTimeout": "100ms",
            "BatchSize": {"MaxMessageCount": 10},
            "Organizations": [
                {"Name": "OrdererOrg", "ID": "OrdererMSP",
                 "MSPDir": os.path.join(ordo, "msp"),
                 "OrdererEndpoints": ["orderer0.example.com:7050"]}],
            "Capabilities": {"V2_0": True},
        },
    }
    genesis = genesis_block(CHANNEL, new_channel_group(profile))
    csp = SWProvider()

    def local_msp(d, mspid):
        m = X509MSP(csp)
        m.setup(msp_config_from_dir(d, mspid, csp=csp))
        return m

    omsp = local_msp(os.path.join(ordo, "orderers",
                                  "orderer0.example.com", "msp"),
                     "OrdererMSP")
    reg = Registrar(str(root / "ord"),
                    omsp.get_default_signing_identity(), csp,
                    {"solo": solo.consenter})
    reg.join(genesis)
    bc = BroadcastHandler(reg)
    dh = DeliverHandler(reg.get_chain)

    peers, deliverers, users = {}, [], {}
    for org_name, org_dir, mspid in (("org1", org1, "Org1MSP"),
                                     ("org2", org2, "Org2MSP")):
        msp = local_msp(
            os.path.join(org_dir, "peers",
                         f"peer0.{org_name}.example.com", "msp"),
            mspid)
        peer = Peer(str(root / f"p_{org_name}"), msp, csp)
        ch = peer.join_channel(genesis)
        peer.chaincode_support.register("echo", EchoCC())
        d = Deliverer(ch, peer.signer, lambda: dh, peer.mcs)
        d.start()
        peers[org_name] = peer
        deliverers.append(d)
        users[org_name] = local_msp(
            os.path.join(org_dir, "users",
                         f"User1@{org_name}.example.com", "msp"),
            mspid).get_default_signing_identity()

    gws = {o: Gateway(peers[o], bc, users[o]) for o in peers}
    yield {"peers": peers, "gws": gws, "users": users,
           "deliver": dh, "root": root}
    for d in deliverers:
        d.stop()
    reg.halt()
    for p in peers.values():
        p.close()


def _sync(net, timeout_s=10.0):
    chans = [p.channel(CHANNEL) for p in net["peers"].values()]
    target = max(ch.ledger.height for ch in chans)
    for ch in chans:
        assert ch.wait_for_height(target, timeout_s)


DEFINITION = {
    "name": "echo",
    "sequence": 1,
    "version": "1.0",
    "endorsement_policy": "",
    "init_required": False,
    "collections": [],
}


class TestLifecycle:
    def test_approve_commit_flow(self, net):
        gws, peers = net["gws"], net["peers"]
        arg = json.dumps(DEFINITION).encode()

        # org1 approves (endorsed by org1's peer only)
        res = gws["org1"].submit_transaction(
            CHANNEL, "_lifecycle",
            [b"ApproveChaincodeDefinitionForMyOrg", arg],
            endorsing_peers=[peers["org1"]])
        assert res.status == txpb.TxValidationCode.VALID
        _sync(net)

        # readiness: org1 yes, org2 no
        resp = gws["org1"].evaluate(
            CHANNEL, "_lifecycle", [b"CheckCommitReadiness", arg])
        ready = json.loads(resp.payload)["approvals"]
        assert ready == {"Org1MSP": True, "Org2MSP": False}

        # premature commit refused at endorsement
        with pytest.raises(GatewayError, match="majority"):
            gws["org1"].endorse(
                CHANNEL, "_lifecycle",
                [b"CommitChaincodeDefinition", arg],
                endorsing_peers=[peers["org1"], peers["org2"]])

        # org2 approves, then commit (endorsed by both orgs)
        res = gws["org2"].submit_transaction(
            CHANNEL, "_lifecycle",
            [b"ApproveChaincodeDefinitionForMyOrg", arg],
            endorsing_peers=[peers["org2"]])
        assert res.status == txpb.TxValidationCode.VALID
        _sync(net)
        res = gws["org1"].submit_transaction(
            CHANNEL, "_lifecycle", [b"CommitChaincodeDefinition", arg],
            endorsing_peers=[peers["org1"], peers["org2"]])
        assert res.status == txpb.TxValidationCode.VALID
        _sync(net)

        # the committed definition is now the source of truth
        for p in peers.values():
            definition = p.channel(CHANNEL).chaincode_definition("echo")
            assert definition.sequence == 1
        resp = gws["org1"].evaluate(
            CHANNEL, "_lifecycle",
            [b"QueryChaincodeDefinition",
             json.dumps({"name": "echo"}).encode()])
        assert json.loads(resp.payload)["sequence"] == 1

        # and the chaincode is invocable under it
        res = gws["org1"].submit_transaction(
            CHANNEL, "echo", [b"put", b"lc", b"works"],
            endorsing_peers=[peers["org1"], peers["org2"]])
        assert res.status == txpb.TxValidationCode.VALID

    def test_forged_approval_for_other_org_invalidated(self, net):
        """org1 cannot submit an approval that writes ORG2's implicit
        collection: validation requires org2's endorsement for that
        write."""
        gws, peers = net["gws"], net["peers"]
        payload = dict(DEFINITION, name="forged")
        arg = json.dumps(payload).encode()
        # craft: endorse approve on org1's peer but as if org2 — the
        # SCC derives the org from the CREATOR, so use org2's user
        # identity with org1's endorsement
        from fabric_tpu.protoutil import txutils
        prop, tx_id = txutils.create_proposal(
            CHANNEL, "_lifecycle",
            [b"ApproveChaincodeDefinitionForMyOrg", arg],
            net["users"]["org2"].serialize())
        sp = txutils.sign_proposal(prop, net["users"]["org2"])
        resp = peers["org1"].endorser.process_proposal(sp)
        assert resp.response.status < 400
        env = txutils.create_signed_tx(prop, [resp],
                                       net["users"]["org2"])
        gws["org2"].submit(env)
        code = gws["org2"].commit_status(CHANNEL, tx_id, timeout_s=10)
        # endorsed only by org1's peer but writes org2's collection
        assert code == txpb.TxValidationCode.ENDORSEMENT_POLICY_FAILURE

    def test_sequence_must_increment(self, net):
        gws, peers = net["gws"], net["peers"]
        bad = dict(DEFINITION, sequence=5)
        arg = json.dumps(bad).encode()
        for org in ("org1", "org2"):
            gws[org].submit_transaction(
                CHANNEL, "_lifecycle",
                [b"ApproveChaincodeDefinitionForMyOrg", arg],
                endorsing_peers=[peers[org]])
        _sync(net)
        with pytest.raises(GatewayError, match="sequence"):
            gws["org1"].endorse(
                CHANNEL, "_lifecycle",
                [b"CommitChaincodeDefinition", arg],
                endorsing_peers=[peers["org1"], peers["org2"]])


class TestSystemChaincodes:
    def test_qscc_queries(self, net):
        gw = net["gws"]["org1"]
        resp = gw.evaluate(CHANNEL, "qscc",
                           [b"GetChainInfo", CHANNEL.encode()])
        assert resp.status == 200
        info = common.BlockchainInfo()
        info.ParseFromString(resp.payload)
        assert info.height >= 1
        resp = gw.evaluate(CHANNEL, "qscc",
                           [b"GetBlockByNumber", CHANNEL.encode(),
                            b"0"])
        blk = common.Block()
        blk.ParseFromString(resp.payload)
        assert blk.header.number == 0
        resp = gw.evaluate(CHANNEL, "qscc",
                           [b"GetTransactionByID", CHANNEL.encode(),
                            b"no-such-tx"])
        assert resp.status >= 400

    def test_cscc_queries(self, net):
        gw = net["gws"]["org1"]
        resp = gw.evaluate(CHANNEL, "cscc", [b"GetChannels"])
        assert CHANNEL in json.loads(resp.payload)["channels"]
        resp = gw.evaluate(CHANNEL, "cscc",
                           [b"GetConfigBlock", CHANNEL.encode()])
        blk = common.Block()
        blk.ParseFromString(resp.payload)
        assert blk.header.number == 0


class TestExternalChaincode:
    def test_ccaas_round_trip(self, net):
        """A chaincode served from its own gRPC process: full endorse →
        commit flow with tunneled state access."""

        class CounterCC(Chaincode):
            def init(self, stub):
                return shim.success()

            def invoke(self, stub):
                fn, params = stub.get_function_and_parameters()
                if fn == "bump":
                    cur = int(stub.get_state("n") or b"0")
                    stub.put_state("n", str(cur + 1).encode())
                    return shim.success(str(cur + 1).encode())
                if fn == "read":
                    return shim.success(stub.get_state("n") or b"0")
                if fn == "scan":
                    items = list(stub.get_state_by_range("", ""))
                    return shim.success(
                        str(len(items)).encode())
                return shim.error("unknown")

        server = ChaincodeServer("counter", CounterCC())
        server.start()
        try:
            peers, gws = net["peers"], net["gws"]
            for p in peers.values():
                p.chaincode_support.register(
                    "counter",
                    ExternalChaincodeClient("counter", server.address))
                from fabric_tpu.core.chaincode import (
                    ChaincodeDefinition,
                )
                p.channel(CHANNEL).define_chaincode(
                    ChaincodeDefinition(name="counter"))
            res = gws["org1"].submit_transaction(
                CHANNEL, "counter", [b"bump"],
                endorsing_peers=[peers["org1"], peers["org2"]])
            assert res.status == txpb.TxValidationCode.VALID
            _sync(net)
            resp = gws["org1"].evaluate(CHANNEL, "counter", [b"read"])
            assert resp.payload == b"1"
            resp = gws["org2"].evaluate(CHANNEL, "counter", [b"scan"])
            assert int(resp.payload) >= 1
        finally:
            for p in net["peers"].values():
                cc = p.chaincode_support._chaincodes.get("counter")
                if isinstance(cc, ExternalChaincodeClient):
                    cc.close()
            server.stop()
