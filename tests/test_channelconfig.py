"""channelconfig + configtx + configtxgen + cryptogen tests.

Mirrors the reference's `common/channelconfig/bundle_test.go`,
`common/configtx/validator_test.go` shapes: profile → genesis →
bundle; config updates validated against mod policies.
"""

import pytest

from fabric_tpu.bccsp.bccsp import ECDSAPrivateKeyImportOpts
from fabric_tpu.bccsp.sw import SWProvider
from fabric_tpu.common.channelconfig import Bundle, ConfigError
from fabric_tpu.common.configtx import (
    ConfigTxError,
    Validator,
    compute_update,
)
from fabric_tpu.common.policies import PolicyError
from fabric_tpu.internal import cryptogen
from fabric_tpu.internal.configtxgen import (
    config_from_block,
    genesis_block,
    new_channel_group,
)
from fabric_tpu.msp import msp_config_from_dir
from fabric_tpu.protos import common, configtx as ctxpb
from fabric_tpu import protoutil as pu


@pytest.fixture(scope="module")
def crypto(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("crypto"))
    org1 = cryptogen.generate_org(out, "org1.example.com", n_peers=2,
                                  n_users=1)
    org2 = cryptogen.generate_org(out, "org2.example.com", n_peers=1)
    ordo = cryptogen.generate_org(out, "example.com", orderer_org=True)
    return {"root": out, "org1": org1, "org2": org2, "orderer": ordo}


@pytest.fixture(scope="module")
def profile(crypto):
    import os
    return {
        "Consortium": "SampleConsortium",
        "Capabilities": {"V2_0": True},
        "Application": {
            "Organizations": [
                {"Name": "Org1", "ID": "Org1MSP",
                 "MSPDir": os.path.join(crypto["org1"], "msp"),
                 "AnchorPeers": [("peer0.org1.example.com", 7051)]},
                {"Name": "Org2", "ID": "Org2MSP",
                 "MSPDir": os.path.join(crypto["org2"], "msp")},
            ],
            "Capabilities": {"V2_0": True},
            "ACLs": {"event/Block": "/Channel/Application/Readers"},
        },
        "Orderer": {
            "OrdererType": "solo",
            "Addresses": ["orderer0.example.com:7050"],
            "BatchTimeout": "1s",
            "BatchSize": {"MaxMessageCount": 100},
            "Organizations": [
                {"Name": "OrdererOrg", "ID": "OrdererMSP",
                 "MSPDir": os.path.join(crypto["orderer"], "msp"),
                 "OrdererEndpoints": ["orderer0.example.com:7050"]},
            ],
            "Capabilities": {"V2_0": True},
        },
    }


@pytest.fixture(scope="module")
def bundle(profile):
    group = new_channel_group(profile)
    block = genesis_block("testchannel", group)
    config = config_from_block(block)
    return Bundle("testchannel", config, SWProvider())


class TestGenesisAndBundle:
    def test_genesis_block_shape(self, profile):
        block = genesis_block("testchannel", new_channel_group(profile))
        assert block.header.number == 0
        assert block.header.data_hash == pu.block_data_hash(block.data)
        env = pu.extract_envelope(block, 0)
        ch = pu.get_channel_header(pu.get_payload(env))
        assert ch.type == common.HeaderType.CONFIG
        assert ch.channel_id == "testchannel"

    def test_bundle_sections(self, bundle):
        assert set(bundle.application.orgs) == {"Org1", "Org2"}
        assert bundle.application.orgs["Org1"].mspid == "Org1MSP"
        assert bundle.application.orgs["Org1"].anchor_peers == \
            [("peer0.org1.example.com", 7051)]
        assert bundle.orderer.consensus_type == "solo"
        assert bundle.orderer.batch_size.max_message_count == 100
        assert bundle.orderer.batch_timeout_s == 1.0
        assert bundle.orderer.orgs["OrdererOrg"].endpoints == \
            ["orderer0.example.com:7050"]
        assert bundle.channel.orderer_addresses == \
            ["orderer0.example.com:7050"]
        assert bundle.application.acls["event/Block"] == \
            "/Channel/Application/Readers"
        assert bundle.application.capabilities.v20_validation()

    def test_bundle_msps(self, bundle):
        assert set(bundle.msp_manager.get_msps()) == \
            {"Org1MSP", "Org2MSP", "OrdererMSP"}

    def test_bundle_policy_tree(self, bundle):
        for path in ("/Channel/Readers", "/Channel/Writers",
                     "/Channel/Admins",
                     "/Channel/Application/Writers",
                     "/Channel/Application/Endorsement",
                     "/Channel/Application/LifecycleEndorsement",
                     "/Channel/Application/Org1/Readers",
                     "/Channel/Orderer/BlockValidation"):
            assert bundle.policy_manager.has_policy(path), path

    def test_unsupported_capability_rejected(self, profile):
        import copy
        p2 = copy.deepcopy(profile)
        p2["Capabilities"] = {"V99_9": True}
        config = config_from_block(
            genesis_block("c", new_channel_group(p2)))
        from fabric_tpu.common.capabilities import CapabilityError
        with pytest.raises(CapabilityError):
            Bundle("c", config, SWProvider())


class _DirSigner:
    """SigningIdentity-alike backed by a cryptogen user MSP dir."""

    def __init__(self, msp_dir, mspid):
        import os
        from cryptography.hazmat.primitives.serialization import (
            load_pem_private_key,
        )
        self.csp = SWProvider()
        with open(os.path.join(msp_dir, "signcerts", "cert.pem"),
                  "rb") as f:
            self._cert_pem = f.read()
        with open(os.path.join(msp_dir, "keystore", "key_sk"), "rb") as f:
            self._key = self.csp.key_import(
                load_pem_private_key(f.read(), None),
                ECDSAPrivateKeyImportOpts(ephemeral=True))
        self._mspid = mspid

    def serialize(self):
        from fabric_tpu.protos import msp as msppb
        return msppb.SerializedIdentity(
            mspid=self._mspid,
            id_bytes=self._cert_pem).SerializeToString(deterministic=True)

    def sign(self, msg):
        return self.csp.sign(self._key, self.csp.hash(msg))


def _shallow_read(group: ctxpb.ConfigGroup) -> ctxpb.ConfigGroup:
    """Version-only read-set entry for a group."""
    out = ctxpb.ConfigGroup()
    out.version = group.version
    return out


def _signed_update(update: ctxpb.ConfigUpdate, signers):
    env = ctxpb.ConfigUpdateEnvelope()
    env.config_update = pu.marshal(update)
    for s in signers:
        cs = env.signatures.add()
        sh = pu.create_signature_header(s.serialize())
        cs.signature_header = pu.marshal(sh)
        cs.signature = s.sign(bytes(cs.signature_header) +
                              bytes(env.config_update))
    return env


class TestConfigUpdate:
    @pytest.fixture()
    def state(self, profile, crypto, bundle):
        import copy
        import os
        config = config_from_block(
            genesis_block("testchannel", new_channel_group(profile)))
        validator = Validator("testchannel", config,
                              bundle.policy_manager)
        admin1 = _DirSigner(
            os.path.join(crypto["org1"], "users",
                         "Admin@org1.example.com", "msp"), "Org1MSP")
        admin2 = _DirSigner(
            os.path.join(crypto["org2"], "users",
                         "Admin@org2.example.com", "msp"), "Org2MSP")
        return {"config": config, "validator": validator,
                "admin1": admin1, "admin2": admin2,
                "profile": copy.deepcopy(profile)}

    def _updated_profile_config(self, state, mutate):
        import copy
        p = copy.deepcopy(state["profile"])
        mutate(p)
        new_config = ctxpb.Config(sequence=state["config"].sequence)
        new_config.channel_group.CopyFrom(new_channel_group(p))
        return new_config

    def test_batchsize_update_majority_admins(self, state):
        """Changing Orderer BatchSize under MAJORITY Admins of the
        orderer org — signed by app admins only — must fail; anchor-peer
        change under Org1 Admins signed by admin1 passes."""
        def mutate(p):
            p["Application"]["Organizations"][0]["AnchorPeers"] = \
                [("peer1.org1.example.com", 7051)]
        new_config = self._updated_profile_config(state, mutate)
        update = compute_update("testchannel", state["config"], new_config)
        env = _signed_update(update, [state["admin1"]])
        out = state["validator"].propose_config_update(env)
        assert out.sequence == 1
        # the new config carries the changed anchor peers
        b2 = Bundle("testchannel", out, SWProvider())
        assert b2.application.orgs["Org1"].anchor_peers == \
            [("peer1.org1.example.com", 7051)]

    def test_update_without_signatures_rejected(self, state):
        def mutate(p):
            p["Application"]["Organizations"][0]["AnchorPeers"] = \
                [("peer1.org1.example.com", 8888)]
        new_config = self._updated_profile_config(state, mutate)
        update = compute_update("testchannel", state["config"], new_config)
        env = _signed_update(update, [])
        with pytest.raises(ConfigTxError, match="mod_policy"):
            state["validator"].propose_config_update(env)

    def test_wrong_org_admin_rejected(self, state):
        def mutate(p):
            p["Application"]["Organizations"][0]["AnchorPeers"] = \
                [("peer1.org1.example.com", 9999)]
        new_config = self._updated_profile_config(state, mutate)
        update = compute_update("testchannel", state["config"], new_config)
        env = _signed_update(update, [state["admin2"]])   # org2 admin
        with pytest.raises(ConfigTxError, match="mod_policy"):
            state["validator"].propose_config_update(env)

    def test_wrong_channel_rejected(self, state):
        update = ctxpb.ConfigUpdate(channel_id="otherchannel")
        env = _signed_update(update, [state["admin1"]])
        with pytest.raises(ConfigTxError, match="channel"):
            state["validator"].propose_config_update(env)

    def test_stale_read_set_rejected(self, state):
        def mutate(p):
            p["Application"]["Organizations"][0]["AnchorPeers"] = \
                [("x", 1)]
        new_config = self._updated_profile_config(state, mutate)
        update = compute_update("testchannel", state["config"], new_config)
        # tamper: claim the org group is at version 5
        update.read_set.groups["Application"].groups["Org1"].version = 5
        env = _signed_update(update, [state["admin1"]])
        with pytest.raises(ConfigTxError, match="read_set"):
            state["validator"].propose_config_update(env)

    def test_no_change_rejected(self, state):
        with pytest.raises(ConfigTxError, match="no differences"):
            compute_update("testchannel", state["config"],
                           state["config"])

    def test_mod_policy_downgrade_without_bump_rejected(self, state):
        """A context (unbumped) group cannot swap its mod_policy — that
        would downgrade the gate without ever passing it."""
        update = ctxpb.ConfigUpdate(channel_id="testchannel")
        update.read_set.CopyFrom(
            _shallow_read(state["config"].channel_group))
        ws = update.write_set
        ws.version = state["config"].channel_group.version
        ws.mod_policy = "Readers"   # downgrade attempt
        env = _signed_update(update, [state["admin1"]])
        with pytest.raises(ConfigTxError, match="mod_policy"):
            state["validator"].propose_config_update(env)

    def test_new_group_with_nonzero_nested_version_rejected(self, state):
        update = ctxpb.ConfigUpdate(channel_id="testchannel")
        update.read_set.CopyFrom(
            _shallow_read(state["config"].channel_group))
        ws = update.write_set
        cur = state["config"].channel_group
        ws.version = cur.version + 1
        ws.mod_policy = cur.mod_policy
        # keep existing membership...
        for kind in ("groups", "values", "policies"):
            for name, elem in getattr(cur, kind).items():
                getattr(ws, kind)[name].CopyFrom(elem)
        # ...and add a new group whose nested value claims version 7
        evil = ws.groups["Evil"]
        evil.version = 0
        evil.mod_policy = "Admins"
        evil.values["X"].version = 7
        evil.values["X"].mod_policy = "Admins"
        env = _signed_update(update, [state["admin1"], state["admin2"]])
        with pytest.raises(ConfigTxError, match="version 0"):
            state["validator"].propose_config_update(env)

    def test_structural_errors_win_over_policy_errors(self, state):
        """The version-0 violation must be reported even with NO
        signatures at all (structural pre-pass runs before any policy
        evaluation)."""
        update = ctxpb.ConfigUpdate(channel_id="testchannel")
        update.read_set.CopyFrom(
            _shallow_read(state["config"].channel_group))
        ws = update.write_set
        cur = state["config"].channel_group
        ws.version = cur.version + 1
        ws.mod_policy = cur.mod_policy
        evil = ws.groups["Evil"]
        evil.mod_policy = "Admins"
        evil.values["X"].version = 7
        evil.values["X"].mod_policy = "Admins"
        env = _signed_update(update, [])
        with pytest.raises(ConfigTxError, match="version 0"):
            state["validator"].propose_config_update(env)

    def test_new_subtree_with_empty_mod_policy_rejected(self, state):
        update = ctxpb.ConfigUpdate(channel_id="testchannel")
        update.read_set.CopyFrom(
            _shallow_read(state["config"].channel_group))
        ws = update.write_set
        cur = state["config"].channel_group
        ws.version = cur.version + 1
        ws.mod_policy = cur.mod_policy
        evil = ws.groups["Evil"]
        evil.mod_policy = "Admins"
        evil.values["X"].version = 0   # version fine, mod_policy empty
        env = _signed_update(update, [])
        with pytest.raises(ConfigTxError, match="empty mod_policy"):
            state["validator"].propose_config_update(env)

    def test_modified_item_with_empty_mod_policy_rejected(self, state):
        """Clearing mod_policy must be an explicit rejection, not a
        silently-retained no-op (reference: update.go
        validateModPolicy)."""
        update = ctxpb.ConfigUpdate(channel_id="testchannel")
        update.read_set.CopyFrom(
            _shallow_read(state["config"].channel_group))
        ws = update.write_set
        cur = state["config"].channel_group
        ws.version = cur.version
        ws.mod_policy = cur.mod_policy
        app = ws.groups["Application"]
        cur_app = cur.groups["Application"]
        app.version = cur_app.version + 1
        app.mod_policy = ""   # attempt to clear
        for kind in ("groups", "values", "policies"):
            for name, elem in getattr(cur_app, kind).items():
                getattr(app, kind)[name].CopyFrom(elem)
        env = _signed_update(update, [state["admin1"], state["admin2"]])
        with pytest.raises(ConfigTxError, match="empty mod_policy"):
            state["validator"].propose_config_update(env)

    def test_mod_policy_only_change_is_an_update(self, state):
        import copy
        new_config = ctxpb.Config()
        new_config.CopyFrom(state["config"])
        new_config.channel_group.groups["Application"].mod_policy = \
            "Writers"
        update = compute_update("testchannel", state["config"],
                                new_config)
        assert update.write_set.groups["Application"].version == \
            state["config"].channel_group.groups["Application"].version + 1


class TestCryptogen:
    def test_layout(self, crypto):
        import os
        org1 = crypto["org1"]
        for sub in ("ca", "msp/cacerts", "peers/peer0.org1.example.com/msp",
                    "peers/peer1.org1.example.com/msp",
                    "users/Admin@org1.example.com/msp",
                    "users/User1@org1.example.com/msp"):
            assert os.path.isdir(os.path.join(org1, sub)), sub

    def test_msp_dir_loads_and_validates(self, crypto):
        import os
        from fabric_tpu.msp import X509MSP
        from fabric_tpu.protos import msp as msppb
        csp = SWProvider()
        msp = X509MSP(csp)
        msp.setup(msp_config_from_dir(
            os.path.join(crypto["org1"], "msp"), "Org1MSP"))
        with open(os.path.join(crypto["org1"],
                               "peers/peer0.org1.example.com/msp",
                               "signcerts/cert.pem"), "rb") as f:
            peer_pem = f.read()
        sid = msppb.SerializedIdentity(mspid="Org1MSP", id_bytes=peer_pem)
        ident = msp.deserialize_identity(
            sid.SerializeToString(deterministic=True))
        ident.validate()
        from fabric_tpu.protos import policies as polpb
        role = polpb.MSPPrincipal(
            classification=polpb.MSPPrincipal.ROLE,
            principal=polpb.MSPRole(
                msp_identifier="Org1MSP",
                role=polpb.MSPRole.PEER).SerializeToString())
        ident.satisfies_principal(role)
