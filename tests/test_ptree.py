"""Pallas VMEM tree-kernel tests (fabric_tpu/ops/ptree.py).

Ground truth: the Python-int projective reference in ops/p256.py (itself
pinned against OpenSSL in test_p256.py). The kernel body (tree_body) is
plain jnp, so most coverage runs it directly; one test goes through
pallas_call in interpreter mode.
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cryptography.hazmat.primitives.asymmetric import ec

from fabric_tpu.ops import comb, limb, p256, ptree

rng = random.Random(777)


def _rand_point():
    k = rng.randrange(1, p256.N)
    nums = ec.derive_private_key(k, ec.SECP256R1()) \
        .public_key().public_numbers()
    return (nums.x, nums.y, 1)


def _to_leading(vals, tile):
    """list of ints -> (L, *tile) limb array (canonical)."""
    arr = limb.ints_to_limbs(vals)                  # (B, L)
    return jnp.asarray(arr.T.reshape((limb.L,) + tile))


def _from_leading(a):
    """(L, *tile) -> flat list of ints."""
    flat = np.asarray(a).reshape(limb.L, -1)
    return [limb.limbs_to_int(flat[:, i]) for i in range(flat.shape[1])]


class TestKMod:
    def test_mul_add_sub_canonical_match_int(self):
        F = ptree._fpk()
        tile = (2, 4)
        n = 8
        xs = [rng.randrange(0, p256.P) for _ in range(n)]
        ys = [rng.randrange(0, p256.P) for _ in range(n)]
        a = _to_leading(xs, tile)
        b = _to_leading(ys, tile)
        got_mul = _from_leading(jax.jit(
            lambda a, b: F.canonical(F.mulmod(a, b)))(a, b))
        got_add = _from_leading(jax.jit(
            lambda a, b: F.canonical(F.addmod(a, b)))(a, b))
        got_sub = _from_leading(jax.jit(
            lambda a, b: F.canonical(F.submod(a, b)))(a, b))
        for i in range(n):
            assert got_mul[i] == xs[i] * ys[i] % p256.P
            assert got_add[i] == (xs[i] + ys[i]) % p256.P
            assert got_sub[i] == (xs[i] - ys[i]) % p256.P

    def test_semi_reduced_inputs_accepted(self):
        """mulmod over outputs of mulmod (semi-reduced) stays exact."""
        F = ptree._fpk()
        xs = [rng.randrange(0, p256.P) for _ in range(4)]
        a = _to_leading(xs, (1, 4))

        def chain(a):
            s = F.mulmod(a, a)
            s = F.mulmod(s, a)
            s = F.addmod(s, s)
            return F.canonical(F.submod(s, a))
        got = _from_leading(jax.jit(chain)(a))
        for i, x in enumerate(xs):
            assert got[i] == (2 * pow(x, 3, p256.P) - x) % p256.P


class TestCaddK:
    def test_matches_int_reference(self):
        pts1, pts2 = [], []
        p0 = _rand_point()
        cases = [
            (_rand_point(), _rand_point()),     # generic
            (p0, p0),                           # doubling via cadd
            (p0, (0, 1, 0)),                    # P + inf
            ((0, 1, 0), p0),                    # inf + P
            ((0, 1, 0), (0, 1, 0)),             # inf + inf
            (p0, (p0[0], p256.P - p0[1], 1)),   # P + (-P) -> inf
            (_rand_point(), _rand_point()),
            (_rand_point(), _rand_point()),
        ]
        pts1 = [c[0] for c in cases]
        pts2 = [c[1] for c in cases]
        tile = (2, 4)
        A = tuple(_to_leading([p[c] for p in pts1], tile) for c in range(3))
        B = tuple(_to_leading([p[c] for p in pts2], tile) for c in range(3))
        X, Y, Z = jax.jit(ptree.cadd_k)(A, B)
        F = ptree._fpk()
        got = [
            tuple(vals)
            for vals in zip(*[_from_leading(F.canonical(v))
                              for v in (X, Y, Z)])
        ]
        for i, (g, (q1, q2)) in enumerate(zip(got, cases)):
            want = p256.cadd_int(q1, q2)
            assert (p256.to_affine_int(g) ==
                    p256.to_affine_int(want)), f"case {i}"


class TestTreeBody:
    @pytest.mark.parametrize("m,b", [(32, 128), (48, 128), (8, 256)])
    def test_collapse_tile_matches_body(self, m, b):
        X = jnp.zeros((limb.L, m, b), jnp.int32)
        ts, tr = ptree._collapse_tile(m, b)
        r = jnp.zeros((limb.L, ts, tr), jnp.int32)
        pm = jnp.ones((ts, tr), jnp.int32)
        out = ptree.tree_body(X, X, X, r, r, pm)
        assert out.shape == (ts, tr)

    def test_sum_matches_int_reference(self):
        """M=8 random points per lane, B=128 lanes (2 interesting)."""
        M, B = 8, 128
        lanes = [[_rand_point() for _ in range(M)] for _ in range(2)]
        # lane 1 gets some infinities mixed in
        lanes[1][2] = (0, 1, 0)
        lanes[1][5] = (0, 1, 0)
        pts = np.zeros((B, M, 3, limb.L), np.int32)
        for ln in range(2):
            for m in range(M):
                for c in range(3):
                    pts[ln, m, c] = limb.int_to_limbs(lanes[ln][m][c])
        # remaining lanes: infinity everywhere (premask off)
        for ln in range(2, B):
            for m in range(M):
                pts[ln, m, 1] = limb.int_to_limbs(1)

        # expected sums
        want = []
        for ln in range(2):
            acc = (0, 1, 0)
            for m in range(M):
                acc = p256.cadd_int(acc, lanes[ln][m])
            want.append(p256.to_affine_int(acc))

        # drive through the full kernel contract: accept iff x(R) == r
        r_vals = []
        for ln in range(B):
            if ln < 2 and want[ln] is not None:
                r_vals.append(want[ln][0] % p256.N)
            else:
                r_vals.append(1)
        rpn_vals = [rv + p256.N if rv + p256.N < p256.P else rv
                    for rv in r_vals]
        premask = np.zeros(B, bool)
        premask[:2] = True
        out = ptree.tree_verify_points(
            jnp.asarray(pts), jnp.asarray(limb.ints_to_limbs(r_vals)),
            jnp.asarray(limb.ints_to_limbs(rpn_vals)),
            jnp.asarray(premask), interpret=True)
        out = np.asarray(out)
        assert out[:2].all()            # correct x(R) accepted
        assert not out[2:].any()        # premask honored

    def test_wrong_r_rejected(self):
        M, B = 4, 128
        lane = [_rand_point() for _ in range(M)]
        pts = np.zeros((B, M, 3, limb.L), np.int32)
        for m in range(M):
            for c in range(3):
                pts[0, m, c] = limb.int_to_limbs(lane[m][c])
        for ln in range(1, B):
            for m in range(M):
                pts[ln, m, 1] = limb.int_to_limbs(1)
        acc = (0, 1, 0)
        for m in range(M):
            acc = p256.cadd_int(acc, lane[m])
        x_aff = p256.to_affine_int(acc)[0]
        wrong = (x_aff + 1) % p256.N or 1
        r_vals = [wrong] * B
        rpn_vals = [rv + p256.N if rv + p256.N < p256.P else rv
                    for rv in r_vals]
        premask = np.ones(B, bool)
        out = np.asarray(ptree.tree_verify_points(
            jnp.asarray(pts), jnp.asarray(limb.ints_to_limbs(r_vals)),
            jnp.asarray(limb.ints_to_limbs(rpn_vals)),
            jnp.asarray(premask), interpret=True))
        assert not out[0]


class TestCombPallasParity:
    def test_comb_verify_pallas_matches_xla(self):
        """Full 8-bit comb verify: tree='pallas' (interpret) ==
        tree='xla' over valid + tampered + masked lanes."""
        import hashlib

        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric.utils import (
            decode_dss_signature,
        )

        B, K = 8, 2
        privs = [ec.generate_private_key(ec.SECP256R1()) for _ in range(K)]
        key_pts = [p.public_key().public_numbers() for p in privs]
        words = np.zeros((B, 8), dtype=np.uint32)
        rs, ws, rpns, key_idx = [], [], [], []
        for i in range(B):
            k = i % K
            msg = f"ptree tx {i}".encode() * (i + 1)
            der = privs[k].sign(msg, ec.ECDSA(hashes.SHA256()))
            r, s = decode_dss_signature(der)
            if i == 5:
                msg += b"!"             # tamper
            words[i] = np.frombuffer(
                hashlib.sha256(msg).digest(), dtype=">u4")
            rs.append(r)
            ws.append(pow(s, -1, p256.N))
            rpns.append(r + p256.N if r + p256.N < p256.P else r)
            key_idx.append(k)
        premask = np.ones(B, bool)
        premask[6] = False

        qx = jnp.asarray(limb.ints_to_limbs([p.x for p in key_pts]))
        qy = jnp.asarray(limb.ints_to_limbs([p.y for p in key_pts]))
        q_flat = jax.jit(comb.build_q_tables)(qx, qy)
        args = (jnp.asarray(words),
                jnp.asarray(key_idx, dtype=jnp.int32), q_flat,
                jnp.asarray(limb.ints_to_limbs(rs)),
                jnp.asarray(limb.ints_to_limbs(rpns)),
                jnp.asarray(limb.ints_to_limbs(ws)),
                jnp.asarray(premask))
        got_x = np.asarray(comb.comb_verify_with_tables(*args))
        got_p = np.asarray(comb.comb_verify_with_tables(
            *args, tree="pallas"))
        assert got_x.tolist() == got_p.tolist()
        assert got_x.tolist() == [True, True, True, True, True,
                                  False, False, True]
