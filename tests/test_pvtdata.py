"""Private data collections: hashing, MVCC, distribution, BTL expiry.

Mirrors the reference's pvtdata semantics (SURVEY §2.5/§2.6,
`integration/pvtdata`): cleartext never on-chain; hashed reads/writes
drive MVCC identically on every peer; non-endorsing peers commit hashes
and record the missing cleartext; BTL purges cleartext AND hashes.
"""

import os

import pytest

from fabric_tpu.bccsp.sw import SWProvider
from fabric_tpu.common.deliver import DeliverHandler
from fabric_tpu.common.policies.policydsl import from_string
from fabric_tpu.core.chaincode import (
    Chaincode, ChaincodeDefinition, shim,
)
from fabric_tpu.core.transientstore import TransientStore
from fabric_tpu.internal import cryptogen
from fabric_tpu.internal.configtxgen import genesis_block, new_channel_group
from fabric_tpu.ledger import CollectionConfig
from fabric_tpu.ledger.pvtdata import hash_ns, key_hash, pvt_ns, value_hash
from fabric_tpu.msp import msp_config_from_dir
from fabric_tpu.msp.mspimpl import X509MSP
from fabric_tpu.orderer import solo
from fabric_tpu.orderer.broadcast import BroadcastHandler
from fabric_tpu.orderer.multichannel import Registrar
from fabric_tpu.peer import Peer
from fabric_tpu.peer.deliverclient import Deliverer
from fabric_tpu.peer.gateway import Gateway
from fabric_tpu.protos import policies as polpb, rwset as rwpb
from fabric_tpu.protos import transaction as txpb

CHANNEL = "pvtchannel"


class MarbleCC(Chaincode):
    """The pvtdata marbles analog: public name, private price."""

    def init(self, stub):
        return shim.success()

    def invoke(self, stub):
        fn, params = stub.get_function_and_parameters()
        if fn == "put":
            # transient map carries the secret (reference pattern:
            # pvt payload rides in transient, never in args).
            # read-before-write so MVCC guards concurrent updates
            stub.get_private_data("prices", params[0])
            price = stub.get_transient()["price"]
            stub.put_state(params[0], b"marble")
            stub.put_private_data("prices", params[0], price)
            return shim.success()
        if fn == "getprice":
            val = stub.get_private_data("prices", params[0])
            if val is None:
                return shim.error("no price")
            return shim.success(val)
        if fn == "gethash":
            h = stub.get_private_data_hash("prices", params[0])
            return shim.success(h or b"")
        if fn == "delprice":
            stub.del_private_data("prices", params[0])
            return shim.success()
        return shim.error("unknown")


def _or_policy(*orgs) -> bytes:
    spec = "OR(" + ", ".join(f"'{o}.member'" for o in orgs) + ")"
    return polpb.ApplicationPolicy(
        signature_policy=from_string(spec)).SerializeToString()


@pytest.fixture(scope="module")
def network(tmp_path_factory):
    root = tmp_path_factory.mktemp("pvtnet")
    cdir = str(root / "crypto")
    org1 = cryptogen.generate_org(cdir, "org1.example.com", n_peers=1,
                                  n_users=1)
    org2 = cryptogen.generate_org(cdir, "org2.example.com", n_peers=1,
                                  n_users=1)
    ordo = cryptogen.generate_org(cdir, "example.com", orderer_org=True)
    profile = {
        "Consortium": "SampleConsortium",
        "Capabilities": {"V2_0": True},
        "Application": {
            "Organizations": [
                {"Name": "Org1", "ID": "Org1MSP",
                 "MSPDir": os.path.join(org1, "msp")},
                {"Name": "Org2", "ID": "Org2MSP",
                 "MSPDir": os.path.join(org2, "msp")},
            ],
            "Capabilities": {"V2_0": True},
        },
        "Orderer": {
            "OrdererType": "solo",
            "Addresses": ["orderer0.example.com:7050"],
            "BatchTimeout": "150ms",
            "BatchSize": {"MaxMessageCount": 10},
            "Organizations": [
                {"Name": "OrdererOrg", "ID": "OrdererMSP",
                 "MSPDir": os.path.join(ordo, "msp"),
                 "OrdererEndpoints": ["orderer0.example.com:7050"]},
            ],
            "Capabilities": {"V2_0": True},
        },
    }
    genesis = genesis_block(CHANNEL, new_channel_group(profile))
    csp = SWProvider()

    def local_msp(msp_dir, mspid):
        m = X509MSP(csp)
        m.setup(msp_config_from_dir(msp_dir, mspid, csp=csp))
        return m

    orderer_msp = local_msp(
        os.path.join(ordo, "orderers", "orderer0.example.com", "msp"),
        "OrdererMSP")
    registrar = Registrar(str(root / "orderer"),
                          orderer_msp.get_default_signing_identity(),
                          csp, {"solo": solo.consenter})
    registrar.join(genesis)
    broadcast = BroadcastHandler(registrar)
    deliver = DeliverHandler(registrar.get_chain)

    definition = ChaincodeDefinition(
        name="marbles",
        # OR policy: one org's endorsement suffices — lets us create
        # blocks where org2 never saw the cleartext
        endorsement_policy=_or_policy("Org1MSP", "Org2MSP"),
        collections=(
            CollectionConfig(name="prices",
                             member_orgs=("Org1MSP", "Org2MSP"),
                             block_to_live=0),
            CollectionConfig(name="ephemeral",
                             member_orgs=("Org1MSP",),
                             block_to_live=1),
        ))

    peers, deliverers = {}, []
    for org_name, org_dir, mspid in (("org1", org1, "Org1MSP"),
                                     ("org2", org2, "Org2MSP")):
        msp = local_msp(
            os.path.join(org_dir, "peers",
                         f"peer0.{org_name}.example.com", "msp"),
            mspid)
        peer = Peer(str(root / f"peer_{org_name}"), msp, csp)
        channel = peer.join_channel(genesis)
        peer.chaincode_support.register("marbles", MarbleCC())
        channel.define_chaincode(definition)
        d = Deliverer(channel, peer.signer, lambda: deliver, peer.mcs)
        d.start()
        peers[org_name] = peer
        deliverers.append(d)

    user_msp = local_msp(
        os.path.join(org1, "users", "User1@org1.example.com", "msp"),
        "Org1MSP")
    gateway = Gateway(peers["org1"], broadcast,
                      user_msp.get_default_signing_identity())
    yield {"peers": peers, "gateway": gateway, "csp": csp}
    for d in deliverers:
        d.stop()
    registrar.halt()
    for p in peers.values():
        p.close()


def _sync(net, timeout_s=10.0):
    chans = [net["peers"][o].channel(CHANNEL) for o in ("org1", "org2")]
    target = max(ch.ledger.height for ch in chans)
    for ch in chans:
        assert ch.wait_for_height(target, timeout_s)


class TestPrivateData:
    def test_cleartext_on_endorser_hash_on_chain(self, network):
        gw = network["gateway"]
        res = gw.submit_transaction(
            CHANNEL, "marbles", [b"put", b"m1"],
            transient={"price": b"99"},
            endorsing_peers=[network["peers"]["org1"]])
        assert res.status == txpb.TxValidationCode.VALID
        _sync(network)

        led1 = network["peers"]["org1"].channel(CHANNEL).ledger
        led2 = network["peers"]["org2"].channel(CHANNEL).ledger
        # org1 endorsed → has cleartext
        assert led1.get_private_data("marbles", "prices", "m1") == b"99"
        # both peers hold the HASH (public, deterministic)
        for led in (led1, led2):
            assert led.get_private_data_hash(
                "marbles", "prices", "m1") == value_hash(b"99")
        # org2 never saw the cleartext → missing entry recorded
        assert led2.get_private_data("marbles", "prices", "m1") is None
        missing = led2.missing_pvt_data()
        assert any(m.namespace == "marbles" and
                   m.collection == "prices" for m in missing)
        # and org1 has no missing entries for this collection
        assert not any(m.collection == "prices"
                       for m in led1.missing_pvt_data())

    def test_cleartext_never_in_block(self, network):
        """The secret must not appear anywhere in the committed block
        bytes — the core privacy property."""
        gw = network["gateway"]
        secret = b"supersecret-7741"
        gw.submit_transaction(
            CHANNEL, "marbles", [b"put", b"m2"],
            transient={"price": secret},
            endorsing_peers=[network["peers"]["org1"]])
        _sync(network)
        ch = network["peers"]["org1"].channel(CHANNEL)
        for num in range(ch.ledger.height):
            blk = ch.get_block(num)
            assert secret not in blk.SerializeToString()

    def test_evaluate_reads_private_state(self, network):
        gw = network["gateway"]
        gw.submit_transaction(
            CHANNEL, "marbles", [b"put", b"m3"],
            transient={"price": b"55"},
            endorsing_peers=[network["peers"]["org1"]])
        _sync(network)
        resp = gw.evaluate(CHANNEL, "marbles", [b"getprice", b"m3"])
        assert resp.status == 200 and resp.payload == b"55"
        resp = gw.evaluate(CHANNEL, "marbles", [b"gethash", b"m3"])
        assert resp.payload == value_hash(b"55")

    def test_pvt_mvcc_conflict(self, network):
        """Two txs in one block reading the same private key: hashed
        reads collide → second gets MVCC_READ_CONFLICT, on BOTH peers
        (org2 validates purely from hashes)."""
        gw = network["gateway"]
        gw.submit_transaction(
            CHANNEL, "marbles", [b"put", b"race"],
            transient={"price": b"1"},
            endorsing_peers=[network["peers"]["org1"]])
        env1, tx1 = gw.endorse(
            CHANNEL, "marbles", [b"put", b"race"],
            transient={"price": b"2"},
            endorsing_peers=[network["peers"]["org1"]])
        env2, tx2 = gw.endorse(
            CHANNEL, "marbles", [b"put", b"race"],
            transient={"price": b"3"},
            endorsing_peers=[network["peers"]["org1"]])
        gw.submit(env1)
        gw.submit(env2)
        c1 = gw.commit_status(CHANNEL, tx1, timeout_s=10)
        c2 = gw.commit_status(CHANNEL, tx2, timeout_s=10)
        assert sorted([c1, c2]) == sorted(
            [txpb.TxValidationCode.VALID,
             txpb.TxValidationCode.MVCC_READ_CONFLICT])
        _sync(network)
        # org2, validating from hashes alone, reached the same verdict
        ch2 = network["peers"]["org2"].channel(CHANNEL)
        assert ch2.tx_validation_code(tx1) == c1
        assert ch2.tx_validation_code(tx2) == c2

    def test_btl_expiry_purges_cleartext_and_hash(self, network):
        """block_to_live=1: data written at block N is purged at commit
        of block N+2."""
        gw = network["gateway"]
        org1 = network["peers"]["org1"]

        class EphemeralCC(MarbleCC):
            def invoke(self, stub):
                fn, params = stub.get_function_and_parameters()
                if fn == "eput":
                    stub.put_private_data(
                        "ephemeral", params[0],
                        stub.get_transient()["v"])
                    return shim.success()
                return super().invoke(stub)

        for p in network["peers"].values():
            p.chaincode_support.register("marbles", EphemeralCC())

        gw.submit_transaction(CHANNEL, "marbles", [b"eput", b"tmp"],
                              transient={"v": b"gone-soon"},
                              endorsing_peers=[org1])
        _sync(network)
        led = org1.channel(CHANNEL).ledger
        assert led.get_private_data("marbles", "ephemeral",
                                    "tmp") == b"gone-soon"
        kh = key_hash("tmp")
        assert led.state_db.get_state(
            hash_ns("marbles", "ephemeral"), kh.hex()) is not None

        # two more blocks → purge fires (expiry = write_block + 1 + 1)
        for i in range(2):
            gw.submit_transaction(
                CHANNEL, "marbles", [b"put", f"fill{i}".encode()],
                transient={"price": b"0"},
                endorsing_peers=[org1])
        _sync(network)
        assert led.get_private_data("marbles", "ephemeral",
                                    "tmp") is None
        assert led.state_db.get_state(
            hash_ns("marbles", "ephemeral"), kh.hex()) is None
        # non-expiring collection data survives
        assert led.get_private_data("marbles", "prices",
                                    "m1") == b"99"

    def test_delete_private_data(self, network):
        gw = network["gateway"]
        org1 = network["peers"]["org1"]
        gw.submit_transaction(CHANNEL, "marbles", [b"put", b"delme"],
                              transient={"price": b"11"},
                              endorsing_peers=[org1])
        gw.submit_transaction(CHANNEL, "marbles",
                              [b"delprice", b"delme"],
                              endorsing_peers=[org1])
        _sync(network)
        led = org1.channel(CHANNEL).ledger
        assert led.get_private_data("marbles", "prices",
                                    "delme") is None
        assert led.get_private_data_hash("marbles", "prices",
                                         "delme") is None


class TestTransientStore:
    def _pvt(self, ns="ns", coll="c", key="k", val=b"v"):
        tx = rwpb.TxPvtReadWriteSet(data_model=rwpb.TxReadWriteSet.KV)
        kv = rwpb.KVRWSet()
        kv.writes.add(key=key, value=val)
        tx.ns_pvt_rwset.add(namespace=ns).collection_pvt_rwset.add(
            collection_name=coll,
            rwset=kv.SerializeToString(deterministic=True))
        return tx

    def test_persist_get_purge(self, tmp_path):
        ts = TransientStore(str(tmp_path / "t.db"))
        ts.persist("tx1", 5, self._pvt(val=b"a"))
        ts.persist("tx2", 7, self._pvt(val=b"b"))
        assert ts.get("tx1") is not None
        assert ts.get("nope") is None
        ts.purge_by_txids(["tx1"])
        assert ts.get("tx1") is None
        assert ts.get("tx2") is not None
        assert ts.min_height() == 7
        ts.purge_below_height(8)
        assert ts.get("tx2") is None
        assert ts.min_height() is None
        ts.close()

    def test_latest_endorsement_wins(self, tmp_path):
        ts = TransientStore(str(tmp_path / "t.db"))
        ts.persist("tx", 3, self._pvt(val=b"old"))
        ts.persist("tx", 9, self._pvt(val=b"new"))
        got = ts.get("tx")
        kv = rwpb.KVRWSet()
        kv.ParseFromString(
            got.ns_pvt_rwset[0].collection_pvt_rwset[0].rwset)
        assert kv.writes[0].value == b"new"
        ts.close()
