"""TLS on the gRPC surface: cryptogen TLS material + secure channels.

Reference: cryptogen's tlsca/ + per-node tls/ output and
`internal/pkg/comm` SecureOptions — a peer serving with its TLS server
cert, clients verifying against the org's TLS CA.
"""

import os

import grpc
import pytest

from fabric_tpu.comm.server import GRPCServer, ServerConfig, UNARY_UNARY
from fabric_tpu.comm.clients import _uu, channel_to
from fabric_tpu.internal import cryptogen
from fabric_tpu.protos import gossip as gpb


@pytest.fixture(scope="module")
def tls_org(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("tls"))
    org = cryptogen.generate_org(root, "org1.example.com", n_peers=1)
    node = os.path.join(org, "peers", "peer0.org1.example.com")
    return {
        "ca": open(os.path.join(org, "tlsca",
                                "tlsca.org1.example.com-cert.pem"),
                   "rb").read(),
        "cert": open(os.path.join(node, "tls", "server.crt"),
                     "rb").read(),
        "key": open(os.path.join(node, "tls", "server.key"),
                    "rb").read(),
    }


def _tls_server(tls_org, client_cas=None) -> GRPCServer:
    server = GRPCServer(ServerConfig(
        address="localhost:0", tls_cert=tls_org["cert"],
        tls_key=tls_org["key"], client_root_cas=client_cas))
    server.add_service("ftpu.Test", {
        "Ping": (UNARY_UNARY, lambda req, ctx: gpb.Empty(),
                 gpb.Empty, gpb.Empty)})
    server.start()
    return server


class TestTLS:
    def test_material_layout(self, tls_org):
        assert b"BEGIN CERTIFICATE" in tls_org["ca"]
        assert b"BEGIN CERTIFICATE" in tls_org["cert"]
        assert b"BEGIN PRIVATE KEY" in tls_org["key"]

    def test_tls_round_trip(self, tls_org):
        server = _tls_server(tls_org)
        try:
            ch = channel_to(server.address, tls_root_ca=tls_org["ca"])
            call = _uu(ch, "ftpu.Test", "Ping", gpb.Empty, gpb.Empty)
            assert call(gpb.Empty(), timeout=10) is not None
        finally:
            server.stop()

    def test_untrusted_root_rejected(self, tls_org, tmp_path):
        other = cryptogen.generate_org(str(tmp_path),
                                       "evil.example.com", n_peers=1)
        wrong_ca = open(os.path.join(
            other, "tlsca", "tlsca.evil.example.com-cert.pem"),
            "rb").read()
        server = _tls_server(tls_org)
        try:
            ch = channel_to(server.address, tls_root_ca=wrong_ca)
            call = _uu(ch, "ftpu.Test", "Ping", gpb.Empty, gpb.Empty)
            with pytest.raises(grpc.RpcError):
                call(gpb.Empty(), timeout=5)
        finally:
            server.stop()

    def test_mutual_tls_requires_client_cert(self, tls_org):
        """mTLS: a server demanding client certs rejects bare-TLS
        clients and accepts ones presenting a cert from the org CA."""
        server = _tls_server(tls_org, client_cas=tls_org["ca"])
        try:
            ch = channel_to(server.address, tls_root_ca=tls_org["ca"])
            call = _uu(ch, "ftpu.Test", "Ping", gpb.Empty, gpb.Empty)
            with pytest.raises(grpc.RpcError):
                call(gpb.Empty(), timeout=5)
            ch = channel_to(server.address, tls_root_ca=tls_org["ca"],
                            client_cert=tls_org["cert"],
                            client_key=tls_org["key"])
            call = _uu(ch, "ftpu.Test", "Ping", gpb.Empty, gpb.Empty)
            assert call(gpb.Empty(), timeout=10) is not None
        finally:
            server.stop()
