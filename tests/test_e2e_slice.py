"""End-to-end slice: endorse → order (solo) → deliver → batched
validate → commit.

The rebuild's first benchmarkable milestone (SURVEY.md §7 step 8):
a 2-org network, one solo orderer, two in-process peers, an in-process
KV chaincode, a gateway client. Exercises the entire north-star path
of SURVEY §3.4 — including ONE batched `verify_batch` per block in the
txvalidator — and the failure modes (bad endorsement, tampered block,
duplicate txid).

Reference analog: `integration/e2e/e2e_test.go` under the nwo harness
(in-process here; the multi-process nwo equivalent comes with the gRPC
comm layer).
"""

import os

import pytest

from fabric_tpu.bccsp.sw import SWProvider
from fabric_tpu.common.deliver import DeliverHandler
from fabric_tpu.core.chaincode import Chaincode, ChaincodeDefinition
from fabric_tpu.core.chaincode import shim
from fabric_tpu.internal import cryptogen
from fabric_tpu.internal.configtxgen import genesis_block, new_channel_group
from fabric_tpu.msp import msp_config_from_dir
from fabric_tpu.msp.mspimpl import X509MSP
from fabric_tpu.orderer import solo
from fabric_tpu.orderer.broadcast import BroadcastHandler
from fabric_tpu.orderer.multichannel import Registrar
from fabric_tpu.peer import Peer
from fabric_tpu.peer.deliverclient import Deliverer
from fabric_tpu.peer.gateway import Gateway, GatewayError
from fabric_tpu.protos import transaction as txpb

CHANNEL = "testchannel"


class KVChaincode(Chaincode):
    """The e2e asset-transfer-basic analog."""

    def init(self, stub):
        return shim.success()

    def invoke(self, stub):
        fn, params = stub.get_function_and_parameters()
        if fn == "put":
            stub.put_state(params[0], params[1].encode())
            stub.set_event("put", params[0].encode())
            return shim.success()
        if fn == "get":
            val = stub.get_state(params[0])
            if val is None:
                return shim.error(f"key {params[0]} not found")
            return shim.success(val)
        if fn == "transfer":
            src, dst, amt = params[0], params[1], int(params[2])
            a = int(stub.get_state(src) or b"0")
            b = int(stub.get_state(dst) or b"0")
            if a < amt:
                return shim.error("insufficient funds")
            stub.put_state(src, str(a - amt).encode())
            stub.put_state(dst, str(b + amt).encode())
            return shim.success()
        return shim.error(f"unknown function {fn}")


@pytest.fixture(scope="module")
def network(tmp_path_factory):
    root = tmp_path_factory.mktemp("net")
    cdir = str(root / "crypto")
    org1 = cryptogen.generate_org(cdir, "org1.example.com", n_peers=1,
                                  n_users=1)
    org2 = cryptogen.generate_org(cdir, "org2.example.com", n_peers=1,
                                  n_users=1)
    ordo = cryptogen.generate_org(cdir, "example.com", orderer_org=True)

    profile = {
        "Consortium": "SampleConsortium",
        "Capabilities": {"V2_0": True},
        "Application": {
            "Organizations": [
                {"Name": "Org1", "ID": "Org1MSP",
                 "MSPDir": os.path.join(org1, "msp")},
                {"Name": "Org2", "ID": "Org2MSP",
                 "MSPDir": os.path.join(org2, "msp")},
            ],
            "Capabilities": {"V2_0": True},
        },
        "Orderer": {
            "OrdererType": "solo",
            "Addresses": ["orderer0.example.com:7050"],
            "BatchTimeout": "250ms",
            "BatchSize": {"MaxMessageCount": 10},
            "Organizations": [
                {"Name": "OrdererOrg", "ID": "OrdererMSP",
                 "MSPDir": os.path.join(ordo, "msp"),
                 "OrdererEndpoints": ["orderer0.example.com:7050"]},
            ],
            "Capabilities": {"V2_0": True},
        },
    }
    genesis = genesis_block(CHANNEL, new_channel_group(profile))
    csp = SWProvider()

    def local_msp(msp_dir, mspid):
        m = X509MSP(csp)
        m.setup(msp_config_from_dir(msp_dir, mspid, csp=csp))
        return m

    # ---- ordering service ----
    orderer_msp = local_msp(
        os.path.join(ordo, "orderers", "orderer0.example.com", "msp"),
        "OrdererMSP")
    registrar = Registrar(str(root / "orderer"),
                          orderer_msp.get_default_signing_identity(),
                          csp, {"solo": solo.consenter})
    registrar.join(genesis)
    broadcast = BroadcastHandler(registrar)
    deliver = DeliverHandler(registrar.get_chain)

    # ---- peers ----
    peers = {}
    deliverers = []
    for org_name, org_dir, mspid in (("org1", org1, "Org1MSP"),
                                     ("org2", org2, "Org2MSP")):
        msp = local_msp(
            os.path.join(org_dir, "peers",
                         f"peer0.{org_name}.example.com", "msp"),
            mspid)
        peer = Peer(str(root / f"peer_{org_name}"), msp, csp)
        channel = peer.join_channel(genesis)
        peer.chaincode_support.register("basic", KVChaincode())
        channel.define_chaincode(ChaincodeDefinition(name="basic"))
        d = Deliverer(channel, peer.signer, lambda: deliver, peer.mcs)
        d.start()
        peers[org_name] = peer
        deliverers.append(d)

    # ---- gateway client (Org1 user) ----
    user_msp = local_msp(
        os.path.join(org1, "users", "User1@org1.example.com", "msp"),
        "Org1MSP")
    gateway = Gateway(peers["org1"],
                      broadcast,
                      user_msp.get_default_signing_identity())

    yield {
        "peers": peers, "gateway": gateway, "registrar": registrar,
        "deliver": deliver, "csp": csp, "genesis": genesis,
    }

    for d in deliverers:
        d.stop()
    registrar.halt()
    for p in peers.values():
        p.close()


def _both_peers(net):
    return [net["peers"]["org1"], net["peers"]["org2"]]


def _sync(net, timeout_s=10.0):
    """Wait until every peer's channel has caught up to the tallest
    ledger (commit_status only proves finality on the gateway's local
    peer; other peers commit via their own deliverers)."""
    chans = [p.channel(CHANNEL) for p in _both_peers(net)]
    target = max(ch.ledger.height for ch in chans)
    for ch in chans:
        assert ch.wait_for_height(target, timeout_s), (
            f"peer stuck at height {ch.ledger.height} < {target}")


class TestEndToEnd:
    def test_submit_and_commit(self, network):
        gw = network["gateway"]
        res = gw.submit_transaction(
            CHANNEL, "basic", [b"put", b"alice", b"100"],
            endorsing_peers=_both_peers(network))
        assert res.status == txpb.TxValidationCode.VALID

        # committed state is visible on BOTH peers (org2 got the block
        # via deliver → batched validate → commit)
        _sync(network)
        for peer in _both_peers(network):
            ch = peer.channel(CHANNEL)
            assert ch.ledger.get_state("basic", "alice") == b"100"

    def test_evaluate_reads_committed_state(self, network):
        gw = network["gateway"]
        gw.submit_transaction(CHANNEL, "basic",
                              [b"put", b"bob", b"42"],
                              endorsing_peers=_both_peers(network))
        resp = gw.evaluate(CHANNEL, "basic", [b"get", b"bob"])
        assert resp.status == 200
        assert resp.payload == b"42"

    def test_transfer_chain(self, network):
        gw = network["gateway"]
        gw.submit_transaction(CHANNEL, "basic",
                              [b"put", b"carol", b"50"],
                              endorsing_peers=_both_peers(network))
        # both endorsers must simulate against the same height or the
        # endorsement payloads diverge (clients retry in production)
        _sync(network)
        res = gw.submit_transaction(
            CHANNEL, "basic", [b"transfer", b"alice", b"carol", b"30"],
            endorsing_peers=_both_peers(network))
        assert res.status == txpb.TxValidationCode.VALID
        _sync(network)
        ch = network["peers"]["org2"].channel(CHANNEL)
        assert ch.ledger.get_state("basic", "alice") == b"70"
        assert ch.ledger.get_state("basic", "carol") == b"80"

    def test_single_org_endorsement_fails_majority_policy(self, network):
        """2-of-2 MAJORITY endorsement: one org's endorsement must be
        rejected at validation (ENDORSEMENT_POLICY_FAILURE), not at
        endorsement time — exactly the reference's VSCC behavior."""
        gw = network["gateway"]
        env, tx_id = gw.endorse(
            CHANNEL, "basic", [b"put", b"mallory", b"1"],
            endorsing_peers=[network["peers"]["org1"]])
        gw.submit(env)
        code = gw.commit_status(CHANNEL, tx_id, timeout_s=10)
        assert code == txpb.TxValidationCode.ENDORSEMENT_POLICY_FAILURE
        ch = network["peers"]["org1"].channel(CHANNEL)
        assert ch.ledger.get_state("basic", "mallory") is None

    def test_chaincode_error_refuses_endorsement(self, network):
        gw = network["gateway"]
        with pytest.raises(GatewayError, match="endorsement refused"):
            gw.endorse(CHANNEL, "basic",
                       [b"transfer", b"nobody", b"alice", b"999"],
                       endorsing_peers=_both_peers(network))

    def test_mvcc_conflict_between_racing_txs(self, network):
        """Two txs reading the same key in one block: the second gets
        MVCC_READ_CONFLICT (reference txmgmt/validation semantics)."""
        gw = network["gateway"]
        gw.submit_transaction(CHANNEL, "basic",
                              [b"put", b"race", b"1"],
                              endorsing_peers=_both_peers(network))
        _sync(network)
        env1, tx1 = gw.endorse(CHANNEL, "basic",
                               [b"transfer", b"race", b"alice", b"1"],
                               endorsing_peers=_both_peers(network))
        env2, tx2 = gw.endorse(CHANNEL, "basic",
                               [b"transfer", b"race", b"bob", b"1"],
                               endorsing_peers=_both_peers(network))
        gw.submit(env1)
        gw.submit(env2)
        c1 = gw.commit_status(CHANNEL, tx1, timeout_s=10)
        c2 = gw.commit_status(CHANNEL, tx2, timeout_s=10)
        assert sorted([c1, c2]) == sorted(
            [txpb.TxValidationCode.VALID,
             txpb.TxValidationCode.MVCC_READ_CONFLICT])

    def test_deliver_rejects_unauthorized_seeker(self, network, tmp_path):
        """An identity from outside the channel's MSPs must get
        FORBIDDEN from the deliver service (Readers policy)."""
        from fabric_tpu.peer.deliverclient import seek_envelope
        outsider_dir = cryptogen.generate_org(
            str(tmp_path), "evil.example.com", n_peers=1)
        csp = network["csp"]
        msp = X509MSP(csp)
        msp.setup(msp_config_from_dir(
            os.path.join(outsider_dir, "peers",
                         "peer0.evil.example.com", "msp"),
            "EvilMSP", csp=csp))
        env = seek_envelope(CHANNEL, 0,
                            msp.get_default_signing_identity())
        responses = list(network["deliver"].handle(env))
        assert len(responses) == 1
        from fabric_tpu.protos import common
        assert responses[0].status == common.Status.FORBIDDEN


class TestChaincodeEvents:
    def test_event_stream_replays_and_tails(self, network):
        """Gateway ChaincodeEvents: replay from genesis catches the
        `put` events committed by earlier tests, and a live submit
        shows up in the tail (reference api.go:508)."""
        import threading
        gw = network["gateway"]
        stop = threading.Event()
        seen = []
        stream = gw.chaincode_events(CHANNEL, "basic", start_block=0,
                                     stop=stop)
        # drain history until we see at least one committed put event
        for num, events in stream:
            seen.extend(events)
            if any(e.event_name == "put" for e in seen):
                break
        assert any(e.event_name == "put" and e.chaincode_id == "basic"
                   for e in seen)
        # live tail: submit and expect the new event
        def tail():
            for _num, events in gw.chaincode_events(
                    CHANNEL, "basic", stop=stop):
                seen.extend(events)
                if any(e.payload == b"evtkey" for e in events):
                    stop.set()
                    return
        t = threading.Thread(target=tail, daemon=True)
        t.start()
        gw.submit_transaction(CHANNEL, "basic",
                              [b"put", b"evtkey", b"1"],
                              endorsing_peers=_both_peers(network))
        t.join(timeout=15)
        stop.set()
        assert any(e.payload == b"evtkey" for e in seen)
