"""Multi-chip sharded batch verify (ISSUE 6 tentpole): parity, knob,
faults, observability.

The provider shards the batch axis of its comb/tree pipeline over a
1-D device mesh (`BCCSP.TPU.Devices`, default = all local devices;
1 = the pre-mesh single-device path bit-for-bit). The contract under
test: sharded verdicts are BIT-IDENTICAL to the single-chip path and
the sw oracle — on dividing and non-dividing batch sizes, mixed and
all-invalid accept/reject bitmaps — the round-robin span feeder deals
lanes across the mesh with per-device transfer streams, a faulted
sharded dispatch degrades through the breaker exactly like the
single-chip path, and the per-device `bccsp_shard_*` gauges publish.

Device math uses the recorder-stub idiom (tests/test_pipeline_overlap
.py): real staging, mesh placement, span splitting, premask assembly;
the jitted kernel is replaced by a premask recorder. The real sharded
XLA arithmetic is covered by the multi-process case below (sharded
SHA-256, bit-exact vs hashlib — compiles in under a second) and by
the slow-marked full-kernel parity at the bottom; the multi-minute
comb compiles stay out of tier-1.
"""

import hashlib
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from fabric_tpu.bccsp import ECDSAKeyGenOpts, VerifyItem, factory, utils
from fabric_tpu.bccsp.sw import SWProvider
from fabric_tpu.bccsp.tpu import TPUProvider
from fabric_tpu.common import faults
from fabric_tpu.parallel import batch_mesh

_SW = SWProvider()
_KEYS = [_SW.key_gen(ECDSAKeyGenOpts(ephemeral=True)) for _ in range(2)]

# aligned_span granule for an 8-way mesh (ops/ptree.py LANE_ALIGN=128)
SPAN8 = 1024


def _stubbed_provider(mesh=None, **kw):
    kw.setdefault("min_batch", 1)
    kw.setdefault("use_g16", False)
    tpu = TPUProvider(mesh=mesh, **kw)
    calls = {"premask": [], "key_idx": [], "ladder": 0}

    def fake_qtab_fn(K):
        return lambda qx, qy: np.zeros((K,), dtype=np.int32)

    def fake_pipeline_digest(K, q16=False, donate=False):
        def run(key_idx, q_flat, g16, r8, rpn8, w8, premask, digests):
            calls["premask"].append(np.asarray(premask).copy())
            calls["key_idx"].append(np.asarray(key_idx).copy())
            return np.asarray(premask)
        return run

    def fake_ladder():
        def run(blocks, nblocks, qx, qy, r, rpn, w, premask, digests,
                has_digest):
            calls["ladder"] += 1
            return np.asarray(premask)
        return run

    tpu._qtab_fn = fake_qtab_fn
    tpu._comb_pipeline_digest = fake_pipeline_digest
    tpu._pipeline = fake_ladder
    return tpu, calls


def _corpus(n, all_invalid=False):
    items, expected = [], []
    for i in range(n):
        k = _KEYS[i % 2]
        m = f"shard {i}".encode()
        sig = _SW.sign(k, hashlib.sha256(m).digest())
        if all_invalid or i % 3 == 2:
            r, s = utils.unmarshal_signature(sig)
            sig = (sig[:-2] if i % 2 else
                   utils.marshal_signature(r, utils.P256_N - s))
            expected.append(False)
        else:
            expected.append(True)
        items.append(VerifyItem(key=k.public_key(), signature=sig,
                                message=m))
    return items, expected


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh from conftest")
    return batch_mesh(8)


class TestShardedParity:
    def test_dividing_batch_parity(self, mesh8):
        """2048 lanes over 1024-lane spans: sharded verdicts match the
        mesh-less provider and the sw oracle lane for lane, and the
        per-device shard stats populate."""
        faults.clear()
        sharded, calls = _stubbed_provider(mesh=mesh8,
                                           pipeline_chunk=SPAN8)
        single, _ = _stubbed_provider(pipeline_chunk=SPAN8)
        items, expected = _corpus(2048)
        out8 = sharded.verify_batch(items)
        out1 = single.verify_batch(items)
        assert out8 == out1 == expected == _SW.verify_batch(items)
        assert sharded.stats["pipeline_batches"] == 1
        assert sharded.stats["pipeline_chunks"] == 2
        assert [len(p) for p in calls["premask"]] == [SPAN8, SPAN8]
        assert sharded.stats["shard_devices"] == 8
        assert sharded.stats["shard_dispatches"] == 2
        assert sharded.shard_stats["lanes"] == [SPAN8 // 8] * 8
        assert len(sharded.shard_stats["transfer_s"]) == 8

    def test_nondividing_batch_parity(self, mesh8):
        """2500 lanes -> 3 spans with 572 padded tail lanes: one
        compiled shape per device count, padding never leaks a
        verdict, bitmaps bit-identical to single-chip and oracle."""
        faults.clear()
        sharded, calls = _stubbed_provider(mesh=mesh8,
                                           pipeline_chunk=SPAN8)
        single, _ = _stubbed_provider(pipeline_chunk=SPAN8)
        items, expected = _corpus(2500)
        out8 = sharded.verify_batch(items)
        assert out8 == single.verify_batch(items) == expected
        assert sharded.stats["pipeline_chunks"] == 3
        assert [len(p) for p in calls["premask"]] == [SPAN8] * 3
        # the padded tail is premasked dead
        assert not calls["premask"][-1][2500 - 2048:].any()

    def test_all_invalid_batch_parity(self, mesh8):
        """Every lane failing the host gates leaves key_map empty:
        the batch routes to the generic ladder staging — sharded and
        single-chip alike — and the all-False bitmap matches."""
        faults.clear()
        sharded, calls = _stubbed_provider(mesh=mesh8,
                                           pipeline_chunk=SPAN8)
        items, expected = _corpus(1100, all_invalid=True)
        assert sharded.verify_batch(items) == expected
        assert not any(expected)
        assert sharded.stats["pipeline_batches"] == 0
        assert calls["ladder"] == 1

    def test_whole_batch_digest_path_sharded(self, mesh8):
        """pipeline_chunk=0 (overlap off): the whole-batch digest comb
        staging also rides the sharded feeder, with mesh-aligned
        buckets."""
        faults.clear()
        sharded, calls = _stubbed_provider(mesh=mesh8,
                                           pipeline_chunk=0)
        single, _ = _stubbed_provider(pipeline_chunk=0)
        items, expected = _corpus(300)
        out8 = sharded.verify_batch(items)
        assert out8 == single.verify_batch(items) == expected
        assert sharded.stats["shard_dispatches"] >= 1
        # mesh-aligned bucket: every staged span divides the mesh
        assert all(len(p) % 8 == 0 for p in calls["premask"])

    def test_mixed_digest_and_sw_lanes(self, mesh8):
        """Digest-carrying lanes ride the sharded pipeline; non-P256 /
        bad-digest lanes fall to the per-lane sw path without
        degrading the batch — same contract as single-chip."""
        faults.clear()
        sharded, _ = _stubbed_provider(mesh=mesh8,
                                       pipeline_chunk=SPAN8)
        items, expected = _corpus(1200)
        for i in range(0, 1200, 10):
            it = items[i]
            items[i] = VerifyItem(
                key=it.key, signature=it.signature,
                digest=hashlib.sha256(it.message).digest())
        items[5] = VerifyItem(key=items[5].key,
                              signature=items[5].signature,
                              digest=b"\x00" * 20)
        expected[5] = False
        assert sharded.verify_batch(items) == expected
        assert sharded.stats["nonp256_sw_lanes"] == 1


class TestDevicesKnob:
    def test_default_is_all_local_devices(self):
        prov = factory.new_bccsp(factory.FactoryOpts.from_config(
            {"Default": "TPU"}))
        assert prov._mesh is not None
        assert prov._mesh.size == len(jax.devices())
        assert prov.stats["shard_devices"] == len(jax.devices())

    def test_devices_one_pins_the_single_device_path(self):
        """Devices: 1 must be the pre-mesh path bit for bit: no mesh
        object at all, so every dispatch takes exactly the code the
        single-chip provider always took."""
        prov = factory.new_bccsp(factory.FactoryOpts.from_config(
            {"Default": "TPU", "TPU": {"Devices": 1}}))
        assert prov._mesh is None
        assert prov.stats["shard_devices"] == 1

    def test_devices_n_uses_first_n(self):
        prov = factory.new_bccsp(factory.FactoryOpts.from_config(
            {"Default": "TPU", "TPU": {"Devices": 4}}))
        assert prov._mesh is not None and prov._mesh.size == 4

    def test_devices_over_ask_clamps_to_available(self, caplog):
        """A stale `Devices: N` on a smaller rig serves on every
        device there IS (with a warning) — degrading to ONE device
        would silently cost ~N x the configured throughput."""
        import logging
        with caplog.at_level(logging.WARNING, logger="bccsp.factory"):
            prov = factory.new_bccsp(factory.FactoryOpts.from_config(
                {"Default": "TPU", "TPU": {"Devices": 999}}))
        assert prov._mesh is not None
        assert prov._mesh.size == len(jax.devices())
        assert any("clamping" in r.message for r in caplog.records)

    def test_devices_one_verdicts_match_premesh_provider(self):
        """A factory-built Devices:1 provider takes the identical code
        path (and produces identical bitmaps) as a directly-built
        pre-mesh provider."""
        faults.clear()
        premesh, _ = _stubbed_provider(pipeline_chunk=SPAN8)
        one = factory.new_bccsp(factory.FactoryOpts.from_config(
            {"Default": "TPU",
             "TPU": {"Devices": 1, "MinBatch": 1, "UseG16": False,
                     "PipelineChunk": SPAN8}}))
        assert one._mesh is None
        # same recorder stubs on the factory-built provider
        stub_src, _ = _stubbed_provider(pipeline_chunk=SPAN8)
        one._qtab_fn = stub_src._qtab_fn
        one._comb_pipeline_digest = stub_src._comb_pipeline_digest
        one._pipeline = stub_src._pipeline
        items, expected = _corpus(1500)
        assert premesh.verify_batch(items) == \
            one.verify_batch(items) == expected
        assert one.stats["pipeline_chunks"] == \
            premesh.stats["pipeline_chunks"]


class TestShardedFaults:
    def test_dispatch_fault_falls_back_bit_identical(self, mesh8):
        """tpu.dispatch armed: the sharded dispatch fires the SAME
        per-dispatch fault point, the breaker path serves sw with
        identical verdicts, and the next batch rides the sharded
        pipeline again."""
        faults.clear()
        faults.arm("tpu.dispatch", mode="error", count=1)
        try:
            sharded, _ = _stubbed_provider(mesh=mesh8,
                                           pipeline_chunk=SPAN8)
            items, expected = _corpus(1100)
            assert sharded.verify_batch(items) == expected
            assert sharded.stats["sw_fallbacks"] == 1
            assert sharded.stats["pipeline_batches"] == 0
            assert sharded.verify_batch(items) == expected
            assert sharded.stats["pipeline_batches"] == 1
        finally:
            faults.clear()


class TestShardObservability:
    def test_shard_gauges_published(self, mesh8):
        """bccsp_shard_devices/skew and the per-device
        transfer_s/lanes series render on /metrics with their
        canonical help text and a device label."""
        from fabric_tpu.common import metrics as m
        from fabric_tpu.common import profiling

        faults.clear()
        sharded, _ = _stubbed_provider(mesh=mesh8,
                                       pipeline_chunk=SPAN8)
        items, _ = _corpus(2048)
        sharded.verify_batch(items)
        provider = m.PrometheusProvider()
        t = profiling.publish_provider_stats(provider, sharded,
                                             poll_s=0.01)
        assert t is not None
        import time
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            text = provider.render()
            if 'bccsp_shard_lanes{device="7"}' in text:
                break
            time.sleep(0.02)
        text = provider.render()
        assert "bccsp_shard_devices 8" in text
        assert 'bccsp_shard_transfer_s{device="0"}' in text
        assert 'bccsp_shard_lanes{device="7"} 128' in text
        assert "bccsp_shard_skew_s" in text
        assert "round-robin span feeder" in text


class TestMultiProcessCPUMesh:
    def test_sharded_provider_in_fresh_forced_mesh_process(self,
                                                           tmp_path):
        """The satellite's multi-process case: a CHILD process forces
        its own 8-device CPU platform (XLA_FLAGS, not the conftest
        in-process mesh), builds factory providers at Devices=all and
        Devices=1, and reports (a) provider-seam verdict parity on a
        mixed corpus through the sharded staging (recorder-stub
        kernels — the real comb compile is minutes on CPU) and (b) a
        REAL sharded XLA computation: the device SHA-256 stage under
        batch sharding, bit-exact vs hashlib."""
        child = tmp_path / "shard_child.py"
        child.write_text(_CHILD_SRC)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "host_platform_device_count" not in f]
        flags.append("--xla_force_host_platform_device_count=8")
        env["XLA_FLAGS"] = " ".join(flags)
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH",
                                                        "")
        p = subprocess.run([sys.executable, str(child)], env=env,
                           cwd=repo, capture_output=True, text=True,
                           timeout=420)
        assert p.returncode == 0, (p.stdout[-2000:], p.stderr[-2000:])
        res = json.loads(p.stdout.strip().splitlines()[-1])
        assert res["devices"] == 8
        assert res["mesh_all"] == 8
        assert res["mesh_one"] is None
        assert res["parity"] is True
        assert res["expected_mixed"] is True
        assert res["sha_ok"] is True
        if not os.environ.get("FTPU_FAULTS"):
            # chaos runs arm tpu.dispatch in the child's env too: the
            # faulted dispatch serves sw (parity above still binds),
            # so only fault-free runs can pin the dispatch count
            assert res["shard_dispatches"] >= 1


_CHILD_SRC = '''
import json
import hashlib
import os

import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

from fabric_tpu.bccsp import ECDSAKeyGenOpts, VerifyItem, factory, utils
from fabric_tpu.bccsp.sw import SWProvider
from fabric_tpu.ops import sha256
from fabric_tpu.parallel import BATCH_AXIS, batch_mesh

res = {"devices": len(jax.devices())}

def stub(tpu):
    def fake_qtab_fn(K):
        return lambda qx, qy: np.zeros((K,), dtype=np.int32)
    def fake_pipeline_digest(K, q16=False, donate=False):
        def run(key_idx, q_flat, g16, r8, rpn8, w8, premask, digests):
            return np.asarray(premask)
        return run
    def fake_ladder():
        def run(blocks, nblocks, qx, qy, r, rpn, w, premask, digests,
                has_digest):
            return np.asarray(premask)
        return run
    tpu._qtab_fn = fake_qtab_fn
    tpu._comb_pipeline_digest = fake_pipeline_digest
    tpu._pipeline = fake_ladder
    return tpu

sw = SWProvider()
keys = [sw.key_gen(ECDSAKeyGenOpts(ephemeral=True)) for _ in range(2)]
items, expected = [], []
for i in range(96):
    k = keys[i % 2]
    m = f"mp shard {i}".encode()
    sig = sw.sign(k, hashlib.sha256(m).digest())
    if i % 3 == 2:
        r, s = utils.unmarshal_signature(sig)
        sig = utils.marshal_signature(r, utils.P256_N - s)
        expected.append(False)
    else:
        expected.append(True)
    items.append(VerifyItem(key=k.public_key(), signature=sig,
                            message=m))

alldev = stub(factory.new_bccsp(factory.FactoryOpts.from_config(
    {"Default": "TPU",
     "TPU": {"MinBatch": 1, "UseG16": False, "PipelineChunk": 0}})))
onedev = stub(factory.new_bccsp(factory.FactoryOpts.from_config(
    {"Default": "TPU",
     "TPU": {"Devices": 1, "MinBatch": 1, "UseG16": False,
             "PipelineChunk": 0}})))
res["mesh_all"] = alldev.stats["shard_devices"]
res["mesh_one"] = (onedev._mesh.size if onedev._mesh is not None
                   else None)
out_all = alldev.verify_batch(items)
out_one = onedev.verify_batch(items)
res["parity"] = out_all == out_one == expected
res["expected_mixed"] = (any(expected) and not all(expected))
res["shard_dispatches"] = alldev.stats["shard_dispatches"]

# real sharded XLA compute: device SHA-256 under batch sharding
from jax.sharding import NamedSharding, PartitionSpec as P
mesh = batch_mesh(8)
msgs = [f"mp sha {i}".encode() * (1 + i % 3) for i in range(16)]
blocks, nblocks = sha256.pack_messages(msgs, 2)
s = NamedSharding(mesh, P(BATCH_AXIS))
fn = jax.jit(sha256.sha256_blocks, in_shardings=(s, s),
             out_shardings=s)
words = np.asarray(fn(jax.device_put(blocks, s),
                      jax.device_put(nblocks, s)))
res["sha_ok"] = bool(all(
    (np.frombuffer(hashlib.sha256(m).digest(),
                   dtype=">u4") == words[i]).all()
    for i, m in enumerate(msgs)))
print(json.dumps(res))
'''


class TestCompatDerivePrivateKey:
    """The multichip dry run (`__graft_entry__._dryrun_in_process`)
    signs its q16 oracle lanes with `ec.derive_private_key` through
    the compat seam — on wheel-free images the pure-python fallback
    must provide it (MULTICHIP regression: a direct `cryptography`
    import made the dry run rc=1 on this container)."""

    def test_scalar_one_is_generator_and_signs(self):
        from fabric_tpu.bccsp._crypto_compat import ec, hashes
        from fabric_tpu.ops import p256
        priv = ec.derive_private_key(1, ec.SECP256R1())
        nums = priv.public_key().public_numbers()
        assert (nums.x, nums.y) == (p256.GX, p256.GY)
        msg = b"compat derive"
        der = priv.sign(msg, ec.ECDSA(hashes.SHA256()))
        priv.public_key().verify(der, msg, ec.ECDSA(hashes.SHA256()))

    def test_out_of_range_scalar_rejected(self):
        from fabric_tpu.bccsp._crypto_compat import ec
        from fabric_tpu.bccsp import utils
        with pytest.raises(ValueError):
            ec.derive_private_key(0, ec.SECP256R1())
        with pytest.raises(ValueError):
            ec.derive_private_key(utils.P256_N, ec.SECP256R1())


@pytest.mark.slow
class TestShardedRealKernel:
    def test_real_comb_parity_sharded_vs_oracle(self, mesh8):
        """Full provider, REAL q8 comb kernel under shard_map on the
        8-device CPU mesh: verdicts bit-identical to the sw oracle on
        a mixed 64-lane batch. Minutes of XLA compile — slow suite
        only; tier-1 covers the same plumbing with recorder stubs."""
        faults.clear()
        prov = TPUProvider(min_batch=16, use_g16=False, mesh=mesh8,
                           pipeline_chunk=0, hash_on_host=True)
        items, expected = _corpus(64)
        assert prov.verify_batch(items) == expected == \
            _SW.verify_batch(items)
        assert prov.stats["comb_batches"] == 1
        assert prov.stats["shard_dispatches"] >= 1
