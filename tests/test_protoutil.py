"""Wire-format + protoutil tests (layer 0).

Mirrors the reference's protoutil tests (`protoutil/*_test.go`):
roundtrips, hash chaining, signed-data extraction, tx assembly."""

import hashlib

import pytest

from fabric_tpu.protos import common, proposal as pb, transaction as txpb
from fabric_tpu import protoutil as pu


class FakeSigner:
    """Deterministic test signer: 'signature' = sha256(identity || msg)."""

    def __init__(self, identity=b"org1-admin"):
        self._id = identity

    def serialize(self):
        return self._id

    def sign(self, msg):
        return hashlib.sha256(self._id + msg).digest()


def test_envelope_roundtrip():
    ch = pu.make_channel_header(common.HeaderType.MESSAGE, "mychannel")
    sh = pu.create_signature_header(b"creator")
    payload = pu.make_payload(ch, sh, b"hello")
    env = pu.sign_or_panic(FakeSigner(), payload)

    env2 = pu.unmarshal_envelope(env.SerializeToString())
    p2 = pu.get_payload(env2)
    assert pu.get_channel_header(p2).channel_id == "mychannel"
    assert p2.data == b"hello"


def test_compute_tx_id_unique_per_nonce():
    a = pu.compute_tx_id(b"n1", b"creator")
    b = pu.compute_tx_id(b"n2", b"creator")
    assert a != b
    assert a == hashlib.sha256(b"n1creator").hexdigest()


def test_block_hash_chain():
    b0 = pu.new_block(0, b"")
    b0.data.data.append(b"genesis-tx")
    b0.header.data_hash = pu.block_data_hash(b0.data)

    b1 = pu.new_block(1, pu.block_header_hash(b0.header))
    assert b1.header.previous_hash == pu.block_header_hash(b0.header)
    # header hash is sensitive to every field
    mutated = common.BlockHeader()
    mutated.CopyFrom(b0.header)
    mutated.number = 7
    assert pu.block_header_hash(mutated) != pu.block_header_hash(b0.header)


def test_block_data_hash_is_concat_sha256():
    bd = common.BlockData()
    bd.data.append(b"aa")
    bd.data.append(b"bb")
    assert pu.block_data_hash(bd) == hashlib.sha256(b"aabb").digest()


def test_new_block_has_all_metadata_slots():
    b = pu.new_block(3, b"prev")
    assert len(b.metadata.metadata) == 5


def test_envelope_as_signed_data():
    ch = pu.make_channel_header(common.HeaderType.MESSAGE, "ch")
    sh = pu.create_signature_header(b"creator-bytes")
    env = pu.sign_or_panic(FakeSigner(b"creator-bytes"),
                           pu.make_payload(ch, sh, b"data"))
    sds = pu.envelope_as_signed_data(env)
    assert len(sds) == 1
    assert sds[0].identity == b"creator-bytes"
    assert sds[0].data == env.payload
    assert sds[0].signature == env.signature


def test_block_signature_set():
    block = pu.new_block(5, b"prev")
    md = common.Metadata()
    md.value = b"md-value"
    sig = md.signatures.add()
    sh = pu.create_signature_header(b"orderer-id")
    sig.signature_header = pu.marshal(sh)
    sig.signature = b"sig-bytes"
    block.metadata.metadata[common.BlockMetadataIndex.SIGNATURES] = \
        pu.marshal(md)

    sds = pu.block_signature_set(block)
    assert len(sds) == 1
    assert sds[0].identity == b"orderer-id"
    assert sds[0].data == (md.value + sig.signature_header +
                           pu.block_header_bytes(block.header))


def test_proposal_and_signed_tx_assembly():
    signer = FakeSigner(b"endorser-1")
    prop, tx_id = pu.create_proposal("ch1", "mycc", [b"invoke", b"a", b"b"],
                                     creator=b"client-1")
    assert len(tx_id) == 64

    resp = pb.Response(status=200, message="OK", payload=b"result")
    ccid = pb.ChaincodeID(name="mycc", version="1.0")
    prop_bytes = pu.marshal(prop)
    presp = pu.create_proposal_response(prop_bytes, b"rwset-bytes",
                                        b"", resp, ccid, signer)
    assert presp.endorsement.endorser == b"endorser-1"

    env = pu.create_signed_tx(prop, [presp], FakeSigner(b"client-1"))
    action = pu.get_action_from_envelope(env.SerializeToString())
    assert action.results == b"rwset-bytes"
    assert action.response.status == 200

    # mismatched responses must be rejected
    presp2 = pu.create_proposal_response(prop_bytes, b"DIFFERENT", b"",
                                         resp, ccid, signer)
    with pytest.raises(ValueError, match="do not match"):
        pu.create_signed_tx(prop, [presp, presp2], FakeSigner(b"client-1"))


def test_signed_tx_strips_transient_map():
    prop, _ = pu.create_proposal("ch1", "mycc", [b"put"], creator=b"c",
                                 transient_map={"secret": b"s3cret"})
    resp = pb.Response(status=200)
    presp = pu.create_proposal_response(pu.marshal(prop), b"rw", b"", resp,
                                        pb.ChaincodeID(name="mycc"),
                                        FakeSigner())
    env = pu.create_signed_tx(prop, [presp], FakeSigner(b"c"))

    payload = pu.get_payload(env)
    tx = txpb.Transaction()
    tx.ParseFromString(payload.data)
    cap = txpb.ChaincodeActionPayload()
    cap.ParseFromString(tx.actions[0].payload)
    ccpp = pb.ChaincodeProposalPayload()
    ccpp.ParseFromString(cap.chaincode_proposal_payload)
    assert not ccpp.transient_map


def test_rejected_proposal_cannot_become_tx():
    prop, _ = pu.create_proposal("ch1", "mycc", [b"x"], creator=b"c")
    resp = pb.Response(status=500, message="simulation failed")
    presp = pu.create_proposal_response(pu.marshal(prop), b"", b"", resp,
                                        pb.ChaincodeID(name="mycc"),
                                        FakeSigner())
    with pytest.raises(ValueError, match="not successful"):
        pu.create_signed_tx(prop, [presp], FakeSigner(b"c"))


def test_extract_envelope_bounds():
    b = pu.new_block(0, b"")
    with pytest.raises(IndexError):
        pu.extract_envelope(b, 0)
