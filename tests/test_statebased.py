"""Key-level (state-based) endorsement policy tests.

Reference semantics being pinned:
`core/common/validation/statebased/validator_keylevel.go` (key-level
policies override the chaincode policy per key; the chaincode policy is
required iff some written key has no key-level policy) and
`vpmanagerimpl.go` (same-block ordering: a VALID tx's parameter updates
govern later txs in the same block; an invalid tx's do not).
"""

import os

import pytest

from fabric_tpu.bccsp.sw import SWProvider
from fabric_tpu.common.deliver import DeliverHandler
from fabric_tpu.core.chaincode import Chaincode, ChaincodeDefinition, shim
from fabric_tpu.core.policycheck import org_member_policy_bytes
from fabric_tpu.core.txvalidator import TxValidator
from fabric_tpu.internal import cryptogen
from fabric_tpu.internal.configtxgen import genesis_block, new_channel_group
from fabric_tpu.ledger.kvdb import DBHandle, KVStore
from fabric_tpu.ledger.statedb import Height, StateDB, UpdateBatch
from fabric_tpu.ledger.txmgr import (
    TxMgr, TxSimulator, deserialize_metadata, serialize_metadata,
)
from fabric_tpu.msp import msp_config_from_dir
from fabric_tpu.msp.mspimpl import X509MSP
from fabric_tpu.orderer import solo
from fabric_tpu.orderer.broadcast import BroadcastHandler
from fabric_tpu.orderer.multichannel import Registrar
from fabric_tpu.peer import Peer
from fabric_tpu.peer.deliverclient import Deliverer
from fabric_tpu.peer.gateway import Gateway
from fabric_tpu.protos import common, transaction as txpb
from fabric_tpu.protoutil import protoutil as pu

CHANNEL = "sbechannel"
TVC = txpb.TxValidationCode


# ---------------------------------------------------------------------------
# Ledger-level: metadata write semantics through TxMgr
# ---------------------------------------------------------------------------

class TestMetadataCommit:
    @pytest.fixture()
    def db(self, tmp_path):
        kv = KVStore(str(tmp_path / "s.db"))
        return StateDB(DBHandle(kv, "s"))

    def _commit(self, db, block, sims):
        mgr = TxMgr(db)
        codes, batch = mgr.validate_and_prepare(
            block, [s.get_tx_simulation_results() for s in sims])
        db.apply_updates(batch, Height(block, 0))
        return codes

    def test_metadata_roundtrip_and_preservation(self, db):
        sim = TxSimulator(db)
        sim.put_state("cc", "k", b"v1")
        sim.set_state_metadata("cc", "k", {"VALIDATION_PARAMETER": b"P1"})
        self._commit(db, 1, [sim])
        assert deserialize_metadata(db.get_state_metadata("cc", "k")) == \
            {"VALIDATION_PARAMETER": b"P1"}

        # a value-only write preserves existing metadata
        sim = TxSimulator(db)
        sim.put_state("cc", "k", b"v2")
        self._commit(db, 2, [sim])
        assert db.get_state("cc", "k").value == b"v2"
        assert deserialize_metadata(db.get_state_metadata("cc", "k")) == \
            {"VALIDATION_PARAMETER": b"P1"}

        # a metadata-only write replaces the map and bumps the version
        sim = TxSimulator(db)
        sim.set_state_metadata("cc", "k", {"OTHER": b"x"})
        self._commit(db, 3, [sim])
        assert db.get_state("cc", "k").value == b"v2"
        assert deserialize_metadata(db.get_state_metadata("cc", "k")) == \
            {"OTHER": b"x"}
        assert db.get_version("cc", "k") == Height(3, 0)

        # delete clears value and metadata
        sim = TxSimulator(db)
        sim.del_state("cc", "k")
        self._commit(db, 4, [sim])
        assert db.get_state("cc", "k") is None

    def test_metadata_write_to_absent_key_is_noop(self, db):
        sim = TxSimulator(db)
        sim.set_state_metadata("cc", "ghost", {"m": b"1"})
        self._commit(db, 1, [sim])
        assert db.get_state("cc", "ghost") is None

    def test_metadata_read_your_writes_and_rwset(self, db):
        sim = TxSimulator(db)
        sim.put_state("cc", "k", b"v")
        sim.set_state_metadata("cc", "k", {"VP": b"pol"})
        assert sim.get_state_metadata("cc", "k") == {"VP": b"pol"}
        txrw = sim.get_tx_simulation_results()
        from fabric_tpu.protos import rwset as rwpb
        kv = rwpb.KVRWSet()
        kv.ParseFromString(txrw.ns_rwset[0].rwset)
        assert [mw.key for mw in kv.metadata_writes] == ["k"]
        assert kv.metadata_writes[0].entries[0].name == "VP"

    def test_private_metadata_hashed_rwset_and_commit(self, db):
        from fabric_tpu.ledger import pvtdata as pvt
        from fabric_tpu.protos import rwset as rwpb
        sim = TxSimulator(db)
        sim.put_private_data("cc", "col", "pk", b"secret")
        sim.set_private_data_metadata("cc", "col", "pk", {"VP": b"q"})
        txrw = sim.get_tx_simulation_results()
        hset = rwpb.HashedRWSet()
        hset.ParseFromString(
            txrw.ns_rwset[0].collection_hashed_rwset[0].rwset)
        assert len(hset.metadata_writes) == 1
        assert hset.metadata_writes[0].key_hash == pvt.key_hash("pk")
        self._commit(db, 1, [sim])
        hns = pvt.hash_ns("cc", "col")
        hkey = pvt.hashed_key_str(pvt.key_hash("pk"))
        assert deserialize_metadata(
            db.get_state_metadata(hns, hkey)) == {"VP": b"q"}


class TestBlockOverlayNamespacing:
    def test_vp_updates_do_not_bleed_across_chaincodes(self):
        """Two chaincodes writing the same key name in one block must
        not see each other's validation parameters."""
        from fabric_tpu.core.statebased import BlockOverlay, WriteSetInfo
        ov = BlockOverlay()
        info = WriteSetInfo(namespace="ccA",
                            vp_updates={(None, "k"): b"POLICY-A"})
        ov.apply(info)
        assert ov.get("ccA", None, "k") == b"POLICY-A"
        assert ov.get("ccB", None, "k") is None


# ---------------------------------------------------------------------------
# Validator-level: a 2-org network enforcing key-level policies
# ---------------------------------------------------------------------------

class SBEChaincode(Chaincode):
    def init(self, stub):
        return shim.success()

    def invoke(self, stub):
        fn, params = stub.get_function_and_parameters()
        if fn == "put":
            stub.put_state(params[0], params[1].encode())
            return shim.success()
        if fn == "lock":        # key now requires an org-member sig
            stub.set_state_validation_parameter(
                params[0], org_member_policy_bytes(params[1]))
            return shim.success()
        if fn == "unlock":
            stub.set_state_validation_parameter(params[0], b"")
            return shim.success()
        if fn == "getvp":
            return shim.success(
                stub.get_state_validation_parameter(params[0]) or b"")
        return shim.error(f"unknown {fn}")


@pytest.fixture(scope="module")
def net(tmp_path_factory):
    root = tmp_path_factory.mktemp("sbe")
    cdir = str(root / "crypto")
    org1 = cryptogen.generate_org(cdir, "org1.example.com", n_peers=1,
                                  n_users=1)
    org2 = cryptogen.generate_org(cdir, "org2.example.com", n_peers=1,
                                  n_users=1)
    ordo = cryptogen.generate_org(cdir, "example.com", orderer_org=True)
    profile = {
        "Consortium": "SampleConsortium",
        "Capabilities": {"V2_0": True},
        "Application": {
            "Organizations": [
                {"Name": "Org1", "ID": "Org1MSP",
                 "MSPDir": os.path.join(org1, "msp")},
                {"Name": "Org2", "ID": "Org2MSP",
                 "MSPDir": os.path.join(org2, "msp")},
            ],
            "Capabilities": {"V2_0": True},
        },
        "Orderer": {
            "OrdererType": "solo",
            "Addresses": ["orderer0.example.com:7050"],
            "BatchTimeout": "100ms",
            "BatchSize": {"MaxMessageCount": 10},
            "Organizations": [
                {"Name": "OrdererOrg", "ID": "OrdererMSP",
                 "MSPDir": os.path.join(ordo, "msp"),
                 "OrdererEndpoints": ["orderer0.example.com:7050"]}],
            "Capabilities": {"V2_0": True},
        },
    }
    genesis = genesis_block(CHANNEL, new_channel_group(profile))
    csp = SWProvider()

    def local_msp(d, mspid):
        m = X509MSP(csp)
        m.setup(msp_config_from_dir(d, mspid, csp=csp))
        return m

    omsp = local_msp(os.path.join(ordo, "orderers",
                                  "orderer0.example.com", "msp"),
                     "OrdererMSP")
    reg = Registrar(str(root / "ord"),
                    omsp.get_default_signing_identity(), csp,
                    {"solo": solo.consenter})
    reg.join(genesis)
    broadcast = BroadcastHandler(reg)
    deliver = DeliverHandler(reg.get_chain)

    # the chaincode-level policy: ONE Org1 member — so org1-only
    # endorsements pass unless a key-level parameter tightens the key
    definition = ChaincodeDefinition(
        name="sbe", endorsement_policy=org_member_policy_bytes("Org1MSP"))

    peers = {}
    deliverers = []
    for org_name, org_dir, mspid in (("org1", org1, "Org1MSP"),
                                     ("org2", org2, "Org2MSP")):
        msp = local_msp(
            os.path.join(org_dir, "peers",
                         f"peer0.{org_name}.example.com", "msp"), mspid)
        p = Peer(str(root / f"peer_{org_name}"), msp, csp)
        ch = p.join_channel(genesis)
        p.chaincode_support.register("sbe", SBEChaincode())
        ch.define_chaincode(definition)
        d = Deliverer(ch, p.signer, lambda: deliver, p.mcs)
        d.start()
        peers[org_name] = p
        deliverers.append(d)

    user = local_msp(os.path.join(org1, "users",
                                  "User1@org1.example.com", "msp"),
                     "Org1MSP")
    gw = Gateway(peers["org1"], broadcast,
                 user.get_default_signing_identity())
    yield {"peers": peers, "gw": gw, "reg": reg, "deliver": deliver,
           "csp": csp}
    for d in deliverers:
        d.stop()
    reg.halt()
    for p in peers.values():
        p.close()


def _sync(net, timeout_s=10.0):
    chans = [p.channel(CHANNEL) for p in net["peers"].values()]
    target = max(ch.ledger.height for ch in chans)
    for ch in chans:
        assert ch.wait_for_height(target, timeout_s)


class TestKeyLevelPolicies:
    def test_grant_enforce_and_revoke_across_blocks(self, net):
        gw = net["gw"]
        org1 = [net["peers"]["org1"]]
        both = list(net["peers"].values())

        # baseline: cc policy (Org1) lets an org1-only endorsement in
        r = gw.submit_transaction(CHANNEL, "sbe", [b"put", b"a", b"1"],
                                  endorsing_peers=org1)
        assert r.status == TVC.VALID

        # lock: attach VP = Org2 member (key has no VP yet, so the cc
        # policy gates this metadata write — org1 suffices)
        r = gw.submit_transaction(CHANNEL, "sbe",
                                  [b"lock", b"a", b"Org2MSP"],
                                  endorsing_peers=org1)
        assert r.status == TVC.VALID
        _sync(net)

        # now an org1-only write to `a` must FAIL the key-level policy
        r = gw.submit_transaction(CHANNEL, "sbe", [b"put", b"a", b"2"],
                                  endorsing_peers=org1)
        assert r.status == TVC.ENDORSEMENT_POLICY_FAILURE

        # an uncovered key still validates under the cc policy alone
        r = gw.submit_transaction(CHANNEL, "sbe", [b"put", b"b", b"9"],
                                  endorsing_peers=org1)
        assert r.status == TVC.VALID

        # writing `a` WITH org2's endorsement passes (VP satisfied; cc
        # policy not required — every written key is covered)
        r = gw.submit_transaction(CHANNEL, "sbe", [b"put", b"a", b"3"],
                                  endorsing_peers=both)
        assert r.status == TVC.VALID

        # removing the VP is itself gated by the current VP
        r = gw.submit_transaction(CHANNEL, "sbe", [b"unlock", b"a"],
                                  endorsing_peers=org1)
        assert r.status == TVC.ENDORSEMENT_POLICY_FAILURE
        r = gw.submit_transaction(CHANNEL, "sbe", [b"unlock", b"a"],
                                  endorsing_peers=both)
        assert r.status == TVC.VALID
        _sync(net)

        # revoked: org1-only writes work again
        r = gw.submit_transaction(CHANNEL, "sbe", [b"put", b"a", b"4"],
                                  endorsing_peers=org1)
        assert r.status == TVC.VALID

    def _manual_block(self, net, envelopes):
        blk = common.Block()
        blk.header.number = 99
        for env in envelopes:
            blk.data.data.append(pu.marshal(env))
        return blk

    def test_same_block_parameter_ordering(self, net):
        """tx1 locks key `c` to Org2; tx2 (later in the SAME block)
        writes `c` with org1-only endorsement → tx2 must fail. If tx1
        is invalid, tx2 must pass (committed state has no VP)."""
        gw = net["gw"]
        org1 = [net["peers"]["org1"]]
        p1 = net["peers"]["org1"]
        ch = p1.channel(CHANNEL)
        _sync(net)

        env_lock, _ = gw.endorse(
            CHANNEL, "sbe", [b"lock", b"c", b"Org2MSP"],
            endorsing_peers=org1)
        env_put, _ = gw.endorse(
            CHANNEL, "sbe", [b"put", b"c", b"7"], endorsing_peers=org1)

        validator = TxValidator(
            CHANNEL, ch.ledger, ch.bundle, net["csp"],
            ch.chaincode_definition,
            configtx_validator_source=ch.configtx_validator)

        codes = validator.validate(
            self._manual_block(net, [env_lock, env_put]))
        assert codes == [TVC.VALID, TVC.ENDORSEMENT_POLICY_FAILURE]

        # tamper tx1's endorsement: it goes invalid, so its parameter
        # update must NOT govern tx2
        tampered = common.Envelope()
        tampered.CopyFrom(env_lock)
        payload = pu.get_payload(tampered)
        tx = txpb.Transaction()
        tx.ParseFromString(payload.data)
        cap = txpb.ChaincodeActionPayload()
        cap.ParseFromString(tx.actions[0].payload)
        sig = bytearray(cap.action.endorsements[0].signature)
        sig[-1] ^= 1
        cap.action.endorsements[0].signature = bytes(sig)
        tx.actions[0].payload = cap.SerializeToString()
        payload.data = tx.SerializeToString()
        tampered.payload = pu.marshal(payload)
        # re-sign the envelope so only the endorsement is broken
        env2 = pu.sign_or_panic(gw._signer, payload)

        env_put2, _ = gw.endorse(
            CHANNEL, "sbe", [b"put", b"c", b"8"], endorsing_peers=org1)
        codes = validator.validate(
            self._manual_block(net, [env2, env_put2]))
        assert codes[0] != TVC.VALID
        assert codes[1] == TVC.VALID
