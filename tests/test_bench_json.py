"""Bench output contract (ISSUE 2 satellite): ONE compact final line.

Rounds 3-5 lost their numbers to an oversized JSON tail, a crash, and
an rc=124 — the driver parses the LAST stdout line as JSON. The
contract pinned here: `final_line` emits exactly one parseable line
with no nested per-chunk arrays (full detail goes to the sidecar
file), the pipeline overlap ratio is surfaced on both, and
`emit_final` is idempotent (the self-deadline watchdog and the normal
exit path race through it).
"""

import json

import pytest

import bench


def _sample():
    result = {
        "metric": "block-validation sig-verify throughput",
        "value": 50613.2,
        "unit": "sigs/s",
        "vs_baseline": 5.28,
        "deadline_hit": False,
    }
    detail = {
        "provider_stats": {
            "pipeline_overlap_ratio": 0.42,
            "pipeline_batches": 4,
            "pipeline_host_s": 0.8,
            "pipeline_device_s": 1.9,
            "comb_batches": 5,
        },
        "per_chunk": [[i, i * 2] for i in range(200)],  # sidecar-only
        "restart": {"ok": True},
    }
    return result, detail


def test_final_line_is_one_compact_parseable_line(monkeypatch,
                                                  tmp_path):
    side = str(tmp_path / "detail.json")
    monkeypatch.setattr(bench, "SIDECAR", side)
    result, detail = _sample()
    line = bench.final_line(result, detail)
    assert "\n" not in line
    assert len(line) < 2000          # compact: no embedded arrays
    parsed = json.loads(line)
    assert parsed["value"] == 50613.2
    assert parsed["unit"] == "sigs/s"
    assert parsed["pipeline_overlap_ratio"] == 0.42
    assert "detail" not in parsed
    assert "per_chunk" not in parsed
    # flat: no nested containers on the driver-parsed line
    for v in parsed.values():
        assert not isinstance(v, (list, dict))
    # the full detail landed in the sidecar
    assert parsed["sidecar"] == side
    with open(side) as f:
        dumped = json.load(f)
    assert dumped["provider_stats"]["pipeline_overlap_ratio"] == 0.42
    assert len(dumped["per_chunk"]) == 200


def test_final_line_without_detail(monkeypatch, tmp_path):
    monkeypatch.setattr(bench, "SIDECAR", str(tmp_path / "d.json"))
    result, _ = _sample()
    parsed = json.loads(bench.final_line(result))
    assert parsed["value"] == result["value"]
    assert "sidecar" not in parsed


def test_unwritable_sidecar_does_not_break_the_line(monkeypatch):
    monkeypatch.setattr(bench, "SIDECAR",
                        "/nonexistent-dir/nope/detail.json")
    result, detail = _sample()
    parsed = json.loads(bench.final_line(result, detail))
    assert parsed["value"] == result["value"]
    assert "sidecar" not in parsed


def test_emit_final_is_idempotent(monkeypatch, tmp_path, capsys):
    monkeypatch.setattr(bench, "SIDECAR", str(tmp_path / "d.json"))
    monkeypatch.setattr(bench, "_FINAL_EMITTED",
                        type(bench._FINAL_EMITTED)())
    result, detail = _sample()
    bench.emit_final(result, detail)
    bench.emit_final({"value": -1}, None)     # watchdog double-fire
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln]
    assert len(lines) == 1
    assert json.loads(lines[0])["value"] == 50613.2


def test_watchdog_shape_parses(monkeypatch, tmp_path):
    """The deadline-hit salvage line must satisfy the same parse
    contract (lists of section names are the one allowed nesting)."""
    monkeypatch.setattr(bench, "SIDECAR", str(tmp_path / "d.json"))
    parsed = json.loads(bench.final_line({
        "metric": "smoke, self-deadline hit",
        "value": None,
        "unit": "sigs/s",
        "deadline_s": 540.0,
        "deadline_hit": True,
        "completed_sections": ["prewarm_s", "sign_s"],
    }))
    assert parsed["deadline_hit"] is True
    for v in parsed.values():
        if isinstance(v, list):
            assert all(isinstance(x, str) for x in v)
        assert not isinstance(v, dict)
