"""Round-16 device-cost observability tests.

Compile-seam counting (cold vs persistent-cache-hit, wheel-free via
recorder stubs with injected clocks/cache dirs), the provider `_jit`
seam + armed `tpu.compile` faults (error-status compile spans,
compile_failures), busy-ratio math, memory-gauge rendering, the
/healthz HBM-headroom sub-state, the perf ledger's parse/compare over
checked-in copies of the real r01–r05 driver captures (including the
crashed r04 and rc=124 r05 shapes) with a seeded regression that must
be flagged, and the /debug/jax/trace busy/bounded hardening.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from fabric_tpu.common import devicecost, faults, profiling, tracing
from fabric_tpu.common import metrics as metrics_mod
from fabric_tpu.common.devicecost import (
    CompileRecorder, DeviceBusy,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(ROOT, "tests", "fixtures", "perf_rounds")

pytestmark = pytest.mark.chaos


@pytest.fixture()
def trace_env(tmp_path):
    """Isolated flight recorder with instant dumps (the test_tracing
    fixture shape)."""
    tracing.configure(enabled=True, ring_size=256, sample_every=1,
                      dump_dir=str(tmp_path),
                      dump_min_interval_s=0.0, shed_burst=32)
    tracing.reset()
    yield tmp_path
    tracing.wait_dumps()
    tracing.configure(enabled=True, ring_size=4096, sample_every=1,
                      dump_dir="", dump_min_interval_s=10.0,
                      shed_burst=32)
    tracing.reset()


class _Clock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class _FakeLowered:
    """Quacks like jax.stages.Lowered for the AOT seam."""

    def __init__(self, jit, cost=None):
        self._jit = jit
        self._cost = cost

    def cost_analysis(self):
        return self._cost

    def compile(self):
        self._jit._run_once()
        return "compiled"


class _FakeJit:
    """Recorder stub for the wheel-free compile-seam tests: each
    'compile' advances the injected clock by the next scripted
    duration and optionally writes a persistent-cache entry."""

    def __init__(self, clock, durations, cache_dir=None,
                 writes=None, cost=None, raises=None):
        self.clock = clock
        self.durations = list(durations)
        self.cache_dir = cache_dir
        self.writes = list(writes or [])
        self.cost = cost
        self.raises = raises
        self.calls = 0

    def _run_once(self):
        if self.raises is not None:
            raise self.raises
        self.calls += 1
        self.clock.advance(self.durations.pop(0)
                           if self.durations else 0.0)
        if self.writes and self.writes.pop(0) and self.cache_dir:
            with open(os.path.join(
                    self.cache_dir,
                    f"entry_{self.calls}.bin"), "wb") as f:
                f.write(b"x")

    def __call__(self, *args):
        self._run_once()
        return "out"

    def lower(self, *args):
        return _FakeLowered(self, cost=self.cost)


# ---------------------------------------------------------------------------
# the compile seam (CompileRecorder + InstrumentedJit)
# ---------------------------------------------------------------------------

class TestCompileSeam:
    def _recorder(self, tmp_path, **kw):
        kw.setdefault("analysis", False)
        clock = kw.pop("clock", _Clock())
        return CompileRecorder(clock=clock, cache_dir=str(tmp_path),
                               **kw), clock

    def test_cold_then_seen_shape_records_once(self, tmp_path):
        rec, clock = self._recorder(tmp_path)
        fake = _FakeJit(clock, durations=[10.0, 0.0])
        fn = rec.wrap("comb", fake)
        a = np.zeros((8,), np.int32)
        assert fn(a) == "out"
        assert rec.stats["compile_total"] == 1
        assert rec.stats["compile_cold_total"] == 1
        assert rec.stats["compile_cache_hits"] == 0
        assert rec.stats["compile_seconds"] == pytest.approx(10.0)
        # seen shape: steady dispatch, no second event
        fn(a)
        assert rec.stats["compile_total"] == 1
        assert fake.calls == 2
        (ev,) = rec.events
        assert ev["kind"] == "comb" and ev["cold"] \
            and not ev["cache_hit"]

    def test_fast_load_without_cache_write_is_a_hit(self, tmp_path):
        rec, clock = self._recorder(tmp_path)
        fn = rec.wrap("comb", _FakeJit(clock, durations=[0.05]))
        fn(np.zeros((8,), np.int32))
        assert rec.stats["compile_cache_hits"] == 1
        assert rec.stats["compile_cold_total"] == 0

    def test_cache_dir_delta_beats_the_threshold(self, tmp_path):
        # a FAST compile that still wrote a cache entry is a MISS —
        # the delta rule catches what the wall-time threshold cannot
        rec, clock = self._recorder(tmp_path)
        fake = _FakeJit(clock, durations=[0.05],
                        cache_dir=str(tmp_path), writes=[True])
        fn = rec.wrap("comb", fake)
        fn(np.zeros((8,), np.int32))
        assert rec.stats["compile_cold_total"] == 1
        assert rec.stats["compile_cache_hits"] == 0

    def test_new_shape_records_its_own_compile(self, tmp_path):
        rec, clock = self._recorder(tmp_path)
        fn = rec.wrap("comb", _FakeJit(clock, durations=[10.0, 0.01]))
        fn(np.zeros((8,), np.int32))
        fn(np.zeros((16,), np.int32))
        assert rec.stats["compile_total"] == 2
        assert rec.stats["compile_cold_total"] == 1
        assert rec.stats["compile_cache_hits"] == 1

    def test_aot_lower_compile_records(self, tmp_path):
        rec, clock = self._recorder(tmp_path)
        fn = rec.wrap("comb_digest",
                      _FakeJit(clock, durations=[10.0, 0.01]))
        fn.lower(np.zeros((8,), np.int32)).compile()
        assert rec.stats["compile_total"] == 1
        assert rec.events[0]["aot"] is True
        # the jit's own dispatch cache still pays (and records) the
        # first real call — a persistent-cache hit
        fn(np.zeros((8,), np.int32))
        assert rec.stats["compile_total"] == 2
        assert rec.stats["compile_cache_hits"] == 1

    def test_failure_counts_and_propagates(self, tmp_path):
        rec, clock = self._recorder(tmp_path)
        boom = RuntimeError("XLA died")
        fn = rec.wrap("comb", _FakeJit(clock, durations=[],
                                       raises=boom))
        with pytest.raises(RuntimeError):
            fn(np.zeros((8,), np.int32))
        assert rec.stats["compile_failures"] == 1
        assert rec.stats["compile_total"] == 0
        assert rec.events[0]["error"] is not None

    def test_cost_analysis_captured_when_available(self, tmp_path):
        rec, clock = self._recorder(tmp_path, analysis=True)
        fake = _FakeJit(clock, durations=[10.0],
                        cost={"flops": 128.0, "bytes accessed": 64.0})
        fn = rec.wrap("comb", fake)
        fn(np.zeros((8,), np.int32))
        assert rec.events[0]["cost"] == {"flops": 128.0,
                                         "bytes_accessed": 64.0}

    def test_cold_instant_and_steady_auto_dump(self, tmp_path,
                                               trace_env):
        rec, clock = self._recorder(tmp_path)
        fn = rec.wrap("comb", _FakeJit(clock, durations=[10.0, 20.0]))
        fn(np.zeros((8,), np.int32))     # cold, but NOT steady yet
        evs = [e for e in tracing.snapshot()
               if e[1] == "compile.cold"]
        assert len(evs) == 1 and evs[0][8]["steady"] is False
        assert not list(trace_env.iterdir())     # no dump pre-steady
        rec.mark_steady()
        fn(np.zeros((32,), np.int32))    # the steady-state cliff
        tracing.wait_dumps()
        dumps = [p for p in trace_env.iterdir()
                 if "cold_compile" in p.name]
        assert dumps, list(trace_env.iterdir())
        doc = json.loads(dumps[0].read_text())
        assert doc["ftpu"]["reason"] == "cold_compile"

    def test_event_history_bounded(self, tmp_path):
        rec, clock = self._recorder(tmp_path)
        for i in range(devicecost._EVENT_CAP + 40):
            fn = rec.wrap("comb", _FakeJit(clock, durations=[0.01]))
            fn(np.zeros((8,), np.int32))
        assert len(rec.events) == devicecost._EVENT_CAP


class TestProviderJitSeam:
    """The provider-side integration: TPUProvider._jit is the one
    build seam — real jax.jit on a trivial fn (milliseconds on CPU),
    counters land in provider stats, armed tpu.compile faults become
    compile_failures + error-status tpu.compile spans."""

    def _prov(self):
        from fabric_tpu.bccsp.tpu import TPUProvider
        return TPUProvider(min_batch=4, use_g16=False)

    def test_jit_seam_counts_into_provider_stats(self):
        faults.clear()
        prov = self._prov()
        fn = prov._jit("probe", lambda x: x + 1)
        out = fn(np.arange(4, dtype=np.int32))
        assert np.asarray(out).tolist() == [1, 2, 3, 4]
        assert prov.stats["compile_total"] == 1
        fn(np.arange(4, dtype=np.int32))
        assert prov.stats["compile_total"] == 1     # seen shape
        assert prov.device_cost.events[0]["kind"] == "probe"

    def test_armed_compile_fault_books_failure_and_error_span(
            self, trace_env):
        faults.clear()
        prov = self._prov()
        faults.arm("tpu.compile", mode="error", count=1)
        try:
            with pytest.raises(faults.FaultInjected):
                prov._jit("probe", lambda x: x)
            assert prov.stats["compile_failures"] == 1
            assert prov.stats["compile_total"] == 0
            spans = [e for e in tracing.snapshot()
                     if e[1] == "tpu.compile" and e[9] is not None]
            assert spans, "no error-status tpu.compile span recorded"
            # the breaker interplay: a later build (fault consumed)
            # serves normally — degrade, don't wedge
            fn = prov._jit("probe", lambda x: x * 3)
            assert np.asarray(
                fn(np.arange(3, dtype=np.int32))).tolist() == \
                [0, 3, 6]
            assert prov.stats["compile_total"] == 1
        finally:
            faults.reset()

    def test_dispatch_marks_steady(self):
        faults.clear()
        prov = self._prov()
        assert prov.device_cost.steady is False
        with prov._dispatch_span():
            pass
        assert prov.device_cost.steady is True


# ---------------------------------------------------------------------------
# busy-ratio math
# ---------------------------------------------------------------------------

class TestBusyRatio:
    def test_windowed_ratio_and_reset(self):
        clock = _Clock()
        busy = DeviceBusy(clock=clock)
        busy.note(0, 0.5)
        busy.note(1, 0.25)
        clock.advance(1.0)
        assert busy.ratios() == {0: 0.5, 1: 0.25}
        # window reset: no new busy time, a later poll reads idle
        clock.advance(2.0)
        assert busy.ratios() == {0: 0.0, 1: 0.0}

    def test_ratio_clamped_to_one(self):
        clock = _Clock()
        busy = DeviceBusy(clock=clock)
        busy.note(3, 5.0)               # overlapping dispatches
        clock.advance(1.0)
        assert busy.ratios()[3] == 1.0

    def test_nonpositive_and_totals(self):
        busy = DeviceBusy(clock=_Clock())
        busy.note(0, 0.0)
        busy.note(0, -1.0)
        busy.note(2, 0.125)
        assert busy.totals() == {2: 0.125}

    def test_shard_ready_feeds_busy(self):
        """The provider's per-chip ready readings land in the busy
        accumulator keyed by FULL-mesh index."""
        from fabric_tpu.bccsp.tpu import TPUProvider
        prov = TPUProvider(min_batch=4, use_g16=False)
        prov.device_cost.busy.note(2, 0.25)
        assert prov.device_cost.busy.totals() == {2: 0.25}


# ---------------------------------------------------------------------------
# memory gauges + healthz headroom
# ---------------------------------------------------------------------------

def _fake_rows(used=900, limit=1000, peak=950, device=0):
    return [{"device": device, "kind": "fake-hbm",
             "bytes_in_use": used, "peak_bytes_in_use": peak,
             "bytes_limit": limit}]


class TestMemoryGauges:
    def test_devicecost_gauges_render(self, monkeypatch):
        rows = _fake_rows(used=100, peak=200, limit=1000) + \
            _fake_rows(used=50, peak=60, limit=1000, device=1)
        monkeypatch.setattr(devicecost, "device_memory",
                            lambda: rows)

        class _Rec:
            class busy:                  # noqa: N801 - stub namespace
                @staticmethod
                def ratios():
                    return {0: 0.5, 1: 0.0}

        class _Csp:
            device_cost = _Rec()

        provider = metrics_mod.PrometheusProvider()
        # one deterministic tick (the poller wraps this exact
        # callable — no leaked fast thread crossing into jax for the
        # rest of the session)
        tick = profiling.devicecost_tick(provider, _Csp())
        assert tick is not None
        tick()
        text = provider.render()
        assert 'bccsp_device_mem_used_bytes{device="0"} 100' in text
        assert 'bccsp_device_mem_peak_bytes{device="0"} 200' in text
        assert 'bccsp_device_mem_limit_bytes{device="1"} 1000' in text
        assert 'bccsp_device_busy_ratio{device="0"} 0.5' in text
        assert 'bccsp_device_busy_ratio{device="1"} 0' in text

    def test_compile_gauges_render_canonically(self):
        """The compile counters ride publish_provider_stats under
        their canonical fqnames (the both-node-assemblies wiring)."""
        from fabric_tpu.bccsp.tpu import TPUProvider
        faults.clear()
        prov = TPUProvider(min_batch=4, use_g16=False)
        fn = prov._jit("probe", lambda x: x + 1)
        fn(np.arange(4, dtype=np.int32))
        provider = metrics_mod.PrometheusProvider()
        t = profiling.publish_provider_stats(provider, prov,
                                             poll_s=0.01)
        assert t is not None
        deadline = time.monotonic() + 5.0
        text = ""
        while time.monotonic() < deadline:
            text = provider.render()
            if "bccsp_compile_total 1" in text:
                break
            time.sleep(0.02)
        assert "bccsp_compile_total 1" in text
        assert "bccsp_compile_cache_hits 1" in text
        assert "bccsp_compile_seconds" in text
        assert "bccsp_compile_cold_total 0" in text

    def test_device_memory_empty_without_stats_api(self):
        # CPU devices answer memory_stats() with None — no rows, no
        # gauges, no headroom sub-state
        devicecost._mem_capable.clear()
        assert devicecost.device_memory() == []
        assert devicecost.peak_memory_bytes([]) == 0
        # the capability is learned ONCE: a stats-less fleet stops
        # crossing into the runtime on later polls
        assert devicecost._mem_capable and \
            not any(devicecost._mem_capable.values())
        import jax
        assert len(devicecost._mem_capable) == len(jax.local_devices())

    def test_poller_spawns_and_returns_thread(self, monkeypatch):
        monkeypatch.setattr(devicecost, "device_memory", lambda: [])
        provider = metrics_mod.PrometheusProvider()

        class _Csp:
            device_cost = None

        t = profiling.publish_devicecost_stats(provider, _Csp(),
                                               poll_s=60.0)
        assert t is not None and t.daemon and t.is_alive()


class TestHbmHealth:
    def test_substate_names_tightest_device(self):
        rows = _fake_rows(used=950, limit=1000) + \
            _fake_rows(used=100, limit=1000, device=1)
        sub = devicecost.hbm_substate(rows, headroom_frac=0.10)
        assert sub == "hbm_low:d0:5%free"
        assert devicecost.hbm_substate(
            rows, headroom_frac=0.01) is None
        assert devicecost.hbm_substate([], 0.5) is None

    def test_zero_limit_rows_ignored(self):
        assert devicecost.hbm_substate(
            _fake_rows(used=5, limit=0), 0.5) is None

    def test_provider_health_grows_hbm_substate(self, monkeypatch):
        from fabric_tpu.bccsp.tpu import TPUProvider
        prov = TPUProvider(min_batch=4, use_g16=False)
        assert prov.health() == "device"
        monkeypatch.setattr(devicecost, "device_memory",
                            lambda: _fake_rows(used=990, limit=1000))
        assert prov.health() == "device;hbm_low:d0:1%free"


# ---------------------------------------------------------------------------
# the perf ledger over the real round history (fixture copies)
# ---------------------------------------------------------------------------

def _ledger():
    spec = importlib.util.spec_from_file_location(
        "perf_ledger_under_test",
        os.path.join(ROOT, "tools", "perf_ledger.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestPerfLedger:
    def test_trajectory_over_real_rounds_nonempty(self):
        pl = _ledger()
        traj = pl.trajectory(FIXTURES)
        statuses = {r["round"]: r["status"] for r in traj["rounds"]}
        assert statuses == {1: "ok", 2: "ok", 3: "salvaged",
                            4: "crashed", 5: "timeout"}
        assert {b["round"] for b in traj["broken_rounds"]} == {4, 5}
        # the truncated r03 tail still yields its numbers
        r3 = next(r for r in traj["rounds"] if r["round"] == 3)
        assert r3["metrics"]["tpu_steady_s"] == 0.2206
        assert r3["metrics"]["order_raft_s"] == 87.68
        # pre-staged-bench alias lands on the canonical series
        assert r3["metrics"]["provider_sigs_per_s"] == 29309.2
        assert traj["metrics"]["value"]["best"] == 50605.0
        assert traj["metrics"]["tpu_steady_s"]["best"] == 0.2206
        # the crashed round carries its error, not silence
        r4 = next(r for r in traj["rounds"] if r["round"] == 4)
        assert "KeyError" in (r4.get("error") or "")

    def test_multichip_rounds_attached(self):
        pl = _ledger()
        traj = pl.trajectory(FIXTURES)
        mc = {r["round"]: r.get("multichip") for r in traj["rounds"]}
        assert mc[1]["ok"] is False and mc[1]["rc"] == 1
        assert mc[2]["ok"] is True
        assert mc[5]["rc"] == 124

    def test_check_passes_at_history_best(self):
        pl = _ledger()
        traj = pl.trajectory(FIXTURES)
        cand = {"on_tpu": True,
                "value": traj["metrics"]["value"]["best"],
                "tpu_steady_s":
                    traj["metrics"]["tpu_steady_s"]["best"]}
        res = pl.compare(cand, traj)
        assert res["ok"] is True
        assert set(res["checked"]) == {"value", "tpu_steady_s"}

    def test_seeded_regression_flagged(self):
        pl = _ledger()
        traj = pl.trajectory(FIXTURES)
        cand = {"on_tpu": True,
                "value": traj["metrics"]["value"]["best"] * 0.5,
                "tpu_steady_s": 9.9}
        res = pl.compare(cand, traj)
        assert res["ok"] is False
        names = {r["metric"] for r in res["regressions"]}
        assert names == {"value", "tpu_steady_s"}

    def test_verdict_strings(self, tmp_path):
        pl = _ledger()
        assert pl.verdict({"on_tpu": True, "value": 1.0},
                          str(tmp_path)) == "no_history"
        assert pl.verdict({"on_tpu": False, "value": 1.0},
                          FIXTURES) == "skipped:cpu-rig"
        good = pl.verdict({"on_tpu": True, "value": 60000.0},
                          FIXTURES)
        assert good.startswith("ok(")
        bad = pl.verdict({"on_tpu": True, "value": 10.0}, FIXTURES)
        assert bad == "regressed:value"

    def test_crashed_round_salvage_never_gates(self, tmp_path):
        """A crashed round's tail can carry MID-RUN stage-line
        numbers (half the final aggregate); they must appear on the
        round row but never become the series' best/last gating
        reference."""
        pl = _ledger()
        (tmp_path / "BENCH_r01.json").write_text(json.dumps({
            "n": 1, "rc": 0, "tail": "",
            "parsed": {"value": 50000.0, "unit": "sigs/s"}}))
        (tmp_path / "BENCH_r02.json").write_text(json.dumps({
            "n": 2, "rc": 1, "parsed": None,
            "tail": '{"stage": "kernel_steady", "value": 12000.0}\n'
                    "Traceback (most recent call last):\n  boom\n"}))
        traj = pl.trajectory(str(tmp_path))
        r2 = next(r for r in traj["rounds"] if r["round"] == 2)
        assert r2["status"] == "crashed"
        assert r2["metrics"]["value"] == 12000.0   # represented...
        s = traj["metrics"]["value"]
        assert s["last"] == 50000.0                # ...never gating
        assert s["best"] == 50000.0

    def test_candidate_from_raw_stdout(self, tmp_path):
        pl = _ledger()
        f = tmp_path / "bench.out"
        f.write_text(
            "WARNING: some log line\n"
            '{"stage": "core", "value": 1.0}\n'
            '{"value": 42.0, "unit": "sigs/s", "on_tpu": true}\n')
        cand = pl.load_candidate(str(f))
        assert cand["value"] == 42.0 and "stage" not in cand

    def test_cli_exit_codes(self, tmp_path):
        env = dict(os.environ)
        tool = os.path.join(ROOT, "tools", "perf_ledger.py")
        out = subprocess.run(
            [sys.executable, tool, "--dir", FIXTURES],
            capture_output=True, text=True, env=env, timeout=60)
        assert out.returncode == 0, out.stderr
        traj = json.loads(out.stdout)
        assert len(traj["rounds"]) == 5 and traj["metrics"]
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"on_tpu": True, "value": 10.0}))
        out = subprocess.run(
            [sys.executable, tool, "check", "--candidate", str(bad),
             "--dir", FIXTURES],
            capture_output=True, text=True, env=env, timeout=60)
        assert out.returncode == 1, (out.stdout, out.stderr)
        assert "REGRESSION value" in out.stderr
        out = subprocess.run(
            [sys.executable, tool, "check", "--candidate",
             str(tmp_path / "missing.json"), "--dir", FIXTURES],
            capture_output=True, text=True, env=env, timeout=60)
        assert out.returncode == 2

    def test_empty_history_dir_is_usage_error(self, tmp_path):
        out = subprocess.run(
            [sys.executable,
             os.path.join(ROOT, "tools", "perf_ledger.py"),
             "--dir", str(tmp_path)],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 2


# ---------------------------------------------------------------------------
# /debug/jax/trace hardening (busy refusal + bounded output dirs)
# ---------------------------------------------------------------------------

class TestJaxTraceHardening:
    def test_concurrent_capture_refused_immediately(self):
        assert profiling._trace_lock.acquire(blocking=False)
        try:
            t0 = time.monotonic()
            with pytest.raises(profiling.ProfilerBusyError):
                profiling.capture_jax_trace("/tmp/unused", 5.0)
            assert time.monotonic() - t0 < 1.0, \
                "busy refusal must not wait out the capture window"
        finally:
            profiling._trace_lock.release()

    def test_bounded_keeps_last_n_dirs(self, tmp_path, monkeypatch):
        import jax
        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda d: None)
        monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
        made = [profiling.capture_jax_trace_bounded(
            seconds=0.0, parent_dir=str(tmp_path), keep=2)
            for _ in range(4)]
        left = sorted(p.name for p in tmp_path.iterdir())
        assert len(left) == 2, left
        assert os.path.basename(made[-1]) in left

    def test_busy_bounded_does_not_leak_a_dir(self, tmp_path):
        assert profiling._trace_lock.acquire(blocking=False)
        try:
            with pytest.raises(profiling.ProfilerBusyError):
                profiling.capture_jax_trace_bounded(
                    seconds=0.0, parent_dir=str(tmp_path))
        finally:
            profiling._trace_lock.release()
        assert list(tmp_path.iterdir()) == []

    def test_ops_endpoint_replies_409_when_busy(self):
        from urllib.request import urlopen
        from urllib.error import HTTPError

        from fabric_tpu.node.operations import OperationsServer
        srv = OperationsServer(address="127.0.0.1:0",
                               profile_enabled=True)
        srv.start()
        try:
            assert profiling._trace_lock.acquire(blocking=False)
            try:
                with pytest.raises(HTTPError) as exc:
                    urlopen("http://%s/debug/jax/trace?seconds=0.1"
                            % srv.address, timeout=10)
                assert exc.value.code == 409
                body = json.loads(exc.value.read())
                assert "already running" in body["Error"]
            finally:
                profiling._trace_lock.release()
        finally:
            srv.stop()
