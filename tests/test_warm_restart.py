"""Warm-restart wiring: Q-table key-set persistence → prewarm rebuild.

Round-3 verdict: the warm-keys machinery existed but was unreachable
(no `WarmKeysDir` in the factory, `prewarm()` never called
`_prewarm_tables()`). These tests pin the WIRING end to end — config →
factory → provider, build → persist, fresh provider → prewarm →
cache hit — with the table builders stubbed (the real 16-bit comb
build is a multi-minute device job measured by bench.py, not a unit
concern).
"""

import json
import os

import numpy as np

from fabric_tpu.bccsp import factory
from fabric_tpu.bccsp.tpu import TPUProvider
from fabric_tpu.ops import limb


def _limbs(kb: bytes):
    qk = np.frombuffer(kb, dtype=np.uint8).reshape(1, 64).copy()
    return (limb.be_bytes_to_limbs(qk[:, :32]),
            limb.be_bytes_to_limbs(qk[:, 32:]))


def _stub_builders(monkeypatch, builds):
    import jax.numpy as jnp

    def fake_qtab_fn(self, K):
        return lambda qx, qy: jnp.zeros((2, 3, 20), jnp.int32)

    def fake_q16_fn(self, K):
        def build(q8, k):
            builds.append(k)
            return jnp.zeros((4, 3, 20), jnp.int32)
        return build

    monkeypatch.setattr(TPUProvider, "_qtab_fn", fake_qtab_fn)
    monkeypatch.setattr(TPUProvider, "_q16_fn", fake_q16_fn)


def test_factory_passes_warm_keys_dir(tmp_path):
    warm = str(tmp_path / "warm")
    opts = factory.FactoryOpts.from_config(
        {"Default": "TPU", "TPU": {"WarmKeysDir": warm}})
    assert opts.tpu.warm_keys_dir == warm
    prov = factory.new_bccsp(opts)
    assert prov._warm_keys_dir == warm
    # unset stays disabled
    assert factory.FactoryOpts.from_config(
        {"Default": "TPU"}).tpu.warm_keys_dir is None


def test_build_persists_and_fresh_provider_prewarms(tmp_path,
                                                    monkeypatch):
    builds: list = []
    _stub_builders(monkeypatch, builds)
    warm = str(tmp_path / "warm")
    kb = bytes(range(64))

    prov = TPUProvider(warm_keys_dir=warm, use_g16=True)
    qx, qy = _limbs(kb)
    assert prov._q16_cached((kb,), 1, qx, qy) is not None
    assert prov.stats["q16_builds"] == 1
    # table bytes land asynchronously; prewarm only restores sets
    # whose bytes exist on disk (stub bytes fail the size check, so
    # the fresh provider below exercises the REBUILD fallback)
    prov.flush_warm_tables()

    # the key set was persisted (MRU first, hex encoded)
    sets = json.load(open(os.path.join(warm, "warm_keysets.json")))
    assert sets == [[kb.hex()]]

    # "restarted peer": a fresh provider over the same dir rebuilds the
    # persisted set during prewarm, so the first block's table lookup
    # is a cache HIT — zero builds on the serving path
    prov2 = TPUProvider(warm_keys_dir=warm, use_g16=True)
    assert prov2._prewarm_tables() == 1
    assert prov2.stats["q16_builds"] == 1
    before = prov2.stats["q16_builds"]
    assert prov2._q16_cached((kb,), 1, qx, qy) is not None
    assert prov2.stats["q16_builds"] == before  # served from cache


def test_prewarm_invokes_table_rebuild(monkeypatch):
    """prewarm() (the node-assembly entry point) must reach
    _prewarm_tables when the 16-bit path is enabled."""
    from fabric_tpu.ops import comb
    called = []
    monkeypatch.setattr(TPUProvider, "_prewarm_tables",
                        lambda self: called.append(True) or 0)
    monkeypatch.setattr(comb, "g16_tables", lambda: None)
    prov = TPUProvider(use_g16=True)
    prov.prewarm(buckets=(), key_counts=(), wait_restore=True)
    assert called


def test_corrupt_warm_file_ignored(tmp_path):
    warm = str(tmp_path / "warm")
    os.makedirs(warm)
    with open(os.path.join(warm, "warm_keysets.json"), "w") as f:
        f.write("{not json")
    prov = TPUProvider(warm_keys_dir=warm, use_g16=True)
    assert prov._load_warm_keys() == []
    assert prov._prewarm_tables() == 0
