"""Multi-process proof of the pluggable state-database seam: a real
peer PROCESS runs its world state against an external state-server
process (fabric_tpu/ledger/stateserver.py — statecouchdb's deployment
shape) while the other org stays on the embedded engine, and both
commit identical state through endorse→order→validate→commit.
Round-4 verdict #7 done-criterion: "nwo test runs a peer on the
alternate backend".
"""

import json
import os
import time

import pytest

from tests.nwo import Network


def _wait(cond, timeout=60.0, step=0.25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if cond():
                return True
        except Exception:
            pass
        time.sleep(step)
    return False


@pytest.fixture(scope="module")
def network(tmp_path_factory):
    net = Network(str(tmp_path_factory.mktemp("nwo_http")),
                  n_orderers=1,
                  state_backend={"org2": "http"})
    try:
        net.start_all()
        net.join_all()
        yield net
    finally:
        net.teardown()
        for name, node in net.nodes.items():
            print(f"--- {name} log tail ---")
            try:
                with open(node.log_path, "rb") as f:
                    print(f.read()[-2000:].decode(errors="replace"))
            except OSError:
                pass


@pytest.mark.integration
class TestPeerOnHTTPStateBackend:
    def test_commit_visible_on_both_backends(self, network):
        assert _wait(lambda: json.loads(network.invoke(
            "org1", 0, "put", "ext1", "42"))["status"] == "VALID",
            timeout=60)
        # org1 (embedded) and org2 (external http engine) agree
        assert _wait(lambda: network.query(
            "org1", 0, "get", "ext1").strip() == "42")
        assert _wait(lambda: network.query(
            "org2", 0, "get", "ext1").strip() == "42")
        # the state actually lives in the server process's data dir
        sdir = os.path.join(network.root, "stateserver")
        assert any(n.endswith(".state.db") for n in os.listdir(sdir)), \
            os.listdir(sdir)

    def test_endorse_on_http_backend_peer(self, network):
        """The http-backed peer can ENDORSE (simulate against the
        external engine), not just commit."""
        assert _wait(lambda: json.loads(network.invoke(
            "org2", 0, "put", "ext2", "7"))["status"] == "VALID",
            timeout=60)
        assert _wait(lambda: network.query(
            "org1", 0, "get", "ext2").strip() == "7")
