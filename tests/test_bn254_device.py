"""Differential tests: batched TPU BN254 kernels vs the int reference.

The device Miller loop's line values are scaled by Fp2 subfield factors
(projective denominators) that the final exponentiation kills, so
Miller outputs are compared up to an Fp2 factor; one lane is also taken
through the full final exponentiation for exact GT equality.

Loop counts are truncated (the bit-scan body is identical for any
count) so the suite compiles/runs on the CPU mesh; full-length runs
ride the TPU bench path.
"""

import os
import random

import numpy as np

import jax
import jax.numpy as jnp

import pytest

from fabric_tpu.ops import bn254 as dev
from fabric_tpu.ops import bn254_ref as ref

rng = random.Random(5151)

SMALL_LOOP = 0b1011010          # 6 scan steps, mixed bits


def _g1_points(ks):
    out = []
    for k in ks:
        p = ref.ec_mul(k, ref.g1_embed(ref.G1))
        out.append((p[0][0][0][0], p[1][0][0][0]))
    return out


def _g2_points(ks):
    return [ref.g2_mul(k, (ref.G2_X, ref.G2_Y)) for k in ks]


def _is_fp2(el) -> bool:
    """True when an int-reference Fp12 element lies in the Fp2
    subfield (c0 coefficient of the first Fp6 component only)."""
    d0, d1 = el
    return (d0[1] == ref.F2_ZERO and d0[2] == ref.F2_ZERO
            and d1 == ref.F6_ZERO)


class TestTowerOps:
    def test_f2_f6_f12_mul_match_reference(self):
        B = 3
        F = dev.F

        def rnd2():
            return [(rng.randrange(ref.P), rng.randrange(ref.P))
                    for _ in range(B)]

        a2, b2 = rnd2(), rnd2()

        def stage2(vals):
            return (jnp.asarray(np.stack([F.to_mont(v[0]) for v in vals])),
                    jnp.asarray(np.stack([F.to_mont(v[1]) for v in vals])))

        got = jax.jit(dev.f2_mul)(stage2(a2), stage2(b2))
        for i in range(B):
            want = ref.f2_mul(a2[i], b2[i])
            assert (F.from_limbs(np.asarray(got[0][i])),
                    F.from_limbs(np.asarray(got[1][i]))) == want

        a6 = [tuple((rng.randrange(ref.P), rng.randrange(ref.P))
                    for _ in range(3)) for _ in range(B)]
        b6 = [tuple((rng.randrange(ref.P), rng.randrange(ref.P))
                    for _ in range(3)) for _ in range(B)]

        def stage6(vals):
            return tuple(stage2([v[c] for v in vals]) for c in range(3))

        got6 = jax.jit(dev.f6_mul)(stage6(a6), stage6(b6))
        for i in range(B):
            want = ref.f6_mul(a6[i], b6[i])
            got_i = tuple(
                (F.from_limbs(np.asarray(got6[c][0][i])),
                 F.from_limbs(np.asarray(got6[c][1][i])))
                for c in range(3))
            assert got_i == want, f"f6 lane {i}"

        a12 = [(a6[i], b6[i]) for i in range(B)]
        b12 = [(b6[i], a6[i]) for i in range(B)]

        def stage12(vals):
            return (stage6([v[0] for v in vals]),
                    stage6([v[1] for v in vals]))

        got12 = jax.jit(dev.f12_mul)(stage12(a12), stage12(b12))
        back = dev.f12_from_device(got12)
        for i in range(B):
            assert back[i] == ref.f12_mul(a12[i], b12[i]), f"f12 lane {i}"


@pytest.mark.slow
@pytest.mark.skipif(
    os.environ.get("FTPU_SLOW") != "1",
    reason="heavy differential; set FTPU_SLOW=1 (~50 min compile)")
class TestMillerLoop:
    @pytest.fixture(scope="class")
    def batch(self):
        B = 4
        g1k = [rng.randrange(2, ref.R) for _ in range(B)]
        g2k = [rng.randrange(2, ref.R) for _ in range(B)]
        ps = _g1_points(g1k)
        qs = _g2_points(g2k)
        xP, yP = dev.stage_g1(ps)
        Q, Q1, nQ2 = dev.stage_g2(qs)

        def to_dev(t):
            return jax.tree_util.tree_map(jnp.asarray, t)

        fn = jax.jit(lambda x, y, q, q1, nq2: dev.miller_loop_batch(
            x, y, q, q1, nq2, loop=SMALL_LOOP))
        f_dev = fn(jnp.asarray(xP), jnp.asarray(yP), to_dev(Q),
                   to_dev(Q1), to_dev(nQ2))
        return ps, qs, dev.f12_from_device(f_dev)

    def test_matches_reference_up_to_fp2_scaling(self, batch):
        ps, qs, f_dev = batch
        for i, (p, q) in enumerate(zip(ps, qs)):
            want = ref.miller_loop(q, p, loop=SMALL_LOOP)
            ratio = ref.f12_mul(f_dev[i], ref.f12_inv(want))
            assert _is_fp2(ratio), (
                f"lane {i}: device/ref Miller ratio escapes Fp2 — "
                f"the kernels disagree beyond line scaling")
            assert ratio != ref.F12_ZERO

    def test_final_exponentiation_exact_equality(self, batch):
        ps, qs, f_dev = batch
        want = ref.final_exponentiation(
            ref.miller_loop(qs[0], ps[0], loop=SMALL_LOOP))
        got = ref.final_exponentiation(f_dev[0])
        assert got == want


@pytest.mark.slow
@pytest.mark.skipif(
    os.environ.get("FTPU_SLOW") != "1",
    reason="heavy differential; set FTPU_SLOW=1 (30+ min compile)")
class TestDeviceFinalExp:
    def test_final_exp_batch_matches_reference(self):
        B = 2
        F = dev.F

        def stage2(vals):
            return (jnp.asarray(np.stack([F.to_mont(v[0]) for v in vals])),
                    jnp.asarray(np.stack([F.to_mont(v[1]) for v in vals])))

        def stage12(vals):
            return tuple(
                tuple(stage2([v[h][c] for v in vals]) for c in range(3))
                for h in range(2))

        fs = [tuple(tuple((rng.randrange(ref.P), rng.randrange(ref.P))
                          for _ in range(3)) for _ in range(2))
              for _ in range(B)]
        got = jax.jit(dev.final_exp_batch)(stage12(fs))
        back = dev.f12_from_device(got)
        for i in range(B):
            assert back[i] == ref.final_exponentiation(fs[i]), f"lane {i}"

    def test_pairing_product_check_bilinearity(self):
        """e(aP, Q) * e(P, -aQ) == 1 on device, truncated Miller loop
        on BOTH sides is not possible for products (the check needs
        the true pairing) — so this uses the full ATE loop; it also
        covers gt_is_one and the staging helpers."""
        a = 7
        P1 = ref.g1_mul(1, ref.G1)
        aP = ref.g1_mul(a, ref.G1)
        Q = (ref.G2_X, ref.G2_Y)
        naQ = ref.g2_neg_tw(ref.g2_mul(a, Q))
        products = [
            [(aP, Q), (P1, naQ)],            # == 1
            [(aP, Q), (P1, ref.g2_neg_tw(Q))],   # != 1
        ]
        staged = dev.stage_pairing_products(products)
        out = np.asarray(jax.jit(
            lambda *s: dev.pairing_product_is_one(*s))(*staged))
        assert out.tolist() == [True, False]


class TestBLSProviderSeam:
    def test_sw_and_tpu_bls_verify_batch_agree_host_path(self):
        """The provider surface (pairing_check_batch/bls_verify_batch)
        with the HOST fallback path: small batches route to the exact
        reference pairing on both providers."""
        from fabric_tpu.bccsp.sw import SWProvider
        from fabric_tpu.bccsp.tpu import TPUProvider
        sk, pk = ref.bls_keygen(b"seam")
        msgs = [b"m1", b"m2", b"m3"]
        sigs = [ref.bls_sign(sk, msgs[0]),
                ref.bls_sign(sk, b"WRONG"), None]
        want = [True, False, False]
        assert SWProvider().bls_verify_batch(pk, msgs, sigs) == want
        tpu = TPUProvider(min_batch=64)   # below cutoff -> host path
        assert tpu.bls_verify_batch(pk, msgs, sigs) == want


@pytest.mark.slow
class TestG2MSMBatch:
    """Device G2 multi-scalar multiplication (idemix PS Schnorr
    recombination + batched subgroup test) vs the host Strauss MSM.
    Scalar widths truncated (the scan body is identical per bit) so
    the suite compiles on CPU; full-width runs ride the TPU bench."""

    def test_matches_host_msm(self):
        G2 = (ref.G2_X, ref.G2_Y)
        lanes = []
        for i in range(6):
            T = ref.g2_mul_fast(rng.randrange(1, 1 << 30), G2)
            lanes.append([
                (rng.randrange(1 << 10), G2),
                (rng.randrange(1 << 10), T),
                (0 if i == 2 else rng.randrange(1 << 10),
                 None if i == 4 else T),
            ])
        # lane where everything is zero -> infinity
        lanes.append([(0, G2), (0, None), (0, G2)])
        # lane that lands exactly ON infinity mid-way: k*Q + (r-k)*Q
        k = rng.randrange(1, 1 << 9)
        lanes.append([(k, G2), (0, None), ((1 << 10) - k,
                                           ref.g2_neg_tw(G2))])
        bits, qf = dev.stage_g2_msm(lanes, nbits=10)
        out = jax.jit(dev.g2_msm_scan)(
            jnp.asarray(bits), *[jnp.asarray(a) for a in qf])
        got = dev.read_g2_msm(out)
        for lane, g in zip(lanes, got):
            want = None
            for kk, q in lane:
                want = ref.g2_add_fast(want, ref.g2_msm([(kk, q)])
                                       if kk and q else None)
            assert g == want, lane
