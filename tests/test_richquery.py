"""Rich (JSON selector) state queries — the statecouchdb analog.

Reference semantics: statecouchdb rich queries (selector subset,
pagination, committed-state-only visibility, per-key read recording,
no phantom re-check).
"""

import json

import pytest

from fabric_tpu.ledger.kvdb import DBHandle, KVStore
from fabric_tpu.ledger.richquery import (
    IndexRegistry, QueryError, matches,
)
from fabric_tpu.ledger.statedb import Height, StateDB, UpdateBatch
from fabric_tpu.ledger.txmgr import TxSimulator


class TestSelector:
    def test_equality_and_nested(self):
        doc = {"color": "red", "owner": {"name": "alice"}, "size": 5}
        assert matches(doc, {"color": "red"})
        assert matches(doc, {"owner.name": "alice"})
        assert not matches(doc, {"color": "blue"})
        assert not matches(doc, {"missing": 1})

    def test_comparison_ops(self):
        doc = {"size": 5, "name": "m"}
        assert matches(doc, {"size": {"$gt": 4}})
        assert matches(doc, {"size": {"$gte": 5, "$lte": 5}})
        assert not matches(doc, {"size": {"$lt": 5}})
        assert matches(doc, {"size": {"$ne": 6}})
        assert matches(doc, {"name": {"$gt": "a"}})
        # cross-type comparisons never match
        assert not matches(doc, {"name": {"$gt": 3}})

    def test_in_exists_combinators(self):
        doc = {"color": "red", "size": 5}
        assert matches(doc, {"color": {"$in": ["red", "blue"]}})
        assert matches(doc, {"color": {"$exists": True},
                             "weight": {"$exists": False}})
        assert matches(doc, {"$or": [{"color": "blue"},
                                     {"size": {"$gt": 1}}]})
        assert matches(doc, {"$and": [{"color": "red"},
                                      {"size": 5}]})
        assert matches(doc, {"$not": {"color": "blue"}})
        assert not matches(doc, {"color": {"$nin": ["red"]}})

    def test_unsupported_operator_raises(self):
        with pytest.raises(QueryError):
            matches({"a": 1}, {"a": {"$regex": "x"}})
        with pytest.raises(QueryError):
            matches({"a": 1}, {"$nor": [{"a": 1}]})


def _statedb():
    db = StateDB(DBHandle(KVStore(":memory:"), "s"))
    batch = UpdateBatch()
    marbles = [
        ("m1", {"color": "red", "size": 1, "owner": "alice"}),
        ("m2", {"color": "blue", "size": 2, "owner": "bob"}),
        ("m3", {"color": "red", "size": 3, "owner": "alice"}),
        ("m4", {"color": "green", "size": 4, "owner": "carol"}),
        ("m5", {"color": "red", "size": 5, "owner": "bob"}),
    ]
    for i, (key, doc) in enumerate(marbles):
        batch.put("cc", key, json.dumps(doc).encode(), Height(1, i))
    batch.put("cc", "binary", b"\x00not-json", Height(1, 9))
    db.apply_updates(batch, Height(1, 9))
    return db


class TestQueryExecution:
    def test_selector_query_records_reads(self):
        db = _statedb()
        sim = TxSimulator(db, "tx1")
        results, _ = sim.get_query_result(
            "cc", json.dumps({"selector": {"color": "red"}}))
        assert [k for k, _ in results] == ["m1", "m3", "m5"]
        rwset = sim.get_tx_simulation_results()
        import fabric_tpu.protos.rwset_pb2 as rwpb
        kv = rwpb.KVRWSet()
        kv.ParseFromString(rwset.ns_rwset[0].rwset)
        assert [r.key for r in kv.reads] == ["m1", "m3", "m5"]

    def test_sort_limit_fields(self):
        db = _statedb()
        sim = TxSimulator(db, "tx")
        results, _ = sim.get_query_result("cc", json.dumps({
            "selector": {"size": {"$gte": 2}},
            "sort": [{"size": "desc"}],
            "limit": 2,
            "fields": ["owner", "size"],
        }))
        docs = [json.loads(v) for _k, v in results]
        assert docs == [{"owner": "bob", "size": 5},
                        {"owner": "carol", "size": 4}]

    def test_pagination_bookmarks(self):
        db = _statedb()
        sim = TxSimulator(db, "tx")
        q = json.dumps({"selector": {"color": "red"}})
        page1, bm1 = sim.get_query_result("cc", q, page_size=2)
        assert [k for k, _ in page1] == ["m1", "m3"] and bm1 == "m3"
        page2, bm2 = sim.get_query_result("cc", q, page_size=2,
                                          bookmark=bm1)
        assert [k for k, _ in page2] == ["m5"] and bm2 == ""

    def test_non_json_invisible_and_writes_not_visible(self):
        db = _statedb()
        sim = TxSimulator(db, "tx")
        sim.put_state("cc", "m9",
                      json.dumps({"color": "red"}).encode())
        results, _ = sim.get_query_result(
            "cc", json.dumps({"selector": {"color": {"$exists":
                                                     True}}}))
        keys = [k for k, _ in results]
        assert "binary" not in keys   # non-JSON skipped
        assert "m9" not in keys       # committed-state-only visibility

    def test_mvcc_conflict_on_queried_key(self):
        """A doc returned by a rich query that changes before commit
        invalidates the tx (per-key read recording)."""
        from fabric_tpu.ledger.txmgr import TxMgr
        from fabric_tpu.protos import transaction as txpb
        db = _statedb()
        sim = TxSimulator(db, "tx")
        sim.get_query_result(
            "cc", json.dumps({"selector": {"owner": "carol"}}))
        sim.put_state("cc", "result", b"based-on-query")
        rwset = sim.get_tx_simulation_results()
        # concurrent update to m4 commits first
        batch = UpdateBatch()
        batch.put("cc", "m4", json.dumps(
            {"color": "green", "size": 4, "owner": "dave"}).encode(),
            Height(2, 0))
        db.apply_updates(batch, Height(2, 0))
        codes, _ = TxMgr(db).validate_and_prepare(3, [rwset])
        assert codes == [txpb.TxValidationCode.MVCC_READ_CONFLICT]

    def test_index_registry(self):
        reg = IndexRegistry()
        reg.define("cc", "byColor", json.dumps(
            {"index": {"fields": ["color"]}, "name": "byColor",
             "type": "json"}))
        assert reg.list("cc") == ["byColor"]
        with pytest.raises(QueryError):
            reg.define("cc", "bad", "{}")


class TestChaincodeSurface:
    def test_stub_get_query_result(self):
        from fabric_tpu.core.chaincode import shim
        db = _statedb()
        sim = TxSimulator(db, "tx")
        stub = shim.ChaincodeStub(
            channel_id="ch", tx_id="tx", namespace="cc",
            simulator=sim, args=[b"q"], creator=b"", transient=None,
            support=None, timestamp=0)
        rows = list(stub.get_query_result(
            json.dumps({"selector": {"owner": "alice"}})))
        assert [k for k, _ in rows] == ["m1", "m3"]
        rows, bm = stub.get_query_result_with_pagination(
            json.dumps({"selector": {"color": "red"}}), 2)
        assert len(list(rows)) == 2 and bm == "m3"


class TestMaterializedIndexes:
    """Round-4: Mango use_index planning over materialized index
    keyspaces — selector queries on indexed fields stop scanning
    (reference: statecouchdb index/pagination behavior)."""

    @staticmethod
    def _indexed_db(n=100_000):
        from fabric_tpu.ledger.richquery import execute_query
        db = StateDB(DBHandle(KVStore(":memory:"), "s"))
        db.define_index("cc", "byColor", json.dumps(
            {"index": {"fields": ["color"]}, "name": "byColor",
             "type": "json"}))
        db.define_index("cc", "bySize", json.dumps(
            {"index": {"fields": ["size"]}, "name": "bySize",
             "type": "json"}))
        colors = ["red", "blue", "green", "gold"]
        batch = UpdateBatch()
        for i in range(n):
            doc = {"color": colors[i % len(colors)]
                   if i % 1000 else "rare",
                   "size": i % 50, "owner": f"o{i % 7}"}
            batch.put("cc", f"k{i:06d}", json.dumps(doc).encode(),
                      Height(1, i))
        db.apply_updates(batch, Height(1, n))
        return db, execute_query

    def test_index_hit_no_full_scan_100k_keys(self):
        db, execute_query = self._indexed_db()
        q = json.dumps({"selector": {"color": "rare"}})
        import time
        t0 = time.perf_counter()
        out, _bm = execute_query(db, "cc", q)
        dt_indexed = time.perf_counter() - t0
        assert db.query_stats["index_scans"] == 1
        assert db.query_stats["full_scans"] == 0
        assert len(out) == 100  # i % 1000 == 0 -> 100 docs
        assert all(json.loads(raw)["color"] == "rare"
                   for _k, raw, _v in out)
        # same answer through the scan path, much slower
        saved = db.indexes
        from fabric_tpu.ledger.richquery import IndexRegistry as IR
        db.indexes = IR()
        t0 = time.perf_counter()
        out_scan, _ = execute_query(db, "cc", q)
        dt_scan = time.perf_counter() - t0
        db.indexes = saved
        assert sorted(k for k, _r, _v in out) == \
            sorted(k for k, _r, _v in out_scan)
        assert db.query_stats["full_scans"] == 1
        assert dt_indexed < dt_scan / 5, (dt_indexed, dt_scan)

    def test_range_and_use_index(self):
        db, execute_query = self._indexed_db(5000)
        q = json.dumps({"selector": {"size": {"$gte": 48}},
                        "use_index": "bySize"})
        out, _ = execute_query(db, "cc", q)
        assert db.query_stats["index_scans"] == 1
        want = {f"k{i:06d}" for i in range(5000) if i % 50 >= 48}
        assert {k for k, _r, _v in out} == want

    def test_index_maintained_on_update_and_delete(self):
        db, execute_query = self._indexed_db(2000)
        q = json.dumps({"selector": {"color": "rare"}})
        out, _ = execute_query(db, "cc", q)
        n0 = len(out)
        assert n0 == 2          # i in {0, 1000}
        batch = UpdateBatch()
        # repaint one rare marble and delete the other
        batch.put("cc", "k000000",
                  json.dumps({"color": "blue", "size": 1}).encode(),
                  Height(2, 0))
        batch.delete("cc", "k001000", Height(2, 1))
        db.apply_updates(batch, Height(2, 1))
        out2, _ = execute_query(db, "cc", q)
        assert len(out2) == 0
        assert "k000000" not in {k for k, _r, _v in out2}
        q_blue = json.dumps({"selector": {"color": "blue"},
                             "use_index": "byColor"})
        out3, _ = execute_query(db, "cc", q_blue)
        assert "k000000" in {k for k, _r, _v in out3}

    def test_index_pagination_bookmarks(self):
        db, execute_query = self._indexed_db(3000)
        q = json.dumps({"selector": {"color": "red"}})
        seen = []
        bm = ""
        while True:
            out, bm = execute_query(db, "cc", q, page_size=100,
                                    bookmark=bm)
            seen.extend(k for k, _r, _v in out)
            if not bm:
                break
        want = [f"k{i:06d}" for i in range(3000)
                if i % 1000 and i % 4 == 0]
        assert sorted(seen) == sorted(want)
        assert len(seen) == len(set(seen))

    def test_unindexed_selector_falls_back_to_scan(self):
        db, execute_query = self._indexed_db(500)
        q = json.dumps({"selector": {"owner": "o3"}})
        out, _ = execute_query(db, "cc", q)
        assert db.query_stats["full_scans"] == 1
        assert all(json.loads(raw)["owner"] == "o3"
                   for _k, raw, _v in out)


class TestChaincodeIndexInstall:
    def test_definition_indexes_install_and_serve(self, tmp_path):
        """A chaincode definition shipping META-INF-style indexes gets
        them built on define; the stub's rich query then plans through
        the index."""
        from fabric_tpu.bccsp.sw import SWProvider
        from fabric_tpu.core.chaincode import (
            Chaincode, ChaincodeDefinition, shim,
        )
        from fabric_tpu.internal import cryptogen
        from fabric_tpu.internal.configtxgen import (
            genesis_block, new_channel_group,
        )
        from fabric_tpu.msp import msp_config_from_dir
        from fabric_tpu.msp.mspimpl import X509MSP
        from fabric_tpu.peer import Peer
        import os

        csp = SWProvider()
        cdir = str(tmp_path / "crypto")
        org = cryptogen.generate_org(cdir, "org1.example.com",
                                     n_peers=1, n_users=1)
        profile = {
            "Consortium": "C", "Capabilities": {"V2_0": True},
            "Application": {
                "Organizations": [{"Name": "Org1", "ID": "Org1MSP",
                                   "MSPDir": os.path.join(org, "msp")}],
                "Capabilities": {"V2_0": True}},
            "Orderer": {"OrdererType": "solo",
                        "Addresses": ["o:7050"],
                        "BatchTimeout": "1s",
                        "BatchSize": {"MaxMessageCount": 10},
                        "Organizations": [],
                        "Capabilities": {"V2_0": True}},
        }
        genesis = genesis_block("idxchan", new_channel_group(profile))
        msp = X509MSP(csp)
        msp.setup(msp_config_from_dir(
            os.path.join(org, "peers", "peer0.org1.example.com",
                         "msp"), "Org1MSP", csp=csp))
        peer = Peer(str(tmp_path / "peer"), msp, csp)
        channel = peer.join_channel(genesis)
        channel.define_chaincode(ChaincodeDefinition(
            name="marbles",
            indexes=(("byColor", json.dumps(
                {"index": {"fields": ["color"]}, "name": "byColor",
                 "type": "json"})),)))
        ledger = channel.ledger
        batch = UpdateBatch()
        for i in range(50):
            batch.put("marbles", f"m{i}",
                      json.dumps({"color": "red" if i % 5 == 0
                                  else "blue"}).encode(),
                      Height(1, i))
        ledger.state_db.apply_writes_only(batch)
        sim = ledger.new_tx_simulator("t1")
        results, _ = sim.get_query_result(
            "marbles", json.dumps({"selector": {"color": "red"}}))
        assert len(results) == 10
        assert ledger.state_db.query_stats["index_scans"] == 1
        peer.close()


class TestIndexDurability:
    def test_registry_persists_across_reopen(self):
        from fabric_tpu.ledger.richquery import execute_query
        store = KVStore(":memory:")
        db = StateDB(DBHandle(store, "s"))
        db.define_index("cc", "byColor", json.dumps(
            {"index": {"fields": ["color"]}, "name": "byColor"}))
        batch = UpdateBatch()
        batch.put("cc", "k1", b'{"color": "red"}', Height(1, 0))
        db.apply_updates(batch, Height(1, 0))
        # "restart": a fresh StateDB over the same store must keep
        # maintaining AND serving the index
        db2 = StateDB(DBHandle(store, "s"))
        assert db2.indexes.list("cc") == ["byColor"]
        b2 = UpdateBatch()
        b2.put("cc", "k2", b'{"color": "red"}', Height(2, 0))
        db2.apply_updates(b2, Height(2, 0))
        out, _ = execute_query(db2, "cc", json.dumps(
            {"selector": {"color": "red"}}))
        assert {k for k, _r, _v in out} == {"k1", "k2"}
        assert db2.query_stats["index_scans"] == 1

    def test_reinstall_drops_stale_entries(self):
        from fabric_tpu.ledger.richquery import execute_query
        store = KVStore(":memory:")
        db = StateDB(DBHandle(store, "s"))
        db.define_index("cc", "bySize", json.dumps(
            {"index": {"fields": ["size"]}, "name": "bySize"}))
        batch = UpdateBatch()
        batch.put("cc", "k1", b'{"size": 1}', Height(1, 0))
        db.apply_updates(batch, Height(1, 0))
        # simulate the registry being lost while entries persist
        # (pre-fix restart shape), value changes unmaintained, then
        # the chaincode definition re-installs the index with a NEW
        # shape (different json forces the rebuild path)
        db.indexes._indexes.clear()
        b2 = UpdateBatch()
        b2.put("cc", "k1", b'{"size": 9}', Height(2, 0))
        db.apply_writes_only(b2)
        db.define_index("cc", "bySize", json.dumps(
            {"index": {"fields": ["size"]}, "name": "bySize",
             "type": "json"}))
        # paginated query must not return k1 twice / under stale value
        seen = []
        bm = ""
        while True:
            out, bm = execute_query(
                db, "cc", json.dumps(
                    {"selector": {"size": {"$gte": 0}}}),
                page_size=1, bookmark=bm)
            seen.extend(k for k, _r, _v in out)
            if not bm:
                break
        assert seen == ["k1"]

    def test_string_extension_bounds_match_scan(self):
        """$gt on a string whose extensions contain NULs: indexed and
        scan plans must agree (escape-aware bound composition)."""
        from fabric_tpu.ledger.richquery import (
            IndexRegistry, execute_query,
        )
        store = KVStore(":memory:")
        db = StateDB(DBHandle(store, "s"))
        db.define_index("cc", "byColor", json.dumps(
            {"index": {"fields": ["color"]}, "name": "byColor"}))
        batch = UpdateBatch()
        batch.put("cc", "k1", json.dumps(
            {"color": "ab\u0000x"}).encode(), Height(1, 0))
        batch.put("cc", "k2", b'{"color": "ac"}', Height(1, 1))
        batch.put("cc", "k3", b'{"color": "ab"}', Height(1, 2))
        db.apply_updates(batch, Height(1, 2))
        for q in ({"selector": {"color": {"$gt": "ab"}}},
                  {"selector": {"color": {"$lte": "ab"}}},
                  {"selector": {"color": "ab"}}):
            out, _ = execute_query(db, "cc", json.dumps(q))
            saved = db.indexes
            db.indexes = IndexRegistry()
            scan, _ = execute_query(db, "cc", json.dumps(q))
            db.indexes = saved
            assert sorted(k for k, _r, _v in out) == \
                sorted(k for k, _r, _v in scan), q
