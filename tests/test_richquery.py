"""Rich (JSON selector) state queries — the statecouchdb analog.

Reference semantics: statecouchdb rich queries (selector subset,
pagination, committed-state-only visibility, per-key read recording,
no phantom re-check).
"""

import json

import pytest

from fabric_tpu.ledger.kvdb import DBHandle, KVStore
from fabric_tpu.ledger.richquery import (
    IndexRegistry, QueryError, matches,
)
from fabric_tpu.ledger.statedb import Height, StateDB, UpdateBatch
from fabric_tpu.ledger.txmgr import TxSimulator


class TestSelector:
    def test_equality_and_nested(self):
        doc = {"color": "red", "owner": {"name": "alice"}, "size": 5}
        assert matches(doc, {"color": "red"})
        assert matches(doc, {"owner.name": "alice"})
        assert not matches(doc, {"color": "blue"})
        assert not matches(doc, {"missing": 1})

    def test_comparison_ops(self):
        doc = {"size": 5, "name": "m"}
        assert matches(doc, {"size": {"$gt": 4}})
        assert matches(doc, {"size": {"$gte": 5, "$lte": 5}})
        assert not matches(doc, {"size": {"$lt": 5}})
        assert matches(doc, {"size": {"$ne": 6}})
        assert matches(doc, {"name": {"$gt": "a"}})
        # cross-type comparisons never match
        assert not matches(doc, {"name": {"$gt": 3}})

    def test_in_exists_combinators(self):
        doc = {"color": "red", "size": 5}
        assert matches(doc, {"color": {"$in": ["red", "blue"]}})
        assert matches(doc, {"color": {"$exists": True},
                             "weight": {"$exists": False}})
        assert matches(doc, {"$or": [{"color": "blue"},
                                     {"size": {"$gt": 1}}]})
        assert matches(doc, {"$and": [{"color": "red"},
                                      {"size": 5}]})
        assert matches(doc, {"$not": {"color": "blue"}})
        assert not matches(doc, {"color": {"$nin": ["red"]}})

    def test_unsupported_operator_raises(self):
        with pytest.raises(QueryError):
            matches({"a": 1}, {"a": {"$regex": "x"}})
        with pytest.raises(QueryError):
            matches({"a": 1}, {"$nor": [{"a": 1}]})


def _statedb():
    db = StateDB(DBHandle(KVStore(":memory:"), "s"))
    batch = UpdateBatch()
    marbles = [
        ("m1", {"color": "red", "size": 1, "owner": "alice"}),
        ("m2", {"color": "blue", "size": 2, "owner": "bob"}),
        ("m3", {"color": "red", "size": 3, "owner": "alice"}),
        ("m4", {"color": "green", "size": 4, "owner": "carol"}),
        ("m5", {"color": "red", "size": 5, "owner": "bob"}),
    ]
    for i, (key, doc) in enumerate(marbles):
        batch.put("cc", key, json.dumps(doc).encode(), Height(1, i))
    batch.put("cc", "binary", b"\x00not-json", Height(1, 9))
    db.apply_updates(batch, Height(1, 9))
    return db


class TestQueryExecution:
    def test_selector_query_records_reads(self):
        db = _statedb()
        sim = TxSimulator(db, "tx1")
        results, _ = sim.get_query_result(
            "cc", json.dumps({"selector": {"color": "red"}}))
        assert [k for k, _ in results] == ["m1", "m3", "m5"]
        rwset = sim.get_tx_simulation_results()
        import fabric_tpu.protos.rwset_pb2 as rwpb
        kv = rwpb.KVRWSet()
        kv.ParseFromString(rwset.ns_rwset[0].rwset)
        assert [r.key for r in kv.reads] == ["m1", "m3", "m5"]

    def test_sort_limit_fields(self):
        db = _statedb()
        sim = TxSimulator(db, "tx")
        results, _ = sim.get_query_result("cc", json.dumps({
            "selector": {"size": {"$gte": 2}},
            "sort": [{"size": "desc"}],
            "limit": 2,
            "fields": ["owner", "size"],
        }))
        docs = [json.loads(v) for _k, v in results]
        assert docs == [{"owner": "bob", "size": 5},
                        {"owner": "carol", "size": 4}]

    def test_pagination_bookmarks(self):
        db = _statedb()
        sim = TxSimulator(db, "tx")
        q = json.dumps({"selector": {"color": "red"}})
        page1, bm1 = sim.get_query_result("cc", q, page_size=2)
        assert [k for k, _ in page1] == ["m1", "m3"] and bm1 == "m3"
        page2, bm2 = sim.get_query_result("cc", q, page_size=2,
                                          bookmark=bm1)
        assert [k for k, _ in page2] == ["m5"] and bm2 == ""

    def test_non_json_invisible_and_writes_not_visible(self):
        db = _statedb()
        sim = TxSimulator(db, "tx")
        sim.put_state("cc", "m9",
                      json.dumps({"color": "red"}).encode())
        results, _ = sim.get_query_result(
            "cc", json.dumps({"selector": {"color": {"$exists":
                                                     True}}}))
        keys = [k for k, _ in results]
        assert "binary" not in keys   # non-JSON skipped
        assert "m9" not in keys       # committed-state-only visibility

    def test_mvcc_conflict_on_queried_key(self):
        """A doc returned by a rich query that changes before commit
        invalidates the tx (per-key read recording)."""
        from fabric_tpu.ledger.txmgr import TxMgr
        from fabric_tpu.protos import transaction as txpb
        db = _statedb()
        sim = TxSimulator(db, "tx")
        sim.get_query_result(
            "cc", json.dumps({"selector": {"owner": "carol"}}))
        sim.put_state("cc", "result", b"based-on-query")
        rwset = sim.get_tx_simulation_results()
        # concurrent update to m4 commits first
        batch = UpdateBatch()
        batch.put("cc", "m4", json.dumps(
            {"color": "green", "size": 4, "owner": "dave"}).encode(),
            Height(2, 0))
        db.apply_updates(batch, Height(2, 0))
        codes, _ = TxMgr(db).validate_and_prepare(3, [rwset])
        assert codes == [txpb.TxValidationCode.MVCC_READ_CONFLICT]

    def test_index_registry(self):
        reg = IndexRegistry()
        reg.define("cc", "byColor", json.dumps(
            {"index": {"fields": ["color"]}, "name": "byColor",
             "type": "json"}))
        assert reg.list("cc") == ["byColor"]
        with pytest.raises(QueryError):
            reg.define("cc", "bad", "{}")


class TestChaincodeSurface:
    def test_stub_get_query_result(self):
        from fabric_tpu.core.chaincode import shim
        db = _statedb()
        sim = TxSimulator(db, "tx")
        stub = shim.ChaincodeStub(
            channel_id="ch", tx_id="tx", namespace="cc",
            simulator=sim, args=[b"q"], creator=b"", transient=None,
            support=None, timestamp=0)
        rows = list(stub.get_query_result(
            json.dumps({"selector": {"owner": "alice"}})))
        assert [k for k, _ in rows] == ["m1", "m3"]
        rows, bm = stub.get_query_result_with_pagination(
            json.dumps({"selector": {"color": "red"}}), 2)
        assert len(list(rows)) == 2 and bm == "m3"
