"""Lock-order sanitizer tests (ISSUE 5 tentpole, runtime half).

The sanitizer itself must be trustworthy before its findings gate CI:
a forced A→B/B→A inversion is reported with BOTH acquisition stacks, a
lock held across a (stubbed) device dispatch or an injected-fault
stall is flagged, clean nesting and reentrant RLocks stay silent, and
the whole apparatus is a no-op when FTPU_LOCKCHECK is unset.

Tests use private `LockSanitizer` instances (never the env-installed
global) so deliberate violations cannot fail a sanitizer-armed CI run
of this very file.
"""

import hashlib
import threading

import numpy as np
import pytest

from fabric_tpu.bccsp import ECDSAKeyGenOpts, VerifyItem
from fabric_tpu.bccsp.sw import SWProvider
from fabric_tpu.bccsp.tpu import TPUProvider
from fabric_tpu.common import faults, lockcheck
from fabric_tpu.common.lockcheck import LockOrderError, LockSanitizer


def _acquire_ab(lock_a, lock_b):
    with lock_a:
        with lock_b:
            pass


class TestInversionDetection:
    def test_ab_ba_inversion_reported_with_both_stacks(self):
        san = LockSanitizer()
        lock_a = san.lock()
        lock_b = san.lock()
        _acquire_ab(lock_a, lock_b)
        assert san.violations() == []      # one order alone is fine
        _acquire_ab(lock_b, lock_a)
        vs = san.violations()
        assert len(vs) == 1
        v = vs[0]
        assert v.kind == "order-inversion"
        report = v.render()
        # both creation sites named, and the acquiring frames of BOTH
        # orders present (the helper appears for this thread's edge
        # and for the recorded prior edge)
        assert "test_lockcheck.py" in report
        assert report.count("_acquire_ab") >= 2
        assert "while acquiring" in report
        assert "already acquired" in report

    def test_inversion_across_threads(self):
        san = LockSanitizer()
        lock_a = san.lock()
        lock_b = san.lock()
        t = threading.Thread(target=_acquire_ab,
                             args=(lock_a, lock_b))
        t.start()
        t.join()
        _acquire_ab(lock_b, lock_a)
        vs = san.violations()
        assert len(vs) == 1
        assert vs[0].kind == "order-inversion"

    def test_three_lock_cycle(self):
        # A→B, B→C, then C→A: no single pair inverts, the CYCLE does
        san = LockSanitizer()
        a = san.lock()
        b = san.lock()
        c = san.lock()          # three lines: three distinct classes
        _acquire_ab(a, b)
        _acquire_ab(b, c)
        assert san.violations() == []
        _acquire_ab(c, a)
        vs = san.violations()
        assert len(vs) == 1
        assert vs[0].kind == "order-inversion"

    def test_clean_nesting_passes(self):
        san = LockSanitizer()
        lock_a = san.lock()
        lock_b = san.lock()
        threads = [threading.Thread(target=_acquire_ab,
                                    args=(lock_a, lock_b))
                   for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        _acquire_ab(lock_a, lock_b)
        assert san.violations() == []

    def test_inversion_deduplicated(self):
        san = LockSanitizer()
        lock_a = san.lock()
        lock_b = san.lock()
        _acquire_ab(lock_a, lock_b)
        _acquire_ab(lock_b, lock_a)
        _acquire_ab(lock_b, lock_a)
        assert len(san.violations()) == 1

    def test_reentrant_rlock_is_not_a_finding(self):
        san = LockSanitizer()
        r = san.rlock()
        with r:
            with r:
                san.note_blocking("probe")  # reentrancy: one held entry
        assert [v for v in san.violations()
                if v.kind == "order-inversion"] == []

    def test_same_class_nesting_skipped(self):
        # two instances from ONE creation line are one lock class:
        # nesting them is not an inversion finding (documented limit)
        san = LockSanitizer()
        locks = [san.lock() for _ in range(2)]
        _acquire_ab(locks[0], locks[1])
        _acquire_ab(locks[1], locks[0])
        assert san.violations() == []

    def test_raise_mode(self):
        san = LockSanitizer(raise_on_violation=True)
        lock_a = san.lock()
        lock_b = san.lock()
        _acquire_ab(lock_a, lock_b)
        with pytest.raises(LockOrderError):
            _acquire_ab(lock_b, lock_a)

    def test_allow_pair_waiver(self):
        san = LockSanitizer()
        lock_a = san.lock()
        lock_b = san.lock()
        san.allow_pair(lock_a._site, lock_b._site,
                       reason="test: documented benign pair")
        _acquire_ab(lock_a, lock_b)
        _acquire_ab(lock_b, lock_a)
        assert san.violations() == []
        with pytest.raises(ValueError):
            san.allow_pair("x", "y", reason="")


class TestHeldAcrossBlocking:
    def test_lock_held_across_blocking_span(self):
        san = LockSanitizer()
        lock = san.lock()
        with lock:
            san.note_blocking("tpu.dispatch")
        vs = san.violations()
        assert len(vs) == 1
        v = vs[0]
        assert v.kind == "held-across-blocking"
        assert "tpu.dispatch" in v.description
        report = v.render()
        assert "acquired at" in report
        assert "blocking span" in report
        assert "test_lockcheck.py" in report

    def test_cross_thread_release_evicts_holder_entry(self):
        # a plain Lock released by ANOTHER thread (handoff idiom) must
        # evict the owner's held entry, or the owner's next blocking
        # probe reports a lock it no longer holds
        san = LockSanitizer()
        handoff = san.lock()
        handoff.acquire()
        t = threading.Thread(target=handoff.release)
        t.start()
        t.join()
        san.note_blocking("tpu.dispatch")
        assert san.violations() == []

    def test_no_lock_held_is_clean(self):
        san = LockSanitizer()
        lock = san.lock()
        with lock:
            pass
        san.note_blocking("tpu.dispatch")
        assert san.violations() == []

    def test_allow_blocking_waiver(self):
        san = LockSanitizer()
        lock = san.lock()
        san.allow_blocking("tpu.dispatch", lock._site,
                           reason="test: prewarm holds this by design")
        with lock:
            san.note_blocking("tpu.dispatch")
        assert san.violations() == []

    def test_condition_wait_releases_bookkeeping(self):
        # Condition.wait goes through _release_save/_acquire_restore:
        # the held-set must empty during the wait and refill after, so
        # a blocking probe AFTER a wait still sees exactly one holder
        san = LockSanitizer()
        cond = san.condition()
        with cond:
            cond.wait(timeout=0.01)
            san.note_blocking("probe")
        vs = san.violations()
        assert len(vs) == 1        # held on re-acquire: flagged once
        san.clear()
        with cond:
            cond.wait(timeout=0.01)
        san.note_blocking("probe")
        assert san.violations() == []   # fully released afterwards

    def test_lock_held_across_stubbed_device_dispatch(self, monkeypatch):
        """End-to-end: the note_blocking hooks in bccsp/tpu.py fire on
        a real (device-stubbed) verify_batch, so holding a tracked
        lock across it is a finding tagged tpu.dispatch."""
        san = LockSanitizer()
        monkeypatch.setattr(lockcheck, "_SAN", san)
        sw = SWProvider()
        key = sw.key_gen(ECDSAKeyGenOpts(ephemeral=True))
        items = []
        for i in range(8):
            m = f"lockcheck {i}".encode()
            sig = sw.sign(key, hashlib.sha256(m).digest())
            items.append(VerifyItem(key=key.public_key(),
                                    signature=sig, message=m))
        tpu = TPUProvider(min_batch=4, use_g16=False)

        def fake_qtab_fn(K):
            return lambda qx, qy: np.zeros((K,), dtype=np.int32)

        def fake_pipeline_digest(K, q16=False):
            def run(key_idx, q_flat, g16, r8, rpn8, w8, premask,
                    digests):
                return np.asarray(premask)
            return run

        def fake_pipeline(K, q16=False):
            def run(blocks, nblocks, key_idx, q_flat, g16, r, rpn, w,
                    premask, digests, has_digest):
                return np.asarray(premask)
            return run

        def fake_ladder():
            def run(blocks, nblocks, qx, qy, r, rpn, w, premask,
                    digests, has_digest):
                return np.asarray(premask)
            return run

        monkeypatch.setattr(tpu, "_qtab_fn", fake_qtab_fn)
        monkeypatch.setattr(tpu, "_comb_pipeline_digest",
                            fake_pipeline_digest)
        monkeypatch.setattr(tpu, "_comb_pipeline", fake_pipeline)
        monkeypatch.setattr(tpu, "_pipeline", fake_ladder)
        caller_lock = san.lock()
        with caller_lock:
            out = tpu.verify_batch(items)
        assert out == [True] * len(items)
        vs = [v for v in san.violations()
              if v.kind == "held-across-blocking"]
        assert len(vs) == 1
        assert "tpu.dispatch" in vs[0].description
        # clean run afterwards: no lock held -> nothing new
        san.clear()
        assert tpu.verify_batch(items) == [True] * len(items)
        assert san.violations() == []

    def test_lock_held_across_injected_fault_sleep(self, monkeypatch):
        """faults.check delay mode routes through the sanitizer: an
        injected stall under a tracked lock is a finding."""
        san = LockSanitizer()
        monkeypatch.setattr(lockcheck, "_SAN", san)
        faults.arm("tpu.dispatch", mode="delay", count=1,
                   delay_s=0.01)
        lock = san.lock()
        with lock:
            faults.check("tpu.dispatch")
        vs = san.violations()
        assert len(vs) == 1
        assert vs[0].kind == "held-across-blocking"
        assert "fault-delay:tpu.dispatch" in vs[0].description


class TestNoOpWhenDisabled:
    def test_threading_untouched_without_install(self):
        if lockcheck.enabled():
            pytest.skip("global sanitizer armed (FTPU_LOCKCHECK run)")
        assert threading.Lock is lockcheck._orig_lock
        assert threading.RLock is lockcheck._orig_rlock
        assert threading.Condition is lockcheck._orig_condition

    def test_note_blocking_is_free_when_disabled(self):
        if lockcheck.enabled():
            pytest.skip("global sanitizer armed (FTPU_LOCKCHECK run)")
        # must not raise, record, or allocate a sanitizer
        lockcheck.note_blocking("tpu.dispatch")
        assert lockcheck.sanitizer() is None

    def test_install_from_env_off_values(self, monkeypatch):
        if lockcheck.enabled():
            pytest.skip("global sanitizer armed (FTPU_LOCKCHECK run)")
        for off in ("", "0", "false", "off"):
            monkeypatch.setenv(lockcheck.ENV_VAR, off)
            assert lockcheck.install_from_env() is None

    def test_install_uninstall_roundtrip(self):
        if lockcheck.enabled():
            pytest.skip("global sanitizer armed (FTPU_LOCKCHECK run)")
        try:
            san = lockcheck.install()
            assert lockcheck.enabled()
            lk = threading.Lock()
            assert isinstance(lk, lockcheck._TrackedLock)
            with lk:
                san.note_blocking("probe")
            assert len(san.violations()) == 1
        finally:
            lockcheck.uninstall()
        assert threading.Lock is lockcheck._orig_lock
        assert not lockcheck.enabled()


class TestReport:
    def test_clean_report(self):
        assert LockSanitizer().report() == "lockcheck: clean"

    def test_report_counts_and_renders(self):
        san = LockSanitizer()
        lock = san.lock()
        with lock:
            san.note_blocking("tpu.dispatch")
        rep = san.report()
        assert "1 violation(s)" in rep
        assert "held-across-blocking" in rep
