"""Verified onboarding / chain replication: adversarial catch-up.

The claims under test (ISSUE 3 tentpole): a joining orderer pulls the
chain from ANY available consenter with per-endpoint failover, verifies
every block (hash chain, previous-hash linkage, signatures through the
batched BCCSP seam) before committing, survives mid-stream source
death AND process kills (resume from the last durable block, no
re-pull of the verified prefix), and never commits a forged, tampered,
or truncated suffix.

Everything here runs WITHOUT the `cryptography` wheel: block
signatures use a deterministic stub scheme behind the same
policy.prepare/finish + csp.verify_batch seam the real BlockValidation
policy uses (the x509-backed end-to-end run lives in
test_integration_nwo.py).
"""

from __future__ import annotations

import hashlib
import threading
import time
from types import SimpleNamespace

import pytest

from fabric_tpu.common import faults
from fabric_tpu.common.backoff import FullJitterBackoff
from fabric_tpu.common.policies.policy import PolicyError
from fabric_tpu.orderer import onboarding as onb
from fabric_tpu.protos import common, configtx as ctxpb
from fabric_tpu.protoutil import protoutil as pu

CHANNEL = "onbchannel"


# ---------------------------------------------------------------------------
# stub crypto/policy fabric (same seam shape as the real thing)
# ---------------------------------------------------------------------------

def _sign(ident: bytes, msg: bytes) -> bytes:
    return hashlib.sha256(b"stubsig|" + ident + b"|" + msg).digest()


class _StubCsp:
    def __init__(self):
        self.batches = 0
        self.items_seen = 0

    def verify_batch(self, items):
        self.batches += 1
        self.items_seen += len(items)
        return [sig == _sign(ident, msg) for ident, msg, sig in items]


class _Prepared:
    def __init__(self, policy, signed):
        self.items = [(sd.identity, sd.data, sd.signature)
                      for sd in signed]
        self._policy = policy
        self._signed = signed

    def finish(self, ok):
        for sd, o in zip(self._signed, ok):
            if o and sd.identity in self._policy.signers:
                return
        raise PolicyError("no valid orderer signature")


class _StubPolicy:
    """BlockValidation stand-in: ANY valid signature by a known
    orderer identity satisfies the policy."""

    def __init__(self, signers):
        self.signers = set(signers)

    def prepare(self, signed):
        return _Prepared(self, signed)


class _StubBundle:
    def __init__(self, csp, signers, consenters=()):
        self.csp = csp
        self.policy_manager = SimpleNamespace(
            get_policy=lambda path: _StubPolicy(signers))
        meta = ctxpb.ConsensusMetadata()
        for ep in consenters:
            host, port = ep.rsplit(":", 1)
            c = meta.consenters.add()
            c.host, c.port = host, int(port)
        self.orderer = SimpleNamespace(
            consensus_metadata=meta.SerializeToString(
                deterministic=True))


# ---------------------------------------------------------------------------
# stub chain construction (real Block protos, stub signatures)
# ---------------------------------------------------------------------------

def _config_envelope(new_signers: list[bytes]) -> bytes:
    ch = pu.make_channel_header(common.HeaderType.CONFIG, CHANNEL)
    payload = common.Payload()
    payload.header.channel_header = pu.marshal(ch)
    payload.data = b"signers:" + b",".join(new_signers)
    env = common.Envelope(payload=pu.marshal(payload))
    return pu.marshal(env)


def _signers_from_config_block(block: common.Block) -> list[bytes]:
    payload = pu.get_payload(pu.extract_envelope(block, 0))
    return payload.data.split(b":", 1)[1].split(b",")


def _make_chain(n: int, signer: bytes = b"orderer-a",
                config_at: dict | None = None) -> list[common.Block]:
    """n blocks, hash-chained; block 0 unsigned (genesis), the rest
    stub-signed. `config_at[num] = [new signers]` makes block `num` a
    CONFIG block switching the signing identity from there on."""
    config_at = config_at or {}
    blocks = []
    prev = b""
    for i in range(n):
        block = pu.new_block(i, prev)
        if i in config_at:
            block.data.data.append(_config_envelope(config_at[i]))
        else:
            block.data.data.append(b"payload-%d" % i)
        block.header.data_hash = pu.block_data_hash(block.data)
        md = common.Metadata()
        md.value = pu.encode_last_config(
            max([0] + [c for c in config_at if c <= i]))
        if i > 0:
            ms = md.signatures.add()
            ms.signature_header = pu.marshal(
                pu.create_signature_header(signer, b"n" * 24))
            ms.signature = _sign(
                signer, md.value + ms.signature_header +
                pu.block_header_bytes(block.header))
        block.metadata.metadata[
            common.BlockMetadataIndex.SIGNATURES] = pu.marshal(md)
        blocks.append(block)
        prev = pu.block_header_hash(block.header)
        if i in config_at:
            signer = config_at[i][0]
    return blocks


# ---------------------------------------------------------------------------
# fake cluster fabric
# ---------------------------------------------------------------------------

class _Source:
    def __init__(self, blocks):
        self.blocks = list(blocks)
        self.dead = False
        self.pulls = []          # recorded (start, end) requests

    def serve(self, start, end):
        if self.dead:
            raise ConnectionError("source down")
        self.pulls.append((start, end))
        return [b for b in self.blocks
                if start <= b.header.number < end]


class _FakeTransport:
    endpoint = "joiner:0"

    def __init__(self, sources: dict):
        self.sources = sources

    def pull_blocks(self, ep, channel, start, end):
        assert channel == CHANNEL
        return self.sources[ep].serve(start, end)


class _ListSink:
    """Minimal crash-safe-ledger stand-in: verify through the SAME
    verify_block_span the production sinks use, commit = append."""

    def __init__(self, bundle):
        self.chain = []
        self.bundle = bundle

    def height(self):
        return len(self.chain)

    def tip_hash(self):
        if not self.chain:
            return None
        return pu.block_header_hash(self.chain[-1].header)

    def verify(self, blocks):
        n, bundle_after, err = onb.verify_block_span(
            CHANNEL, blocks, self.height(), self.tip_hash(),
            self.bundle)
        self._bundle_after = bundle_after
        return n, err

    def commit(self, block):
        self.chain.append(block)
        self.bundle = self._bundle_after


def _replicator(sink, sources, provider=None, **kw):
    transport = _FakeTransport(sources)
    kw.setdefault("backoff", FullJitterBackoff(0.001, 0.01))
    kw.setdefault("selector",
                  onb.SourceSelector(exclude_after=2, cooldown_s=0.2))
    return onb.ChainReplicator(
        CHANNEL, transport,
        consenters_fn=lambda: list(sources),
        sink=sink, metrics_provider=provider, **kw), transport


# ---------------------------------------------------------------------------
# SourceSelector
# ---------------------------------------------------------------------------

class TestSourceSelector:
    def test_round_robin_and_exclusion(self):
        t = [0.0]
        s = onb.SourceSelector(exclude_after=2, cooldown_s=5.0,
                               clock=lambda: t[0])
        s.update(["a:1", "b:2", "c:3"])
        assert {s.pick(), s.pick(), s.pick()} == {"a:1", "b:2", "c:3"}
        assert not s.report_failure("a:1")
        assert s.report_failure("a:1")        # second failure excludes
        assert not s.admitted("a:1")
        picks = {s.pick() for _ in range(4)}
        assert "a:1" not in picks

    def test_cooldown_readmits_with_clean_slate(self):
        t = [0.0]
        s = onb.SourceSelector(exclude_after=1, cooldown_s=5.0,
                               clock=lambda: t[0])
        s.update(["a:1"])
        s.report_failure("a:1")
        assert not s.admitted("a:1")
        t[0] = 5.1
        assert s.admitted("a:1")
        # clean slate: one more failure is needed to exclude again
        assert s.report_failure("a:1")

    def test_all_excluded_desperation_pick(self):
        t = [0.0]
        s = onb.SourceSelector(exclude_after=1, cooldown_s=10.0,
                               clock=lambda: t[0])
        s.update(["a:1", "b:2"])
        s.report_failure("a:1")
        t[0] = 1.0
        s.report_failure("b:2")
        # everything excluded: the earliest-expiring one is offered
        assert s.pick() == "a:1"

    def test_update_drops_departed_endpoints(self):
        s = onb.SourceSelector()
        s.update(["a:1", "b:2"])
        s.update(["b:2"])
        assert s.pick() == "b:2"
        assert s.pick() == "b:2"

    def test_success_clears_exclusion(self):
        s = onb.SourceSelector(exclude_after=1, cooldown_s=100.0)
        s.update(["a:1"])
        s.report_failure("a:1")
        s.report_success("a:1")
        assert s.admitted("a:1")


# ---------------------------------------------------------------------------
# verify_block_span (the VerifyBlocks twin)
# ---------------------------------------------------------------------------

class TestVerifyBlockSpan:
    def _bundle(self, signers=(b"orderer-a",)):
        return _StubBundle(_StubCsp(), signers)

    def test_valid_span_verifies_in_one_batch(self):
        chain = _make_chain(6)
        bundle = self._bundle()
        n, after, err = onb.verify_block_span(CHANNEL, chain, 0, None,
                                              bundle)
        assert (n, err) == (6, None)
        assert bundle.csp.batches == 1          # ONE batched dispatch
        assert bundle.csp.items_seen == 5       # genesis unsigned

    def test_forged_signature_truncates_prefix(self):
        chain = _make_chain(6)
        chain[3].metadata.metadata[0] = chain[3].metadata.metadata[0]
        md = common.Metadata()
        md.ParseFromString(chain[3].metadata.metadata[
            common.BlockMetadataIndex.SIGNATURES])
        md.signatures[0].signature = b"\x00" * 32       # forged
        chain[3].metadata.metadata[
            common.BlockMetadataIndex.SIGNATURES] = pu.marshal(md)
        n, _after, err = onb.verify_block_span(
            CHANNEL, chain, 0, None, self._bundle())
        assert n == 3
        assert isinstance(err, onb.VerificationError)
        assert err.number == 3

    def test_wrong_signer_rejected(self):
        chain = _make_chain(4, signer=b"intruder")
        n, _after, err = onb.verify_block_span(
            CHANNEL, chain, 0, None, self._bundle())
        assert n == 1          # only the unsigned genesis survives
        assert isinstance(err, onb.VerificationError)

    def test_tampered_previous_hash_rejected(self):
        chain = _make_chain(5)
        chain[2].header.previous_hash = b"\xde\xad" * 16
        n, _after, err = onb.verify_block_span(
            CHANNEL, chain, 0, None, self._bundle())
        assert n == 2
        assert "linkage" in str(err)

    def test_tampered_data_rejected(self):
        chain = _make_chain(5)
        chain[2].data.data[0] = b"rewritten-history"
        n, _after, err = onb.verify_block_span(
            CHANNEL, chain, 0, None, self._bundle())
        assert n == 2
        assert "data hash" in str(err)

    def test_out_of_order_numbering_rejected(self):
        chain = _make_chain(5)
        n, _after, err = onb.verify_block_span(
            CHANNEL, [chain[0], chain[2]], 0, None, self._bundle())
        assert n == 1
        assert "out of order" in str(err)

    def test_config_block_advances_policy(self, monkeypatch):
        csp = _StubCsp()
        monkeypatch.setattr(
            onb, "bundle_from_config_block",
            lambda cid, block, c=csp: _StubBundle(
                c, _signers_from_config_block(block)))
        chain = _make_chain(7, config_at={3: [b"orderer-b"]})
        bundle = _StubBundle(csp, [b"orderer-a"])
        n, after, err = onb.verify_block_span(CHANNEL, chain, 0, None,
                                              bundle)
        assert (n, err) == (7, None)
        # the bundle in force after the span is the config block's
        assert after.policy_manager.get_policy("x").signers == \
            {b"orderer-b"}

    def test_pre_config_signer_invalid_after_config(self, monkeypatch):
        csp = _StubCsp()
        monkeypatch.setattr(
            onb, "bundle_from_config_block",
            lambda cid, block, c=csp: _StubBundle(
                c, _signers_from_config_block(block)))
        # blocks after the config keep being signed by the OLD orderer
        chain = _make_chain(7, config_at={3: [b"orderer-b"]})
        rest = _make_chain(7, config_at={3: [b"orderer-b"]})
        # rebuild blocks 4.. signed by orderer-a against the same
        # headers: forge by re-signing with the retired identity
        for i in (4, 5, 6):
            md = common.Metadata()
            md.ParseFromString(chain[i].metadata.metadata[
                common.BlockMetadataIndex.SIGNATURES])
            md.signatures[0].signature_header = pu.marshal(
                pu.create_signature_header(b"orderer-a", b"n" * 24))
            md.signatures[0].signature = _sign(
                b"orderer-a",
                md.value + md.signatures[0].signature_header +
                pu.block_header_bytes(chain[i].header))
            chain[i].metadata.metadata[
                common.BlockMetadataIndex.SIGNATURES] = pu.marshal(md)
        del rest
        n, _after, err = onb.verify_block_span(
            CHANNEL, chain, 0, None, _StubBundle(csp, [b"orderer-a"]))
        assert n == 4          # up to and including the config block
        assert isinstance(err, onb.VerificationError)


# ---------------------------------------------------------------------------
# ChainReplicator: failover, resume, adversaries
# ---------------------------------------------------------------------------

class TestChainReplicator:
    def _setup(self, n=12, sources=2, provider=None):
        chain = _make_chain(n)
        bundle = _StubBundle(_StubCsp(), [b"orderer-a"])
        srcs = {f"src{i}:1": _Source(chain) for i in range(sources)}
        sink = _ListSink(bundle)
        rep, transport = _replicator(sink, srcs, provider=provider)
        return chain, srcs, sink, rep

    def test_catch_up_to_target(self):
        chain, srcs, sink, rep = self._setup(n=12)
        rep.run(target_height=12, max_wall_s=10)
        assert sink.height() == 12
        assert [b.header.number for b in sink.chain] == list(range(12))
        assert rep.state == "done"

    def test_mid_stream_source_death_fails_over(self):
        """The source serving the catch-up dies after ONE span (20
        blocks at the default batch size): replication fails over to
        the other consenter and resumes from the committed height —
        the verified prefix is never re-pulled.

        Pins the EXACT pull pattern, so ambient chaos arming (which
        injects extra failures and source switches) is cleared."""
        faults.clear()
        chain = _make_chain(30)
        bundle = _StubBundle(_StubCsp(), [b"orderer-a"])
        sink = _ListSink(bundle)
        srcs = {"a:1": _Source(chain), "b:2": _Source(chain)}
        from fabric_tpu.common import metrics as metrics_mod
        provider = metrics_mod.PrometheusProvider()
        rep, _t = _replicator(sink, srcs, provider=provider)

        killed = []

        def dying_serve(src, start, end):
            if src.dead:
                raise ConnectionError("down")
            src.pulls.append((start, end))
            if not killed:          # first span served, then death
                killed.append(src)
                src.dead = True
            return [blk for blk in src.blocks
                    if start <= blk.header.number < end]
        for s in srcs.values():
            s.serve = dying_serve.__get__(s)

        rep.run(target_height=30, max_wall_s=10)
        assert sink.height() == 30
        dead = killed[0]
        survivor = next(s for s in srcs.values() if s is not dead)
        # the dead source served exactly blocks [0, 20); the survivor
        # was first asked from height 20, never for the prefix
        assert dead.pulls[0] == (0, 20)
        assert survivor.pulls[0][0] == 20
        assert all(start >= 20 for start, _ in survivor.pulls)
        text = provider.render()
        assert 'onboarding_source_failovers_total' \
               '{channel="onbchannel"} 1' in text
        assert 'onboarding_blocks_pulled_total' \
               '{channel="onbchannel"} 30' in text

    def test_forged_source_rejected_honest_source_wins(self):
        honest = _make_chain(10)
        forged = _make_chain(10, signer=b"intruder")
        bundle = _StubBundle(_StubCsp(), [b"orderer-a"])
        sink = _ListSink(bundle)
        srcs = {"bad:1": _Source(forged), "good:2": _Source(honest)}
        rep, _t = _replicator(sink, srcs)
        rep.run(target_height=10, max_wall_s=10)
        assert sink.height() == 10
        # every committed block is from the HONEST chain
        for i, blk in enumerate(sink.chain):
            assert pu.block_header_hash(blk.header) == \
                pu.block_header_hash(honest[i].header)

    def test_truncated_source_fails_over(self):
        chain = _make_chain(20)
        bundle = _StubBundle(_StubCsp(), [b"orderer-a"])
        sink = _ListSink(bundle)
        srcs = {"stale:1": _Source(chain[:5]),   # truncated history
                "full:2": _Source(chain)}
        rep, _t = _replicator(sink, srcs)
        rep.run(target_height=20, max_wall_s=10)
        assert sink.height() == 20

    def test_all_sources_down_raises_then_resumes(self):
        chain, srcs, sink, rep = self._setup(n=10)
        for s in srcs.values():
            s.dead = True
        with pytest.raises(onb.OnboardingError):
            rep.run(target_height=10, max_wall_s=0.5)
        assert sink.height() == 0
        for s in srcs.values():
            s.dead = False
        rep.run(target_height=10, max_wall_s=10)
        assert sink.height() == 10

    def test_halt_event_aborts_run(self):
        chain, srcs, sink, rep = self._setup(n=10)
        for s in srcs.values():
            s.dead = True
        stop = threading.Event()
        timer = threading.Timer(0.2, stop.set)
        timer.start()
        with pytest.raises(onb.OnboardingError, match="halted"):
            rep.run(target_height=10, stop=stop, max_wall_s=30)
        timer.cancel()

    def test_state_gauge_reaches_done(self):
        from fabric_tpu.common import metrics as metrics_mod
        provider = metrics_mod.PrometheusProvider()
        chain, srcs, sink, rep = self._setup(n=4, provider=provider)
        rep.run(target_height=4, max_wall_s=10)
        text = provider.render()
        assert 'onboarding_state{channel="onbchannel",state="done"} 1'\
            in text

    def test_tracking_mode_tip_quiescence_is_healthy(self):
        faults.clear()     # pins exclusion state: no ambient arming
        chain, srcs, sink, rep = self._setup(n=5)
        rep.run(target_height=5, max_wall_s=10)
        # at the tip: polls return nothing, nobody gets excluded
        for _ in range(6):
            assert rep.poll_once() == 0
        assert all(rep.selector.admitted(ep) for ep in srcs)
        # new blocks appear: tracking picks them up
        more = _make_chain(8)
        for s in srcs.values():
            s.blocks = more
        picked = 0
        for _ in range(4):
            picked += rep.poll_once()
        assert sink.height() == 8, (picked, sink.height())


# ---------------------------------------------------------------------------
# chaos: the new fault points
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestOnboardingChaos:
    def _setup(self, n=10):
        chain = _make_chain(n)
        bundle = _StubBundle(_StubCsp(), [b"orderer-a"])
        srcs = {"a:1": _Source(chain), "b:2": _Source(chain)}
        sink = _ListSink(bundle)
        rep, _t = _replicator(sink, srcs)
        return sink, rep

    def test_pull_faults_are_survived(self):
        faults.arm("cluster.pull", mode="error", count=3)
        sink, rep = self._setup()
        rep.run(target_height=10, max_wall_s=15)
        assert sink.height() == 10
        assert faults.fires("cluster.pull") == 3

    def test_verify_faults_counted_and_survived(self):
        from fabric_tpu.common import metrics as metrics_mod
        provider = metrics_mod.PrometheusProvider()
        faults.arm("cluster.verify", mode="error", count=2)
        chain = _make_chain(10)
        bundle = _StubBundle(_StubCsp(), [b"orderer-a"])
        sink = _ListSink(bundle)
        rep, _t = _replicator(sink, {"a:1": _Source(chain)},
                              provider=provider)
        rep.run(target_height=10, max_wall_s=15)
        assert sink.height() == 10
        assert 'onboarding_verify_failures_total' \
               '{channel="onbchannel"} 2' in provider.render()

    def test_commit_faults_keep_durable_prefix(self):
        faults.arm("onboarding.commit", mode="error", count=1)
        sink, rep = self._setup()
        rep.run(target_height=10, max_wall_s=15)
        assert sink.height() == 10
        assert [b.header.number for b in sink.chain] == list(range(10))

    def test_commit_delay_fault_just_slows(self):
        faults.arm("onboarding.commit", mode="delay", count=2,
                   delay_s=0.02)
        sink, rep = self._setup()
        rep.run(target_height=10, max_wall_s=15)
        assert sink.height() == 10


# ---------------------------------------------------------------------------
# BootstrapSink: anchoring + crash-resume through the real block store
# ---------------------------------------------------------------------------

class TestBootstrapSink:
    @pytest.fixture()
    def stub_bundles(self, monkeypatch):
        csp = _StubCsp()

        def stub_bundle(cid, block, _real_csp=None):
            if pu.is_config_block(block) and b"signers:" in \
                    pu.get_payload(pu.extract_envelope(block, 0)).data:
                signers = _signers_from_config_block(block)
            else:
                signers = [b"orderer-a"]
            return _StubBundle(csp, signers)

        monkeypatch.setattr(onb, "bundle_from_config_block",
                            stub_bundle)
        return csp

    def _ledger(self, tmp_path, name="lg"):
        from fabric_tpu.orderer.multichannel import OrdererLedger
        return OrdererLedger(str(tmp_path / name))

    def test_anchor_mismatch_rejects_forked_chain(self, tmp_path,
                                                  stub_bundles):
        honest = _make_chain(8, config_at={6: [b"orderer-a"]})
        fork = _make_chain(8, config_at={6: [b"orderer-a"]})
        # the fork diverges at genesis (different payloads) but is
        # internally consistent and signed by a VALID orderer identity
        fork[0].data.data[0] = b"other-universe"
        fork[0].header.data_hash = pu.block_data_hash(fork[0].data)
        prev = pu.block_header_hash(fork[0].header)
        for blk in fork[1:]:
            blk.header.previous_hash = prev
            md = common.Metadata()
            md.ParseFromString(blk.metadata.metadata[
                common.BlockMetadataIndex.SIGNATURES])
            md.signatures[0].signature = _sign(
                b"orderer-a",
                md.value + md.signatures[0].signature_header +
                pu.block_header_bytes(blk.header))
            blk.metadata.metadata[
                common.BlockMetadataIndex.SIGNATURES] = pu.marshal(md)
            prev = pu.block_header_hash(blk.header)
        join_block = honest[6]                 # trusted config block
        ledger = self._ledger(tmp_path)
        sink = onb.BootstrapSink(CHANNEL, ledger, join_block, None)
        n, err = sink.verify(fork[:8])
        assert isinstance(err, onb.ChainAnchorError)
        # the WHOLE span is rejected: nothing from a chain that fails
        # to anchor may be committed
        assert n == 0
        ledger.close()

    def test_forged_chain_with_own_config_rejected_at_attestation(
            self, tmp_path, stub_bundles):
        """The sharpest adversary: a source serving a fully
        self-consistent forged chain whose OWN embedded genesis config
        names the forger's identity — per-span verification alone
        would accept it (configs are re-derived from the pulled chain,
        reference semantics). Source attestation against the trusted
        join block rejects the source at first contact: NOTHING is
        committed, and replication completes from the honest source.
        """
        honest = _make_chain(9, config_at={0: [b"orderer-a"],
                                           7: [b"orderer-a"]})
        forged = _make_chain(9, config_at={0: [b"intruder"],
                                           7: [b"intruder"]},
                             signer=b"intruder")
        join_block = honest[7]
        srcs = {"evil:1": _Source(forged), "good:2": _Source(honest)}
        ledger = self._ledger(tmp_path)
        sink = onb.BootstrapSink(CHANNEL, ledger, join_block, None)
        rep, _t = _replicator(sink, srcs, batch=3)
        rep.run(target_height=8, max_wall_s=10)
        assert ledger.height >= 8
        # every committed block is the HONEST one, and the forger
        # never served a span (only, at most, the attestation probe)
        for i in range(8):
            assert pu.block_header_hash(
                ledger.get_block(i).header) == \
                pu.block_header_hash(honest[i].header)
        assert all(end - start == 1
                   for start, end in srcs["evil:1"].pulls)
        ledger.close()

        # with ONLY forged sources available, nothing ever commits
        ledger2 = self._ledger(tmp_path, "lg2")
        sink2 = onb.BootstrapSink(CHANNEL, ledger2, join_block, None)
        rep2, _t2 = _replicator(sink2, {"evil:1": _Source(forged)},
                                batch=3)
        with pytest.raises(onb.OnboardingError):
            rep2.run(target_height=8, max_wall_s=0.8)
        assert ledger2.height == 0
        ledger2.close()

    def test_discovery_ignores_historical_configs(self, tmp_path,
                                                  monkeypatch):
        """Verification follows the chain's historical configs, but
        source DISCOVERY must not: a config block from the channel's
        past lists since-retired endpoints, and adopting it for source
        selection would point replication at dead addresses. Only
        configs PAST the join height may move the discovery set."""
        csp = _StubCsp()
        bundles = {}

        def stub_bundle(cid, block, _real_csp=None):
            b = _StubBundle(csp, [b"orderer-a"])
            bundles[block.header.number] = b
            return b
        monkeypatch.setattr(onb, "bundle_from_config_block",
                            stub_bundle)
        chain = _make_chain(10, config_at={2: [b"orderer-a"],
                                           7: [b"orderer-a"],
                                           9: [b"orderer-a"]})
        join_block = chain[7]
        from fabric_tpu.orderer.multichannel import OrdererLedger
        ledger = OrdererLedger(str(tmp_path / "disc"))
        sink = onb.BootstrapSink(CHANNEL, ledger, join_block, None)
        join_bundle = sink.bundle
        # a HISTORICAL config (height 2 < join height 7) commits:
        # verification adopts it, discovery must not budge
        sink.commit(chain[0])
        sink.commit(chain[1])
        sink.commit(chain[2])
        assert sink.bundle is join_bundle
        assert sink._bundle is bundles[2]
        # a config PAST the join height moves both
        for b in chain[3:10]:
            sink.commit(b)
        assert sink.bundle is bundles[9]
        assert sink._bundle is bundles[9]
        ledger.close()

    def test_adaptive_equivocator_commits_nothing(self, tmp_path,
                                                  stub_bundles):
        """Sharper still: a source that answers the attestation probe
        AND the backward anchor walk honestly, then serves forged
        blocks on the forward span pulls. The pins derived by the walk
        make every forward sub-anchor block hash-checkable, so the
        forged spans are rejected whole — the equivocator commits
        NOTHING (before the backward binding it could durably wedge
        the node with a forged prefix)."""
        honest = _make_chain(9, config_at={0: [b"orderer-a"],
                                           7: [b"orderer-a"]})
        forged = _make_chain(9, config_at={0: [b"intruder"],
                                           7: [b"intruder"]},
                             signer=b"intruder")
        join_block = honest[7]

        class _TwoFace(_Source):
            def __init__(self, honest_blocks, forged_blocks, n_honest):
                super().__init__(forged_blocks)
                self._honest = honest_blocks
                self._n_honest = n_honest
                self._served = 0

            def serve(self, start, end):
                if self.dead:
                    raise ConnectionError("down")
                self.pulls.append((start, end))
                use = self._honest if self._served < self._n_honest \
                    else self.blocks
                self._served += 1
                return [b for b in use
                        if start <= b.header.number < end]

        # honest for the probe + the single walk chunk, forged after
        two_face = _TwoFace(honest, forged, n_honest=2)
        ledger = self._ledger(tmp_path)
        sink = onb.BootstrapSink(CHANNEL, ledger, join_block, None)
        rep, _t = _replicator(sink, {"evil:1": two_face}, batch=3)
        with pytest.raises(onb.OnboardingError):
            rep.run(target_height=8, max_wall_s=0.8)
        assert ledger.height == 0
        ledger.close()

    def test_bootstrap_with_failover_and_resume(self, tmp_path,
                                                stub_bundles):
        chain = _make_chain(9, config_at={7: [b"orderer-a"]})
        join_block = chain[7]
        srcs = {"a:1": _Source(chain), "b:2": _Source(chain)}
        ledger = self._ledger(tmp_path)
        sink = onb.BootstrapSink(CHANNEL, ledger, join_block, None)
        rep, _t = _replicator(sink, srcs, batch=3)
        # phase 1: a:1 passes attestation, serves the first span
        # (blocks 0-2, re-served as needed if ambient chaos faults the
        # commits), then dies as soon as progress past height 0 is
        # requested; b:2 is down the whole time
        orig_serve = _Source.serve

        def die_after_first_span(src, start, end):
            if end - start > 1 and start > 0:
                src.dead = True
                raise ConnectionError("died mid-stream")
            return orig_serve(src, start, end)
        srcs["a:1"].serve = die_after_first_span.__get__(srcs["a:1"])
        srcs["b:2"].dead = True
        with pytest.raises(onb.OnboardingError):
            rep.run(target_height=8, max_wall_s=1.0)
        committed_phase1 = ledger.height
        assert 0 < committed_phase1 <= 4
        ledger.close()

        # phase 2: "process restart" — fresh sink over the reopened
        # ledger resumes from the durable height; only the live source
        # remains and must never be asked for the verified prefix
        ledger2 = self._ledger(tmp_path)
        assert ledger2.height == committed_phase1
        srcs["b:2"].dead = False
        sink2 = onb.BootstrapSink(CHANNEL, ledger2, join_block, None)
        rep2, _t2 = _replicator(sink2, {"b:2": srcs["b:2"]})
        rep2.run(target_height=8, max_wall_s=10)
        # whole verified spans commit, so the tip may pass the target
        assert ledger2.height >= 8
        assert all(start >= committed_phase1
                   for start, _end in srcs["b:2"].pulls)
        for i in range(8):
            got = ledger2.get_block(i)
            assert pu.block_header_hash(got.header) == \
                pu.block_header_hash(chain[i].header)
        ledger2.close()


# ---------------------------------------------------------------------------
# FollowerChain promotion trigger (stub support)
# ---------------------------------------------------------------------------

class TestFollowerPromotion:
    def test_follower_promotes_when_config_adds_it(self):
        from fabric_tpu.orderer.raft.follower import FollowerChain
        chain = _make_chain(5)
        csp = _StubCsp()
        state = {"bundle": _StubBundle(csp, [b"orderer-a"],
                                       consenters=["a:1"])}
        sink_chain = []

        def verify_span(blocks):
            n, _bundle, err = onb.verify_block_span(
                CHANNEL, blocks, len(sink_chain),
                pu.block_header_hash(sink_chain[-1].header)
                if sink_chain else None, state["bundle"])
            return n, err

        class _Ledger:
            @property
            def height(self):
                return len(sink_chain)

            def get_block(self, num):
                return sink_chain[num]

        support = SimpleNamespace(
            channel_id=CHANNEL,
            ledger=_Ledger(),
            bundle=lambda: state["bundle"],
            verify_onboarded_span=verify_span,
            commit_onboarded_block=lambda b: sink_chain.append(b),
        )

        src = _Source(chain)
        transport = _FakeTransport({"a:1": src})
        transport.endpoint = "me:9"
        promoted = threading.Event()
        fc = FollowerChain(support, transport, poll_interval_s=0.01,
                           on_became_consenter=promoted.set)
        fc.start()
        try:
            deadline = time.monotonic() + 10
            while len(sink_chain) < 5 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert len(sink_chain) == 5
            assert not promoted.is_set()
            # a config update adds this orderer to the consenter set
            state["bundle"] = _StubBundle(csp, [b"orderer-a"],
                                          consenters=["a:1", "me:9"])
            assert promoted.wait(10), "follower did not promote"
        finally:
            fc.halt()
