"""Elastic device-mesh fault tolerance (ISSUE 11 tentpole).

The contract under test: ONE chip failing or stalling mid-dispatch
must cost that one chip, never the fleet. A device-attributed fault
(`tpu.device_lost` armed against chip k, or a runtime error naming a
device) quarantines exactly that chip through its per-device breaker
(common/devicehealth.py), the provider rebuilds a smaller mesh over
the survivors and KEEPS dispatching on it — (N-1)/N device throughput
instead of the fleet-wide sw degrade — while every accept/reject
bitmap stays bit-identical to the sw oracle. After the cooldown a
bounded single-chip probe re-admits a recovered chip and the mesh
grows back. Stragglers (`tpu.device_straggler` delay faults inflating
one chip's transfer stream) quarantine through consecutive-strike
accounting fed by the `bccsp_shard_*` readings.

Device math uses the recorder-stub idiom (tests/test_shard_verify.py):
real staging, mesh placement, span feeding, fault points, per-device
breakers and mesh rebuilds — the jitted kernel is replaced by a
premask recorder so host pre-validation IS the verdict. The
slow-marked test at the bottom drives the same loss/rebuild scenario
through the real compiled q8 comb kernel.
"""

import hashlib
import logging
import time

import jax
import numpy as np
import pytest

from fabric_tpu.bccsp import ECDSAKeyGenOpts, VerifyItem, factory, utils
from fabric_tpu.bccsp.sw import SWProvider
from fabric_tpu.bccsp.tpu import TPUProvider
from fabric_tpu.common import devicehealth, faults
from fabric_tpu.common.devicehealth import (
    DeviceHealth,
    DeviceHealthConfig,
    DeviceLostError,
)
from fabric_tpu.parallel import batch_mesh
from tests.test_chaos import _StepClock

pytestmark = pytest.mark.chaos

_SW = SWProvider()
_KEYS = [_SW.key_gen(ECDSAKeyGenOpts(ephemeral=True)) for _ in range(2)]

SPAN8 = 1024     # aligned_span granule for an 8-way mesh


_POOL: list = []


def _corpus(n):
    """Mixed valid/invalid lanes tiled from a 24-lane signed pool
    (pure-python signing is ~10ms/lane — per-lane signing made this
    module dominate tier-1): verdicts are decided by host
    pre-validation, so tiling loses no coverage."""
    if not _POOL:
        for i in range(24):
            k = _KEYS[i % 2]
            m = f"devhealth {i}".encode()
            sig = _SW.sign(k, hashlib.sha256(m).digest())
            if i % 3 == 2:
                r, s = utils.unmarshal_signature(sig)
                sig = (sig[:-2] if i % 2 else
                       utils.marshal_signature(r, utils.P256_N - s))
                _POOL.append((VerifyItem(key=k.public_key(),
                                         signature=sig, message=m),
                              False))
            else:
                _POOL.append((VerifyItem(key=k.public_key(),
                                         signature=sig, message=m),
                              True))
    items = [_POOL[i % len(_POOL)][0] for i in range(n)]
    expected = [_POOL[i % len(_POOL)][1] for i in range(n)]
    return items, expected


def _stubbed_provider(mesh=None, dh_config=None, **kw):
    kw.setdefault("min_batch", 1)
    kw.setdefault("use_g16", False)
    kw.setdefault("pipeline_chunk", SPAN8)
    tpu = TPUProvider(mesh=mesh, device_health=dh_config, **kw)
    calls = {"premask": [], "dispatches": 0}

    def fake_qtab_fn(K):
        return lambda qx, qy: np.zeros((K,), dtype=np.int32)

    def fake_pipeline_digest(K, q16=False, donate=False):
        def run(key_idx, q_flat, g16, r8, rpn8, w8, premask, digests):
            calls["premask"].append(np.asarray(premask).copy())
            calls["dispatches"] += 1
            return np.asarray(premask)
        return run

    def fake_ladder():
        def run(blocks, nblocks, qx, qy, r, rpn, w, premask, digests,
                has_digest):
            calls["dispatches"] += 1
            return np.asarray(premask)
        return run

    tpu._qtab_fn = fake_qtab_fn
    tpu._comb_pipeline_digest = fake_pipeline_digest
    tpu._pipeline = fake_ladder
    return tpu, calls


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh from conftest")
    return batch_mesh(8)


def _wait_for(cond, timeout=10.0, what="condition"):
    """Poll for an async outcome (re-admission probes run on daemon
    threads off the hot path — admission never blocks on them)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# fault-point arg targeting (the chaos seam the device points ride)
# ---------------------------------------------------------------------------

class TestFaultArgTargeting:
    def test_armed_arg_fires_only_on_matching_check(self):
        faults.clear()
        faults.arm("tpu.device_lost", mode="error", count=None, arg=3)
        try:
            faults.check("tpu.device_lost", arg=1)   # no fire
            faults.check("tpu.device_lost")          # arg-less: no fire
            assert faults.fires("tpu.device_lost") == 0
            with pytest.raises(faults.FaultInjected):
                faults.check("tpu.device_lost", arg=3)
            assert faults.fires("tpu.device_lost") == 1
        finally:
            faults.clear()

    def test_env_grammar_fourth_field_targets_device(self):
        faults.clear()
        try:
            faults.arm_from_env("tpu.device_lost=error:1::5")
            faults.check("tpu.device_lost", arg=4)
            with pytest.raises(faults.FaultInjected):
                faults.check("tpu.device_lost", arg=5)
            # count=1 consumed
            faults.check("tpu.device_lost", arg=5)
        finally:
            faults.clear()

    def test_argless_arming_fires_for_any_device(self):
        faults.clear()
        faults.arm("tpu.device_lost", mode="error", count=2)
        try:
            with pytest.raises(faults.FaultInjected):
                faults.check("tpu.device_lost", arg=0)
            with pytest.raises(faults.FaultInjected):
                faults.check("tpu.device_lost", arg=7)
        finally:
            faults.clear()

    def test_new_points_in_known_registry(self):
        assert "tpu.device_lost" in faults.KNOWN_POINTS
        assert "tpu.device_straggler" in faults.KNOWN_POINTS


# ---------------------------------------------------------------------------
# the quarantine ring (unit)
# ---------------------------------------------------------------------------

class TestDeviceHealthRing:
    def test_fault_quarantines_then_probe_readmits(self):
        clk = _StepClock()
        dh = DeviceHealth(8, DeviceHealthConfig(cooldown_s=5.0),
                          clock=clk)
        assert dh.healthy() == list(range(8))
        assert dh.record_fault(3, RuntimeError("boom")) is True
        assert dh.healthy() == [0, 1, 2, 4, 5, 6, 7]
        assert dh.totals()["device_quarantines"] == 1
        # cooldown not elapsed: no probe slot offered
        assert dh.probe_candidates() == []
        clk.advance(5.1)
        assert dh.probe_candidates() == [3]
        # the slot is single-admission until the outcome reports
        assert dh.probe_candidates() == []
        dh.probe_result(3, True)
        assert dh.healthy() == list(range(8))
        assert dh.totals()["device_readmits"] == 1

    def test_failed_probe_reopens_cooldown(self):
        clk = _StepClock()
        dh = DeviceHealth(4, DeviceHealthConfig(cooldown_s=2.0),
                          clock=clk)
        dh.record_fault(1, RuntimeError("x"))
        clk.advance(2.1)
        assert dh.probe_candidates() == [1]
        dh.probe_result(1, False)
        assert dh.healthy() == [0, 2, 3]
        assert dh.probe_candidates() == []       # cooling down again
        clk.advance(2.1)
        assert dh.probe_candidates() == [1]
        dh.probe_result(1, True)
        assert dh.healthy() == [0, 1, 2, 3]

    def test_stale_reclaimed_probe_success_is_not_a_readmit(self):
        """A probe slower than the breaker's stale-probe reclaim
        window (max(cooldown_s, 1s)): a state poll reclaims the slot
        and re-opens the breaker; the probe's late success must NOT
        count a readmit — the chip never rejoined the mesh. Held
        under probe_execution() the same slow probe is NOT
        reclaimable and its success re-admits for real."""
        clk = _StepClock()
        dh = DeviceHealth(4, DeviceHealthConfig(cooldown_s=0.5),
                          clock=clk)
        dh.record_fault(2, RuntimeError("x"))
        clk.advance(0.6)
        assert dh.probe_candidates() == [2]
        # probe runs WITHOUT the execution marker and outlives the
        # reclaim window (max(0.5, 1.0) = 1.0s): a state poll
        # reclaims the slot
        clk.advance(1.1)
        assert dh.healthy() == [0, 1, 3]     # reclaim fired
        dh.probe_result(2, True)             # late success
        assert dh.totals()["device_readmits"] == 0
        assert 2 in dh.quarantined()
        # next round, probe held LIVE via probe_execution: the same
        # slow probe is not reclaimed and its success re-admits
        clk.advance(0.6)
        assert dh.probe_candidates() == [2]
        with dh.probe_execution(2):
            clk.advance(1.1)
            assert 2 not in dh.healthy()     # still just probing
            dh.probe_result(2, True)
        assert dh.totals()["device_readmits"] == 1
        assert dh.healthy() == [0, 1, 2, 3]

    def test_straggler_strikes_consecutive_then_reset(self):
        dh = DeviceHealth(4, DeviceHealthConfig(
            straggler_skew_s=0.1, straggler_strikes=3))
        idx = [0, 1, 2, 3]
        slow = [0.0, 0.0, 0.5, 0.0]      # device 2 over budget
        clean = [0.0] * 4
        assert dh.observe_shard(idx, slow, []) == []
        assert dh.observe_shard(idx, slow, []) == []
        # a clean batch resets the consecutive count
        assert dh.observe_shard(idx, clean, []) == []
        assert dh.observe_shard(idx, slow, []) == []
        assert dh.observe_shard(idx, slow, []) == []
        assert dh.observe_shard(idx, slow, []) == [2]
        assert dh.healthy() == [0, 1, 3]
        assert dh.totals()["device_quarantines"] == 1
        assert dh.totals()["device_straggler_strikes"] == 5

    def test_ready_lag_jump_localizes_straggler(self):
        """ready_s is sampled in mesh order (cumulative upper bound):
        a straggler at chip k steps the curve AT k — the jump, not
        the absolute value, attributes the strike."""
        dh = DeviceHealth(4, DeviceHealthConfig(
            straggler_skew_s=0.1, straggler_strikes=1))
        ready = [0.01, 0.02, 0.5, 0.5]   # the step is at device 2
        assert dh.observe_shard([0, 1, 2, 3], [], ready) == [2]
        assert dh.healthy() == [0, 1, 3]

    def test_correlated_stragglers_both_quarantine(self):
        """Two chips on one degrading link cross the strike budget in
        the SAME batch: both quarantine — neither escapes with its
        strikes silently reset."""
        dh = DeviceHealth(4, DeviceHealthConfig(
            straggler_skew_s=0.1, straggler_strikes=2))
        idx = [0, 1, 2, 3]
        slow2 = [0.0, 0.5, 0.0, 0.5]     # devices 1 and 3 over budget
        assert dh.observe_shard(idx, slow2, []) == []
        assert sorted(dh.observe_shard(idx, slow2, [])) == [1, 3]
        assert dh.healthy() == [0, 2]
        assert dh.totals()["device_quarantines"] == 2

    def test_skew_zero_disables_straggler_quarantine(self):
        dh = DeviceHealth(4, DeviceHealthConfig(
            straggler_skew_s=0.0, straggler_strikes=1))
        assert dh.observe_shard([0, 1, 2, 3],
                                [0.0, 9.0, 0.0, 0.0], []) == []
        assert dh.healthy() == [0, 1, 2, 3]

    def test_reattributed_fault_never_extends_cooldown(self):
        """Stale dispatches keep naming an already-benched chip (the
        total-loss shape): the extra faults must NOT re-arm its
        cooldown, or the chip never reaches its re-admission probe."""
        clk = _StepClock()
        dh = DeviceHealth(4, DeviceHealthConfig(cooldown_s=3.0),
                          clock=clk)
        dh.record_fault(1, RuntimeError("x"))
        clk.advance(2.9)
        # re-attribution just before cooldown expiry: ignored
        assert dh.record_fault(1, RuntimeError("again")) is False
        assert dh.attribute(RuntimeError("device 1 still dead")) == 1
        clk.advance(0.2)
        assert dh.probe_candidates() == [1]

    def test_attribute_parses_device_naming_errors(self):
        dh = DeviceHealth(8, DeviceHealthConfig())
        assert dh.attribute(RuntimeError("transfer to device 6 "
                                         "failed")) == 6
        assert dh.attribute(DeviceLostError(2, RuntimeError("x"))) == 2
        assert dh.attribute(RuntimeError("shape mismatch")) is None
        assert dh.attribute(RuntimeError("device 99 gone")) is None
        assert sorted(dh.quarantined()) == [2, 6]


# ---------------------------------------------------------------------------
# elastic mesh through the provider (recorder stubs, mesh8)
# ---------------------------------------------------------------------------

class TestElasticMeshDeviceLoss:
    def test_mid_dispatch_loss_shrinks_then_probe_regrows(self, mesh8):
        """The acceptance scenario at test scale: tpu.device_lost
        armed against chip 3 mid-run — the faulted batch serves sw
        BIT-IDENTICALLY, chip 3 is quarantined (never the whole
        breaker), the next batches dispatch on a 7-device mesh, and
        after the cooldown the re-admission probe restores all 8."""
        faults.clear()
        clk = _StepClock()
        tpu, calls = _stubbed_provider(
            mesh=mesh8,
            dh_config=DeviceHealthConfig(cooldown_s=30.0))
        tpu._devhealth.set_clock(clk)
        items, expected = _corpus(2048)
        oracle = _SW.verify_batch(items)
        assert expected == oracle

        faults.arm("tpu.device_lost", mode="error", count=1, arg=3)
        # batch 1: chip 3 dies mid-span-feed -> sw fallback, parity
        assert tpu.verify_batch(items) == oracle
        assert tpu.stats["sw_fallbacks"] == 1
        assert tpu.stats["device_quarantines"] == 1
        assert tpu._breaker.state == "device"      # fleet NOT benched
        assert tpu.stats["breaker_trips"] == 0
        assert tpu._mesh.size == 7
        assert tpu.stats["shard_devices"] == 7
        assert tpu.stats["mesh_devices_full"] == 8
        assert "degraded_mesh:7/8" in tpu.health()
        assert tpu.device_stats["state"][3] == 2   # quarantined
        # batches 2..4: DISPATCHED on the 7-device mesh (never full
        # sw while healthy chips remain)
        for _ in range(3):
            assert tpu.verify_batch(items) == oracle
        assert tpu.stats["pipeline_batches"] == 3
        assert tpu.stats["sw_fallbacks"] == 1      # no new fallbacks
        assert len(tpu.shard_stats["transfer_s"]) == 7
        # cooldown elapses -> the next admission KICKS chip 3's probe
        # (async — a wedged chip must never stall a batch); the fault
        # budget is exhausted so it succeeds, and a later admission
        # grows the mesh back
        clk.advance(30.1)
        assert tpu.verify_batch(items) == oracle
        _wait_for(lambda: tpu.stats["device_readmits"] == 1,
                  what="probe re-admission")
        assert tpu.verify_batch(items) == oracle
        assert tpu._mesh.size == 8
        assert tpu.health() == "device"
        assert tpu.device_stats["readmits"][3] == 1

    def test_probe_fails_while_fault_still_armed(self, mesh8):
        """An unlimited device_lost arming keeps the chip benched:
        every probe fails through the SAME fault point, the mesh
        stays at 7, and disarming finally re-admits."""
        faults.clear()
        clk = _StepClock()
        tpu, _ = _stubbed_provider(
            mesh=mesh8,
            dh_config=DeviceHealthConfig(cooldown_s=10.0,
                                         probe_timeout_s=2.0))
        tpu._devhealth.set_clock(clk)
        items, expected = _corpus(SPAN8 + 8)
        faults.arm("tpu.device_lost", mode="error", count=None, arg=5)
        assert tpu.verify_batch(items) == expected
        assert tpu._mesh.size == 7
        clk.advance(10.1)
        assert tpu.verify_batch(items) == expected   # kicks the probe
        # the async probe fails through the armed point: the chip
        # drops back to quarantined (state 2) and the mesh stays at 7
        _wait_for(lambda: tpu.device_stats["state"][5] == 2,
                  what="failed probe re-opening quarantine")
        assert tpu.verify_batch(items) == expected
        assert tpu._mesh.size == 7
        assert tpu.stats["device_readmits"] == 0
        faults.clear()
        clk.advance(10.1)
        assert tpu.verify_batch(items) == expected   # kicks the probe
        _wait_for(lambda: tpu.stats["device_readmits"] == 1,
                  what="probe re-admission after disarm")
        assert tpu.verify_batch(items) == expected
        assert tpu._mesh.size == 8

    def test_ten_k_lane_stream_bit_identical_across_loss(self, mesh8):
        """10k lanes streamed in batches with the chip loss landing
        mid-stream: every bitmap bit-identical to the sw oracle, and
        the provider never serves a full-sw batch after the rebuild."""
        faults.clear()
        tpu, _ = _stubbed_provider(
            mesh=mesh8, dh_config=DeviceHealthConfig(cooldown_s=300.0))
        items, expected = _corpus(10_000)
        oracle = _SW.verify_batch(items)
        assert expected == oracle
        batches = [(i, min(i + 2500, 10_000))
                   for i in range(0, 10_000, 2500)]
        out: list = []
        for bi, (lo, hi) in enumerate(batches):
            if bi == 1:     # the loss lands mid-stream
                faults.arm("tpu.device_lost", mode="error", count=1,
                           arg=6)
            out.extend(tpu.verify_batch(items[lo:hi]))
        assert out == oracle
        assert tpu.stats["device_quarantines"] == 1
        assert tpu._mesh.size == 7
        # exactly ONE batch fell back (the one that lost the chip);
        # everything after dispatched on the surviving mesh
        assert tpu.stats["sw_fallbacks"] == 1
        assert tpu.stats["pipeline_batches"] == len(batches) - 1
        assert tpu._breaker.state == "device"

    def test_whole_batch_digest_path_loses_chip_too(self, mesh8):
        """pipeline_chunk=0 (overlap off): the whole-batch sharded
        staging rides the same per-device fault seam and elastic
        rebuild."""
        faults.clear()
        tpu, _ = _stubbed_provider(
            mesh=mesh8, pipeline_chunk=0,
            dh_config=DeviceHealthConfig(cooldown_s=300.0))
        items, expected = _corpus(640)
        faults.arm("tpu.device_lost", mode="error", count=1, arg=0)
        assert tpu.verify_batch(items) == expected
        assert tpu._mesh.size == 7
        assert tpu.verify_batch(items) == expected
        assert tpu.stats["sw_fallbacks"] == 1

    def test_cached_tables_rehosted_on_rebuild(self, mesh8):
        """_resolve_tables stores REPLICATED table copies back into
        the caches; after a mesh swap those old-mesh handles hold a
        replica on the benched chip (poisoned on real hardware). The
        rebuild re-materializes them on the host from a kept replica
        so the next dispatch re-replicates clean bytes."""
        faults.clear()
        tpu, _ = _stubbed_provider(
            mesh=mesh8, dh_config=DeviceHealthConfig(cooldown_s=300.0))
        items, expected = _corpus(2048)
        assert tpu.verify_batch(items) == expected
        cached = next(iter(tpu._q8_cache.values()))
        shards = getattr(cached, "addressable_shards", None)
        assert shards is not None and len(shards) == 8
        faults.arm("tpu.device_lost", mode="error", count=1, arg=1)
        assert tpu.verify_batch(items) == expected   # loss + rebuild
        assert tpu._mesh.size == 7
        cached = next(iter(tpu._q8_cache.values()))
        assert getattr(cached, "addressable_shards", None) is None, \
            "old-mesh replicated handle survived the rebuild"
        # the host copy re-replicates on the next dispatch
        assert tpu.verify_batch(items) == expected
        assert tpu.stats["pipeline_batches"] == 2

    def test_runtime_error_naming_a_device_attributes(self, mesh8):
        """A dispatch failure whose message names a chip (the real
        XLA/PJRT error shape) quarantines that chip even without the
        DeviceLostError wrapper."""
        faults.clear()
        tpu, calls = _stubbed_provider(
            mesh=mesh8, dh_config=DeviceHealthConfig(cooldown_s=300.0))

        real = tpu._comb_pipeline_digest
        state = {"failed": False}

        def failing_pipeline(K, q16=False, donate=False):
            inner = real(K, q16, donate)

            def run(*a):
                if not state["failed"]:
                    state["failed"] = True
                    raise RuntimeError(
                        "XLA:TPU compile permanent error on device 4:"
                        " core halted")
                return inner(*a)
            return run

        tpu._comb_pipeline_digest = failing_pipeline
        items, expected = _corpus(2048)
        assert tpu.verify_batch(items) == expected   # sw fallback
        assert tpu.stats["device_quarantines"] == 1
        assert tpu.device_stats["state"][4] == 2
        assert tpu._mesh.size == 7
        assert tpu.verify_batch(items) == expected   # 7-dev dispatch
        assert tpu.stats["pipeline_batches"] == 1

    def test_total_loss_serves_sw_until_a_probe_recovers(self, mesh8):
        """Every chip quarantined: batches serve sw OUTRIGHT (no
        doomed device dispatch paying transfer latency per batch —
        the provider breaker ignores device-attributed errors, so it
        could never degrade on its own), verdicts stay bit-identical,
        and recovered probes rebuild the mesh."""
        faults.clear()
        clk = _StepClock()
        tpu, calls = _stubbed_provider(
            mesh=mesh8, dh_config=DeviceHealthConfig(cooldown_s=5.0))
        tpu._devhealth.set_clock(clk)
        items, expected = _corpus(SPAN8 + 4)
        for d in range(8):
            tpu._devhealth.record_fault(d, RuntimeError("gone"))
        assert tpu._devhealth.healthy() == []
        assert tpu.verify_batch(items) == expected
        assert calls["dispatches"] == 0          # no doomed dispatch
        assert tpu.stats["degraded_batches"] == 1
        assert tpu.stats["sw_fallbacks"] == 0
        clk.advance(5.1)
        assert tpu.verify_batch(items) == expected  # kicks all probes
        _wait_for(lambda: tpu.stats["device_readmits"] == 8,
                  what="all 8 probes re-admitting")
        assert tpu.verify_batch(items) == expected
        # full mesh back, dispatching again
        assert tpu._mesh.size == 8
        assert calls["dispatches"] >= 1


class TestStragglerQuarantine:
    def test_straggler_delay_fault_trips_after_strikes(self, mesh8):
        """tpu.device_straggler (delay mode) inflates chip 2's
        per-device transfer stream; after StragglerStrikes struck
        batches the chip is quarantined and the mesh rebuilds —
        verdicts bit-identical throughout (the straggler only ever
        cost latency)."""
        faults.clear()
        tpu, _ = _stubbed_provider(
            mesh=mesh8,
            dh_config=DeviceHealthConfig(
                cooldown_s=300.0, straggler_skew_s=0.02,
                straggler_strikes=2))
        items, expected = _corpus(2048)
        faults.arm("tpu.device_straggler", mode="delay",
                   delay_s=0.01, arg=2)
        assert tpu.verify_batch(items) == expected
        assert tpu.stats["device_straggler_strikes"] == 1
        assert tpu._mesh.size == 8        # one strike is not a verdict
        assert tpu.verify_batch(items) == expected
        assert tpu.stats["device_quarantines"] == 1
        assert tpu.verify_batch(items) == expected
        assert tpu._mesh.size == 7
        assert "degraded_mesh:7/8" in tpu.health()
        # no sw fallback at any point: a straggler costs latency,
        # never the device path
        assert tpu.stats["sw_fallbacks"] == 0


# ---------------------------------------------------------------------------
# startup degrade + observability
# ---------------------------------------------------------------------------

class TestDegradedStartupHealth:
    def test_provider_reports_unmet_mesh_ask(self):
        tpu = TPUProvider(min_batch=4, use_g16=False,
                          mesh_requested=8)
        assert tpu.health() == "device;degraded_mesh:1/8"

    def test_factory_enumeration_failure_surfaces_on_health(
            self, monkeypatch):
        """_resolve_mesh blowing up (mid-flight libtpu upgrade,
        broken tunnel) still degrades to single-device — but now as a
        /healthz fact, not just a log line."""
        import fabric_tpu.bccsp.factory as fmod

        def boom(n):
            raise RuntimeError("enumeration failed")
        monkeypatch.setattr(fmod, "_resolve_mesh",
                            lambda nd: (None, nd or "all"))
        prov = fmod.new_bccsp(fmod.FactoryOpts.from_config(
            {"Default": "TPU", "TPU": {"Devices": 4,
                                       "UseG16": False}}))
        assert prov.health() == "device;degraded_mesh:1/4"

    def test_resolve_mesh_reports_unmet_ask_on_failure(
            self, monkeypatch):
        import fabric_tpu.bccsp.factory as fmod

        class _BoomJax:
            def devices(self):
                raise RuntimeError("no backend")
        import sys
        monkeypatch.setitem(sys.modules, "jax", _BoomJax())
        mesh, unmet = fmod._resolve_mesh(4)
        assert mesh is None and unmet == 4
        mesh, unmet = fmod._resolve_mesh(None)
        assert mesh is None and unmet == "all"
        mesh, unmet = fmod._resolve_mesh(1)
        assert mesh is None and unmet is None   # 1 was the ask: met

    def test_devicehealth_config_parsed_from_core_yaml(self):
        opts = factory.FactoryOpts.from_config(
            {"Default": "TPU",
             "TPU": {"DeviceHealth": {"TripThreshold": 2,
                                      "CooldownS": 7.5,
                                      "StragglerSkewS": 0.5,
                                      "StragglerStrikes": 4,
                                      "ProbeTimeoutS": 1.5}}})
        dh = opts.tpu.device_health
        assert dh.trip_threshold == 2
        assert dh.cooldown_s == 7.5
        assert dh.straggler_skew_s == 0.5
        assert dh.straggler_strikes == 4
        assert dh.probe_timeout_s == 1.5


class TestDeviceGauges:
    def test_device_gauges_published_with_device_label(self, mesh8):
        """bccsp_device_{state,trips,quarantines,readmits} render on
        /metrics device-labeled, reading the provider's live
        device_stats property (state changes show without a
        dispatch)."""
        from fabric_tpu.common import metrics as m
        from fabric_tpu.common import profiling

        faults.clear()
        tpu, _ = _stubbed_provider(
            mesh=mesh8, dh_config=DeviceHealthConfig(cooldown_s=300.0))
        items, _ = _corpus(SPAN8 + 8)
        faults.arm("tpu.device_lost", mode="error", count=1, arg=3)
        tpu.verify_batch(items)
        assert tpu.stats["device_quarantines"] == 1
        provider = m.PrometheusProvider()
        t = profiling.publish_provider_stats(provider, tpu,
                                             poll_s=0.01)
        assert t is not None
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            text = provider.render()
            if 'bccsp_device_state{device="3"} 2' in text:
                break
            time.sleep(0.02)
        text = provider.render()
        assert 'bccsp_device_state{device="3"} 2' in text
        assert 'bccsp_device_state{device="0"} 0' in text
        assert 'bccsp_device_quarantines{device="3"} 1' in text
        assert 'bccsp_device_trips{device="3"} 1' in text
        assert 'bccsp_device_readmits{device="3"} 0' in text
        # the scalar aggregates stay out of the generic gauge set
        # (fqname collision with the labeled series)
        assert "bccsp_device_quarantines 1" not in text
        # elastic-mesh scalars DO publish
        assert "bccsp_mesh_rebuilds 1" in text
        assert "bccsp_mesh_devices_full 8" in text

    def test_device_stats_property_no_mesh(self):
        tpu = TPUProvider(min_batch=4, use_g16=False)
        assert tpu.device_stats == {"state": [], "trips": [],
                                    "quarantines": [], "readmits": []}


# ---------------------------------------------------------------------------
# real kernel (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestElasticMeshRealKernel:
    def test_real_comb_loss_rebuild_parity(self, mesh8):
        """Full provider, REAL q8 comb kernel: chip 2 lost on the
        first sharded batch (sw fallback, parity), the rebuilt
        7-device mesh recompiles and dispatches the next batch with
        verdicts bit-identical to the sw oracle. Minutes of XLA
        compile — slow suite only; tier-1 covers the same plumbing
        with recorder stubs."""
        faults.clear()
        prov = TPUProvider(
            min_batch=16, use_g16=False, mesh=mesh8,
            pipeline_chunk=0, hash_on_host=True,
            device_health=DeviceHealthConfig(cooldown_s=3600.0))
        items, expected = _corpus(64)
        oracle = _SW.verify_batch(items)
        assert expected == oracle
        faults.arm("tpu.device_lost", mode="error", count=1, arg=2)
        assert prov.verify_batch(items) == oracle    # sw fallback
        assert prov.stats["device_quarantines"] == 1
        assert prov._mesh.size == 7
        assert prov.verify_batch(items) == oracle    # 7-dev kernel
        assert prov.stats["comb_batches"] >= 1
        assert prov.stats["shard_dispatches"] >= 1
        assert prov._breaker.state == "device"
