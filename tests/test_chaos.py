"""Chaos / graceful-degradation tests (ISSUE 1 tentpole).

`BCCSP.Default: TPU` must be invisible in verdicts: with faults armed
at every device dispatch point (forced errors, deadline stalls,
fail-N-then-recover) a mixed valid/invalid `verify_batch` stays
bit-identical to the SW provider, the breaker trips within
`TripThreshold` failures, refuses the device while open, and re-admits
it after cooldown via a bounded probe. The deliver client reconnects
with full-jitter backoff and resets after progress; a raft chain drops
a faulted step instead of crashing its loop.

Device math is replaced by the recorder-stub idiom from
tests/test_bccsp.py TestQ16TableCache (real staging + fault points +
breaker, no XLA compile), with the corpus chosen so that host
pre-validation (premask) IS the verdict; the `slow`-marked test at the
bottom runs the same scenario through the real compiled kernel.

All of these run green under JAX_PLATFORMS=cpu with no `cryptography`
wheel installed (the pure-python P-256 backend).
"""

import hashlib
import threading
import time
import urllib.request

import numpy as np
import pytest

from fabric_tpu.bccsp import ECDSAKeyGenOpts, VerifyItem, factory, utils
from fabric_tpu.bccsp.sw import SWProvider
from fabric_tpu.bccsp.tpu import TPUProvider
from fabric_tpu.common import breaker as breaker_mod
from fabric_tpu.common import faults
from fabric_tpu.common.breaker import (
    BreakerConfig,
    CircuitBreaker,
    CircuitOpen,
    DeadlineExceeded,
)

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

_SW = SWProvider()
_KEYS = [_SW.key_gen(ECDSAKeyGenOpts(ephemeral=True)) for _ in range(3)]


class _StepClock:
    """Injectable monotonic clock for the breaker's clock seam
    (`CircuitBreaker(clock=)` / `DeviceHealth(clock=)`): cooldown
    transitions are driven by `advance()`, never by wall sleeps, so
    timing assertions cannot lose races on a loaded box."""

    def __init__(self):
        self._t = time.monotonic()

    def __call__(self) -> float:
        return self._t

    def advance(self, dt: float) -> None:
        self._t += dt


def _premask_pool(n_keys=2):
    """(VerifyItem, expected) pool whose verdicts are decided by host
    pre-validation alone (valid low-S sig -> True; malformed DER,
    high-S, out-of-range r -> False) so the recorder stub — which
    returns premask — is bit-exact with the sw oracle."""
    pool = []
    for i in range(8):
        k = _KEYS[i % n_keys]
        m = f"chaos payload {i}".encode() * (i % 3 + 1)
        sig = _SW.sign(k, hashlib.sha256(m).digest())
        pool.append((VerifyItem(key=k.public_key(), signature=sig,
                                message=m), True))
        r, s = utils.unmarshal_signature(sig)
        if i % 3 == 0:     # malformed DER
            pool.append((VerifyItem(key=k.public_key(),
                                    signature=sig[:-2], message=m),
                         False))
        elif i % 3 == 1:   # high-S twin
            pool.append((VerifyItem(
                key=k.public_key(),
                signature=utils.marshal_signature(r, utils.P256_N - s),
                message=m), False))
        else:              # r >= n
            pool.append((VerifyItem(
                key=k.public_key(),
                signature=utils.marshal_signature(utils.P256_N, 5),
                message=m), False))
    return pool


def _tile(pool, n):
    items = [pool[i % len(pool)][0] for i in range(n)]
    expected = [pool[i % len(pool)][1] for i in range(n)]
    return items, expected


def _stubbed_provider(monkeypatch, **kw):
    """TPUProvider with device math stubbed (returns premask), real
    staging/fault/breaker logic — the TestQ16TableCache idiom."""
    kw.setdefault("min_batch", 4)
    kw.setdefault("use_g16", False)
    tpu = TPUProvider(**kw)
    calls = {"premask": []}

    def fake_qtab_fn(K):
        return lambda qx, qy: np.zeros((K,), dtype=np.int32)

    def fake_pipeline_digest(K, q16=False):
        def run(key_idx, q_flat, g16, r8, rpn8, w8, premask, digests):
            calls["premask"].append(np.asarray(premask).copy())
            return np.asarray(premask)
        return run

    def fake_pipeline(K, q16=False):
        def run(blocks, nblocks, key_idx, q_flat, g16, r, rpn, w,
                premask, digests, has_digest):
            calls["premask"].append(np.asarray(premask).copy())
            return np.asarray(premask)
        return run

    def fake_ladder():
        def run(blocks, nblocks, qx, qy, r, rpn, w, premask, digests,
                has_digest):
            calls["premask"].append(np.asarray(premask).copy())
            return np.asarray(premask)
        return run

    monkeypatch.setattr(tpu, "_qtab_fn", fake_qtab_fn)
    monkeypatch.setattr(tpu, "_comb_pipeline", fake_pipeline)
    monkeypatch.setattr(tpu, "_comb_pipeline_digest",
                        fake_pipeline_digest)
    # an all-dead batch has an empty key map and routes to the generic
    # ladder pipeline — stub that too (premask passthrough)
    monkeypatch.setattr(tpu, "_pipeline", fake_ladder)
    return tpu, calls


# ---------------------------------------------------------------------------
# the fault registry itself
# ---------------------------------------------------------------------------

class TestFaultRegistry:
    def test_unarmed_check_is_noop(self):
        faults.clear()
        faults.check("tpu.dispatch")
        assert faults.fires("tpu.dispatch") == 0

    def test_error_mode_counts_down(self):
        faults.clear()
        faults.arm("x.y", mode="error", count=2)
        for _ in range(2):
            with pytest.raises(faults.FaultInjected):
                faults.check("x.y")
        faults.check("x.y")            # exhausted -> disarmed
        assert faults.fires("x.y") == 2
        assert not faults.armed("x.y")

    def test_delay_mode_stalls_then_proceeds(self):
        faults.clear()
        faults.arm("x.y", mode="delay", count=1, delay_s=0.05)
        t0 = time.monotonic()
        faults.check("x.y")            # stalls, does not raise
        assert time.monotonic() - t0 >= 0.04
        faults.check("x.y")            # exhausted

    def test_env_spec_parsing(self):
        faults.clear()
        faults.arm_from_env("a.b=error:2; c.d=delay::0.01,e.f=error")
        assert faults.armed("a.b") and faults.armed("c.d") \
            and faults.armed("e.f")
        faults.arm_from_env("garbage==:::")   # must not raise

    def test_reset_restores_env_baseline(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_VAR, "p.q=error:1")
        faults.reset()
        assert faults.armed("p.q")
        with pytest.raises(faults.FaultInjected):
            faults.check("p.q")
        faults.reset()                 # re-arms from env
        assert faults.armed("p.q")


# ---------------------------------------------------------------------------
# breaker state machine (no device)
# ---------------------------------------------------------------------------

class TestCircuitBreaker:
    def test_trip_cooldown_probe_cycle(self):
        clock = [0.0]
        br = CircuitBreaker(BreakerConfig(trip_threshold=3,
                                          cooldown_s=10.0),
                            clock=lambda: clock[0])
        assert br.state == breaker_mod.DEVICE
        for _ in range(2):
            br.failure(RuntimeError("boom"))
        assert br.state == breaker_mod.DEVICE     # below threshold
        br.failure(RuntimeError("boom"))
        assert br.state == breaker_mod.DEGRADED
        assert br.stats["trips"] == 1
        with pytest.raises(CircuitOpen):
            br.run(lambda: "never")
        clock[0] = 10.5
        assert br.state == breaker_mod.PROBING
        assert br.run(lambda: "probe-ok") == "probe-ok"
        assert br.state == breaker_mod.DEVICE
        assert br.stats["probes"] == 1

    def test_probe_failure_reopens(self):
        clock = [0.0]
        br = CircuitBreaker(BreakerConfig(trip_threshold=1,
                                          cooldown_s=5.0),
                            clock=lambda: clock[0])
        br.failure(RuntimeError("boom"))
        clock[0] = 6.0
        with pytest.raises(RuntimeError):
            br.run(lambda: (_ for _ in ()).throw(RuntimeError("still")))
        assert br.state == breaker_mod.DEGRADED   # probe failed
        clock[0] = 12.0
        assert br.state == breaker_mod.PROBING

    def test_single_probe_slot(self):
        clock = [0.0]
        br = CircuitBreaker(BreakerConfig(trip_threshold=1,
                                          cooldown_s=1.0),
                            clock=lambda: clock[0])
        br.failure(RuntimeError("boom"))
        clock[0] = 2.0
        assert br.admit() is True      # takes the probe slot
        with pytest.raises(CircuitOpen):
            br.admit()                 # concurrent probe refused
        br.success()
        assert br.state == breaker_mod.DEVICE
        assert br.admit() is False     # closed-state admission

    def test_stale_probe_slot_reclaimed(self):
        """A caller that takes the probe slot and never reports the
        outcome (dropped resolver) must not wedge the breaker in
        'probing' forever — the slot is reclaimed as a failed probe."""
        clock = [0.0]
        br = CircuitBreaker(BreakerConfig(trip_threshold=1,
                                          cooldown_s=2.0),
                            clock=lambda: clock[0])
        br.failure(RuntimeError("boom"))
        clock[0] = 3.0
        br.admit()                     # probe slot taken, outcome lost
        clock[0] = 5.5                 # past the probe timeout
        assert br.state == breaker_mod.DEGRADED
        assert br.stats["stale_probes"] == 1
        clock[0] = 8.0                 # cooldown over: a NEW probe
        assert br.state == breaker_mod.PROBING
        assert br.run(lambda: "ok") == "ok"
        assert br.state == breaker_mod.DEVICE

    def test_running_probe_is_not_reclaimed(self):
        """A probe still EXECUTING (e.g. paying a long first-dispatch
        compile with no deadline) keeps its slot past the stale-probe
        timeout — only a DROPPED outcome is reclaimed."""
        clock = [0.0]
        br = CircuitBreaker(BreakerConfig(trip_threshold=1,
                                          cooldown_s=1.0),
                            clock=lambda: clock[0])
        br.failure(RuntimeError("boom"))
        clock[0] = 2.0

        def slow_probe():
            clock[0] = 60.0            # far past the probe timeout
            assert br.state == breaker_mod.PROBING
            return "ok"

        assert br.run(slow_probe) == "ok"
        assert br.state == breaker_mod.DEVICE
        assert br.stats["stale_probes"] == 0

    def test_deadline_guard(self):
        br = CircuitBreaker(BreakerConfig(deadline_ms=50,
                                          trip_threshold=2))
        with pytest.raises(DeadlineExceeded):
            br.guard(lambda: time.sleep(0.5))
        assert br.stats["deadline_timeouts"] == 1
        assert br.guard(lambda: 42) == 42         # fast call fine

    def test_stale_success_cannot_close_open_breaker(self):
        """An in-flight dispatch admitted BEFORE the trip that resolves
        successfully afterwards must not bypass cooldown + probe."""
        clock = [0.0]
        br = CircuitBreaker(BreakerConfig(trip_threshold=1,
                                          cooldown_s=10.0),
                            clock=lambda: clock[0])
        assert br.admit() is False     # healthy admission
        br.failure(RuntimeError("wedged"))
        assert br.state == breaker_mod.DEGRADED
        br.success()                   # the straggler resolves late
        assert br.state == breaker_mod.DEGRADED
        clock[0] = 11.0
        assert br.state == breaker_mod.PROBING

    def test_ignored_exceptions_do_not_count(self):
        br = CircuitBreaker(BreakerConfig(trip_threshold=1,
                                          ignore=(TypeError,)))
        with pytest.raises(TypeError):
            br.guard(lambda: (_ for _ in ()).throw(TypeError("caller")))
        assert br.state == breaker_mod.DEVICE


# ---------------------------------------------------------------------------
# TPU provider degradation (the acceptance scenario)
# ---------------------------------------------------------------------------

class TestTPUProviderDegradation:
    def test_forced_errors_10k_bit_identical_and_trips(self, monkeypatch):
        """Faults armed at EVERY device dispatch/compile/persist point:
        a 10k mixed batch is bit-identical to sw, the breaker trips
        within TripThreshold failures, and while open the device is
        never even attempted."""
        faults.clear()
        faults.arm("tpu.dispatch", mode="error")       # unlimited
        faults.arm("tpu.compile", mode="error")
        faults.arm("tpu.table_persist", mode="error")
        tpu, _ = _stubbed_provider(
            monkeypatch, min_batch=16,
            fallback=BreakerConfig(trip_threshold=3, cooldown_s=60.0,
                                   probe_batch=64))
        pool = _premask_pool()
        items, expected = _tile(pool, 10_000)
        # the sw oracle agrees with the pool verdicts by construction;
        # pin it on the unique pool to keep the wall clock sane
        assert _SW.verify_batch([it for it, _ in pool]) == \
            [e for _, e in pool]

        out = tpu.verify_batch(items)                  # failure 1
        assert out == expected
        small, small_exp = _tile(pool, 16)
        assert tpu.verify_batch(small) == small_exp    # failure 2
        assert tpu.health() != "degraded"
        assert tpu.verify_batch(small) == small_exp    # failure 3: trip
        assert tpu.health() == "degraded"
        assert tpu.stats["breaker_trips"] == 1
        assert tpu.stats["sw_fallbacks"] == 3

        # open breaker: the device is not attempted at all
        fires_before = faults.fires("tpu.dispatch")
        assert tpu.verify_batch(small) == small_exp
        assert faults.fires("tpu.dispatch") == fires_before
        assert tpu.stats["degraded_batches"] >= 1
        assert tpu.stats["breaker_state"] == 2

    def test_deadline_stall_trips_then_reprobes(self, monkeypatch):
        """Stalled dispatches (delay faults) exceed DeadlineMs, count
        as failures, trip the breaker; after CooldownS the next batch
        probes the device and re-admits it.

        Cooldown passage is driven through the breaker's monotonic
        CLOCK SEAM (a stepped fake), not wall sleeps: on a loaded box
        the old 0.2s margin lost races — more than the cooldown could
        elapse between the trip inside verify_batch and the health()
        assertion, reading `probing` where the test pinned
        `degraded`. The deadline watchdog itself still runs on wall
        time (the 1.0s injected stall vs the 300ms deadline leaves no
        meaningful race)."""
        faults.clear()
        # the deadline must measure the DISPATCH, not first-use costs:
        # warm the jax backend and the native-extension probe (a ~3s
        # one-time g++ attempt) before arming
        import jax.numpy as jnp
        jnp.zeros(1).block_until_ready()
        from fabric_tpu import native as native_mod
        native_mod.available()
        faults.arm("tpu.dispatch", mode="delay", count=2, delay_s=1.0)
        tpu, _ = _stubbed_provider(
            monkeypatch, min_batch=4,
            fallback=BreakerConfig(deadline_ms=300, trip_threshold=2,
                                   cooldown_s=0.2, probe_batch=64))
        clk = _StepClock()
        tpu._breaker._clock = clk
        items, expected = _tile(_premask_pool(), 16)
        assert tpu.verify_batch(items) == expected     # timeout 1
        assert tpu.verify_batch(items) == expected     # timeout 2: trip
        assert tpu.stats["breaker_deadline_timeouts"] == 2
        # deterministic: the breaker's clock has not moved since the
        # trip, so the cooldown CANNOT have elapsed yet
        assert tpu.health() == "degraded"
        clk.advance(0.25)
        assert tpu.health() == "probing"
        # fault budget exhausted: the probe dispatch succeeds
        assert tpu.verify_batch(items) == expected
        assert tpu.health() == "device"
        assert tpu.stats["breaker_probes"] == 1
        # drain the abandoned watchdog workers (each sleeps 1.0s in
        # the delay fault, then re-checks tpu.dispatch during staging)
        # so they cannot consume the NEXT test's armed fault budget
        time.sleep(1.1)

    def test_fail_n_then_recover_below_threshold(self, monkeypatch):
        faults.clear()
        faults.arm("tpu.dispatch", mode="error", count=2)
        tpu, calls = _stubbed_provider(
            monkeypatch, min_batch=4,
            fallback=BreakerConfig(trip_threshold=5))
        items, expected = _tile(_premask_pool(), 24)
        for _ in range(2):                             # transient faults
            assert tpu.verify_batch(items) == expected
        assert tpu.stats["sw_fallbacks"] == 2
        assert tpu.health() == "device"                # never tripped
        assert tpu.verify_batch(items) == expected     # device again
        assert calls["premask"], "device path did not run after recovery"

    def test_probe_risks_at_most_probe_batch_lanes(self, monkeypatch):
        faults.clear()
        faults.arm("tpu.dispatch", mode="error", count=1)
        tpu, _ = _stubbed_provider(
            monkeypatch, min_batch=4,
            fallback=BreakerConfig(trip_threshold=1, cooldown_s=0.4,
                                   probe_batch=8))
        items, expected = _tile(_premask_pool(), 32)
        assert tpu.verify_batch(items) == expected     # trip
        assert tpu.health() == "degraded"
        time.sleep(0.45)
        seen = []
        real = tpu._verify_batch_device

        def spy(batch):
            seen.append(len(batch))
            return real(batch)

        monkeypatch.setattr(tpu, "_verify_batch_device", spy)
        assert tpu.verify_batch(items) == expected     # probe + sw rest
        assert seen == [8]
        assert tpu.health() == "device"

    @staticmethod
    def _prepared_arrays(n, bad_lane=3):
        """Pre-staged operand arrays for verify_prepared (one key,
        lane `bad_lane` malformed)."""
        key = _KEYS[0]
        digests = np.zeros((n, 32), dtype=np.uint8)
        r = np.zeros((n, 32), dtype=np.uint8)
        rpn = np.zeros((n, 32), dtype=np.uint8)
        w = np.zeros((n, 32), dtype=np.uint8)
        der_ok = np.ones(n, dtype=bool)
        sigs = []
        P256_P = (1 << 256) - (1 << 224) + (1 << 192) + (1 << 96) - 1
        for i in range(n):
            m = f"prepared {i}".encode()
            dg = hashlib.sha256(m).digest()
            sig = _SW.sign(key, dg)
            ri, si = utils.unmarshal_signature(sig)
            wi = pow(si, -1, utils.P256_N)
            rpni = ri + utils.P256_N \
                if ri + utils.P256_N < P256_P else ri
            digests[i] = np.frombuffer(dg, np.uint8)
            r[i] = np.frombuffer(ri.to_bytes(32, "big"), np.uint8)
            rpn[i] = np.frombuffer(rpni.to_bytes(32, "big"), np.uint8)
            w[i] = np.frombuffer(wi.to_bytes(32, "big"), np.uint8)
            sigs.append(sig)
        sigs[bad_lane] = sigs[bad_lane][:-2]
        der_ok[bad_lane] = False
        expected = [i != bad_lane for i in range(n)]
        key_idx = np.zeros(n, dtype=np.int32)
        return digests, r, rpn, w, der_ok, key_idx, [key], sigs, \
            expected

    def test_prepared_path_degrades_bit_identically(self, monkeypatch):
        """verify_prepared under an open breaker rides
        _verify_prepared_sw with identical verdicts."""
        faults.clear()
        tpu, _ = _stubbed_provider(
            monkeypatch, min_batch=4,
            fallback=BreakerConfig(trip_threshold=1, cooldown_s=60.0))
        digests, r, rpn, w, der_ok, key_idx, keys, sigs, expected = \
            self._prepared_arrays(8)
        tpu._breaker.failure(RuntimeError("boom"))     # trip (thresh 1)
        assert tpu.health() == "degraded"
        out = tpu.verify_prepared(digests, r, rpn, w, der_ok, key_idx,
                                  keys, lambda i: sigs[i])
        assert out == expected
        assert tpu.stats["degraded_batches"] == 1

    def test_prepared_probe_is_bounded(self, monkeypatch):
        """In probing state the prepared path risks at most ProbeBatch
        lanes on the device; the rest verify on the host, and the
        merged verdicts stay bit-identical."""
        faults.clear()
        tpu, _ = _stubbed_provider(
            monkeypatch, min_batch=4,
            fallback=BreakerConfig(trip_threshold=1, cooldown_s=0.2,
                                   probe_batch=4))
        digests, r, rpn, w, der_ok, key_idx, keys, sigs, expected = \
            self._prepared_arrays(16)
        tpu._breaker.failure(RuntimeError("boom"))     # trip
        time.sleep(0.25)                               # -> probing
        seen = []
        real = tpu._verify_prepared_device

        def spy(dg, *args):
            seen.append(len(dg))
            return real(dg, *args)

        monkeypatch.setattr(tpu, "_verify_prepared_device", spy)
        out = tpu.verify_prepared(digests, r, rpn, w, der_ok, key_idx,
                                  keys, lambda i: sigs[i])
        assert out == expected
        assert seen == [4]                             # probe bounded
        assert tpu.health() == "device"

    def test_persist_fault_surfaces_in_counter(self, tmp_path):
        faults.clear()
        faults.arm("tpu.table_persist", mode="error", count=1)
        tpu = TPUProvider(min_batch=4, warm_keys_dir=str(tmp_path))
        tpu._persist_table((b"\x01" * 64,),
                           np.zeros(4, dtype=np.int32), "qtab8")
        tpu.flush_warm_tables(timeout=5.0)
        assert tpu.stats["warm_table_persist_failures"] == 1
        assert not list(tmp_path.glob("qtab8_*.npy"))

    def test_flush_warm_tables_total_deadline(self):
        """N stuck writers must cost ONE timeout, not N timeouts."""
        tpu = TPUProvider(min_batch=4)
        for _ in range(3):
            t = threading.Thread(target=time.sleep, args=(5.0,),
                                 daemon=True)
            t.start()
            tpu._persist_threads.append(t)
        t0 = time.monotonic()
        tpu.flush_warm_tables(timeout=0.4)
        assert time.monotonic() - t0 < 2.0
        assert len(tpu._persist_threads) == 3      # still alive, kept

    def test_fallback_config_reaches_breaker(self):
        opts = factory.FactoryOpts.from_config({
            "Default": "TPU",
            "TPU": {"Fallback": {"DeadlineMs": 250, "TripThreshold": 7,
                                 "CooldownS": 3, "ProbeBatch": 128}},
        })
        assert opts.tpu.fallback.deadline_ms == 250
        assert opts.tpu.fallback.trip_threshold == 7
        assert opts.tpu.fallback.cooldown_s == 3
        assert opts.tpu.fallback.probe_batch == 128
        csp = factory.new_bccsp(opts)
        assert isinstance(csp, TPUProvider)
        assert csp._breaker.config.trip_threshold == 7
        assert csp.health() == "device"

    def test_differential_under_ambient_faults(self, monkeypatch):
        """Whatever FTPU_FAULTS armed (nothing, errors, stalls): the
        provider's verdicts match the sw oracle bit for bit. This is
        the invariant tools/chaos_check.sh re-runs under env arming."""
        tpu, _ = _stubbed_provider(
            monkeypatch, min_batch=4,
            fallback=BreakerConfig(trip_threshold=2, cooldown_s=0.01,
                                   deadline_ms=500))
        pool = _premask_pool()
        items, expected = _tile(pool, 64)
        for _ in range(4):
            assert tpu.verify_batch(items) == expected


# ---------------------------------------------------------------------------
# /healthz surface
# ---------------------------------------------------------------------------

class TestHealthzSurface:
    def test_breaker_state_reported(self, monkeypatch):
        from fabric_tpu.node.operations import OperationsServer
        faults.clear()
        tpu, _ = _stubbed_provider(
            monkeypatch, fallback=BreakerConfig(trip_threshold=1,
                                                cooldown_s=60.0))
        srv = OperationsServer()
        srv.register_checker("bccsp", tpu.health)
        srv.start()
        try:
            def get():
                import json
                with urllib.request.urlopen(
                        f"http://{srv.address}/healthz",
                        timeout=10) as resp:
                    return resp.status, json.loads(resp.read())
            status, body = get()
            assert status == 200
            assert body["components"]["bccsp"] == "device"
            tpu._breaker.failure(RuntimeError("dead device"))
            status, body = get()
            assert status == 200       # degraded still SERVES
            assert body["components"]["bccsp"] == "degraded"
        finally:
            srv.stop()

    def test_canonical_fallback_instruments_published(self,
                                                      monkeypatch):
        """The documented bccsp_fallback_state / _trips_total series
        exist and move with the breaker (not just the dynamic
        bccsp_breaker_* stats gauges)."""
        from fabric_tpu.common import metrics as metrics_mod
        from fabric_tpu.common import profiling
        faults.clear()
        tpu, _ = _stubbed_provider(
            monkeypatch, fallback=BreakerConfig(trip_threshold=1,
                                                cooldown_s=60.0))
        provider = metrics_mod.PrometheusProvider()
        assert profiling.publish_provider_stats(
            provider, tpu, poll_s=0.05) is not None
        tpu._breaker.failure(RuntimeError("dead device"))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            text = provider.render()
            if "bccsp_fallback_state 2" in text:
                break
            time.sleep(0.02)
        assert "bccsp_fallback_state 2" in text, text
        assert "bccsp_fallback_trips_total 1" in text, text

    def test_failing_checker_still_503s(self):
        from fabric_tpu.node.operations import OperationsServer
        srv = OperationsServer()
        srv.register_checker("doomed", lambda: 1 / 0)
        srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://{srv.address}/healthz", timeout=10)
            assert ei.value.code == 503
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# deliver client chaos
# ---------------------------------------------------------------------------

class _FakeSigner:
    def serialize(self):
        return b"test-signer"

    def sign(self, msg):
        return b"sig"


class _FakeLedger:
    def __init__(self):
        self.height = 0


class _FakeChannel:
    channel_id = "chaoschannel"

    def __init__(self):
        self.ledger = _FakeLedger()

    def process_block(self, block):
        self.ledger.height += 1


class _FakeMCS:
    def verify_block(self, channel_id, height, block):
        return None


class _FakeEndpoint:
    """Yields blocks forever; `die_after` ends the stream with an
    error after that many blocks per connection."""

    def __init__(self, die_after=None):
        self.die_after = die_after
        self.connections = 0

    def handle(self, env):
        from fabric_tpu.protos import common, orderer as ordpb
        self.connections += 1
        sent = 0
        while True:
            if self.die_after is not None and sent >= self.die_after:
                raise ConnectionError("stream torn down")
            blk = common.Block()
            blk.header.number = sent
            yield ordpb.DeliverResponse(block=blk)
            sent += 1


class TestDeliverChaos:
    def _deliverer(self, endpoint, **kw):
        from fabric_tpu.peer.deliverclient import Deliverer
        ch = _FakeChannel()
        d = Deliverer(ch, _FakeSigner(), lambda: endpoint, _FakeMCS(),
                      retry_base_s=0.005, retry_max_s=0.05, **kw)
        return d, ch

    def test_stream_faults_reconnect_and_count(self):
        faults.clear()
        faults.arm("deliver.stream", mode="error", count=3)
        d, ch = self._deliverer(_FakeEndpoint())
        d.start()
        try:
            deadline = time.monotonic() + 20
            while ch.ledger.height < 3 and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            d.stop()
        assert ch.ledger.height >= 3
        assert d.reconnects == 3
        assert d._backoff.failures == 0   # reset by processed blocks

    def test_backoff_resets_after_processed_block(self, monkeypatch):
        """One block per connection, then the stream dies: because the
        failure counter resets on progress, every outage backs off
        from the BASE delay — never pinned at retry_max_s."""
        import random as random_mod
        caps = []
        monkeypatch.setattr(
            random_mod, "uniform",
            lambda lo, hi: caps.append(hi) or 0.0)
        faults.clear()
        d, ch = self._deliverer(_FakeEndpoint(die_after=1))
        d.start()
        try:
            deadline = time.monotonic() + 20
            while ch.ledger.height < 5 and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            d.stop()
        assert ch.ledger.height >= 5
        assert len(caps) >= 4
        # failures reset after each delivered block: every cap is the
        # first-retry cap (base * 2), far below retry_max_s
        assert all(abs(c - 0.01) < 1e-9 for c in caps), caps

    def test_reconnect_counter_exported(self):
        from fabric_tpu.common import metrics as metrics_mod
        faults.clear()
        faults.arm("deliver.stream", mode="error", count=2)
        provider = metrics_mod.PrometheusProvider()
        d, ch = self._deliverer(_FakeEndpoint(),
                                metrics_provider=provider)
        d.start()
        try:
            deadline = time.monotonic() + 20
            while ch.ledger.height < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            d.stop()
        text = provider.render()
        assert 'deliver_client_reconnects{channel="chaoschannel"} 2' \
            in text, text


# ---------------------------------------------------------------------------
# raft chain chaos
# ---------------------------------------------------------------------------

class TestRaftStepChaos:
    def _bare_chain(self):
        """RaftChain with just the attrs _handle_event touches — the
        event-loop drop-don't-crash contract is what's under test."""
        from fabric_tpu.orderer.raft.chain import RaftChain

        class _Node:
            def __init__(self):
                self.stepped = []

            def step(self, msg):
                self.stepped.append(msg)

        class _Support:
            channel_id = "chaosraft"

        chain = RaftChain.__new__(RaftChain)
        chain.node = _Node()
        chain._peer_seen = {}
        chain._support = _Support()
        return chain

    def test_faulted_step_is_dropped_not_fatal(self):
        from fabric_tpu.protos import raft as rpb
        faults.clear()
        faults.arm("raft.step", mode="error", count=2)
        chain = self._bare_chain()
        msg = rpb.RaftMessage(from_=2, to=1, term=1)
        chain._handle_event(("step", msg), now=0.0)    # dropped
        chain._handle_event(("step", msg), now=0.0)    # dropped
        assert chain.node.stepped == []
        assert chain._peer_seen == {}
        chain._handle_event(("step", msg), now=1.0)    # recovers
        assert len(chain.node.stepped) == 1
        assert chain._peer_seen == {2: 1.0}
        assert faults.fires("raft.step") == 2

    def test_step_exception_does_not_leak(self):
        faults.clear()
        chain = self._bare_chain()

        def bad_step(msg):
            raise ValueError("corrupt message")

        chain.node.step = bad_step
        from fabric_tpu.protos import raft as rpb
        msg = rpb.RaftMessage(from_=3, to=1, term=1)
        chain._handle_event(("step", msg), now=0.0)    # swallowed


# ---------------------------------------------------------------------------
# the real compiled kernel (slow: ~minutes of XLA compile on CPU)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestRealDeviceRecovery:
    def test_fail_n_then_recover_on_real_kernel(self):
        """Same fail-N-then-recover scenario, real device math: after
        the transient faults the batch — including lanes only curve
        math can reject — is verified ON DEVICE, bit-identical to sw."""
        faults.clear()
        faults.arm("tpu.dispatch", mode="error", count=2)
        sw = SWProvider()
        keys = [sw.key_gen(ECDSAKeyGenOpts(ephemeral=True))
                for _ in range(2)]
        items, expected = [], []
        for i in range(12):
            k = keys[i % 2]
            m = f"real kernel {i}".encode()
            sig = sw.sign(k, hashlib.sha256(m).digest())
            ok = i % 4 != 2
            if not ok:
                m += b"!"           # tampered: premask passes, curve
                #                     math must reject
            items.append(VerifyItem(key=k.public_key(), signature=sig,
                                    message=m))
            expected.append(ok)
        tpu = TPUProvider(min_batch=4,
                          fallback=BreakerConfig(trip_threshold=5))
        assert tpu.verify_batch(items) == expected     # fault 1 -> sw
        assert tpu.verify_batch(items) == expected     # fault 2 -> sw
        assert tpu.stats["sw_fallbacks"] == 2
        out = tpu.verify_batch(items)                  # real device
        assert out == expected
        assert tpu.health() == "device"
        assert tpu.stats["comb_batches"] >= 1
