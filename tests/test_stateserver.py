"""Pluggable state-database seam + the external HTTP backend.

Round-4 verdict #7: the reference lets operators run state in an
external database (CouchDB over HTTP, `statecouchdb.go`) behind the
`statedb.go` VersionedDB interface; the rebuild had only the embedded
engine and no seam. These tests pin the seam: the HTTP backend must be
drop-in — same MVCC verdicts, same rich-query results (executed
server-side with the server's indexes), same savepoint/crash
semantics — proven by differential runs against the embedded engine.
The multi-process peer proof lives in test_integration_nwo.py-style
harness (nwo state_backend option).
"""

import json

import pytest

from fabric_tpu.ledger.kvdb import DBHandle, KVStore
from fabric_tpu.ledger.statedb import (
    Height, StateDB, UpdateBatch, VersionedValue,
)
from fabric_tpu.ledger.stateserver import HTTPVersionedDB, StateServer


@pytest.fixture()
def server(tmp_path):
    srv = StateServer(str(tmp_path / "state"), "127.0.0.1:0")
    srv.start()
    yield srv
    srv.stop()


def _fill(db):
    b = UpdateBatch()
    for i in range(20):
        doc = {"color": "red" if i % 2 else "blue", "size": i,
               "owner": f"org{i % 3}"}
        b.put("cc", f"k{i:02d}", json.dumps(doc).encode(),
              Height(1, i))
    b.put("cc", "binkey", b"\x00\x01raw", Height(1, 20),
          metadata=b"md-bytes")
    b.put("other", "x", b"1", Height(1, 21))
    db.apply_updates(b, Height(1, 21))


class TestHTTPBackendParity:
    def test_crud_range_savepoint_parity(self, server, tmp_path):
        http_db = HTTPVersionedDB(server.address, "ch1")
        emb = StateDB(DBHandle(KVStore(":memory:"), "statedb"))
        _fill(http_db)
        _fill(emb)

        for ns, key in (("cc", "k03"), ("cc", "binkey"),
                        ("cc", "missing"), ("other", "x")):
            assert http_db.get_state(ns, key) == emb.get_state(ns, key)
        assert http_db.get_version("cc", "k07") == Height(1, 7)
        assert http_db.get_state_metadata("cc", "binkey") == b"md-bytes"
        assert http_db.get_state_metadata("cc", "k01") is None
        assert http_db.savepoint() == emb.savepoint() == Height(1, 21)

        got = list(http_db.get_state_range("cc", "k05", "k10"))
        want = list(emb.get_state_range("cc", "k05", "k10"))
        assert got == want and len(got) == 5
        # unbounded end + namespace isolation
        assert len(list(http_db.get_state_range("cc", "", ""))) == 21
        assert [k for k, _ in http_db.get_state_range("other", "", "")] \
            == ["x"]
        assert sorted(http_db.iterate_all()) == sorted(emb.iterate_all())

    def test_rich_query_executes_server_side(self, server):
        db = HTTPVersionedDB(server.address, "ch2")
        _fill(db)
        q = json.dumps({"selector": {"color": "red"},
                        "fields": ["size"]})
        results, bm = db.execute_query("cc", q)
        emb = StateDB(DBHandle(KVStore(":memory:"), "statedb"))
        _fill(emb)
        assert (results, bm) == emb.execute_query("cc", q)
        assert len(results) == 10
        # server-side index: define + query with use_index
        db.define_index("cc", "bySize", json.dumps(
            {"index": {"fields": ["size"]}, "name": "bySize"}))
        q2 = json.dumps({"selector": {"size": {"$gte": 15}},
                         "use_index": "bySize"})
        r2, _ = db.execute_query("cc", q2)
        assert sorted(k for k, _raw, _v in r2) == \
            [f"k{i}" for i in range(15, 20)]

    def test_pagination_bookmarks(self, server):
        db = HTTPVersionedDB(server.address, "ch3")
        _fill(db)
        q = json.dumps({"selector": {"color": "blue"}})
        seen = []
        bm = ""
        while True:
            page, bm = db.execute_query("cc", q, page_size=3,
                                        bookmark=bm)
            seen.extend(k for k, _r, _v in page)
            if not bm:
                break
        assert seen == [f"k{i:02d}" for i in range(0, 20, 2)]

    def test_databases_are_isolated(self, server):
        a = HTTPVersionedDB(server.address, "chA")
        b = HTTPVersionedDB(server.address, "chB")
        _fill(a)
        assert b.get_state("cc", "k01") is None
        assert b.savepoint() is None

    def test_bad_requests_surface_errors(self, server):
        db = HTTPVersionedDB(server.address, "bad/../name")
        with pytest.raises(Exception):
            db.get_state("cc", "k")


class TestLedgerOnHTTPBackend:
    def test_kvledger_commit_and_query(self, server, tmp_path):
        """The full ledger pipeline (MVCC validate → commit → state)
        over the external backend, via the factory seam."""
        from fabric_tpu.ledger.kvledger import KVLedger

        def factory(ledger_id, _handle):
            return HTTPVersionedDB(server.address, ledger_id)

        ledger = KVLedger("extchan", str(tmp_path / "ledger"),
                          state_db_factory=factory)
        assert isinstance(ledger.state_db, HTTPVersionedDB)
        b = UpdateBatch()
        b.put("cc", "alpha",
              json.dumps({"color": "green", "size": 1}).encode(),
              Height(0, 0))
        b.put("cc", "beta", b"plain", Height(0, 1))
        ledger.state_db.apply_updates(b, Height(0, 1))
        assert ledger.get_state("cc", "alpha") is not None
        assert ledger.get_state("cc", "beta") == b"plain"
        # rich query through the ledger's simulator surface
        sim2 = ledger.new_tx_simulator("t2")
        rows, _bm = sim2.get_query_result(
            "cc", json.dumps({"selector": {"color": "green"}}))
        assert [k for k, _ in rows] == ["alpha"]


class TestHardening:
    """ISSUE 3 satellite: non-loopback binds need a shared secret, the
    mutating API enforces it, and metadata round-trips null-vs-base64
    (None and b"" are different ledger states)."""

    def test_non_loopback_bind_refused_without_token(self, tmp_path):
        with pytest.raises(ValueError, match="auth token"):
            StateServer(str(tmp_path / "s"), "0.0.0.0:0")

    def test_non_loopback_bind_allowed_with_token(self, tmp_path):
        srv = StateServer(str(tmp_path / "s"), "0.0.0.0:0",
                          auth_token="sekrit")
        srv.start()
        srv.stop()

    def test_loopback_bind_needs_no_token(self, tmp_path):
        srv = StateServer(str(tmp_path / "s"), "127.0.0.1:0")
        srv.start()
        srv.stop()

    def test_mutating_calls_rejected_without_token(self, tmp_path):
        import urllib.error
        srv = StateServer(str(tmp_path / "s"), "127.0.0.1:0",
                          auth_token="sekrit")
        srv.start()
        try:
            naked = HTTPVersionedDB(srv.address, "ch1")
            b = UpdateBatch()
            b.put("cc", "k", b"v", Height(0, 0))
            with pytest.raises(urllib.error.HTTPError) as ei:
                naked.apply_updates(b, Height(0, 0))
            assert ei.value.code == 401
            with pytest.raises(urllib.error.HTTPError):
                naked.define_index("cc", "i1", "{}")
            # an unauthenticated READ must not materialize a new
            # database on disk either (unbounded-creation guard)
            with pytest.raises(urllib.error.HTTPError):
                naked.get_state("cc", "k")
            assert not any(
                f.endswith(".state.db")
                for f in __import__("os").listdir(str(tmp_path / "s")))
            # the authed client works end to end; once the database
            # exists, reads stay open
            authed = HTTPVersionedDB(srv.address, "ch1",
                                     auth_token="sekrit")
            authed.apply_updates(b, Height(0, 0))
            assert naked.get_state("cc", "k").value == b"v"
        finally:
            srv.stop()

    def test_wrong_token_rejected(self, tmp_path):
        import urllib.error
        srv = StateServer(str(tmp_path / "s"), "127.0.0.1:0",
                          auth_token="sekrit")
        srv.start()
        try:
            bad = HTTPVersionedDB(srv.address, "ch1",
                                  auth_token="wrong")
            b = UpdateBatch()
            b.put("cc", "k", b"v", Height(0, 0))
            with pytest.raises(urllib.error.HTTPError) as ei:
                bad.apply_updates(b, Height(0, 0))
            assert ei.value.code == 401
        finally:
            srv.stop()

    def test_metadata_none_vs_empty_roundtrip(self, server):
        db = HTTPVersionedDB(server.address, "mdch")
        b = UpdateBatch()
        b.put("cc", "no-md", b"v", Height(1, 0))               # b""
        b.updates[("cc", "with-md")] = VersionedValue(
            b"v", Height(1, 1), b"md!")
        b.updates[("cc", "none-md")] = VersionedValue(
            b"v", Height(1, 2), None)
        db.apply_updates(b, Height(1, 2))
        # get_state preserves exactly what the engine stores
        assert db.get_state("cc", "with-md").metadata == b"md!"
        # get_state_metadata matches the embedded engine's semantics:
        # None for absent key OR no metadata, bytes otherwise
        assert db.get_state_metadata("cc", "with-md") == b"md!"
        assert db.get_state_metadata("cc", "no-md") is None
        assert db.get_state_metadata("cc", "none-md") is None
        assert db.get_state_metadata("cc", "missing") is None
        assert db.get_state_metadata_many(
            [("cc", "with-md"), ("cc", "no-md"), ("cc", "missing")]
        ) == {("cc", "with-md"): b"md!"}
