"""Deterministic leader-election tests (fabric_tpu/gossip/election.py
ElectionCore) — the synchronous harness the round-2 verdict asked for:
whole multi-peer elections driven with simulated time, message drops,
partitions and adversarial orderings; no threads, no wall clock.

Also pins the two node-level transport properties the e2e flake traced
back to: random fanout selection and leadership-message relay
(fabric_tpu/gossip/node.py gossip_channel/_on_message).
"""

import itertools
import random
import threading
from types import SimpleNamespace

from fabric_tpu.gossip.election import (
    DECLARE,
    GAIN,
    LOSE,
    PROPOSE,
    ElectionCore,
)

ALIVE = 1.5
TICK = 0.3


class SimElection:
    """N ElectionCores + a message fabric with drops/partitions.

    Deterministic: all randomness from the seeded rng; peers tick in a
    shuffled order each round; messages deliver next round unless
    dropped or partitioned.
    """

    def __init__(self, n, seed=0, drop=0.0):
        self.rng = random.Random(seed)
        self.pkis = [bytes([i + 1]) * 4 for i in range(n)]
        self.cores = {p: ElectionCore(p, ALIVE) for p in self.pkis}
        self.alive = set(self.pkis)
        self.now = 0.0
        self.drop = drop
        self.cut = set()            # frozenset({a, b}) partitions
        self.inflight = []          # (dst, src, is_declaration)

    def partition(self, a, b):
        self.cut.add(frozenset((a, b)))

    def heal(self):
        self.cut.clear()

    def _broadcast(self, src, is_declaration):
        for dst in self.pkis:
            if dst == src or dst not in self.alive:
                continue
            if frozenset((src, dst)) in self.cut:
                continue
            if self.rng.random() < self.drop:
                continue
            self.inflight.append((dst, src, is_declaration))

    def step(self):
        """One propose interval: deliver last round's messages in a
        random order, then tick every alive peer in a random order."""
        self.now += TICK
        msgs, self.inflight = self.inflight, []
        self.rng.shuffle(msgs)
        for dst, src, decl in msgs:
            if dst not in self.alive:
                continue
            for act in self.cores[dst].on_leadership(src, decl, self.now):
                if act in (PROPOSE, DECLARE):
                    self._broadcast(dst, act == DECLARE)
        order = [p for p in self.pkis if p in self.alive]
        self.rng.shuffle(order)
        for p in order:
            for act in self.cores[p].tick(self.now):
                if act in (PROPOSE, DECLARE):
                    self._broadcast(p, act == DECLARE)

    def leaders(self):
        return [p for p in self.pkis
                if p in self.alive and self.cores[p].is_leader]

    def settle(self, rounds=30):
        for _ in range(rounds):
            self.step()


class TestConvergence:
    def test_single_leader_from_cold_start_many_seeds(self):
        for seed in range(20):
            sim = SimElection(5, seed=seed)
            sim.settle(20)
            assert sim.leaders() == [sim.pkis[0]], f"seed {seed}"
            # stability: 20 more rounds, leadership never flaps
            for _ in range(20):
                sim.step()
                assert sim.leaders() == [sim.pkis[0]], f"seed {seed}"

    def test_convergence_under_30pct_message_loss(self):
        for seed in range(10):
            sim = SimElection(4, seed=seed, drop=0.3)
            sim.settle(60)
            assert sim.leaders() == [sim.pkis[0]], f"seed {seed}"

    def test_followers_quiet_while_leader_fresh(self):
        sim = SimElection(3, seed=1)
        sim.settle(20)
        follower = sim.cores[sim.pkis[2]]
        assert follower.tick(sim.now) == []   # fresh leader -> silence


class TestFailover:
    def test_leader_crash_triggers_reelection(self):
        sim = SimElection(4, seed=7)
        sim.settle(20)
        sim.alive.discard(sim.pkis[0])        # leader dies silently
        # next-smallest takes over after the alive window expires
        sim.settle(int(ALIVE / TICK) + 10)
        assert sim.leaders() == [sim.pkis[1]]

    def test_smaller_pki_preempts_sitting_leader(self):
        sim = SimElection(3, seed=3)
        small = sim.pkis[0]
        sim.alive.discard(small)              # start without the small
        sim.settle(20)
        assert sim.leaders() == [sim.pkis[1]]
        sim.alive.add(small)                  # small pki joins late
        sim.settle(20)
        assert sim.leaders() == [small]

    def test_partition_heal_collapses_dual_leaders(self):
        """The round-2 flake scenario: two leaders form during a split;
        after healing, declarations must collapse it to one within the
        alive window."""
        for seed in range(10):
            sim = SimElection(4, seed=seed)
            a, b, c, d = sim.pkis
            for x, y in [(a, c), (a, d), (b, c), (b, d)]:
                sim.partition(x, y)
            sim.settle(20)
            assert sorted(sim.leaders()) == sorted([a, c]), f"seed {seed}"
            sim.heal()
            sim.settle(int(ALIVE / TICK) + 10)
            assert sim.leaders() == [a], f"seed {seed}"
            # the ex-leader must have actually emitted LOSE exactly once
            # (its deliverer stops): is_leader False suffices here since
            # the service maps the transition 1:1


class TestAdversarialOrderings:
    def test_all_declaration_interleavings_two_peers(self):
        """Exhaustive: two peers both claim during a race; every
        delivery interleaving of their declarations converges."""
        a, b = bytes([1]) * 4, bytes([2]) * 4
        for order in itertools.permutations([(a, True), (b, True),
                                             (a, False), (b, False)]):
            ca, cb = ElectionCore(a, ALIVE), ElectionCore(b, ALIVE)
            # both self-elected (split brain)
            ca.tick(0.3)
            cb.tick(0.3)
            assert ca.is_leader and cb.is_leader
            now = 0.6
            for src, decl in order:
                ca.on_leadership(src, decl, now) if src != a else None
                cb.on_leadership(src, decl, now) if src != b else None
            # one more round of declarations both ways
            for acts, core, other in [(ca.tick(0.9), ca, cb),
                                      (cb.tick(0.9), cb, ca)]:
                if DECLARE in acts:
                    other.on_leadership(core.pki, True, 0.9)
            cb.tick(1.2)
            assert ca.is_leader and not cb.is_leader, order


class TestNodeTransport:
    """The two transport properties the flake traced to."""

    def _member(self, ep):
        return SimpleNamespace(member=SimpleNamespace(endpoint=ep))

    def test_gossip_channel_fanout_is_randomized(self):
        from fabric_tpu.gossip.node import GossipNode
        node = GossipNode.__new__(GossipNode)
        node.cfg = SimpleNamespace(fanout=1)
        sent = []
        node._send_raw = lambda ep, smsg: sent.append(ep)
        ch = SimpleNamespace(
            members=lambda: [self._member(f"e{i}") for i in range(3)])
        for _ in range(200):
            node.gossip_channel(ch, object())
        # a deterministic first-k prefix would starve e1/e2 forever
        assert set(sent) == {"e0", "e1", "e2"}

    def test_leadership_messages_are_relayed_once(self):
        from fabric_tpu.gossip import message as gmsg
        from fabric_tpu.gossip.node import GossipNode
        from fabric_tpu.protos import gossip as gpb

        from fabric_tpu.gossip.node import GossipMetrics
        node = GossipNode.__new__(GossipNode)
        node.cfg = SimpleNamespace(fanout=8)
        node.metrics = GossipMetrics()
        node._lock = threading.Lock()
        node._leadership_seen = {}
        node.discovery = SimpleNamespace(
            handle_message=lambda *a: False)
        handled = []
        forwarded = []
        # handler returns True = verified (the service's _handle
        # contract); relay only happens on True
        ch = SimpleNamespace(
            members=lambda: [self._member("peerB"),
                             self._member("peerC")],
            on_leadership=lambda s, m, sm: (handled.append(s), True)[1])
        node.channel = lambda cid: ch
        node._send_raw = lambda ep, smsg: forwarded.append(ep)

        msg = gpb.GossipMessage(tag=gpb.GossipMessage.CHAN_AND_ORG,
                                channel=b"ch1")
        msg.leadership_msg.pki_id = b"\x09" * 4
        msg.leadership_msg.is_declaration = True
        msg.leadership_msg.timestamp.inc_num = 1
        msg.leadership_msg.timestamp.seq_num = 42
        smsg = gmsg.unsigned(msg)
        node._on_message("peerA", smsg)
        assert handled == ["peerA"]
        assert sorted(forwarded) == ["peerB", "peerC"]   # relayed
        # duplicate copy: neither re-handled nor re-relayed
        node._on_message("peerB", smsg)
        assert handled == ["peerA"]
        assert sorted(forwarded) == ["peerB", "peerC"]

    def test_unverified_leadership_not_relayed_nor_dedup_poisoned(self):
        """A forged message must not be relayed NOR consume the dedup
        key — otherwise the genuine declaration with the same
        (pki, inc, seq) would be suppressed network-wide."""
        from fabric_tpu.gossip import message as gmsg
        from fabric_tpu.gossip.node import GossipNode
        from fabric_tpu.protos import gossip as gpb

        from fabric_tpu.gossip.node import GossipMetrics
        node = GossipNode.__new__(GossipNode)
        node.cfg = SimpleNamespace(fanout=8)
        node.metrics = GossipMetrics()
        node._lock = threading.Lock()
        node._leadership_seen = {}
        node.discovery = SimpleNamespace(
            handle_message=lambda *a: False)
        verdicts = iter([False, True])   # forgery fails, genuine passes
        handled = []
        forwarded = []
        ch = SimpleNamespace(
            members=lambda: [self._member("peerB")],
            on_leadership=lambda s, m, sm:
                (handled.append(s), next(verdicts))[1])
        node.channel = lambda cid: ch
        node._send_raw = lambda ep, smsg: forwarded.append(ep)

        msg = gpb.GossipMessage(tag=gpb.GossipMessage.CHAN_AND_ORG,
                                channel=b"ch1")
        msg.leadership_msg.pki_id = b"\x09" * 4
        msg.leadership_msg.is_declaration = True
        msg.leadership_msg.timestamp.inc_num = 1
        msg.leadership_msg.timestamp.seq_num = 7
        smsg = gmsg.unsigned(msg)
        node._on_message("attacker", smsg)       # forged: verify fails
        assert forwarded == [] and not node._leadership_seen
        node._on_message("leader", smsg)         # genuine same key
        assert forwarded == ["peerB"]            # NOT suppressed
        assert handled == ["attacker", "leader"]
