"""Randomized differential fuzz: sw ↔ tpu bit-identical accept/reject.

SURVEY §4 asks for adversarial *corpora*, not a fixed case list. Every
batch here is generated from a seeded RNG (override with
FTPU_FUZZ_SEED to explore; failures print the seed + lane recipe) and
asserted ELEMENTWISE equal between the two providers — the contract is
bit-identical decisions (`bccsp/sw/ecdsa.go:41-57` semantics), not
"both mostly work". A curated corpus of previously-interesting shapes
(tests/fuzz_corpus.json) replays on every run.

Classes covered (round-2 verdict list):
  * random DER byte mutations at scale (flips, truncations, splices);
  * hand-encoded boundary scalars incl. r >= n, s >= n, s = half-order,
    the r+n < p wrap branch, r/s = 0/negative;
  * tampered digests / messages (single bit);
  * mixed digest-mode and message-mode lanes in one batch;
  * duplicate keys across lanes + shuffled key appearance order;
  * off-curve / infinity / wrong-curve public keys (import-time parity).
"""

import hashlib
import json
import os
import random

import pytest

from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.utils import (
    decode_dss_signature,
)

from fabric_tpu.bccsp import ECDSAKeyGenOpts, VerifyItem, utils
from fabric_tpu.bccsp.bccsp import ECDSAPublicKeyImportOpts
from fabric_tpu.bccsp.sw import SWProvider
from fabric_tpu.bccsp.tpu import TPUProvider

SEED = int(os.environ.get("FTPU_FUZZ_SEED", "20260731"))
N = utils.P256_N
P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
HALF = utils.P256_HALF_N
CORPUS = os.path.join(os.path.dirname(__file__), "fuzz_corpus.json")


BATCH = 256          # every check() pads to ONE device shape (and one
#                      key-set size), so the whole suite compiles a
#                      single pipeline — CI-budget critical on CPU


class Workbench:
    def __init__(self, seed):
        self.rng = random.Random(seed)
        self.sw = SWProvider()
        self.tpu = TPUProvider(min_batch=1)
        self.keys = [self.sw.key_gen(ECDSAKeyGenOpts(ephemeral=True))
                     for _ in range(4)]
        self._filler = []
        for i in range(BATCH):
            msg = f"filler {i}".encode()
            self._filler.append(VerifyItem(
                key=self.keys[i % 4].public_key(),
                signature=self.sign(i % 4, msg), message=msg))

    def sign(self, ki, msg):
        return self.sw.sign(self.keys[ki],
                            hashlib.sha256(msg).digest())

    def check(self, items, label):
        assert len(items) <= BATCH
        padded = list(items) + self._filler[len(items):]
        got_sw = self.sw.verify_batch(padded)
        got_tpu = self.tpu.verify_batch(padded)
        assert got_tpu == got_sw, (
            f"{label}: divergence at lanes "
            f"{[i for i, (a, b) in enumerate(zip(got_sw, got_tpu)) if a != b]}"
            f" (seed {SEED})")
        assert all(got_sw[len(items):]), f"{label}: filler rejected"
        return got_sw[:len(items)]


@pytest.fixture(scope="module")
def wb():
    return Workbench(SEED)


def _mutate_der(rng, der: bytes) -> bytes:
    der = bytearray(der)
    op = rng.randrange(4)
    if op == 0 and der:                      # bit flip
        i = rng.randrange(len(der))
        der[i] ^= 1 << rng.randrange(8)
    elif op == 1 and len(der) > 2:           # truncate
        der = der[:rng.randrange(1, len(der))]
    elif op == 2:                            # append garbage
        der += bytes(rng.randrange(256)
                     for _ in range(rng.randrange(1, 5)))
    else:                                    # splice two halves
        j = rng.randrange(1, max(2, len(der)))
        der = der[j:] + der[:j]
    return bytes(der)


class TestDERMutationFuzz:
    def test_thousands_of_mutated_signatures(self, wb):
        rng = wb.rng
        rounds, per = 8, 192
        for rnd in range(rounds):
            items = []
            for i in range(per):
                ki = rng.randrange(len(wb.keys))
                msg = bytes(rng.randrange(256)
                            for _ in range(rng.randrange(0, 200)))
                der = wb.sign(ki, msg)
                if i % 4:                     # 75% mutated
                    der = _mutate_der(rng, der)
                items.append(VerifyItem(
                    key=wb.keys[ki].public_key(), signature=der,
                    message=msg))
            got = wb.check(items, f"der-mutation round {rnd}")
            # sanity: the unmutated quarter all accepted
            assert all(got[i] for i in range(0, per, 4))


class TestBoundaryScalars:
    def test_hand_encoded_boundary_r_s(self, wb):
        msg = b"boundary probe"
        scalars = [1, 2, HALF - 1, HALF, HALF + 1, N - 1, N, N + 1,
                   P, P - N - 1, P - N, (1 << 256) - 1]
        items = []
        for r in scalars:
            for s in [1, HALF, N - 1, N]:
                items.append(VerifyItem(
                    key=wb.keys[0].public_key(),
                    signature=utils.marshal_signature(r, s),
                    message=msg))
        # every lane is an invalid signature; both sides must agree
        got = wb.check(items, "boundary scalars")
        assert not any(got)

    def test_r_plus_n_wrap_branch_kernel_parity(self, wb):
        """r < p - n exercises the x(R) == r + n candidate. Real
        signatures with such r are ~2^-32 rare, so drive the device
        decision directly with synthetic r: the device must REJECT
        (premask passes, curve check fails) exactly like sw."""
        small_rs = [1, 2, (P - N) - 1]        # r + n < p holds
        items = [VerifyItem(
            key=wb.keys[0].public_key(),
            signature=utils.marshal_signature(r, HALF - 7),
            message=b"wrap branch") for r in small_rs]
        got = wb.check(items, "r+n wrap")
        assert not any(got)


class TestMixedLanesAndKeys:
    def test_mixed_digest_message_duplicate_keys_shuffled(self, wb):
        rng = wb.rng
        for rnd in range(4):
            items, valid = [], []
            order = [rng.randrange(len(wb.keys)) for _ in range(128)]
            for i, ki in enumerate(order):
                msg = f"mix {rnd} {i}".encode() * rng.randrange(1, 9)
                der = wb.sign(ki, msg)
                ok = True
                mode = rng.randrange(4)
                if mode == 0:                 # digest lane
                    item = VerifyItem(
                        key=wb.keys[ki].public_key(), signature=der,
                        digest=hashlib.sha256(msg).digest())
                elif mode == 1:               # tampered digest bit
                    d = bytearray(hashlib.sha256(msg).digest())
                    d[rng.randrange(32)] ^= 1 << rng.randrange(8)
                    item = VerifyItem(
                        key=wb.keys[ki].public_key(), signature=der,
                        digest=bytes(d))
                    ok = False
                elif mode == 2:               # message lane
                    item = VerifyItem(
                        key=wb.keys[ki].public_key(), signature=der,
                        message=msg)
                else:                         # wrong key lane
                    other = (ki + 1) % len(wb.keys)
                    item = VerifyItem(
                        key=wb.keys[other].public_key(), signature=der,
                        message=msg)
                    ok = False
                items.append(item)
                valid.append(ok)
            got = wb.check(items, f"mixed lanes round {rnd}")
            assert got == valid, f"seed {SEED} round {rnd}"

    def test_high_s_twins_rejected_identically(self, wb):
        items = []
        for i in range(32):
            msg = f"high-s {i}".encode()
            r, s = decode_dss_signature(wb.sign(i % 4, msg))
            items.append(VerifyItem(
                key=wb.keys[i % 4].public_key(),
                signature=utils.marshal_signature(r, N - s),
                message=msg))
        got = wb.check(items, "high-s twins")
        assert not any(got)


class TestBadAndForeignKeys:
    def test_off_curve_point_unconstructible(self):
        """Off-curve/infinity points cannot enter either provider: the
        EC point validation happens at key construction (the reference
        gets the same guarantee from elliptic.Unmarshal)."""
        from cryptography.hazmat.primitives.asymmetric.ec import (
            EllipticCurvePublicNumbers,
        )
        good = ec.generate_private_key(
            ec.SECP256R1()).public_key().public_numbers()
        with pytest.raises(Exception):
            EllipticCurvePublicNumbers(
                good.x, (good.y + 1) % P, ec.SECP256R1()).public_key()

    def test_p384_lanes_match_sw_without_batch_degradation(self, wb):
        """Found by this fuzz in round 3: P-384 keys import fine (the
        reference supports Security: 384) but the old low-S gate used
        the P-256 half-order, rejecting ALL P-384 signatures, and a
        P-384 coordinate overflowed the TPU batch packing, degrading
        the WHOLE batch to sw. Now: per-curve half-orders, per-LANE sw
        fallback."""
        from cryptography.hazmat.primitives.asymmetric.ec import SECP384R1
        from fabric_tpu.bccsp.bccsp import ECDSAPrivateKeyImportOpts

        p384_priv = wb.sw.key_import(
            ec.generate_private_key(SECP384R1()),
            ECDSAPrivateKeyImportOpts())
        p384_pub = wb.tpu.key_import(
            p384_priv.raw.public_key(), ECDSAPublicKeyImportOpts())
        items = []
        expected = []
        for i in range(16):
            if i % 4 == 1:          # valid P-384 lane
                msg = f"p384 {i}".encode()
                sig = wb.sw.sign(p384_priv, hashlib.sha256(msg).digest())
                items.append(VerifyItem(key=p384_pub, signature=sig,
                                        message=msg))
                expected.append(True)
            elif i % 4 == 3:        # P-384 key, tampered message
                msg = f"p384 bad {i}".encode()
                sig = wb.sw.sign(p384_priv, hashlib.sha256(msg).digest())
                items.append(VerifyItem(key=p384_pub, signature=sig,
                                        message=msg + b"!"))
                expected.append(False)
            else:                   # normal P-256 lane
                msg = f"p256 {i}".encode()
                items.append(VerifyItem(
                    key=wb.keys[i % 4].public_key(),
                    signature=wb.sign(i % 4, msg), message=msg))
                expected.append(True)
        # plus a 48-byte (SHA-384) precomputed-digest lane: must route
        # to sw per-lane, not crash the device batch
        msg = b"p384 sha384 digest lane"
        d48 = hashlib.sha384(msg).digest()
        items.append(VerifyItem(key=p384_pub,
                                signature=wb.sw.sign(p384_priv, d48),
                                digest=d48))
        expected.append(True)
        before = wb.tpu.stats["sw_fallbacks"]
        got = wb.check(items, "p384 mixed lanes")
        assert got == expected
        assert wb.tpu.stats["sw_fallbacks"] == before   # no whole-batch
        assert wb.tpu.stats["nonp256_sw_lanes"] >= 9


class TestCorpusRegression:
    def test_replay_recorded_corpus(self, wb):
        """Curated signature byte-strings that exercised interesting
        parser states; replayed verbatim every run."""
        if not os.path.exists(CORPUS):
            pytest.skip("no corpus file")
        with open(CORPUS) as f:
            corpus = json.load(f)
        msg = b"corpus replay"
        items = [VerifyItem(key=wb.keys[0].public_key(),
                            signature=bytes.fromhex(entry["der"]),
                            message=msg)
                 for entry in corpus]
        wb.check(items, "corpus replay")
