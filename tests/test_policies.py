"""Policy engine tests — mirrors the reference's
`common/cauthdsl/cauthdsl_test.go`, `policydsl_test.go`,
`implicitmeta_test.go` shapes, plus the batched signature-set path."""

import pytest

from fabric_tpu.bccsp.bccsp import ECDSAPrivateKeyImportOpts
from fabric_tpu.bccsp.sw import SWProvider
from fabric_tpu.common.policies import (
    ImplicitMetaPolicy,
    Manager,
    PolicyError,
    SignaturePolicy,
    from_string,
    signature_set_to_valid_identities,
)
from fabric_tpu.common.policies.policydsl import PolicyParseError
from fabric_tpu.msp import Manager as MSPManager, X509MSP, build_msp_config
from fabric_tpu.protos import msp as msppb, policies as polpb
from fabric_tpu.protoutil import SignedData
from tests import certgen


@pytest.fixture(scope="module")
def orgs():
    """Three orgs, one signer each, one shared MSP manager + csp."""
    csp = SWProvider()
    mgr = MSPManager()
    msps = []
    world = {}
    for org in ("Org1", "Org2", "Org3"):
        root, root_key = certgen.make_self_signed(f"{org.lower()}-ca")
        leaf, leaf_key = certgen.make_leaf("signer", root, root_key)
        admin, admin_key = certgen.make_leaf("admin", root, root_key)
        msp = X509MSP(csp)
        msp.setup(build_msp_config(
            name=f"{org}MSP",
            root_certs=[certgen.pem(root)],
            admins=[certgen.pem(admin)],
        ))
        msps.append(msp)
        priv = csp.key_import(leaf_key, ECDSAPrivateKeyImportOpts())
        apriv = csp.key_import(admin_key, ECDSAPrivateKeyImportOpts())
        sid = msppb.SerializedIdentity(
            mspid=f"{org}MSP", id_bytes=certgen.pem(leaf))
        asid = msppb.SerializedIdentity(
            mspid=f"{org}MSP", id_bytes=certgen.pem(admin))
        world[org] = {
            "sid": sid.SerializeToString(deterministic=True),
            "asid": asid.SerializeToString(deterministic=True),
            "priv": priv, "apriv": apriv,
        }
    mgr.setup(msps)
    world["mgr"] = mgr
    world["csp"] = csp
    return world


def _signed(orgs, org, msg, admin=False, garbage=False):
    csp = orgs["csp"]
    o = orgs[org]
    key = o["apriv"] if admin else o["priv"]
    sig = b"\x01bad" if garbage else csp.sign(key, csp.hash(msg))
    return SignedData(data=msg, identity=o["asid"] if admin else o["sid"],
                      signature=sig)


class TestPolicyDSL:
    def test_and_or_outof(self):
        env = from_string("AND('Org1.member', OR('Org2.member', "
                          "'Org3.admin'))")
        assert env.rule.n_out_of.n == 2
        assert len(env.rule.n_out_of.rules) == 2
        assert env.rule.n_out_of.rules[1].n_out_of.n == 1
        assert len(env.identities) == 3
        role = polpb.MSPRole()
        role.ParseFromString(env.identities[2].principal)
        assert role.msp_identifier == "Org3MSP" or \
            role.msp_identifier == "Org3"

    def test_outof(self):
        env = from_string("OutOf(2, 'Org1.member', 'Org2.member', "
                          "'Org3.member')")
        assert env.rule.n_out_of.n == 2
        assert len(env.rule.n_out_of.rules) == 3

    def test_duplicate_principals_are_shared(self):
        env = from_string("OR('Org1.member', 'Org1.member')")
        assert len(env.identities) == 1

    def test_dotted_mspid(self):
        env = from_string("OR('org.example.com.member')")
        role = polpb.MSPRole()
        role.ParseFromString(env.identities[0].principal)
        assert role.msp_identifier == "org.example.com"
        assert role.role == polpb.MSPRole.MEMBER

    @pytest.mark.parametrize("bad", [
        "", "AND()", "AND('Org1.member'", "XOR('A.member','B.member')",
        "'Org1.wizard'", "'no-dot'", "OutOf('Org1.member')",
        "OutOf(3, 'Org1.member')", "AND('A.member') garbage",
        "OutOf(0, 'Org1.member')",   # n=0 would be fail-open
    ])
    def test_malformed_rejected(self, bad):
        with pytest.raises(PolicyParseError):
            from_string(bad)


class TestSignaturePolicy:
    def _policy(self, orgs, spec):
        env = from_string(spec)
        # the DSL writes bare org names; our fixture MSP ids end in MSP
        for p in env.identities:
            role = polpb.MSPRole()
            role.ParseFromString(p.principal)
            if not role.msp_identifier.endswith("MSP"):
                role.msp_identifier += "MSP"
                p.principal = role.SerializeToString()
        return SignaturePolicy(env, orgs["mgr"], orgs["csp"])

    def test_two_of_three(self, orgs):
        pol = self._policy(
            orgs, "OutOf(2, 'Org1.member', 'Org2.member', 'Org3.member')")
        msg = b"the block payload"
        pol.evaluate_signed_data([
            _signed(orgs, "Org1", msg), _signed(orgs, "Org2", msg)])
        with pytest.raises(PolicyError):
            pol.evaluate_signed_data([_signed(orgs, "Org1", msg)])

    def test_bad_signature_drops_identity(self, orgs):
        pol = self._policy(orgs, "AND('Org1.member', 'Org2.member')")
        msg = b"payload"
        with pytest.raises(PolicyError):
            pol.evaluate_signed_data([
                _signed(orgs, "Org1", msg),
                _signed(orgs, "Org2", msg, garbage=True)])

    def test_one_identity_cannot_satisfy_two_leaves(self, orgs):
        """The `used` semantics: AND('Org1.member','Org1.member') needs
        two DISTINCT Org1 signers (reference cauthdsl used-vector)."""
        pol = self._policy(orgs, "AND('Org1.member', 'Org1.member')")
        msg = b"payload"
        with pytest.raises(PolicyError):
            pol.evaluate_signed_data([_signed(orgs, "Org1", msg)])
        # member + admin of Org1 are two distinct identities
        pol.evaluate_signed_data([
            _signed(orgs, "Org1", msg),
            _signed(orgs, "Org1", msg, admin=True)])

    def test_admin_role(self, orgs):
        pol = self._policy(orgs, "AND('Org1.admin')")
        msg = b"cfg update"
        pol.evaluate_signed_data([_signed(orgs, "Org1", msg, admin=True)])
        with pytest.raises(PolicyError):
            pol.evaluate_signed_data([_signed(orgs, "Org1", msg)])

    def test_duplicate_signed_data_deduped(self, orgs):
        sd = _signed(orgs, "Org1", b"m")
        idents = signature_set_to_valid_identities(
            [sd, sd, sd], orgs["mgr"], orgs["csp"])
        assert len(idents) == 1

    def test_unknown_identity_skipped(self, orgs):
        sd = SignedData(data=b"m", identity=b"not-an-identity",
                        signature=b"x")
        assert signature_set_to_valid_identities(
            [sd], orgs["mgr"], orgs["csp"]) == []


class TestImplicitMeta:
    def _org_manager(self, orgs, org):
        env = from_string(f"OR('{org}.member')")
        role = polpb.MSPRole()
        role.ParseFromString(env.identities[0].principal)
        role.msp_identifier += "MSP"
        env.identities[0].principal = role.SerializeToString()
        pol = SignaturePolicy(env, orgs["mgr"], orgs["csp"])
        return Manager(name=org, policies={"Writers": pol})

    def test_majority(self, orgs):
        managers = [self._org_manager(orgs, o)
                    for o in ("Org1", "Org2", "Org3")]
        meta = polpb.ImplicitMetaPolicy(
            sub_policy="Writers", rule=polpb.ImplicitMetaPolicy.MAJORITY)
        pol = ImplicitMetaPolicy.from_managers(meta, managers)
        msg = b"tx"
        pol.evaluate_signed_data([
            _signed(orgs, "Org1", msg), _signed(orgs, "Org2", msg)])
        with pytest.raises(PolicyError, match="needed 2"):
            pol.evaluate_signed_data([_signed(orgs, "Org1", msg)])

    def test_all_and_any(self, orgs):
        managers = [self._org_manager(orgs, o) for o in ("Org1", "Org2")]
        msg = b"tx"
        any_pol = ImplicitMetaPolicy.from_managers(
            polpb.ImplicitMetaPolicy(
                sub_policy="Writers", rule=polpb.ImplicitMetaPolicy.ANY),
            managers)
        any_pol.evaluate_signed_data([_signed(orgs, "Org2", msg)])
        all_pol = ImplicitMetaPolicy.from_managers(
            polpb.ImplicitMetaPolicy(
                sub_policy="Writers", rule=polpb.ImplicitMetaPolicy.ALL),
            managers)
        with pytest.raises(PolicyError):
            all_pol.evaluate_signed_data([_signed(orgs, "Org2", msg)])

    def test_any_over_nothing_fails_closed(self):
        meta = polpb.ImplicitMetaPolicy(
            sub_policy="Writers", rule=polpb.ImplicitMetaPolicy.ANY)
        pol = ImplicitMetaPolicy(meta, [])
        with pytest.raises(PolicyError):
            pol.evaluate_signed_data([])

    def test_all_over_nothing_passes_vacuously(self):
        # reference implicitmeta.go: remaining == 0 -> nil
        meta = polpb.ImplicitMetaPolicy(
            sub_policy="Writers", rule=polpb.ImplicitMetaPolicy.ALL)
        ImplicitMetaPolicy(meta, []).evaluate_signed_data([])

    def test_converter_batches_once(self, orgs):
        """With a converter, K sub-policies trigger exactly ONE
        verify_batch dispatch over the signature set."""
        managers = [self._org_manager(orgs, o)
                    for o in ("Org1", "Org2", "Org3")]
        meta = polpb.ImplicitMetaPolicy(
            sub_policy="Writers", rule=polpb.ImplicitMetaPolicy.MAJORITY)
        calls = {"n": 0}
        csp = orgs["csp"]
        orig = csp.verify_batch

        def counting(items):
            calls["n"] += 1
            return orig(items)
        csp.verify_batch = counting
        try:
            pol = ImplicitMetaPolicy.from_managers(
                meta, managers, converter=(orgs["mgr"], csp))
            msg = b"tx"
            pol.evaluate_signed_data([
                _signed(orgs, "Org1", msg), _signed(orgs, "Org2", msg)])
        finally:
            csp.verify_batch = orig
        assert calls["n"] == 1


class TestManager:
    def test_path_routing(self, orgs):
        writers = self._dummy_policy()
        app = Manager(name="Application", policies={"Writers": writers})
        channel = Manager(name="Channel", sub_managers={"Application": app})
        assert channel.get_policy("/Channel/Application/Writers") is writers
        assert channel.get_policy("Application/Writers") is writers
        assert app.get_policy("Writers") is writers
        assert not channel.has_policy("/Channel/Application/Nope")
        with pytest.raises(PolicyError, match="does not start"):
            channel.get_policy("/Other/Application/Writers")

    @staticmethod
    def _dummy_policy():
        class Always:
            def evaluate_signed_data(self, sd):
                pass

            def evaluate_identities(self, ids):
                pass
        return Always()
