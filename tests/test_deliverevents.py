"""Peer deliver event-stream tests: filtered blocks + block-with-pvtdata.

Reference behavior pinned: `core/peer/deliverevents.go` —
DeliverFiltered strips event payloads and carries per-tx verdicts;
DeliverWithPrivateData attaches held cleartext collections, filtered by
the requester's collection membership.
"""

import os

import pytest

from fabric_tpu.bccsp.sw import SWProvider
from fabric_tpu.common.deliver import DeliverHandler
from fabric_tpu.core.chaincode import Chaincode, ChaincodeDefinition, shim
from fabric_tpu.internal import cryptogen
from fabric_tpu.internal.configtxgen import genesis_block, new_channel_group
from fabric_tpu.ledger.pvtdata import CollectionConfig
from fabric_tpu.msp import msp_config_from_dir
from fabric_tpu.msp.mspimpl import X509MSP
from fabric_tpu.orderer import solo
from fabric_tpu.orderer.broadcast import BroadcastHandler
from fabric_tpu.orderer.multichannel import Registrar
from fabric_tpu.peer import Peer
from fabric_tpu.peer.deliverclient import Deliverer, seek_envelope
from fabric_tpu.peer.deliverevents import EventsDeliverHandler
from fabric_tpu.protos import common, transaction as txpb

CHANNEL = "evchannel"


class EvCC(Chaincode):
    def init(self, stub):
        return shim.success()

    def invoke(self, stub):
        fn, params = stub.get_function_and_parameters()
        if fn == "put":
            stub.put_state(params[0], params[1].encode())
            stub.set_event("put-event", b"secret-payload")
            return shim.success()
        if fn == "pvt":
            stub.put_private_data("col1", params[0], params[1].encode())
            return shim.success()
        return shim.error("unknown")


@pytest.fixture(scope="module")
def net(tmp_path_factory):
    root = tmp_path_factory.mktemp("ev")
    cdir = str(root / "crypto")
    org1 = cryptogen.generate_org(cdir, "org1.example.com", n_peers=1,
                                  n_users=1)
    org2 = cryptogen.generate_org(cdir, "org2.example.com", n_peers=1,
                                  n_users=1)
    ordo = cryptogen.generate_org(cdir, "example.com", orderer_org=True)
    profile = {
        "Consortium": "SampleConsortium",
        "Capabilities": {"V2_0": True},
        "Application": {
            "Organizations": [
                {"Name": "Org1", "ID": "Org1MSP",
                 "MSPDir": os.path.join(org1, "msp")},
                {"Name": "Org2", "ID": "Org2MSP",
                 "MSPDir": os.path.join(org2, "msp")},
            ],
            "Capabilities": {"V2_0": True},
        },
        "Orderer": {
            "OrdererType": "solo",
            "Addresses": ["orderer0.example.com:7050"],
            "BatchTimeout": "100ms",
            "BatchSize": {"MaxMessageCount": 10},
            "Organizations": [
                {"Name": "OrdererOrg", "ID": "OrdererMSP",
                 "MSPDir": os.path.join(ordo, "msp"),
                 "OrdererEndpoints": ["orderer0.example.com:7050"]}],
            "Capabilities": {"V2_0": True},
        },
    }
    genesis = genesis_block(CHANNEL, new_channel_group(profile))
    csp = SWProvider()

    def local_msp(d, mspid):
        m = X509MSP(csp)
        m.setup(msp_config_from_dir(d, mspid, csp=csp))
        return m

    omsp = local_msp(os.path.join(ordo, "orderers",
                                  "orderer0.example.com", "msp"),
                     "OrdererMSP")
    reg = Registrar(str(root / "ord"),
                    omsp.get_default_signing_identity(), csp,
                    {"solo": solo.consenter})
    reg.join(genesis)
    broadcast = BroadcastHandler(reg)
    deliver = DeliverHandler(reg.get_chain)

    # col1 is Org1-members-only; cc policy Org1-member so the single
    # org1 peer can endorse alone
    from fabric_tpu.core.policycheck import org_member_policy_bytes
    definition = ChaincodeDefinition(
        name="ev",
        endorsement_policy=org_member_policy_bytes("Org1MSP"),
        collections=(CollectionConfig(name="col1",
                                      member_orgs=("Org1MSP",)),))
    msp1 = local_msp(os.path.join(org1, "peers",
                                  "peer0.org1.example.com", "msp"),
                     "Org1MSP")
    peer = Peer(str(root / "peer1"), msp1, csp)
    ch = peer.join_channel(genesis)
    peer.chaincode_support.register("ev", EvCC())
    ch.define_chaincode(definition)
    d = Deliverer(ch, peer.signer, lambda: deliver, peer.mcs)
    d.start()

    from fabric_tpu.peer.gateway import Gateway
    user1 = local_msp(os.path.join(org1, "users",
                                   "User1@org1.example.com", "msp"),
                      "Org1MSP")
    user2 = local_msp(os.path.join(org2, "users",
                                   "User1@org2.example.com", "msp"),
                      "Org2MSP")
    gw = Gateway(peer, broadcast, user1.get_default_signing_identity())

    r = gw.submit_transaction(CHANNEL, "ev", [b"put", b"a", b"1"])
    assert r.status == txpb.TxValidationCode.VALID
    r = gw.submit_transaction(CHANNEL, "ev", [b"pvt", b"p", b"2"])
    assert r.status == txpb.TxValidationCode.VALID

    yield {"peer": peer, "ch": ch,
           "signer1": user1.get_default_signing_identity(),
           "signer2": user2.get_default_signing_identity()}
    d.stop()
    reg.halt()
    peer.close()


def _collect(stream, want_blocks):
    """Drain `want_blocks` data items + the trailing cursor position."""
    out = []
    for resp in stream:
        which = resp.WhichOneof("type")
        if which == "status":
            break
        out.append(resp)
        if len(out) >= want_blocks:
            break
    return out


class TestFilteredStream:
    def test_filtered_blocks_carry_verdicts_not_payloads(self, net):
        h = EventsDeliverHandler(
            lambda cid: net["ch"] if cid == CHANNEL else None)
        env = seek_envelope(CHANNEL, 0, net["signer1"],
                            stop=net["ch"].ledger.height - 1)
        got = _collect(h.handle_filtered(env), net["ch"].ledger.height)
        assert got, "no filtered blocks streamed"
        fbs = [r.filtered_block for r in got]
        assert fbs[0].channel_id == CHANNEL
        assert [fb.number for fb in fbs] == list(range(len(fbs)))
        # find the endorser tx that set an event
        events = [
            (ft.txid, ft.tx_validation_code, fca.chaincode_event)
            for fb in fbs
            for ft in fb.filtered_transactions
            for fca in ft.transaction_actions.chaincode_actions
            if ft.type == common.HeaderType.ENDORSER_TRANSACTION
        ]
        named = [e for _, _, e in events if e.event_name == "put-event"]
        assert named, "put-event missing from the filtered stream"
        assert named[0].chaincode_id == "ev"
        assert named[0].payload == b"", "payload must be stripped"
        assert all(code == txpb.TxValidationCode.VALID
                   for _, code, _ in events)


class TestBlockWithPrivateData:
    def _pvt_stream(self, net, signer):
        h = EventsDeliverHandler(
            lambda cid: net["ch"] if cid == CHANNEL else None)
        env = seek_envelope(CHANNEL, 0, signer,
                            stop=net["ch"].ledger.height - 1)
        return _collect(h.handle_with_pvtdata(env),
                        net["ch"].ledger.height)

    def test_member_sees_cleartext(self, net):
        got = self._pvt_stream(net, net["signer1"])
        maps = [r.block_and_private_data.private_data_map for r in got]
        colls = [
            coll.collection_name
            for m in maps for txpvt in m.values()
            for ns in txpvt.ns_pvt_rwset
            for coll in ns.collection_pvt_rwset
        ]
        assert "col1" in colls

    def test_non_member_collections_filtered_out(self, net):
        got = self._pvt_stream(net, net["signer2"])
        assert got, "org2 reader should still receive blocks"
        maps = [r.block_and_private_data.private_data_map for r in got]
        assert all(len(m) == 0 for m in maps), \
            "org2 must not receive org1-only collection cleartext"
