"""Pipelined block intake (ISSUE 4 tentpole): parity + barriers.

The contract under test: the CommitPipeline (validate block N+1 on
stage A while block N commits on stage B) produces BIT-IDENTICAL
per-tx validation codes, TRANSACTIONS_FILTER bytes and commit hashes
to the sequential `Channel.process_block` path — on a mixed stream
containing a config block, a validation-parameter-style state update,
and a duplicate txid across adjacent in-flight blocks — and that
every failure mode degrades to the sequential path rather than a
wrong answer:

  * config-block / state-update barriers drain the pipeline so
    validate-ahead never reads a stale bundle or stale state;
  * a stage-A fault (`commit.validate_ahead` / `commit.barrier`)
    demotes the block to the sequential fallback on the commit
    worker;
  * speculative validation publishes nothing early — a crash between
    validate(N+1) and commit(N) leaves no trace and replays
    identically through the real block store;
  * a forged block rejects (sticky CommitPipelineError) and reset()
    recovers to the committed height.

Wheel-free per the PR 3 idiom: a stub validator whose verdicts depend
on COMMITTED state + the adopted config (the exact dependencies the
barriers exist for) over the REAL `peer.Channel` commit glue, REAL
KVLedger, REAL LedgerCommitter and REAL CommitPipeline.
"""

import os
import threading
import time

import pytest

from fabric_tpu import protoutil as pu
from fabric_tpu.common import faults
from fabric_tpu.core.commitpipeline import (
    CommitPipeline,
    CommitPipelineError,
)
from fabric_tpu.core.committer import LedgerCommitter
from fabric_tpu.core.txvalidator import ValidationResult
from fabric_tpu.ledger import KVLedger
from fabric_tpu.ledger.kvdb import DBHandle, KVStore
from fabric_tpu.ledger.kvledger import extract_tx_rwset
from fabric_tpu.ledger.statedb import StateDB
from fabric_tpu.ledger.txmgr import TxSimulator
from fabric_tpu.peer import peer as peer_mod
from fabric_tpu.peer.mcs import BlockVerificationError
from fabric_tpu.protos import common, proposal as proppb
from fabric_tpu.protos import transaction as txpb

TVC = txpb.TxValidationCode
CHANNEL = "pipech"
CC = "mycc"


class FakeSigner:
    def __init__(self, identity=b"endorser"):
        self._id = identity

    def serialize(self):
        return self._id

    def sign(self, msg):
        import hashlib
        return hashlib.sha256(self._id + msg).digest()


# ---------------------------------------------------------------- streams

def _tx_env(scratch_db: StateDB, key: str, value: bytes = b"v"
            ) -> tuple[bytes, str]:
    """A committed-format endorser tx writing one key (write-only
    rwset: immune to MVCC, so verdicts are purely the stub
    validator's)."""
    sim = TxSimulator(scratch_db, "sim")
    sim.put_state(CC, key, value)
    results = pu.marshal(sim.get_tx_simulation_results())
    prop, tx_id = pu.create_proposal(CHANNEL, CC, [b"invoke"],
                                     creator=b"client")
    resp = proppb.Response(status=200)
    presp = pu.create_proposal_response(
        pu.marshal(prop), results, b"", resp,
        proppb.ChaincodeID(name=CC), FakeSigner())
    env = pu.create_signed_tx(prop, [presp], FakeSigner(b"client"))
    return pu.marshal(env), tx_id


def _config_env(mode: bytes) -> bytes:
    """A CONFIG-typed envelope whose payload data carries the stub
    "mode" the validator adopts at commit (the bundle-update analog)."""
    ch = pu.make_channel_header(common.HeaderType.CONFIG, CHANNEL)
    sh = pu.create_signature_header(b"orderer", pu.random_nonce())
    payload = pu.make_payload(ch, sh, mode)
    return pu.marshal(common.Envelope(payload=pu.marshal(payload)))


def _chain_blocks(env_lists: list[list[bytes]]) -> list[bytes]:
    """Serialize a header-chained stream: genesis (config, mode A) +
    one block per env list. Returned raw so each twin parses private
    copies."""
    blocks = []
    genesis = pu.new_block(0, b"")
    genesis.data.data.append(_config_env(b"A"))
    genesis.header.data_hash = pu.block_data_hash(genesis.data)
    blocks.append(genesis)
    for envs in env_lists:
        prev = blocks[-1]
        blk = pu.new_block(prev.header.number + 1,
                           pu.block_header_hash(prev.header))
        for e in envs:
            blk.data.data.append(e)
        blk.header.data_hash = pu.block_data_hash(blk.data)
        blocks.append(blk)
    return [b.SerializeToString() for b in blocks]


# ------------------------------------------------------------- the seam

class SeamValidator:
    """State- and config-dependent verdicts over the real commit glue.

    Rules (per written key):
      need_mode_B:*     VALID only when the ADOPTED config mode is B
      need_policy_v2:*  VALID only when committed state __policy==v2
      __policy          always VALID; marks the block vp_dirty (the
                        BlockOverlay / record_valid analog)
    plus the duplicate-txid rule over known_txids + the ledger index.
    Validating ahead of the governing commit therefore yields WRONG
    codes — exactly what the pipeline barriers must prevent.
    """

    def __init__(self, ledger):
        self._ledger = ledger
        self.mode = b"A"
        self.calls: list[int] = []          # block numbers validated

    def adopt_config(self, block):
        env = pu.extract_envelope(block, 0)
        self.mode = pu.get_payload(env).data

    def _tx_code(self, env_bytes: bytes, known: set,
                 seen: set) -> tuple[int, str]:
        try:
            env = pu.unmarshal_envelope(env_bytes)
            ch = pu.get_channel_header(pu.get_payload(env))
        except Exception:
            return TVC.MARSHAL_TX_ERROR, ""
        if ch.type == common.HeaderType.CONFIG:
            return TVC.VALID, ""
        tx_id = ch.tx_id
        if tx_id in seen or tx_id in known or \
                self._ledger.get_transaction_by_id(tx_id) is not None:
            return TVC.DUPLICATE_TXID, tx_id
        seen.add(tx_id)
        txrw = extract_tx_rwset(env_bytes)
        if txrw is None:
            return TVC.INVALID_ENDORSER_TRANSACTION, tx_id
        for nsrw in txrw.ns_rwset:
            from fabric_tpu.protos import rwset as rwpb
            kv = rwpb.KVRWSet()
            kv.ParseFromString(nsrw.rwset)
            for w in kv.writes:
                if w.key.startswith("need_mode_B:") and \
                        self.mode != b"B":
                    return TVC.ENDORSEMENT_POLICY_FAILURE, tx_id
                if w.key.startswith("need_policy_v2:") and \
                        self._ledger.get_state(CC, "__policy") != b"v2":
                    return TVC.ENDORSEMENT_POLICY_FAILURE, tx_id
        return TVC.VALID, tx_id

    def validate_ahead(self, block, known_txids=None
                       ) -> ValidationResult:
        t0 = time.perf_counter()
        known = set(known_txids or ())
        seen: set = set()
        codes = []
        vp_dirty = False
        for env_bytes in block.data.data:
            code, _tx = self._tx_code(env_bytes, known, seen)
            codes.append(code)
            if code == TVC.VALID:
                txrw = extract_tx_rwset(env_bytes)
                if txrw is not None and any(
                        w.key == "__policy"
                        for nsrw in txrw.ns_rwset
                        for w in _kv(nsrw.rwset).writes):
                    vp_dirty = True
        self.calls.append(block.header.number)
        return ValidationResult(codes=codes, n_items=len(codes),
                                duration_s=time.perf_counter() - t0,
                                vp_dirty=vp_dirty)

    def publish_validation(self, block, result) -> None:
        while len(block.metadata.metadata) <= \
                common.BlockMetadataIndex.TRANSACTIONS_FILTER:
            block.metadata.metadata.append(b"")
        block.metadata.metadata[
            common.BlockMetadataIndex.TRANSACTIONS_FILTER] = \
            bytes(result.codes)

    def validate(self, block):
        result = self.validate_ahead(block)
        self.publish_validation(block, result)
        return result.codes


def _kv(raw):
    from fabric_tpu.protos import rwset as rwpb
    kv = rwpb.KVRWSet()
    kv.ParseFromString(raw)
    return kv


class _StubTransientStore:
    def get(self, tx_id):
        return None

    def purge_by_txids(self, tx_ids):
        pass


class _StubPeer:
    def __init__(self):
        self.transient_store = _StubTransientStore()


def make_seam_channel(root: str, name: str = CHANNEL):
    """A real `peer.Channel` (commit glue, metrics, notification)
    over a real KVLedger, skipping the Bundle-building __init__ —
    the wheel-free seam."""
    from fabric_tpu.common import metrics as _pm
    ledger = KVLedger(name, os.path.join(root, name))
    ch = peer_mod.Channel.__new__(peer_mod.Channel)
    ch.channel_id = name
    ch.ledger = ledger
    ch._peer = _StubPeer()
    ch._lock = threading.Lock()
    ch._commit_listeners = []
    ch._commit_cond = threading.Condition()
    ch.commit_pipeline = None
    validator = SeamValidator(ledger)
    ch.validator = validator
    ch.committer = LedgerCommitter(
        ledger, on_config_block=validator.adopt_config)
    prov = _pm.DisabledProvider()
    ch._m_pvt_commit = prov.new_histogram(
        peer_mod.PVT_COMMIT_BLOCK_DURATION).with_labels(
        "channel", name)
    ch._m_pvt_pull = prov.new_histogram(
        peer_mod.PVT_PULL_DURATION).with_labels("channel", name)
    ch._m_pvt_purge = prov.new_histogram(
        peer_mod.PVT_PURGE_DURATION).with_labels("channel", name)
    return ch


def _parse(raw: bytes) -> common.Block:
    blk = common.Block()
    blk.ParseFromString(raw)
    return blk


def _filters(ledger, upto: int) -> list[bytes]:
    out = []
    for n in range(1, upto):
        blk = ledger.block_store.get_block_by_number(n)
        out.append(bytes(blk.metadata.metadata[
            common.BlockMetadataIndex.TRANSACTIONS_FILTER]))
    return out


@pytest.fixture()
def stream(tmp_path):
    """Genesis + 5 blocks exercising every barrier:
      b1  plain writes (incl. txid T1)
      b2  __policy=v2 (vp_dirty)  +  duplicate of T1 (adjacent-block
          dup: caught only via known_txids threading)
      b3  need_policy_v2 txs — correct ONLY after b2's state commit
      b4  config block: mode B
      b5  need_mode_B txs — correct ONLY after b4's adoption
    """
    scratch = StateDB(DBHandle(KVStore(
        str(tmp_path / "scratch.db")), "s"))
    e_plain1, _t1 = _tx_env(scratch, "k1")
    dup_env = e_plain1            # same bytes, same txid
    e_plain2, _ = _tx_env(scratch, "k2")
    e_policy, _ = _tx_env(scratch, "__policy", b"v2")
    e_need_p1, _ = _tx_env(scratch, "need_policy_v2:a")
    e_need_p2, _ = _tx_env(scratch, "need_policy_v2:b")
    e_need_b1, _ = _tx_env(scratch, "need_mode_B:a")
    e_plain3, _ = _tx_env(scratch, "k3")
    return _chain_blocks([
        [e_plain1, e_plain2],
        [e_policy, dup_env],
        [e_need_p1, e_need_p2],
        [_config_env(b"B")],
        [e_need_b1, e_plain3],
    ])


def _run_sequential(tmp_path, stream, sub="seq"):
    ch = make_seam_channel(str(tmp_path / sub))
    ch.ledger.initialize_from_genesis(_parse(stream[0]))
    codes = [ch.process_block(_parse(raw)) for raw in stream[1:]]
    return ch, codes


class TestParity:
    def test_mixed_stream_bit_identical(self, tmp_path, stream):
        faults.clear()      # pins fallback/barrier counts
        seq_ch, seq_codes = _run_sequential(tmp_path, stream)

        pipe_ch = make_seam_channel(str(tmp_path / "pipe"))
        pipe_ch.ledger.initialize_from_genesis(_parse(stream[0]))
        committed = []
        pipeline = CommitPipeline(
            pipe_ch, depth=1,
            on_committed=lambda s, b, c: committed.append((s, c)))
        try:
            for i, raw in enumerate(stream[1:], start=1):
                pipeline.submit(i, raw=raw)
            pipeline.drain(timeout=30)
        finally:
            pipeline.stop()

        assert [c for _s, c in sorted(committed)] == seq_codes
        # TRANSACTIONS_FILTER bytes off the stored blocks
        assert _filters(pipe_ch.ledger, 6) == _filters(seq_ch.ledger, 6)
        # commit-hash chain: the strongest equality — every code byte
        # and data hash of every block matched
        assert pipe_ch.ledger.commit_hash == seq_ch.ledger.commit_hash
        assert pipe_ch.ledger.height == seq_ch.ledger.height == 6

        # the interesting verdicts actually happened:
        flat = [c for blk in seq_codes for c in blk]
        assert TVC.DUPLICATE_TXID in flat
        assert all(c == TVC.VALID for c in seq_codes[2])   # b3 post-VP
        assert all(c == TVC.VALID for c in seq_codes[4])   # b5 post-cfg
        # barriers fired for the vp update and the config block
        assert pipeline.stats["barriers"] >= 2
        assert pipeline.stats["validated_ahead"] == 5
        assert pipeline.stats["fallbacks"] == 0
        seq_ch.ledger.close()
        pipe_ch.ledger.close()

    def test_without_barrier_codes_would_differ(self, tmp_path, stream):
        """The control experiment: validating b3/b5 BEFORE their
        governing commits yields different codes — proof the barriers
        are load-bearing, not decorative."""
        ch = make_seam_channel(str(tmp_path / "ctl"))
        ch.ledger.initialize_from_genesis(_parse(stream[0]))
        # validate b3 against genesis state (no __policy committed)
        early = ch.validator.validate_ahead(_parse(stream[3]))
        assert TVC.ENDORSEMENT_POLICY_FAILURE in early.codes
        # validate b5 against mode A
        early5 = ch.validator.validate_ahead(_parse(stream[5]))
        assert TVC.ENDORSEMENT_POLICY_FAILURE in early5.codes
        ch.ledger.close()

    def test_overlap_is_measured(self, tmp_path, stream):
        """With a slowed commit, validate(N+1) demonstrably runs
        inside commit(N)'s window: overlap_ratio > 0."""
        faults.clear()      # pins committed/overlap stats
        ch = make_seam_channel(str(tmp_path / "ovl"))
        ch.ledger.initialize_from_genesis(_parse(stream[0]))
        orig = ch.commit_validated

        def slow_commit(block, codes, **kw):
            time.sleep(0.05)
            return orig(block, codes, **kw)
        ch.commit_validated = slow_commit
        # widen stage A too: with instant validation, a lagging commit
        # worker on a loaded 1-core box can make every validate window
        # miss every commit window (scheduling flake)
        orig_va = ch.validator.validate_ahead

        def slow_validate(block, known_txids=None):
            time.sleep(0.02)
            return orig_va(block, known_txids=known_txids)
        ch.validator.validate_ahead = slow_validate
        pipeline = CommitPipeline(ch, depth=1)
        try:
            for i, raw in enumerate(stream[1:], start=1):
                pipeline.submit(i, raw=raw)
            pipeline.drain(timeout=30)
        finally:
            pipeline.stop()
        assert pipeline.stats["committed"] == 5
        assert pipeline.overlap_ratio > 0.0
        ch.ledger.close()


class TestFaults:
    def test_stage_a_fault_falls_back_sequential(self, tmp_path,
                                                 stream):
        faults.clear()      # the test arms its own fault
        seq_ch, seq_codes = _run_sequential(tmp_path, stream)
        ch = make_seam_channel(str(tmp_path / "fault"))
        ch.ledger.initialize_from_genesis(_parse(stream[0]))
        faults.arm("commit.validate_ahead", mode="error", count=2)
        pipeline = CommitPipeline(ch, depth=1)
        try:
            for i, raw in enumerate(stream[1:], start=1):
                pipeline.submit(i, raw=raw)
            pipeline.drain(timeout=30)
        finally:
            pipeline.stop()
            faults.reset()
        assert pipeline.stats["fallbacks"] == 2
        assert ch.ledger.commit_hash == seq_ch.ledger.commit_hash
        assert _filters(ch.ledger, 6) == _filters(seq_ch.ledger, 6)
        seq_ch.ledger.close()
        ch.ledger.close()

    def test_barrier_fault_demotes_not_corrupts(self, tmp_path,
                                                stream):
        faults.clear()      # the test arms its own fault
        seq_ch, _ = _run_sequential(tmp_path, stream)
        ch = make_seam_channel(str(tmp_path / "bfault"))
        ch.ledger.initialize_from_genesis(_parse(stream[0]))
        faults.arm("commit.barrier", mode="error", count=1)
        pipeline = CommitPipeline(ch, depth=1)
        try:
            for i, raw in enumerate(stream[1:], start=1):
                pipeline.submit(i, raw=raw)
            pipeline.drain(timeout=30)
        finally:
            pipeline.stop()
            faults.reset()
        assert pipeline.stats["fallbacks"] >= 1
        assert ch.ledger.commit_hash == seq_ch.ledger.commit_hash
        seq_ch.ledger.close()
        ch.ledger.close()

    def test_forged_block_rejects_and_reset_recovers(self, tmp_path,
                                                     stream):
        faults.clear()      # pins the rejection path

        class RejectOnceMCS:
            def __init__(self):
                self.rejected = 0

            def verify_block(self, cid, seq, block):
                if seq == 2 and not self.rejected:
                    self.rejected += 1
                    raise BlockVerificationError("forged")

        ch = make_seam_channel(str(tmp_path / "rej"))
        ch.ledger.initialize_from_genesis(_parse(stream[0]))
        pipeline = CommitPipeline(ch, mcs=RejectOnceMCS(), depth=1)
        try:
            pipeline.submit(1, raw=stream[1])
            pipeline.submit(2, raw=stream[2])
            with pytest.raises(CommitPipelineError) as ei:
                pipeline.drain(timeout=30)
            assert ei.value.stage == "verify"
            assert ei.value.seq == 2
            # the sequential-retry recovery: reset to committed
            # height, re-feed from there
            pipeline.reset()
            assert pipeline.next_seq == ch.ledger.height
            for i in range(pipeline.next_seq, 6):
                pipeline.submit(i, raw=stream[i])
            pipeline.drain(timeout=30)
        finally:
            pipeline.stop()
        assert ch.ledger.height == 6
        ch.ledger.close()

    def test_depth_zero_refused(self, tmp_path, stream):
        ch = make_seam_channel(str(tmp_path / "d0"))
        with pytest.raises(ValueError, match="depth"):
            CommitPipeline(ch, depth=0)
        ch.ledger.close()


class TestCrash:
    def test_crash_between_validate_ahead_and_commit(self, tmp_path,
                                                     stream):
        """Kill the pipeline while commit(b1) is in flight and
        validate(b2) has already finished: NOTHING of b2 is published
        (no filter stamp, no durable bytes), and a reopened ledger
        replays both blocks to the same commit hash as the sequential
        twin."""
        faults.clear()      # pins stage timings around the crash
        seq_ch, _ = _run_sequential(tmp_path, stream)

        root = str(tmp_path / "crash")
        ch = make_seam_channel(root)
        ch.ledger.initialize_from_genesis(_parse(stream[0]))
        commit_entered = threading.Event()
        hold_commit = threading.Event()
        crashed = threading.Event()
        orig = ch.commit_validated

        def gated(block, codes, **kw):
            commit_entered.set()
            hold_commit.wait(10)
            if crashed.is_set():
                # the crash lands BEFORE anything durable happens
                raise RuntimeError("simulated crash before commit")
            return orig(block, codes, **kw)
        ch.commit_validated = gated

        pipeline = CommitPipeline(ch, depth=1)
        pipeline.submit(1, raw=stream[1])
        pipeline.submit(2, raw=stream[2])
        assert commit_entered.wait(10)
        deadline = time.monotonic() + 10
        spec = None
        while spec is None:
            assert time.monotonic() < deadline, \
                "validate-ahead of b2 never ran"
            with pipeline._cond:
                if pipeline._validated:
                    spec = pipeline._validated[0]
            time.sleep(0.01)
        # b2 validated while b1 uncommitted: no early side effects —
        # its in-memory block carries NO transactions filter and the
        # store has neither block
        assert pipeline.stats["validated_ahead"] == 2
        filt_idx = common.BlockMetadataIndex.TRANSACTIONS_FILTER
        assert len(spec.block.metadata.metadata) <= filt_idx or \
            not spec.block.metadata.metadata[filt_idx]
        assert ch.ledger.height == 1

        # crash: abandon the pipeline mid-commit, reopen from disk
        crashed.set()
        hold_commit.set()            # unblock the worker so stop joins
        pipeline.stop()
        ch.ledger.close()

        re_ch = make_seam_channel(root)    # same dir: real block store
        assert re_ch.ledger.height == 1    # nothing was committed
        for raw in stream[1:]:
            re_ch.process_block(_parse(raw))
        assert re_ch.ledger.commit_hash == seq_ch.ledger.commit_hash
        assert _filters(re_ch.ledger, 6) == _filters(seq_ch.ledger, 6)
        seq_ch.ledger.close()
        re_ch.ledger.close()


class TestDeliverClientPath:
    def test_deliverer_feeds_pipeline(self, tmp_path, stream):
        """The deliver-client ingest path: a stream endpoint feeding a
        pipelined channel commits everything, without the inline
        verify+process of the sequential branch."""
        from fabric_tpu.peer.deliverclient import Deliverer
        from fabric_tpu.protos import orderer as ordpb
        faults.reset()

        ch = make_seam_channel(str(tmp_path / "dlv"))
        ch.ledger.initialize_from_genesis(_parse(stream[0]))
        ch.commit_pipeline = CommitPipeline(ch, depth=1)

        served = threading.Event()

        class Endpoint:
            """Serves from the seek position like a real handler, so
            the reconnect loop (re-seek from the committed height)
            stays consistent with the pipeline's resets."""

            def __init__(self, raws):
                self._raws = raws     # raws[0] is block 1

            def handle(self, env):
                seek = ordpb.SeekInfo()
                seek.ParseFromString(pu.get_payload(env).data)
                start = seek.start.specified.number
                todo = self._raws[start - 1:]
                if not todo:
                    served.set()
                    time.sleep(0.02)   # tip: nothing new yet
                    return
                for raw in todo:
                    yield ordpb.DeliverResponse(block=_parse(raw))

        endpoint = Endpoint(stream[1:])
        deliverer = Deliverer(ch, FakeSigner(b"peer"),
                              lambda: endpoint, mcs=None)
        deliverer.start()
        try:
            assert served.wait(10)
            deadline = time.monotonic() + 10
            while ch.ledger.height < 6:
                assert time.monotonic() < deadline, \
                    f"stalled at height {ch.ledger.height}"
                time.sleep(0.02)
        finally:
            deliverer.stop()
            ch.commit_pipeline.stop()
        assert ch.ledger.height == 6
        ch.ledger.close()


class _FakeGChannel:
    on_block = on_state_request = on_state_response = None

    def publish_state_info(self, h):
        pass

    def heights(self):
        return {}

    def _tag_channel(self, msg):
        pass


class _FakeNode:
    def join_channel(self, cid):
        return _FakeGChannel()

    def gossip_block(self, cid, seq, raw):
        pass


class TestLeaderAdapterPath:
    def test_leader_runahead_feeds_pipeline(self, tmp_path, stream):
        """The leader's orderer intake: with a pipelined channel the
        adapter allows `depth` blocks of runahead (fetch+validate of
        N+1 proceeds while N commits) and the stream still lands
        fully, in order."""
        from fabric_tpu.gossip.service import _LeaderChannelAdapter
        from fabric_tpu.gossip.state import GossipStateProvider
        faults.clear()

        ch = make_seam_channel(str(tmp_path / "leader"))
        ch.ledger.initialize_from_genesis(_parse(stream[0]))
        ch.commit_pipeline = CommitPipeline(ch, depth=1)
        provider = GossipStateProvider(_FakeNode(), CHANNEL, ch, None,
                                       anti_entropy_interval_s=60)
        adapter = _LeaderChannelAdapter(ch, provider)
        provider.start()
        try:
            for i in range(1, 6):
                adapter.process_block(_parse(stream[i]))
            deadline = time.monotonic() + 15
            while ch.ledger.height < 6:
                assert time.monotonic() < deadline, \
                    f"stalled at height {ch.ledger.height}"
                time.sleep(0.02)
        finally:
            provider.stop()
            ch.commit_pipeline.stop()
        assert ch.ledger.height == 6
        ch.ledger.close()


class _RejectOnceMCS:
    """Forged-block simulation: rejects `bad_seq` exactly once."""

    def __init__(self, bad_seq):
        self.bad_seq = bad_seq
        self.rejected = 0

    def verify_block(self, cid, seq, block):
        if seq == self.bad_seq and not self.rejected:
            self.rejected += 1
            raise BlockVerificationError("forged")


class TestDeliverRejection:
    def test_forged_block_reconnects_immediately(self, tmp_path,
                                                 stream):
        """A forged block mid-stream must surface synchronously (via
        wait_validated) — tearing the stream for reconnect/failover —
        not idle unseen at the tip; the re-seek then heals."""
        from fabric_tpu.peer.deliverclient import Deliverer
        from fabric_tpu.protos import orderer as ordpb
        faults.clear()

        ch = make_seam_channel(str(tmp_path / "dlvrej"))
        ch.ledger.initialize_from_genesis(_parse(stream[0]))
        mcs = _RejectOnceMCS(bad_seq=3)
        ch.commit_pipeline = CommitPipeline(ch, mcs=mcs, depth=1)

        class Endpoint:
            def __init__(self, raws):
                self._raws = raws

            def handle(self, env):
                seek = ordpb.SeekInfo()
                seek.ParseFromString(pu.get_payload(env).data)
                start = seek.start.specified.number
                for raw in self._raws[start - 1:]:
                    yield ordpb.DeliverResponse(block=_parse(raw))
                time.sleep(0.02)

        deliverer = Deliverer(ch, FakeSigner(b"peer"),
                              lambda: Endpoint(stream[1:]), mcs=None,
                              retry_base_s=0.01, retry_max_s=0.05)
        deliverer.start()
        try:
            deadline = time.monotonic() + 15
            while ch.ledger.height < 6:
                assert time.monotonic() < deadline, \
                    f"stalled at height {ch.ledger.height}"
                time.sleep(0.02)
        finally:
            deliverer.stop()
            ch.commit_pipeline.stop()
        assert mcs.rejected == 1
        # the rejection tore the stream: at least one reconnect
        assert deliverer.reconnects >= 1
        assert ch.ledger.height == 6
        ch.ledger.close()


class TestGossipTipRejection:
    def test_rejection_at_tip_recovers_via_idle_probe(self, tmp_path,
                                                      stream):
        """A forged LAST block (nothing arriving after it) must not
        wedge: the feeder's idle tick probes the sticky error, rewinds
        the buffer, and an anti-entropy re-delivery heals."""
        from fabric_tpu.gossip.state import GossipStateProvider
        faults.clear()

        ch = make_seam_channel(str(tmp_path / "gtip"))
        ch.ledger.initialize_from_genesis(_parse(stream[0]))
        mcs = _RejectOnceMCS(bad_seq=5)
        ch.commit_pipeline = CommitPipeline(ch, mcs=mcs, depth=1)
        provider = GossipStateProvider(_FakeNode(), CHANNEL, ch, None,
                                       anti_entropy_interval_s=60)
        provider.start()
        try:
            for i in range(1, 6):
                provider.buffer.push(i, stream[i])
            # block 5 is rejected at the TIP — no newer block ever
            # arrives to shake the loop loose; only the feeder's idle
            # probe can rewind the buffer. Play anti-entropy: keep
            # re-delivering from the committed height (pushes below
            # the buffer's _next are dropped until the rewind lands).
            deadline = time.monotonic() + 20
            while ch.ledger.height < 6:
                assert time.monotonic() < deadline, \
                    f"wedged at height {ch.ledger.height}"
                for i in range(ch.ledger.height, 6):
                    provider.buffer.push(i, stream[i])
                time.sleep(0.05)
        finally:
            provider.stop()
            ch.commit_pipeline.stop()
        assert mcs.rejected == 1
        ch.ledger.close()


class TestGossipStatePath:
    def test_state_provider_commit_loop_uses_pipeline(self, tmp_path,
                                                      stream):
        """The gossip ingest path: the commit loop becomes the
        pipeline feeder; out-of-order arrival still commits in order
        and heights publish."""
        from fabric_tpu.gossip.state import GossipStateProvider
        faults.reset()

        class _FakeGChannel:
            on_block = None
            on_state_request = None
            on_state_response = None

            def publish_state_info(self, h):
                pass

            def heights(self):
                return {}

            def _tag_channel(self, msg):
                pass

        class _FakeNode:
            def join_channel(self, cid):
                return _FakeGChannel()

            def gossip_block(self, cid, seq, raw):
                pass

        ch = make_seam_channel(str(tmp_path / "gsp"))
        ch.ledger.initialize_from_genesis(_parse(stream[0]))
        ch.commit_pipeline = CommitPipeline(ch, depth=1)
        provider = GossipStateProvider(_FakeNode(), CHANNEL, ch, None,
                                       anti_entropy_interval_s=60)
        provider.start()
        try:
            # push out of order: 2..5 first, then 1 releases the run
            for i in (2, 3, 4, 5):
                provider.buffer.push(i, stream[i])
            provider.buffer.push(1, stream[1])
            deadline = time.monotonic() + 15
            while ch.ledger.height < 6:
                assert time.monotonic() < deadline, \
                    f"stalled at height {ch.ledger.height}"
                time.sleep(0.02)
        finally:
            provider.stop()
            ch.commit_pipeline.stop()
        assert ch.ledger.height == 6
        ch.ledger.close()
